"""Benchmark suite: all five BASELINE.md configs, tunnel-proof.

Prints exactly ONE json line on stdout:

  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N,
   "backend": "...", "configs": {...}}

The headline metric is BASELINE config 5 (the north star): place a
1M-task random DAG onto 512 simulated workers with the level-synchronous
device engine (`ops/leveled.py`) versus the stock pure-python
decide_worker loop (reference scheduler.py:8550, ~1 ms/task per
docs/source/efficiency.rst:48-50).  `configs` carries the other four
BASELINE configs (array-sum, rechunk+tensordot, steal-imbalance,
P2P shuffle) measured end-to-end on a live LocalCluster.

Robustness (the round-2 lesson — BENCH_r02 died `rc=1` on a transient
"Unable to initialize backend 'axon'" with no parseable output):

- the jax backend is probed in a SUBPROCESS with a hard timeout and up
  to 3 retries with backoff; on total failure the suite falls back to
  the CPU backend and records the error instead of dying;
- every config runs in its own subprocess with a hard timeout; a hang
  or crash in one config yields an "error" entry for that config only;
- the final JSON line is ALWAYS printed and the exit code is ALWAYS 0.

Scheduler-cluster configs (1-4) force JAX_PLATFORMS=cpu: they measure
the asyncio scheduler/worker runtime, and the placement co-processor
must plan at event-loop latency, not tunnel latency (PERF.md).  Config 5
runs on the real backend (the TPU chip under axon).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Backend probe budget (BENCH_r05 burned 90 s of bench wall on a wedged
# tunnel): configurable, and a TIMEOUT is terminal — a tunnel that cannot
# answer a trivial device query within the budget will not recover within
# a retry backoff, so only probe ERRORS (transient init failures) retry.
PROBE_TIMEOUT = float(os.environ.get("DTPU_BENCH_PROBE_TIMEOUT", "30"))
PROBE_RETRIES = int(os.environ.get("DTPU_BENCH_PROBE_RETRIES", "3"))
PROBE_BACKOFF = [5.0, 15.0]

# (name, timeout_s, force_cpu)
CONFIGS = [
    ("array_sum", 240.0, True),
    ("rechunk_tensordot", 420.0, True),
    ("steal", 240.0, True),
    ("shuffle", 420.0, True),
    ("dag_1m", 600.0, False),
    # the sharded engine headline: always on the 8-device CPU mesh (the
    # per-shard H2D/collective structure is what is measured; the box
    # has no multi-chip accelerator)
    ("dag_10m", 900.0, True),
    # sans-io cluster simulator headline (distributed_tpu/sim): 1M tasks
    # through the REAL scheduler engine + 10,000 REAL worker state
    # machines on a virtual clock, run twice — the virtual makespan and
    # whole-run digest must be bit-identical, so the reported number is
    # immune to the box's 2x wall drift
    ("sim_10k", 7200.0, True),
]


def _mesh_xla_flags(existing: str, n: int = 8) -> str:
    """``existing`` XLA_FLAGS with the host-device-count flag added
    (idempotent) — shared by the in-process dance below and main()'s
    child-env construction for dag_10m."""
    if "--xla_force_host_platform_device_count" in existing:
        return existing
    return (existing + f" --xla_force_host_platform_device_count={n}").strip()


def _ensure_cpu_mesh_env(n: int = 8) -> None:
    """Force an ``n``-device CPU mesh BEFORE the first backend init:
    XLA_FLAGS for jax < 0.5 (where it is honored), jax_num_cpu_devices
    for jax >= 0.5 (where the flag became a no-op) — the same dance as
    tests/conftest.py."""
    os.environ["XLA_FLAGS"] = _mesh_xla_flags(
        os.environ.get("XLA_FLAGS", ""), n
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:  # jax < 0.5: XLA_FLAGS carries it
        pass

BANDWIDTH = 100e6


# =====================================================================
# config 1: da.ones((10_000, 10_000), chunks=1000).sum()
# LocalCluster(processes=False), 4 workers  (BASELINE.md config 1)
# =====================================================================

def _np_ones(shape):
    import numpy as np

    return np.ones(shape, np.float64)


def _np_sum(a):
    return float(a.sum())


def _sum_list(xs):
    return sum(xs)


def _inc(x):
    return x + 1


async def cfg_array_sum():
    import numpy as np  # noqa: F401  (workers build numpy chunks)

    from distributed_tpu.client.client import Client
    from distributed_tpu.deploy.local import LocalCluster
    from distributed_tpu.graph.spec import Graph, TaskRef, TaskSpec

    g = Graph()
    partials = []
    for i in range(10):
        for j in range(10):
            ck = f"ones-{i}-{j}"
            g.tasks[ck] = TaskSpec(_np_ones, ((1000, 1000),))
            sk = f"sum-{i}-{j}"
            g.tasks[sk] = TaskSpec(_np_sum, (TaskRef(ck),))
            partials.append(sk)
    level, r = partials, 0
    while len(level) > 1:
        nxt = []
        for b in range(0, len(level), 8):
            k = f"agg-{r}-{b}"
            g.tasks[k] = TaskSpec(
                _sum_list, ([TaskRef(x) for x in level[b : b + 8]],)
            )
            nxt.append(k)
        level, r = nxt, r + 1
    root = level[0]
    n_tasks = len(g.tasks)

    async with LocalCluster(n_workers=4, threads_per_worker=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            t0 = time.perf_counter()
            futs = c.compute_graph(g, [root])
            result = await futs[root].result()
            wall = time.perf_counter() - t0
            assert result == 10_000 * 10_000, result

            # dedicated trivial-task probe: per-task end-to-end overhead
            # vs the reference's ~1 ms/task (docs/source/efficiency.rst)
            t0 = time.perf_counter()
            await c.gather(c.map(_inc, range(500)))
            owall = time.perf_counter() - t0

    overhead = owall / 500
    return {
        "desc": "ones((10000,10000),chunks=1000).sum(), 4 workers",
        "n_tasks": n_tasks,
        "wall_s": round(wall, 3),
        "tasks_per_s": round(n_tasks / wall),
        "overhead_us_per_task": round(overhead * 1e6),
        "vs_baseline": round(0.001 / overhead, 1),
    }


# =====================================================================
# config 2: rechunk + tensordot, ~50k tasks, 16 workers
# (BASELINE.md config 2) — tiny payloads so the SCHEDULER is measured;
# reports placement co-processor plan hit-rate with jax on vs off.
# =====================================================================

def _blk():
    import numpy as np

    return np.full((4, 4), 1.0)


def _quad(a, qi, qj):
    h = a.shape[0] // 2
    return a[qi * h : (qi + 1) * h, qj * h : (qj + 1) * h]


def _assemble(q00, q01, q10, q11):
    import numpy as np

    return np.block([[q00, q01], [q10, q11]])


def _mul(a, b):
    return a @ b


def _add_all(xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def _tensordot_graph(G, tag=""):
    """rechunk(A) then C = A' @ B blockwise: ~45k tasks at G=32."""
    from distributed_tpu.graph.spec import Graph, TaskRef, TaskSpec

    g = Graph()
    for i in range(G):
        for k in range(G):
            g.tasks[f"A{tag}-{i}-{k}"] = TaskSpec(_blk)
            g.tasks[f"B{tag}-{i}-{k}"] = TaskSpec(_blk)
    # rechunk stage: quarter every A chunk and reassemble (same tiling —
    # the graph SHAPE of a rechunk: split tasks + gather tasks)
    for i in range(G):
        for k in range(G):
            for qi in range(2):
                for qj in range(2):
                    g.tasks[f"Aq{tag}-{i}-{k}-{qi}{qj}"] = TaskSpec(
                        _quad, (TaskRef(f"A{tag}-{i}-{k}"), qi, qj)
                    )
            g.tasks[f"Ar{tag}-{i}-{k}"] = TaskSpec(
                _assemble,
                tuple(
                    TaskRef(f"Aq{tag}-{i}-{k}-{qi}{qj}")
                    for qi in range(2)
                    for qj in range(2)
                ),
            )
    # blockwise tensordot with tree reduction (fan-in 8)
    outs = []
    for i in range(G):
        for j in range(G):
            for k in range(G):
                g.tasks[f"mul{tag}-{i}-{j}-{k}"] = TaskSpec(
                    _mul, (TaskRef(f"Ar{tag}-{i}-{k}"), TaskRef(f"B{tag}-{k}-{j}"))
                )
            level = [f"mul{tag}-{i}-{j}-{k}" for k in range(G)]
            r = 0
            while len(level) > 1:
                nxt = []
                for b in range(0, len(level), 8):
                    key = f"red{tag}-{i}-{j}-{r}-{b}"
                    g.tasks[key] = TaskSpec(
                        _add_all, ([TaskRef(x) for x in level[b : b + 8]],)
                    )
                    nxt.append(key)
                level, r = nxt, r + 1
            outs.append(level[0])
    return g, outs


async def _run_tensordot(jax_enabled, G=32):
    """Steady-state measurement: a warm-up graph first (jit caches,
    connections, duration estimates), then an identically-shaped graph
    timed in the same cluster.

    ``jax_enabled=None`` runs the TRUE DEFAULT configuration (since the
    partitioner planner landed, the co-processor engages at 16 workers
    by default); ``False`` forces the pure-python oracle baseline."""
    from distributed_tpu import config
    from distributed_tpu.client.client import Client
    from distributed_tpu.deploy.local import LocalCluster

    overrides = {} if jax_enabled is None else {
        "scheduler.jax.enabled": jax_enabled,
        "scheduler.jax.min-workers": 0,
        "scheduler.jax.min-transfer-ratio": 0,
    }
    with config.set(overrides):
        async with LocalCluster(n_workers=16, threads_per_worker=1) as cluster:
            async with Client(cluster.scheduler_address) as c:
                wg, wouts = _tensordot_graph(G, tag="w")
                futs = c.compute_graph(wg, wouts)
                await c.gather([futs[k] for k in wouts])
                del futs
                placement = cluster.scheduler.state.placement
                if placement is not None:
                    placement.plan_hits = placement.plan_misses = 0
                    placement.plan_parks = 0
                    placement.plans_computed = 0
                    for k in placement.miss_reasons:
                        placement.miss_reasons[k] = 0
                    for k in placement.hint_drops:
                        placement.hint_drops[k] = 0

                g, outs = _tensordot_graph(G)
                n_tasks = len(g.tasks)
                t0 = time.perf_counter()
                futs = c.compute_graph(g, outs)
                await c.gather([futs[k] for k in outs])
                wall = time.perf_counter() - t0
                stats = (
                    {
                        "plans": placement.plans_computed,
                        "hits": placement.plan_hits,
                        "parks": placement.plan_parks,
                        "misses": placement.plan_misses,
                        "miss_reasons": dict(placement.miss_reasons),
                        "hint_drops": dict(placement.hint_drops),
                    }
                    if placement is not None
                    else None
                )
    return n_tasks, wall, stats


def _jax_cpu_ready(timeout: float = 45.0) -> bool:
    """True when the jax CPU backend answers within ``timeout``.

    The accelerator site hook initializes EVERY registered platform on
    first backend query — including the tunneled one — so a wedged
    tunnel blocks even JAX_PLATFORMS=cpu processes indefinitely.  Probe
    from a daemon thread so a hang costs ``timeout``, not the config."""
    import threading

    ok = []

    def probe():
        try:
            import jax

            jax.devices("cpu")
            ok.append(True)
        except Exception:
            pass

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout)
    return bool(ok)


async def cfg_rechunk_tensordot():
    """Headline ``wall_s``: the TRUE DEFAULT configuration — since the
    partitioner planner (ops/partition.py) the co-processor engages at
    16 workers by default, tiles the graph, and the plan is consumed
    with deep home stacks + steal exemption.  ``wall_s_python_only`` is
    the forced-off oracle baseline measured in the same process;
    ``wall_s_jax_forced`` keeps its historical meaning (co-processor on)
    for round-over-round comparison — it now equals the default path."""
    n_tasks, wall_py, _ = await _run_tensordot(False)
    if _jax_cpu_ready():
        _, wall, stats = await _run_tensordot(None)
        forced = round(wall, 3)
        vs_py = round(wall_py / wall, 2)
    else:
        # publish the python wall as wall_s (it IS what the default
        # config would deliver here) but keep the co-processor fields
        # explicit about unavailability — never alias a python-only
        # number under the forced label
        wall, stats = wall_py, {"error": "jax backend unavailable"}
        forced = None
        vs_py = None
    return {
        "desc": "rechunk+tensordot blockwise, 16 workers",
        "n_tasks": n_tasks,
        "wall_s": round(wall, 3),
        "wall_s_python_only": round(wall_py, 3),
        "wall_s_jax_forced": forced,
        "tasks_per_s": round(n_tasks / wall),
        "overhead_us_per_task": round(wall / n_tasks * 1e6),
        "plan_stats": stats,
        "vs_python_only": vs_py,
        "vs_baseline": round(0.001 / (wall / n_tasks), 1),
    }


# =====================================================================
# config 3: imbalanced slowinc + work stealing, 64 workers
# (BASELINE.md config 3; reference test_steal.py)
# =====================================================================

def _slowinc(i, x=0, delay=0.02):
    time.sleep(delay)
    return i + x


async def _run_steal(steal_enabled):
    from distributed_tpu import config
    from distributed_tpu.client.client import Client
    from distributed_tpu.deploy.local import LocalCluster

    n_tasks, n_workers, delay = 320, 64, 0.02
    mirror_stats = None
    with config.set(
        {
            "scheduler.work-stealing": steal_enabled,
            "scheduler.jax.enabled": False,
        }
    ):
        async with LocalCluster(
            n_workers=n_workers, threads_per_worker=1
        ) as cluster:
            async with Client(cluster.scheduler_address) as c:
                w0 = cluster.workers[0].address
                # prime the prefix duration estimate, then pin every task
                # to ONE worker with loose restrictions — only work
                # stealing can spread them (the reference's
                # test_steal.py steal-cheap-data-slow-computation shape)
                await c.submit(_slowinc, -1, delay=delay).result()
                t0 = time.perf_counter()
                futs = c.map(
                    _slowinc,
                    range(n_tasks),
                    delay=delay,
                    workers=[w0],
                    allow_other_workers=True,
                )
                await c.gather(futs)
                wall = time.perf_counter() - t0
                mirror = cluster.scheduler.state.mirror
                if mirror is not None:
                    mirror_stats = mirror.stats()
    ideal = n_tasks * delay / n_workers
    return wall, ideal, n_tasks, mirror_stats


def _host_canary_ms() -> float:
    """Milliseconds for a fixed pure-python workload: the steal config's
    walls swing with host load (this box drifts 2x+ through a day —
    PERF.md Rounds 5-6), so cross-round comparisons of
    ``balance_efficiency`` are only meaningful normalized by this
    canary, same role as ``stock_us_per_task`` in the dag_1m entry."""
    t0 = time.perf_counter()
    acc = 0
    for i in range(200_000):
        acc += i % 7
    return (time.perf_counter() - t0) * 1e3


async def cfg_steal():
    # median-of-N (N >= 3, odd): this box is a shared host and the wall
    # of an 0.1 s-ideal run swings 0.18-0.30 s with load (BENCH_r05 saw
    # one of three runs at 0.302 s vs 0.196 s).  The MEDIAN is robust to
    # a single loaded run while not hiding a real regression the way
    # min-of-N does; all runs plus their spread are reported so a
    # regression is distinguishable from noise.
    import statistics

    n_runs = max(int(os.environ.get("DTPU_BENCH_STEAL_RUNS", "3")), 3)
    n_runs += 1 - n_runs % 2  # odd, so the median is a real run
    canary = _host_canary_ms()
    walls = []
    ideal = n_tasks = None
    mirror_stats = None
    for _ in range(n_runs):
        wall, ideal, n_tasks, mstats = await _run_steal(True)
        walls.append(round(wall, 3))
        mirror_stats = mstats or mirror_stats
    wall = statistics.median(walls)
    # median-of-3 for the baseline too: a single noisy no-steal run
    # against a median steal run would misstate the benefit either way
    walls_off = []
    for _ in range(3):
        wall_off, _, _, _ = await _run_steal(False)
        walls_off.append(round(wall_off, 3))
    wall_off = statistics.median(walls_off)
    return {
        "desc": "imbalanced slowinc x320 from one worker's data, 64 workers",
        "n_tasks": n_tasks,
        "wall_s": wall,
        "wall_s_runs": walls,
        "wall_s_spread": round(max(walls) - min(walls), 3),
        "wall_s_no_steal": round(wall_off, 3),
        "wall_s_no_steal_runs": walls_off,
        "ideal_s": round(ideal, 3),
        "balance_efficiency": round(ideal / wall, 3),
        "host_canary_ms": round(canary, 2),
        "mirror": mirror_stats,
        "vs_baseline": round(wall_off / wall, 1),
    }


# =====================================================================
# config 4: P2P shuffle, 10M rows, columnar (BASELINE.md config 4)
# =====================================================================

def _reference_shuffle_dataplane_rows_per_s(n_rows=2_000_000, n_parts=16,
                                            nout=128):
    """The reference's P2P shuffle DATA PLANE re-run faithfully on this
    host: per input partition a pandas merge with the worker_for
    categorical, arrow conversion, sort_by destination, slicing into
    shards and buffer serialization; per output partition deserialize +
    concat + to_pandas (reference shuffle/_shuffle.py split_by_worker
    :490-533, _core.py add_partition/_fetch semantics, _arrow.py
    serialize_table/deserialize_table).  Scheduler, network and disk are
    all EXCLUDED — this measures only the rows/s ceiling of the
    reference's per-row machinery, which favors the reference.
    Subsampled (2M rows) and scaled: the per-row cost is flat in n.
    """
    from collections import defaultdict

    import numpy as np
    import pandas as pd
    import pyarrow as pa

    rows_per = n_rows // n_parts
    workers = [f"w{i}" for i in range(128)]
    worker_for = pd.Series(
        pd.Categorical([workers[i % 128] for i in range(nout)]),
        index=pd.RangeIndex(nout), name="_workers",
    )
    rng = np.random.default_rng(0)
    dfs = [
        pd.DataFrame({
            "key": rng.integers(0, nout, rows_per),
            "value": rng.random(rows_per),
        })
        for _ in range(n_parts)
    ]

    t0 = time.perf_counter()
    inbox: defaultdict[str, list] = defaultdict(list)
    codes = worker_for.cat.codes.rename("_worker")
    for df in dfs:
        # split_by_worker (reference _shuffle.py:490): merge the
        # destination codes in, convert to arrow, sort, slice
        df = df.merge(right=codes, left_on="key", right_index=True,
                      how="inner")
        t = pa.Table.from_pandas(df, preserve_index=True)
        t = t.sort_by("_worker")
        wcodes = np.asarray(t["_worker"])
        t = t.drop(["_worker"])
        splits = np.where(wcodes[1:] != wcodes[:-1])[0] + 1
        splits = np.concatenate([[0], splits, [len(wcodes)]])
        for a, b in zip(splits[:-1], splits[1:]):
            if b > a:
                shard = t.slice(offset=a, length=b - a)
                # the wire format (reference _arrow.py:133
                # serialize_table): one arrow IPC stream per shard
                stream = pa.BufferOutputStream()
                with pa.ipc.new_stream(stream, shard.schema) as writer:
                    writer.write_table(shard)
                inbox[workers[wcodes[a] % 128]].append(
                    stream.getvalue().to_pybytes()
                )
    for addr, blobs in inbox.items():
        tables = []
        for blob in blobs:
            with pa.ipc.open_stream(pa.py_buffer(blob)) as reader:
                tables.append(reader.read_all())
        out = pa.concat_tables(tables).to_pandas()
        assert len(out)
    wall = time.perf_counter() - t0
    return n_rows / wall


async def cfg_shuffle():
    import numpy as np

    from distributed_tpu.client.client import Client
    from distributed_tpu.deploy.local import LocalCluster

    try:
        from distributed_tpu.shuffle.api import p2p_shuffle_arrays
        columnar = True
    except ImportError:
        from distributed_tpu.shuffle.api import p2p_shuffle
        columnar = False

    n_rows = 10_000_000 if columnar else 1_000_000
    # BASELINE.md config 4: 128 workers (in-process on this one-core
    # host; a real deployment spreads them over machines)
    n_parts = 128
    n_workers = 128
    rows_per = n_rows // n_parts

    def make_part(i, n):
        rng = np.random.default_rng(i)
        return {
            "key": rng.integers(0, 1 << 30, n).astype(np.int64),
            "value": rng.random(n),
        }

    def make_part_records(i, n):
        rng = np.random.default_rng(i)
        keys = rng.integers(0, 1 << 30, n)
        vals = rng.random(n)
        return list(zip(keys.tolist(), vals.tolist()))

    async with LocalCluster(
        n_workers=n_workers, threads_per_worker=1
    ) as cluster:
        async with Client(cluster.scheduler_address) as c:
            maker = make_part if columnar else make_part_records
            parts = c.map(maker, range(n_parts), n=rows_per)
            await c.gather(parts, errors="raise")
            t0 = time.perf_counter()
            if columnar:
                outs = await p2p_shuffle_arrays(
                    c, parts, npartitions_out=n_parts, on="key"
                )
            else:
                outs = await p2p_shuffle(c, parts, npartitions_out=n_parts)
            sizes = await c.gather(
                c.map(
                    (lambda p: len(p["key"])) if columnar else len,
                    outs,
                )
            )
            wall = time.perf_counter() - t0
    assert sum(sizes) == n_rows, (sum(sizes), n_rows)
    # apples-to-apples: the reference cannot run e2e here (no dask in
    # the image), so compare DATA PLANE vs DATA PLANE — its pandas/arrow
    # split+serialize+concat loop vs our vectorized columnar one — and
    # report our full e2e wall alongside.
    ref_rows_per_s = _reference_shuffle_dataplane_rows_per_s()
    ours_rows_per_s = _our_shuffle_dataplane_rows_per_s()
    return {
        "desc": f"P2P shuffle {n_rows} rows, {n_parts} partitions, "
        f"{n_workers} workers ({'columnar' if columnar else 'records'})",
        "n_rows": n_rows,
        "wall_s": round(wall, 3),
        "rows_per_s": round(n_rows / wall),
        "dataplane_rows_per_s": round(ours_rows_per_s),
        "ref_dataplane_rows_per_s": round(ref_rows_per_s),
        "vs_baseline": round(ours_rows_per_s / ref_rows_per_s, 2),
    }


def _our_shuffle_dataplane_rows_per_s(n_rows=2_000_000, n_parts=16,
                                      nout=128):
    """Our columnar data plane on the same workload shape as the
    reference harness above: vectorized hash split into per-destination
    shards (shuffle/columnar.py split_arrays_by_hash), the frame
    serialization the comm layer applies (protocol.serialize numpy
    family, zero-copy), and per-output concat (concat_arrays)."""
    from collections import defaultdict

    import numpy as np

    from distributed_tpu.protocol.serialize import serialize, deserialize
    from distributed_tpu.shuffle.columnar import (
        concat_arrays,
        split_arrays_by_hash,
    )

    rows_per = n_rows // n_parts
    rng = np.random.default_rng(0)
    parts = [
        {
            "key": rng.integers(0, nout, rows_per).astype(np.int64),
            "value": rng.random(rows_per),
        }
        for _ in range(n_parts)
    ]
    t0 = time.perf_counter()
    inbox: defaultdict[int, list] = defaultdict(list)
    for part in parts:
        shards = split_arrays_by_hash(part, nout, on="key")
        for j, shard in shards.items():
            # wire cost parity: serialize each column like the comm
            # layer would (numpy family header + zero-copy frame)
            blob = {c: serialize(a) for c, a in shard.items()}
            inbox[j % 128].append(blob)
    for w, blobs in inbox.items():
        shards = [
            {c: deserialize(*sb) for c, sb in blob.items()} for blob in blobs
        ]
        out = concat_arrays(shards)
        assert len(out["key"])
    wall = time.perf_counter() - t0
    return n_rows / wall


# =====================================================================
# config 5 (north star): 1M-task DAG onto 512 simulated workers with the
# level-synchronous device engine vs the stock python placement loop
# =====================================================================

N_TASKS = 1_000_000
N_WORKERS = 512
N_EDGES_PER_TASK = 2
ORACLE_SUBSET = 2_000


def build_graph(rng):
    import numpy as np

    durations = rng.uniform(0.01, 1.0, N_TASKS).astype(np.float32)
    out_bytes = rng.uniform(1e3, 1e7, N_TASKS).astype(np.float32)
    # random DAG: each task depends on up to 2 uniformly-random earlier tasks
    n_deps = rng.integers(0, N_EDGES_PER_TASK + 1, N_TASKS)
    n_deps[0] = 0
    total = int(n_deps.sum())
    dst = np.repeat(np.arange(N_TASKS), n_deps).astype(np.int32)
    src = (rng.random(total) * np.maximum(dst, 1)).astype(np.int32)
    return durations, out_bytes, src, dst


def bench_device(durations, out_bytes, src, dst):
    import numpy as np

    from distributed_tpu.ops.leveled import (
        place_graph_streamed,
        validate_leveled,
    )

    nthreads = np.full(N_WORKERS, 2, np.int32)
    occ0 = np.zeros(N_WORKERS, np.float32)
    running = np.ones(N_WORKERS, bool)

    # warm up: builds the native library and compiles every wave bucket
    # (compile excluded from the measurement, like the reference excludes
    # interpreter startup)
    packed, res = place_graph_streamed(
        durations, out_bytes, src, dst, nthreads, occ0, running,
        bandwidth=BANDWIDTH,
    )

    # streamed driver: pack fill + H2D upload + waves pipeline; only the
    # topology phase is serial (reported as "pack")
    tm: dict = {}
    t0 = time.perf_counter()
    packed, res = place_graph_streamed(
        durations, out_bytes, src, dst, nthreads, occ0, running,
        bandwidth=BANDWIDTH, timings=tm,
    )
    t2 = time.perf_counter()
    t1 = t0 + tm.get("topo_s", 0.0)

    validate_leveled(packed, res, src, dst, running)
    counts = np.bincount(res.assignment, minlength=N_WORKERS)
    return t1 - t0, t2 - t1, res.n_waves, counts


def bench_stock_python(durations, out_bytes, src, dst, n=ORACLE_SUBSET,
                       n_workers=None):
    """Stock semantics: per-task min() over all workers of
    (occupancy/nthreads + missing_bytes/bandwidth, nbytes) — the
    reference's decide_worker/worker_objective python loop."""
    import numpy as np

    N_WORKERS = n_workers or globals()["N_WORKERS"]
    occ = np.zeros(N_WORKERS)
    wnbytes = np.zeros(N_WORKERS)
    nthreads = 2
    deps: list[list[int]] = [[] for _ in range(n)]
    for s, d in zip(src, dst):
        if d < n:
            deps[d].append(s)
    placed = {}
    t0 = time.perf_counter()
    for t in range(n):
        best = None
        best_key = None
        missing_cache = {}
        for w in range(N_WORKERS):
            missing = 0.0
            for dep in deps[t]:
                if placed.get(dep) != w:
                    missing += out_bytes[dep]
            key = (occ[w] / nthreads + missing / BANDWIDTH, wnbytes[w], w)
            if best_key is None or key < best_key:
                best_key = key
                best = w
                missing_cache[w] = missing
        placed[t] = best
        occ[best] += durations[t] + missing_cache.get(best, 0.0) / BANDWIDTH
        wnbytes[best] += out_bytes[t]
    elapsed = time.perf_counter() - t0
    return elapsed / n  # seconds per task


def cfg_dag_1m():
    import jax
    import numpy as np

    rng = np.random.default_rng(0)
    durations, out_bytes, src, dst = build_graph(rng)
    pack_s, place_s, n_waves, counts = bench_device(
        durations, out_bytes, src, dst
    )
    stock_per_task = bench_stock_python(durations, out_bytes, src, dst)
    stock_total = stock_per_task * N_TASKS
    total_s = pack_s + place_s
    print(
        f"# pack {pack_s*1e3:.1f} ms + device {place_s*1e3:.1f} ms, "
        f"{n_waves} waves, load imbalance "
        f"{counts.max() / max(counts.mean(), 1):.2f}x, "
        f"stock python {stock_per_task*1e6:.0f} us/task "
        f"(extrapolated {stock_total:.0f} s for 1M)",
        file=sys.stderr,
    )
    return {
        "desc": "1M-task DAG placed on 512 simulated workers, device engine",
        "backend": jax.default_backend(),
        "pack_ms": round(pack_s * 1e3, 1),
        "device_ms": round(place_s * 1e3, 1),
        "wall_s": round(total_s, 4),
        "decisions_per_s": round(N_TASKS / total_s),
        "stock_us_per_task": round(stock_per_task * 1e6),
        "vs_baseline": round(stock_total / total_s, 1),
    }


# =====================================================================
# config 6 (dag_10m): the sharded engine headline — 10M tasks onto 4096
# MIRROR-BACKED simulated workers, one partitioned XLA program over the
# 8-device CPU mesh, same-session canary-stamped A/B vs the
# single-device engine.  Fleet size becomes a device-count knob: the
# fleet SoA rows live sharded on the mesh (scheduler/mirror.py), each
# shard receives only its task tiles (per-shard H2D), and a fresh cycle
# ships ZERO fleet rows per shard (counter-asserted below).
# =====================================================================

N10_TASKS = 10_000_000
N10_WORKERS = 4096


def build_graph_10m(rng):
    import numpy as np

    durations = rng.uniform(0.01, 1.0, N10_TASKS).astype(np.float32)
    out_bytes = rng.uniform(1e3, 1e7, N10_TASKS).astype(np.float32)
    n_deps = rng.integers(0, N_EDGES_PER_TASK + 1, N10_TASKS)
    n_deps[0] = 0
    dst = np.repeat(np.arange(N10_TASKS), n_deps).astype(np.int32)
    src = (rng.random(int(n_deps.sum())) * np.maximum(dst, 1)).astype(
        np.int32
    )
    return durations, out_bytes, src, dst


def cfg_dag_10m():
    import jax
    import numpy as np

    from distributed_tpu.ops.leveled import (
        place_graph_leveled_sharded,
        place_graph_streamed,
        validate_leveled,
    )
    from distributed_tpu.ops.partition import make_engine_mesh
    from distributed_tpu.scheduler.state import SchedulerState

    n_dev = len(jax.devices())
    assert n_dev >= 2, (
        f"dag_10m needs the multi-device CPU mesh, got {jax.devices()}"
    )
    mesh = make_engine_mesh()  # 8 -> 4x2 (tasks x workers)

    canary0 = _host_canary_ms()
    rng = np.random.default_rng(0)
    durations, out_bytes, src, dst = build_graph_10m(rng)

    # mirror-backed fleet: 4096 registered workers; the engine consumes
    # the mirror's workers-axis device shards, so the fleet never
    # re-crosses the wire once resident
    state = SchedulerState()
    assert state.mirror is not None, "dag_10m needs the fleet mirror"
    for i in range(N10_WORKERS):
        state.add_worker_state(f"tcp://dag10m:{i}", nthreads=2,
                               memory_limit=2**30, name=f"w{i}")
    fv = state.mirror.fleet_view()
    nthreads = fv.nthreads.copy()
    occ0 = fv.occupancy.copy()
    running = fv.running.copy()
    fleet_dev = state.mirror.sharded_device_view(mesh)
    assert fleet_dev is not None

    # --- A: single-device engine (warm, then timed) -------------------
    a_args = (durations, out_bytes, src, dst, nthreads, occ0, running)
    packed, res_a = place_graph_streamed(*a_args, bandwidth=BANDWIDTH)
    tm_a: dict = {}
    t0 = time.perf_counter()
    packed, res_a = place_graph_streamed(
        *a_args, bandwidth=BANDWIDTH, timings=tm_a
    )
    wall_a = time.perf_counter() - t0

    # --- B: sharded engine (warm, then timed) -------------------------
    stats_w: dict = {}
    _, res_b = place_graph_streamed(
        *a_args, bandwidth=BANDWIDTH, mesh=mesh,
        fleet_dev=state.mirror.sharded_device_view(mesh), stats=stats_w,
    )
    shard_before = state.mirror.sharded_stats()
    stats_b: dict = {}
    tm_b: dict = {}
    t0 = time.perf_counter()
    _, res_b = place_graph_streamed(
        *a_args, bandwidth=BANDWIDTH, timings=tm_b, mesh=mesh,
        fleet_dev=state.mirror.sharded_device_view(mesh), stats=stats_b,
    )
    wall_b = time.perf_counter() - t0
    shard_after = state.mirror.sharded_stats()

    # fresh-cycle zero fleet H2D, PER SHARD: nothing mutated the fleet
    # between the warm and timed sharded runs, so no shard may have
    # received a row (and none may have been re-packed wholesale)
    assert shard_after["rows_uploaded"] == shard_before["rows_uploaded"], (
        shard_before, shard_after,
    )
    assert shard_after["full_packs"] == shard_before["full_packs"], (
        shard_before, shard_after,
    )

    validate_leveled(packed, res_b, src, dst, running)
    # parity at this scale is QUALITY parity, not per-task identity:
    # the multi-device psum re-associates 3M-element wave-load sums, and
    # with 4096 near-equal workers the spread ordering's float near-ties
    # flip and cascade through worker IDENTITY (measured ~0.73 raw
    # agreement) while load balance, choice mix and total occupancy stay
    # equal — the 1x1 mesh (smoke gate) and moderate scales
    # (tests/test_sharded_engine.py, 1.0 agreement at 1M/1024) pin the
    # identity-refactor claim; this gate pins equal plan quality.
    agreement = float((res_a.assignment == res_b.assignment).mean())
    counts_a = np.bincount(res_a.assignment, minlength=len(nthreads))
    counts = np.bincount(res_b.assignment, minlength=len(nthreads))
    imb_a = float(counts_a.max() / max(counts_a.mean(), 1))
    imb_b = float(counts.max() / max(counts.mean(), 1))
    assert imb_b <= imb_a * 1.05 + 0.01, (
        f"sharded load quality regressed: {imb_a:.4f} -> {imb_b:.4f}"
    )
    occ_rel = abs(
        float(res_b.occupancy.sum()) - float(res_a.occupancy.sum())
    ) / max(float(res_a.occupancy.sum()), 1e-9)
    assert occ_rel < 1e-3, f"total modeled occupancy diverged: {occ_rel}"
    choice_mix_a = np.bincount(res_a.choice, minlength=3) / len(res_a.choice)
    choice_mix_b = np.bincount(res_b.choice, minlength=3) / len(res_b.choice)
    assert np.abs(choice_mix_a - choice_mix_b).max() < 0.05, (
        choice_mix_a, choice_mix_b,
    )
    stock_per_task = bench_stock_python(
        durations, out_bytes, src, dst, n=500, n_workers=N10_WORKERS
    )
    canary1 = _host_canary_ms()
    print(
        f"# dag_10m: single-device {wall_a:.2f} s vs sharded "
        f"{wall_b:.2f} s over {stats_b.get('n_shards')} shards "
        f"({stats_b.get('runs')} fused runs), agreement "
        f"{agreement:.4f}, canary {canary0:.0f}/{canary1:.0f} ms",
        file=sys.stderr,
    )
    return {
        "desc": (
            "10M-task DAG onto 4096 mirror-backed workers: sharded "
            "engine over the device mesh vs single-device, same session"
        ),
        "backend": jax.default_backend(),
        "n_devices": n_dev,
        "mesh": "x".join(str(mesh.shape[a]) for a in mesh.axis_names),
        "single_wall_s": round(wall_a, 3),
        "sharded_wall_s": round(wall_b, 3),
        "wall_s": round(wall_b, 3),
        "sharded_topo_s": round(tm_b.get("topo_s", 0.0), 3),
        "decisions_per_s": round(N10_TASKS / wall_b),
        "agreement": round(agreement, 5),
        "load_imbalance_single": round(imb_a, 4),
        "load_imbalance": round(imb_b, 4),
        "engine_shards": stats_b.get("shards"),
        "mirror_shards": shard_after,
        "fleet_h2d_rows_fresh_cycle": sum(
            a - b
            for a, b in zip(
                shard_after["rows_uploaded"], shard_before["rows_uploaded"]
            )
        ),
        "stock_us_per_task": round(stock_per_task * 1e6),
        "host_canary_ms": round((canary0 + canary1) / 2, 1),
    }


def _sim_10k_once(seed: int, native: bool | None = None):
    """One 1M-task / 10k-virtual-worker run through the real engines on
    the virtual clock; returns (report, digest)."""
    from distributed_tpu.sim import ClusterSim, SyntheticDag

    sim = ClusterSim(
        10_000, nthreads=1, seed=seed, validate=False, native=native,
        # per-link telemetry would build ~10^5 native t-digests at this
        # fleet scale; the headline measures the engines, not telemetry
        config_overrides={"scheduler.telemetry.enabled": False},
    )
    sim.install_digest()
    trace = SyntheticDag(
        n_layers=50, layer_width=20_000, fanin=2, seed=seed,
        layers_per_chunk=2, n_roots=10_000,
        # independent chunk-graphs: completed chunks FORGET, so
        # resident TaskStates stay bounded at a few chunks instead of
        # pinning the whole 1M chain (docs/simulator.md)
        linked_chunks=False,
    )
    t0 = time.perf_counter()
    trace.start(sim)
    report = sim.run()
    report["wall_s"] = round(time.perf_counter() - t0, 1)
    report["n_tasks"] = trace.n_tasks
    report["engine_wall_s"] = round(
        sim.state.wall.totals.get("engine.drain", 0.0), 1
    )
    if sim.state.native is not None:
        report["native"] = sim.state.native.counters()
    digest = sim.digest()
    # quiesce-clean proof at the 1M-task scale (docs/observability.md
    # "State census & retention"): release everything, drain, require
    # zero retained TaskStates and zero non-allowlisted residue across
    # the scheduler + all 10k worker censuses — the bounded-memory
    # oracle the ROADMAP 5(b) fuzzer asserts.  AFTER digest capture:
    # the teardown cascade folds into the running digest.
    from distributed_tpu.sim.validate import check_census_clean

    report["census"] = check_census_clean(sim)
    return report, digest


def cfg_sim_10k():
    """Simulator headline (ROADMAP item 1): place-and-run a 1M-task
    layered graph on 10,000 REAL WorkerState machines + the REAL
    scheduler engine with steal + AMM cycles, single process, virtual
    clock — twice with the same seed: run 1 with the native transition
    engine attached (the config default), run 2 forced onto the pure-
    python oracle.  The virtual makespan and the whole-run transition
    digest must be BIT-IDENTICAL between the two runs — the same-seed
    determinism contract now doubles as the native engine's at-scale
    parity gate (docs/native_engine.md)."""
    rep1, digest1 = _sim_10k_once(seed=0, native=True)
    assert rep1.get("native"), (
        "run 1 did not attach the native engine — the parity gate "
        "would compare oracle against oracle"
    )
    rep2, digest2 = _sim_10k_once(seed=0, native=False)
    assert digest1 == digest2, (
        f"sim_10k native-vs-oracle digests diverged: {digest1} vs "
        f"{digest2}"
    )
    assert rep1["virtual_makespan_s"] == rep2["virtual_makespan_s"], (
        rep1["virtual_makespan_s"], rep2["virtual_makespan_s"],
    )
    assert rep1["keys_done"] >= rep1["keys_wanted"] > 0, rep1
    transitions = (
        rep1["scheduler_transitions"] + rep1["worker_transitions"]
    )
    return {
        "n_tasks": rep1["n_tasks"],
        "n_workers": rep1["n_workers"],
        "virtual_makespan_s": rep1["virtual_makespan_s"],
        "wall_s": [rep1["wall_s"], rep2["wall_s"]],
        "transitions": transitions,
        # transitions/s is the headline the native engine is judged on
        # (ROADMAP item 4); decisions_per_s is the same value under its
        # pre-existing name (one shared local, so they cannot drift)
        "transitions_per_s": (tps := round(transitions / rep1["wall_s"])),
        "scheduler_engine_wall_s": [
            rep1["engine_wall_s"], rep2["engine_wall_s"],
        ],
        "native": rep1.get("native"),
        "decisions_per_s": tps,
        "steals": rep1["steals"],
        "amm_cycles": rep1["counters"].get("amm_cycles", 0),
        "steal_cycles": rep1["counters"].get("steal_cycles", 0),
        "events": rep1["events"],
        "digest": digest1,
        "deterministic": True,
        # the 1M-task quiesce-clean proof (both runs pass or raise)
        "census": rep1["census"],
        "host_canary_ms": _host_canary_ms(),
    }


# =====================================================================
# smoke mode: seconds-scale, CPU-pinned miniatures of the live-path and
# placement-path configs, run by a tier-1 test on every PR so the perf
# plumbing (batched transition engine, coalesced streams, chunked
# pack/upload) is exercised continuously instead of only in full bench
# rounds.  Unlike the headline harness this RAISES on failure — it is a
# CI gate, not a measurement round.
# =====================================================================

SMOKE_TASKS = 120
SMOKE_DAG_TASKS = 6_000


async def _smoke_cluster() -> dict:
    from distributed_tpu.client.client import Client
    from distributed_tpu.deploy.local import LocalCluster
    from distributed_tpu.graph.spec import Graph, TaskRef, TaskSpec

    async with LocalCluster(n_workers=2, threads_per_worker=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            # trivial-task flood: exercises task-finished batch dispatch
            # and the payload-boundary send coalescer
            t0 = time.perf_counter()
            await c.gather(c.map(_inc, range(SMOKE_TASKS)))
            flood_wall = time.perf_counter() - t0
            # small dependent graph: compute-task batches + free/release
            g = Graph()
            for i in range(24):
                g.tasks[f"src-{i}"] = TaskSpec(_inc, (i,))
                g.tasks[f"dep-{i}"] = TaskSpec(_inc, (TaskRef(f"src-{i}"),))
            level = [f"dep-{i}" for i in range(24)]
            g.tasks["root"] = TaskSpec(
                _sum_list, ([TaskRef(k) for k in level],)
            )
            t0 = time.perf_counter()
            futs = c.compute_graph(g, ["root"])
            result = await futs["root"].result()
            graph_wall = time.perf_counter() - t0
            assert result == sum(range(24)) + 48, result
    return {
        "n_tasks": SMOKE_TASKS + len(g.tasks),
        "flood_wall_s": round(flood_wall, 3),
        "graph_wall_s": round(graph_wall, 3),
        "overhead_us_per_task": round(flood_wall / SMOKE_TASKS * 1e6),
    }


def _smoke_placement() -> dict:
    import numpy as np

    from distributed_tpu.ops.leveled import (
        place_graph_streamed,
        validate_leveled,
    )

    rng = np.random.default_rng(0)
    T, W = SMOKE_DAG_TASKS, 32
    durations = rng.uniform(0.01, 1.0, T).astype(np.float32)
    out_bytes = rng.uniform(1e3, 1e7, T).astype(np.float32)
    n_deps = rng.integers(0, 3, T)
    n_deps[0] = 0
    dst = np.repeat(np.arange(T), n_deps).astype(np.int32)
    src = (rng.random(len(dst)) * np.maximum(dst, 1)).astype(np.int32)
    nthreads = np.full(W, 2, np.int32)
    occ0 = np.zeros(W, np.float32)
    running = np.ones(W, bool)
    t0 = time.perf_counter()
    packed, res = place_graph_streamed(
        durations, out_bytes, src, dst, nthreads, occ0, running,
        bandwidth=BANDWIDTH, chunk_rows=2048, min_stream=1,
    )
    wall = time.perf_counter() - t0
    validate_leveled(packed, res, src, dst, running)
    return {
        "n_tasks": T,
        "wall_s": round(wall, 3),
        "n_waves": int(res.n_waves),
    }


def _smoke_mirror() -> dict:
    """Mirror-fed steal + AMM cycle on a 64-worker synthetic fleet: the
    persistent SoA mirror (scheduler/mirror.py) feeds both device
    kernels with zero from-scratch Python packs; raises if a cycle fell
    back to the oracle pack or re-uploaded the whole fleet."""
    from distributed_tpu.scheduler.amm import (
        ActiveMemoryManagerExtension,
        ReduceReplicas,
    )
    from distributed_tpu.scheduler.state import SchedulerState
    from distributed_tpu.scheduler.stealing import WorkStealing
    from distributed_tpu.utils.test import StubScheduler

    state = SchedulerState(validate=True)
    assert state.mirror is not None, "mirror disabled in smoke config"
    sched = StubScheduler(state)
    for i in range(64):
        state.add_worker_state(f"tcp://smoke:{i}", nthreads=1,
                               memory_limit=2**30, name=f"w{i}")
    # after the fleet exists: WorkStealing's init registers the per-
    # worker stealable levels for current workers
    stealing_ext = WorkStealing(sched)
    amm = ActiveMemoryManagerExtension(
        sched, policies=[ReduceReplicas()], register=False, start=False
    )
    workers = list(state.workers.values())
    w0 = workers[0]
    # steal half: a 200-task pile pinned to w0 (loose restrictions)
    from distributed_tpu.graph.spec import TaskSpec

    state.new_task_prefix("smk").add_duration(0.05)
    tasks = {f"smk-{i}": TaskSpec(_inc, (i,)) for i in range(200)}
    state.update_graph_core(
        tasks, {k: set() for k in tasks}, list(tasks), client="smoke",
        annotations_by_key={
            k: {"workers": [w0.address], "allow_other_workers": True}
            for k in tasks
        },
        stimulus_id="smoke-steal",
    )
    idle = [ws for ws in state.idle.values() if ws in state.running]
    t0 = time.perf_counter()
    stealing_ext._balance_device(idle)  # no loop: plans inline
    steal_wall = time.perf_counter() - t0
    n_steals = len(stealing_ext.in_flight)
    assert n_steals > 0, "device balance planned no steals"
    # AMM half: 72 over-replicated keys -> device drop selection
    for i in range(72):
        key = f"rep-{i}"
        state.new_task(key, None).priority = (0,)
        state._transition(key, "memory", "smoke-amm", nbytes=1_000,
                          worker=w0.address)
        for ws in workers[1 + i % 8: 4 + i % 8]:
            state.add_replica(state.tasks[key], ws)
    t0 = time.perf_counter()
    amm.run_once()
    amm_wall = time.perf_counter() - t0
    n_drops = sum(
        len(msg.get("keys", ()))
        for _, wmsgs in sched.sent
        for msgs in wmsgs.values()
        for msg in msgs
        if msg.get("op") == "remove-replicas"
    )
    assert n_drops > 0, "AMM device round dropped nothing"
    stats = state.mirror.stats()
    assert stats["oracle_packs"] == 0, stats
    assert stats["oracle_failures"] == 0, stats
    # device residency: at most the one initial whole-cache upload
    assert stats["full_uploads"] <= 1, stats
    state.mirror.verify()
    return {
        "n_workers": 64,
        "n_steals": n_steals,
        "n_drops": n_drops,
        "steal_cycle_s": round(steal_wall, 3),
        "amm_cycle_s": round(amm_wall, 3),
        "mirror": stats,
    }


def _smoke_mesh() -> dict:
    """Sharded-engine gate on the 8-device CPU mesh (the same
    ``xla_force_host_platform_device_count`` fallback conftest uses):

    - the 1x1 mesh must reproduce the single-device engine
      BIT-IDENTICALLY (the sharded path is the identity refactor there);
    - the full mesh, fed the MIRROR's workers-axis fleet shards, must
      agree with the single-device placements;
    - a fresh second cycle must ship ZERO fleet rows on every shard and
      must not re-pack any shard wholesale.

    Raises on any violation — this is the CI gate for the dag_10m
    architecture at seconds scale.
    """
    import jax
    import numpy as np

    from distributed_tpu.ops.leveled import (
        pack_graph,
        place_graph_leveled,
        place_graph_leveled_sharded,
        validate_leveled,
    )
    from distributed_tpu.ops.partition import make_engine_mesh
    from distributed_tpu.scheduler.state import SchedulerState

    assert len(jax.devices()) >= 2, (
        f"mesh smoke needs the multi-device CPU mesh, got {jax.devices()}"
    )
    T, W = SMOKE_DAG_TASKS, 64
    rng = np.random.default_rng(5)
    durations = rng.uniform(0.01, 1.0, T).astype(np.float32)
    out_bytes = rng.uniform(1e3, 1e7, T).astype(np.float32)
    n_deps = rng.integers(0, 3, T)
    n_deps[0] = 0
    dst = np.repeat(np.arange(T), n_deps).astype(np.int32)
    src = (rng.random(len(dst)) * np.maximum(dst, 1)).astype(np.int32)
    packed = pack_graph(durations, out_bytes, src, dst,
                        bandwidth=BANDWIDTH)

    state = SchedulerState()
    assert state.mirror is not None, "mirror disabled in smoke config"
    for i in range(W):
        state.add_worker_state(f"tcp://mesh:{i}", nthreads=2,
                               memory_limit=2**30, name=f"w{i}")
    fv = state.mirror.fleet_view()
    nthreads = fv.nthreads.copy()
    occ0 = fv.occupancy.copy()
    running = fv.running.copy()

    res_1d = place_graph_leveled(packed, nthreads, occ0, running)

    # identity refactor: 1x1 mesh, bit-identical
    mesh1 = make_engine_mesh(layout="1x1")
    r11 = place_graph_leveled_sharded(mesh1, packed, nthreads, occ0,
                                      running)
    assert np.array_equal(r11.assignment, res_1d.assignment), (
        "1x1 sharded engine is not the identity refactor"
    )
    assert np.array_equal(r11.choice, res_1d.choice)

    # full mesh, mirror-resident fleet
    mesh = make_engine_mesh()
    stats: dict = {}
    t0 = time.perf_counter()
    r_sh = place_graph_leveled_sharded(
        mesh, packed, nthreads, occ0, running,
        fleet_dev=state.mirror.sharded_device_view(mesh), stats=stats,
    )
    wall = time.perf_counter() - t0
    validate_leveled(packed, r_sh, src, dst, running)
    agreement = float((r_sh.assignment == res_1d.assignment).mean())
    assert agreement > 0.97, (
        f"sharded/single-device parity divergence: {agreement:.4f}"
    )

    # fresh cycle: zero fleet H2D per shard, no wholesale re-pack
    before = state.mirror.sharded_stats()
    r_sh2 = place_graph_leveled_sharded(
        mesh, packed, nthreads, occ0, running,
        fleet_dev=state.mirror.sharded_device_view(mesh),
    )
    after = state.mirror.sharded_stats()
    assert after["rows_uploaded"] == before["rows_uploaded"], (
        f"fresh cycle scattered fleet rows per shard: {before} -> {after}"
    )
    assert after["full_packs"] == before["full_packs"], (
        f"fresh cycle re-packed a shard wholesale: {before} -> {after}"
    )
    assert np.array_equal(r_sh2.assignment, r_sh.assignment)

    return {
        "n_tasks": T,
        "n_workers": W,
        "mesh": "x".join(str(mesh.shape[a]) for a in mesh.axis_names),
        "wall_s": round(wall, 3),
        "agreement": round(agreement, 5),
        "identity_1x1": True,
        "engine_shards": stats.get("shards"),
        "mirror_shards": after,
    }


async def _smoke_wire() -> dict:
    """Wire microbench: loopback TCP echo round trips at 1 KB / 64 KB /
    8 MB frames through the real comm stack, next to a join-copy
    baseline writer over the same streams.  Raises if the zero-copy
    send contract breaks (any payload copy recorded) or the pool never
    gets a hit."""
    import numpy as np

    from distributed_tpu.comm.core import connect, listen
    from distributed_tpu.protocol.buffers import WIRE
    from distributed_tpu.protocol.serialize import Serialize

    async def echo(comm):
        try:
            while True:
                msg = await comm.read()
                await comm.write({"op": "ack", "n": msg["n"]})
        except Exception:
            pass

    listener = listen("tcp://127.0.0.1:0", echo)
    await listener.start()
    comm = await connect(listener.contact_address)
    out: dict = {"mb_s": {}}
    try:
        before = WIRE.snapshot()
        for label, size, reps in (
            ("1KB", 1024, 60), ("64KB", 65536, 30), ("8MB", 8 * 2**20, 3)
        ):
            payload = np.random.default_rng(0).integers(
                0, 256, size, dtype=np.uint8
            )
            await comm.write({"n": size, "data": Serialize(payload)})
            await comm.read()  # warm
            t0 = time.perf_counter()
            for _ in range(reps):
                await comm.write({"n": size, "data": Serialize(payload)})
                await comm.read()
            wall = time.perf_counter() - t0
            out["mb_s"][label] = round(size * reps / wall / 2**20, 1)
        after = WIRE.snapshot()
    finally:
        await comm.close()
        listener.stop()
    out["payload_copies"] = after["payload_copies"] - before["payload_copies"]
    out["pool_hits"] = after["pool_hits"] - before["pool_hits"]
    out["wire_mb"] = round((after["bytes_sent"] - before["bytes_sent"]) / 2**20, 1)
    assert out["payload_copies"] == 0, (
        f"zero-copy send contract broken: {out['payload_copies']} payload "
        f"copies on a tcp round trip"
    )
    assert out["pool_hits"] > 0, "receive pool recorded no reuse"
    return out


def _smoke_trace() -> dict:
    """Flight-recorder gate (tracing.py; docs/observability.md): floods
    the batched engine traced-on vs traced-off on identical synthetic
    states (same-session A/B, min-of-N, canary-stamped) and raises if

    - traced-on overhead exceeds 5%,
    - the fast-path ``emit`` allocates (``sys.getallocatedblocks``
      delta over a 20k-emit burst), or
    - a recorded stimulus journal replayed through the batched engine
      does not reproduce the identical transition stream.
    """
    import sys as _sys

    from distributed_tpu import config as dtpu_config
    from distributed_tpu.diagnostics.flight_recorder import (
        replay_stimulus_trace,
        transition_stream,
    )
    from distributed_tpu.graph.spec import TaskSpec
    from distributed_tpu.scheduler.state import SchedulerState

    # REPS 7: the min-per-pair estimator needs one CLEAN pair; on a
    # degraded box phase 5 pairs sometimes all read 5-15% high with
    # the feature OFF too (measured), while a real overhead shows in
    # every pair — more pairs only reduce false alarms
    N_WORKERS, N_TASKS, REPS = 16, 2000, 7

    def build(enabled):
        with dtpu_config.set({"scheduler.trace.enabled": enabled}):
            state = SchedulerState(validate=False)
            for i in range(N_WORKERS):
                state.add_worker_state(
                    f"tcp://trace:{i}", nthreads=2, memory_limit=2**30,
                    name=f"t{i}",
                )
            tasks = {f"trc-{i}": TaskSpec(_inc, (i,)) for i in range(N_TASKS)}
            state.update_graph_core(
                tasks, {k: set() for k in tasks}, list(tasks),
                client="smoke", stimulus_id="smoke-trace-graph",
            )
        return state

    def flood(state) -> float:
        """Drive every task to memory via task-finished floods, one
        batched engine pass per 'stream payload' (the processing set)."""
        t0 = time.perf_counter()
        rounds = 0
        while True:
            batch = [
                (ts.key, ws.address, f"smk-fin-{ts.key}", {"nbytes": 8})
                for ws in state.workers.values()
                for ts in list(ws.processing)
            ]
            if not batch:
                break
            state.stimulus_tasks_finished_batch(batch)
            rounds += 1
            assert rounds < 10 * N_TASKS, "flood did not converge"
        return time.perf_counter() - t0

    # A/B: one untimed warmup per arm first (the process's first flood
    # pays allocator/code warmup — without this the arm that happens to
    # run first eats it as fake overhead), then back-to-back pairs.
    # Estimator: the MINIMUM per-pair on/off ratio — a real overhead
    # shows up in every adjacent pair, while this box's one-sided floor
    # noise (±7% between two 0.1s runs, PERF.md "2x drift") does not,
    # so min-of-ratios is the drift-robust gate (min-of-walls flaked)
    flood(build(True))
    flood(build(False))
    on_walls, off_walls = [], []
    for _ in range(REPS):
        on_walls.append(flood(build(True)))
        off_walls.append(flood(build(False)))
    min_ratio = min(on / off for on, off in zip(on_walls, off_walls))
    overhead_pct = max(0.0, (min_ratio - 1.0) * 100)
    assert overhead_pct < 5.0, (
        f"traced-on overhead {overhead_pct:.1f}% exceeds the 5% budget "
        f"(on={on_walls}, off={off_walls})"
    )

    # allocation contract on the fast path: steady-state emits allocate
    # nothing (ints/floats replaced in place net to ~0 blocks).  Warm a
    # FULL ring wrap first: the first pass retires each slot's shared
    # initial 0.0 for a resident float, which is one-time ring capacity
    # cost, not per-event allocation.
    tr = build(True).trace
    for _ in range(len(tr) + tr._mask + 2):
        tr.emit("engine", "alloc-check", "smoke-alloc")
    b0 = _sys.getallocatedblocks()
    for _ in range(20_000):
        tr.emit("engine", "alloc-check", "smoke-alloc")
    alloc_delta = _sys.getallocatedblocks() - b0
    assert alloc_delta < 50, (
        f"fast-path emit allocated ({alloc_delta} blocks over 20k events)"
    )

    # record-then-replay parity: journal a flood, re-feed it through the
    # batched engine on an identically-built state, require the
    # identical transition stream (key, start, finish, stimulus, order)
    rec_state = build(True)
    mark = len(rec_state.transition_log)
    rec_state.trace.journal_start()
    flood(rec_state)
    records = list(rec_state.trace.journal)
    assert records, "journal captured nothing in record mode"
    rep_state = build(True)
    mark_b = len(rep_state.transition_log)
    replay_stimulus_trace(rep_state, records)
    recorded = transition_stream(rec_state, mark)
    replayed = transition_stream(rep_state, mark_b)
    assert recorded == replayed, (
        "replayed transition stream diverged from the recording "
        f"(recorded {len(recorded)} rows, replayed {len(replayed)})"
    )

    n_events = rec_state.trace.total
    assert n_events > 0, "traced run emitted no flight-recorder events"
    return {
        "n_workers": N_WORKERS,
        "n_tasks": N_TASKS,
        "traced_on_s": [round(w, 3) for w in on_walls],
        "traced_off_s": [round(w, 3) for w in off_walls],
        "overhead_pct": round(overhead_pct, 2),
        "alloc_delta_blocks": alloc_delta,
        "replay_match": True,
        "replay_rows": len(recorded),
        "n_events": n_events,
        "host_canary_ms": _host_canary_ms(),
    }


async def _smoke_stall_watchdog() -> dict:
    """Deterministic stall-watchdog half of the selfprofile gate: a
    synthetic loop block (a tight busy-wait INSIDE a coroutine, well
    past the threshold) must produce EXACTLY ONE stall capture whose
    traceback names the blocking frame, plus a flight-recorder
    ``stall`` event — and a recovered loop must re-arm cleanly."""
    import asyncio
    import threading

    from distributed_tpu.diagnostics.selfprofile import LoopWatchdog
    from distributed_tpu.tracing import FlightRecorder

    tr = FlightRecorder(enabled=True, ring_size=64)
    wd = LoopWatchdog(trace=tr, interval=0.02, stall_threshold=0.12)
    wd.start(threading.get_ident())

    async def ticker():
        while True:
            wd.tick()
            await asyncio.sleep(0.02)

    tick_task = asyncio.create_task(ticker())
    try:
        await asyncio.sleep(0.1)  # healthy baseline ticks

        def _block_loop():
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < 0.35:
                pass  # the synthetic stall: the loop thread is pinned here

        _block_loop()
        await asyncio.sleep(0.3)  # recovery window: watchdog re-arms
    finally:
        tick_task.cancel()
        wd.stop()
    assert wd.stalls_total == 1, (
        f"expected exactly one stall capture, got {wd.stalls_total}"
    )
    stall = wd.stalls[0]
    assert "_block_loop" in stall["traceback"], stall["traceback"]
    stall_events = [e for e in tr.tail() if e["cat"] == "stall"]
    assert len(stall_events) == 1 and "_block_loop" in stall_events[0]["key"]
    assert wd.hist_lag.count > 0
    return {
        "stall_events": wd.stalls_total,
        "stall_lag_s": stall["lag_s"],
        "stall_frame_named": True,
        "ticks": wd.ticks_total,
    }


def _smoke_selfprofile() -> dict:
    """Control-plane self-profiler gate (diagnostics/selfprofile.py;
    docs/observability.md "Self-profiling"): floods the batched engine
    with the always-on control-plane sampler ON vs OFF on identical
    synthetic states (same-session A/B, min-per-pair-ratio estimator —
    the drift-robust gate from the trace smoke) and raises if

    - sampling-on overhead exceeds 5% (the always-on contract),
    - the sampled tree carries no phase-stamped samples or the wall
      budget recorded no ``engine.drain`` seconds,
    - arm attribution (opt-in) produces no per-arm rows, or
    - the deterministic stall-watchdog scenario above fails.
    """
    import asyncio
    import threading

    from distributed_tpu.diagnostics.selfprofile import ControlPlaneProfiler
    from distributed_tpu import config as dtpu_config
    from distributed_tpu.graph.spec import TaskSpec
    from distributed_tpu.scheduler.state import SchedulerState

    # REPS 7: the min-per-pair estimator needs one CLEAN pair (see the
    # trace smoke's rationale)
    N_WORKERS, N_TASKS, REPS = 16, 2000, 7

    def build():
        state = SchedulerState(validate=False)
        for i in range(N_WORKERS):
            state.add_worker_state(
                f"tcp://prof:{i}", nthreads=2, memory_limit=2**30,
                name=f"p{i}",
            )
        tasks = {f"prf-{i}": TaskSpec(_inc, (i,)) for i in range(N_TASKS)}
        state.update_graph_core(
            tasks, {k: set() for k in tasks}, list(tasks),
            client="smoke", stimulus_id="smoke-selfprofile-graph",
        )
        return state

    def flood(state) -> float:
        t0 = time.perf_counter()
        rounds = 0
        while True:
            batch = [
                (ts.key, ws.address, f"prf-fin-{ts.key}", {"nbytes": 8})
                for ws in state.workers.values()
                for ts in list(ws.processing)
            ]
            if not batch:
                break
            state.stimulus_tasks_finished_batch(batch)
            rounds += 1
            assert rounds < 10 * N_TASKS, "flood did not converge"
        return time.perf_counter() - t0

    main_ident = threading.get_ident()

    def run(profiled: bool) -> float:
        state = build()
        prof = None
        if profiled:
            # default config rate: the gate measures the ALWAYS-ON cost
            prof = ControlPlaneProfiler(
                idents=lambda: [main_ident], wall=state.wall
            )
            prof.start()
        try:
            return flood(state)
        finally:
            if prof is not None:
                prof.stop()

    run(True)   # untimed warmup per arm (allocator/code warm)
    run(False)
    on_walls, off_walls = [], []
    for _ in range(REPS):
        on_walls.append(run(True))
        off_walls.append(run(False))
    min_ratio = min(on / off for on, off in zip(on_walls, off_walls))
    overhead_pct = max(0.0, (min_ratio - 1.0) * 100)
    assert overhead_pct < 5.0, (
        f"sampling-on overhead {overhead_pct:.1f}% exceeds the 5% budget "
        f"(on={on_walls}, off={off_walls})"
    )

    # attribution probe: a dense-rate profiled flood must produce
    # phase-stamped samples and nonzero engine.drain wall
    probe = build()
    prof = ControlPlaneProfiler(
        idents=lambda: [main_ident], wall=probe.wall, interval=0.002
    )
    prof.start()
    flood(probe)
    prof.stop()
    wall = probe.wall.snapshot()
    assert wall.get("engine.drain", 0.0) > 0.0, wall
    assert prof.total_samples > 0
    tree = prof.get_profile()
    phase_nodes = [
        k for k in tree["children"] if k.startswith("phase:engine.drain")
    ]
    assert phase_nodes, list(tree["children"])
    assert any(ph == "engine.drain" for _, ph, _s in prof.samples)

    # opt-in arm attribution: per-arm rows exist and cover most of the
    # engine wall (the sim.profile_run artifact's property); its cost
    # is REPORTED here, gated only by the profile_run tier-1 test
    with dtpu_config.set({"scheduler.profile.arm-attribution": True}):
        arm_state = build()
    arm_wall = flood(arm_state)
    totals = arm_state.wall.snapshot()
    arms = {
        k: v for k, v in totals.items()
        if k.startswith("engine.scalar-arm:")
    }
    assert arms, "arm attribution produced no per-arm rows"
    engine_wall = totals.get("engine.drain", 0.0) + sum(arms.values())
    arm_share = sum(arms.values()) / engine_wall if engine_wall else 0.0

    out = asyncio.run(_smoke_stall_watchdog())
    out.update({
        "n_workers": N_WORKERS,
        "n_tasks": N_TASKS,
        "sampling_on_s": [round(w, 3) for w in on_walls],
        "sampling_off_s": [round(w, 3) for w in off_walls],
        "overhead_pct": round(overhead_pct, 2),
        "samples": prof.total_samples,
        "engine_drain_wall_s": round(wall["engine.drain"], 4),
        "arm_rows": len(arms),
        "arm_share": round(arm_share, 3),
        "arm_flood_s": round(arm_wall, 3),
        "host_canary_ms": _host_canary_ms(),
    })
    return out


async def _smoke_telemetry_links() -> dict:
    """Measured-link half of the telemetry gate (telemetry.py): a tcp
    echo through the real comm stack files per-round-trip link samples
    through the REAL collector class workers use, and the collector's
    EWMA bandwidth must land within 2x of the bench's own observed
    MB/s.  The measured/constant ratio is reported as the Round 4
    artifact — the loopback truth vs the 100 MB/s `scheduler.bandwidth`
    constant — and must diverge by >1.5x (the "constant is ~10x off"
    finding, reproduced and checked on every PR)."""
    import numpy as np

    from distributed_tpu import config as dtpu_config
    from distributed_tpu.comm.core import connect, listen
    from distributed_tpu.protocol.serialize import Serialize
    from distributed_tpu.telemetry import LinkTelemetry
    from distributed_tpu.utils.misc import time as mono

    async def echo(comm):
        try:
            while True:
                msg = await comm.read()
                await comm.write({"op": "ack", "data": msg["data"]})
        except Exception:
            pass

    listener = listen("tcp://127.0.0.1:0", echo)
    await listener.start()
    comm = await connect(listener.contact_address)
    collector = LinkTelemetry(enabled=True)
    src, dst = listener.contact_address, "tcp://smoke-requester"
    size, reps = 4 * 2**20, 4
    payload = np.random.default_rng(0).integers(0, 256, size, dtype=np.uint8)
    try:
        await comm.write({"data": Serialize(payload)})
        await comm.read()  # warm
        t0 = time.perf_counter()
        for _ in range(reps):
            m0 = mono()
            await comm.write({"data": Serialize(payload)})
            await comm.read()
            # one round trip moves the payload BOTH ways; file the echo
            # leg as one link sample, exactly as _gather_dep files a
            # fetch (payload bytes over the full round trip)
            collector.record(src, dst, size, mono() - m0)
        wall = time.perf_counter() - t0
    finally:
        await comm.close()
        listener.stop()
    bench_bw = size * reps / wall  # bytes/s, same numerator as samples
    link = collector.links[(src, dst)]
    measured_bw = link.bandwidth.value
    n_samples = link.bandwidth.count
    assert n_samples == reps and link.bytes_total == size * reps, (
        "tcp echo produced no/short link samples"
    )
    assert bench_bw / 2 <= measured_bw <= bench_bw * 2, (
        f"collector EWMA bandwidth {measured_bw / 2**20:.1f} MB/s not "
        f"within 2x of the bench's observed {bench_bw / 2**20:.1f} MB/s"
    )
    constant = float(dtpu_config.get("scheduler.bandwidth"))
    constant_ratio = measured_bw / constant
    assert constant_ratio > 1.5 or constant_ratio < 1 / 1.5, (
        f"loopback measured bandwidth {measured_bw / 2**20:.1f} MB/s "
        f"does not diverge >1.5x from the scheduler.bandwidth constant "
        f"({constant / 2**20:.1f} MB/s) — the Round 4 artifact "
        f"disappeared; re-examine the constant"
    )
    # heartbeat-delta encode/fold round trip stays intact
    rows = collector.rows(collector.take())
    assert rows and rows[0][4] == reps
    return {
        "n_link_samples": n_samples,
        "measured_mb_s": round(measured_bw / 2**20, 1),
        "bench_mb_s": round(bench_bw / 2**20, 1),
        "bw_within_2x": True,
        "constant_ratio": round(constant_ratio, 2),
    }


def _smoke_telemetry() -> dict:
    """Telemetry gate (telemetry.py; docs/observability.md): measured
    link samples off a real tcp echo (above), plus the shadow-monitor
    overhead contract — telemetry-on vs -off engine floods on identical
    synthetic states, gated <5% with the MIN PER-PAIR RATIO estimator
    (the drift-robust A/B from the trace smoke)."""
    import asyncio

    from distributed_tpu import config as dtpu_config
    from distributed_tpu.graph.spec import TaskSpec
    from distributed_tpu.scheduler.state import SchedulerState

    out = asyncio.run(_smoke_telemetry_links())

    # REPS 7: the min-per-pair estimator needs one CLEAN pair; on a
    # degraded box phase 5 pairs sometimes all read 5-15% high with
    # the feature OFF too (measured), while a real overhead shows in
    # every pair — more pairs only reduce false alarms
    N_WORKERS, N_TASKS, REPS = 16, 2000, 7
    addrs = [f"tcp://tel:{i}" for i in range(N_WORKERS)]

    def build(enabled):
        with dtpu_config.set({"scheduler.telemetry.enabled": enabled}):
            state = SchedulerState(validate=False)
        for i, a in enumerate(addrs):
            state.add_worker_state(
                a, nthreads=2, memory_limit=2**30, name=f"t{i}"
            )
        if enabled:
            # measured links exist so the shadow evals take the
            # real (per-dep link scan) path, not the cheap fallback
            state.telemetry.fold_rows(
                [[addrs[i], addrs[(i + 1) % N_WORKERS],
                  1_000_000_000, 1.0, 4] for i in range(N_WORKERS)],
                reporter="",
            )
        tasks = {f"tlm-{i}": TaskSpec(_inc, (i,)) for i in range(N_TASKS)}
        deps: dict = {f"tlm-{i}": set() for i in range(N_TASKS)}
        for i in range(0, N_TASKS, 4):
            tasks[f"tld-{i}"] = TaskSpec(_inc, (i,))
            deps[f"tld-{i}"] = {f"tlm-{i}", f"tlm-{(i + 1) % N_TASKS}"}
        state.update_graph_core(
            tasks, deps, list(tasks), client="smoke",
            stimulus_id="smoke-telemetry-graph",
        )
        return state

    def flood(state) -> float:
        t0 = time.perf_counter()
        rounds = 0
        while True:
            batch = [
                (ts.key, ws.address, f"tel-fin-{ts.key}", {"nbytes": 8})
                for ws in state.workers.values()
                for ts in list(ws.processing)
            ]
            if not batch:
                break
            state.stimulus_tasks_finished_batch(batch)
            rounds += 1
            assert rounds < 10 * N_TASKS, "flood did not converge"
        return time.perf_counter() - t0

    flood(build(True))   # untimed warmup per arm (allocator/code warm)
    flood(build(False))
    on_walls, off_walls = [], []
    for _ in range(REPS):
        on_walls.append(flood(build(True)))
        off_walls.append(flood(build(False)))
    min_ratio = min(on / off for on, off in zip(on_walls, off_walls))
    overhead_pct = max(0.0, (min_ratio - 1.0) * 100)
    assert overhead_pct < 5.0, (
        f"telemetry-on overhead {overhead_pct:.1f}% exceeds the 5% "
        f"budget (on={on_walls}, off={off_walls})"
    )
    probe = build(True)
    flood(probe)
    assert probe.telemetry.shadow_evals > 0, (
        "telemetry-on flood performed no shadow evaluations"
    )
    assert probe.telemetry.hist_divergence.count > 0
    out.update({
        "n_workers": N_WORKERS,
        "n_tasks": N_TASKS,
        "telemetry_on_s": [round(w, 3) for w in on_walls],
        "telemetry_off_s": [round(w, 3) for w in off_walls],
        "overhead_pct": round(overhead_pct, 2),
        "shadow_evals": probe.telemetry.shadow_evals,
        "shadow_measured": probe.telemetry.shadow_measured,
        "host_canary_ms": _host_canary_ms(),
    })
    return out


def _smoke_sim() -> dict:
    """Simulator gate (distributed_tpu/sim; docs/simulator.md): the
    tier-1 miniature of ``sim_10k``.  Raises if

    - two same-seed runs (48 virtual workers, ~1k tasks, steal + AMM
      cycles live) do not produce BIT-IDENTICAL whole-run digests,
      transition-stream digests, and virtual makespans — the
      determinism contract every sim-based perf gate rests on;
    - a worker-death chaos run loses a key or leaves the replica model
      disagreeing with the fleet;
    - a journal recorded from a simulated run does not replay through
      the batched engine to the identical transition stream (the
      sim <-> live replay-format contract, docs/observability.md).
    """
    from distributed_tpu.diagnostics.flight_recorder import (
        replay_stimulus_trace,
        transition_stream,
    )
    from distributed_tpu.sim import ClusterSim, SyntheticDag
    from distributed_tpu.sim.chaos import scenario_worker_death
    from distributed_tpu.sim.validate import check_no_lost_keys

    N_WORKERS, LAYERS, WIDTH = 48, 12, 90

    def build(run_periodics=True, layers=LAYERS, chunk=3):
        sim = ClusterSim(
            N_WORKERS, seed=0, validate=True,
            steal_interval=None if run_periodics else 0,
            amm_interval=None if run_periodics else 0,
            find_missing_interval=1.0 if run_periodics else 0,
        )
        sim.install_digest()
        trace = SyntheticDag(
            n_layers=layers, layer_width=WIDTH, fanin=2, seed=0,
            layers_per_chunk=chunk,
        )
        return sim, trace

    t0 = time.perf_counter()
    sim1, tr1 = build()
    tr1.start(sim1)
    rep1 = sim1.run()
    wall = time.perf_counter() - t0
    check_no_lost_keys(sim1)
    sim2, tr2 = build()
    tr2.start(sim2)
    rep2 = sim2.run()
    check_no_lost_keys(sim2)
    assert sim1.digest() == sim2.digest(), (
        f"same-seed sim digests diverged: {sim1.digest()} {sim2.digest()}"
    )
    assert rep1["virtual_makespan_s"] == rep2["virtual_makespan_s"], (
        rep1["virtual_makespan_s"], rep2["virtual_makespan_s"],
    )

    # chaos mini: deterministic worker death converges with no lost keys
    _csim, crep = scenario_worker_death(seed=1, n_workers=12)
    assert crep["keys_done"] >= crep["keys_wanted"], crep

    # record -> replay parity: a sim-captured stimulus journal re-fed
    # through the batched engine reproduces the identical stream.
    # Single-chunk workload: the journal records ENGINE stimuli, so the
    # replay state must be structurally identical up front — chunked
    # submission materializes tasks mid-run, outside the contract
    # (docs/observability.md "replayable stimulus-trace format")
    rsim, rtrace = build(run_periodics=False, layers=5, chunk=5)
    rtrace.start(rsim)
    mark = len(rsim.state.transition_log)
    rsim.journal_start()
    rsim.run()
    records = rsim.journal()
    assert records, "sim journal captured nothing"
    psim, ptrace = build(run_periodics=False, layers=5, chunk=5)
    ptrace.start(psim)
    mark_p = len(psim.state.transition_log)
    replay_stimulus_trace(psim.state, records)
    recorded = transition_stream(rsim.state, mark)
    replayed = transition_stream(psim.state, mark_p)
    assert recorded == replayed, (
        f"sim journal replay diverged ({len(recorded)} vs "
        f"{len(replayed)} rows)"
    )

    transitions = rep1["scheduler_transitions"] + rep1["worker_transitions"]
    return {
        "n_workers": N_WORKERS,
        "n_tasks": LAYERS * WIDTH,
        "virtual_makespan_s": rep1["virtual_makespan_s"],
        "wall_s": round(wall, 2),
        "transitions": transitions,
        "decisions_per_s": round(transitions / wall),
        "steals": rep1["steals"],
        "digest": sim1.digest(),
        "deterministic": True,
        "chaos_death_lost": crep["keys_lost"],
        "replay_match": True,
        "replay_rows": len(recorded),
    }


async def _hard_kill_scheduler(s) -> None:
    """Crash, not close: abort every stream/comm/callback WITHOUT the
    graceful protocol (no close-worker ops, no final durability
    snapshot) — the durable image is whatever already hit disk.  The
    in-process approximation of kill -9 on the scheduler."""
    from distributed_tpu.rpc.core import Status

    s.status = Status.closing  # stops the comm loops mid-read
    for pc in s.periodic_callbacks.values():
        pc.stop()
    s.periodic_callbacks.clear()
    if s.watchdog is not None:
        s.watchdog.stop()
    if s.cp_profiler is not None:
        s.cp_profiler.stop()
    for listener in s.listeners:
        listener.stop()
    for bs in list(s.stream_comms.values()):
        bs.abort()
    s.stream_comms.clear()
    for bs in list(s.client_comms.values()):
        bs.abort()
    s.client_comms.clear()
    for comm in list(s._comms):
        try:
            comm.abort()
        except Exception:
            pass
    await s._ongoing_background_tasks.stop()
    await s.rpc.close()
    if s.http_server is not None:
        await s.http_server.stop()
    s.status = Status.closed
    s._event_finished.set()


async def _smoke_restart_live() -> dict:
    """Live half of the restart gate (scheduler/durability.py;
    docs/durability.md): a real TCP cluster computes 40 keys, the
    scheduler snapshots and is then HARD-bounced (comms aborted, no
    graceful close); a fresh scheduler process-equivalent restarts on
    the same port from snapshot + journal tail, the workers reconnect
    with backoff+jitter carrying their held keys, and the gate asserts

    - ZERO lost completed keys: every pre-bounce memory key is memory
      with a live worker replica on the restarted scheduler;
    - recovery under budget: restore + full worker re-registration
      completes within the (generous, hang-guarding) RTO deadline;
    - liveness: a fresh client computes new work against the restarted
      scheduler.
    """
    import asyncio
    import shutil
    import tempfile

    from distributed_tpu import config as dtpu_config
    from distributed_tpu.client.client import Client
    from distributed_tpu.scheduler.server import Scheduler
    from distributed_tpu.worker.server import Worker

    tmp = tempfile.mkdtemp(prefix="dtpu-smoke-restart-")
    overrides = {
        "scheduler.jax.enabled": False,
        "scheduler.durability.directory": tmp,
        "scheduler.durability.snapshot-interval": "500ms",
        "scheduler.durability.flush-interval": "50ms",
        "scheduler.durability.grace": "15s",
        "worker.reconnect-attempts": 40,
        "worker.register.base-delay": "50ms",
        "worker.register.max-delay": "250ms",
    }
    N = 40
    workers: list = []
    s2 = None
    c = None
    try:
        with dtpu_config.set(overrides):
            s1 = Scheduler(listen_addr="tcp://127.0.0.1:0", validate=True)
            await s1.start()
            addr = s1.address
            for i in range(2):
                w = Worker(addr, name=f"rw{i}", nthreads=1, validate=True,
                           listen_addr="tcp://127.0.0.1:0")
                await w.start()
                workers.append(w)
            c = Client(addr)
            await c.__aenter__()
            futs = c.map(_inc, range(N))
            res = await c.gather(futs)
            assert res == list(range(1, N + 1)), res[:5]
            # one explicit epoch now, then MORE completed work so the
            # crash leaves a real journal tail: the second batch's graph
            # intake and completions are durable only as tail records
            s1.durability.snapshot()
            futs2 = c.map(_inc, range(N, N + 10))
            res2 = await c.gather(futs2)
            assert res2 == list(range(N + 1, N + 11)), res2
            pre_keys = sorted(
                k for k, ts in s1.state.tasks.items()
                if ts.state == "memory"
            )
            assert len(pre_keys) >= N + 10, pre_keys
            s1.durability.flush_journal()
            t_kill = time.perf_counter()
            await _hard_kill_scheduler(s1)
            s1.durability.sink.drain()  # queued writes had hit disk pre-crash

            # restart on the SAME port: the workers' reconnect loop is
            # already probing it with backoff + jitter
            s2 = Scheduler(listen_addr=addr, validate=True)
            await s2.start()
            restore_s = s2.durability.stats.restore_seconds
            assert restore_s > 0, "restart did not restore from the sink"
            assert s2.durability.stats.replay_records > 0, (
                "the bounce left no journal tail — the gate must "
                "exercise snapshot + TAIL replay, not snapshot alone"
            )
            worker_addrs = {w.address for w in workers}
            deadline = time.perf_counter() + 30
            lost: list = list(pre_keys)
            while time.perf_counter() < deadline:
                lost = [
                    k for k in pre_keys
                    if (ts := s2.state.tasks.get(k)) is None
                    or ts.state != "memory" or not ts.who_has
                ]
                reregistered = worker_addrs <= set(s2.stream_comms)
                if not lost and reregistered:
                    break
                await asyncio.sleep(0.05)
            rto_live = time.perf_counter() - t_kill
            assert not lost, (
                f"{len(lost)} completed keys lost across the bounce: "
                f"{lost[:5]}"
            )
            assert worker_addrs <= set(s2.stream_comms), (
                "workers never re-registered", sorted(s2.stream_comms)
            )
            assert rto_live < 30, f"recovery took {rto_live:.1f}s"
            # liveness: fresh work through the restarted control plane
            async with Client(addr) as c2:
                res2 = await c2.gather(c2.map(_inc, range(100, 110)))
                assert res2 == list(range(101, 111)), res2
            return {
                "pre_keys": len(pre_keys),
                "lost_completed_keys": 0,
                "rto_live_s": round(rto_live, 3),
                "restore_s": round(restore_s, 4),
                "replay_records": s2.durability.stats.replay_records,
                "torn_records": s2.durability.stats.torn_records,
                "workers_reregistered": len(worker_addrs),
                "liveness_ok": True,
            }
    finally:
        if c is not None:
            try:
                await asyncio.wait_for(c.close(), 5)
            except Exception:
                pass
        for w in workers:
            try:
                await w.close(report=False)
            except Exception:
                pass
        if s2 is not None:
            await s2.close()
        shutil.rmtree(tmp, ignore_errors=True)


def _smoke_restart_capture() -> dict:
    """Synthetic half of the restart gate: steady-state capture
    overhead + the measured-RTO curve.

    - **Overhead**: durability armed (dirty tracker + journal-segment
      capture — the always-on, every-flood cost) vs off on identical
      engine floods, min-per-pair-ratio (the drift-robust estimator
      from the trace smoke) must stay under 5%.  Snapshot ENCODE cost
      is deliberately off the timed path here: it is the periodic
      O(changed-rows) cost, measured and reported below, and amortized
      by the snapshot-interval (default 5s) in production — the
      reported ``amortized_snapshot_pct`` pins that claim.
    - **RTO curve**: the same flood captured at three snapshot
      cadences (many deltas / few deltas / base-only) restores into a
      fresh state — fold + rebuild + digest-verify + tail replay —
      and each point reports (epochs, tail records, restore seconds),
      with the restored state digest-identical to the original.
    """
    from distributed_tpu import config as dtpu_config
    from distributed_tpu.graph.spec import TaskSpec
    from distributed_tpu.scheduler.durability import (
        DurabilityManager,
        MemorySink,
        state_digest,
    )
    from distributed_tpu.scheduler.state import SchedulerState

    N_WORKERS, N_TASKS, REPS = 16, 2000, 7

    def build(enabled):
        with dtpu_config.set({"scheduler.trace.enabled": False}):
            state = SchedulerState(validate=False)
            for i in range(N_WORKERS):
                state.add_worker_state(
                    f"tcp://restart:{i}", nthreads=2, memory_limit=2**30,
                    name=f"r{i}",
                )
            tasks = {
                f"rst-{i}": TaskSpec(_inc, (i,)) for i in range(N_TASKS)
            }
            state.update_graph_core(
                tasks, {k: set() for k in tasks}, list(tasks),
                client="smoke", stimulus_id="smoke-restart-graph",
            )
        mgr = None
        if enabled:
            mgr = DurabilityManager(
                state, MemorySink(), full_every=10**6, state_digests=True,
            )
            mgr.attach()
        return state, mgr

    def flood(state, mgr=None, cadence=0) -> float:
        t0 = time.perf_counter()
        rounds = 0
        while True:
            batch = [
                (ts.key, ws.address, f"smk-fin-{ts.key}", {"nbytes": 8})
                for ws in state.workers.values()
                for ts in list(ws.processing)
            ]
            if not batch:
                break
            state.stimulus_tasks_finished_batch(batch)
            rounds += 1
            if mgr is not None and cadence and rounds % cadence == 0:
                mgr.snapshot()
            assert rounds < 10 * N_TASKS, "flood did not converge"
        return time.perf_counter() - t0

    # A/B: untimed warmup per arm, then adjacent pairs; min-of-ratios
    flood(*build(True))
    flood(build(False)[0])
    on_walls, off_walls = [], []
    for _ in range(REPS):
        s, m = build(True)
        on_walls.append(flood(s, m))
        off_walls.append(flood(build(False)[0]))
    min_ratio = min(on / off for on, off in zip(on_walls, off_walls))
    overhead_pct = max(0.0, (min_ratio - 1.0) * 100)
    assert overhead_pct < 5.0, (
        f"steady-state durability capture overhead {overhead_pct:.1f}% "
        f"exceeds the 5% budget (on={on_walls}, off={off_walls})"
    )

    # measured-RTO curve: snapshot cadence (rounds per epoch) x journal
    # tail length -> restore seconds, each point digest-verified
    rto_curve = []
    snap_seconds_per_epoch = 0.0
    for cadence in (2, 8, 10**9):
        s, m = build(True)
        flood(s, m, cadence)
        m.flush_journal()
        fresh = SchedulerState(validate=False)
        t0 = time.perf_counter()
        info = DurabilityManager.restore_into(fresh, m.sink)
        restore_s = time.perf_counter() - t0
        assert state_digest(fresh) == state_digest(s), (
            f"cadence={cadence}: restored state diverged from original"
        )
        st = m.stats
        if cadence == 8:
            snap_seconds_per_epoch = st.snapshot_seconds / max(st.epochs, 1)
        rto_curve.append({
            "cadence_rounds": min(cadence, 10**6),
            "epochs": st.epochs,
            "snapshot_rows": st.snapshot_rows,
            "snapshot_s": round(st.snapshot_seconds, 4),
            "tail_records": info["tail_records"],
            "restore_s": round(restore_s, 4),
            "digest_ok": True,
        })
    # shorter tails must not come from serializing the world every
    # epoch: the deltas stay O(changed) — total rows across ALL the
    # fine-cadence epochs stay within a small multiple of the table
    fine = rto_curve[0]
    assert fine["snapshot_rows"] < 6 * N_TASKS, fine
    # production amortization: one delta epoch per snapshot-interval
    default_interval = dtpu_config.parse_timedelta(
        dtpu_config.get("scheduler.durability.snapshot-interval")
    )
    amortized_pct = 100.0 * snap_seconds_per_epoch / default_interval
    assert amortized_pct < 5.0, (
        f"snapshot encode {snap_seconds_per_epoch:.3f}s/epoch is "
        f"{amortized_pct:.1f}% of the default {default_interval}s cadence"
    )
    return {
        "capture_on_s": [round(w, 3) for w in on_walls],
        "capture_off_s": [round(w, 3) for w in off_walls],
        "overhead_pct": round(overhead_pct, 2),
        "snapshot_s_per_epoch": round(snap_seconds_per_epoch, 4),
        "amortized_snapshot_pct": round(amortized_pct, 3),
        "rto_curve": rto_curve,
        "host_canary_ms": _host_canary_ms(),
    }


def _smoke_restart() -> dict:
    """Scheduler-durability gate: live hard-bounce restart + synthetic
    capture-overhead / RTO-curve halves (scheduler/durability.py;
    docs/durability.md; gated in tests/test_bench_smoke.py)."""
    import asyncio

    out = asyncio.run(_smoke_restart_live())
    out.update(_smoke_restart_capture())
    return out


async def _smoke_ledger_live() -> dict:
    """Join-correctness half of the ledger gate on a SMALL LIVE
    cluster: a real flood + a dependent graph over real tcp must leave
    every placement decision joined to a realized outcome (ledger.py)
    with regret observed — the live counterpart of the simulator's
    exact-join tests."""
    from distributed_tpu.client.client import Client
    from distributed_tpu.deploy.local import LocalCluster
    from distributed_tpu.graph.spec import Graph, TaskRef, TaskSpec

    async with LocalCluster(n_workers=2, threads_per_worker=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            await c.gather(c.map(_inc, range(60)))
            g = Graph()
            for i in range(16):
                g.tasks[f"lsrc-{i}"] = TaskSpec(_inc, (i,))
                g.tasks[f"ldep-{i}"] = TaskSpec(
                    _inc, (TaskRef(f"lsrc-{i}"),)
                )
            g.tasks["lroot"] = TaskSpec(
                _sum_list, ([TaskRef(f"ldep-{i}") for i in range(16)],)
            )
            futs = c.compute_graph(g, ["lroot"])
            result = await futs["lroot"].result()
            assert result == sum(range(16)) + 32, result
            led = cluster.scheduler.state.ledger
            summary = led.summary()
            # ...and the RPC/HTTP surface serves the same snapshot
            rpc_snap = await c.scheduler.get_ledger(n=10)
    assert summary["joined"] >= 60, summary
    assert summary["unjoined"] == 0, summary
    assert summary["open"] == 0, summary
    assert summary["outcomes"].get("memory", 0) >= 60, summary
    n_regret = sum(k["count"] for k in summary["kinds"].values())
    assert n_regret > 0, summary
    assert rpc_snap and rpc_snap[0]["type"] == "ledger-summary"
    return {
        "live_joined": summary["joined"],
        "live_unjoined": summary["unjoined"],
        "live_regret_rows": n_regret,
    }


def _smoke_ledger() -> dict:
    """Decision-ledger gate (ledger.py, diagnostics/critical_path.py;
    docs/observability.md "Decision ledger & critical-path").  Raises if

    - ledger-on vs -off engine-flood overhead exceeds 5% (min-per-pair-
      ratio estimator, the drift-robust A/B from the trace smoke),
    - the steady-state file+join hot path allocates (PR 6's
      ``sys.getallocatedblocks`` gate pattern),
    - a small LIVE cluster leaves any decision unjoined (above),
    - on a telemetry-seeded NON-UNIFORM simulated fleet the measured-
      shadow model's aggregate |regret| is not lower than the
      constants' — the ROADMAP item 1 calibration artifact,
    - critical-path attribution does not sum to the sim run's virtual
      makespan within 1% (``critical_path.check``).
    """
    import asyncio
    import sys as _sys

    from distributed_tpu import config as dtpu_config
    from distributed_tpu.graph.spec import TaskSpec
    from distributed_tpu.scheduler.state import SchedulerState

    N_WORKERS, N_TASKS, REPS = 16, 2000, 7

    def build(enabled):
        with dtpu_config.set({"scheduler.ledger.enabled": enabled}):
            state = SchedulerState(validate=False)
        for i in range(N_WORKERS):
            state.add_worker_state(
                f"tcp://led:{i}", nthreads=2, memory_limit=2**30,
                name=f"l{i}",
            )
        tasks = {f"led-{i}": TaskSpec(_inc, (i,)) for i in range(N_TASKS)}
        deps: dict = {f"led-{i}": set() for i in range(N_TASKS)}
        for i in range(0, N_TASKS, 4):
            tasks[f"ldp-{i}"] = TaskSpec(_inc, (i,))
            deps[f"ldp-{i}"] = {f"led-{i}", f"led-{(i + 1) % N_TASKS}"}
        state.update_graph_core(
            tasks, deps, list(tasks), client="smoke",
            stimulus_id="smoke-ledger-graph",
        )
        return state

    # live task-finished messages ALWAYS carry startstops (the worker
    # stamps every compute): the flood includes them so the baseline is
    # the real ingest path — prefix duration folds, group timing — not
    # an artificially thin engine pass
    SS = ({"action": "compute", "start": 0.0, "stop": 0.005},)

    def flood(state) -> float:
        t0 = time.perf_counter()
        rounds = 0
        while True:
            batch = [
                (
                    ts.key, ws.address, f"led-fin-{ts.key}",
                    {"nbytes": 8, "startstops": SS},
                )
                for ws in state.workers.values()
                for ts in list(ws.processing)
            ]
            if not batch:
                break
            state.stimulus_tasks_finished_batch(batch)
            rounds += 1
            assert rounds < 10 * N_TASKS, "flood did not converge"
        return time.perf_counter() - t0

    flood(build(True))   # untimed warmup per arm (allocator/code warm)
    flood(build(False))
    on_walls, off_walls = [], []
    for _ in range(REPS):
        on_walls.append(flood(build(True)))
        off_walls.append(flood(build(False)))
    min_ratio = min(on / off for on, off in zip(on_walls, off_walls))
    overhead_pct = max(0.0, (min_ratio - 1.0) * 100)
    assert overhead_pct < 5.0, (
        f"ledger-on overhead {overhead_pct:.1f}% exceeds the 5% budget "
        f"(on={on_walls}, off={off_walls})"
    )

    # allocation contract on the file+join hot path: steady-state
    # decision rows allocate nothing net (preallocated slots + dict
    # insert/pop pairs).  Warm a FULL ring wrap first — the first pass
    # retires each slot's shared initial constants — plus the aggregate
    # dicts (prefix/link/kind/histogram entries are one-time).
    import gc

    from distributed_tpu.ledger import DecisionLedger

    led = DecisionLedger(size=16384, enabled=True)
    keys = [f"alloc-{i}" for i in range(64)]
    wraps = (led._mask + 2) // len(keys) + 2

    def cycle():
        for k in keys:
            h = led.file(
                "placement", k, "alloc", "tcp://led:0", "smk",
                0.001, 0.002, True, 1024, 1, 0.01, "tcp://led:1", "",
            )
            led.join_row(h, "memory", "tcp://led:0", None, 0.005, None)

    for _ in range(wraps):
        cycle()
    # the A/B floods above leave reference cycles whose lazy collection
    # would otherwise land inside the measured window; collect, then
    # re-warm so the window starts from a settled allocator
    gc.collect()
    for _ in range(32):
        cycle()
    b0 = _sys.getallocatedblocks()
    for _ in range(20_000 // len(keys)):
        cycle()
    alloc_delta = _sys.getallocatedblocks() - b0
    assert alloc_delta < 50, (
        f"ledger file+join allocated ({alloc_delta} blocks over 20k "
        "decision cycles)"
    )

    # regret artifact + critical-path gate on the deterministic sim:
    # telemetry-seeded non-uniform fleet — the measured shadow must
    # out-predict the constants, and attribution must sum to the
    # virtual makespan within 1%
    from distributed_tpu.diagnostics.critical_path import check
    from distributed_tpu.sim import ClusterSim, SyntheticDag
    from distributed_tpu.sim.links import LinkProfile

    links = LinkProfile(bandwidth=2e7, jitter=0.9, seed=7)
    sim = ClusterSim(
        12, nthreads=2, seed=7, links=links, validate=True,
        ledger_size=65536,
    )
    rows = []
    addrs = list(sim.workers)
    for src in addrs:
        for dst in addrs:
            if src == dst:
                continue
            bw, lat = links._edge(src, dst)
            nb = 10_000_000
            rows.append([src, dst, nb, nb / bw + lat, 4])
    sim.state.telemetry.fold_rows(rows, reporter="")
    SyntheticDag(
        n_layers=6, layer_width=18, fanin=2, seed=7, layers_per_chunk=3,
        duration_range=(0.001, 0.005), nbytes_range=(256_000, 2_000_000),
    ).start(sim)
    rep = sim.run()
    lsum = rep["ledger"]
    assert lsum["unjoined"] == 0 and lsum["open"] == 0, lsum
    reg = lsum["regret_abs_mean"]
    assert reg["measured"] < reg["constant"], (
        "measured-shadow aggregate regret did not beat the constants "
        f"on the telemetry-seeded non-uniform fleet: {reg}"
    )
    cp = sim.critical_path()
    assert cp is not None
    check(cp, tolerance=0.01)
    assert abs(cp["makespan"] - rep["virtual_makespan_s"]) <= (
        0.01 * rep["virtual_makespan_s"]
    ), (cp["makespan"], rep["virtual_makespan_s"])

    out = asyncio.run(_smoke_ledger_live())
    out.update({
        "n_workers": N_WORKERS,
        "n_tasks": N_TASKS,
        "ledger_on_s": [round(w, 3) for w in on_walls],
        "ledger_off_s": [round(w, 3) for w in off_walls],
        "overhead_pct": round(overhead_pct, 2),
        "alloc_delta_blocks": alloc_delta,
        "regret_abs_constant": round(reg["constant"], 6),
        "regret_abs_measured": round(reg["measured"], 6),
        "measured_beats_constant": True,
        "cp_makespan_s": round(cp["makespan"], 6),
        "cp_check_ok": True,
        "sim_joined": lsum["joined"],
        "host_canary_ms": _host_canary_ms(),
    })
    return out


def _smoke_engine() -> dict:
    """Native transition-engine gate (native/engine.cpp +
    scheduler/native_engine.py; docs/native_engine.md): a randomized
    dependency flood driven through the compiled engine must

    - be BIT-IDENTICAL to the pure-python oracle (final states, per-key
      stories, per-destination message multisets),
    - absorb the four compiled arms natively (escape rate < 10% of
      transitions — the sim_10k trace measures ~0%),
    - DEFER: a no-introspection flood hydrates zero tape rows inside
      the stimulus call (the authoritative-SoA contract — python truth
      materializes at the next read, outside the engine plane),
    - hold a same-session speedup >= 10x on the engine plane (the
      stimulus_tasks_finished_batch calls alone, batch building and
      deferred hydration excluded) and >= 1.3x on the whole flood loop
      including the python-side batch building + replay, both
      best-of-pairs (one-sided box-phase noise shrinks single pairs; a
      real regression drops EVERY pair — PERF.md Round 12), and
    - allocate nothing per flood in the bridge's steady state (stale-
      completion floods: prep + native drain + tape apply with no state
      growth, the PR 6 getallocatedblocks pattern).
    """
    import random as _random
    import sys as _sys

    from distributed_tpu import config as dtpu_config
    from distributed_tpu.scheduler.state import SchedulerState

    N_WORKERS, WIDTH, LAYERS, REPS = 32, 64, 10, 5
    OVR = {
        "scheduler.trace.enabled": False,
        "scheduler.telemetry.enabled": False,
        "scheduler.native-engine.enabled": False,  # explicit attach
        "scheduler.native-engine.min-flood": 0,
    }

    class _Spec:
        __slots__ = ()

    spec = _Spec()

    def build(native_on, seed=0):
        with dtpu_config.set(OVR):
            state = SchedulerState(validate=False)
            if native_on:
                assert state.attach_native(build=True), (
                    "native toolchain unavailable (engine smoke needs "
                    "the on-demand g++ build this image carries)"
                )
            for i in range(N_WORKERS):
                state.add_worker_state(
                    f"sim://w{i}", nthreads=1, memory_limit=2**30,
                    name=f"w{i}",
                )
            rng = _random.Random(seed)
            addrs = list(state.workers)
            prev = []
            for i in range(WIDTH):
                k = f"root-{i}"
                state.client_desires_keys([k], "c")
                recs, cm, wm = state._transition(
                    k, "memory", "scatter", nbytes=65536,
                    worker=addrs[i % len(addrs)],
                )
                state._transitions(recs, cm, wm, "scatter")
                prev.append(k)
            tasks, deps, prios = {}, {}, {}
            rank = 0
            for j in range(LAYERS):
                layer = [f"L{j}-{i}" for i in range(WIDTH)]
                for k in layer:
                    deps[k] = {
                        prev[rng.randrange(len(prev))] for _ in range(2)
                    }
                    tasks[k] = spec
                    prios[k] = (rank,)
                    rank += 1
                prev = layer
            state.update_graph_core(
                tasks, deps, prev, client="c", priorities=prios,
                stimulus_id="graph",
            )
        return state

    def flood(state, collect=False):
        """Drive to quiescence.  Returns (wall_total, wall_engine,
        hydrations_in_timer, rounds_out): wall_engine times ONLY the
        stimulus_tasks_finished_batch calls — the batch-plane engine
        wall the >=10x gate measures.  Batch building (list(ws.
        processing), which hydrates the previous flood's deferred
        segments) stays outside the engine timer, and the hydration
        counter is sampled around each timed call so the gate can
        assert the engine plane itself hydrates nothing."""
        rounds, out = 0, []
        eng, hyd = 0.0, 0
        ne = getattr(state, "native", None)
        t_all = time.perf_counter()
        with dtpu_config.set(OVR):
            while True:
                batch = [
                    (
                        ts.key, ws.address, f"f{rounds}-{i}",
                        {"nbytes": 2048, "startstops": [{
                            "action": "compute", "start": 0.0,
                            "stop": 0.01,
                        }]},
                    )
                    for ws in state.workers.values()
                    for i, ts in enumerate(list(ws.processing))
                ]
                if not batch:
                    break
                h0 = ne.hydrations if ne is not None else 0
                t0 = time.perf_counter()
                r = state.stimulus_tasks_finished_batch(batch)
                eng += time.perf_counter() - t0
                if ne is not None:
                    hyd += ne.hydrations - h0
                if collect:
                    out.append(r)
                rounds += 1
                assert rounds < 5000
        return time.perf_counter() - t_all, eng, hyd, out

    def freeze(obj):
        if isinstance(obj, dict):
            return tuple(sorted((k, freeze(v)) for k, v in obj.items()))
        if isinstance(obj, (list, tuple)):
            return tuple(freeze(v) for v in obj)
        if isinstance(obj, (str, bytes, int, float, bool)) or obj is None:
            return obj
        return repr(type(obj))

    def canon(rounds):
        return [
            {
                dest: sorted(
                    (freeze({k: v for k, v in m.items()
                             if k != "run_spec"}) for m in msgs),
                    key=repr,
                )
                for dest, msgs in d.items()
            }
            for cm, wm in rounds for d in (cm, wm)
        ]

    def snap(state):
        return {
            k: (
                ts.state,
                ts.processing_on.address if ts.processing_on else None,
                tuple(ws.address for ws in ts.who_has),
            )
            for k, ts in state.tasks.items()
        }

    # --- bit-parity on a randomized flood ----------------------------
    a, b = build(False, seed=3), build(True, seed=3)
    _, _, _, ra = flood(a, collect=True)
    _, _, _, rb = flood(b, collect=True)
    assert snap(a) == snap(b), "native/oracle state mismatch"
    assert [r[:5] for r in a.transition_log] ==         [r[:5] for r in b.transition_log], "story mismatch"
    assert canon(ra) == canon(rb), "message mismatch"
    counters = b.native.counters()
    total = counters["transitions"] + counters["oracle_transitions"]
    escape_rate = counters["escapes"] / max(total, 1)
    assert counters["transitions"] > 0, "native engine never ran"
    assert escape_rate < 0.10, (
        f"escape rate {escape_rate:.1%} — the compiled arms are not "
        f"absorbing their share ({counters})"
    )

    # --- same-session speedup (best-of-pairs, drift-robust) ----------
    # Two planes per pair: the ENGINE plane (stimulus calls only — the
    # deferred-materialization contract keeps python bookkeeping out of
    # it, gate >= 10x) and the whole flood loop including the python
    # batch builds that hydrate the previous round (legacy gate 1.3x).
    flood(build(False))
    flood(build(True))
    ratios, eng_ratios, hyd_in_timer = [], [], 0
    for _ in range(REPS):
        wo, eo, _, _ = flood(build(False))
        wn, en, h, _ = flood(build(True))
        ratios.append(wo / wn)
        eng_ratios.append(eo / en)
        hyd_in_timer += h
    speedup = max(ratios)
    speedup_engine = max(eng_ratios)
    assert hyd_in_timer == 0, (
        f"{hyd_in_timer} rows hydrated INSIDE the engine timer — a "
        "no-introspection flood must defer every segment (escape or "
        "stray read on the stimulus path is dragging replay back into "
        "the engine plane)"
    )
    assert speedup_engine >= 10.0, (
        f"engine-plane speedup {speedup_engine:.2f}x under the 10x "
        f"floor (pairs {[round(r, 1) for r in eng_ratios]}; PERF.md "
        f"Round 12)"
    )
    assert speedup >= 1.3, (
        f"native flood speedup {speedup:.2f}x under the 1.3x floor "
        f"(pairs {[round(r, 2) for r in ratios]})"
    )

    # --- per-flood alloc budget (stale floods: no state growth) ------
    st = build(True, seed=4)
    stale = [(f"ghost-{i}", "sim://w0", f"g{i}", {"nbytes": 8})
             for i in range(64)]
    def drain(r):
        # consume the lazy flood messages: the read barrier replays the
        # deferred segment and returns its tape to the pool, so the
        # steady state the block budget measures includes recycling
        return sum(len(v) for v in r[1].values())

    with dtpu_config.set(OVR):
        for _ in range(4):
            drain(st.stimulus_tasks_finished_batch(list(stale)))
        b0 = _sys.getallocatedblocks()
        for _ in range(32):
            drain(st.stimulus_tasks_finished_batch(list(stale)))
        alloc_delta = _sys.getallocatedblocks() - b0
    assert alloc_delta < 300, (
        f"native flood path leaked {alloc_delta} blocks over 32 "
        "identical stale floods"
    )

    return {
        "n_tasks": WIDTH * LAYERS,
        "transitions": b.transition_counter,
        "native_transitions": counters["transitions"],
        "escapes": counters["escapes"],
        "escape_rate": round(escape_rate, 4),
        "parity": True,
        "speedup_best": round(speedup, 2),
        "speedup_pairs": [round(r, 2) for r in ratios],
        "speedup_engine_best": round(speedup_engine, 2),
        "speedup_engine_pairs": [round(r, 1) for r in eng_ratios],
        "hydrations_in_timer": hyd_in_timer,
        "alloc_delta_blocks": alloc_delta,
        "host_canary_ms": _host_canary_ms(),
    }


async def _smoke_census_live() -> dict:
    """Live half of the census gate: a real in-process cluster computes
    keys, the client releases everything, and the run must QUIESCE
    CENSUS-CLEAN on every role — zero non-allowlisted residue, every
    walk-vs-counter audit green (diagnostics/census.py)."""
    import asyncio

    from distributed_tpu import config as dtpu_config
    from distributed_tpu.client.client import Client
    from distributed_tpu.deploy.local import LocalCluster

    with dtpu_config.set({"scheduler.jax.enabled": False}):
        async with LocalCluster(n_workers=2, threads_per_worker=1) as cluster:
            async with Client(cluster.scheduler_address) as c:
                futs = c.map(_inc, range(64))
                res = await c.gather(futs)
                assert res == list(range(1, 65)), res[:5]
                for f in futs:
                    f.release()
                del futs
                s = cluster.scheduler.state
                deadline = time.monotonic() + 20.0
                while time.monotonic() < deadline:
                    if not s.tasks and s.census.quiesced() and all(
                        not w.state.tasks for w in cluster.workers
                    ):
                        break
                    await asyncio.sleep(0.05)
                assert s.census.quiesced(), {
                    m: s.census.families[m].probe() for m in s.census.motion
                }
                censuses = [("scheduler", s.census)] + [
                    (w.address, w.state.census) for w in cluster.workers
                ]
                n_fam = 0
                for who, census in censuses:
                    census.audit()
                    residue = census.residue()
                    assert not residue, (who, census.enrich_findings(residue))
                    n_fam += len(census.families)
                # the RPC twin serves the same truth
                recs = await c.scheduler.get_census(deep=True)
                head = recs[0]
                assert head["quiesced"] is True, head
    return {"censuses": len(censuses), "families": n_fam}


def _smoke_census() -> dict:
    """State-census gate (diagnostics/census.py; docs/observability.md
    "State census & retention"):

    - census-on (sentinel ticking every flood round — a strict
      over-approximation of the 2s production cadence) vs census-off
      engine floods stay under the 5% budget by the min-per-pair-ratio
      estimator;
    - sentinel ticks are allocation-free (``sys.getallocatedblocks``
      over a 20k-tick burst);
    - a live run-then-quiesce LocalCluster ends census-clean on every
      role, and the walk-vs-counter audits pass throughout.
    """
    import asyncio
    import sys as _sys

    from distributed_tpu.diagnostics.census import RetentionSentinel
    from distributed_tpu.graph.spec import TaskSpec
    from distributed_tpu.scheduler.state import SchedulerState

    N_WORKERS, N_TASKS, REPS = 16, 2000, 7

    def build():
        state = SchedulerState(validate=False)
        for i in range(N_WORKERS):
            state.add_worker_state(
                f"tcp://census:{i}", nthreads=2, memory_limit=2**30,
                name=f"c{i}",
            )
        tasks = {f"cns-{i}": TaskSpec(_inc, (i,)) for i in range(N_TASKS)}
        state.update_graph_core(
            tasks, {k: set() for k in tasks}, list(tasks),
            client="smoke", stimulus_id="smoke-census-graph",
        )
        return state

    def flood(state, sentinel) -> float:
        t0 = time.perf_counter()
        rounds = 0
        while True:
            batch = [
                (ts.key, ws.address, f"smk-cns-{ts.key}", {"nbytes": 8})
                for ws in state.workers.values()
                for ts in list(ws.processing)
            ]
            if not batch:
                break
            state.stimulus_tasks_finished_batch(batch)
            if sentinel is not None:
                sentinel.tick()
            rounds += 1
            assert rounds < 10 * N_TASKS, "flood did not converge"
        return time.perf_counter() - t0

    def arm(on: bool) -> float:
        state = build()
        sentinel = RetentionSentinel(state.census) if on else None
        return flood(state, sentinel)

    arm(True)   # untimed warmup (allocator/code warmup)
    arm(False)
    on_walls, off_walls = [], []
    for _ in range(REPS):
        on_walls.append(arm(True))
        off_walls.append(arm(False))
    min_ratio = min(on / off for on, off in zip(on_walls, off_walls))
    overhead_pct = max(0.0, (min_ratio - 1.0) * 100)
    assert overhead_pct < 5.0, (
        f"census-on overhead {overhead_pct:.1f}% exceeds the 5% budget "
        f"(on={on_walls}, off={off_walls})"
    )

    # allocation contract: the sentinel tick (every cheap probe + the
    # slope folds) allocates nothing in steady state
    state = build()
    sentinel = RetentionSentinel(state.census)
    for _ in range(64):
        sentinel.tick()  # warm per-family floats + probe code paths
    b0 = _sys.getallocatedblocks()
    for _ in range(20_000):
        sentinel.tick()
    alloc_delta = _sys.getallocatedblocks() - b0
    assert alloc_delta < 50, (
        f"sentinel tick allocated ({alloc_delta} blocks over 20k ticks)"
    )

    live = asyncio.run(_smoke_census_live())
    return {
        "n_workers": N_WORKERS,
        "n_tasks": N_TASKS,
        "census_on_s": [round(w, 3) for w in on_walls],
        "census_off_s": [round(w, 3) for w in off_walls],
        "overhead_pct": round(overhead_pct, 2),
        "alloc_delta_blocks": alloc_delta,
        "live_clean": True,
        "live_censuses": live["censuses"],
        "live_families": live["families"],
        "host_canary_ms": _host_canary_ms(),
    }


def _smoke_lint() -> dict:
    """The determinism lint gate rides the smoke: bench headlines are
    only comparable across runs and processes if every scheduling
    decision is hash-seed- and allocation-independent
    (docs/determinism.md), so --smoke refuses to bless a tree with
    determinism findings."""
    t0 = time.perf_counter()
    r = subprocess.run(
        [sys.executable, "-m", "distributed_tpu.analysis",
         "--rule", "determinism", "--format", "json"],
        capture_output=True, text=True, timeout=180,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    report = json.loads(r.stdout)
    assert report["findings"] == [], report["findings"]
    assert report["errors"] == [], report["errors"]
    return {
        "rule": "determinism",
        "findings": 0,
        "suppressed": report["suppressed"],
        "wall_s": round(time.perf_counter() - t0, 2),
    }


def run_smoke(only: str | None = None):
    """``python bench.py --smoke [name]``: tiny CPU-pinned configs; one
    JSON line on stdout; raises (non-zero exit) on any failure.  With a
    name (e.g. ``--smoke restart``) runs just that config."""
    import asyncio

    # the mesh smoke needs the 8-device CPU mesh; the flag must be in
    # place before ANY config initializes the backend
    _ensure_cpu_mesh_env()
    t0 = time.perf_counter()

    def retry_once(fn):
        # the 5% overhead gates sit at this box's noise margin: in a
        # noisy phase a single A/B reads 7-15% with or WITHOUT the
        # feature under test (measured at 1 device too).  A genuine
        # overhead regression is systematic and fails both attempts;
        # one-shot box-phase noise does not.
        try:
            return fn()
        except AssertionError:
            return fn()

    builders = {
        "cluster": lambda: asyncio.run(_smoke_cluster()),
        "placement": _smoke_placement,
        "mirror": _smoke_mirror,
        "wire": lambda: asyncio.run(_smoke_wire()),
        "trace": lambda: retry_once(_smoke_trace),
        "telemetry": lambda: retry_once(_smoke_telemetry),
        "selfprofile": lambda: retry_once(_smoke_selfprofile),
        "ledger": lambda: retry_once(_smoke_ledger),
        "engine": lambda: retry_once(_smoke_engine),
        "sim": _smoke_sim,
        "restart": lambda: retry_once(_smoke_restart),
        "census": lambda: retry_once(_smoke_census),
        "lint": _smoke_lint,
        # "mesh" LAST on purpose: the sharded programs spin up the
        # 8-device XLA runtime (one thread pool per virtual device on a
        # 2-core box) and that background churn measurably widens the
        # pure-python flood A/Bs above — trace/telemetry's 5% overhead
        # gates flaked 2-in-3 with the mesh config ahead of them
        "mesh": _smoke_mesh,
    }
    if only is not None:
        if only not in builders:
            raise SystemExit(
                f"unknown smoke config {only!r}; one of {sorted(builders)}"
            )
        names = [only]
    else:
        names = list(builders)
    configs = {name: builders[name]() for name in names}
    print(
        json.dumps(
            {
                "smoke": True,
                "total_s": round(time.perf_counter() - t0, 1),
                "configs": configs,
            }
        )
    )


# =====================================================================
# harness
# =====================================================================

def run_config(name, force_cpu=False):
    """Child entry: run one config, print its JSON dict as the last line."""
    if name == "dag_10m":
        # the sharded headline always runs on the multi-device CPU mesh
        _ensure_cpu_mesh_env()
        force_cpu = False  # handled above, before backend init
    if force_cpu:
        # JAX_PLATFORMS=cpu in the env is NOT enough on this box: a
        # sitecustomize pins the axon (tunneled TPU) backend at import.
        # jax.config.update works as long as no backend is initialized.
        import jax

        jax.config.update("jax_platforms", "cpu")
    if name == "dag_1m":
        result = cfg_dag_1m()
    elif name == "dag_10m":
        result = cfg_dag_10m()
    elif name == "sim_10k":
        result = cfg_sim_10k()
    else:
        import asyncio

        fn = {
            "array_sum": cfg_array_sum,
            "rechunk_tensordot": cfg_rechunk_tensordot,
            "steal": cfg_steal,
            "shuffle": cfg_shuffle,
        }[name]
        result = asyncio.run(fn())
    sys.stdout.flush()
    print(json.dumps(result))


def _parse_json_tail(stdout: str):
    """Last JSON-looking line of a child's stdout, or None."""
    for line in reversed(stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    return None


def probe_backend(env):
    """Probe jax backend init in a subprocess: hard timeout + retries.

    A probe TIMEOUT fails fast (no retries): the accelerator tunnel is
    wedged, not warming up — BENCH_r05 spent 90 s x no useful retries on
    exactly this.  Probe errors (transient init failures) still retry
    with backoff.  ``DTPU_BENCH_PROBE_TIMEOUT`` / ``_RETRIES`` tune it.
    """
    last_err = None
    for attempt in range(PROBE_RETRIES):
        try:
            out = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    "import jax; print('BACKEND=' + jax.default_backend())",
                ],
                env=env,
                capture_output=True,
                text=True,
                timeout=PROBE_TIMEOUT,
            )
            for line in out.stdout.splitlines():
                if line.startswith("BACKEND="):
                    return line.split("=", 1)[1], None
            last_err = (out.stderr or out.stdout).strip()[-400:]
        except subprocess.TimeoutExpired:
            last_err = (
                f"backend probe timed out after {PROBE_TIMEOUT}s "
                f"(device backend unreachable; falling back to cpu — "
                f"set DTPU_BENCH_PROBE_TIMEOUT to adjust)"
            )
            break  # a wedged tunnel will not answer the next attempt either
        if attempt < PROBE_RETRIES - 1:
            time.sleep(PROBE_BACKOFF[min(attempt, len(PROBE_BACKOFF) - 1)])
    return None, last_err


# per-config scalar that must not get worse round-over-round
# (name, key, higher_is_better)
_GATE_METRICS = {
    "array_sum": ("overhead_us_per_task", False),
    "rechunk_tensordot": ("wall_s", False),
    "steal": ("wall_s", False),
    "shuffle": ("rows_per_s", True),
    "dag_1m": ("wall_s", False),
    "dag_10m": ("sharded_wall_s", False),
}


def _regression_gate(configs: dict) -> None:
    """WARN (stderr) when any config is >20% worse than the newest
    committed BENCH_r*.json — the round-4 config-1 regression shipped
    unnoticed because nothing compared rounds."""
    import glob
    import re

    # advisory only: NOTHING in here may kill the run (the headline JSON
    # line must always print — the round-2 rc=1 lesson above)
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        candidates = []
        for p in glob.glob(os.path.join(here, "BENCH_r*.json")):
            m = re.search(r"r(\d+)", os.path.basename(p))
            if m:
                candidates.append((int(m.group(1)), p))
        if not candidates:
            return
        with open(max(candidates)[1]) as f:
            prev = json.load(f).get("parsed", {}).get("configs", {})
    except Exception:
        return
    try:
        for name, (key, higher) in _GATE_METRICS.items():
            old = (prev.get(name) or {}).get(key)
            new = (configs.get(name) or {}).get(key)
            if not old or not new:
                continue
            ratio = (old / new) if higher else (new / old)
            if ratio > 1.2:
                sys.stderr.write(
                    f"WARN: regression gate: {name}.{key} {old} -> {new} "
                    f"({ratio:.2f}x worse than previous round)\n"
                )
    except Exception:
        return


def main():
    t_start = time.perf_counter()
    cpu_env = dict(os.environ, JAX_PLATFORMS="cpu")

    backend, probe_err = probe_backend(dict(os.environ))
    if backend is None:
        # tunnel down: fall back to CPU so the round still gets a number
        backend = "cpu-fallback"
        os.environ["JAX_PLATFORMS"] = "cpu"

    configs = {}
    errors = {}
    if probe_err:
        errors["backend_probe"] = probe_err
    for name, timeout, force_cpu in CONFIGS:
        force_cpu = force_cpu or backend == "cpu-fallback"
        env = cpu_env if force_cpu else dict(os.environ)
        if name == "dag_10m":
            # the flag must be in the child's env before any
            # sitecustomize-triggered jax import (run_config's in-
            # process fallback covers direct invocations)
            env = dict(env)
            env["XLA_FLAGS"] = _mesh_xla_flags(env.get("XLA_FLAGS", ""))
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--config", name]
                + (["--force-cpu"] if force_cpu else []),
                env=env,
                capture_output=True,
                text=True,
                timeout=timeout,
            )
            if proc.stderr:
                sys.stderr.write(proc.stderr[-2000:])
            parsed = _parse_json_tail(proc.stdout)
            if parsed is None:
                raise RuntimeError(
                    f"rc={proc.returncode}: "
                    + (proc.stderr or proc.stdout).strip()[-400:]
                )
            configs[name] = parsed
        except subprocess.TimeoutExpired:
            errors[name] = f"timed out after {timeout}s"
        except Exception as exc:
            errors[name] = str(exc)[:400]

    if "dag_1m" not in configs and os.environ.get("JAX_PLATFORMS") != "cpu":
        # the headline config died on the real backend (e.g. the tunnel
        # flaked AFTER a successful probe): one retry on the CPU backend
        # so the round still gets a number, clearly labelled.  Skipped
        # when the primary attempt already ran on CPU.
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--config", "dag_1m", "--force-cpu"],
                env=cpu_env, capture_output=True, text=True, timeout=600.0,
            )
            parsed = _parse_json_tail(proc.stdout)
            if parsed is not None:
                parsed["backend"] = "cpu-fallback"
                configs["dag_1m"] = parsed
            else:
                errors["dag_1m_cpu_retry"] = (
                    f"rc={proc.returncode}: no JSON line in retry output: "
                    + (proc.stderr or proc.stdout).strip()[-300:]
                )
        except Exception as exc:
            errors["dag_1m_cpu_retry"] = str(exc)[:400]

    _regression_gate(configs)

    dag = configs.get("dag_1m")
    headline = {
        "metric": "task-placement decisions/sec, 1M-task DAG on 512 workers",
        "value": dag["decisions_per_s"] if dag else 0,
        "unit": "decisions/s",
        "vs_baseline": dag["vs_baseline"] if dag else 0.0,
        "backend": backend,
        "total_bench_s": round(time.perf_counter() - t_start, 1),
        "configs": configs,
    }
    if errors:
        headline["errors"] = errors
    print(json.dumps(headline))
    sys.exit(0)


if __name__ == "__main__":
    if "--smoke" in sys.argv[1:]:
        _i = sys.argv.index("--smoke")
        _only = (
            sys.argv[_i + 1]
            if len(sys.argv) > _i + 1 and not sys.argv[_i + 1].startswith("-")
            else None
        )
        run_smoke(_only)
    elif len(sys.argv) >= 3 and sys.argv[1] == "--config":
        run_config(sys.argv[2], force_cpu="--force-cpu" in sys.argv)
    else:
        try:
            main()
        except SystemExit:
            raise  # main's own clean exit — the JSON is already printed
        except BaseException as exc:  # absolute backstop: always emit JSON
            print(
                json.dumps(
                    {
                        "metric": "task-placement decisions/sec, "
                        "1M-task DAG on 512 workers",
                        "value": 0,
                        "unit": "decisions/s",
                        "vs_baseline": 0.0,
                        "error": f"{type(exc).__name__}: {exc}"[:400],
                    }
                )
            )
            sys.exit(0)
