"""North-star benchmark: place a 1M-task random DAG onto 512 simulated
workers (BASELINE.json config 5) with the level-synchronous device engine
(`ops/leveled.py`), versus the stock pure-python decide_worker loop
(reference scheduler.py:8550, ~1 ms/task per docs/source/efficiency.rst:48-50).

Prints ONE json line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

- value: placement decisions/second achieved end-to-end: O(T+E) C++ host
  pack (levels/heavy-deps/transfer costs) -> 10 B/task upload -> one
  frontier-sized device dispatch per wave -> int16 assignment download.
- vs_baseline: speedup over the stock python placement loop, measured by
  running a faithful python replica of worker_objective/decide_worker on a
  subset and extrapolating linearly (the python loop is O(T*W)).

Stderr carries the phase breakdown (pack/upload+compute/download) because
on a tunneled TPU backend (axon) the transfer phases are bounded by
tunnel bandwidth, not the chip — see PERF.md for the floor analysis.

Runs on whatever jax backend the environment provides (the real TPU chip
under axon; CPU elsewhere).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

N_TASKS = 1_000_000
N_WORKERS = 512
N_EDGES_PER_TASK = 2
ORACLE_SUBSET = 2_000
BANDWIDTH = 100e6


def build_graph(rng):
    durations = rng.uniform(0.01, 1.0, N_TASKS).astype(np.float32)
    out_bytes = rng.uniform(1e3, 1e7, N_TASKS).astype(np.float32)
    # random DAG: each task depends on up to 2 uniformly-random earlier tasks
    n_deps = rng.integers(0, N_EDGES_PER_TASK + 1, N_TASKS)
    n_deps[0] = 0
    total = int(n_deps.sum())
    dst = np.repeat(np.arange(N_TASKS), n_deps).astype(np.int32)
    src = (rng.random(total) * np.maximum(dst, 1)).astype(np.int32)
    return durations, out_bytes, src, dst


def bench_device(durations, out_bytes, src, dst):
    from distributed_tpu.ops.leveled import (
        pack_graph, place_graph_leveled, validate_leveled,
    )

    nthreads = np.full(N_WORKERS, 2, np.int32)
    occ0 = np.zeros(N_WORKERS, np.float32)
    running = np.ones(N_WORKERS, bool)

    # warm up: builds the native library and compiles every wave bucket
    # (compile excluded from the measurement, like the reference excludes
    # interpreter startup)
    packed = pack_graph(durations, out_bytes, src, dst, bandwidth=BANDWIDTH)
    res = place_graph_leveled(packed, nthreads, occ0, running)

    t0 = time.perf_counter()
    packed = pack_graph(durations, out_bytes, src, dst, bandwidth=BANDWIDTH)
    t1 = time.perf_counter()
    res = place_graph_leveled(packed, nthreads, occ0, running)
    t2 = time.perf_counter()

    validate_leveled(packed, res, src, dst, running)
    counts = np.bincount(res.assignment, minlength=N_WORKERS)
    return t1 - t0, t2 - t1, res.n_waves, counts


def bench_stock_python(durations, out_bytes, src, dst, n=ORACLE_SUBSET):
    """Stock semantics: per-task min() over all workers of
    (occupancy/nthreads + missing_bytes/bandwidth, nbytes) — the reference's
    decide_worker/worker_objective python loop."""
    occ = np.zeros(N_WORKERS)
    wnbytes = np.zeros(N_WORKERS)
    nthreads = 2
    deps: list[list[int]] = [[] for _ in range(n)]
    for s, d in zip(src, dst):
        if d < n:
            deps[d].append(s)
    placed = {}
    t0 = time.perf_counter()
    for t in range(n):
        best = None
        best_key = None
        missing_cache = {}
        for w in range(N_WORKERS):
            missing = 0.0
            for dep in deps[t]:
                if placed.get(dep) != w:
                    missing += out_bytes[dep]
            key = (occ[w] / nthreads + missing / BANDWIDTH, wnbytes[w], w)
            if best_key is None or key < best_key:
                best_key = key
                best = w
                missing_cache[w] = missing
        placed[t] = best
        occ[best] += durations[t] + missing_cache.get(best, 0.0) / BANDWIDTH
        wnbytes[best] += out_bytes[t]
    elapsed = time.perf_counter() - t0
    return elapsed / n  # seconds per task


def main():
    rng = np.random.default_rng(0)
    durations, out_bytes, src, dst = build_graph(rng)

    pack_s, place_s, n_waves, counts = bench_device(
        durations, out_bytes, src, dst
    )
    stock_per_task = bench_stock_python(durations, out_bytes, src, dst)
    stock_total = stock_per_task * N_TASKS

    total_s = pack_s + place_s
    decisions_per_sec = N_TASKS / total_s
    vs_baseline = stock_total / total_s

    print(
        json.dumps(
            {
                "metric": "task-placement decisions/sec, 1M-task DAG on 512 workers",
                "value": round(decisions_per_sec),
                "unit": "decisions/s",
                "vs_baseline": round(vs_baseline, 1),
            }
        )
    )
    print(
        f"# pack {pack_s*1e3:.1f} ms + device {place_s*1e3:.1f} ms "
        f"(upload+compute+download over the axon tunnel), "
        f"{n_waves} waves, load imbalance "
        f"{counts.max() / max(counts.mean(), 1):.2f}x, "
        f"stock python {stock_per_task*1e6:.0f} us/task "
        f"(extrapolated {stock_total:.0f} s for 1M)",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
