"""sim.profile_run: the per-transition-arm wall table (ROADMAP item 4's
prioritization artifact) — acceptance properties on a fresh seeded run
plus the LOOSE drift gate against docs/state_machine/engine_wall.json.

Wall SECONDS are box-dependent (PERF.md: 2x day-to-day swing), so
nothing here pins absolute numbers: the gates are structural — which
arms exist, that arms dominate the engine wall, that the table is
internally consistent."""

from __future__ import annotations

import json
from pathlib import Path

from distributed_tpu.sim.profile_run import (
    ARTIFACT,
    compare_to_artifact,
    run_profile,
    table_markdown,
)

REPO = Path(__file__).resolve().parent.parent


def _small_run():
    # miniature of the artifact config: seconds-scale in tier-1, same
    # engine seams, same arm vocabulary
    return run_profile(n_workers=16, layers=8, width=48, seed=0)


def test_profile_run_arms_dominate_engine_wall():
    """The acceptance bar: the scheduler table's arms sum to >= 70% of
    the scheduler engine wall — the per-arm attribution captures the
    engine's cost rather than its own bookkeeping."""
    result = _small_run()
    sched = result["scheduler"]
    assert sched["engine_wall_s"] > 0
    assert sched["arm_share"] >= 0.70, table_markdown(result)
    # internal consistency: rows' shares sum to ~arm_share
    rows_share = sum(r["share_of_engine"] for r in sched["arms"])
    assert abs(rows_share - sched["arm_share"]) < 0.02
    # the known hot arms of the scheduler engine are present and top
    arm_names = [r["arm"] for r in sched["arms"]]
    assert "waiting,processing" in arm_names[:3]
    assert "processing,memory" in arm_names[:3]
    # worker side: attribution (arms + handler bodies + ensure drains)
    # accounts for the majority of the worker engine wall too
    assert result["worker"]["arm_share"] >= 0.5
    # the ROADMAP item 4 claim direction: the two engines are the bulk
    # of the harness wall (loose floor; sim_10k measured >85%)
    assert result["engines_share_of_run"] >= 0.4
    # entries are real transition counts, not zeros
    assert all(
        r["entries"] > 0 for r in sched["arms"]
        if not r["arm"].startswith("(")
    )


def test_profile_run_artifact_drift_gate():
    """The checked-in engine_wall.json stays structurally honest: its
    named top arms must still exist in a fresh run (loose gate — shares
    drift with the box, arm identity does not)."""
    artifact_path = REPO / ARTIFACT
    assert artifact_path.exists(), (
        f"{ARTIFACT} missing — regenerate with "
        "python -m distributed_tpu.sim.profile_run --out " + ARTIFACT
    )
    artifact = json.loads(artifact_path.read_text())
    assert artifact["v"] == 1
    assert artifact["scheduler"]["arm_share"] >= 0.70
    result = _small_run()
    issues = compare_to_artifact(result, artifact)
    assert not issues, issues


def test_profile_run_default_config_has_no_arm_attribution_leak():
    """run_profile flips scheduler.profile.arm-attribution only inside
    the sim's config window: a state machine built afterwards must be
    back to the cheap default."""
    from distributed_tpu import config
    from distributed_tpu.scheduler.state import SchedulerState

    _small_run()
    assert config.get("scheduler.profile.arm-attribution") is False
    assert SchedulerState().WALL_ARMS is False
