"""Graph partitioner kernel (ops/partition.py): quality, balance,
numpy/jax parity, and the live planner path."""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from distributed_tpu.ops.partition import (
    block_init,
    jax_available,
    partition_jax,
    partition_numpy,
)


def _blockwise_graph(G: int):
    """mul grid + per-(i,j) reduction — the tensordot proxy."""
    keys: dict[str, int] = {}
    src, dst = [], []

    def add(k):
        keys[k] = len(keys)
        return keys[k]

    for i in range(G):
        for k in range(G):
            add(f"A-{i}-{k}")
    for i in range(G):
        for j in range(G):
            for k in range(G):
                m = add(f"m-{i}-{j}-{k}")
                src.append(keys[f"A-{i}-{k}"])
                dst.append(m)
            r = add(f"r-{i}-{j}")
            for k in range(G):
                src.append(keys[f"m-{i}-{j}-{k}"])
                dst.append(r)
    T = len(keys)
    return (
        keys,
        np.ones(T, np.float32),
        np.ones(len(src), np.float32),
        np.asarray(src, np.int32),
        np.asarray(dst, np.int32),
    )


def _comm_volume(labels, src, dst) -> int:
    """Unique (producer, consumer-worker) cross pairs — peer fetches
    after replica caching, which is what the cluster actually pays."""
    return len(
        {
            (s, labels[d])
            for s, d in zip(src.tolist(), dst.tolist())
            if labels[s] != labels[d]
        }
    )


def test_block_init_equal_load():
    d = np.ones(100, np.float32)
    lab = block_init(d, 10)
    counts = np.bincount(lab, minlength=10)
    assert (counts == 10).all()
    # heavier tasks shrink their block
    d2 = np.ones(100, np.float32)
    d2[:10] = 9.0
    lab2 = block_init(d2, 10)
    assert np.bincount(lab2, minlength=10)[0] < 10


def test_partition_beats_random_and_balances():
    keys, dur, wts, src, dst = _blockwise_graph(10)
    W = 8
    labels = partition_numpy(dur, wts, src, dst, W)
    assert labels.min() >= 0 and labels.max() < W
    vol = _comm_volume(labels, src, dst)
    rng = np.random.default_rng(0)
    vol_rand = _comm_volume(rng.integers(0, W, len(dur)), src, dst)
    vol_blocks = _comm_volume(block_init(dur, W), src, dst)
    # refinement beats both a random partition and its own init
    assert vol < 0.4 * vol_rand
    assert vol < vol_blocks
    # hard admission cap keeps load within ~cap of the average
    load = np.bincount(labels, minlength=W).astype(float)
    assert load.max() <= 1.5 * (len(dur) / W)


def test_partition_trivial_cases():
    assert len(partition_numpy(np.ones(0, np.float32), np.ones(0, np.float32),
                               np.zeros(0, np.int32), np.zeros(0, np.int32), 4)) == 0
    one = partition_numpy(np.ones(5, np.float32), np.ones(0, np.float32),
                          np.zeros(0, np.int32), np.zeros(0, np.int32), 1)
    assert (one == 0).all()


@pytest.mark.skipif(not jax_available(), reason="jax backend unavailable")
def test_partition_jax_matches_numpy():
    keys, dur, wts, src, dst = _blockwise_graph(8)
    W = 6
    init = block_init(dur, W)
    a = partition_numpy(dur, wts, src, dst, W, init=init)
    b = partition_jax(dur, wts, src, dst, W, init=init)
    # identical algorithm, identical deterministic updates
    assert (a == b).all()


def test_live_planner_partitions_and_wins_locality():
    """Product path: LocalCluster with the partitioner planner (numpy
    engine for determinism), a blockwise graph, and plan consumption via
    deep home stacks.  Transfers must come in well under the no-plan
    run's."""
    from distributed_tpu import config
    from distributed_tpu.client.client import Client
    from distributed_tpu.deploy.local import LocalCluster

    def mul(a, b):
        return a * b

    def red(*xs):
        return sum(xs)

    async def run(jax_on: bool):
        from distributed_tpu.graph.spec import Graph, TaskRef, TaskSpec

        with config.set({
            "scheduler.jax.enabled": jax_on,
            "scheduler.jax.min-workers": 0,
            "scheduler.jax.min-batch": 64,
            "scheduler.jax.min-transfer-ratio": 0,
            "scheduler.jax.partitioner": "numpy",
            "scheduler.jax.sync-plan": True,
        }):
            async with LocalCluster(n_workers=8, threads_per_worker=1) as cluster:
                async with Client(cluster.scheduler_address) as c:
                    G = 8
                    g = Graph()
                    outs = []
                    for i in range(G):
                        for k in range(G):
                            g.tasks[f"s-{i}-{k}"] = TaskSpec(mul, (i, k))
                    for i in range(G):
                        for j in range(G):
                            for k in range(G):
                                g.tasks[f"m-{i}-{j}-{k}"] = TaskSpec(
                                    mul,
                                    (TaskRef(f"s-{i}-{k}"), TaskRef(f"s-{j}-{k}")),
                                )
                            g.tasks[f"r-{i}-{j}"] = TaskSpec(
                                red,
                                tuple(TaskRef(f"m-{i}-{j}-{k}") for k in range(G)),
                            )
                            outs.append(f"r-{i}-{j}")
                    futs = c.compute_graph(g, outs)
                    res = await asyncio.wait_for(
                        c.gather([futs[k] for k in outs]), 120
                    )
                    # correctness oracle
                    assert res[0] == sum((0 * k) * (0 * k) for k in range(G))
                    assert res[-1] == sum(
                        (7 * k) * (7 * k) for k in range(G)
                    )
                    served = sum(
                        getattr(w, "get_data_keys_served", 0)
                        for w in cluster.workers
                    )
                    pl = cluster.scheduler.state.placement
                    stats = (
                        (pl.plans_computed, pl.plan_hits) if pl else (0, 0)
                    )
                    return served, stats

    async def main():
        # bounded retries: the margin is normally huge (plan runs cut
        # transfers ~10x), but a CPU-starved CI box can stall the
        # no-plan run's stealing into an unusually LOW served_off —
        # both measurements are re-taken together so the comparison
        # stays within one load regime.  Attempts print their numbers
        # so an eventual failure is diagnosable from the CI log.
        import sys

        history = []
        for attempt in range(3):
            served_off, _ = await run(False)
            served_on, (plans, hits) = await run(True)
            history.append(
                (attempt, served_on, served_off, plans, hits)
            )
            print(
                f"# locality attempt {attempt}: served_on={served_on} "
                f"served_off={served_off} plans={plans} hits={hits}",
                file=sys.stderr,
            )
            assert plans >= 1
            assert hits > 0
            # the whole point: the plan must cut peer transfers hard
            if served_on < 0.75 * served_off:
                return
        raise AssertionError(history)

    asyncio.run(main())
