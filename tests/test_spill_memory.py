"""Spill buffer + worker memory management tests (reference test_spill.py,
test_worker_memory.py patterns)."""

from __future__ import annotations

import asyncio
import os

import pytest

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.local import LocalCluster
from distributed_tpu.worker.spill import SpillBuffer

from conftest import gen_test


def test_spill_buffer_basic(tmp_path):
    buf = SpillBuffer(str(tmp_path / "spill"), target=0)
    buf["a"] = b"x" * 1000
    buf["b"] = list(range(100))
    assert len(buf) == 2
    assert buf["a"] == b"x" * 1000
    assert sorted(buf) == ["a", "b"]
    del buf["a"]
    assert "a" not in buf
    with pytest.raises(KeyError):
        buf["a"]
    buf.close()


def test_spill_buffer_evicts_lru(tmp_path):
    buf = SpillBuffer(str(tmp_path / "spill"), target=0)
    buf["a"] = b"a" * 10_000
    buf["b"] = b"b" * 10_000
    buf["c"] = b"c" * 10_000
    _ = buf["a"]  # touch: a becomes most-recent
    freed = buf.evict()  # LRU is b
    assert freed > 0
    assert "b" in buf.slow and "b" not in buf.fast
    assert buf.spilled_count == 1
    # read-through unspills and promotes
    assert buf["b"] == b"b" * 10_000
    assert "b" in buf.fast and "b" not in buf.slow
    assert buf.unspilled_count == 1
    buf.close()


def test_spill_buffer_target_auto_evicts(tmp_path):
    buf = SpillBuffer(str(tmp_path / "spill"), target=25_000)
    for i in range(5):
        buf[f"k{i}"] = b"v" * 10_000
    # fast layer must have shrunk to the budget; nothing lost
    assert buf.fast_bytes <= 25_000
    assert len(buf) == 5
    for i in range(5):
        assert buf[f"k{i}"] == b"v" * 10_000
    buf.close()


def test_spill_buffer_overwrite_accounting(tmp_path):
    buf = SpillBuffer(str(tmp_path / "spill"))
    buf["k"] = b"x" * 1000
    b1 = buf.fast_bytes
    buf["k"] = b"x" * 2000
    assert buf.fast_bytes > b1
    assert len(buf) == 1
    buf.close()


@gen_test()
async def test_cluster_serves_spilled_data():
    """Data evicted to disk is still gatherable and usable as a dependency."""
    async with LocalCluster(
        n_workers=1,
        worker_kwargs={"memory_limit": 10**12, "validate": True},
        scheduler_kwargs={"validate": True},
    ) as cluster:
        async with Client(cluster.scheduler_address) as c:
            fut = c.submit(lambda: b"payload" * 1000, key="spillme")
            assert (await fut.result())[:7] == b"payload"
            worker = cluster.workers[0]
            assert hasattr(worker.data, "evict")
            # force the key to disk
            while "spillme" in worker.data.fast:
                worker.data.evict()
            assert "spillme" in worker.data.slow
            # gather reads through the slow layer
            assert (await fut.result())[:7] == b"payload"
            # and dependent tasks can consume it
            ln = c.submit(len, fut)
            assert await ln.result() == 7000


@gen_test()
async def test_paused_worker_stops_executing():
    """A paused worker defers ready tasks until unpaused."""
    async with LocalCluster(
        n_workers=1, scheduler_kwargs={"validate": True},
        worker_kwargs={"validate": True},
    ) as cluster:
        worker = cluster.workers[0]
        from distributed_tpu.utils.misc import seq_name
        from distributed_tpu.worker.state_machine import PauseEvent, UnpauseEvent

        async with Client(cluster.scheduler_address) as c:
            worker.handle_stimulus(PauseEvent(stimulus_id=seq_name("test-pause")))
            worker.batched_stream.send(
                {"op": "worker-status-change", "status": "paused",
                 "stimulus_id": "test-pause"}
            )
            await asyncio.sleep(0.05)
            # scheduler took it out of the running pool
            assert not cluster.scheduler.state.running
            fut = c.submit(lambda: 11, key="paused-task")
            await asyncio.sleep(0.1)
            assert not fut.done()
            worker.handle_stimulus(UnpauseEvent(stimulus_id=seq_name("test-unpause")))
            worker.batched_stream.send(
                {"op": "worker-status-change", "status": "running",
                 "stimulus_id": "test-unpause"}
            )
            assert await asyncio.wait_for(fut.result(), 10) == 11
