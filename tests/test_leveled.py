"""Level-synchronous placement engine (ops/leveled.py): C++ pack parity
with the numpy fallback, placement invariants, policy behaviors.

Mirrors the reference's placement semantics tests in spirit
(decide_worker locality + rootish spreading, scheduler.py:8550,2135);
the engine itself is validated against host oracles, not the reference.
"""

from __future__ import annotations

import numpy as np
import pytest

from distributed_tpu.ops.leveled import (
    SMALL_WAVE,
    _pack_numpy,
    _plan_runs,
    pack_graph,
    place_graph_leveled,
    validate_leveled,
)

BW = 100e6


def random_dag(rng, n, max_deps=2):
    durations = rng.uniform(0.01, 1.0, n).astype(np.float32)
    out_bytes = rng.uniform(1e3, 1e7, n).astype(np.float32)
    n_deps = rng.integers(0, max_deps + 1, n)
    n_deps[0] = 0
    dst = np.repeat(np.arange(n), n_deps).astype(np.int32)
    src = (rng.random(len(dst)) * np.maximum(dst, 1)).astype(np.int32)
    return durations, out_bytes, src, dst


def workers(W, threads=2, stopped=()):
    running = np.ones(W, bool)
    for s in stopped:
        running[s] = False
    return (
        np.full(W, threads, np.int32),
        np.zeros(W, np.float32),
        running,
    )


# ------------------------------------------------------------------ pack


def test_pack_native_matches_numpy_fallback():
    rng = np.random.default_rng(1)
    durations, out_bytes, src, dst = random_dag(rng, 3000)
    packed = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    lv, perm, heavy, heavy2, dep_total, offsets, L = _pack_numpy(
        durations, out_bytes, src.astype(np.int64), dst.astype(np.int64)
    )
    assert packed.n_levels == L
    np.testing.assert_array_equal(packed.level, lv)
    np.testing.assert_array_equal(packed.perm, perm)
    np.testing.assert_array_equal(packed.offsets, offsets)
    inv = np.empty(3000, np.int32)
    inv[perm] = np.arange(3000)
    hp = heavy[perm]
    np.testing.assert_array_equal(
        packed.heavy_s, np.where(hp >= 0, inv[np.maximum(hp, 0)], -1)
    )
    h2p = heavy2[perm]
    np.testing.assert_array_equal(
        packed.heavy2_s, np.where(h2p >= 0, inv[np.maximum(h2p, 0)], -1)
    )
    indeg = np.zeros(3000, np.float32)
    np.add.at(indeg, dst[(src != dst)], 1.0)
    np.testing.assert_allclose(
        packed.xfer_all_s,
        dep_total[perm] / BW + 0.001 * indeg[perm],
        rtol=1e-5, atol=1e-7,
    )
    np.testing.assert_array_equal(packed.duration_s, durations[perm])
    # latency=0 strips the per-dependency round-trip term
    packed0 = pack_graph(durations, out_bytes, src, dst, bandwidth=BW,
                         latency=0.0)
    np.testing.assert_allclose(
        packed0.xfer_all_s, dep_total[perm] / BW, rtol=1e-5
    )


def test_pack_levels_are_topological():
    rng = np.random.default_rng(2)
    _, _, src, dst = random_dag(rng, 2000)
    packed = pack_graph(*random_dag(np.random.default_rng(2), 2000))
    lv = packed.level
    assert (lv[dst] > lv[src]).all()
    # level 0 == tasks with no deps
    has_dep = np.zeros(2000, bool)
    has_dep[dst] = True
    np.testing.assert_array_equal(lv == 0, ~has_dep)


def test_pack_cycle_detected():
    durations = np.ones(3, np.float32)
    out_bytes = np.ones(3, np.float32)
    src = np.asarray([0, 1, 2], np.int32)
    dst = np.asarray([1, 2, 0], np.int32)
    with pytest.raises(ValueError, match="cycle"):
        pack_graph(durations, out_bytes, src, dst)
    with pytest.raises(ValueError, match="cycle"):
        _pack_numpy(durations, out_bytes, src.astype(np.int64),
                    dst.astype(np.int64))


def test_pack_empty_and_single():
    p = pack_graph(np.ones(1, np.float32), np.ones(1, np.float32),
                   np.zeros(0, np.int32), np.zeros(0, np.int32))
    assert p.n_levels == 1
    assert p.offsets.tolist() == [0, 1]


def test_plan_runs_fuses_small_waves():
    # 5 small waves then one big one then 2 small
    offsets = np.cumsum([0, 10, 20, 30, 40, 50, SMALL_WAVE * 3, 10, 10])
    runs = _plan_runs(offsets.astype(np.int32))
    assert runs[0] == (SMALL_WAVE, [0, 1, 2, 3, 4])
    assert runs[1][1] == [5]
    assert runs[1][0] > SMALL_WAVE
    assert runs[2] == (SMALL_WAVE, [6, 7])


# ------------------------------------------------------------- placement


def test_chain_stays_local():
    n = 50
    durations = np.ones(n, np.float32)
    out_bytes = np.full(n, 1e6, np.float32)
    src = np.arange(n - 1, dtype=np.int32)
    dst = src + 1
    packed = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    res = place_graph_leveled(packed, *workers(4))
    validate_leveled(packed, res, src, dst, workers(4)[2])
    assert res.n_waves == n
    assert len(np.unique(res.assignment)) == 1  # heavy-dep following


def test_mapreduce_spreads_roots_and_pins_reducers():
    width, reducers = 64, 8
    n = width + reducers + 1
    durations = np.ones(n, np.float32)
    out_bytes = np.full(n, 1e6, np.float32)
    src, dst = [], []
    per = width // reducers
    for r in range(reducers):
        for i in range(r * per, (r + 1) * per):
            src.append(i)
            dst.append(width + r)
    for r in range(reducers):
        src.append(width + r)
        dst.append(width + reducers)
    src = np.asarray(src, np.int32)
    dst = np.asarray(dst, np.int32)
    packed = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    nthreads, occ, running = workers(8)
    res = place_graph_leveled(packed, nthreads, occ, running)
    validate_leveled(packed, res, src, dst, running)
    assert res.n_waves == 3
    a = res.assignment
    counts = np.bincount(a[:width], minlength=8)
    assert counts.max() <= 2 * counts.min() + 2, counts
    # each reducer lands with one of its feeders (locality)
    for r in range(reducers):
        feeders = set(a[r * per:(r + 1) * per])
        assert a[width + r] in feeders


def test_stopped_workers_get_nothing():
    rng = np.random.default_rng(3)
    durations, out_bytes, src, dst = random_dag(rng, 800)
    packed = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    nthreads, occ, running = workers(8, stopped=(2, 5))
    res = place_graph_leveled(packed, nthreads, occ, running)
    validate_leveled(packed, res, src, dst, running)
    counts = np.bincount(res.assignment, minlength=8)
    assert counts[2] == 0 and counts[5] == 0


def test_random_dag_invariants_and_start_times():
    rng = np.random.default_rng(4)
    durations, out_bytes, src, dst = random_dag(rng, 5000)
    packed = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    nthreads, occ, running = workers(16)
    res = place_graph_leveled(packed, nthreads, occ, running)
    validate_leveled(packed, res, src, dst, running)
    # modeled start times respect dependency order
    assert (res.start_time[dst] >= res.start_time[src]).all()
    counts = np.bincount(res.assignment, minlength=16)
    assert counts.max() / counts.mean() < 2.0


def test_initial_occupancy_biases_spread():
    # all workers idle except worker 0 which is very busy: the spread
    # choice must put almost nothing new on worker 0
    n = 1000
    durations = np.ones(n, np.float32)
    out_bytes = np.zeros(n, np.float32)
    src = np.zeros(0, np.int32)
    dst = np.zeros(0, np.int32)
    packed = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    nthreads = np.full(4, 2, np.int32)
    occ0 = np.asarray([1e6, 0, 0, 0], np.float32)
    running = np.ones(4, bool)
    res = place_graph_leveled(packed, nthreads, occ0, running)
    counts = np.bincount(res.assignment, minlength=4)
    assert counts[0] <= counts[1:].min()


def test_wide_graph_exercises_fused_and_big_waves():
    # two levels: one tiny (fused path), one far above SMALL_WAVE (big path)
    n_roots = 4
    n_leaves = SMALL_WAVE * 2 + 17
    n = n_roots + n_leaves
    durations = np.ones(n, np.float32)
    out_bytes = np.full(n, 1e3, np.float32)
    dst = np.arange(n_roots, n, dtype=np.int32)
    src = (dst % n_roots).astype(np.int32)
    packed = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    nthreads, occ, running = workers(8)
    res = place_graph_leveled(packed, nthreads, occ, running)
    validate_leveled(packed, res, src, dst, running)
    assert res.n_waves == 2
    counts = np.bincount(res.assignment, minlength=8)
    assert counts.max() / counts.mean() < 1.5
