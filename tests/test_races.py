"""Deterministic race tests over the gen_cluster harness (the reference's
test_cancelled_state / test_steal / test_worker deathmatch tier).

Each test pins an interleaving with Blocked* workers or in-task barriers
and asserts the cluster converges with correct results and clean
validate-mode state.
"""

from __future__ import annotations

import asyncio

import pytest

from distributed_tpu import config
from distributed_tpu.client.client import wait
from distributed_tpu.exceptions import KilledWorker
from utils_cluster import (
    BlockedExecute,
    BlockedGatherDep,
    BlockedGetData,
    add,
    gen_cluster,
    inc,
    slowinc,
    wait_for,
)

import threading as _threading

# Module-level event/run registries: task closures over threading.Event
# are unpicklable (cloudpickle can't do locks), which matters the moment
# the cluster runs over tcp.  Workers are in-process even then, so a
# module-global registry gives tests the same tight thread control with
# functions that pickle by reference.
_EVENTS: dict[str, _threading.Event] = {}
_RUNS: dict[str, list] = {}


def _event(name: str) -> _threading.Event:
    ev = _EVENTS[name] = _threading.Event()
    _RUNS[name] = []
    return ev


def blocked_on_event(x, name, timeout=30):
    _RUNS[name].append(x)
    _EVENTS[name].wait(timeout)
    return x + 1


# ------------------------------------------------------- transport smoke


@gen_cluster(transports=("inproc", "tcp"))
async def test_submit_chain_both_transports(c, s, a, b):
    """The basic E2E flow must behave identically over inproc and tcp
    (framing, backpressure, serialization)."""
    x = c.submit(inc, 1)
    y = c.submit(inc, x)
    z = c.submit(add, x, y)
    assert await z.result() == 5


@gen_cluster(transports=("inproc", "tcp"))
async def test_cross_worker_fetch_both_transports(c, s, a, b):
    x = c.submit(inc, 1, workers=[a.address], key="x")
    y = c.submit(add, x, 10, workers=[b.address], key="y")
    assert await y.result() == 12
    assert "x" in b.data or "x" in a.data


# ------------------------------------------------- cancelled / resumed


@gen_cluster(transports=("inproc", "tcp"))
async def test_cancel_while_executing(c, s, a, b):
    """Releasing a future mid-execution: the worker cannot interrupt the
    thread — the task enters 'cancelled', finishes silently, and its
    value is dropped."""
    ev = _event("cancelme")
    fut = c.submit(blocked_on_event, 1, "cancelme", key="cancelme",
                   workers=[a.address])
    await wait_for(lambda: a.state.tasks.get("cancelme") is not None
                   and a.state.tasks["cancelme"].state == "executing")
    await c.cancel([fut])
    await wait_for(lambda: a.state.tasks["cancelme"].state == "cancelled")
    ev.set()
    await wait_for(lambda: "cancelme" not in a.state.tasks
                   or a.state.tasks["cancelme"].state in ("released", "forgotten"))
    assert "cancelme" not in a.data


@gen_cluster(transports=("inproc", "tcp"))
async def test_resume_while_executing(c, s, a, b):
    """Cancel then immediately resubmit while the thread still runs: the
    single execution must satisfy the resumed request (no double run)."""
    ev = _event("resume-x")
    fut = c.submit(blocked_on_event, 1, "resume-x", key="resume-x",
                   workers=[a.address])
    await wait_for(lambda: a.state.tasks.get("resume-x") is not None
                   and a.state.tasks["resume-x"].state == "executing")
    await c.cancel([fut])
    await wait_for(lambda: a.state.tasks["resume-x"].state == "cancelled")
    fut2 = c.submit(blocked_on_event, 1, "resume-x", key="resume-x",
                    workers=[a.address])
    # the cancellation is forgotten in place (reference wsm.py:2157)
    await wait_for(lambda: a.state.tasks["resume-x"].state == "executing")
    ev.set()
    assert await fut2.result() == 2
    assert len(_RUNS["resume-x"]) == 1  # the cancelled execution was reused


@gen_cluster(transports=("inproc", "tcp"), worker_cls=[BlockedExecute, None])
async def test_release_between_instruction_and_first_tick(c, s, a, b):
    """Execute issued -> released -> recomputed before the coroutine
    ticks: the resumed task must still complete (round-3 restart hang)."""
    fut = c.submit(inc, 1, key="tick-x", workers=[a.address])
    await a.in_execute.wait()
    await c.cancel([fut])
    await wait_for(lambda: a.state.tasks.get("tick-x") is None
                   or a.state.tasks["tick-x"].state in ("cancelled", "released"))
    fut2 = c.submit(inc, 1, key="tick-x", workers=[a.address])
    a.block_execute.set()
    a.block_execute_exit.set()
    assert await fut2.result() == 2


# --------------------------------------------------- fetch / flight races


@gen_cluster(transports=("inproc", "tcp"), worker_cls=[BlockedGatherDep, None])
async def test_worker_death_mid_gather_dep(c, s, a, b):
    """The peer dies while a dependency fetch is in flight: the fetcher
    reports missing data and the dep is recomputed; the dependent still
    completes."""
    x = c.submit(inc, 1, key="gx", workers=[b.address],
                 allow_other_workers=True)
    await x.result()
    y = c.submit(add, x, 10, key="gy", workers=[a.address])
    await a.in_gather_dep.wait()
    await b.close(report=False)
    a.block_gather_dep.set()
    assert await y.result() == 12


@gen_cluster(transports=("inproc", "tcp"), worker_cls=[BlockedGatherDep, None, None], nthreads=[1, 1, 1])
async def test_fetch_races_with_replica_on_second_worker(c, s, a, b, d):
    """While a fetch from one holder is blocked, the holder dies but a
    second replica exists: the retry must fetch from the survivor."""
    x = c.submit(inc, 1, key="rx", workers=[b.address])
    await x.result()
    await s.replicate(keys=["rx"], workers=[b.address, d.address])
    await wait_for(lambda: len(s.state.tasks["rx"].who_has) == 2)
    y = c.submit(add, x, 10, key="ry", workers=[a.address])
    await a.in_gather_dep.wait()
    await b.close(report=False)
    a.block_gather_dep.set()
    assert await y.result() == 12


@gen_cluster(transports=("inproc", "tcp"), worker_cls=[None, BlockedGetData])
async def test_cancelled_flight_drops_data_without_phantom_replica(c, s, a, b):
    """A fetch cancelled mid-flight whose bytes still arrive must drop
    them AND not announce a replica (the round-3 tensordot livelock)."""
    x = c.submit(inc, 1, key="px", workers=[b.address])
    await wait([x])  # completion only: a result() gather would block on b
    y = c.submit(add, x, 10, key="py", workers=[a.address])
    await b.in_get_data.wait()
    # cancel the dependent: the in-flight fetch of px on a is cancelled
    await c.cancel([y])
    await wait_for(
        lambda: (ts := a.state.tasks.get("px")) is None
        or ts.state in ("cancelled", "released")
    )
    b.block_get_data.set()
    await wait_for(
        lambda: (ts := a.state.tasks.get("px")) is None
        or ts.state in ("released", "forgotten")
    )
    # no phantom replica on a in the scheduler's books
    assert all(
        ws.address != a.address for ws in s.state.tasks["px"].who_has
    )
    # and the cluster still works
    z = c.submit(add, x, 20, key="pz")
    assert await z.result() == 22


@gen_cluster(transports=("inproc", "tcp"), worker_cls=[None, BlockedGetData])
async def test_fetch_cancel_recompute_satisfied_by_arriving_data(c, s, a, b):
    """flight -> cancelled -> re-requested as compute on the same worker:
    the data arriving from the original fetch satisfies the resumed task
    directly (no execution exists to complete it)."""
    x = c.submit(inc, 1, key="fx", workers=[b.address])
    await wait([x])  # completion only: a result() gather would block on b
    y = c.submit(add, x, 10, key="fy", workers=[a.address])
    await b.in_get_data.wait()
    await c.cancel([y])
    await wait_for(
        lambda: (ts := a.state.tasks.get("fx")) is None
        or ts.state in ("cancelled", "released")
    )
    # re-request fx as a computation pinned to a while the old fetch is
    # still in flight
    fx2 = c.submit(inc, 1, key="fx", workers=[a.address])
    b.block_get_data.set()
    assert await fx2.result() == 2
    await wait_for(
        lambda: (ts := a.state.tasks.get("fx")) is None
        or ts.state in ("memory", "released", "forgotten")
    )


@gen_cluster(transports=("inproc", "tcp"))
async def test_pause_during_flight(c, s, a, b):
    """Pausing a worker while its dependency fetches are in flight must
    not lose them; tasks complete after unpause."""
    from distributed_tpu.worker.state_machine import PauseEvent, UnpauseEvent

    x = c.submit(inc, 1, key="pax", workers=[b.address])
    await x.result()
    a.handle_stimulus(PauseEvent(stimulus_id="test-pause"))
    y = c.submit(add, x, 10, key="pay", workers=[a.address])
    await asyncio.sleep(0.2)  # y must not run while paused
    assert a.state.tasks.get("pay") is None or \
        a.state.tasks["pay"].state != "memory"
    a.handle_stimulus(UnpauseEvent(stimulus_id="test-unpause"))
    assert await y.result() == 12


# ------------------------------------------------------------- stealing


@gen_cluster(transports=("inproc", "tcp"), config_overrides={"scheduler.work-stealing-interval": "50ms"})
async def test_steal_confirm_vs_completion(c, s, a, b):
    """A steal request racing task completion: the victim answers with
    its current state and the scheduler must NOT double-run the task."""
    steal = s.extensions["stealing"]
    await c.submit(slowinc, -1, delay=0.01).result()  # prime duration
    futs = c.map(
        slowinc, range(10), delay=0.05,
        workers=[a.address], allow_other_workers=True,
    )
    assert await c.gather(futs) == list(range(1, 11))
    # every key computed exactly once cluster-wide per completion
    story = [e for e in steal.log if e[0] in ("confirm", "reject")]
    for f in futs:
        assert s.state.tasks[f.key].state == "memory"
    # at least one steal interaction happened under the pin
    assert steal.count >= 1 or any(e[0] == "reject" for e in story)


@gen_cluster(transports=("inproc", "tcp"), worker_cls=[BlockedExecute, None],
             config_overrides={"scheduler.work-stealing-interval": "50ms"})
async def test_steal_request_for_executing_task_rejected(c, s, a, b):
    """The victim is already executing the task: the steal confirm must
    report it and the scheduler leaves it in place."""
    steal = s.extensions["stealing"]
    fut = c.submit(
        slowinc, 1, delay=0.01, key="steal-exec",
        workers=[a.address], allow_other_workers=True,
    )
    await a.in_execute.wait()
    ts = s.state.tasks["steal-exec"]
    victim = s.state.workers[a.address]
    thief = s.state.workers[b.address]
    steal.move_task_request(ts, victim, thief)
    a.block_execute.set()
    a.block_execute_exit.set()
    assert await fut.result() == 2
    await wait_for(lambda: not steal.in_flight)
    # the task must have completed on the victim (reject path)
    assert any(e[0] == "reject" for e in steal.story("steal-exec")) or \
        s.state.tasks["steal-exec"].state == "memory"


# -------------------------------------------------------- worker death


@gen_cluster(transports=("inproc", "tcp"))
async def test_worker_death_mid_execute_recomputes(c, s, a, b):
    """Kill the worker running a task: the scheduler reassigns it and the
    client sees the result."""

    def slow_unique(x, delay=0.5):
        import time

        time.sleep(delay)
        return x + 1

    fut = c.submit(slow_unique, 1, key="die-x", workers=[a.address],
                   allow_other_workers=True)
    await wait_for(lambda: (ts := a.state.tasks.get("die-x")) is not None
                   and ts.state == "executing")
    await a.close(report=False)
    assert await fut.result() == 2
    assert s.state.tasks["die-x"].who_has


@gen_cluster(transports=("inproc", "tcp"), config_overrides={"scheduler.allowed-failures": 1},
             leak_check=False)  # parks sleep(30) bodies in executor threads
async def test_repeated_worker_death_kills_task(c, s, a, b):
    """A task whose workers keep dying exhausts allowed-failures and
    errs with KilledWorker instead of looping forever."""
    def forever(x):
        import time

        time.sleep(30)
        return x

    fut = c.submit(forever, 1, key="kw-x")
    extras = []  # replacement workers: the harness only closes originals
    try:
        for _ in range(3):
            await wait_for(
                lambda: (pts := s.state.tasks.get("kw-x")) is not None
                and (pts.processing_on is not None or pts.state == "erred")
            )
            if s.state.tasks["kw-x"].state == "erred":
                break
            addr = s.state.tasks["kw-x"].processing_on.address
            victim = a if a.address == addr else b
            await victim.close(report=False)
            if s.state.tasks["kw-x"].state == "erred":
                break
            # revive a replacement so the cluster keeps going
            from distributed_tpu.worker.server import Worker

            nw = Worker(s.address, nthreads=1, validate=True,
                        listen_addr="inproc://")
            await nw.start()
            extras.append(nw)
            if victim is a:
                a = nw
            else:
                b = nw
        with pytest.raises(KilledWorker):
            await fut.result()
    finally:
        for nw in extras:
            try:
                await nw.close(report=False)
            except Exception:
                pass


@gen_cluster(transports=("inproc", "tcp"), nthreads=[1, 1, 1], leak_check=False)  # blocked bodies
async def test_broadcast_replica_survives_holder_death(c, s, a, b, d):
    """With replicas on two workers, losing one must not interrupt
    consumers."""
    [x] = await c.scatter([41], workers=[a.address])
    await s.replicate(keys=[x.key], workers=[a.address, b.address])
    await wait_for(lambda: len(s.state.tasks[x.key].who_has) == 2)
    await a.close(report=False)
    y = c.submit(inc, x, workers=[d.address])
    assert await y.result() == 42


# ------------------------------------------------------ queue / lifecycle


@gen_cluster(transports=("inproc", "tcp"), nthreads=[1], config_overrides={"scheduler.worker-saturation": 1.0},
             leak_check=False)  # blocked bodies
async def test_cancel_queued_tasks(c, s, a):
    """Cancelling tasks that sit in the scheduler queue removes them
    without disturbing the rest."""
    ev = _event("q-head")
    first = c.submit(blocked_on_event, 0, "q-head", key="q-head")
    await wait_for(lambda: (ts := s.state.tasks.get("q-head")) is not None
                   and ts.state == "processing")
    rest = c.map(slowinc, range(8), delay=0.01, pure=False)
    await wait_for(lambda: any(
        ts.state == "queued" for ts in s.state.tasks.values()
    ))
    victims = rest[:4]
    survivors = rest[4:]
    await c.cancel(victims)
    ev.set()
    assert await c.gather(survivors) == [i + 1 for i in range(4, 8)]
    assert await first.result() == 1


@gen_cluster(transports=("inproc", "tcp"), leak_check=False)  # blocked bodies outlive the cluster
async def test_retire_worker_while_processing(c, s, a, b):
    """Gracefully retiring a busy worker moves its data and queued work;
    all results remain reachable."""
    futs = c.map(slowinc, range(10), delay=0.05, pure=False)
    await asyncio.sleep(0.05)
    await s.retire_workers(workers=[a.address])
    assert await c.gather(futs) == list(range(1, 11))
    assert a.address not in s.state.workers


@gen_cluster(transports=("inproc", "tcp"), leak_check=False)  # blocked bodies outlive the cluster
async def test_missing_data_reroute_after_manual_drop(c, s, a, b):
    """A peer that claims a key but cannot serve it (data vanished) must
    be purged from who_has via missing-data and the key recomputed."""
    from distributed_tpu.worker.state_machine import FreeKeysEvent

    x = c.submit(inc, 1, key="mx", workers=[b.address])
    await x.result()
    # sabotage: release the data on b without the scheduler knowing (the
    # free-keys path normally only runs scheduler->worker)
    b.handle_stimulus(FreeKeysEvent(stimulus_id="sabotage", keys=("mx",)))
    assert "mx" not in b.data
    y = c.submit(add, x, 10, key="my", workers=[a.address])
    assert await y.result() == 12


# --------------------------------------------------------- shuffle x race


@gen_cluster(transports=("inproc", "tcp"), nthreads=[1, 1, 1], timeout=150, leak_check=False)  # killed worker leaves transfer body
async def test_mid_shuffle_kill_under_blocked_transfer(c, s, a, b, d):
    """Kill an output owner while transfers are mid-stream; the epoch
    restart must converge with complete output."""
    from distributed_tpu.shuffle import p2p_shuffle

    def part(i, n=500):
        return [(i * n + k, k) for k in range(n)]

    inputs = [c.submit(part, i, key=f"sin-{i}") for i in range(6)]
    await c.gather(inputs)
    ext = s.extensions["shuffle"]
    outs = await p2p_shuffle(c, inputs, npartitions_out=6)
    await wait_for(lambda: bool(ext.active))
    sid = next(iter(ext.active))
    victim_addr = ext.active[sid].worker_for[0]
    victim = next(w for w in (a, b, d) if w.address == victim_addr)
    await victim.close(report=False)
    results = await c.gather(outs)
    got = sorted(x for p in results for x in p)
    want = sorted(x for i in range(6) for x in part(i))
    assert got == want


@gen_cluster(transports=("inproc", "tcp"))
async def test_removal_reschedule_with_dependent_chain(c, s, a, b):
    """Worker removal while it holds BOTH a finished chain's data and a
    running task: the reschedule cascade sees deps transiently in
    'memory' with no replica and must still recompute everything (the
    round-3 stranded-k3 bug found by /verify)."""
    ev = _event("ck1")
    f1 = c.submit(blocked_on_event, 1, "ck1", key="ck1",
                  workers=[a.address], allow_other_workers=True)
    await wait_for(lambda: (ts := a.state.tasks.get("ck1")) is not None
                   and ts.state == "executing")
    await c.cancel([f1])
    f2 = c.submit(blocked_on_event, 1, "ck1", key="ck1",
                  workers=[a.address], allow_other_workers=True)
    ev.set()
    assert await f2.result() == 2
    f3 = c.submit(lambda v: v * 2, f2, key="ck2", workers=[a.address],
                  allow_other_workers=True)
    assert await f3.result() == 4

    def slow(x):
        import time

        time.sleep(0.4)
        return x + 10

    f4 = c.submit(slow, 5, key="ck3", workers=[a.address],
                  allow_other_workers=True)
    await wait_for(lambda: (ts := s.state.tasks.get("ck3")) is not None
                   and ts.processing_on is not None)
    await a.close(report=False)
    # everything recomputes on b, including the chain ck1 -> ck2
    assert await asyncio.wait_for(f4.result(), 30) == 15
    assert await c.submit(lambda v: v + 1, f3, key="ck4").result() == 5


@gen_cluster(transports=("inproc", "tcp"), nthreads=[1, 1, 1])
async def test_amm_drop_races_with_new_dependent(c, s, a, b, d):
    """ReduceReplicas drops a replica while a NEW dependent is being
    placed on the dropping worker: the placement must not crash and the
    dependent must still compute (replica re-fetched if needed)."""
    x = c.submit(inc, 1, key="amm-x", workers=[a.address])
    await x.result()
    # replicate to all three workers
    await s.replicate(keys=["amm-x"])
    await wait_for(lambda: len(s.state.tasks["amm-x"].who_has) == 3)
    # AMM wants the extras dropped; meanwhile dependents land everywhere
    futs = [
        c.submit(add, x, i, key=f"amm-child-{i}", workers=[w.address])
        for i, w in enumerate((a, b, d))
    ]
    amm = s.extensions["amm"]
    amm.run_once()
    assert await asyncio.wait_for(c.gather(futs), 30) == [2, 3, 4]
    s.state.validate_state()


@gen_cluster(transports=("inproc", "tcp"), nthreads=[1, 1])
async def test_retire_worker_during_steal_confirm(c, s, a, b):
    """Retiring the thief mid steal-confirm must not lose the task."""
    from distributed_tpu.worker.state_machine import StealRequestEvent  # noqa: F401

    fut = c.submit(slowinc, 1, delay=0.4, key="rsc-x", workers=[a.address],
                   allow_other_workers=True)
    await wait_for(lambda: "rsc-x" in s.state.tasks
                   and s.state.tasks["rsc-x"].state == "processing")
    stealing = s.extensions["stealing"]
    ts = s.state.tasks["rsc-x"]
    # request a steal onto b, then immediately retire b
    victim = s.state.workers[a.address]
    thief = s.state.workers[b.address]
    stealing.move_task_request(ts, victim, thief)
    await s.retire_workers(workers=[b.address])
    assert await asyncio.wait_for(fut.result(), 30) == 2
    s.state.validate_state()


@gen_cluster(transports=("inproc", "tcp"), nthreads=[1, 1], worker_cls=[None, BlockedGetData])
async def test_client_releases_keys_while_fetch_blocked(c, s, a, b):
    """Releasing the only consumer while its dep fetch is stuck inside
    the peer's get_data: everything unwinds without phantom state."""
    x = c.submit(inc, 1, key="rel-x", workers=[b.address])
    # completion via the report stream, NOT x.result(): the result fetch
    # itself would block on b's wedged get_data
    await wait_for(lambda: "rel-x" in s.state.tasks
                   and s.state.tasks["rel-x"].state == "memory")
    y = c.submit(add, x, 1, key="rel-y", workers=[a.address])
    await b.in_get_data.wait()
    y.release()
    await wait_for(lambda: "rel-y" not in s.state.tasks)
    b.block_get_data.set()
    # the cluster stays healthy; x is still computable data
    assert await c.submit(add, x, 5, key="rel-z").result() == 7
    s.state.validate_state()
    a.state.validate_state()


@gen_cluster(transports=("inproc", "tcp"), nthreads=[1, 1])
async def test_scatter_data_survives_holder_retirement(c, s, a, b):
    """Scattered (lineage-free) data must be replicated away when its
    holder retires, not lost (reference retire_workers semantics)."""
    [x] = await c.scatter([123], workers=[a.address])
    await s.retire_workers(workers=[a.address])
    assert a.address not in s.state.workers
    # data survived onto b and is usable
    assert await c.submit(inc, x, key="sc-y").result() == 124


@gen_cluster(transports=("inproc", "tcp"), nthreads=[1, 1], config_overrides={"scheduler.work-stealing": False})
async def test_resubmit_same_key_different_spec_while_erred(c, s, a, b):
    """Resubmitting a key whose previous incarnation erred replaces the
    spec and computes cleanly (cancelled/erred resubmission contract)."""
    bad = c.submit(lambda: 1 // 0, key="respec-k", pure=False)
    with pytest.raises(ZeroDivisionError):
        await bad.result()
    bad.release()
    await wait_for(lambda: "respec-k" not in s.state.tasks)
    good = c.submit(inc, 41, key="respec-k", pure=False)
    assert await asyncio.wait_for(good.result(), 30) == 42


# ------------------------------------------- await-atomicity regressions


def test_retire_workers_revalidates_replica_landing_after_await():
    """Regression (await-atomicity lint, rule 10): retire_workers binds
    each unique-replica TaskState BEFORE awaiting the recipient's
    gather.  If the task is released while the transfer runs, landing
    the replica afterwards resurrects a forgotten task as a phantom
    replica record peers would be sent to fetch forever.  The fix
    re-validates task and recipient against live state after the await."""
    import asyncio as _asyncio

    from distributed_tpu.scheduler.server import Scheduler

    async def body():
        s = Scheduler(listen_addr="inproc://", http_port=None)
        state = s.state
        retiree = state.add_worker_state("tcp://w1:1", nthreads=1)
        target = state.add_worker_state("tcp://w2:1", nthreads=1)
        # a pure-data (scattered) key whose only replica lives on the
        # retiree — exactly what retire_workers must move
        ts = state.new_task("k", None, "released")
        state._transition("k", "memory", "seed", worker=retiree.address,
                          nbytes=8)
        assert ts.state == "memory" and list(ts.who_has) == [retiree]

        class _Proxy:
            async def gather(self, who_has=None):
                # the concurrent client release lands mid-transfer, on
                # the same loop turn the server yielded
                state.transitions({"k": "released"}, "concurrent-release")
                return {"status": "OK"}

            async def terminate(self):
                return "OK"

        s.rpc = lambda addr: _Proxy()

        async def _remove(addr, reason, safe=False):
            state.remove_worker_state(addr, stimulus_id="retire", safe=safe)

        s.remove_worker = _remove
        retired = await s.retire_workers(["tcp://w1:1"])
        assert retired == ["tcp://w1:1"]
        # pure data with no lineage: released -> forgotten, gone for good
        assert "k" not in state.tasks
        # the phantom replica must NOT have been landed on the survivor
        assert ts not in target.has_what, "forgotten task resurrected"
        assert not ts.who_has
        assert target.nbytes == 0
        state.validate_state()

    _asyncio.run(body())


def test_waiting_released_reroutes_resurrected_waiters():
    """waiting->released on a non-rerunning (erred-blamed) task used to
    blindly clear its waiters — but an erred-retry hop in the SAME
    recommendation drain can have resurrected a dependent back to
    waiting and re-registered it, leaving the dependent waiting on a
    dep that would never run (dangling ``waiting_on``, a liveness
    hole).  The interleaving is recommendation-dict (hash) order
    dependent, so the historical repro flaked per process; this pins
    the failing interleaving via PYTHONHASHSEED and replays the mirror
    churn trace that first exposed it."""
    import os
    import subprocess
    import sys

    script = r"""
import random, sys
sys.path.insert(0, %r)
from test_mirror import _state, _submit, _flip_status
rng = random.Random(1)
state = _state(n_workers=3, nthreads=rng.choice([1, 2]))
graph_n = 0
for step in range(250):
    op = rng.random()
    workers = list(state.workers.values())
    if op < 0.06 and len(workers) < 12:
        state.add_worker_state(
            f"tcp://127.0.0.1:{20000 + step}",
            nthreads=rng.choice([1, 2, 4]), memory_limit=2**30)
    elif op < 0.10 and len(workers) > 1:
        state.remove_worker_state(
            rng.choice(workers).address, stimulus_id=f"rm-{step}",
            safe=True)
    elif op < 0.13 and workers:
        state.set_worker_nthreads(rng.choice(workers),
                                  rng.choice([1, 2, 3, 4]))
    elif op < 0.18 and workers:
        ws = rng.choice(workers)
        _flip_status(state, ws,
                     "paused" if ws in state.running else "running")
    elif op < 0.28:
        graph_n += 1
        _submit(state, rng, rng.randint(4, 12), f"g{graph_n}")
    elif op < 0.34:
        mem = [t for t in state.tasks.values() if t.state == "memory"]
        if mem and workers:
            t = rng.choice(mem); ws = rng.choice(workers)
            if ws in t.who_has:
                if len(t.who_has) > 1:
                    state.remove_replica(t, ws)
            else:
                state.add_replica(t, ws)
    else:
        processing = [t for t in state.tasks.values()
                      if t.state == "processing"]
        if processing:
            t = rng.choice(processing)
            if rng.random() < 0.85:
                state.stimulus_task_finished(
                    t.key, worker=t.processing_on.address,
                    stimulus_id=f"fin-{step}",
                    nbytes=rng.randint(1, 10_000), typename="int")
            else:
                state.stimulus_task_erred(
                    t.key, worker=t.processing_on.address,
                    stimulus_id=f"err-{step}", exception_text="boom")
    state.validate_state()
print("TRACE-OK")
""" % (os.path.dirname(os.path.abspath(__file__)),)
    # hash seeds 6/8/25 historically popped the recommendation dict in
    # the failing order; 6 is the pinned repro
    proc = subprocess.run(
        [sys.executable, "-c", script],
        env={**os.environ, "PYTHONHASHSEED": "6", "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0 and "TRACE-OK" in proc.stdout, (
        proc.stdout[-1000:], proc.stderr[-3000:],
    )
