"""Persistent SchedulerState fleet mirror (scheduler/mirror.py).

Contracts under test:

- **Oracle parity.**  Replaying random transition + worker-churn traces
  (add/remove/resize, status flips, replica add/drop, finishes/errors),
  the incrementally-maintained mirror equals the from-scratch snapshot
  bit-for-bit at every step (``SchedulerMirror.verify`` raises
  otherwise — the same contract the ``DTPU_MIRROR_CHECK`` runtime mode
  enforces).
- **Slot stability.**  Worker slots survive unrelated churn; tombstoned
  slots are reused; capacity doubles and never invalidates live rows.
- **O(dirty) cycles.**  With the mirror fresh, a kernel cycle performs
  no O(W) Python-loop fleet pack (``oracle_packs`` stays 0) and no
  fleet H2D upload (``rows_uploaded``/``full_uploads`` deltas are 0).
- **Steal comm-cost fidelity.**  The device balance prices a task at
  the best idle thief's TRUE cost (thief-resident dependency bytes
  subtracted), so a profitable steal toward data is no longer rejected
  by the old every-thief-pays-everything estimate; moves re-check the
  criterion with the per-thief oracle cost at apply time.
"""

from __future__ import annotations

import random

import pytest

from distributed_tpu.graph.spec import TaskRef, TaskSpec
from distributed_tpu.scheduler.mirror import (
    MirrorParityError,
    SchedulerMirror,
    oracle_fleet,
)
from distributed_tpu.scheduler.state import SchedulerState
from distributed_tpu.scheduler.stealing import WorkStealing
from distributed_tpu.utils.test import StubScheduler


def _noop(*args):
    return 0


def _state(n_workers=0, nthreads=1, **kwargs) -> SchedulerState:
    state = SchedulerState(
        validate=True, transition_counter_max=500_000, **kwargs
    )
    for i in range(n_workers):
        state.add_worker_state(
            f"tcp://127.0.0.1:{10000 + i}",
            nthreads=nthreads,
            memory_limit=2**30,
            name=f"w{i}",
        )
    return state


def _submit(state, rng, n_tasks, tag):
    keys: list[str] = []
    tasks: dict = {}
    deps: dict = {}
    for i in range(n_tasks):
        key = f"{tag}-{i}"
        n_deps = rng.randint(0, min(2, len(keys)))
        dep_keys = rng.sample(keys, n_deps) if n_deps else []
        tasks[key] = TaskSpec(_noop, tuple(TaskRef(d) for d in dep_keys))
        deps[key] = set(dep_keys)
        keys.append(key)
    state.update_graph_core(
        tasks, deps, keys[-max(3, n_tasks // 3):], client="client-1",
        stimulus_id=f"graph-{tag}",
    )
    return keys


def _flip_status(state, ws, status):
    """Mimic server.handle_worker_status_change's state side effects."""
    state.set_worker_status(ws, status)
    if status == "paused":
        state.running.discard(ws)
        state.idle.pop(ws.address, None)
        state.idle_task_count.discard(ws)
        state.splice_parked(ws.address)
    else:
        state.running.add(ws)
        state.check_idle_saturated(ws)
        recs = state.bulk_schedule_unrunnable_after_adding_worker(ws)
        recs.update(state.stimulus_queue_slots_maybe_opened("flip"))
        state.transitions(recs, "flip")


# ------------------------------------------------------- oracle parity


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_mirror_parity_random_trace(seed):
    """The incremental mirror is bit-identical to the from-scratch
    snapshot after EVERY step of a random transition + churn trace."""
    rng = random.Random(seed)
    state = _state(n_workers=3, nthreads=rng.choice([1, 2]))
    m = state.mirror
    assert isinstance(m, SchedulerMirror)
    m.verify()
    graph_n = 0
    for step in range(250):
        op = rng.random()
        workers = list(state.workers.values())
        if op < 0.06 and len(workers) < 12:
            state.add_worker_state(
                f"tcp://127.0.0.1:{20000 + step}",
                nthreads=rng.choice([1, 2, 4]),
                memory_limit=2**30,
            )
        elif op < 0.10 and len(workers) > 1:
            ws = rng.choice(workers)
            state.remove_worker_state(
                ws.address, stimulus_id=f"rm-{step}", safe=True
            )
        elif op < 0.13 and workers:
            state.set_worker_nthreads(
                rng.choice(workers), rng.choice([1, 2, 3, 4])
            )
        elif op < 0.18 and workers:
            ws = rng.choice(workers)
            _flip_status(
                state, ws,
                "paused" if ws in state.running else "running",
            )
        elif op < 0.28:
            graph_n += 1
            _submit(state, rng, rng.randint(4, 12), f"g{graph_n}")
        elif op < 0.34:
            # replica churn on memory tasks (the AMM's delta source)
            mem = [
                ts for ts in state.tasks.values() if ts.state == "memory"
            ]
            if mem and workers:
                ts = rng.choice(mem)
                ws = rng.choice(workers)
                if ws in ts.who_has:
                    if len(ts.who_has) > 1:
                        state.remove_replica(ts, ws)
                else:
                    state.add_replica(ts, ws)
        else:
            processing = [
                ts
                for ts in state.tasks.values()
                if ts.state == "processing"
            ]
            if processing:
                ts = rng.choice(processing)
                if rng.random() < 0.85:
                    state.stimulus_task_finished(
                        ts.key,
                        worker=ts.processing_on.address,
                        stimulus_id=f"fin-{step}",
                        nbytes=rng.randint(1, 10_000),
                        typename="int",
                    )
                else:
                    state.stimulus_task_erred(
                        ts.key,
                        worker=ts.processing_on.address,
                        stimulus_id=f"err-{step}",
                        exception_text="boom",
                    )
        state.validate_state()
        m.verify()  # raises MirrorParityError on any divergence
    assert m.oracle_failures == 0
    assert m.deltas_applied > 0


def test_mirror_check_mode_catches_unmarked_mutation():
    """DTPU_MIRROR_CHECK semantics: a mirrored-field mutation that
    bypasses the delta paths (exactly what the mirror-parity lint rule
    exists to prevent) is caught by the oracle check."""
    state = _state(n_workers=3)
    m = state.mirror
    m.check = True
    m.fleet_view()
    ws = next(iter(state.workers.values()))
    ws.occupancy += 1.0  # graft-lint: allow[mirror-parity] deliberately unmarked to prove the check fires
    with pytest.raises(MirrorParityError):
        m.fleet_view()
    assert m.oracle_failures == 1
    # marking the row heals the mirror
    m.mark(ws)
    m.fleet_view()


def test_oracle_fleet_matches_disabled_mirror_state():
    """A mirror=False state runs with no mirror at all (consumers use
    the from-scratch pack), and the oracle pack sees the same fleet."""
    state = _state(n_workers=3, mirror=False)
    assert state.mirror is None
    rows = oracle_fleet(state)
    assert set(rows) == set(state.workers)


# ------------------------------------------------------- slot stability


def test_slot_stability_tombstones_and_growth():
    state = _state(n_workers=6)
    m = state.mirror
    slots = {addr: ws.idx for addr, ws in state.workers.items()}
    assert sorted(slots.values()) == list(range(6))
    victims = list(state.workers)[1:4:2]
    for addr in victims:
        state.remove_worker_state(addr, stimulus_id="t", safe=True)
    survivors = {addr: ws.idx for addr, ws in state.workers.items()}
    # unrelated churn never moves a live worker's slot
    assert all(slots[a] == i for a, i in survivors.items())
    freed = sorted(slots[a] for a in victims)
    w_new = state.add_worker_state("tcp://fresh:1", nthreads=2)
    assert w_new.idx in freed  # tombstone reused, no growth
    cap0 = m.cap
    for i in range(cap0 + 1):
        state.add_worker_state(f"tcp://grow:{i}", nthreads=1)
    assert m.cap > cap0  # capacity doubled
    assert {ws.idx for ws in state.workers.values()} == {
        ws.idx for ws in state.workers.values()
    }
    m.verify()
    fv = m.fleet_view()
    assert fv.n_live == len(state.workers)
    # live_pos inverts slots for every live worker
    for ws in state.workers.values():
        assert fv.live_list[fv.live_pos[ws.idx]] is ws


# --------------------------------------------- O(dirty) cycle contracts


def test_fresh_mirror_cycle_no_pack_no_upload():
    state = _state(n_workers=8, nthreads=2)
    m = state.mirror
    m.fleet_view()
    dv = m.device_view()
    if dv is None:
        pytest.skip("jax unavailable")
    base = m.stats()
    # an untouched fleet: views are free — no refresh, no upload
    fv = m.fleet_view()
    dv = m.device_view()
    after = m.stats()
    assert after["rows_refreshed"] == base["rows_refreshed"]
    assert after["rows_uploaded"] == base["rows_uploaded"]
    assert after["full_uploads"] == base["full_uploads"]
    assert after["oracle_packs"] == 0
    # one worker's occupancy changes -> exactly one row refreshes and
    # uploads; never a full rebuild
    ws = next(iter(state.workers.values()))
    state._adjust_occupancy(ws, 1.5)
    m.fleet_view()
    m.device_view()
    after2 = m.stats()
    assert after2["rows_refreshed"] == after["rows_refreshed"] + 1
    assert after2["rows_uploaded"] == after["rows_uploaded"] + 1
    assert after2["full_uploads"] == after["full_uploads"]
    import numpy as np

    assert float(m.occupancy[ws.idx]) == np.float32(ws.occupancy)


def test_sharded_device_view_per_shard_scatter_and_growth():
    """Mesh-sharded fleet arrays (sharded_device_view): per-shard
    dirty-row accounting, zero rows on fresh cycles, values in lockstep
    with the host SoA, full per-shard re-pack only on capacity growth
    (growth remaps slot->shard, so nothing cheaper is sound)."""
    import numpy as np

    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    from distributed_tpu.ops.partition import make_engine_mesh

    mesh = make_engine_mesh(layout="4x2")  # workers axis: 2 shards
    state = _state(n_workers=16, nthreads=2)
    m = state.mirror
    v = m.sharded_device_view(mesh)
    assert v is not None
    ss = m.sharded_stats()
    assert ss["n_shards"] == 2
    assert ss["full_packs"] == [1, 1]
    assert ss["rows_uploaded"] == [0, 0]
    # fresh second view: nothing moves on any shard
    m.sharded_device_view(mesh)
    assert m.sharded_stats()["rows_uploaded"] == [0, 0]
    # dirty one worker per shard half; only the owning shard scatters
    rows_per_shard = m.cap // 2
    ws_lo = next(ws for ws in state.workers.values()
                 if ws.idx < rows_per_shard)
    state._adjust_occupancy(ws_lo, 2.5)
    v = m.sharded_device_view(mesh)
    ss = m.sharded_stats()
    assert ss["rows_uploaded"] == [1, 0], ss
    assert float(np.asarray(v["occupancy"])[ws_lo.idx]) == np.float32(
        ws_lo.occupancy
    )
    ws_hi = next(ws for ws in state.workers.values()
                 if ws.idx >= rows_per_shard)
    state.set_worker_nthreads(ws_hi, 4)
    v = m.sharded_device_view(mesh)
    ss = m.sharded_stats()
    assert ss["rows_uploaded"] == [1, 1], ss
    assert int(np.asarray(v["nthreads"])[ws_hi.idx]) == 4
    # growth: capacity doubles, slot->shard remaps, shards re-pack once
    for i in range(m.cap):  # force at least one _grow
        state.add_worker_state(
            f"tcp://127.0.0.1:{20000 + i}", nthreads=1,
            memory_limit=2**30, name=f"g{i}",
        )
    v = m.sharded_device_view(mesh)
    ss = m.sharded_stats()
    assert ss["full_packs"] == [2, 2], ss
    # ...and values still match the host SoA everywhere
    for name in ("nthreads", "occupancy", "running"):
        np.testing.assert_array_equal(
            np.asarray(v[name]), getattr(m, name)
        )
    m.verify()


def test_sharded_device_view_indivisible_mesh_returns_none():
    """A mesh whose workers axis cannot divide the slot capacity gets
    the replicated fallback (None), never a crash."""
    import jax

    if len(jax.devices()) < 3:
        pytest.skip("needs >= 3 devices")
    import numpy as np
    from jax.sharding import Mesh

    state = _state(n_workers=4)
    mesh = Mesh(
        np.asarray(jax.devices()[:3]).reshape(1, 3),
        axis_names=("tasks", "workers"),
    )
    assert state.mirror.sharded_device_view(mesh) is None


def test_shared_fleet_view_feeds_steal_and_amm_without_repack():
    """One dirty flush serves a whole cycle: steal + AMM both consume
    the mirror with zero additional refreshes and zero Python packs."""
    from distributed_tpu.scheduler.amm import (
        ActiveMemoryManagerExtension,
        ReduceReplicas,
    )

    state = _state(n_workers=6, nthreads=1)
    sched = StubScheduler(state)
    stealing = WorkStealing(sched)
    amm = ActiveMemoryManagerExtension(
        sched, policies=[ReduceReplicas()], register=False, start=False
    )
    m = state.mirror
    # a few replicated memory tasks for the AMM half
    for i in range(4):
        key = f"mem-{i}"
        state.new_task(key, None).priority = (0,)
        state._transition(
            key, "memory", "seed", nbytes=1000,
            worker=list(state.workers)[0],
        )
        for ws in list(state.workers.values())[1:3]:
            state.add_replica(state.tasks[key], ws)
    m.fleet_view()
    base = m.stats()
    fv1 = m.fleet_view()
    amm.run_once()
    fv2 = m.fleet_view()
    after = m.stats()
    assert after["oracle_packs"] == 0
    assert after["rows_refreshed"] == base["rows_refreshed"]
    assert fv1.slots is fv2.slots  # membership untouched, view reused
    # the AMM round produced drop messages for the over-replicated keys
    assert any(
        msg.get("op") == "remove-replicas"
        for _, wmsgs in sched.sent
        for msgs in wmsgs.values()
        for msg in msgs
    )


# --------------------------------------- device steal comm-cost fidelity


def _steal_state(dep_on_thief: bool):
    """w0: 4 stealable 0.1 s tasks + the dep replica; w1 idle.  The dep
    is big enough that pricing the steal at full transfer cost fails the
    criterion, while the true cost to a thief already holding the dep
    passes it."""
    state = _state(n_workers=2, nthreads=1)
    sched = StubScheduler(state)
    ext = WorkStealing(sched)
    w0, w1 = state.workers.values()
    state.new_task_prefix("sl").add_duration(0.1)
    dep = state.new_task("data", None)
    dep.priority = (0,)
    state._transition("data", "memory", "seed", nbytes=40_000_000,
                      worker=w0.address)
    if dep_on_thief:
        state.add_replica(dep, w1)
    tasks = {
        f"sl-{i}": TaskSpec(_noop, (TaskRef("data"),)) for i in range(4)
    }
    state.update_graph_core(
        tasks, {k: {"data"} for k in tasks}, list(tasks),
        client="client-1",
        annotations_by_key={
            k: {"workers": [w0.address], "allow_other_workers": True}
            for k in tasks
        },
        stimulus_id="graph-steal",
    )
    assert all(
        state.tasks[k].processing_on is w0 for k in tasks
    ), {k: state.tasks[k].state for k in tasks}
    return state, sched, ext, w0, w1


def test_device_steal_accounts_thief_resident_bytes():
    """Regression (over-estimate wrongly rejected a profitable steal):
    the idle thief already holds the 40 MB dependency, so the move is
    nearly free for it — the old full-cost estimate priced it at 0.5 s
    and refused."""
    state, sched, ext, w0, w1 = _steal_state(dep_on_thief=True)
    idle = [ws for ws in state.idle.values() if ws in state.running]
    assert w1 in idle
    ext._balance_device(idle)  # no loop -> plans inline
    thieves = {info.thief for info in ext.in_flight.values()}
    assert thieves == {w1}, (ext.in_flight, sched.sent)
    assert state.mirror.oracle_packs == 0


def test_device_steal_still_rejects_when_no_thief_holds_data():
    """Control: same shape, dep only on the victim — every thief truly
    pays the full transfer, so the criterion correctly refuses."""
    state, sched, ext, w0, w1 = _steal_state(dep_on_thief=False)
    idle = [ws for ws in state.idle.values() if ws in state.running]
    ext._balance_device(idle)
    assert not ext.in_flight, ext.in_flight


def test_device_steal_drains_paused_victim():
    """A paused worker keeps its pile, and the pause handler re-marks
    its homed tasks stealable precisely so the balancer drains them:
    the device victim selection must include non-running workers (it
    briefly filtered on the mirror's running bit and orphaned them)."""
    state, sched, ext, w0, w1 = _steal_state(dep_on_thief=True)
    _flip_status(state, w0, "paused")
    # not via the saturated shortcut — force the array-mask victim scan
    state.saturated.discard(w0)
    state.mirror.mark(w0)
    idle = [ws for ws in state.idle.values() if ws in state.running]
    assert w1 in idle and w0 not in state.running
    ext._balance_device(idle)
    assert {info.thief for info in ext.in_flight.values()} == {w1}, (
        ext.in_flight
    )


def test_device_steal_mirror_and_oracle_paths_agree():
    """The no-mirror from-scratch pack (the oracle path) plans the same
    moves as the mirror-fed pack on identical fleets."""
    results = []
    for use_mirror in (True, False):
        state = _state(n_workers=2, nthreads=1, mirror=use_mirror)
        sched = StubScheduler(state)
        ext = WorkStealing(sched)
        w0, w1 = state.workers.values()
        state.new_task_prefix("sl").add_duration(0.1)
        dep = state.new_task("data", None)
        dep.priority = (0,)
        state._transition("data", "memory", "seed", nbytes=40_000_000,
                          worker=w0.address)
        state.add_replica(dep, w1)
        tasks = {
            f"sl-{i}": TaskSpec(_noop, (TaskRef("data"),))
            for i in range(4)
        }
        state.update_graph_core(
            tasks, {k: {"data"} for k in tasks}, list(tasks),
            client="client-1",
            annotations_by_key={
                k: {"workers": [w0.address], "allow_other_workers": True}
                for k in tasks
            },
            stimulus_id="graph-steal",
        )
        idle = [ws for ws in state.idle.values() if ws in state.running]
        ext._balance_device(idle)
        results.append(
            sorted(
                (key, info.thief.name) for key, info in ext.in_flight.items()
            )
        )
    assert results[0] == results[1], results
