"""Scheduler durability (distributed_tpu/scheduler/durability.py;
docs/durability.md): snapshot/restore round trips, the journal
head-eviction regression, typed rejection of corrupt/mismatched
images, torn-write tolerance, worker re-registration idempotence,
restart-during-in-flight-steal reconciliation, and the deterministic
scheduler-bounce chaos proof across both transition engines."""

from __future__ import annotations

import asyncio
import json
import os

import pytest

from distributed_tpu import config
from distributed_tpu.diagnostics.flight_recorder import (
    replay_stimulus_trace,
    verify_journal,
)
from distributed_tpu.graph.spec import TaskSpec
from distributed_tpu.scheduler.durability import (
    DurabilityManager,
    FileSink,
    JournalCorruptError,
    MemorySink,
    SnapshotCorruptError,
    SnapshotVersionError,
    decode_run_spec,
    encode_run_spec,
    reconcile_worker,
    restore_state,
    restore_stealing,
    state_digest,
)
from distributed_tpu.scheduler.state import SchedulerState
from distributed_tpu.scheduler.stealing import WorkStealing
from distributed_tpu.utils.test import StubScheduler

from utils_cluster import gen_cluster

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _inc(x):
    return x + 1


def load_model() -> dict:
    out = {}
    for role in ("scheduler", "worker"):
        path = os.path.join(
            REPO_ROOT, "docs", "state_machine", f"{role}.json"
        )
        with open(path) as f:
            out[role] = json.load(f)
    return out


def _flood_state(n_workers=8, n_tasks=200, **overrides):
    with config.set({"scheduler.jax.enabled": False, **overrides}):
        state = SchedulerState(validate=True)
        for i in range(n_workers):
            state.add_worker_state(
                f"tcp://dur:{i}", nthreads=2, memory_limit=2**30,
                name=f"d{i}",
            )
        tasks = {f"dur-{i}": TaskSpec(_inc, (i,)) for i in range(n_tasks)}
        state.update_graph_core(
            tasks, {k: set() for k in tasks}, list(tasks),
            client="dur-client", stimulus_id="dur-graph",
        )
    return state


def _run_flood(state, mgr=None, cadence=0) -> int:
    rounds = 0
    while True:
        batch = [
            (ts.key, ws.address, f"dur-fin-{ts.key}", {"nbytes": 8})
            for ws in state.workers.values()
            for ts in list(ws.processing)
        ]
        if not batch:
            return rounds
        state.stimulus_tasks_finished_batch(batch)
        rounds += 1
        if mgr is not None and cadence and rounds % cadence == 0:
            mgr.snapshot()
        assert rounds < 10_000, "flood did not converge"


# ----------------------------------------------------------- round trips


def test_snapshot_restore_roundtrip_with_deltas():
    """Base + delta snapshots + journal tail fold back into a state
    whose structural digest matches the original bit-exactly."""
    state = _flood_state()
    mgr = DurabilityManager(
        state, MemorySink(), full_every=10**6, state_digests=True
    )
    mgr.attach()
    _run_flood(state, mgr, cadence=3)
    mgr.flush_journal()
    assert mgr.stats.epochs >= 2, "flood produced no delta epochs"

    fresh = SchedulerState(validate=True)
    info = DurabilityManager.restore_into(fresh, mgr.sink)
    assert state_digest(fresh) == state_digest(state)
    assert info["deltas"] >= 1
    assert info["torn_records"] == 0
    # interest survived: the client's keys are still wanted, so a
    # restored scheduler will not GC completed work
    cs = fresh.clients.get("dur-client")
    assert cs is not None and len(cs.wants_what) == 200


def test_journal_eviction_race_regression():
    """The head-truncation durability gap: with a tiny journal deque a
    long flood evicts its head, so the in-memory journal alone FAILS
    verification — but the sink capture (armed atomically with the
    base snapshot at attach) stays complete and restores exactly."""
    state = _flood_state(**{"scheduler.trace.journal-size": 8})
    assert state.trace.journal.maxlen == 8
    mgr = DurabilityManager(
        state, MemorySink(), full_every=10**6, state_digests=True
    )
    mgr.attach()
    _run_flood(state, mgr, cadence=5)
    mgr.flush_journal()
    assert mgr.stats.journal_records > 8
    # the deque lost its head: a capture that relied on it would replay
    # from a hole.  verify_journal is the detector...
    with pytest.raises(ValueError, match="complete capture"):
        verify_journal(list(state.trace.journal))
    # ...and the segment writer is the fix: restore is digest-exact
    fresh = SchedulerState(validate=True)
    DurabilityManager.restore_into(fresh, mgr.sink)
    assert state_digest(fresh) == state_digest(state)


def test_run_spec_codec_roundtrip():
    from distributed_tpu.protocol.serialize import Serialized

    spec = Serialized({"kind": "task"}, [b"frame-a", b"frame-b"])
    out = decode_run_spec(encode_run_spec(spec))
    assert isinstance(out, Serialized)
    assert out.header == {"kind": "task"}
    assert out.frames == [b"frame-a", b"frame-b"]
    # non-picklable degrades to a schedulable opaque marker
    opaque = decode_run_spec(encode_run_spec(lambda x: x))
    assert opaque  # truthy: the scheduler still schedules the task
    assert decode_run_spec(encode_run_spec(None)) is None
    assert decode_run_spec(encode_run_spec(7)) == 7


# ------------------------------------------------------ typed rejection


def _captured_sink() -> tuple:
    # floods journal ONE tasks-finished-batch record per engine batch:
    # enough tasks for a multi-record TAIL segment (the torn/gap tests
    # corrupt mid-span, so every record must be past the watermark —
    # no mid-flood snapshots)
    state = _flood_state(n_tasks=200)
    mgr = DurabilityManager(
        state, MemorySink(), full_every=10**6, state_digests=True
    )
    mgr.attach()
    _run_flood(state, mgr)
    mgr.flush_journal()
    return state, mgr.sink


def test_snapshot_version_mismatch_rejected():
    _state, sink = _captured_sink()
    blob = sink.snapshots[0]
    outer = json.loads(blob)
    outer["body"]["v"] = 999
    # re-stamp the digest so ONLY the version mismatches
    import hashlib

    check = json.dumps(
        outer["body"], default=repr, sort_keys=True,
        separators=(",", ":"),
    ).encode()
    outer["d"] = hashlib.blake2b(check, digest_size=16).hexdigest()
    sink.snapshots[0] = json.dumps(outer).encode()
    fresh = SchedulerState(validate=True)
    with pytest.raises(SnapshotVersionError, match="schema v999"):
        DurabilityManager.restore_into(fresh, sink)


def test_snapshot_digest_corruption_rejected():
    _state, sink = _captured_sink()
    blob = sink.snapshots[0]
    outer = json.loads(blob)
    outer["body"]["journal_seq"] = 12345  # bit rot, digest not re-stamped
    sink.snapshots[0] = json.dumps(outer).encode()
    fresh = SchedulerState(validate=True)
    with pytest.raises(SnapshotCorruptError, match="digest"):
        DurabilityManager.restore_into(fresh, sink)


def test_snapshot_unparseable_rejected():
    _state, sink = _captured_sink()
    sink.snapshots[0] = b"\x00not json"
    with pytest.raises(SnapshotCorruptError, match="parse"):
        DurabilityManager.restore_into(SchedulerState(validate=True), sink)


def test_torn_final_record_tolerated(tmp_path):
    """A crash mid-append leaves a torn FINAL line in the FINAL
    segment: that record was never durable — dropped and counted, and
    the restore still lands on the last durable prefix."""
    state, mem = _captured_sink()
    sink = FileSink(str(tmp_path))
    for e in mem.snapshot_epochs():
        sink.write_snapshot(e, mem.read_snapshot(e))
    for e in mem.journal_epochs():
        with open(sink._journal_path(e), "wb") as f:
            f.write(mem.read_journal(e))
    last = max(sink.journal_epochs())
    path = sink._journal_path(last)
    blob = open(path, "rb").read()
    if not blob.strip():
        pytest.skip("flood left an empty final segment")
    torn = blob.rstrip(b"\n")
    torn = torn[: len(torn) - len(torn.rsplit(b"\n", 1)[-1]) // 2 - 1]
    with open(path, "wb") as f:
        f.write(torn)
    fresh = SchedulerState(validate=True)
    info = DurabilityManager.restore_into(fresh, sink)
    assert info["torn_records"] == 1


def test_torn_middle_record_rejected(tmp_path):
    state, mem = _captured_sink()
    sink = FileSink(str(tmp_path))
    for e in mem.snapshot_epochs():
        sink.write_snapshot(e, mem.read_snapshot(e))
    for e in mem.journal_epochs():
        with open(sink._journal_path(e), "wb") as f:
            f.write(mem.read_journal(e))
    seg = next(
        e for e in sink.journal_epochs()
        if len(sink.read_journal(e).splitlines()) >= 3
    )
    path = sink._journal_path(seg)
    lines = open(path, "rb").read().splitlines()
    lines[1] = lines[1][: len(lines[1]) // 2]  # torn MID-segment
    with open(path, "wb") as f:
        f.write(b"\n".join(lines) + b"\n")
    with pytest.raises(JournalCorruptError, match="refusing to replay"):
        DurabilityManager.restore_into(SchedulerState(validate=True), sink)


def test_torn_penultimate_line_no_trailing_newline_rejected(tmp_path):
    """The torn-write allowance is exactly the LAST non-empty line.  A
    segment without a trailing newline whose PENULTIMATE line is
    corrupt must raise — not count the corruption as the crash artifact
    and silently drop the valid final record."""
    state, mem = _captured_sink()
    sink = FileSink(str(tmp_path))
    for e in mem.snapshot_epochs():
        sink.write_snapshot(e, mem.read_snapshot(e))
    for e in mem.journal_epochs():
        with open(sink._journal_path(e), "wb") as f:
            f.write(mem.read_journal(e))
    seg = max(sink.journal_epochs())
    lines = [
        ln for ln in sink.read_journal(seg).splitlines() if ln.strip()
    ]
    if len(lines) < 3:
        pytest.skip("flood left too few records in the final segment")
    lines[-2] = lines[-2][: len(lines[-2]) // 2]  # corrupt penultimate
    with open(sink._journal_path(seg), "wb") as f:
        f.write(b"\n".join(lines))  # NO trailing newline
    with pytest.raises(JournalCorruptError, match="refusing to replay"):
        DurabilityManager.restore_into(SchedulerState(validate=True), sink)


def test_reconcile_empty_held_keys_strips_stale_replicas():
    """A worker that re-registers holding NOTHING still reconciles: a
    restored who_has full of replicas it no longer has must be stripped
    through the engine (the server gate is `held_keys is not None`, not
    truthiness — an empty list is a meaningful report)."""
    state = _flood_state(n_workers=2, n_tasks=8)
    _run_flood(state)
    addr = next(iter(state.workers))
    ws = state.workers[addr]
    stale = [ts.key for ts in ws.has_what]
    assert stale, "flood left this worker no replicas to strip"
    _msgs, counts = reconcile_worker(state, addr, [], "reconcile-empty")
    assert counts["stripped"] == len(stale)
    assert not ws.has_what


def test_native_delta_snapshot_marks_workers_dirty():
    """Native tape appliers mutate ws.processing/has_what inline; they
    must mark the WORKER dirty too, or a delta snapshot taken after a
    native flood carries stale order lists and the restore fails its
    state-digest check (a quiescing workload whose last flood only
    completed tasks)."""
    from distributed_tpu import native

    if native.load() is None:
        pytest.skip("native toolchain unavailable")
    with config.set({"scheduler.jax.enabled": False,
                     "scheduler.work-stealing": False}):
        state = SchedulerState(validate=False)
        if not state.attach_native(build=True):
            pytest.skip("native engine did not attach")
        addrs = []
        for i in range(4):
            state.add_worker_state(
                f"tcp://nat:{i}", nthreads=2, memory_limit=2**30,
                name=f"n{i}",
            )
            addrs.append(f"tcp://nat:{i}")
        # scattered roots + a fanin layer: non-rootish tasks stay on the
        # compiled placement arm instead of escaping to the oracle
        roots = []
        for i in range(8):
            k = f"natroot-{i}"
            state.client_desires_keys([k], "nat-client")
            recs, cm, wm = state._transition(
                k, "memory", "nat-scatter", nbytes=65536,
                worker=addrs[i % 4],
            )
            state._transitions(recs, cm, wm, "nat-scatter")
            roots.append(k)
        tasks = {f"nat-{i}": TaskSpec(_inc, (i,)) for i in range(40)}
        deps = {k: {roots[i % 8]} for i, k in enumerate(tasks)}
        state.update_graph_core(
            tasks, deps, list(tasks), client="nat-client",
            priorities={k: (i,) for i, k in enumerate(tasks)},
            stimulus_id="nat-graph",
        )
        mgr = DurabilityManager(
            state, MemorySink(), full_every=10**6, state_digests=True
        )
        mgr.attach()
        # complete every processing task in REVERSED order so the
        # per-worker mirror orders change relative to the base
        # snapshot, then snapshot the quiesced state — a delta whose
        # only mutations came through the native tape appliers
        while True:
            batch = [
                (ts.key, ws.address, f"nat-fin-{ts.key}", {"nbytes": 8})
                for ws in state.workers.values()
                for ts in reversed(list(ws.processing))
            ]
            if not batch:
                break
            state.stimulus_tasks_finished_batch(batch)
        assert state.native is not None and (
            state.native.counters()["transitions"] > 40
        ), f"flood did not run natively: {state.native.counters()}"
        mgr.snapshot()
        mgr.flush_journal()
        fresh = SchedulerState(validate=False)
        DurabilityManager.restore_into(fresh, mgr.sink)
        assert state_digest(fresh) == state_digest(state)


def test_delta_snapshot_while_native_flood_is_deferred():
    """Deferred materialization meets durability: a delta snapshot
    taken while the last purely-native flood is still parked (no read
    has hydrated its rows) must force the replay from inside
    ``DurabilityTracker.drain`` — its dirty marks only exist after the
    tape appliers run — or the delta captures an empty dirty set and
    the restore's state digest diverges."""
    from distributed_tpu import native

    if native.load() is None:
        pytest.skip("native toolchain unavailable")
    with config.set({"scheduler.jax.enabled": False,
                     "scheduler.work-stealing": False,
                     "scheduler.native-engine.min-flood": 0}):
        state = SchedulerState(validate=False)
        if not state.attach_native(build=True):
            pytest.skip("native engine did not attach")
        addrs = []
        for i in range(4):
            state.add_worker_state(
                f"tcp://defer:{i}", nthreads=2, memory_limit=2**30,
                name=f"d{i}",
            )
            addrs.append(f"tcp://defer:{i}")
        roots = []
        for i in range(8):
            k = f"defroot-{i}"
            state.client_desires_keys([k], "def-client")
            recs, cm, wm = state._transition(
                k, "memory", "def-scatter", nbytes=65536,
                worker=addrs[i % 4],
            )
            state._transitions(recs, cm, wm, "def-scatter")
            roots.append(k)
        tasks = {f"def-{i}": TaskSpec(_inc, (i,)) for i in range(40)}
        deps = {k: {roots[i % 8]} for i, k in enumerate(tasks)}
        state.update_graph_core(
            tasks, deps, list(tasks), client="def-client",
            priorities={k: (i,) for i, k in enumerate(tasks)},
            stimulus_id="def-graph",
        )
        mgr = DurabilityManager(
            state, MemorySink(), full_every=10**6, state_digests=True
        )
        mgr.attach()
        ne = state.native
        # one purely-native flood, nothing reading python truth after:
        # the segments stay parked with their rows un-hydrated
        batch = [
            (ts.key, ws.address, f"def-fin-{ts.key}", {"nbytes": 8})
            for ws in state.workers.values()
            for ts in list(ws.processing)
        ]
        assert batch
        state.stimulus_tasks_finished_batch(batch)
        assert ne._pending, "flood did not defer (premise)"
        mgr.snapshot()  # delta over un-hydrated rows: drain must sync
        assert not ne._pending, "drain() did not materialize first"
        # finish the workload and round-trip the full image
        while True:
            batch = [
                (ts.key, ws.address, f"def-fin2-{ts.key}", {"nbytes": 8})
                for ws in state.workers.values()
                for ts in list(ws.processing)
            ]
            if not batch:
                break
            state.stimulus_tasks_finished_batch(batch)
        mgr.snapshot()
        mgr.flush_journal()
        assert ne.counters()["transitions"] > 0
        fresh = SchedulerState(validate=False)
        DurabilityManager.restore_into(fresh, mgr.sink)
        assert state_digest(fresh) == state_digest(state)


def test_snapshot_epoch_gap_rejected():
    """A delta snapshot lost to a swallowed off-loop sink write (the
    live threaded sink logs-and-drops failures) must fail the load
    loudly: folding around the hole would silently drop every row that
    was dirty only in the missing epoch's window."""
    state = _flood_state()
    mgr = DurabilityManager(
        state, MemorySink(), full_every=10**6, state_digests=True
    )
    mgr.attach()
    _run_flood(state, mgr, cadence=2)
    mgr.flush_journal()
    assert mgr.stats.epochs >= 4, "flood produced too few delta epochs"
    missing = mgr.sink.snapshot_epochs()[2]
    del mgr.sink.snapshots[missing]
    with pytest.raises(SnapshotCorruptError, match="epoch gap"):
        DurabilityManager.load(mgr.sink)


def test_journal_seq_gap_rejected():
    _state, sink = _captured_sink()
    seg = next(
        e for e in sink.journal_epochs()
        if len(sink.read_journal(e).splitlines()) >= 3
    )
    lines = sink.read_journal(seg).splitlines()
    del lines[1]  # a record vanished mid-span
    sink.journals[seg] = bytearray(b"\n".join(lines) + b"\n")
    with pytest.raises(JournalCorruptError, match="contiguity"):
        DurabilityManager.restore_into(SchedulerState(validate=True), sink)


def test_journal_payload_digest_rejected():
    _state, sink = _captured_sink()
    seg = sink.journal_epochs()[0]
    lines = sink.read_journal(seg).splitlines()
    rec = json.loads(lines[0])
    rec["payload"] = {"forged": True}
    lines[0] = json.dumps(rec).encode()
    sink.journals[seg] = bytearray(b"\n".join(lines) + b"\n")
    with pytest.raises(JournalCorruptError, match="payload digest"):
        DurabilityManager.restore_into(SchedulerState(validate=True), sink)


# ----------------------------------------------- worker re-registration


@gen_cluster(client=True)
async def test_reregistration_idempotent(c, s, a, b):
    """A register-worker retry (same server_id) after the reply was
    lost must not double-count replicas, occupancy, or worker rows —
    the scheduler reuses the state row and only replaces the stream."""
    from distributed_tpu.comm.core import connect

    futs = c.map(_inc, range(6))
    await c.gather(futs)
    ws = s.state.workers[a.address]
    occ0 = ws.occupancy
    nbytes0 = ws.nbytes
    has0 = [ts.key for ts in ws.has_what]
    n_workers0 = len(s.state.workers)
    held = [[ts.key, ts.nbytes or 0] for ts in ws.has_what]

    comm = await connect(s.address, **s.connection_args)
    await comm.write({
        "op": "register-worker", "address": a.address,
        "nthreads": a.nthreads, "name": a.name,
        "memory_limit": a.memory_limit, "resources": {},
        "server_id": a.id, "held_keys": held, "reply": False,
    })
    resp = await comm.read()
    assert resp["status"] == "OK"
    assert s.state.workers[a.address] is ws, "state row was rebuilt"
    assert len(s.state.workers) == n_workers0
    assert ws.occupancy == occ0
    assert ws.nbytes == nbytes0, "replicas were double-counted"
    assert [ts.key for ts in ws.has_what] == has0
    # a DIFFERENT process claiming the address while the stream lives
    # is still rejected (no silent takeover)
    comm2 = await connect(s.address, **s.connection_args)
    await comm2.write({
        "op": "register-worker", "address": a.address,
        "nthreads": 1, "name": "imposter", "memory_limit": 0,
        "resources": {}, "server_id": "not-the-same-worker",
        "reply": False,
    })
    resp2 = await comm2.read()
    assert resp2["status"] == "error"
    await comm2.close()
    await comm.close()


def test_reconcile_worker_idempotent_and_corrective():
    """held_keys reconciliation routes every correction through the
    engine and converges: a second identical pass finds nothing."""
    state = _flood_state(n_tasks=20)
    _run_flood(state)
    ws = next(iter(state.workers.values()))
    held = [[ts.key, ts.nbytes or 0] for ts in ws.has_what]
    assert held
    # strip one replica behind the scheduler's back (the worker still
    # reports it) and forge one stale scheduler-side replica (the
    # worker lost it)
    missing_key = held[0][0]
    ts_missing = state.tasks[missing_key]
    state.remove_replica(ts_missing, ws)
    stale = next(
        ts for ts in ws.has_what if ts.key != missing_key
    )
    reported = [
        [k, nb] for k, nb in held if k != stale.key
    ] + [["totally-unknown-key", 5]]

    (cm, wm), counts = reconcile_worker(
        state, ws.address, reported, "reconcile-1"
    )
    assert counts["added"] == 1
    assert counts["stripped"] == 1
    assert counts["unknown"] == 1
    assert ws in ts_missing.who_has
    assert stale not in ws.has_what
    # idempotence: the same report again corrects nothing
    (_cm2, _wm2), counts2 = reconcile_worker(
        state, ws.address, reported, "reconcile-2"
    )
    assert counts2["added"] == 0
    assert counts2["stripped"] == 0


# ------------------------------------------- restart during in-flight steal


def _steal_setup():
    with config.set({
        "scheduler.jax.enabled": False,
        "scheduler.work-stealing": False,  # no periodic cb registration
    }):
        state = SchedulerState(validate=True)
        sched = StubScheduler(state)
        for i in range(2):
            state.add_worker_state(
                f"tcp://steal:{i}", nthreads=1, memory_limit=2**30,
                name=f"s{i}",
            )
        # a duration prior so steal pricing has something to read
        state.new_task_prefix("stl").add_duration(0.1)
        tasks = {f"stl-{i}": TaskSpec(_inc, (i,)) for i in range(4)}
        state.update_graph_core(
            tasks, {k: set() for k in tasks}, list(tasks),
            client="steal-client", stimulus_id="steal-graph",
        )
        steal = WorkStealing(sched)
        state.extensions["stealing"] = steal
    return state, sched, steal


def _restore_with_stealing(sink):
    with config.set({
        "scheduler.jax.enabled": False,
        "scheduler.work-stealing": False,
    }):
        state2 = SchedulerState(validate=True)
        sched2 = StubScheduler(state2)
        folded, tail, info = DurabilityManager.load(sink)
        restore_state(state2, folded)
        want = info.get("state_digest")
        if want:
            assert state_digest(state2) == want
        steal2 = WorkStealing(sched2)
        state2.extensions["stealing"] = steal2
        restore_stealing(steal2, folded.get("ext") or None)
        replay_stimulus_trace(state2, tail, verify_digests=False)
    return state2, steal2, info


def test_restart_during_in_flight_steal_confirm_in_tail():
    """A steal requested before the snapshot and CONFIRMED after it
    (but before the crash) reconciles from the journal tail: the
    restored task runs on the thief, the confirm window is closed with
    its occupancy overlays reverted, and no ledger row leaks open."""
    state, sched, steal = _steal_setup()
    mgr = DurabilityManager(
        state, MemorySink(), full_every=10**6, state_digests=True
    )
    mgr.attach()
    ts = next(
        t for t in state.tasks.values() if t.state == "processing"
    )
    victim = ts.processing_on
    thief = next(
        w for w in state.workers.values() if w is not victim
    )
    steal.move_task_request(ts, victim, thief)
    stim = steal.in_flight[ts.key].stimulus_id
    mgr.snapshot()  # the open confirm window is snapshot truth
    asyncio.run(steal.move_task_confirm(
        key=ts.key, state="ready", stimulus_id=stim
    ))
    assert ts.processing_on is thief
    mgr.flush_journal()

    state2, steal2, info = _restore_with_stealing(mgr.sink)
    assert info["tail_records"] >= 2  # steal-confirm + steal-move
    ts2 = state2.tasks[ts.key]
    assert ts2.processing_on is not None
    assert ts2.processing_on.address == thief.address
    assert ts.key not in steal2.in_flight, "confirm window leaked open"
    assert not steal2.in_flight_occupancy, "occupancy overlays leaked"
    assert state_digest(state2) == state_digest(state)
    # drive every task to memory on both states: the replayed steal's
    # ledger row must JOIN (superseding the request row), not age out
    for st in (state, state2):
        _run_flood(st)
        assert st.ledger.open_rows == 0, "ledger row leaked open"
    assert state_digest(state2) == state_digest(state)


def test_restart_before_steal_confirm():
    """Crash with the confirm window still open: the snapshot carries
    the in_flight entry, and the victim's answer arriving AFTER the
    restart finds it and completes the move."""
    state, sched, steal = _steal_setup()
    mgr = DurabilityManager(
        state, MemorySink(), full_every=10**6, state_digests=True
    )
    mgr.attach()
    ts = next(
        t for t in state.tasks.values() if t.state == "processing"
    )
    victim = ts.processing_on
    thief = next(w for w in state.workers.values() if w is not victim)
    steal.move_task_request(ts, victim, thief)
    stim = steal.in_flight[ts.key].stimulus_id
    mgr.snapshot()
    mgr.flush_journal()

    state2, steal2, _info = _restore_with_stealing(mgr.sink)
    assert ts.key in steal2.in_flight
    assert steal2.in_flight[ts.key].stimulus_id == stim
    asyncio.run(steal2.move_task_confirm(
        key=ts.key, state="ready", stimulus_id=stim
    ))
    ts2 = state2.tasks[ts.key]
    assert ts2.processing_on is not None
    assert ts2.processing_on.address == thief.address
    _run_flood(state2)
    assert state2.ledger.open_rows == 0


# ------------------------------------------------------ the chaos proof


def test_scenario_scheduler_bounce_oracle():
    from distributed_tpu.sim.chaos import scenario_scheduler_bounce

    model = load_model()
    sim, rep = scenario_scheduler_bounce(model=model)
    assert rep["counters"]["scheduler_bounces"] == 1
    assert rep["bounce_tail_records"] > 0
    assert rep["keys_lost"] == 0
    assert rep["keys_done"] >= rep["keys_wanted"]
    # deterministic: the same scenario digests identically
    _sim2, rep2 = scenario_scheduler_bounce(model=model)
    assert rep["digest"] == rep2["digest"]


def test_scenario_scheduler_bounce_native():
    from distributed_tpu import native
    from distributed_tpu.sim.chaos import scenario_scheduler_bounce

    if native.load() is None:
        pytest.skip("native toolchain unavailable")
    model = load_model()
    sim, rep = scenario_scheduler_bounce(model=model, native=True)
    assert sim.state.native is not None, "native engine never attached"
    assert rep["counters"]["scheduler_bounces"] == 1
    assert rep["keys_lost"] == 0
    assert rep["keys_done"] >= rep["keys_wanted"]


# The PYTHONHASHSEED sweep of the bounce proof lives with the rest of
# the hashseed harness: tests/test_determinism.py::
# test_bounce_scenario_across_hashseeds (seeds 6/8 caught the original
# plain-set ``stealable``/``saturated`` divergence).
