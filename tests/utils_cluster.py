"""Deterministic race-test harness (reference utils_test.py:865,2202-2340).

``gen_cluster`` starts Scheduler + N Workers (+ Client) in one event loop
with ``validate=True`` everywhere, parametrized over comm transports, and
tears everything down even on failure.  The ``Blocked*`` worker classes
pause a worker at a chosen point in the data plane so tests can interleave
events deterministically — the technique the reference uses to pin down
cancelled/resumed transitions, steal-confirm races, and mid-transfer
worker deaths.
"""

from __future__ import annotations

import asyncio
import functools
import os
import threading
import time as _time
from typing import Any

import pytest

from distributed_tpu import config
from distributed_tpu.client.client import Client
from distributed_tpu.scheduler.server import Scheduler
from distributed_tpu.worker.server import Worker

# thread-name prefixes a finished cluster must not leave behind
_OWNED_THREAD_PREFIXES = ("dtpu-worker-exec",)


def _fd_count() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:  # pragma: no cover - non-linux
        return 0


def _owned_threads() -> set[int]:
    return {
        t.ident
        for t in threading.enumerate()
        if t.ident is not None
        and any(t.name.startswith(p) for p in _OWNED_THREAD_PREFIXES)
    }


async def assert_no_cluster_leaks(fds_before: int,
                                  threads_before: set[int] | None = None,
                                  fd_slack: int = 8) -> None:
    """Post-teardown leak oracle (the role of reference
    pytest_resourceleaks.py): executor threads gone, no stray asyncio
    tasks beyond the current one, fd count back to ~baseline.  Retries
    with a grace window — closes are asynchronous.  Only threads CREATED
    since ``threads_before`` count: an earlier opted-out test may have
    parked an unkillable blocked body in its executor."""
    threads_before = threads_before or set()
    deadline = _time.monotonic() + 5.0
    current = asyncio.current_task()
    while True:
        import gc

        threads = [
            t.name
            for t in threading.enumerate()
            if t.ident is not None and t.ident not in threads_before
            and any(t.name.startswith(p) for p in _OWNED_THREAD_PREFIXES)
        ]
        tasks = [
            t for t in asyncio.all_tasks()
            if t is not current and not t.done()
        ]
        gc.collect()
        fds = _fd_count()
        ok = not threads and not tasks and fds <= fds_before + fd_slack
        if ok:
            return
        if _time.monotonic() > deadline:
            assert not threads, f"leaked executor threads: {threads}"
            assert not tasks, f"leaked asyncio tasks: {tasks[:5]}"
            assert fds <= fds_before + fd_slack, (
                f"leaked fds: {fds} now vs {fds_before} before"
            )
        await asyncio.sleep(0.05)


def gen_cluster(
    nthreads: list[int] | None = None,
    client: bool = True,
    timeout: float = 120,
    worker_cls: Any = None,
    scheduler_kwargs: dict | None = None,
    worker_kwargs: dict | None = None,
    config_overrides: dict | None = None,
    transports: tuple[str, ...] = ("inproc",),
    leak_check: bool = True,
):
    """Decorator: run ``fn(c, s, *workers)`` (or ``fn(s, *workers)`` with
    ``client=False``) on a fresh cluster per listed transport."""
    nthreads = nthreads if nthreads is not None else [1, 1]
    classes = (
        worker_cls
        if isinstance(worker_cls, (list, tuple))
        else [worker_cls] * len(nthreads)
    )

    def decorator(fn):
        @pytest.mark.parametrize("transport", list(transports))
        def wrapper(transport):
            async def run():
                fds_before = _fd_count()
                threads_before = _owned_threads()
                overrides = {
                    "scheduler.jax.enabled": False,
                    **(config_overrides or {}),
                }
                with config.set(overrides):
                    listen = (
                        "inproc://" if transport == "inproc"
                        else "tcp://127.0.0.1:0"
                    )
                    s = Scheduler(
                        listen_addr=listen, validate=True,
                        **(scheduler_kwargs or {}),
                    )
                    await s.start()
                    workers = []
                    try:
                        for i, nt in enumerate(nthreads):
                            cls = classes[i] or Worker
                            w = cls(
                                s.address, name=f"w{i}", nthreads=nt,
                                validate=True, listen_addr=listen,
                                **(worker_kwargs or {}),
                            )
                            await w.start()
                            workers.append(w)
                        if client:
                            async with Client(s.address) as c:
                                await asyncio.wait_for(
                                    fn(c, s, *workers), timeout
                                )
                        else:
                            await asyncio.wait_for(fn(s, *workers), timeout)
                    finally:
                        for w in workers:
                            try:
                                await w.close(report=False)
                            except Exception:
                                pass
                        await s.close()
                # leak oracle ON BY DEFAULT for every gen_cluster test;
                # leak_check=False is for tests that deliberately park
                # blocked user code in executor threads (python offers
                # no way to kill a thread, so those outlive the cluster)
                if leak_check:
                    await assert_no_cluster_leaks(fds_before, threads_before)

            asyncio.run(run())

        wrapper.__name__ = fn.__name__
        wrapper.__doc__ = fn.__doc__
        wrapper.__module__ = fn.__module__
        return wrapper

    return decorator


class BlockedGatherDep(Worker):
    """Sets ``in_gather_dep`` on first entering the gather path and then
    holds the fetch until the test sets ``block_gather_dep`` — tasks stay
    in flight indefinitely (reference utils_test.py:2202)."""

    def __init__(self, *args: Any, **kwargs: Any):
        self.in_gather_dep = asyncio.Event()
        self.block_gather_dep = asyncio.Event()
        super().__init__(*args, **kwargs)

    async def _gather_dep(self, worker, to_gather, total_nbytes, stimulus_id):
        self.in_gather_dep.set()
        await self.block_gather_dep.wait()
        return await super()._gather_dep(
            worker, to_gather, total_nbytes, stimulus_id
        )


class BlockedGetData(Worker):
    """Sets ``in_get_data`` when a peer asks for data and withholds the
    answer until the test sets ``block_get_data`` (reference
    utils_test.py:2238)."""

    def __init__(self, *args: Any, **kwargs: Any):
        self.in_get_data = asyncio.Event()
        self.block_get_data = asyncio.Event()
        super().__init__(*args, **kwargs)

    async def get_data(self, comm, keys=(), who=None, **kwargs):
        self.in_get_data.set()
        await self.block_get_data.wait()
        return await super().get_data(comm, keys=keys, who=who, **kwargs)


class BlockedExecute(Worker):
    """Sets ``in_execute`` on first entering execution and blocks until
    the test sets ``block_execute``; then blocks once more between the
    task body finishing and its completion event being processed
    (``in_execute_exit`` / ``block_execute_exit``, reference
    utils_test.py:2260)."""

    def __init__(self, *args: Any, **kwargs: Any):
        self.in_execute = asyncio.Event()
        self.block_execute = asyncio.Event()
        self.in_execute_exit = asyncio.Event()
        self.block_execute_exit = asyncio.Event()
        super().__init__(*args, **kwargs)

    async def _execute(self, key, stimulus_id):
        self.in_execute.set()
        await self.block_execute.wait()
        try:
            return await super()._execute(key, stimulus_id)
        finally:
            self.in_execute_exit.set()
            await self.block_execute_exit.wait()


async def wait_for(predicate, timeout: float = 30, interval: float = 0.01):
    """Poll ``predicate()`` until truthy (reference utils_test.py
    async_poll_for)."""
    deadline = asyncio.get_running_loop().time() + timeout
    while not predicate():
        if asyncio.get_running_loop().time() > deadline:
            raise TimeoutError("condition never became true")
        await asyncio.sleep(interval)


def inc(x):
    return x + 1


def add(x, y):
    return x + y


def slowinc(x, delay=0.1):
    import time

    time.sleep(delay)
    return x + 1
