"""ICI-class device data plane (ops/ici.py): mesh all-to-all shuffle and
ring exchange on the virtual 8-device CPU mesh.  The point under test:
shard bytes move device-to-device inside one jitted program — no comm
layer, no msgpack, no host round-trip (the role of reference
comm/ucx.py:211)."""

from __future__ import annotations

import numpy as np
import pytest

import jax

from distributed_tpu.ops.ici import (
    _mix32,
    compact_shuffle_output,
    make_mesh_1d,
    ring_exchange,
    shuffle_on_mesh,
)

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


@needs_mesh
def test_shuffle_on_mesh_routes_and_preserves_rows():
    mesh = make_mesh_1d(8)
    N = 8 * 64
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 30, N).astype(np.int32)
    vals = rng.random((N, 4)).astype(np.float32)
    ko, vo, counts, sent = shuffle_on_mesh(mesh, keys, vals)
    parts = compact_shuffle_output(ko, vo, counts, 8)
    assert sum(len(k) for k, _ in parts) == N
    # routing: every row landed on hash(key) % 8
    for d, (k, _) in enumerate(parts):
        assert (np.asarray(_mix32(k.astype(np.int32))) % 8 == d).all()
    # integrity: multiset of (key, value) preserved end-to-end
    want = sorted(
        map(tuple, np.column_stack([keys, vals[:, 0]]).tolist())
    )
    got = sorted(
        map(tuple, np.column_stack([
            np.concatenate([k for k, _ in parts]),
            np.concatenate([v for _, v in parts])[:, 0],
        ]).tolist())
    )
    assert got == want


@needs_mesh
def test_shuffle_on_mesh_overflow_detected_not_silent():
    mesh = make_mesh_1d(8)
    # all rows share one key -> one destination: tiny capacity overflows
    keys = np.full(8 * 16, 7, np.int32)
    vals = np.arange(8 * 16, dtype=np.float32)[:, None]
    ko, vo, counts, sent = shuffle_on_mesh(mesh, keys, vals, capacity=4)
    # TRUE counts on both ends: source and receiver each see values
    # above capacity and know rows were truncated
    assert np.asarray(sent).max() > 4
    assert np.asarray(counts).max() > 4
    # the host-side compactor enforces the contract rather than
    # silently returning short partitions
    with pytest.raises(ValueError, match="truncated"):
        compact_shuffle_output(ko, vo, counts, 8)
    # mesh construction fails at the source when oversubscribed
    with pytest.raises(ValueError, match="devices"):
        make_mesh_1d(1000)


@needs_mesh
def test_shuffle_on_mesh_stays_on_device():
    """The exchange is one jitted program over jax arrays: inputs sharded
    on the mesh produce outputs sharded on the mesh, with no host
    serialization layer in between."""
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = make_mesh_1d(8)
    N = 8 * 32
    keys = jax.device_put(
        np.arange(N, dtype=np.int32),
        NamedSharding(mesh, PartitionSpec("shuffle")),
    )
    vals = jax.device_put(
        np.ones((N, 2), np.float32),
        NamedSharding(mesh, PartitionSpec("shuffle")),
    )
    ko, vo, counts, sent = shuffle_on_mesh(mesh, keys, vals)
    # outputs live on the mesh, still sharded over the shuffle axis
    assert ko.sharding.is_equivalent_to(
        NamedSharding(mesh, PartitionSpec("shuffle")), ko.ndim
    )
    assert int(np.asarray(counts).sum()) == N


@needs_mesh
def test_ring_exchange():
    mesh = make_mesh_1d(8)
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    y = np.asarray(ring_exchange(mesh, x))
    for i in range(8):
        assert (y[(i + 1) % 8] == x[i]).all()
    # a full lap returns home
    z = x
    for _ in range(8):
        z = np.asarray(ring_exchange(mesh, z))
    assert (z == x).all()
