"""Native transition engine (native/engine.cpp + scheduler/native_engine.py).

The contract under test (docs/native_engine.md): floods and
recommendation rounds driven through the compiled engine produce
BIT-IDENTICAL outputs to the pure-python oracle — final task states,
per-key stories, journals, ledger digests, and per-destination message
multisets — with anything the C++ core does not model escaping to the
oracle per key.  Plus the fallback chain: no toolchain / kill-switch =>
the oracle engages silently.
"""

from __future__ import annotations

import logging
import os
import random
import subprocess
import sys

import pytest

from distributed_tpu import config, native
from distributed_tpu.scheduler.state import SchedulerState
from distributed_tpu.utils.collections import OrderedSet


def _native_state(**kw):
    state = SchedulerState(**kw)
    if not state.attach_native(build=True):
        pytest.skip("native toolchain unavailable")
    return state


class _Spec:
    __slots__ = ()

    def __repr__(self):
        return "<spec>"


SPEC = _Spec()

OVR = {
    "scheduler.trace.enabled": False,
    "scheduler.native-engine.enabled": False,  # explicit attach only
    "scheduler.native-engine.min-flood": 0,    # no oracle routing floor
}


class _StepClock:
    """Deterministic injectable clock in the VirtualClock mold: time
    only advances when the harness steps it, never per read — so both
    engines see identical stamps for identical work.  (Clock-call
    COUNTS are explicitly not part of the parity contract: the native
    path hoists reads the oracle performs per row.)"""

    def __init__(self):
        self.t = 0.0

    def step(self):
        self.t += 0.25

    def __call__(self):
        return self.t


def _build_pair(n_workers=32, width=64, layers=8, fanin=2, seed=0,
                journal=False, restrictions=False, actors=False):
    """(oracle, native) SchedulerStates with the identical graph."""
    states = []
    for native_on in (False, True):
        with config.set(OVR):
            state = SchedulerState(validate=False, clock=_StepClock())
            state.ledger.digest_enabled = True
            if native_on:
                if not state.attach_native(build=True):
                    pytest.skip("native toolchain unavailable")
            if journal:
                state.trace.journal_start()
            for i in range(n_workers):
                state.add_worker_state(
                    f"sim://w{i}", nthreads=1, memory_limit=2**30,
                    name=f"w{i}",
                )
            rng = random.Random(seed)
            addrs = list(state.workers)
            prev = []
            for i in range(width):
                k = f"root-{i}"
                state.client_desires_keys([k], "c")
                recs, cm, wm = state._transition(
                    k, "memory", "scatter", nbytes=65536,
                    worker=addrs[i % len(addrs)],
                )
                state._transitions(recs, cm, wm, "scatter")
                prev.append(k)
            tasks, deps, prios = {}, {}, {}
            ann = {}
            rank = 0
            for j in range(layers):
                layer = [f"L{j}-{i}" for i in range(width)]
                for k in layer:
                    deps[k] = {
                        prev[rng.randrange(len(prev))]
                        for _ in range(fanin)
                    }
                    tasks[k] = SPEC
                    prios[k] = (rank,)
                    rank += 1
                    if restrictions and rng.random() < 0.1:
                        ann[k] = {"workers": [addrs[rng.randrange(len(addrs))]],
                                  "allow_other_workers": True}
                prev = layer
            state.update_graph_core(
                tasks, deps, prev, client="c", priorities=prios,
                annotations_by_key=ann or None,
                actors=[k for k in tasks if actors and k.endswith("-0")],
                stimulus_id="graph",
            )
        states.append(state)
    return states


def _drive(state, seed=0, err_rate=0.0, release_at=None):
    """Drive every processing task to completion via floods; returns the
    collected (client_msgs, worker_msgs) rounds."""
    rng = random.Random(seed)
    out = []
    rounds = 0
    with config.set(OVR):
        while True:
            batch = [
                (
                    ts.key, ws.address, f"fin-{rounds}-{i}",
                    {
                        "nbytes": 1024 + (hash(ts.key) % 7) * 512,
                        "typename": "int",
                        "startstops": [{
                            "action": "compute", "start": 0.0,
                            "stop": 0.01,
                        }],
                    },
                )
                for ws in state.workers.values()
                for i, ts in enumerate(list(ws.processing))
            ]
            if not batch:
                break
            state.clock.step()  # virtual time advances between floods
            if err_rate and rng.random() < err_rate:
                errs = [
                    (k, w, s, dict(exception_text="boom"))
                    for k, w, s, _kw in batch
                ]
                out.append(state.stimulus_tasks_erred_batch(errs))
            else:
                out.append(state.stimulus_tasks_finished_batch(batch))
            if release_at is not None and rounds == release_at:
                out.append(state.client_releases_keys(
                    [f"root-{i}" for i in range(4)], "c", "rel",
                ))
            rounds += 1
            assert rounds < 5000
    return out


def _freeze(obj):
    if isinstance(obj, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in obj.items()))
    if isinstance(obj, (list, tuple)):
        return tuple(_freeze(v) for v in obj)
    if isinstance(obj, (str, bytes, int, float, bool)) or obj is None:
        return obj
    return repr(type(obj))


def _canon(rounds):
    out = []
    for cm, wm in rounds:
        for d in (cm, wm):
            c = {}
            for dest, msgs in d.items():
                c[dest] = sorted(
                    (
                        _freeze({k: v for k, v in m.items()
                                 if k != "run_spec"})
                        for m in msgs
                    ),
                    key=repr,
                )
            out.append(c)
    return out


def _stories(state):
    return [row[:5] for row in state.transition_log]


def _snapshot(state):
    return {
        key: (
            ts.state,
            ts.processing_on.address if ts.processing_on else None,
            tuple(ws.address for ws in ts.who_has),
            tuple(d.key for d in ts.waiters),
            tuple(d.key for d in ts.waiting_on),
        )
        for key, ts in state.tasks.items()
    }


# ------------------------------------------------------------- parity


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_multiflood_parity(seed):
    """Randomized multi-flood traces: bit-identical states, stories,
    journals, ledger digests and message multisets vs the oracle."""
    oracle, nat = _build_pair(seed=seed, journal=True)
    ro = _drive(oracle, seed=seed, release_at=3)
    rn = _drive(nat, seed=seed, release_at=3)
    assert nat.native.counters()["transitions"] > 0, "native never ran"
    assert _snapshot(oracle) == _snapshot(nat)
    assert _stories(oracle) == _stories(nat)
    assert _canon(ro) == _canon(rn)
    # journals: the counter clock makes stamps identical too
    assert list(oracle.trace.journal) == list(nat.trace.journal)
    # decision ledger: same rows, same joins, same digest
    assert oracle.ledger.digest() == nat.ledger.digest()
    assert oracle.transition_counter == nat.transition_counter


def _drive_probed(state, seed=0):
    """_drive plus randomized introspection between batches: every
    probe is a hydration barrier on the native side (TaskState /
    WorkerState properties, the story deque, the ledger digest, the
    returned lazy message dicts).  Returns the probe results so the
    harness can compare them bit-for-bit across engines."""
    rng = random.Random(seed ^ 0x5EED)
    probes = []
    rounds = 0
    with config.set(OVR):
        while True:
            batch = [
                (
                    ts.key, ws.address, f"fin-{rounds}-{i}",
                    {
                        "nbytes": 1024 + (hash(ts.key) % 7) * 512,
                        "typename": "int",
                        "startstops": [{
                            "action": "compute", "start": 0.0,
                            "stop": 0.01,
                        }],
                    },
                )
                for ws in state.workers.values()
                for i, ts in enumerate(list(ws.processing))
            ]
            if not batch:
                break
            state.clock.step()
            cm, wm = state.stimulus_tasks_finished_batch(batch)
            keys = sorted(state.tasks)
            for _ in range(rng.randrange(4)):
                ts = state.tasks[keys[rng.randrange(len(keys))]]
                probes.append((
                    ts.key, ts.state, ts.nbytes,
                    tuple(sorted(w.address for w in ts.who_has)),
                    tuple(sorted(d.key for d in ts.waiters)),
                ))
            if rng.random() < 0.5:
                probes.append(len(state.transition_log))
            if rng.random() < 0.4:
                probes.append(state.ledger.digest())
            if rng.random() < 0.4:
                probes.append(sorted(
                    (dest, len(msgs)) for dest, msgs in wm.items()
                ))
            if rng.random() < 0.4:
                addrs = sorted(state.workers)
                ws = state.workers[addrs[rng.randrange(len(addrs))]]
                probes.append((
                    ws.address, ws.occupancy, ws.nbytes,
                    len(ws.processing),
                ))
            rounds += 1
            assert rounds < 5000
    return probes


@pytest.mark.parametrize("seed", [11, 12])
def test_randomized_introspection_parity(seed):
    """The lazy-hydration property test: arbitrary python-truth reads
    between batches land on identical truth at the moment of the read,
    and the whole trace stays bit-identical vs the oracle — states,
    stories, journal, ledger digests AND the probe results themselves."""
    oracle, nat = _build_pair(seed=seed, journal=True)
    po = _drive_probed(oracle, seed=seed)
    pn = _drive_probed(nat, seed=seed)
    c = nat.native.counters()
    assert c["transitions"] > 0, "native never ran"
    assert c["hydrations"] > 0, "nothing was ever deferred"
    assert c["hydration_cache_hits"] > 0, \
        "every probe forced a replay — the cache never hit"
    assert po == pn
    assert _snapshot(oracle) == _snapshot(nat)
    assert _stories(oracle) == _stories(nat)
    assert list(oracle.trace.journal) == list(nat.trace.journal)
    assert oracle.ledger.digest() == nat.ledger.digest()
    assert oracle.transition_counter == nat.transition_counter


def test_no_introspection_flood_defers_fully():
    """A purely-native flood with nothing reading python truth parks
    its segments: zero tape rows hydrate inside the flood, and the
    first later read (here: the message dict) replays them all."""
    _oracle, nat = _build_pair(seed=13, width=16, layers=2)
    ne = nat.native
    batch = [
        (ts.key, ws.address, "nf", {"nbytes": 8})
        for ws in nat.workers.values()
        for ts in list(ws.processing)
    ]
    assert batch
    h0 = ne.hydrations
    cm, wm = nat.stimulus_tasks_finished_batch(batch)
    assert ne._pending, "flood did not defer"
    assert ne.hydrations == h0, "flood hydrated rows with no reader"
    n_msgs = sum(len(v) for v in wm.values())  # lazy read: forces sync
    assert not ne._pending
    assert ne.hydrations > h0
    assert n_msgs > 0


def test_parity_with_erred_floods_and_restrictions():
    """Erred floods (uncompiled arm) and restricted tasks force per-key
    escapes; outputs stay bit-identical."""
    oracle, nat = _build_pair(seed=7, restrictions=True)
    ro = _drive(oracle, seed=7, err_rate=0.3)
    rn = _drive(nat, seed=7, err_rate=0.3)
    c = nat.native.counters()
    assert c.get("escape_restricted", 0) > 0
    assert _snapshot(oracle) == _snapshot(nat)
    assert _stories(oracle) == _stories(nat)
    assert _canon(ro) == _canon(rn)


def test_parity_under_check_mode(monkeypatch):
    """DTPU_NATIVE_CHECK audits the SoA against python truth after
    every flood; a clean run raises nothing and stays bit-identical."""
    monkeypatch.setenv("DTPU_NATIVE_CHECK", "1")
    oracle, nat = _build_pair(seed=3)
    assert nat.native.check
    _drive(oracle, seed=3)
    _drive(nat, seed=3)
    assert _snapshot(oracle) == _snapshot(nat)
    assert _stories(oracle) == _stories(nat)


def test_check_mode_catches_injected_divergence(monkeypatch):
    """Corrupting one SoA field makes the next flood's audit raise —
    the dual-run mode actually bites."""
    monkeypatch.setenv("DTPU_NATIVE_CHECK", "1")
    _oracle, nat = _build_pair(seed=4, width=16, layers=2)
    ne = nat.native
    # consume the dirty marks the ingest left behind (the unreachable-
    # task cull dirties its dependency neighborhood) BEFORE corrupting:
    # the next flood's resync would otherwise heal the injected
    # divergence and the audit would rightly find nothing
    ne.flush()
    ts = next(iter(nat.tasks.values()))
    ne.lib.eng_task_who_wants(ne.h, ts.nrow, 99)  # corrupt
    with pytest.raises(AssertionError, match="diverged"):
        _drive(nat, seed=4)


def test_escape_taxonomy_rootish_and_actor():
    """Rootish groups (dep-free, width > 2x total threads) and actors
    escape to the oracle with the right labels, and outputs still
    match."""
    oracle, nat = _build_pair(
        n_workers=8, width=40, layers=3, fanin=0, seed=5
    )
    _drive(oracle, seed=5)
    _drive(nat, seed=5)
    c = nat.native.counters()
    assert c.get("escape_rootish", 0) > 0
    assert _snapshot(oracle) == _snapshot(nat)
    assert _stories(oracle) == _stories(nat)

    oracle, nat = _build_pair(
        n_workers=16, width=24, layers=2, seed=9, actors=True
    )
    _drive(oracle, seed=9)
    _drive(nat, seed=9)
    c = nat.native.counters()
    assert c.get("escape_actor", 0) > 0
    assert _snapshot(oracle) == _snapshot(nat)
    assert _stories(oracle) == _stories(nat)


def test_misrouted_completion_still_applies_metadata():
    """A completion from a worker the task was stolen away from is
    dropped by the worker guard — but the oracle pops the event's
    metadata first.  The native path must replay exactly that
    (reviewer-found parity gap; OP_META)."""
    outs = []
    for native_on in (False, True):
        with config.set(OVR):
            state = SchedulerState(validate=False)
            if native_on and not state.attach_native(build=True):
                pytest.skip("native toolchain unavailable")
            w1 = state.add_worker_state(
                "sim://w0", nthreads=1, memory_limit=2**30, name="w0"
            )
            w2 = state.add_worker_state(
                "sim://w1", nthreads=1, memory_limit=2**30, name="w1"
            )
            tasks = {"mk-0": SPEC, "mk-1": SPEC, "mk-2": SPEC}
            state.update_graph_core(
                tasks, {k: set() for k in tasks}, list(tasks),
                client="c", priorities={k: (i,) for i, k in
                                        enumerate(tasks)},
                stimulus_id="g",
            )
            ts = next(ts for ts in state.tasks.values()
                      if ts.state == "processing")
            victim = ts.processing_on
            thief = w2 if victim is w1 else w1
            # steal-style re-placement outside any transition
            state._exit_processing_common(ts)
            ts.state = "waiting"
            state._add_to_processing(ts, thief, "steal", kind="steal")
            # the victim's in-flight completion, carrying metadata
            state.stimulus_tasks_finished_batch([(
                ts.key, victim.address, "late",
                {"nbytes": 8, "metadata": {"late": True}},
            )])
            outs.append((ts.state, ts.metadata,
                         ts.processing_on.address))
    assert outs[0] == outs[1]
    assert outs[0][0] == "processing"
    assert outs[0][1] == {"late": True}


def test_sim_digest_parity_native_vs_oracle():
    """Same-seed ClusterSim runs, native on vs off: bit-identical
    whole-run digests, makespans and ledger digests (steal + AMM
    cycles included)."""
    from distributed_tpu.sim import ClusterSim, SyntheticDag

    reports = {}
    for native_on in (True, False):
        sim = ClusterSim(
            40, nthreads=1, seed=0, validate=False, native=native_on,
            config_overrides={"scheduler.telemetry.enabled": False,
                              "scheduler.native-engine.min-flood": 0},
        )
        sim.install_digest()
        if native_on and sim.state.native is None:
            pytest.skip("native toolchain unavailable")
        trace = SyntheticDag(
            n_layers=6, layer_width=80, fanin=2, seed=0,
            layers_per_chunk=2, n_roots=40, linked_chunks=False,
        )
        trace.start(sim)
        rep = sim.run()
        reports[native_on] = (
            sim.digest(), rep["virtual_makespan_s"],
            sim.state.ledger.digest(),
        )
        if native_on:
            assert sim.state.native.counters()["transitions"] > 0
    assert reports[True] == reports[False]


# ------------------------------------------------------- fallback chain


def test_native_disable_env_forces_silent_fallback():
    """DTPU_NATIVE_DISABLE=1: the pure-python fallback engages with no
    warning logged and no native attach — the no-toolchain path,
    provable on a box that has g++."""
    code = """
import logging, sys
records = []
h = logging.Handler()
h.emit = lambda r: records.append(r)
logging.getLogger("distributed_tpu").addHandler(h)
from distributed_tpu import native
assert native.disabled()
assert native.load() is None
assert native.load_nowait() is None
from distributed_tpu.scheduler.state import SchedulerState
s = SchedulerState()
assert s.native is None
assert not s.attach_native(build=True)
s.add_worker_state("tcp://x:1", nthreads=1, memory_limit=2**30)
ts = s.new_task("k1", object())
ts.priority = (0,)
s.transitions({"k1": "waiting"}, "stim")
assert s.tasks["k1"].state == "processing"
warned = [r for r in records if r.levelno >= logging.WARNING]
assert not warned, [r.getMessage() for r in warned]
print("FALLBACK_OK")
"""
    env = dict(os.environ, DTPU_NATIVE_DISABLE="1")
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, timeout=120,
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
    )
    assert out.returncode == 0, out.stderr.decode()[-2000:]
    assert b"FALLBACK_OK" in out.stdout


def test_needs_build_keys_on_flags_and_source_list(tmp_path, monkeypatch):
    """The mtime check alone left a stale .so loaded when _SOURCES or
    the flags changed; _needs_build must also key on the recorded
    compile command (the .buildinfo sidecar)."""
    lib = tmp_path / "fake.so"
    lib.write_bytes(b"x")
    info = tmp_path / "fake.so.buildinfo"
    src = tmp_path / "a.cpp"
    src.write_text("// src")
    monkeypatch.setattr(native, "_LIB_PATH", str(lib))
    monkeypatch.setattr(native, "_BUILDINFO_PATH", str(info))
    monkeypatch.setattr(native, "_SOURCES", [str(src)])
    # no sidecar: stale by definition
    assert native._needs_build()
    info.write_text(__import__("json").dumps(native._build_spec()))
    os.utime(str(lib))  # newer than src
    assert not native._needs_build()
    # source list drift: same files on disk, different command
    monkeypatch.setattr(
        native, "_SOURCES", [str(src), str(tmp_path / "b.cpp")]
    )
    (tmp_path / "b.cpp").write_text("// b")
    os.utime(str(lib))
    assert native._needs_build(), "source-list drift went unnoticed"
    # flag drift, same sources
    monkeypatch.setattr(native, "_SOURCES", [str(src)])
    monkeypatch.setattr(
        native, "_FLAGS", list(native._FLAGS) + ["-DX"]
    )
    assert native._needs_build(), "flag drift went unnoticed"


def test_min_flood_routes_small_floods_to_oracle():
    """Floods below scheduler.native-engine.min-flood run the oracle
    (per-flood bridge overhead outweighs the savings there)."""
    with config.set({"scheduler.trace.enabled": False,
                     "scheduler.native-engine.enabled": False,
                     "scheduler.native-engine.min-flood": 64}):
        state = SchedulerState(validate=False)
        if not state.attach_native(build=True):
            pytest.skip("native toolchain unavailable")
        state.add_worker_state(
            "sim://w0", nthreads=4, memory_limit=2**30, name="w0"
        )
        tasks = {f"t-{i}": SPEC for i in range(4)}
        state.update_graph_core(
            tasks, {k: set() for k in tasks}, list(tasks), client="c",
            priorities={k: (i,) for i, k in enumerate(tasks)},
            stimulus_id="g",
        )
        floods_before = state.native.floods
        batch = [
            (ts.key, ws.address, f"s{i}", {"nbytes": 8})
            for ws in state.workers.values()
            for i, ts in enumerate(list(ws.processing))
        ]
        assert 0 < len(batch) < 64
        state.stimulus_tasks_finished_batch(batch)
        assert state.native.floods == floods_before  # oracle routed
        for k in batch:
            assert state.tasks[k[0]].state == "memory"


def test_late_attach_first_op_is_a_flood():
    """The server attaches via the prebuild callback AFTER tasks are
    already in flight; the very first native operation is then a
    task-finished flood whose flush() must initialize its buffers
    (reviewer-found: a shared lazy-init dict made this path raise and
    silently disable the engine)."""
    with config.set(OVR):
        state = SchedulerState(validate=False)
        for i in range(4):
            state.add_worker_state(
                f"sim://w{i}", nthreads=1, memory_limit=2**30,
                name=f"w{i}",
            )
        addrs = list(state.workers)
        for i in range(8):
            k = f"r-{i}"
            state.client_desires_keys([k], "c")
            recs, cm, wm = state._transition(
                k, "memory", "sc", nbytes=256, worker=addrs[i % 4]
            )
            state._transitions(recs, cm, wm, "sc")
        tasks = {f"m-{i}": SPEC for i in range(8)}
        deps = {f"m-{i}": {f"r-{i % 8}"} for i in range(8)}
        state.update_graph_core(
            tasks, deps, list(tasks), client="c",
            priorities={k: (i,) for i, k in enumerate(tasks)},
            stimulus_id="g",
        )
        # mid-run attach (the prebuild on_ready path): everything
        # adopted dirty, nothing flushed yet
        if not state.attach_native(build=True):
            pytest.skip("native toolchain unavailable")
        batch = [
            (ts.key, ws.address, f"s{i}", {"nbytes": 8})
            for ws in state.workers.values()
            for i, ts in enumerate(list(ws.processing))
        ]
        assert batch
        state.stimulus_tasks_finished_batch(batch)
        assert state.native is not None, "flood disabled the engine"
        assert state.native.counters()["transitions"] > 0
        for k, *_ in batch:
            assert state.tasks[k].state == "memory"


def test_plugin_without_marker_forces_oracle():
    """Any plugin lacking tape_safe gates the whole flood off the
    native path (the conservative default)."""
    _oracle, nat = _build_pair(seed=6, width=8, layers=1)

    class _P:
        def transition(self, *a, **k):
            pass

    nat.plugins["opaque"] = _P()
    assert not nat.native.active()
    nat.plugins.pop("opaque")
    assert nat.native.active()


def test_wall_bills_native_phase():
    """The ctypes drain bills to engine.native nested under
    engine.drain (dtpu_wall_seconds_total)."""
    _oracle, nat = _build_pair(seed=8, width=16, layers=2)
    _drive(nat, seed=8)
    totals = nat.wall.totals
    assert totals.get("engine.native", 0.0) > 0.0
    assert totals.get("engine.drain", 0.0) > 0.0


# ------------------------------------------------------------ OrderedSet


def test_ordered_set_semantics():
    s: OrderedSet = OrderedSet()
    s.add("a"), s.add("b"), s.add("c")
    s.add("a")  # re-add keeps position
    assert list(s) == ["a", "b", "c"]
    s.discard("b")
    assert list(s) == ["a", "c"]
    s.add("b")  # removed then re-added: appends
    assert list(s) == ["a", "c", "b"]
    assert s == {"a", "b", "c"}
    assert len(s) == 3 and "c" in s and "z" not in s
    # interop with plain sets in either position
    plain = {"a", "z"}
    plain -= s
    assert plain == {"z"}
    assert (s & {"a", "b"}) == {"a", "b"}
    assert list(s & {"a", "b"}) == ["a", "b"]  # keeps left order
    assert sorted({"q"} | s) == ["a", "b", "c", "q"]
    assert list(s.difference({"a"})) == ["c", "b"]
    assert s.union({"q"}) == {"a", "b", "c", "q"}
    s.remove("a")
    with pytest.raises(KeyError):
        s.remove("a")


# The PYTHONHASHSEED sweep of the partition chaos scenario lives with
# the rest of the hashseed harness: tests/test_determinism.py::
# test_partition_chaos_across_hashseeds (seeds 1/6 caught the original
# `(released, memory)` crash).


def test_ordered_set_determinism_across_hashseed():
    """Iteration order is insertion order, independent of
    PYTHONHASHSEED — the property the engine's cross-process
    determinism rests on."""
    from conftest import sweep_hashseed_stdout

    out = sweep_hashseed_stdout(
        "from distributed_tpu.utils.collections import OrderedSet\n"
        "s = OrderedSet()\n"
        "for x in ['k%d' % i for i in range(50)]: s.add(x)\n"
        "s.discard('k7'); s.add('k7')\n"
        "print(','.join(s))\n",
        seeds=("0", "1", "2"), timeout=60,
    )
    assert out.strip().startswith("k0,k1,")
