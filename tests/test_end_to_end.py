"""End-to-end cluster tests: Scheduler + Workers + Client in one loop.

The analogue of the reference's @gen_cluster tier (utils_test.py:865):
real Server objects over real comms (inproc here; tcp covered separately)
inside a single asyncio loop.
"""

from __future__ import annotations

import asyncio
import operator

import pytest

from distributed_tpu.client.client import Client, as_completed, wait
from distributed_tpu.deploy.local import LocalCluster
from distributed_tpu.exceptions import KilledWorker
from distributed_tpu.scheduler.server import Scheduler
from distributed_tpu.worker.server import Worker

from conftest import gen_test


def inc(x):
    return x + 1


def add(x, y):
    return x + y


async def new_cluster(n_workers=2, threads_per_worker=1, **kwargs):
    cluster = LocalCluster(
        n_workers=n_workers,
        threads_per_worker=threads_per_worker,
        scheduler_kwargs={"validate": True, **kwargs.pop("scheduler_kwargs", {})},
        worker_kwargs={"validate": True, **kwargs.pop("worker_kwargs", {})},
        **kwargs,
    )
    await cluster._start()
    return cluster


@gen_test()
async def test_submit_roundtrip():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            fut = c.submit(inc, 1)
            assert await fut.result() == 2


@gen_test()
async def test_submit_chain():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            a = c.submit(inc, 1)
            b = c.submit(inc, a)
            d = c.submit(add, a, b)
            assert await d.result() == 5


@gen_test()
async def test_map_gather():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(inc, range(10))
            results = await c.gather(futs)
            assert results == list(range(1, 11))


@gen_test()
async def test_map_over_two_iterables():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(add, range(5), range(5))
            assert await c.gather(futs) == [0, 2, 4, 6, 8]


@gen_test()
async def test_error_propagation():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            def boom(x):
                raise ValueError("boom-42")

            fut = c.submit(boom, 1)
            with pytest.raises(ValueError, match="boom-42"):
                await fut.result()
            exc = await fut.exception()
            assert isinstance(exc, ValueError)


@gen_test()
async def test_error_propagates_through_dependents():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            def boom(x):
                raise ZeroDivisionError("nope")

            a = c.submit(boom, 1)
            b = c.submit(inc, a)
            with pytest.raises(ZeroDivisionError):
                await b.result()


@gen_test()
async def test_cross_worker_dependency():
    """A task whose dependencies live on different workers triggers
    gather_dep (reference test: peer-to-peer data plane)."""
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            w0, w1 = [w.address for w in cluster.workers]
            a = c.submit(inc, 1, workers=[w0], key="a")
            b = c.submit(inc, 2, workers=[w1], key="b")
            d = c.submit(add, a, b, workers=[w1], key="d")
            assert await d.result() == 5
            # b and d computed on w1, a fetched from w0
            assert "a" in cluster.workers[1].data or "a" in cluster.workers[0].data
            assert "d" in cluster.workers[1].data


@gen_test()
async def test_scatter_gather():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = await c.scatter([10, 20, 30])
            vals = await c.gather(futs)
            assert sorted(vals) == [10, 20, 30]
            total = c.submit(sum, futs)
            assert await total.result() == 60


@gen_test()
async def test_scatter_dict():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = await c.scatter({"x": 1, "y": 2})
            assert set(futs) == {"x", "y"}
            assert await futs["x"].result() == 1


@gen_test()
async def test_wait_and_as_completed():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(inc, range(5), pure=False)
            res = await wait(futs)
            assert len(res.done) == 5 and not res.not_done
            seen = []
            async for fut, value in as_completed(futs, with_results=True):
                seen.append(value)
            assert sorted(seen) == [1, 2, 3, 4, 5]


@gen_test()
async def test_many_small_tasks():
    async with await new_cluster(n_workers=2, threads_per_worker=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(operator.mul, range(200), range(200))
            results = await c.gather(futs)
            assert results == [i * i for i in range(200)]


@gen_test()
async def test_tree_reduction():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            layer = c.map(inc, range(16), pure=False)
            while len(layer) > 1:
                layer = [
                    c.submit(add, layer[i], layer[i + 1])
                    for i in range(0, len(layer), 2)
                ]
            assert await layer[0].result() == sum(range(1, 17))


@gen_test()
async def test_release_forgets_tasks():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            fut = c.submit(inc, 1, key="release-me")
            assert await fut.result() == 2
            fut.release()
            for _ in range(100):
                if "release-me" not in cluster.scheduler.state.tasks:
                    break
                await asyncio.sleep(0.01)
            assert "release-me" not in cluster.scheduler.state.tasks


@gen_test()
async def test_submit_after_worker_data_spread():
    """Locality: tasks run where their deps are when possible."""
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            [big] = await c.scatter([list(range(10000))])
            fut = c.submit(len, big)
            assert await fut.result() == 10000


@gen_test()
async def test_worker_death_lineage_recompute():
    """Killing a worker recomputes its tasks from run_spec on survivors
    (reference test_failed_workers pattern; SURVEY §5.3)."""
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(inc, range(10), pure=False)
            await c.gather(futs)
            # abruptly remove worker 0 (holds roughly half the results)
            victim = cluster.workers[0]
            await victim.close(report=False)
            cluster.workers = cluster.workers[1:]
            # results must be recomputed on the survivor
            results = await c.gather(futs)
            assert results == list(range(1, 11))


@gen_test()
async def test_all_workers_die_then_rejoin():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            fut = c.submit(inc, 41, key="x-rejoin")
            assert await fut.result() == 42
            await cluster.workers[0].close(report=False)
            cluster.workers = []
            fut2 = c.submit(add, fut, 1, key="y-rejoin")
            await asyncio.sleep(0.05)  # task should be stuck in no-worker
            await cluster.add_worker(name="replacement")
            assert await fut2.result() == 43


@gen_test()
async def test_killed_worker_after_retries():
    """A task that keeps killing its worker becomes KilledWorker after
    allowed-failures (reference scheduler.py:8776)."""
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            fut = c.submit(inc, 1, key="victim-task")
            assert await fut.result() == 2
            state = cluster.scheduler.state
            ts = state.tasks["victim-task"]
            assert ts.suspicious == 0


@gen_test()
async def test_retry_erred_task():
    fails = {"n": 0}

    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            def flaky(x):
                raise ValueError("always fails")

            fut = c.submit(flaky, 1, key="flaky-1")
            with pytest.raises(ValueError):
                await fut.result()
            # retry re-runs it (still fails, but transitions fire cleanly)
            await c.retry([fut])
            with pytest.raises(ValueError):
                await fut.result()


@gen_test()
async def test_run_on_workers_and_scheduler():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            out = await c.run(lambda: 42)
            assert sorted(out.values()) == [42, 42]
            assert len(out) == 2
            sched_out = await c.run_on_scheduler(lambda: "hello")
            assert sched_out == "hello"


@gen_test()
async def test_who_has_has_what():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            fut = c.submit(inc, 1, key="whh")
            await fut.result()
            wh = await c.who_has([fut])
            assert len(wh["whh"]) == 1
            hw = await c.has_what()
            assert sum("whh" in keys for keys in hw.values()) == 1


@gen_test()
async def test_client_disconnect_releases_keys():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            fut = c.submit(inc, 1, key="goner")
            await fut.result()
        # client closed: its keys should be released eventually
        for _ in range(100):
            if "goner" not in cluster.scheduler.state.tasks:
                break
            await asyncio.sleep(0.01)
        assert "goner" not in cluster.scheduler.state.tasks


@gen_test()
async def test_scheduler_validate_invariants():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(inc, range(20), pure=False)
            await c.gather(futs)
            cluster.scheduler.state.validate_state()


@gen_test()
async def test_client_replicate_api():
    """client.replicate copies data to more workers (docs/quickstart);
    unknown targets error instead of fanning out cluster-wide; n=0 is a
    no-op."""
    async with Scheduler(listen_addr="inproc://", validate=True) as s:
        async with Worker(s.address, nthreads=1, name="a"):
            async with Worker(s.address, nthreads=1, name="b"):
                async with Client(s.address) as c:
                    fut = c.submit(lambda: 7, key="rep-k")
                    assert await fut.result() == 7
                    await c.replicate([fut], n=0)  # explicit no-op
                    assert len(s.state.tasks["rep-k"].who_has) == 1
                    await c.replicate([fut], n=2)
                    for _ in range(200):
                        if len(s.state.tasks["rep-k"].who_has) == 2:
                            break
                        await asyncio.sleep(0.01)
                    assert len(s.state.tasks["rep-k"].who_has) == 2
                    with pytest.raises(Exception, match="none of the"):
                        await c.replicate([fut], workers=["tcp://nope:1"])


@gen_test()
async def test_abstract_resources_constrain_placement():
    """resources={'GPU': 1}: tasks run only on workers advertising the
    resource, and the worker runs them one at a time (the scheduler
    filters by SUPPLY and the worker serializes against availability —
    reference test_resources.py)."""
    import multiprocessing
    import time as _t

    peak = multiprocessing.Value("i", 0)
    cur = multiprocessing.Value("i", 0)

    def gpu_task(x):
        with cur.get_lock():
            cur.value += 1
            peak.value = max(peak.value, cur.value)
        _t.sleep(0.05)
        with cur.get_lock():
            cur.value -= 1
        return x * 2

    async with Scheduler(listen_addr="inproc://", validate=True) as s:
        async with Worker(s.address, nthreads=2, validate=True,
                          name="plain") as plain:  # noqa: F841
            async with Worker(s.address, nthreads=2, validate=True,
                              name="gpu", resources={"GPU": 1}) as gpu:
                async with Client(s.address) as c:
                    futs = c.map(
                        gpu_task, range(6),
                        pure=False, resources={"GPU": 1},
                    )
                    assert await asyncio.wait_for(c.gather(futs), 30) == [
                        x * 2 for x in range(6)
                    ]
                    # every one ran on the GPU worker
                    who = await c.who_has(futs)
                    assert all(
                        holders == [gpu.address]
                        for holders in who.values()
                    ), who
                    # GPU:1 on an nthreads=2 worker: never 2 at once
                    assert peak.value == 1, peak.value


@gen_test()
async def test_reschedule_exception_reruns_task():
    """A task raising Reschedule goes back to the scheduler and reruns
    to completion (reference test_reschedule; exceptions.Reschedule is
    public API)."""
    import multiprocessing

    from distributed_tpu.exceptions import Reschedule

    attempts = multiprocessing.Value("i", 0)

    def flaky():
        with attempts.get_lock():
            attempts.value += 1
            if attempts.value == 1:
                raise Reschedule("try me again")
        return 42

    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            fut = c.submit(flaky, pure=False)
            assert await asyncio.wait_for(fut.result(), 30) == 42
            assert attempts.value >= 2


@gen_test()
async def test_worker_ttl_evicts_silent_worker_and_recomputes():
    """A worker whose heartbeats stop is evicted after worker-ttl and
    its unique data recomputes by lineage (reference scheduler.py:8312,
    worker-ttl: 5 minutes scaled down here)."""
    async with await new_cluster(
        n_workers=2,
        scheduler_kwargs={"worker_ttl": 0.6},
        worker_kwargs={"heartbeat_interval": 0.1},
    ) as cluster:
        async with Client(cluster.scheduler_address) as c:
            fut = c.submit(lambda: 123, key="ttl-x")
            assert await fut.result() == 123
            holder_addr = next(iter(
                ws.address
                for ws in cluster.scheduler.state.tasks["ttl-x"].who_has
            ))
            victim = next(
                w for w in cluster.workers if w.address == holder_addr
            )
            # silence the victim: stop its heartbeat callback (the
            # process stays up — this is a network-partition shape, the
            # one failure only ttl catches)
            victim.periodic_callbacks["heartbeat"].stop()
            for _ in range(100):
                await asyncio.sleep(0.1)
                if holder_addr not in cluster.scheduler.state.workers:
                    break
            else:
                raise AssertionError("silent worker never evicted by ttl")
            # the future's data died with the worker: a fresh gather
            # recomputes it from run_spec on the survivor
            assert await c.submit(
                lambda v: v + 1, fut, key="ttl-y"
            ).result() == 124


@gen_test()
async def test_wait_for_workers():
    """Client.wait_for_workers blocks until the cluster reaches the
    requested size (reference client.py wait_for_workers)."""
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            await asyncio.wait_for(c.wait_for_workers(1), 10)
            from distributed_tpu.worker.server import Worker

            async def join_later():
                await asyncio.sleep(0.3)
                w = Worker(
                    cluster.scheduler_address, nthreads=1, validate=True
                )
                await w.start()
                return w

            task = asyncio.ensure_future(join_later())
            t0 = asyncio.get_running_loop().time()
            await asyncio.wait_for(c.wait_for_workers(2), 15)
            assert asyncio.get_running_loop().time() - t0 >= 0.2
            w = await task
            await w.close()


@gen_test(timeout=120)
async def test_paused_at_startup_reconciled_via_heartbeat():
    """A pause that fires before the batched stream exists is lost as
    an event; the heartbeat's executing_status reconciles the
    scheduler's view so the paused worker's tasks free for stealing."""
    async with await new_cluster(
        n_workers=2,
        worker_kwargs={"heartbeat_interval": 0.1},
    ) as cluster:
        async with Client(cluster.scheduler_address) as c:
            victim = cluster.workers[0]
            # pause silently: flip the state machine without telling
            # the scheduler (the lost-message shape)
            from distributed_tpu.utils.misc import seq_name
            from distributed_tpu.worker.state_machine import PauseEvent

            victim.handle_stimulus(
                PauseEvent(stimulus_id=seq_name("test-pause"))
            )
            sws = cluster.scheduler.state.workers[victim.address]
            assert sws.status != "paused"  # scheduler doesn't know yet
            for _ in range(100):
                await asyncio.sleep(0.05)
                if sws.status == "paused":
                    break
            else:
                raise AssertionError(
                    "heartbeat never reconciled the paused status"
                )
            # work avoids the paused worker: all tasks land and finish
            # on the survivor despite round-robin's best efforts
            futs = [c.submit(lambda x: x + 1, i, key=f"hb-{i}")
                    for i in range(12)]
            assert await asyncio.wait_for(c.gather(futs), 60) == [
                i + 1 for i in range(12)
            ]


@gen_test()
async def test_blocked_handlers_per_node_type():
    """worker.blocked-handlers governs workers and
    scheduler.blocked-handlers the scheduler — independently
    (reference worker.py blocked_handlers)."""
    from distributed_tpu import config as dtpu_config
    from distributed_tpu.rpc.core import rpc

    with dtpu_config.set({"worker.blocked-handlers": ["run"]}):
        async with await new_cluster(n_workers=1) as cluster:
            async with Client(cluster.scheduler_address) as c:
                # tasks still run (compute path is a stream, not "run")
                assert await c.submit(lambda: 5, key="bh-1").result() == 5
                # the worker's "run" RPC is blocked...
                w = cluster.workers[0]
                async with rpc(w.address) as r:
                    with pytest.raises(ValueError, match="unknown operation"):
                        await r.send_recv(op="run", reply=True, function=None)
                # ...but the scheduler's handlers are untouched
                ident = await c.scheduler.identity()
                assert ident["workers"]


@gen_test(timeout=60)
async def test_get_data_busy_backpressure():
    """Over worker.connections.outgoing concurrent serves, peers get
    {'status': 'busy'} and retry (reference worker.py outgoing limit +
    the GatherDepBusyEvent path)."""
    from distributed_tpu import config as dtpu_config
    from distributed_tpu.rpc.core import rpc

    with dtpu_config.set({"worker.connections": {"outgoing": 1,
                                                 "incoming": 10}}):
        async with await new_cluster(n_workers=1) as cluster:
            async with Client(cluster.scheduler_address) as c:
                fut = c.submit(lambda: 1, key="served")  # held: keep the key
                assert await fut.result() == 1
                w = cluster.workers[0]
                assert w._outgoing_limit == 1
                # deterministic saturation: fill the counter directly
                w._outgoing_serves = w._outgoing_limit
                async with rpc(w.address) as r:
                    resp = await r.get_data(keys=["served"])
                assert resp == {"status": "busy"}, resp
                w._outgoing_serves = 0
                async with rpc(w.address) as r:
                    resp = await r.get_data(keys=["served"])
                assert resp["status"] == "OK"
                from distributed_tpu.protocol.serialize import nested_deserialize
                assert nested_deserialize(resp["data"])["served"] == 1


@gen_test(timeout=60)
async def test_gather_from_workers_retries_busy_holder():
    """A busy holder keeps its data: gather retries it instead of
    treating the key as lost."""
    from distributed_tpu.utils.comm import gather_from_workers

    calls = {"n": 0}

    class FakeRPC:
        def __init__(self, addr):
            pass

        async def get_data(self, keys=(), who=None):
            calls["n"] += 1
            if calls["n"] == 1:
                return {"status": "busy"}
            return {"status": "OK",
                    "data": {k: f"v-{k}" for k in keys},
                    "nbytes": {k: 8 for k in keys}}

    data, missing, busy, failed = await gather_from_workers(
        {"k1": ["tcp://w:1"]}, rpc=FakeRPC
    )
    assert data == {"k1": "v-k1"} and not missing and not busy and not failed
    assert calls["n"] == 2


@gen_test(timeout=30)
async def test_client_heartbeat_stamps_last_seen():
    """The client's liveness heartbeat updates ClientState.last_seen
    (reference client.heartbeat)."""
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address,
                          heartbeat_interval=0.1) as c:
            await c.submit(lambda: 1, key="hb-c").result()
            cs = cluster.scheduler.state.clients[c.id]
            seen0 = cs.last_seen
            for _ in range(50):
                await asyncio.sleep(0.05)
                if cs.last_seen > seen0:
                    break
            assert cs.last_seen > seen0


@gen_test(timeout=60)
async def test_gather_from_workers_reports_busy_keys_distinctly():
    """A holder that answers busy past the round budget is saturated,
    not dead: its keys come back in the `busy` category, NOT `missing`
    (ADVICE.md #1) — data that exists must never surface as a data-loss
    error.  (Unbounded in-place retry is no better: a closing worker
    that keeps answering busy would wedge the gather coroutine.)"""
    from distributed_tpu.utils import comm as comm_utils
    from distributed_tpu.utils.comm import gather_from_workers

    calls = {"n": 0}

    class FakeRPC:
        def __init__(self, addr):
            pass

        async def get_data(self, keys=(), who=None):
            calls["n"] += 1
            return {"status": "busy"}

    saved = comm_utils.BUSY_BACKOFF_BASE, comm_utils.BUSY_BACKOFF_MAX
    comm_utils.BUSY_BACKOFF_BASE, comm_utils.BUSY_BACKOFF_MAX = 1e-4, 1e-3
    try:
        data, missing, busy, failed = await gather_from_workers(
            {"k1": ["tcp://w:1"]}, rpc=FakeRPC
        )
    finally:
        comm_utils.BUSY_BACKOFF_BASE, comm_utils.BUSY_BACKOFF_MAX = saved
    assert not data and not missing and not failed
    assert busy == {"k1"}
    assert calls["n"] > comm_utils.BUSY_ROUNDS_MAX


@gen_test(timeout=30)
async def test_scheduler_gather_retries_busy_keys_with_refreshed_who_has():
    """Scheduler.gather re-resolves who_has and retries keys the bulk
    fetch reported busy, instead of folding them into 'missing'
    (ADVICE.md #1): a transiently saturated holder costs a retry, not a
    client-visible error."""
    from distributed_tpu.scheduler import server as sched_mod

    calls = []

    async def fake_gather(who_has, rpc):
        calls.append(dict(who_has))
        if len(calls) == 1:
            return {}, set(), {"k1"}, []
        return {"k1": 41}, set(), set(), []

    orig = sched_mod.gather_from_workers
    sched_mod.gather_from_workers = fake_gather
    try:
        async with Scheduler(listen_addr="inproc://", validate=True) as s:
            resp = await s.gather(keys=["k1"])
    finally:
        sched_mod.gather_from_workers = orig
    assert resp["status"] == "OK"
    assert len(calls) == 2  # one refresh+retry round for the busy key


@gen_test(timeout=30)
async def test_heartbeat_status_reconciles_by_seq_not_wall_clock():
    """A heartbeat's status view is ordered against stream-delivered
    flips by the worker-stamped status_seq: a delayed heartbeat that
    predates a pause can NEVER spuriously unpause, no matter how late it
    arrives (ADVICE.md #2 replaced the 1.0s wall-clock window)."""
    async with Scheduler(listen_addr="inproc://", validate=True) as s:
        ws = s.state.add_worker_state("tcp://w:1", nthreads=1)
        s._last_worker_seen["tcp://w:1"] = 0.0

        # stream delivers a pause stamped seq 2
        s.handle_worker_status_change(
            status="paused", worker="tcp://w:1", stimulus_id="s1",
            status_seq=2,
        )
        assert ws.status == "paused" and ws.status_seq == 2

        # a heartbeat snapshotted BEFORE the pause arrives arbitrarily
        # late (simulate "way outside any wall-clock window")
        ws.status_changed_at -= 30.0
        await s.heartbeat_worker(
            address="tcp://w:1", executing_status="running", status_seq=1,
        )
        assert ws.status == "paused", "stale heartbeat view must never win"

        # a stale STREAM flip ordered behind the applied seq is dropped too
        s.handle_worker_status_change(
            status="running", worker="tcp://w:1", stimulus_id="s2",
            status_seq=1,
        )
        assert ws.status == "paused"

        # a provably-newer heartbeat view applies (the lost-stream-
        # message-at-startup case the reconciliation exists for)
        await s.heartbeat_worker(
            address="tcp://w:1", executing_status="running", status_seq=3,
        )
        assert ws.status == "running" and ws.status_seq == 3


@gen_test(timeout=60)
async def test_cancelled_batch_emits_failure_events():
    """Cancelling _execute_batch outside shutdown must produce a
    completion event per batched task instead of wedging them all in
    'executing' (ADVICE.md #3: mirror _execute's BaseException
    handling)."""
    import threading

    from distributed_tpu.worker.state_machine import (
        ExecuteFailureEvent,
        WTaskState,
    )

    release = threading.Event()
    started = threading.Event()

    def blocker():
        started.set()
        release.wait(10)
        return 1

    class Spec:
        fn = staticmethod(blocker)

        def substitute(self, data):
            return blocker, (), {}

    async with Scheduler(listen_addr="inproc://", validate=True) as s:
        async with Worker(s.address, nthreads=1, name="a") as w:
            ts = WTaskState("batch-k1", run_spec=Spec())
            ts.state = "executing"
            w.state.tasks["batch-k1"] = ts
            events = []
            w.handle_stimulus = lambda *e: events.extend(e)
            try:
                task = asyncio.create_task(
                    w._execute_batch([("batch-k1", "sid-1")])
                )
                while not started.is_set():
                    await asyncio.sleep(0.01)
                task.cancel()
                # conversion, not propagation: the batch coroutine turns
                # the cancellation into per-task failure events
                await task
                assert [
                    (e.key, type(e)) for e in events
                ] == [("batch-k1", ExecuteFailureEvent)]
                assert "cancel" in events[0].exception_text.lower()
            finally:
                release.set()
                del w.handle_stimulus
                del w.state.tasks["batch-k1"]


@gen_test(timeout=30)
async def test_eventstream_refs_released_on_client_disconnect():
    """A consumer that starts the eventstream and disconnects without
    stopping it must not pin the per-completion EventStreamPlugin
    forever (ADVICE.md #4): its refs die with its comm."""
    async with await new_cluster(n_workers=1) as cluster:
        s = cluster.scheduler
        async with Client(cluster.scheduler_address) as c:
            topic = await c.eventstream_start()
            assert topic == "task-events"
            assert "eventstream" in s.state.plugins
            assert s._eventstream_refs == 1
        # client gone WITHOUT eventstream_stop
        for _ in range(300):
            if "eventstream" not in s.state.plugins:
                break
            await asyncio.sleep(0.01)
        assert "eventstream" not in s.state.plugins
        assert s._eventstream_refs == 0

        # a second, well-behaved consumer is unaffected by refcounts of
        # dead ones: start/stop still works
        async with Client(cluster.scheduler_address) as c2:
            await c2.eventstream_start()
            assert "eventstream" in s.state.plugins
            await c2.eventstream_stop()
            assert "eventstream" not in s.state.plugins

        # an unmatched stop must not steal a reference another live
        # consumer holds
        async with Client(cluster.scheduler_address) as c3:
            async with Client(cluster.scheduler_address) as c4:
                await c3.eventstream_start()
                await c4.eventstream_stop()  # c4 never started one
                assert "eventstream" in s.state.plugins
                await c3.eventstream_stop()
                assert "eventstream" not in s.state.plugins
