"""Control-plane self-profiling tests (diagnostics/selfprofile.py;
docs/observability.md "Self-profiling"): the wall budget's self-time
semantics, the control-plane sampler's phase stamping and boundaries,
the stall watchdog, the shared-watcher lifecycle, profiler stop()
flushing, scope-aware ``Scheduler.get_profile``, and the ``/profile``
routes on both roles."""

from __future__ import annotations

import asyncio
import json
import threading
import time as _time

from distributed_tpu import config
from distributed_tpu.diagnostics.profile import (
    Profiler,
    _SharedWatcher,
    create,
    merge,
    process,
)
from distributed_tpu.diagnostics.selfprofile import (
    ControlPlaneProfiler,
    LoopWatchdog,
    WallBudget,
    profile_records,
    profile_to_speedscope,
)

from conftest import gen_test


# ------------------------------------------------------------ WallBudget


def test_wall_budget_self_time_nesting():
    """Entering a child phase pauses the parent: totals are SELF time,
    and the sum of self times equals the inclusive wall."""
    fake = [0.0]
    budget = WallBudget(clock=lambda: fake[0])
    budget.push("engine.drain", "stim-1")
    fake[0] = 1.0
    budget.push("engine.scalar-arm:waiting,processing", "stim-1")
    fake[0] = 1.5
    budget.pop()
    fake[0] = 2.0
    budget.pop()
    totals = budget.snapshot()
    assert totals["engine.drain"] == 1.5  # 2.0 inclusive minus 0.5 child
    assert totals["engine.scalar-arm:waiting,processing"] == 0.5
    assert budget.snapshot_counts() == {
        "engine.drain": 1,
        "engine.scalar-arm:waiting,processing": 1,
    }
    # balanced stack: the thread is outside every phase again
    assert budget.current(threading.get_ident()) == ("", "")
    # unbalanced pop never corrupts the accumulators
    budget.pop()
    assert budget.snapshot() == totals


def test_wall_budget_active_visible_cross_thread():
    budget = WallBudget()
    seen = {}
    ready = threading.Event()
    release = threading.Event()

    def worker():
        budget.push("kernel.dispatch", "stim-k")
        ready.set()
        release.wait(5)
        budget.pop()

    t = threading.Thread(target=worker)
    t.start()
    assert ready.wait(5)
    seen = budget.current(t.ident)
    release.set()
    t.join()
    assert seen == ("kernel.dispatch", "stim-k")
    assert budget.current(t.ident) == ("", "")


def test_wall_budget_phase_context_restores_on_error():
    budget = WallBudget()
    try:
        with budget.phase("egress.flush"):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert budget.current(threading.get_ident()) == ("", "")
    assert budget.snapshot_counts()["egress.flush"] == 1


# ---------------------------------------------------- profiler mechanics


def test_profiler_stop_flushes_current_cycle():
    """stop() must fold the in-flight cycle into history — a short-lived
    profiler (shorter than one cycle) must not lose its samples."""
    p = Profiler(interval=0.001, cycle=60.0)  # cycle never rolls on its own
    frame = sys_frame()
    p.start()
    p._add_sample(frame, 0.0)
    assert not p.history  # still in the current cycle
    p.stop()
    assert len(p.history) == 1
    assert p.history[0][1]["count"] == 1
    assert p.current["count"] == 0  # flushed, not duplicated
    assert p.get_profile()["count"] == 1


def sys_frame():
    """A real frame object to feed _add_sample directly."""
    import sys

    return sys._getframe()


def test_process_stop_boundary_cuts_outer_frames():
    frame = sys_frame()  # stack: ...pytest... -> this test -> sys_frame
    full = create()
    process(frame, full)
    cut = create()
    process(frame, cut, stop=__file__.rsplit("/", 1)[-1])
    # the boundary file's own frames (and everything outer) are cut:
    # only the root count remains
    assert full["children"], "unbounded process lost the stack"
    assert cut["count"] == 1 and not cut["children"]


def test_control_plane_profiler_stamps_phase_and_counts_idle():
    budget = WallBudget()
    p = ControlPlaneProfiler(
        idents=lambda: [threading.get_ident()], wall=budget,
        interval=0.001, cycle=60.0, stop=None,
    )
    p._last_sample = 0.0
    p._last_cycle = 0.0
    budget.push("engine.drain", "stim-x")
    try:
        p._add_sample(sys_frame(), 1.0, threading.get_ident())
    finally:
        budget.pop()
    assert p.total_samples == 1 and p.idle_samples == 0
    tree = p.get_profile()
    assert "phase:engine.drain" in tree["children"]
    assert list(p.samples) == [(1.0, "engine.drain", "stim-x")]

    # idle selector frames count apart from the tree
    class _Code:
        co_filename = "/usr/lib/python3/selectors.py"
        co_name = "select"

    class _Frame:
        f_code = _Code()
        f_back = None
        f_lineno = 1

    p._add_sample(_Frame(), 2.0, threading.get_ident())
    assert p.idle_samples == 1
    assert p.get_profile()["count"] == 1  # idle sample stayed out


def test_profile_records_and_speedscope_roundtrip():
    budget = WallBudget()
    with budget.phase("engine.drain"):
        pass
    p = ControlPlaneProfiler(
        idents=lambda: [], wall=budget, interval=0.001, cycle=60.0,
    )
    p._last_cycle = 0.0
    with budget.phase("egress.flush"):
        p._add_sample(sys_frame(), 1.0, threading.get_ident())
    records = profile_records("scheduler", p, budget, None)
    kinds = [r["kind"] for r in records]
    assert kinds[0] == "head" and "profile" in kinds and "samples" in kinds
    head = records[0]
    assert "engine.drain" in head["wall_seconds"]
    tree = next(r for r in records if r["kind"] == "profile")["tree"]
    ss = profile_to_speedscope(tree)
    json.dumps(ss)  # must be JSON-safe
    prof = ss["profiles"][0]
    assert prof["samples"] and len(prof["samples"]) == len(prof["weights"])
    assert sum(prof["weights"]) == tree["count"]
    # every sample's frame indices are valid
    nframes = len(ss["shared"]["frames"])
    assert all(0 <= i < nframes for s in prof["samples"] for i in s)


# ------------------------------------------------- shared-watcher lifecycle


def _wait_for(cond, timeout=5.0):
    deadline = _time.monotonic() + timeout
    while _time.monotonic() < deadline:
        if cond():
            return True
        _time.sleep(0.01)
    return False


def test_shared_watcher_register_unregister_and_thread_exit():
    """A fresh watcher spins its sampler thread up on first register,
    lingers briefly after the last unregister, exits, and restarts on
    re-registration (profile.py _SharedWatcher._run)."""
    w = _SharedWatcher()
    p = Profiler(interval=0.005, cycle=60.0, idents=lambda: [])
    p._last_sample = 0.0
    p._last_cycle = 0.0
    w.register(p)
    t1 = w._thread
    assert t1 is not None and t1.is_alive()
    w.unregister(p)
    # linger is 0.5s: the thread must exit after it
    assert _wait_for(lambda: not t1.is_alive(), timeout=3.0)
    # re-registration restarts a fresh sampler thread
    w.register(p)
    t2 = w._thread
    assert t2 is not None and t2.is_alive() and t2 is not t1
    w.unregister(p)
    assert _wait_for(lambda: not t2.is_alive(), timeout=3.0)


def test_shared_watcher_broken_idents_drops_only_offender():
    """A broken _due_idents callback must drop THAT profiler and leave
    the rest sampling (profile.py:137-141)."""
    w = _SharedWatcher()
    ident = threading.get_ident()

    healthy = Profiler(interval=0.005, cycle=60.0, idents=lambda: [ident])

    def broken_idents():
        raise RuntimeError("boom")

    broken = Profiler(interval=0.005, cycle=60.0, idents=broken_idents)
    for p in (healthy, broken):
        p._last_sample = 0.0
        p._last_cycle = _time.monotonic()
    w.register(healthy)
    w.register(broken)
    try:
        # the broken profiler is unregistered by the watcher; the
        # healthy one keeps accumulating samples of this (busy) thread
        assert _wait_for(lambda: broken not in w._profilers)
        assert healthy in w._profilers
        before = healthy.get_profile()["count"]
        assert _wait_for(
            lambda: healthy.get_profile()["count"] > before
        ), "healthy profiler stopped sampling after the offender was dropped"
    finally:
        w.unregister(healthy)
        w.unregister(broken)


# ----------------------------------------------------------- stall watchdog


def test_loop_watchdog_single_capture_per_episode():
    from distributed_tpu.tracing import FlightRecorder

    budget = WallBudget()
    tr = FlightRecorder(enabled=True, ring_size=64)
    wd = LoopWatchdog(
        trace=tr, wall=budget, interval=0.01, stall_threshold=0.08
    )
    blocked = threading.Event()

    def fake_loop():
        for _ in range(3):
            wd.tick()
            _time.sleep(0.01)
        budget.push("engine.drain", "stim-stall")
        blocked.set()
        _time.sleep(0.3)  # the stall: 0.3s >> threshold 0.08s
        budget.pop()
        for _ in range(10):  # recovered and ticking: no second capture
            wd.tick()
            _time.sleep(0.02)

    t = threading.Thread(target=fake_loop)
    t.start()
    blocked.wait(5)
    wd.start(t.ident)
    t.join()
    wd.stop()
    assert wd.stalls_total == 1
    stall = wd.stalls[0]
    assert stall["phase"] == "engine.drain"
    assert stall["stim"] == "stim-stall"
    assert "fake_loop" in stall["traceback"]
    events = [e for e in tr.tail() if e["cat"] == "stall"]
    assert len(events) == 1
    assert events[0]["name"] == "engine.drain"
    assert "fake_loop" in events[0]["key"]
    assert events[0]["n"] >= 80  # lag in ms, at least the threshold


# ------------------------------------------------------------- live cluster


@gen_test()
async def test_profile_routes_and_get_profile_scope():
    """Both roles serve /profile JSONL; Scheduler.get_profile grows a
    scope= arg whose 'scheduler' scope includes the control-plane tree
    without touching workers."""
    from test_observability import http_get, new_cluster

    from distributed_tpu.client.client import Client

    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            await c.gather(c.map(lambda x: x + 1, range(20)))
            sched = cluster.scheduler
            assert sched.cp_profiler is not None
            assert sched.watchdog is not None and sched.watchdog.ticks_total >= 0
            # keep the loop busy until the 20ms sampler catches at least
            # one NON-idle control-plane stack (idle select() samples
            # deliberately stay out of the tree)
            for _ in range(200):
                if sched.cp_profiler.get_profile()["count"] > 0:
                    break
                await c.gather(c.map(lambda x: x + 1, range(50)))
            own = await sched.get_profile(scope="scheduler")
            merged = await sched.get_profile(scope="all")
            workers_only = await sched.get_profile(scope="workers")
            assert own["count"] > 0
            assert merged["count"] >= own["count"]
            assert merged["count"] >= workers_only["count"]
            try:
                await sched.get_profile(scope="nope")
            except ValueError:
                pass
            else:
                raise AssertionError("bad scope accepted")

            # wall budget recorded the engine seams on the live path
            wall = sched.state.wall.snapshot()
            assert wall.get("engine.drain", 0.0) > 0.0
            assert "egress.flush" in wall

            # /profile routes on both roles
            status, body = await http_get(
                sched.http_server.port, "/profile"
            )
            assert status == 200
            records = [
                json.loads(ln) for ln in body.decode().splitlines() if ln
            ]
            assert records[0]["kind"] == "head"
            assert records[0]["role"] == "scheduler"
            assert "engine.drain" in records[0]["wall_seconds"]
            assert any(r["kind"] == "profile" for r in records)
            worker = cluster.workers[0]
            status, body = await http_get(
                worker.http_server.port, "/profile"
            )
            assert status == 200
            wrecords = [
                json.loads(ln) for ln in body.decode().splitlines() if ln
            ]
            assert wrecords[0]["role"] == "worker"
            which = {
                r.get("which") for r in wrecords if r["kind"] == "profile"
            }
            assert {"loop", "exec"} <= which

            # metrics expose the new families on both roles
            for port in (sched.http_server.port, worker.http_server.port):
                status, body = await http_get(port, "/metrics")
                text = body.decode()
                assert "dtpu_wall_seconds_total" in text
                assert "dtpu_loop_lag_seconds_bucket" in text
                assert "dtpu_profile_samples_total" in text

            # cluster dump carries the profile tail
            dump = await sched.get_cluster_state()
            prof = dump["scheduler"]["profile"]
            assert "wall_seconds" in prof and "tree" in prof
            slim = await sched.get_cluster_state(exclude=["profile"])
            assert "profile" not in slim["scheduler"]


# ------------------------------------------------------------- config gate


@gen_test()
async def test_selfprofile_disabled_leaves_no_machinery():
    """scheduler.profile.enabled=False: no sampler, no watchdog — the
    knob is the kill switch for constrained hosts."""
    from test_observability import new_cluster

    with config.set({"scheduler.profile.enabled": False}):
        async with await new_cluster() as cluster:
            assert cluster.scheduler.cp_profiler is None
            assert cluster.scheduler.watchdog is None
            worker = cluster.workers[0]
            assert worker.cp_profiler is None
            assert worker.watchdog is None


def test_merge_keeps_phase_pseudo_nodes():
    a = create()
    a["count"] = 2
    a["children"]["phase:engine.drain"] = {
        "count": 2, "children": {},
        "identifier": "phase:engine.drain",
        "description": "phase:engine.drain",
    }
    b = create()
    b["count"] = 3
    b["children"]["phase:engine.drain"] = {
        "count": 3, "children": {},
        "identifier": "phase:engine.drain",
        "description": "phase:engine.drain",
    }
    m = merge(a, b)
    assert m["count"] == 5
    assert m["children"]["phase:engine.drain"]["count"] == 5
