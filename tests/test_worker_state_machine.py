"""Tier-1 deterministic tests of the worker state machine (reference
test_worker_state_machine.py style: drive a bare WorkerState with synthetic
events, assert on returned Instructions and the transition log)."""

from __future__ import annotations

import pytest

from distributed_tpu.worker.state_machine import (
    AddKeysMsg,
    ComputeTaskEvent,
    Execute,
    ExecuteFailureEvent,
    ExecuteSuccessEvent,
    FreeKeysEvent,
    GatherDep,
    GatherDepBusyEvent,
    GatherDepFailureEvent,
    GatherDepNetworkFailureEvent,
    GatherDepSuccessEvent,
    LongRunningEvent,
    LongRunningMsg,
    MissingDataMsg,
    PauseEvent,
    RefreshWhoHasEvent,
    RequestRefreshWhoHasMsg,
    RetryBusyWorkerEvent,
    RetryBusyWorkerLater,
    StealRequestEvent,
    StealResponseMsg,
    TaskErredMsg,
    TaskFinishedMsg,
    UnpauseEvent,
    UpdateDataEvent,
    WorkerState,
    FindMissingEvent,
)


@pytest.fixture
def ws():
    state = WorkerState(nthreads=2, address="tcp://self:1", validate=True)
    yield state
    state.validate_state()


def finish_exec(ws, key, value=42, nbytes=8):
    return ws.handle_stimulus(
        ExecuteSuccessEvent(
            stimulus_id="s-done", key=key, value=value, start=1.0, stop=2.0,
            nbytes=nbytes, type="int",
        )
    )


def test_gather_dep_local_failure_errs_flight_directly(ws):
    """Regression (state-machine lint, rule 9): a local failure while
    receiving (deserialization error) must take the direct
    (flight, error) edge.  Pre-fix there was no such table entry, so the
    released fallback routed flight->released — which parks the task in
    `cancelled` with previous="flight" left stale — and the
    cancelled->error hop then ran executing-exit semantics, releasing
    execution resources the fetch never held."""
    ws.available_resources = {"gpu": 1.0}
    ws.total_resources = {"gpu": 1.0}
    instrs = ws.handle_stimulus(
        ComputeTaskEvent.dummy(
            "y", priority=(0,),
            who_has={"dep": ["tcp://peer:1"]}, nbytes={"dep": 8},
        )
    )
    assert any(isinstance(i, GatherDep) for i in instrs)
    dep = ws.tasks["dep"]
    assert dep.state == "flight"
    instrs = ws.handle_stimulus(
        GatherDepFailureEvent(
            stimulus_id="s-fail", worker="tcp://peer:1", keys=("dep",),
            exception=ValueError("bad frame"), traceback=None,
        )
    )
    assert dep.state == "error"
    # direct hop: no stale cancelled detour, no stale previous marker
    assert dep.previous is None
    hops = [(start, finish) for key, start, finish, _ in ws.story("dep")
            if key == "dep"]
    assert ("flight", "error") in hops
    assert all("cancelled" not in hop for hop in hops)
    assert any(isinstance(i, TaskErredMsg) for i in instrs)
    # the fetch held no execution resources; none may be released
    assert ws.available_resources == {"gpu": 1.0}
    # unwedge for the fixture's validate: drop the dependent + dep
    ws.handle_stimulus(FreeKeysEvent(stimulus_id="s-free", keys=("y", "dep")))


def test_simple_execution(ws):
    instrs = ws.handle_stimulus(ComputeTaskEvent.dummy("x", priority=(0,)))
    assert [type(i) for i in instrs] == [Execute]
    assert ws.tasks["x"].state == "executing"
    instrs = finish_exec(ws, "x")
    assert [type(i) for i in instrs] == [TaskFinishedMsg]
    assert ws.tasks["x"].state == "memory"
    assert ws.data["x"] == 42


def test_execution_failure(ws):
    ws.handle_stimulus(ComputeTaskEvent.dummy("x", priority=(0,)))
    instrs = ws.handle_stimulus(
        ExecuteFailureEvent(
            stimulus_id="s-err", key="x", exception=ValueError("boom"),
            exception_text="boom",
        )
    )
    assert [type(i) for i in instrs] == [TaskErredMsg]
    assert ws.tasks["x"].state == "error"


def test_thread_slots_respected(ws):
    for i in range(5):
        ws.handle_stimulus(ComputeTaskEvent.dummy(f"t{i}", priority=(i,)))
    states = [ws.tasks[f"t{i}"].state for i in range(5)]
    assert states.count("executing") == 2  # nthreads=2
    assert states.count("ready") == 3
    # finishing one starts the next by priority
    finish_exec(ws, "t0")
    assert ws.tasks["t2"].state == "executing"


def test_priority_order(ws):
    ws.handle_stimulus(PauseEvent(stimulus_id="p"))
    for key, pri in [("low", (9,)), ("high", (1,)), ("mid", (5,))]:
        ws.handle_stimulus(ComputeTaskEvent.dummy(key, priority=pri))
    instrs = ws.handle_stimulus(UnpauseEvent(stimulus_id="u"))
    keys = [i.key for i in instrs if isinstance(i, Execute)]
    assert keys == ["high", "mid"]  # two slots, best priorities first


def test_dependency_fetch_flow(ws):
    """compute-task with a remote dep: fetch -> flight -> memory -> execute."""
    instrs = ws.handle_stimulus(
        ComputeTaskEvent.dummy(
            "y",
            priority=(0,),
            who_has={"dep": ["tcp://peer:1"]},
            nbytes={"dep": 100},
        )
    )
    gd = [i for i in instrs if isinstance(i, GatherDep)]
    assert len(gd) == 1
    assert gd[0].worker == "tcp://peer:1"
    assert gd[0].to_gather == ("dep",)
    assert ws.tasks["dep"].state == "flight"
    assert ws.tasks["y"].state == "waiting"

    instrs = ws.handle_stimulus(
        GatherDepSuccessEvent(
            stimulus_id="s-gd", worker="tcp://peer:1", data={"dep": 7},
            total_nbytes=100,
        )
    )
    assert ws.tasks["dep"].state == "memory"
    assert any(isinstance(i, AddKeysMsg) for i in instrs)
    assert any(isinstance(i, Execute) and i.key == "y" for i in instrs)
    finish_exec(ws, "y")
    assert ws.tasks["y"].state == "memory"


def test_gather_batching_respects_byte_limit():
    ws = WorkerState(nthreads=1, validate=True, transfer_message_bytes_limit=150)
    who_has = {f"d{i}": ["tcp://peer:1"] for i in range(4)}
    nbytes = {f"d{i}": 100 for i in range(4)}
    instrs = ws.handle_stimulus(
        ComputeTaskEvent.dummy("y", priority=(0,), who_has=who_has, nbytes=nbytes)
    )
    gds = [i for i in instrs if isinstance(i, GatherDep)]
    # 100+100 > 150 -> one key per message, but only 1 concurrent per peer
    assert len(gds) == 1
    assert len(gds[0].to_gather) == 1


def test_gather_spreads_across_peers(ws):
    who_has = {"d1": ["tcp://p1:1"], "d2": ["tcp://p2:1"]}
    instrs = ws.handle_stimulus(
        ComputeTaskEvent.dummy("y", priority=(0,), who_has=who_has,
                               nbytes={"d1": 10, "d2": 10})
    )
    gds = [i for i in instrs if isinstance(i, GatherDep)]
    assert {g.worker for g in gds} == {"tcp://p1:1", "tcp://p2:1"}


def test_busy_peer_retry(ws):
    ws.handle_stimulus(
        ComputeTaskEvent.dummy("y", priority=(0,),
                               who_has={"dep": ["tcp://peer:1"]},
                               nbytes={"dep": 10})
    )
    instrs = ws.handle_stimulus(
        GatherDepBusyEvent(stimulus_id="s-busy", worker="tcp://peer:1",
                           keys=("dep",))
    )
    assert any(isinstance(i, RetryBusyWorkerLater) for i in instrs)
    assert ws.tasks["dep"].state == "fetch"  # requeued
    assert "tcp://peer:1" in ws.busy_workers
    # retry clears busy and re-issues the gather
    instrs = ws.handle_stimulus(
        RetryBusyWorkerEvent(stimulus_id="s-retry", worker="tcp://peer:1")
    )
    assert any(isinstance(i, GatherDep) for i in instrs)


def test_network_failure_reroutes(ws):
    ws.handle_stimulus(
        ComputeTaskEvent.dummy(
            "y", priority=(0,),
            who_has={"dep": ["tcp://p1:1", "tcp://p2:1"]},
            nbytes={"dep": 10},
        )
    )
    flight_worker = ws.tasks["dep"].coming_from
    other = ({"tcp://p1:1", "tcp://p2:1"} - {flight_worker}).pop()
    instrs = ws.handle_stimulus(
        GatherDepNetworkFailureEvent(
            stimulus_id="s-net", worker=flight_worker, keys=("dep",)
        )
    )
    assert any(isinstance(i, MissingDataMsg) for i in instrs)
    # rerouted to the surviving peer
    gds = [i for i in instrs if isinstance(i, GatherDep)]
    assert gds and gds[0].worker == other


def test_missing_then_refresh(ws):
    ws.handle_stimulus(
        ComputeTaskEvent.dummy("y", priority=(0,),
                               who_has={"dep": ["tcp://p1:1"]},
                               nbytes={"dep": 10})
    )
    ws.handle_stimulus(
        GatherDepNetworkFailureEvent(stimulus_id="s", worker="tcp://p1:1",
                                     keys=("dep",))
    )
    assert ws.tasks["dep"].state == "missing"
    instrs = ws.handle_stimulus(FindMissingEvent(stimulus_id="fm"))
    assert any(isinstance(i, RequestRefreshWhoHasMsg) for i in instrs)
    instrs = ws.handle_stimulus(
        RefreshWhoHasEvent(stimulus_id="r", who_has={"dep": ["tcp://p3:1"]})
    )
    gds = [i for i in instrs if isinstance(i, GatherDep)]
    assert gds and gds[0].worker == "tcp://p3:1"


def test_steal_request_ready_task():
    ws = WorkerState(nthreads=1, validate=True)
    ws.handle_stimulus(ComputeTaskEvent.dummy("a", priority=(0,)))
    ws.handle_stimulus(ComputeTaskEvent.dummy("b", priority=(1,)))
    assert ws.tasks["b"].state == "ready"
    instrs = ws.handle_stimulus(StealRequestEvent(stimulus_id="st", key="b"))
    resp = [i for i in instrs if isinstance(i, StealResponseMsg)]
    assert resp[0].state == "ready"
    assert "b" not in ws.tasks  # released + forgotten


def test_steal_request_executing_task_is_refused(ws):
    ws.handle_stimulus(ComputeTaskEvent.dummy("a", priority=(0,)))
    instrs = ws.handle_stimulus(StealRequestEvent(stimulus_id="st", key="a"))
    resp = [i for i in instrs if isinstance(i, StealResponseMsg)]
    assert resp[0].state == "executing"
    assert ws.tasks["a"].state == "executing"  # not given up


def test_cancel_executing_goes_cancelled(ws):
    ws.handle_stimulus(ComputeTaskEvent.dummy("x", priority=(0,)))
    ws.handle_stimulus(FreeKeysEvent(stimulus_id="free", keys=("x",)))
    assert ws.tasks["x"].state == "cancelled"
    # completion of a cancelled task drops the result silently
    instrs = finish_exec(ws, "x")
    assert not any(isinstance(i, TaskFinishedMsg) for i in instrs)
    assert "x" not in ws.tasks
    assert "x" not in ws.data


def test_cancel_ready_released_immediately():
    ws = WorkerState(nthreads=1, validate=True)
    ws.handle_stimulus(ComputeTaskEvent.dummy("a", priority=(0,)))
    ws.handle_stimulus(ComputeTaskEvent.dummy("b", priority=(1,)))
    ws.handle_stimulus(FreeKeysEvent(stimulus_id="free", keys=("b",)))
    assert "b" not in ws.tasks


def test_pause_stops_execution_and_gathers(ws):
    ws.handle_stimulus(PauseEvent(stimulus_id="p"))
    instrs = ws.handle_stimulus(
        ComputeTaskEvent.dummy("x", priority=(0,),
                               who_has={"d": ["tcp://p:1"]}, nbytes={"d": 1})
    )
    assert not any(isinstance(i, (Execute, GatherDep)) for i in instrs)
    instrs = ws.handle_stimulus(UnpauseEvent(stimulus_id="u"))
    assert any(isinstance(i, GatherDep) for i in instrs)


def test_long_running_frees_slot():
    ws = WorkerState(nthreads=1, validate=True)
    ws.handle_stimulus(ComputeTaskEvent.dummy("a", priority=(0,)))
    ws.handle_stimulus(ComputeTaskEvent.dummy("b", priority=(1,)))
    assert ws.tasks["b"].state == "ready"
    instrs = ws.handle_stimulus(
        LongRunningEvent(stimulus_id="lr", key="a", compute_duration=1.0)
    )
    assert any(isinstance(i, LongRunningMsg) for i in instrs)
    assert ws.tasks["a"].state == "long-running"
    assert ws.tasks["b"].state == "executing"  # slot freed
    finish_exec(ws, "a")
    assert ws.tasks["a"].state == "memory"


def test_update_data(ws):
    instrs = ws.handle_stimulus(
        UpdateDataEvent(stimulus_id="ud", data={"k": 123})
    )
    assert any(isinstance(i, AddKeysMsg) for i in instrs)
    assert ws.data["k"] == 123
    assert ws.tasks["k"].state == "memory"


def test_resources_constrain_execution():
    ws = WorkerState(nthreads=4, validate=True, resources={"GPU": 1})
    ws.handle_stimulus(
        ComputeTaskEvent.dummy("g1", priority=(0,),
                               resource_restrictions={"GPU": 1})
    )
    ws.handle_stimulus(
        ComputeTaskEvent.dummy("g2", priority=(1,),
                               resource_restrictions={"GPU": 1})
    )
    assert ws.tasks["g1"].state == "executing"
    assert ws.tasks["g2"].state == "constrained"  # GPU exhausted
    finish_exec(ws, "g1")
    assert ws.tasks["g2"].state == "executing"
    assert ws.available_resources["GPU"] == 0


def test_story(ws):
    ws.handle_stimulus(ComputeTaskEvent.dummy("x", priority=(0,)))
    finish_exec(ws, "x")
    transitions = [(t[1], t[2]) for t in ws.story("x")]
    assert ("released", "waiting") in transitions
    assert ("ready", "executing") in transitions or ("waiting", "ready") in transitions
    assert ("executing", "memory") in transitions


def test_deterministic_stimulus_log(ws):
    ws.handle_stimulus(ComputeTaskEvent.dummy("x", priority=(0,)))
    assert len(ws.stimulus_log) == 1


def test_cancelled_flight_data_not_announced(ws):
    """A fetch cancelled mid-flight whose data still arrives must NOT send
    AddKeysMsg: the value is dropped, and announcing it would plant a
    phantom replica in the scheduler that peers then fetch forever (the
    round-3 tensordot livelock)."""
    ws.handle_stimulus(
        ComputeTaskEvent.dummy(
            "y", priority=(0,),
            who_has={"dep": ["tcp://peer:1"]}, nbytes={"dep": 100},
        )
    )
    assert ws.tasks["dep"].state == "flight"
    # scheduler frees the dependent -> dep fetch is cancelled mid-flight
    ws.handle_stimulus(FreeKeysEvent(stimulus_id="s-free", keys=("y", "dep")))
    assert ws.tasks["dep"].state == "cancelled"
    instrs = ws.handle_stimulus(
        GatherDepSuccessEvent(
            stimulus_id="s-gd", worker="tcp://peer:1", data={"dep": 7},
            total_nbytes=100,
        )
    )
    assert not any(isinstance(i, AddKeysMsg) for i in instrs)
    assert "dep" not in ws.data


def test_gather_success_missing_key_notifies_scheduler(ws):
    """Requested-but-not-received keys must emit MissingDataMsg so the
    scheduler drops the stale replica — otherwise refresh-who-has keeps
    pointing this worker back at the same errant peer (livelock)."""
    ws.handle_stimulus(
        ComputeTaskEvent.dummy(
            "y", priority=(0,),
            who_has={"dep": ["tcp://peer:1"], "dep2": ["tcp://peer:1"]},
            nbytes={"dep": 100, "dep2": 100},
        )
    )
    assert ws.tasks["dep"].state == "flight"
    assert ws.tasks["dep2"].state == "flight"
    # peer serves only dep2: it no longer holds dep
    instrs = ws.handle_stimulus(
        GatherDepSuccessEvent(
            stimulus_id="s-gd", worker="tcp://peer:1", data={"dep2": 7},
            total_nbytes=100,
        )
    )
    md = [i for i in instrs if isinstance(i, MissingDataMsg)]
    assert [m.key for m in md] == ["dep"]
    assert md[0].errant_worker == "tcp://peer:1"
    # no replicas left anywhere -> missing (find_missing will refresh)
    assert ws.tasks["dep"].state == "missing"
    assert ws.tasks["dep2"].state == "memory"


def test_compute_cancel_recompute_before_first_tick():
    """Server-level race: Execute instruction issued, but the task is
    released AND re-requested before the _execute coroutine's first tick.
    The (single) execution must still run and complete the resumed task —
    bailing out for state=='resumed' wedges the task forever (the
    round-3 mid-shuffle restart hang)."""
    import asyncio

    from distributed_tpu.worker.server import Worker

    async def main():
        from distributed_tpu.rpc.core import Status

        w = Worker.__new__(Worker)  # bare worker: no comms needed
        from distributed_tpu.worker.state_machine import WorkerState as WS

        w.state = WS(nthreads=1, address="tcp://self:1", validate=True)
        w.state.running = True
        w.data = w.state.data
        w._async_instructions = set()
        w.status = Status.running
        from concurrent.futures import ThreadPoolExecutor

        w.executor = ThreadPoolExecutor(1)
        w.batched_stream = type(
            "B", (), {"send": staticmethod(lambda msg: None)}
        )()
        w.digest_metric = lambda name, value: None
        from distributed_tpu.worker.metrics import FineMetrics

        w.fine_metrics = FineMetrics()
        # inline fast-path state normally set in Worker.__init__
        w._inline_threshold = 0.0
        w._prefix_inner_ema = {}
        w._inline_window_t0 = 0.0
        w._inline_spent = 0.0

        # 1. compute-task -> Execute instruction (coroutine created but
        #    not yet ticked)
        w.handle_stimulus(ComputeTaskEvent.dummy("x", priority=(0,)))
        assert w.state.tasks["x"].state == "executing"
        # 2. released then re-requested BEFORE the loop runs the coroutine
        w.handle_stimulus(FreeKeysEvent(stimulus_id="s-free", keys=("x",)))
        assert w.state.tasks["x"].state == "cancelled"
        w.handle_stimulus(ComputeTaskEvent.dummy("x", priority=(0,)))
        # the cancellation is forgotten: the task reverts straight to
        # executing (reference wsm.py:2157) and the original (not yet
        # ticked) execution must complete it
        assert w.state.tasks["x"].state == "executing"
        # 3. let the coroutine run: it must execute and complete the task
        for _ in range(100):
            await asyncio.sleep(0.01)
            if w.state.tasks["x"].state == "memory":
                break
        assert w.state.tasks["x"].state == "memory", w.state.tasks["x"].state
        w.executor.shutdown(wait=False)

    asyncio.run(main())


def test_reschedule_releases_and_notifies(ws):
    """An executing task that raises Reschedule goes back to the
    scheduler (reference wsm test_reschedule)."""
    from distributed_tpu.worker.state_machine import (
        RescheduleEvent,
        RescheduleMsg,
    )

    ws.handle_stimulus(ComputeTaskEvent.dummy("r1", priority=(0,)))
    assert ws.tasks["r1"].state == "executing"
    instrs = ws.handle_stimulus(RescheduleEvent(stimulus_id="s-res", key="r1"))
    assert [type(i) for i in instrs] == [RescheduleMsg]
    assert "r1" not in ws.data
    assert ws.tasks.get("r1") is None or ws.tasks["r1"].state == "released"


def test_acquire_replicas_fetches_and_announces(ws):
    """AMM acquire-replicas: the worker fetches keys it was told about
    and announces them on arrival (reference wsm.py AcquireReplicas)."""
    from distributed_tpu.worker.state_machine import AcquireReplicasEvent

    instrs = ws.handle_stimulus(
        AcquireReplicasEvent(
            stimulus_id="s-acq",
            who_has={"rep": ["tcp://peer:1"]},
            nbytes={"rep": 8},
        )
    )
    gathers = [i for i in instrs if isinstance(i, GatherDep)]
    assert len(gathers) == 1
    assert ws.tasks["rep"].state == "flight"
    instrs = ws.handle_stimulus(
        GatherDepSuccessEvent(
            stimulus_id="s-got", worker="tcp://peer:1",
            data={"rep": 123}, total_nbytes=8,
        )
    )
    assert ws.data["rep"] == 123
    assert ws.tasks["rep"].state == "memory"
    assert any(isinstance(i, AddKeysMsg) for i in instrs)


def test_remove_replicas_drops_unwanted_data(ws):
    """AMM remove-replicas drops keys no dependent needs."""
    from distributed_tpu.worker.state_machine import RemoveReplicasEvent

    ws.handle_stimulus(
        UpdateDataEvent(stimulus_id="s-up", data={"d1": 1, "d2": 2},
                        report=False)
    )
    assert ws.data["d1"] == 1
    ws.handle_stimulus(RemoveReplicasEvent(stimulus_id="s-rm", keys=("d1",)))
    assert "d1" not in ws.data
    assert "d2" in ws.data
    ws.validate_state()


def test_gather_dep_failure_errors_dependents(ws):
    """A local failure while receiving (e.g. deserialization) errors the
    dependent instead of retrying forever (reference wsm.py
    GatherDepFailureEvent)."""
    from distributed_tpu.worker.state_machine import GatherDepFailureEvent

    ws.handle_stimulus(
        ComputeTaskEvent.dummy(
            "child-g", priority=(0,),
            who_has={"parent-g": ["tcp://peer:1"]}, nbytes={"parent-g": 8},
        )
    )
    assert ws.tasks["parent-g"].state == "flight"
    instrs = ws.handle_stimulus(
        GatherDepFailureEvent(
            stimulus_id="s-fail", worker="tcp://peer:1", keys=("parent-g",),
            exception=TypeError("cannot deserialize"),
        )
    )
    assert ws.tasks["parent-g"].state == "error"
    # the dependent cannot run; it reports erred to the scheduler
    assert any(isinstance(i, TaskErredMsg) for i in instrs)


def test_compute_with_data_already_local_skips_fetch(ws):
    """Dependencies already in memory never produce a GatherDep."""
    ws.handle_stimulus(
        UpdateDataEvent(stimulus_id="s-up", data={"dep-l": 7}, report=False)
    )
    instrs = ws.handle_stimulus(
        ComputeTaskEvent.dummy(
            "child-l", priority=(0,),
            who_has={"dep-l": ["tcp://peer:1"]}, nbytes={"dep-l": 8},
        )
    )
    assert not [i for i in instrs if isinstance(i, GatherDep)]
    assert ws.tasks["child-l"].state == "executing"


def test_free_keys_in_flight_then_late_arrival_dropped(ws):
    """free-keys for an in-flight fetch: the arriving payload must not
    resurrect the task (cancelled-flight contract)."""
    ws.handle_stimulus(
        ComputeTaskEvent.dummy(
            "child-f", priority=(0,),
            who_has={"dep-f": ["tcp://peer:1"]}, nbytes={"dep-f": 8},
        )
    )
    assert ws.tasks["dep-f"].state == "flight"
    ws.handle_stimulus(
        FreeKeysEvent(stimulus_id="s-free", keys=("child-f", "dep-f"))
    )
    instrs = ws.handle_stimulus(
        GatherDepSuccessEvent(
            stimulus_id="s-late", worker="tcp://peer:1",
            data={"dep-f": 9}, total_nbytes=8,
        )
    )
    assert "dep-f" not in ws.data or ws.tasks.get("dep-f") is None
    assert not any(isinstance(i, AddKeysMsg) for i in instrs)
    ws.validate_state()


def test_secede_of_cancelled_task_frees_slot(ws):
    """A cancelled-but-still-running task that secedes must release its
    execution slot (the shuffle deadlock fix depends on it): previous
    flips to long-running and queued work starts."""
    from distributed_tpu.worker.state_machine import LongRunningEvent

    ws.handle_stimulus(ComputeTaskEvent.dummy("c0", priority=(0,)))
    ws.handle_stimulus(ComputeTaskEvent.dummy("c1", priority=(1,)))
    ws.handle_stimulus(ComputeTaskEvent.dummy("c2", priority=(2,)))
    assert ws.tasks["c2"].state == "ready"  # both slots busy
    # cancel c0 while it runs: stays in 'cancelled', slot still held
    ws.handle_stimulus(FreeKeysEvent(stimulus_id="s-free", keys=("c0",)))
    assert ws.tasks["c0"].state == "cancelled"
    assert ws.tasks["c2"].state == "ready"
    # the running body secedes: slot frees, c2 starts
    instrs = ws.handle_stimulus(
        LongRunningEvent(stimulus_id="s-sec", key="c0", compute_duration=0.0)
    )
    assert ws.tasks["c0"].previous == "long-running"
    assert ws.tasks["c2"].state == "executing"
    # eventual completion of the cancelled body is still clean
    finish_exec(ws, "c0")
    ws.validate_state()


def test_long_running_task_error_and_steal_refusal(ws):
    """A seceded (long-running) task still errs cleanly, and a steal
    request for it is refused like an executing task."""
    from distributed_tpu.worker.state_machine import LongRunningEvent

    ws.handle_stimulus(ComputeTaskEvent.dummy("lr1", priority=(0,)))
    ws.handle_stimulus(
        LongRunningEvent(stimulus_id="s-sec", key="lr1", compute_duration=0.0)
    )
    assert ws.tasks["lr1"].state == "long-running"
    # a steal request must be refused: the body is running
    instrs = ws.handle_stimulus(
        StealRequestEvent(stimulus_id="s-steal", key="lr1")
    )
    responses = [i for i in instrs if isinstance(i, StealResponseMsg)]
    assert responses and responses[0].state in ("long-running", "executing")
    assert ws.tasks["lr1"].state == "long-running"
    # and an eventual failure still routes to error
    instrs = ws.handle_stimulus(
        ExecuteFailureEvent(
            stimulus_id="s-err", key="lr1", exception=RuntimeError("boom"),
            exception_text="boom",
        )
    )
    assert any(isinstance(i, TaskErredMsg) for i in instrs)
    assert ws.tasks["lr1"].state == "error"
    ws.validate_state()


def test_execute_pipeline_gates_on_duration():
    """The pipeline extension over-fills slots ONLY with tasks whose
    duration estimate is tiny; unknown (0.5 default) or big estimates
    and actors stop the pipeline at the queue head (priority order is
    preserved — nothing is skipped over)."""
    from distributed_tpu.worker.state_machine import Execute

    ws = WorkerState(nthreads=1, validate=True, execute_pipeline=8,
                     execute_pipeline_threshold=0.005)
    # one long task fills the real slot; tiny tasks pipeline behind it
    instrs = ws.handle_stimulus(
        ComputeTaskEvent.dummy("big", priority=(0,), duration=1.0),
        ComputeTaskEvent.dummy("t1", priority=(1,), duration=0.0001),
        ComputeTaskEvent.dummy("t2", priority=(2,), duration=0.0001),
        ComputeTaskEvent.dummy("t3", priority=(3,), duration=0.5),  # unknown
        ComputeTaskEvent.dummy("t4", priority=(4,), duration=0.0001),
    )
    executes = [i.key for i in instrs if isinstance(i, Execute)]
    # big takes the slot, t1/t2 pipeline, t3 (unknown) blocks the rest
    assert executes == ["big", "t1", "t2"], executes
    assert ws.tasks["t3"].state == "ready"
    assert ws.tasks["t4"].state == "ready"
    ws.validate_state()


def test_execute_pipeline_disabled_by_default():
    from distributed_tpu.worker.state_machine import Execute

    ws = WorkerState(nthreads=1, validate=True)
    instrs = ws.handle_stimulus(
        ComputeTaskEvent.dummy("a", priority=(0,), duration=0.0001),
        ComputeTaskEvent.dummy("b", priority=(1,), duration=0.0001),
    )
    executes = [i.key for i in instrs if isinstance(i, Execute)]
    assert executes == ["a"], executes
    ws.validate_state()


def test_pipelined_task_steal_refused_and_cancel_discards():
    """Edge cases of the execute-pipeline extension: a pipelined task is
    in 'executing' (queued in the thread) so a steal request is refused
    like a running task; a free-keys for it routes through cancelled and
    its eventual ExecuteSuccess is discarded, not stored."""
    from distributed_tpu.worker.state_machine import (
        Execute,
        ExecuteSuccessEvent,
    )

    ws = WorkerState(nthreads=1, validate=True, execute_pipeline=8,
                     execute_pipeline_threshold=0.005)
    instrs = ws.handle_stimulus(
        ComputeTaskEvent.dummy("p1", priority=(0,), duration=0.0001),
        ComputeTaskEvent.dummy("p2", priority=(1,), duration=0.0001),
        ComputeTaskEvent.dummy("p3", priority=(2,), duration=0.0001),
    )
    executes = [i.key for i in instrs if isinstance(i, Execute)]
    assert executes == ["p1", "p2", "p3"]

    # steal request against the PIPELINED (not yet running) p3: refused
    # with its live state, exactly like a truly-executing task
    instrs = ws.handle_stimulus(StealRequestEvent(stimulus_id="s", key="p3"))
    resp = [i for i in instrs if isinstance(i, StealResponseMsg)]
    assert resp and resp[0].state == "executing"

    # scheduler frees p2 while the batch is in flight
    ws.handle_stimulus(FreeKeysEvent(stimulus_id="free", keys=("p2",)))
    assert ws.tasks["p2"].state == "cancelled"

    # batch completes: p1 stored; p2's result discarded (stays out of
    # data); p3 stored
    for key in ("p1", "p2", "p3"):
        ws.handle_stimulus(ExecuteSuccessEvent(
            stimulus_id="done", key=key, value=42, start=0.0, stop=0.001,
            nbytes=28, type="int",
        ))
    assert "p1" in ws.data and "p3" in ws.data
    assert "p2" not in ws.data
    assert ws.tasks.get("p2") is None or ws.tasks["p2"].state in (
        "released", "forgotten"
    )
    ws.validate_state()


def test_pipeline_respects_priority_order():
    """Pipelined Executes are issued strictly in priority order; a
    higher-priority arrival AFTER the batch was issued waits for the
    next slot opening but is not overtaken by later tiny tasks."""
    from distributed_tpu.worker.state_machine import (
        Execute,
        ExecuteSuccessEvent,
    )

    ws = WorkerState(nthreads=1, validate=True, execute_pipeline=2,
                     execute_pipeline_threshold=0.005)
    instrs = ws.handle_stimulus(
        ComputeTaskEvent.dummy("a", priority=(5,), duration=0.0001),
        ComputeTaskEvent.dummy("b", priority=(6,), duration=0.0001),
        ComputeTaskEvent.dummy("c", priority=(7,), duration=0.0001),
        ComputeTaskEvent.dummy("d", priority=(8,), duration=0.0001),
    )
    first = [i.key for i in instrs if isinstance(i, Execute)]
    assert first == ["a", "b", "c"]  # 1 slot + pipeline depth 2
    # urgent task arrives while the batch runs
    instrs = ws.handle_stimulus(
        ComputeTaskEvent.dummy("urgent", priority=(0,), duration=0.0001)
    )
    assert not [i for i in instrs if isinstance(i, Execute)]  # full
    instrs = ws.handle_stimulus(ExecuteSuccessEvent(
        stimulus_id="d1", key="a", value=1, start=0.0, stop=0.001,
        nbytes=28, type="int",
    ))
    nxt = [i.key for i in instrs if isinstance(i, Execute)]
    assert nxt == ["urgent"], nxt  # beats d despite arriving later
    ws.validate_state()
