"""Parity tests: JAX placement kernels vs a straight-line float32 python
oracle replicating the reference's decide_worker/worker_objective semantics
(scheduler.py:8550, 3131).  Runs on the 8-device CPU mesh from conftest."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tpu.ops.placement import (
    PlacementBatch,
    WorkerArrays,
    build_batch_arrays,
    decide_workers,
    occupancy_after_finish,
    pad_to_bucket,
    place_rootish,
)
from distributed_tpu.ops.wavefront import GraphArrays, PlacementResult, place_graph, validate_placement

BW = 100e6


def random_problem(rng, B=50, W=8, D=30, E=120, restrict_frac=0.0):
    occ = rng.uniform(0, 5, W).astype(np.float32)
    threads = rng.integers(1, 5, W).astype(np.int32)
    wnbytes = rng.uniform(0, 1e9, W).astype(np.float32)
    running = np.ones(W, bool)
    running[rng.random(W) < 0.2] = False
    if not running.any():
        running[0] = True
    durations = rng.uniform(0.001, 1.0, B).astype(np.float32)
    dep_bytes = rng.uniform(1e3, 1e8, D).astype(np.float32)
    has = rng.random((D, W)) < 0.3
    edge_task = rng.integers(0, B, E).astype(np.int32)
    edge_dep = rng.integers(0, D, E).astype(np.int32)
    restrict = None
    if restrict_frac:
        restrict = np.ones((B, W), bool)
        mask_rows = rng.random(B) < restrict_frac
        for i in np.flatnonzero(mask_rows):
            allowed = rng.random(W) < 0.4
            restrict[i] = allowed
    workers = WorkerArrays(
        nthreads=jnp.asarray(threads),
        occupancy=jnp.asarray(occ),
        nbytes=jnp.asarray(wnbytes),
        running=jnp.asarray(running),
    )
    batch = build_batch_arrays(durations, (edge_task, edge_dep), dep_bytes, has,
                               restrict=restrict)
    raw = dict(
        occ=occ, threads=threads, wnbytes=wnbytes, running=running,
        durations=durations, dep_bytes=dep_bytes, has=has,
        edge_task=edge_task, edge_dep=edge_dep, restrict=restrict,
    )
    return workers, batch, raw


def oracle_sequential(raw, bandwidth=BW):
    """Float32 replica of the reference decide_worker loop."""
    B = len(raw["durations"])
    W = len(raw["threads"])
    occ = raw["occ"].copy()
    thr = np.maximum(raw["threads"], 1).astype(np.float32)
    inv_bw = np.float32(1.0 / bandwidth)
    # per-task dep lists
    deps = [[] for _ in range(B)]
    for t, d in zip(raw["edge_task"], raw["edge_dep"]):
        deps[t].append(d)
    out = np.full(B, -1, np.int32)
    for t in range(B):
        missing = np.zeros(W, np.float32)
        holder = np.zeros(W, bool)
        for d in deps[t]:
            missing += np.float32(raw["dep_bytes"][d]) * (~raw["has"][d])
            holder |= raw["has"][d]
        holder &= raw["running"]
        cand = holder if holder.any() else raw["running"].copy()
        if raw["restrict"] is not None:
            r = cand & raw["restrict"][t]
            if not r.any():
                r = raw["restrict"][t] & raw["running"]
            cand = r
        if not cand.any():
            continue
        cost = occ / thr + missing * inv_bw
        best = min(
            np.flatnonzero(cand),
            key=lambda w: (cost[w], raw["wnbytes"][w], w),
        )
        out[t] = best
        # raw seconds booked; divide once at compare (reference :3140)
        occ[best] += np.float32(raw["durations"][t]) + missing[best] * inv_bw
    return out, occ


@pytest.mark.parametrize("seed", range(5))
def test_decide_workers_matches_oracle(seed):
    rng = np.random.default_rng(seed)
    workers, batch, raw = random_problem(rng)
    assign, occ = decide_workers(workers, batch, BW, sequential=True)
    expected, occ_expected = oracle_sequential(raw)
    B = len(raw["durations"])
    np.testing.assert_array_equal(np.asarray(assign)[:B], expected)
    np.testing.assert_allclose(np.asarray(occ), occ_expected, rtol=1e-5)


@pytest.mark.parametrize("seed", range(3))
def test_decide_workers_with_restrictions(seed):
    rng = np.random.default_rng(100 + seed)
    workers, batch, raw = random_problem(rng, restrict_frac=0.5)
    assign, _ = decide_workers(workers, batch, BW, sequential=True)
    expected, _ = oracle_sequential(raw)
    B = len(raw["durations"])
    np.testing.assert_array_equal(np.asarray(assign)[:B], expected)


def test_decide_workers_parallel_mode_valid():
    rng = np.random.default_rng(7)
    workers, batch, raw = random_problem(rng, B=100)
    assign, occ = decide_workers(workers, batch, BW, sequential=False)
    a = np.asarray(assign)[:100]
    assert (a >= 0).all()
    assert raw["running"][a].all()  # never places on stopped workers


def test_padding_rows_unassigned():
    rng = np.random.default_rng(3)
    workers, batch, raw = random_problem(rng, B=10)
    assert batch.duration.shape[0] == pad_to_bucket(10)
    assign, _ = decide_workers(workers, batch, BW, sequential=True)
    assert (np.asarray(assign)[10:] == -1).all()


def test_place_rootish_balanced():
    W = 8
    threads = np.array([2, 2, 2, 2, 4, 4, 1, 1], np.int32)
    running = np.ones(W, bool)
    running[3] = False
    workers = WorkerArrays(
        nthreads=jnp.asarray(threads),
        occupancy=jnp.zeros(W, jnp.float32),
        nbytes=jnp.zeros(W, jnp.float32),
        running=jnp.asarray(running),
    )
    n = 160
    assign = np.asarray(place_rootish(jnp.int32(n), workers, max_tasks=256))
    live = assign[:n]
    assert (live >= 0).all()
    assert not (live == 3).any()  # stopped worker skipped
    counts = np.bincount(live, minlength=W)
    # proportional to threads (2,2,2,0,4,4,1,1 = 16 capacity for 160 tasks)
    expected = threads * np.where(running, 1, 0) * 10
    assert (np.abs(counts - expected) <= 16).all(), (counts, expected)
    # contiguity: siblings co-assigned in blocks (like tg.last_worker)
    changes = (np.diff(live) != 0).sum()
    assert changes <= len(np.unique(live))  # one contiguous block per worker
    assert (assign[n:] == -1).all()


def test_occupancy_after_finish():
    occ = jnp.asarray(np.array([5.0, 3.0, 1.0], np.float32))
    threads = jnp.asarray(np.array([2, 1, 1], np.int32))
    fw = jnp.asarray(np.array([0, 0, 1, -1], np.int32))
    fd = jnp.asarray(np.array([2.0, 2.0, 1.0, 99.0], np.float32))
    out = np.asarray(occupancy_after_finish(occ, threads, fw, fd))
    # raw-seconds booking: worker 0 releases 4.0, worker 1 releases 1.0
    np.testing.assert_allclose(out, [1.0, 2.0, 1.0])


# ---------------------------------------------------------- wavefront

def chain_graph(n=50):
    durations = np.ones(n, np.float32)
    out_bytes = np.full(n, 1e6, np.float32)
    src = np.arange(n - 1, dtype=np.int64)
    dst = src + 1
    return GraphArrays.from_arrays(durations, out_bytes, src, dst,
                                   pad_tasks=n + 1, pad_edges=n)


def mapreduce_graph(width=64, reducers=8):
    """width roots -> reducers -> 1 total."""
    n = width + reducers + 1
    durations = np.ones(n, np.float32)
    out_bytes = np.full(n, 1e6, np.float32)
    src, dst = [], []
    per = width // reducers
    for r in range(reducers):
        for i in range(r * per, (r + 1) * per):
            src.append(i)
            dst.append(width + r)
    for r in range(reducers):
        src.append(width + r)
        dst.append(width + reducers)
    return n, GraphArrays.from_arrays(
        durations, out_bytes,
        np.asarray(src, np.int64), np.asarray(dst, np.int64),
        pad_tasks=n + 7, pad_edges=len(src) + 5,
    )


def _workers(W=4, threads=2):
    return (
        jnp.full(W, threads, jnp.int32),
        jnp.zeros(W, jnp.float32),
        jnp.ones(W, bool),
    )


def test_wavefront_chain():
    g = chain_graph(50)
    nthreads, occ, running = _workers(4)
    res = place_graph(g, nthreads, occ, running, bandwidth=BW)
    validate_placement(g, res, np.asarray(running))
    assert int(res.n_waves) == 50  # one wave per chain link
    a = np.asarray(res.assignment)[:50]
    # locality: the chain should stay on one worker (heavy-dep following)
    assert len(np.unique(a)) == 1


def test_wavefront_mapreduce():
    n, g = mapreduce_graph(64, 8)
    nthreads, occ, running = _workers(8, threads=2)
    res = place_graph(g, nthreads, occ, running, bandwidth=BW)
    validate_placement(g, res, np.asarray(running))
    assert int(res.n_waves) == 3
    a = np.asarray(res.assignment)
    roots = a[:64]
    counts = np.bincount(roots, minlength=8)
    assert counts.max() <= 2 * counts.min() + 2, counts  # spread evenly
    # each reducer lands with its heaviest input (one of its 8 feeders)
    for r in range(8):
        feeders = set(roots[r * 8:(r + 1) * 8])
        assert a[64 + r] in feeders


def test_wavefront_respects_stopped_workers():
    n, g = mapreduce_graph(32, 4)
    nthreads, occ, running = _workers(4)
    running = running.at[2].set(False)
    res = place_graph(g, nthreads, occ, running, bandwidth=BW)
    a = np.asarray(res.assignment)
    valid = np.asarray(g.valid)
    assert not (a[valid] == 2).any()


def test_wavefront_random_dag():
    rng = np.random.default_rng(0)
    n = 500
    durations = rng.uniform(0.01, 1, n).astype(np.float32)
    out_bytes = rng.uniform(1e3, 1e7, n).astype(np.float32)
    src, dst = [], []
    for t in range(1, n):
        for d in rng.integers(0, t, rng.integers(0, 3)):
            src.append(d)
            dst.append(t)
    g = GraphArrays.from_arrays(
        durations, out_bytes,
        np.asarray(src, np.int64), np.asarray(dst, np.int64),
        pad_tasks=512, pad_edges=pad_to_bucket(len(src)),
    )
    nthreads, occ, running = _workers(16)
    res = place_graph(g, nthreads, occ, running, bandwidth=BW)
    validate_placement(g, res, np.asarray(running))
    # placement must track dependency order: start[dst] >= start[src] is not
    # guaranteed by the model, but wave count must be <= depth bound
    assert 1 <= int(res.n_waves) <= n


# ---------------------------------------------------------- sharded

def test_sharded_matches_single_device():
    from distributed_tpu.parallel.mesh import make_mesh, sharded_decide_workers

    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device mesh")
    rng = np.random.default_rng(42)
    workers, batch, raw = random_problem(rng, B=64, W=16, D=32, E=200)
    mesh = make_mesh(8)
    sharded = sharded_decide_workers(mesh, workers, batch, BW)
    single, _ = decide_workers(workers, batch, BW, sequential=False)
    np.testing.assert_array_equal(np.asarray(sharded), np.asarray(single))


def test_make_mesh_shapes():
    from distributed_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8)
    assert mesh.shape["tasks"] * mesh.shape["workers"] == 8


def test_sharded_leveled_matches_single_device():
    """The sharded (data-parallel over waves, psum/all_gather per wave)
    leveled engine must reproduce the single-device engine the live
    scheduler runs (parallel/mesh.py place_graph_leveled_sharded)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from distributed_tpu.ops.leveled import pack_graph, place_graph_leveled
    from distributed_tpu.parallel.mesh import place_graph_leveled_sharded

    rng = np.random.default_rng(0)
    T, W = 512, 16
    dur = rng.uniform(0.01, 1, T).astype(np.float32)
    ob = rng.uniform(1e3, 1e6, T).astype(np.float32)
    src, dst = [], []
    for t in range(1, T):
        for d in rng.integers(0, t, rng.integers(0, 3)):
            src.append(int(d))
            dst.append(t)
    src = np.asarray(src, np.int32)
    dst = np.asarray(dst, np.int32)
    packed = pack_graph(dur, ob, src, dst)
    nth = np.full(W, 2, np.int32)
    occ = rng.uniform(0, 0.5, W).astype(np.float32)
    run = np.ones(W, bool)

    n_dev = min(8, len(jax.devices()))
    mesh = Mesh(np.asarray(jax.devices()[:n_dev]), ("tasks",))
    a_sh, load_sh = place_graph_leveled_sharded(mesh, packed, nth, occ, run)
    res = place_graph_leveled(packed, nth, occ, run)
    assert (a_sh >= 0).all() and (a_sh < W).all()
    # identical decisions (same math; psum order differences only shift
    # float ties, which this graph does not exercise)
    agree = (a_sh == res.assignment).mean()
    assert agree > 0.99, agree
    np.testing.assert_allclose(load_sh, res.occupancy, rtol=0.15, atol=1.0)
