"""P2P shuffle tests (reference shuffle/tests patterns)."""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.local import LocalCluster
from distributed_tpu.shuffle import p2p_rechunk, p2p_shuffle

from conftest import gen_test


async def new_cluster(n_workers=3, **kwargs):
    cluster = LocalCluster(
        n_workers=n_workers,
        scheduler_kwargs={"validate": True},
        worker_kwargs={"validate": True},
        **kwargs,
    )
    await cluster._start()
    return cluster


def make_partition(seed, n=50):
    rng = np.random.default_rng(seed)
    return [int(x) for x in rng.integers(0, 10_000, n)]


@gen_test(timeout=120)
async def test_hash_shuffle_repartitions_all_records():
    async with await new_cluster(n_workers=3) as cluster:
        async with Client(cluster.scheduler_address) as c:
            inputs = [
                c.submit(make_partition, i, key=f"input-{i}") for i in range(4)
            ]
            await c.gather(inputs)
            outs = await p2p_shuffle(c, inputs, npartitions_out=5)
            results = await asyncio.wait_for(c.gather(outs), 60)
            # every record lands in exactly one output partition
            all_in = sorted(x for i in range(4) for x in make_partition(i))
            all_out = sorted(x for part in results for x in part)
            assert all_out == all_in
            # and in the right partition
            for j, part in enumerate(results):
                assert all(hash(x) % 5 == j for x in part)


@gen_test(timeout=120)
async def test_keyed_shuffle_groups_by_key():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            def mk(i):
                return [(k, i * 100 + n) for n, k in enumerate("abcd")]

            inputs = [c.submit(mk, i, key=f"kin-{i}") for i in range(3)]
            await c.gather(inputs)
            outs = await p2p_shuffle(
                c, inputs, npartitions_out=4, key=lambda rec: rec[0]
            )
            results = await asyncio.wait_for(c.gather(outs), 60)
            # all records with the same key land in the same partition
            for part in results:
                keys_here = {rec[0] for rec in part}
                for k in keys_here:
                    total_with_k = sum(
                        1 for p in results for rec in p if rec[0] == k
                    )
                    here_with_k = sum(1 for rec in part if rec[0] == k)
                    assert total_with_k == here_with_k == 3


@gen_test(timeout=120)
async def test_shuffle_outputs_respect_worker_assignment():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            inputs = [
                c.submit(make_partition, i, key=f"wi-{i}") for i in range(2)
            ]
            await c.gather(inputs)
            outs = await p2p_shuffle(c, inputs, npartitions_out=4)
            await asyncio.wait_for(c.gather(outs), 60)
            # unpack tasks are pinned round-robin over the two workers
            wh = await c.who_has(outs)
            held = {addr for holders in wh.values() for addr in holders}
            assert len(held) == 2


@gen_test(timeout=120)
async def test_rechunk_1d():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            def mk_chunk(lo, n):
                return np.arange(lo, lo + n)

            chunk_sizes = [30, 30, 40]
            offsets = [0, 30, 60]
            chunks = [
                c.submit(mk_chunk, offsets[i], chunk_sizes[i], key=f"ch-{i}")
                for i in range(3)
            ]
            await c.gather(chunks)
            new_sizes = [25, 25, 25, 25]
            outs = await p2p_rechunk(c, chunks, chunk_sizes, new_sizes)
            results = await asyncio.wait_for(c.gather(outs), 60)
            assert [len(r) for r in results] == new_sizes
            np.testing.assert_array_equal(
                np.concatenate(results), np.arange(100)
            )


@gen_test(timeout=120)
async def test_shuffle_run_id_fencing():
    """A stale epoch's shards are rejected after a newer run starts."""
    async with await new_cluster(n_workers=1) as cluster:
        worker = cluster.workers[0]
        from distributed_tpu.shuffle.core import ShuffleSpec

        spec1 = ShuffleSpec("sx", 1, 2, {0: worker.address, 1: worker.address})
        spec2 = ShuffleSpec("sx", 2, 2, {0: worker.address, 1: worker.address})
        ext = worker.shuffle
        run1 = ext.get_or_create(spec1)
        run2 = ext.get_or_create(spec2)  # supersedes run1
        assert run1.closed
        resp = await ext.shuffle_receive(
            id="sx", run_id=1, spec=spec1.to_msg(),
            shards={0: [(0, [1, 2])]},
        )
        assert resp["status"] == "stale"
        resp = await ext.shuffle_receive(
            id="sx", run_id=2, spec=spec2.to_msg(),
            shards={0: [(0, [3])]},
        )
        assert resp["status"] == "OK"
        assert await run2.store.read(0) == [(0, [3])]


@gen_test(timeout=120)
async def test_transfer_only_worker_shards_flushed_before_unpack():
    """ADVICE r2 (high): a worker that runs transfers but owns no output
    partitions has its outbound shard buffer still draining when the
    barrier fires.  The barrier must broadcast inputs_done to ALL
    participants (not just output owners) and each must flush its comms
    before acknowledging — otherwise unpack silently drops rows
    (reference _core.py:272, _scheduler_plugin.py:95)."""
    from distributed_tpu.shuffle.core import ShuffleRun

    orig_send = ShuffleRun._send_to_peer

    async def slow_send(self, addr, shards):
        await asyncio.sleep(0.3)  # keep shards in flight past the barrier
        await orig_send(self, addr, shards)

    ShuffleRun._send_to_peer = slow_send
    try:
        async with await new_cluster(n_workers=3) as cluster:
            async with Client(cluster.scheduler_address) as c:
                addrs = sorted(cluster.scheduler.state.workers)
                transfer_only = addrs[2]  # 2 outputs -> owners = addrs[:2]
                inputs = [
                    c.submit(make_partition, i, key=f"tfo-{i}",
                             workers=[transfer_only])
                    for i in range(4)
                ]
                await c.gather(inputs)
                outs = await p2p_shuffle(c, inputs, npartitions_out=2)
                results = await asyncio.wait_for(c.gather(outs), 60)
                ext = cluster.scheduler.extensions["shuffle"]
                st = next(iter(ext.active.values()))
                assert transfer_only in st.participants
                all_in = sorted(x for i in range(4) for x in make_partition(i))
                all_out = sorted(x for part in results for x in part)
                assert all_out == all_in
    finally:
        ShuffleRun._send_to_peer = orig_send


@gen_test(timeout=90)
async def test_columnar_shuffle_roundtrip():
    """p2p_shuffle_arrays: columnar partitions hash-partitioned on a key
    column, every row lands in exactly one output, co-keyed rows land
    together (reference _shuffle.py:617 arrow path equivalent)."""
    import numpy as np

    from distributed_tpu.shuffle import p2p_shuffle_arrays

    def make_part(i, n=5000):
        rng = np.random.default_rng(i)
        return {
            "key": rng.integers(0, 1000, n).astype(np.int64),
            "value": rng.random(n),
        }

    async with await new_cluster(n_workers=3) as cluster:
        async with Client(cluster.scheduler_address) as c:
            parts = c.map(make_part, range(6))
            await c.gather(parts)
            outs = await p2p_shuffle_arrays(c, parts, npartitions_out=4,
                                            on="key")
            results = await c.gather(outs)
            total = sum(len(p["key"]) for p in results)
            assert total == 6 * 5000
            # same key never in two outputs
            seen: dict[int, int] = {}
            for j, p in enumerate(results):
                for k in np.unique(p["key"]):
                    assert seen.setdefault(int(k), j) == j
            # row integrity: multiset of (key, value) preserved
            want = sorted(
                (int(k), float(v))
                for i in range(6)
                for k, v in zip(make_part(i)["key"], make_part(i)["value"])
            )
            got = sorted(
                (int(k), float(v))
                for p in results
                for k, v in zip(p["key"], p["value"])
            )
            assert got == want


def test_columnar_split_and_concat():
    import numpy as np

    from distributed_tpu.shuffle.columnar import (
        concat_arrays,
        split_arrays_by_hash,
    )

    rng = np.random.default_rng(0)
    part = {
        "key": rng.integers(0, 100, 1000).astype(np.int64),
        "x": rng.random(1000),
    }
    out = split_arrays_by_hash(part, 7, on="key")
    assert sum(len(s["key"]) for s in out.values()) == 1000
    back = concat_arrays([s for _, s in sorted(out.items())])
    assert sorted(back["key"].tolist()) == sorted(part["key"].tolist())
    # deterministic: same key -> same partition across calls/processes
    out2 = split_arrays_by_hash(part, 7, on="key")
    assert {j: s["key"].tolist() for j, s in out.items()} == \
        {j: s["key"].tolist() for j, s in out2.items()}


def test_columnar_string_keys_fall_back():
    import numpy as np

    from distributed_tpu.shuffle.columnar import split_arrays_by_hash

    part = {
        "key": np.asarray(["a", "b", "c", "a", "b"] * 10),
        "v": np.arange(50),
    }
    out = split_arrays_by_hash(part, 3, on="key")
    assert sum(len(s["v"]) for s in out.values()) == 50
    # all rows of one key share a partition
    for s in out.values():
        for k in np.unique(s["key"]):
            total = (part["key"] == k).sum()
            assert (s["key"] == k).sum() == total


def test_join_arrays_semantics():
    import numpy as np

    from distributed_tpu.shuffle.columnar import join_arrays

    left = {"key": np.asarray([1, 2, 2, 3]), "lv": np.asarray([10.0, 20.0, 21.0, 30.0])}
    right = {"key": np.asarray([2, 2, 4]), "rv": np.asarray([200.0, 201.0, 400.0])}
    inner = join_arrays(left, right, "key", "inner")
    got = sorted(zip(inner["key"].tolist(), inner["lv"].tolist(), inner["rv"].tolist()))
    assert got == [(2, 20.0, 200.0), (2, 20.0, 201.0),
                   (2, 21.0, 200.0), (2, 21.0, 201.0)]
    lj = join_arrays(left, right, "key", "left")
    assert sorted(lj["key"].tolist()) == [1, 2, 2, 2, 2, 3]
    assert np.isnan(lj["rv"][lj["key"] == 1]).all()
    oj = join_arrays(left, right, "key", "outer")
    assert sorted(oj["key"].tolist()) == [1, 2, 2, 2, 2, 3, 4]
    rj = join_arrays(left, right, "key", "right")
    assert sorted(rj["key"].tolist()) == [2, 2, 2, 2, 4]


@gen_test(timeout=90)
async def test_p2p_merge_arrays_live():
    import numpy as np

    from distributed_tpu.shuffle import p2p_merge_arrays

    def lpart(i, n=2000):
        rng = np.random.default_rng(i)
        return {"key": rng.integers(0, 500, n).astype(np.int64),
                "lv": rng.random(n)}

    def rpart(i, n=2000):
        rng = np.random.default_rng(100 + i)
        return {"key": rng.integers(0, 500, n).astype(np.int64),
                "rv": rng.random(n)}

    async with await new_cluster(n_workers=3) as cluster:
        async with Client(cluster.scheduler_address) as c:
            lf = c.map(lpart, range(4))
            rf = c.map(rpart, range(4))
            await c.gather(lf + rf)
            outs = await p2p_merge_arrays(c, lf, rf, on="key", how="inner")
            results = await c.gather(outs)
            total = sum(len(p["key"]) for p in results)
            # oracle: per-key count product
            from collections import Counter

            lc = Counter(int(k) for i in range(4) for k in lpart(i)["key"])
            rc = Counter(int(k) for i in range(4) for k in rpart(i)["key"])
            want = sum(lc[k] * rc[k] for k in lc)
            assert total == want


def test_join_arrays_empty_sides():
    import numpy as np

    from distributed_tpu.shuffle.columnar import join_arrays

    right = {"key": np.asarray([1, 2]), "rv": np.asarray([1.0, 2.0])}
    for how in ("inner", "left", "right", "outer"):
        out = join_arrays({}, right, "key", how)
        n = len(out.get("key", ()))
        assert n == (2 if how in ("right", "outer") else 0), (how, out)
    out = join_arrays({}, {}, "key", "outer")
    assert len(out.get("key", ())) == 0
    # -0.0 and 0.0 co-locate
    from distributed_tpu.shuffle.columnar import split_arrays_by_hash

    part = {"key": np.asarray([0.0, -0.0, 1.5]), "v": np.arange(3.0)}
    out = split_arrays_by_hash(part, 8, on="key")
    for s in out.values():
        if 0.0 in s["key"]:
            assert (s["key"] == 0.0).sum() == 2


# ------------------------------------------------- lifecycle hardening


@gen_test(timeout=60)
async def test_multi_worker_loss_coalesces_to_one_restart():
    """Three participants leaving inside the debounce window must bump
    the epoch ONCE, not once per departure (the restart-storm fix;
    contrast reference _scheduler_plugin.py:336-344 which restarts per
    event)."""
    async with await new_cluster(n_workers=4) as cluster:
        sched = cluster.scheduler
        ext = sched.extensions["shuffle"]
        resp = await ext.handle_get_or_create(
            id="s-coalesce", npartitions_out=8, n_inputs=4
        )
        assert resp["status"] == "OK"
        st = ext.active["s-coalesce"]
        assert st.run_id == 1
        victims = sorted(set(st.worker_for.values()))[:3]
        assert len(victims) == 3
        for addr in victims:
            await sched.remove_worker(addr, reason="test-scale-down")
        await asyncio.sleep(ext.restart_debounce * 6 + 0.05)
        assert st.run_id == 2, "3 departures must coalesce into 1 restart"
        # survivors own every output partition now
        assert not set(st.worker_for.values()) & set(victims)


@gen_test(timeout=60)
async def test_shuffle_during_scheduler_close_aborts_cleanly():
    """Worker departures during Scheduler.close() must not spawn epoch
    restarts (shutdown is not recovery)."""
    async with await new_cluster(n_workers=3) as cluster:
        sched = cluster.scheduler
        ext = sched.extensions["shuffle"]
        await ext.handle_get_or_create(
            id="s-closing", npartitions_out=4, n_inputs=2
        )
        st = ext.active["s-closing"]
    # cluster context exit closed workers + scheduler
    assert ext.active == {}
    assert ext._pending_restarts == {}
    assert st.run_id == 1, "no restart may fire during shutdown"


@gen_test(timeout=90)
async def test_shuffle_restart_budget_errs_tasks():
    """A shuffle that keeps restarting past shuffle.max-restarts must err
    its output tasks with P2PShuffleError instead of looping forever."""
    from distributed_tpu import config
    from distributed_tpu.exceptions import P2PShuffleError

    def slow_partition(i):
        import time as _t

        _t.sleep(30)
        return [i]

    with config.set({"shuffle.max-restarts": 2,
                     "shuffle.restart-debounce": "10ms"}):
        async with await new_cluster(n_workers=2) as cluster:
            async with Client(cluster.scheduler_address) as c:
                ext = cluster.scheduler.extensions["shuffle"]
                # inputs never finish, so the shuffle's tasks sit waiting
                # while we exhaust the restart budget
                inputs = [
                    c.submit(slow_partition, i, key=f"slowin-{i}")
                    for i in range(2)
                ]
                outs = await p2p_shuffle(c, inputs, npartitions_out=2)
                sid = outs[0].key.rsplit("-unpack-", 1)[0]
                st = ext.active[sid]
                for _ in range(4):
                    await ext.handle_restart(id=sid, run_id=st.run_id)
                    await asyncio.sleep(0.2)
                    if sid not in ext.active:
                        break
                assert sid not in ext.active, "budget exhaustion must drop it"
                with pytest.raises(P2PShuffleError):
                    await asyncio.wait_for(c.gather(outs), 30)


@gen_test(timeout=90)
async def test_restart_budget_errs_with_transfers_in_memory():
    """Budget exhaustion while transfer tasks sit in MEMORY (the common
    barrier-keeps-failing shape): the memory tasks must not be
    resurrected to waiting (which would recreate a zombie shuffle) — the
    outputs err with P2PShuffleError and the shuffle stays dropped."""
    from distributed_tpu import config
    from distributed_tpu.exceptions import P2PShuffleError

    with config.set({"shuffle.max-restarts": 1,
                     "shuffle.restart-debounce": "10ms"}):
        async with await new_cluster(n_workers=2) as cluster:
            async with Client(cluster.scheduler_address) as c:
                sched = cluster.scheduler
                ext = sched.extensions["shuffle"]
                # every barrier RPC fails: transfers complete to memory,
                # the barrier task keeps requesting restarts
                async def failing_barrier(**kwargs):
                    return {"status": "barrier-failed", "error": "induced"}

                sched.handlers["shuffle_barrier"] = failing_barrier
                inputs = [
                    c.submit(make_partition, i, key=f"bin-{i}")
                    for i in range(2)
                ]
                await c.gather(inputs)
                outs = await p2p_shuffle(c, inputs, npartitions_out=2)
                with pytest.raises(P2PShuffleError):
                    await asyncio.wait_for(c.gather(outs), 60)
                sid = outs[0].key.rsplit("-unpack-", 1)[0]
                assert sid not in ext.active, "failed shuffle must stay dropped"
