"""P2P shuffle tests (reference shuffle/tests patterns)."""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.local import LocalCluster
from distributed_tpu.shuffle import p2p_rechunk, p2p_shuffle

from conftest import gen_test


async def new_cluster(n_workers=3, **kwargs):
    cluster = LocalCluster(
        n_workers=n_workers,
        scheduler_kwargs={"validate": True},
        worker_kwargs={"validate": True},
        **kwargs,
    )
    await cluster._start()
    return cluster


def make_partition(seed, n=50):
    rng = np.random.default_rng(seed)
    return [int(x) for x in rng.integers(0, 10_000, n)]


@gen_test(timeout=120)
async def test_hash_shuffle_repartitions_all_records():
    async with await new_cluster(n_workers=3) as cluster:
        async with Client(cluster.scheduler_address) as c:
            inputs = [
                c.submit(make_partition, i, key=f"input-{i}") for i in range(4)
            ]
            await c.gather(inputs)
            outs = await p2p_shuffle(c, inputs, npartitions_out=5)
            results = await asyncio.wait_for(c.gather(outs), 60)
            # every record lands in exactly one output partition
            all_in = sorted(x for i in range(4) for x in make_partition(i))
            all_out = sorted(x for part in results for x in part)
            assert all_out == all_in
            # and in the right partition
            for j, part in enumerate(results):
                assert all(hash(x) % 5 == j for x in part)


@gen_test(timeout=120)
async def test_keyed_shuffle_groups_by_key():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            def mk(i):
                return [(k, i * 100 + n) for n, k in enumerate("abcd")]

            inputs = [c.submit(mk, i, key=f"kin-{i}") for i in range(3)]
            await c.gather(inputs)
            outs = await p2p_shuffle(
                c, inputs, npartitions_out=4, key=lambda rec: rec[0]
            )
            results = await asyncio.wait_for(c.gather(outs), 60)
            # all records with the same key land in the same partition
            for part in results:
                keys_here = {rec[0] for rec in part}
                for k in keys_here:
                    total_with_k = sum(
                        1 for p in results for rec in p if rec[0] == k
                    )
                    here_with_k = sum(1 for rec in part if rec[0] == k)
                    assert total_with_k == here_with_k == 3


@gen_test(timeout=120)
async def test_shuffle_outputs_respect_worker_assignment():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            inputs = [
                c.submit(make_partition, i, key=f"wi-{i}") for i in range(2)
            ]
            await c.gather(inputs)
            outs = await p2p_shuffle(c, inputs, npartitions_out=4)
            await asyncio.wait_for(c.gather(outs), 60)
            # unpack tasks are pinned round-robin over the two workers
            wh = await c.who_has(outs)
            held = {addr for holders in wh.values() for addr in holders}
            assert len(held) == 2


@gen_test(timeout=120)
async def test_rechunk_1d():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            def mk_chunk(lo, n):
                return np.arange(lo, lo + n)

            chunk_sizes = [30, 30, 40]
            offsets = [0, 30, 60]
            chunks = [
                c.submit(mk_chunk, offsets[i], chunk_sizes[i], key=f"ch-{i}")
                for i in range(3)
            ]
            await c.gather(chunks)
            new_sizes = [25, 25, 25, 25]
            outs = await p2p_rechunk(c, chunks, chunk_sizes, new_sizes)
            results = await asyncio.wait_for(c.gather(outs), 60)
            assert [len(r) for r in results] == new_sizes
            np.testing.assert_array_equal(
                np.concatenate(results), np.arange(100)
            )


@gen_test(timeout=120)
async def test_shuffle_run_id_fencing():
    """A stale epoch's shards are rejected after a newer run starts."""
    async with await new_cluster(n_workers=1) as cluster:
        worker = cluster.workers[0]
        from distributed_tpu.shuffle.core import ShuffleSpec

        spec1 = ShuffleSpec("sx", 1, 2, {0: worker.address, 1: worker.address})
        spec2 = ShuffleSpec("sx", 2, 2, {0: worker.address, 1: worker.address})
        ext = worker.shuffle
        run1 = ext.get_or_create(spec1)
        run2 = ext.get_or_create(spec2)  # supersedes run1
        assert run1.closed
        resp = await ext.shuffle_receive(
            id="sx", run_id=1, spec=spec1.to_msg(),
            shards={0: [(0, [1, 2])]},
        )
        assert resp["status"] == "stale"
        resp = await ext.shuffle_receive(
            id="sx", run_id=2, spec=spec2.to_msg(),
            shards={0: [(0, [3])]},
        )
        assert resp["status"] == "OK"
        assert await run2.store.read(0) == [(0, [3])]


@gen_test(timeout=120)
async def test_transfer_only_worker_shards_flushed_before_unpack():
    """ADVICE r2 (high): a worker that runs transfers but owns no output
    partitions has its outbound shard buffer still draining when the
    barrier fires.  The barrier must broadcast inputs_done to ALL
    participants (not just output owners) and each must flush its comms
    before acknowledging — otherwise unpack silently drops rows
    (reference _core.py:272, _scheduler_plugin.py:95)."""
    from distributed_tpu.shuffle.core import ShuffleRun

    orig_send = ShuffleRun._send_to_peer

    async def slow_send(self, addr, shards):
        await asyncio.sleep(0.3)  # keep shards in flight past the barrier
        await orig_send(self, addr, shards)

    ShuffleRun._send_to_peer = slow_send
    try:
        async with await new_cluster(n_workers=3) as cluster:
            async with Client(cluster.scheduler_address) as c:
                addrs = sorted(cluster.scheduler.state.workers)
                transfer_only = addrs[2]  # 2 outputs -> owners = addrs[:2]
                inputs = [
                    c.submit(make_partition, i, key=f"tfo-{i}",
                             workers=[transfer_only])
                    for i in range(4)
                ]
                await c.gather(inputs)
                outs = await p2p_shuffle(c, inputs, npartitions_out=2)
                results = await asyncio.wait_for(c.gather(outs), 60)
                ext = cluster.scheduler.extensions["shuffle"]
                st = next(iter(ext.active.values()))
                assert transfer_only in st.participants
                all_in = sorted(x for i in range(4) for x in make_partition(i))
                all_out = sorted(x for part in results for x in part)
                assert all_out == all_in
    finally:
        ShuffleRun._send_to_peer = orig_send
