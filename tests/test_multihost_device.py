"""Multi-host device data plane: a REAL 2-process pod.

Two worker subprocesses join a pod-wide jax runtime
(``jax.distributed.initialize`` via the CLI's ``--jax-coordinator``
flags, 4 virtual CPU devices each = an 8-device global mesh) and run a
device-resident P2P shuffle whose mesh all-to-all executes as an SPMD
collective ACROSS the processes (Gloo on the CPU backend; ICI/DCN on a
TPU pod).  This is the capability the reference's UCX backend provides
per-process via NCCL rendezvous (reference comm/ucx.py:211) — here the
whole exchange is one jitted XLA program.
"""

from __future__ import annotations

import asyncio
import os
import socket
import subprocess
import sys

import pytest

from distributed_tpu import config
from distributed_tpu.client.client import Client
from distributed_tpu.scheduler.server import Scheduler

from conftest import gen_test

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
@gen_test(timeout=300)
async def test_two_process_pod_device_shuffle():
    from distributed_tpu.shuffle.device import p2p_shuffle_device

    # nested defs: pickled BY VALUE (cloudpickle), so the pod worker
    # processes need not import this test module
    def _make_part(i, n_rows):
        """Build partition i's (keys, values) ON global mesh device i —
        pinned to the owning process, so the device index is local."""
        import jax
        import jax.numpy as jnp

        dev = jax.devices()[i]
        keys = jax.device_put(
            jnp.arange(i * n_rows, (i + 1) * n_rows, dtype=jnp.int32), dev
        )
        values = jax.device_put(
            jnp.full((n_rows, 2), float(i), jnp.float32), dev
        )
        return keys, values

    def _to_host(part):
        import numpy as np

        k, v = part
        return np.asarray(k), np.asarray(v)

    coord = f"127.0.0.1:{_free_port()}"
    with config.set({"scheduler.jax.enabled": False}):
        s = Scheduler(listen_addr="tcp://127.0.0.1:0", validate=True)
        await s.start()
        env = dict(
            os.environ,
            PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
            JAX_PLATFORMS="cpu",
        )
        env.pop("XLA_FLAGS", None)  # the worker flag sets the device count
        procs = []
        logs = []
        try:
            import tempfile

            for pid in range(2):
                # log to FILES: an unread PIPE fills and blocks the
                # worker mid-registration (jax/gloo are chatty)
                logf = tempfile.NamedTemporaryFile(
                    prefix=f"pod{pid}-", suffix=".log", delete=False
                )
                logs.append(logf)
                procs.append(subprocess.Popen(
                    [
                        sys.executable, "-m", "distributed_tpu.cli.worker",
                        s.address,
                        "--nthreads", "1",
                        "--name", f"pod{pid}",
                        "--jax-coordinator", coord,
                        "--jax-process-id", str(pid),
                        "--jax-num-processes", "2",
                        "--jax-cpu-devices", "4",
                    ],
                    env=env,
                    stdout=logf,
                    stderr=subprocess.STDOUT,
                ))
            async with Client(s.address) as c:
                # pod bring-up: registration includes the blocking
                # jax.distributed rendezvous of both processes
                deadline = asyncio.get_running_loop().time() + 180
                while len(s.state.workers) < 2:
                    if asyncio.get_running_loop().time() > deadline:
                        for p, lf in zip(procs, logs):
                            p.kill()
                            with open(lf.name, "rb") as f:
                                print(f.read()[-2000:].decode(errors="replace"),
                                      file=sys.stderr)
                        raise TimeoutError("pod workers never registered")
                    await asyncio.sleep(0.2)

                # every worker reported DISJOINT global device ownership
                owners: dict[int, str] = {}
                for ws in s.state.workers.values():
                    devs = ws.extra.get("jax_devices")
                    assert devs is not None and len(devs) == 4, (
                        ws.address, devs,
                    )
                    for d in devs:
                        assert d not in owners
                        owners[d] = ws.address
                assert sorted(owners) == list(range(8))

                # inputs born on their global devices, pinned to owners
                n_rows = 16
                futs = [
                    c.submit(_make_part, i, n_rows,
                             key=f"mkpart-{i}", workers=[owners[i]])
                    for i in range(8)
                ]
                outs = await p2p_shuffle_device(c, futs)
                host = await asyncio.wait_for(
                    c.gather([c.submit(_to_host, o, key=f"host-{j}")
                              for j, o in enumerate(outs)]),
                    120,
                )
                # correctness: every row landed on hash(key) % 8, and
                # all 128 rows survived the cross-process exchange
                import numpy as np

                def mix32(x):
                    z = np.asarray(x, np.uint32)
                    z ^= z >> np.uint32(16)
                    z = (z * np.uint32(0x85EBCA6B)) & np.uint32(0xFFFFFFFF)
                    z ^= z >> np.uint32(13)
                    z = (z * np.uint32(0xC2B2AE35)) & np.uint32(0xFFFFFFFF)
                    z ^= z >> np.uint32(16)
                    return z

                total = 0
                for j, (keys, values) in enumerate(host):
                    total += len(keys)
                    if len(keys):
                        assert (mix32(keys) % 8 == j).all(), j
                assert total == 8 * n_rows
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
            for lf in logs:
                lf.close()
                if not os.environ.get("DTPU_KEEP_POD_LOGS"):
                    os.unlink(lf.name)
            await s.close()
