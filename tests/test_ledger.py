"""Decision–outcome ledger + critical-path attribution (ledger.py,
diagnostics/critical_path.py; docs/observability.md "Decision ledger &
critical-path").

The deterministic core of the ISSUE-12 acceptance surface:

- same-seed simulator runs produce bit-identical ledger digests and
  leave ZERO unjoined/open rows at quiesce (the virtual clock makes
  every decision→outcome join exact);
- ``sim.run_ab`` reports per-arm regret + critical-path attribution,
  with identical digests for identical overrides and real deltas for
  steal on/off;
- on a telemetry-seeded NON-UNIFORM fleet the measured-shadow model's
  aggregate |regret| beats the constants' — the artifact ROADMAP
  item 1's input swap will gate on;
- critical-path attribution sums to the run's virtual makespan within
  1% (``critical_path.check``).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from distributed_tpu import config
from distributed_tpu.diagnostics.critical_path import (
    check,
    critical_path,
    deps_from_dump,
    to_records,
)
from distributed_tpu.ledger import (
    LEDGER_SCHEMA_VERSION,
    ROW_FIELDS,
    DecisionLedger,
)


# --------------------------------------------------------------- unit


def _file(led, key, worker="tcp://w0", src="", n_deps=0, dep_bytes=0,
          pred_c=0.0, pred_m=0.0, kind="placement", supersede=-1):
    return led.file(
        kind, key, "pfx", worker, "stim", pred_c, pred_m, False,
        dep_bytes, n_deps, 0.01, src, "", supersede=supersede,
    )


def test_file_join_basics():
    led = DecisionLedger(size=64, enabled=True)
    t = [0.0]
    led.clock = lambda: t[0]
    h = _file(led, "a", n_deps=1, dep_bytes=1000, pred_c=0.5, pred_m=0.1)
    assert led.filed_total == 1 and led.open_rows == 1
    t[0] = 1.0
    assert led.join_row(h, "memory", "tcp://w0", None, 0.3, None)
    assert led.open_rows == 0 and led.joined_total == 1
    row = led.tail()[-1]
    assert row["type"] == "ledger-row"
    assert row["v"] == LEDGER_SCHEMA_VERSION
    assert row["outcome"] == "memory"
    assert row["compute"] == 0.3
    # regret = (t_join - t_dec - compute) - pred = 0.7 - pred
    assert abs(row["regret_constant"] - 0.2) < 1e-12
    assert abs(row["regret_measured"] - 0.6) < 1e-12
    # a stale handle is a no-op
    assert not led.join_row(h, "memory")


def test_dep_free_rows_skip_regret_fold():
    """Dep-free decisions predict 0 transfer in BOTH models: their rows
    join (realized window intact for the critical path) but observe no
    regret — the aggregates stay a pure transfer-prediction audit."""
    led = DecisionLedger(size=64, enabled=True)
    h = _file(led, "a")  # n_deps=0
    assert led.join_row(h, "memory", "tcp://w0", None, 0.001, None)
    assert led.joined_total == 1
    assert led.summary()["kinds"] == {}
    assert led.tail()[-1]["outcome"] == "memory"


def test_supersede_and_worker_mismatch():
    led = DecisionLedger(size=64, enabled=True)
    h1 = _file(led, "a", worker="tcp://victim")
    h2 = _file(led, "a", worker="tcp://thief", kind="steal",
               supersede=h1)
    assert led.superseded_total == 1
    assert led.tail()[0]["outcome"] == "superseded"
    # the victim finished first: the steal row must NOT absorb the
    # victim's realization
    assert led.join_row(h2, "memory", worker="tcp://victim")
    assert led.outcomes["overtaken"] == 1
    assert led.summary()["kinds"] == {}  # no regret observed


def test_ring_wrap_counts_unjoined():
    led = DecisionLedger(size=4, enabled=True)
    for i in range(10):
        _file(led, f"k{i}")
    assert led.unjoined_total == 10 - 4
    assert led.open_rows == 4
    assert all(r["outcome"] == "" for r in led.tail())


def test_resolve_worker_closes_open_rows():
    led = DecisionLedger(size=64, enabled=True)
    _file(led, "a", worker="tcp://dead")
    keep = _file(led, "b", worker="tcp://alive")
    led.file_amm("amm-repl", "c", "tcp://dead", "s", nbytes=10)
    assert led.resolve_worker("tcp://dead") == 2
    assert led.open_rows == 1
    assert led.outcomes["worker-removed"] == 2
    assert led.join_row(keep, "memory", "tcp://alive")


def test_amm_rows_join_by_key_worker():
    led = DecisionLedger(size=64, enabled=True)
    t = [0.0]
    led.clock = lambda: t[0]
    led.file_amm("amm-repl", "k", "tcp://w1", "s",
                 pred_constant=0.2, pred_measured=0.1, nbytes=100,
                 src="tcp://w0")
    t[0] = 0.5
    assert not led.join_amm("k", "tcp://w2", "replicated")
    assert led.join_amm("k", "tcp://w1", "replicated")
    kinds = led.summary()["kinds"]
    assert kinds["amm-repl"]["count"] == 1
    assert abs(kinds["amm-repl"]["regret_mean_constant"] - 0.3) < 1e-12


def test_metric_lines_unique_and_labeled():
    from distributed_tpu.http.server import ledger_metric_lines

    led = DecisionLedger(size=64, enabled=True)
    h = _file(led, "a", src="tcp://s", n_deps=2, dep_bytes=100,
              pred_c=0.1, pred_m=0.2)
    led.join_row(h, "memory", "tcp://w0", None, 0.0, None)
    lines = ledger_metric_lines(led)
    samples = [
        ln for ln in lines if ln and not ln.startswith("#")
    ]
    assert len(samples) == len(set(s.rsplit(" ", 1)[0] for s in samples))
    assert any('kind="placement",model="constant"' in ln for ln in samples)
    assert any(
        ln.startswith("dtpu_ledger_link_regret_seconds_total")
        for ln in samples
    )


# ------------------------------------------------------ sim determinism


def _build_ab_sim(overrides=None, seed=7):
    """Telemetry-seeded non-uniform fleet: slow, heavily jittered links
    (the constants price them ~5-50x wrong) with the scheduler's link
    EWMAs pre-seeded from the same profile — the regime ROADMAP item 1
    swaps the kernel inputs for."""
    from distributed_tpu.sim import ClusterSim, SyntheticDag
    from distributed_tpu.sim.links import LinkProfile

    links = LinkProfile(bandwidth=2e7, jitter=0.9, seed=seed)
    sim = ClusterSim(
        12, nthreads=2, seed=seed, links=links, validate=True,
        ledger_size=65536, config_overrides=overrides,
    )
    sim.install_digest()
    rows = []
    addrs = list(sim.workers)
    for src in addrs:
        for dst in addrs:
            if src == dst:
                continue
            bw, lat = links._edge(src, dst)
            nb = 10_000_000
            rows.append([src, dst, nb, nb / bw + lat, 4])
    sim.state.telemetry.fold_rows(rows, reporter="")
    trace = SyntheticDag(
        n_layers=6, layer_width=18, fanin=2, seed=seed,
        layers_per_chunk=3, duration_range=(0.001, 0.005),
        nbytes_range=(256_000, 2_000_000),
    )
    return sim, trace


def test_sim_ledger_deterministic_and_fully_joined():
    """Same seed => bit-identical ledger digests; every decision row
    joins by quiesce (zero unjoined, zero open) — the virtual clock
    makes decision→outcome joins exact."""
    reports = []
    digests = []
    for _ in range(2):
        sim, trace = _build_ab_sim()
        trace.start(sim)
        reports.append(sim.run())
        digests.append(sim.state.ledger.digest())
    assert digests[0] == digests[1]
    for rep in reports:
        led = rep["ledger"]
        assert led["filed"] > 0
        assert led["unjoined"] == 0, led
        assert led["open"] == 0, led
        assert led["outcomes"].get("memory", 0) > 0
    assert reports[0]["ledger"] == reports[1]["ledger"]


def test_sim_measured_shadow_regret_beats_constants():
    """THE ROADMAP item 1 calibration artifact: on the telemetry-seeded
    non-uniform fleet the measured-shadow cost model's aggregate
    |regret| is lower than the constants' — the checked input-swap
    gate."""
    sim, trace = _build_ab_sim()
    trace.start(sim)
    rep = sim.run()
    reg = rep["ledger"]["regret_abs_mean"]
    assert reg["measured"] is not None
    assert reg["measured"] < reg["constant"], reg
    # and the rows that priced with measured links say so
    used = [
        r for r in sim.state.ledger.tail()
        if r["outcome"] == "memory" and r["used_measured"]
    ]
    assert used, "no decision was priced over a measured link"


def test_sim_critical_path_sums_to_makespan():
    sim, trace = _build_ab_sim()
    trace.start(sim)
    rep = sim.run()
    cp = sim.critical_path()
    assert cp is not None
    check(cp, tolerance=0.01)
    # t0=0.0 anchors the walk at the virtual epoch, so the path's
    # makespan IS the run's virtual makespan
    assert abs(cp["makespan"] - rep["virtual_makespan_s"]) <= (
        0.01 * rep["virtual_makespan_s"]
    )
    assert cp["attribution"]["compute"] > 0
    assert cp["attribution"]["transfer"] > 0
    # records round-trip for the Perfetto exporter
    recs = to_records(cp)
    assert recs[0]["type"] == "cp-summary"
    segs = [r for r in recs if r["type"] == "cp-segment"]
    assert segs
    for r in segs:
        assert r["t1"] >= r["t0"]


def test_run_ab_reports_regret_and_cp_deltas():
    """run_ab: identical overrides => identical digests AND identical
    ledger reports; steal on/off shows regret + critical-path deltas."""
    from distributed_tpu.sim.ab import run_ab

    def factory():
        # fanin=1 chains cluster hard onto their few input holders:
        # real imbalance, so the steal-on arm reliably steals (the
        # test_sim A/B shape)
        from distributed_tpu.sim import SyntheticDag

        return SyntheticDag(
            n_layers=8, layer_width=40, fanin=1, n_roots=4, seed=9,
        )

    same = run_ab(10, factory, None, None, seed=9, validate=True,
                  ledger_size=65536)
    assert same["a"]["digest"] == same["b"]["digest"]
    assert same["a"]["ledger"] == same["b"]["ledger"]
    assert same["diff"]["virtual_makespan_s"] == 0.0
    assert same["diff"]["regret_abs_mean_constant"] in (0.0, None)
    cp_diff = same["diff"]["critical_path"]
    assert cp_diff is not None
    assert all(abs(v) < 1e-12 for v in cp_diff.values())

    ab = run_ab(
        10, factory,
        {"scheduler.work-stealing": True},
        {"scheduler.work-stealing": False},
        seed=9, validate=True, ledger_size=65536,
    )
    assert ab["a"]["digest"] != ab["b"]["digest"]
    assert ab["a"]["steals"] > 0 and ab["b"]["steals"] == 0
    assert ab["diff"]["critical_path"] is not None
    # per-arm regret reports exist (the steal-on arm has steal-kind
    # regret rows; the steal-off arm has none)
    assert "steal" in ab["a"]["ledger"]["kinds"]
    assert "steal" not in ab["b"]["ledger"]["kinds"]


def test_sim_ab_arm_critical_path_in_report():
    from distributed_tpu.sim.ab import run_policy

    def factory():
        from distributed_tpu.sim import SyntheticDag

        return SyntheticDag(
            n_layers=4, layer_width=12, fanin=2, seed=1,
            layers_per_chunk=2,
        )

    rep = run_policy(8, factory, seed=1, validate=True,
                     ledger_size=65536)
    cp = rep["critical_path"]
    assert cp is not None
    assert cp["makespan"] > 0 and cp["n_tasks"] > 0
    assert set(cp["attribution"]) == {
        "compute", "transfer", "queue", "scheduler",
    }


# --------------------------------------------------------- state joins


def test_state_flood_joins_every_placement():
    from distributed_tpu.graph.spec import TaskSpec
    from distributed_tpu.scheduler.state import SchedulerState

    state = SchedulerState(validate=True)
    for i in range(4):
        state.add_worker_state(
            f"tcp://w{i}", nthreads=2, memory_limit=2**30, name=f"w{i}"
        )
    tasks = {f"t-{i}": TaskSpec(len, ((),)) for i in range(40)}
    deps: dict = {f"t-{i}": set() for i in range(40)}
    tasks["d-0"] = TaskSpec(len, ((),))
    deps["d-0"] = {"t-0", "t-1"}
    state.update_graph_core(
        tasks, deps, list(tasks), client="c", stimulus_id="s"
    )
    rounds = 0
    while True:
        batch = [
            (ts.key, ws.address, f"fin-{ts.key}", {"nbytes": 512})
            for ws in state.workers.values()
            for ts in list(ws.processing)
        ]
        if not batch:
            break
        state.stimulus_tasks_finished_batch(batch)
        rounds += 1
        assert rounds < 1000
    led = state.ledger
    assert led.filed_total == 41
    assert led.outcomes["memory"] == 41
    assert led.open_rows == 0 and led.unjoined_total == 0


def test_remove_worker_prunes_open_rows():
    from distributed_tpu.graph.spec import TaskSpec
    from distributed_tpu.scheduler.state import SchedulerState

    state = SchedulerState(validate=True)
    for i in range(2):
        state.add_worker_state(
            f"tcp://w{i}", nthreads=1, memory_limit=2**30, name=f"w{i}"
        )
    tasks = {f"t-{i}": TaskSpec(len, ((),)) for i in range(4)}
    state.update_graph_core(
        tasks, {k: set() for k in tasks}, list(tasks),
        client="c", stimulus_id="s",
    )
    led = state.ledger
    dead = next(iter(state.workers))
    open_before = led.open_rows
    assert open_before > 0
    state.remove_worker_state(dead, stimulus_id="rm", safe=False)
    assert led.outcomes.get("worker-removed", 0) > 0
    # the cascade re-placed the dead worker's tasks on the survivor:
    # no row may still point at the departed address
    for row in led.tail():
        if row["outcome"] == "":
            assert row["worker"] != dead


def test_erred_task_joins_as_erred():
    from distributed_tpu.graph.spec import TaskSpec
    from distributed_tpu.scheduler.state import SchedulerState

    state = SchedulerState(validate=True)
    state.add_worker_state(
        "tcp://w0", nthreads=1, memory_limit=2**30, name="w0"
    )
    state.update_graph_core(
        {"t": TaskSpec(len, ((),))}, {"t": set()}, ["t"],
        client="c", stimulus_id="s",
    )
    state.stimulus_tasks_erred_batch([
        ("t", "tcp://w0", "err-stim", {
            "exception": "boom", "exception_text": "boom",
        })
    ])
    assert state.ledger.outcomes.get("erred") == 1
    assert state.ledger.open_rows == 0


# ------------------------------------------------------ offline tooling


def test_critical_path_cli_check_and_perfetto(tmp_path):
    """End-to-end offline loop: sim run -> ledger JSONL + deps JSON ->
    critical_path CLI --check/--out -> flight_recorder --ledger renders
    the path track."""
    sim, trace = _build_ab_sim()
    trace.start(sim)
    sim.run()
    from distributed_tpu.tracing import dump_journal

    ledger_path = tmp_path / "ledger.jsonl"
    deps_path = tmp_path / "deps.json"
    dump_journal(sim.state.ledger.tail(), str(ledger_path))
    deps = {
        k: [d.key for d in ts.dependencies]
        for k, ts in sim.state.tasks.items()
    }
    deps_path.write_text(json.dumps(deps))

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    out_path = tmp_path / "cp.jsonl"
    proc = subprocess.run(
        [
            sys.executable, "-m",
            "distributed_tpu.diagnostics.critical_path",
            "--ledger", str(ledger_path), "--deps", str(deps_path),
            "--t0", "0.0", "--check", "--out", str(out_path),
        ],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    assert "OK" in proc.stdout
    cp_records = [
        json.loads(line) for line in out_path.read_text().splitlines()
    ]
    assert cp_records[0]["type"] == "cp-summary"

    # Perfetto: ledger rows + cp segments render as their own tracks
    from distributed_tpu.diagnostics.flight_recorder import to_perfetto

    perf = to_perfetto(
        [], ledger=sim.state.ledger.tail(200) + cp_records
    )
    tracks = {
        e["args"]["name"] for e in perf["traceEvents"]
        if e.get("ph") == "M"
    }
    assert "ledger (decision joins)" in tracks
    assert "critical path" in tracks
    assert any(
        e.get("ph") == "X" and e.get("cat") == "critical-path"
        for e in perf["traceEvents"]
    )
    assert any(
        e.get("name") == "ledger regret seconds"
        for e in perf["traceEvents"]
    )


def test_deps_from_dump_both_shapes():
    dump = {
        "scheduler": {
            "tasks": {"a": {"dependencies": ["b"]}, "b": {}},
        }
    }
    assert deps_from_dump(dump) == {"a": ["b"], "b": []}
    assert deps_from_dump({"a": ["b"]}) == {"a": ["b"]}


def test_critical_path_telescopes_manual_rows():
    """Hand-built chain: attribution telescopes exactly to the span."""
    rows = []
    t = 0.0
    for i, key in enumerate(("a", "b", "c")):
        rows.append({
            "type": "ledger-row", "seq": i, "kind": "placement",
            "key": key, "prefix": "p", "worker": "w", "src": "",
            "stim": f"s{i}", "plan_stim": "",
            "t_decision": t + 0.1, "outcome": "memory",
            "t_join": t + 1.0, "compute": 0.5, "transfer": 0.2,
            "n_deps": 1, "dep_bytes": 10,
        })
        t += 1.0
    deps = {"a": [], "b": ["a"], "c": ["b"]}
    res = critical_path(rows, deps, t0=0.0)
    assert res is not None
    assert res["n_tasks"] == 3
    assert abs(res["makespan"] - 3.0) < 1e-9
    check(res, tolerance=1e-6)
    assert abs(res["attribution"]["compute"] - 1.5) < 1e-9
    assert abs(res["attribution"]["transfer"] - 0.6) < 1e-9
    # scheduler latency: 0.1s per hop
    assert abs(res["attribution"]["scheduler"] - 0.3) < 1e-9


def test_dump_artefact_ledger_and_critical_path():
    from distributed_tpu.diagnostics.cluster_dump import DumpArtefact

    sim, trace = _build_ab_sim()
    trace.start(sim)
    sim.run()
    led = sim.state.ledger
    cp_live = sim.critical_path()
    dump = {
        "scheduler": {
            "tasks": {
                k: {
                    "state": ts.state,
                    "dependencies": [d.key for d in ts.dependencies],
                }
                for k, ts in sim.state.tasks.items()
            },
            "ledger": {
                "rows": led.tail(),
                "summary": led.summary(),
            },
        }
    }
    art = DumpArtefact(dump)
    assert art.ledger and art.ledger_summary["joined"] > 0
    assert art.ledger_rows(outcome="memory")
    cp = art.critical_path()
    assert cp is not None
    # the dump walk anchors at the first path task's own decision (no
    # t0, unrestricted terminal): attribution still sums to ITS makespan
    check(cp, tolerance=0.01)
    assert cp["terminal"] in dump["scheduler"]["tasks"]
    assert cp_live is not None  # the sim's own (terminal-pinned) walk

    # precomputed summary short-circuits
    dump["scheduler"]["ledger"]["critical_path"] = {"makespan": 42.0}
    art2 = DumpArtefact(dump)
    assert art2.critical_path() == {"makespan": 42.0}
    assert art2.critical_path(full=True)["makespan"] != 42.0


def test_ledger_snapshot_shape():
    sim, trace = _build_ab_sim()
    trace.start(sim)
    sim.run()
    snap = sim.state.ledger.snapshot(5)
    assert snap[0]["type"] == "ledger-summary"
    assert snap[0]["digest"] == sim.state.ledger.digest()
    rows = snap[1:]
    assert len(rows) == 5
    assert all(set(ROW_FIELDS) <= set(r) for r in rows)


def test_ledger_disabled_is_inert():
    with config.set({"scheduler.ledger.enabled": False}):
        led = DecisionLedger()
    assert led.file("placement", "k", "p", "w", "s") == -1
    assert led.filed_total == 0
    led.file_amm("amm-repl", "k", "w", "s")
    assert led.open_rows == 0


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
