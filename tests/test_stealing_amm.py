"""Work stealing + Active Memory Manager tests (reference test_steal.py,
test_active_memory_manager.py patterns)."""

from __future__ import annotations

import asyncio
import time as _time

import pytest

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.local import LocalCluster

from conftest import gen_test


def slowinc(x, delay=0.05):
    _time.sleep(delay)
    return x + 1


async def new_cluster(n_workers=2, threads_per_worker=1, **kwargs):
    cluster = LocalCluster(
        n_workers=n_workers,
        threads_per_worker=threads_per_worker,
        scheduler_kwargs={"validate": True, **kwargs.pop("scheduler_kwargs", {})},
        worker_kwargs={"validate": True, **kwargs.pop("worker_kwargs", {})},
        **kwargs,
    )
    await cluster._start()
    return cluster


@gen_test()
async def test_steal_time_ratio_levels():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            ext = cluster.scheduler.extensions["stealing"]
            fut = c.submit(slowinc, 1, key="str-x")
            await fut.result()
            state = cluster.scheduler.state
            ts = state.tasks["str-x"]
            # no dependencies -> trivially stealable at level 0
            assert ext.steal_time_ratio(ts) == (0, 0)


@gen_test()
async def test_stealing_rebalances_load():
    """Tasks assigned to a busy worker migrate to an idle newcomer."""
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            # a pile of slow tasks all queued on the only worker
            futs = c.map(slowinc, range(20), delay=0.1, pure=False)
            await asyncio.sleep(0.15)  # let them assign + first ones start
            w2 = await cluster.add_worker(name="late-joiner")
            results = await asyncio.wait_for(c.gather(futs), 30)
            assert results == list(range(1, 21))
            # the late joiner must have ended up doing some of the work
            # (either via queue-spill on join or stealing)
            assert len(w2.data) > 0 or cluster.scheduler.extensions[
                "stealing"
            ].count > 0


@gen_test()
async def test_steal_respects_restrictions():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            w0 = cluster.workers[0].address
            futs = c.map(
                slowinc, range(6), delay=0.05, workers=[w0], pure=False
            )
            await c.gather(futs)
            # all ran on w0 despite w1 being idle
            assert len(cluster.workers[0].data) == 6
            assert len(cluster.workers[1].data) == 0


@gen_test()
async def test_amm_reduce_replicas():
    async with await new_cluster(n_workers=3) as cluster:
        async with Client(cluster.scheduler_address) as c:
            fut = c.submit(slowinc, 1, key="amm-x", delay=0.01)
            await fut.result()
            sched = cluster.scheduler
            state = sched.state
            ts = state.tasks["amm-x"]
            # replicate everywhere
            await sched.replicate(keys=["amm-x"], n=3)
            for _ in range(200):
                if len(ts.who_has) == 3:
                    break
                await asyncio.sleep(0.01)
            assert len(ts.who_has) == 3
            # AMM round should trim back to 1 (no waiters)
            amm = sched.extensions["amm"]
            for _ in range(200):
                amm.run_once()
                await asyncio.sleep(0.01)
                if len(ts.who_has) == 1:
                    break
            assert len(ts.who_has) == 1
            # the data is still gatherable
            assert await fut.result() == 2


@gen_test()
async def test_retire_workers_moves_unique_data():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(slowinc, range(8), delay=0.01, pure=False)
            await c.gather(futs)
            victim = cluster.workers[0].address
            retired = await cluster.scheduler.retire_workers(workers=[victim])
            assert retired == [victim]
            cluster.workers = [
                w for w in cluster.workers if w.address != victim
            ]
            # every result survives on the remaining worker
            results = await asyncio.wait_for(c.gather(futs), 15)
            assert results == list(range(1, 9))


@gen_test()
async def test_amm_respects_processing_waiters():
    """A replica about to be consumed by a processing dependent is not
    dropped from that worker."""
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            fut = c.submit(slowinc, 1, key="amm-dep", delay=0.01)
            await fut.result()
            state = cluster.scheduler.state
            ts = state.tasks["amm-dep"]
            assert len(ts.who_has) == 1  # single replica: never dropped
            amm = cluster.scheduler.extensions["amm"]
            amm.run_once()
            await asyncio.sleep(0.1)
            assert len(ts.who_has) == 1
            assert await fut.result() == 2


@gen_test(timeout=120)
async def test_speculative_steal_correctness():
    """Speculative handoff (no confirm round trip): a deep pile on one
    worker spreads, results stay correct, and any double-executed task
    is fenced (the thief's run is authoritative)."""
    from distributed_tpu import config

    def slow(x, delay=0.05):
        import time

        time.sleep(delay)
        return x + 1

    with config.set({"scheduler.work-stealing-speculative": True,
                     "scheduler.work-stealing-interval": "50ms",
                     "scheduler.jax.enabled": False}):
        async with LocalCluster(
            n_workers=3,
            scheduler_kwargs={"validate": True},
            worker_kwargs={"validate": True},
        ) as cluster:
            async with Client(cluster.scheduler_address) as c:
                a = cluster.workers[0].address
                futs = c.map(slow, range(24), workers=[a],
                             allow_other_workers=True, pure=False)
                assert await asyncio.wait_for(c.gather(futs), 60) == list(
                    range(1, 25)
                )
                steal = cluster.scheduler.extensions["stealing"]
                assert any(e[0] == "speculative" for e in steal.log), (
                    "speculative path never engaged"
                )
