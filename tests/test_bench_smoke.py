"""Tier-1 gate for the perf plumbing: ``python bench.py --smoke`` runs
seconds-scale, CPU-pinned miniatures of the live-path (batched transition
engine, coalesced streams) and placement-path (chunked pack/upload)
configs on every PR, so regressions in the bench plumbing itself — the
round-2 lesson of a bench that died with no parseable output — and in the
perf-critical code paths it exercises surface in CI instead of only in
full bench rounds."""

from __future__ import annotations

import json
import os
import subprocess
import sys

BENCH = os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py")


def test_bench_smoke_runs_and_reports():
    proc = subprocess.run(
        [sys.executable, BENCH, "--smoke"],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    line = [
        ln for ln in proc.stdout.splitlines() if ln.strip().startswith("{")
    ][-1]
    out = json.loads(line)
    assert out["smoke"] is True
    cluster = out["configs"]["cluster"]
    assert cluster["n_tasks"] > 0
    assert cluster["overhead_us_per_task"] > 0
    placement = out["configs"]["placement"]
    assert placement["n_tasks"] > 0
    assert placement["n_waves"] > 0
    # mirror-fed steal + AMM cycle (scheduler/mirror.py): both kernels
    # planned real work off the persistent fleet SoA with no from-
    # scratch Python pack and no repeat full-fleet upload
    mirror = out["configs"]["mirror"]
    assert mirror["n_steals"] > 0
    assert mirror["n_drops"] > 0
    stats = mirror["mirror"]
    assert stats["oracle_packs"] == 0
    assert stats["oracle_failures"] == 0
    assert stats["full_uploads"] <= 1
    assert stats["rows_uploaded"] == 0
    # sharded engine gate (ops/leveled.place_graph_leveled_sharded on
    # the 8-device CPU mesh): the 1x1 mesh is the identity refactor,
    # the full mesh agrees with the single-device engine, the mirror's
    # workers-axis shards fed the kernel, and a fresh second cycle
    # shipped ZERO fleet rows on every shard with no wholesale re-pack
    # (the bench half raises on any violation; these asserts pin the
    # contract in the gate's own output)
    mesh = out["configs"]["mesh"]
    assert mesh["identity_1x1"] is True
    assert mesh["agreement"] > 0.97
    assert mesh["n_workers"] > 0
    # native transition engine (native/engine.cpp; docs/native_engine.md):
    # randomized-flood bit-parity vs the python oracle, the compiled
    # arms absorbing their share (escape rate < 10%), the deferred-
    # materialization contract (zero rows hydrate inside the engine
    # timer on a no-introspection flood), a same-session engine-plane
    # speedup over the 10x floor (whole-loop floor stays 1.3x), and
    # the per-flood alloc budget (the bench half raises on any
    # violation; these pin the contract)
    engine = out["configs"]["engine"]
    assert engine["parity"] is True
    assert engine["native_transitions"] > 0
    assert engine["escape_rate"] < 0.10
    assert engine["hydrations_in_timer"] == 0
    assert engine["speedup_engine_best"] >= 10.0
    assert engine["speedup_best"] >= 1.3
    assert engine["alloc_delta_blocks"] < 300
    assert len(mesh["engine_shards"]) >= 2
    assert all(r["h2d_bytes"] > 0 for r in mesh["engine_shards"])
    ms = mesh["mirror_shards"]
    assert ms["n_shards"] >= 2
    assert all(r == 0 for r in ms["rows_uploaded"])
    assert all(f == 1 for f in ms["full_packs"])
    # zero-copy wire contract (protocol/buffers.py, docs/wire.md): tcp
    # round trips at 1 KB / 64 KB / 8 MB recorded NO payload copy on
    # the send path and the receive pool saw reuse
    wire = out["configs"]["wire"]
    assert wire["payload_copies"] == 0
    assert wire["pool_hits"] > 0
    for label in ("1KB", "64KB", "8MB"):
        assert wire["mb_s"][label] > 0
    # flight recorder (tracing.py, docs/observability.md): traced-on
    # engine floods stay under the 5% overhead budget (same-session
    # canary-stamped A/B), the fast-path emit allocates nothing, and a
    # recorded stimulus journal replays to the identical transition
    # stream — the bench half raises on any violation, these asserts
    # pin the contract in the gate's own output
    trace = out["configs"]["trace"]
    assert trace["overhead_pct"] < 5.0
    assert trace["alloc_delta_blocks"] < 50
    assert trace["replay_match"] is True
    assert trace["replay_rows"] > 0
    assert trace["n_events"] > 0
    assert trace["host_canary_ms"] > 0
    # measured-truth telemetry plane (telemetry.py,
    # docs/observability.md): the tcp echo produced nonzero link
    # samples with measured bandwidth within 2x of the bench's own
    # observed MB/s, the measured/constant ratio reproduces the Round 4
    # "constant is ~10x off" finding as a checked artifact, and the
    # shadow divergence monitor's on/off engine-flood overhead stays
    # under 5% (paired-ratio estimator)
    telemetry = out["configs"]["telemetry"]
    assert telemetry["n_link_samples"] > 0
    assert telemetry["bw_within_2x"] is True
    assert telemetry["measured_mb_s"] > 0
    ratio = telemetry["constant_ratio"]
    assert ratio > 1.5 or ratio < 1 / 1.5
    assert telemetry["overhead_pct"] < 5.0
    assert telemetry["shadow_evals"] > 0
    assert telemetry["host_canary_ms"] > 0
    # control-plane self-profiler (diagnostics/selfprofile.py,
    # docs/observability.md "Self-profiling"): always-on sampling of the
    # control-plane thread stays under the 5% engine-flood budget
    # (min-per-pair-ratio A/B), samples carry phase stamps with nonzero
    # engine.drain wall, opt-in arm attribution yields per-arm rows, and
    # the deterministic stall scenario produced EXACTLY ONE watchdog
    # capture whose traceback names the blocking frame
    selfprofile = out["configs"]["selfprofile"]
    assert selfprofile["overhead_pct"] < 5.0
    assert selfprofile["samples"] > 0
    assert selfprofile["engine_drain_wall_s"] > 0
    assert selfprofile["arm_rows"] > 0
    # structural floor only: on this 2-core box the tiny synthetic
    # flood's arm share swings with load (measured 0.4-0.8 same-day);
    # the real >=0.70 acceptance gate runs on the longer, stabler sim
    # table in tests/test_profile_run.py
    assert selfprofile["arm_share"] > 0.2
    assert selfprofile["stall_events"] == 1
    assert selfprofile["stall_frame_named"] is True
    assert selfprofile["host_canary_ms"] > 0
    # decision–outcome ledger (ledger.py, diagnostics/critical_path.py,
    # docs/observability.md): ledger-on engine floods stay under the 5%
    # budget, the file+join hot path allocates nothing, a small live
    # cluster joins every decision, the telemetry-seeded non-uniform
    # sim's measured-shadow regret beats the constants' (the ROADMAP
    # item 1 calibration artifact), and critical-path attribution sums
    # to the virtual makespan within 1% (the bench half raises on any
    # violation; these asserts pin the contract in the gate's output)
    ledger = out["configs"]["ledger"]
    assert ledger["overhead_pct"] < 5.0
    assert ledger["alloc_delta_blocks"] < 50
    assert ledger["live_joined"] > 0
    assert ledger["live_unjoined"] == 0
    assert ledger["live_regret_rows"] > 0
    assert ledger["regret_abs_measured"] < ledger["regret_abs_constant"]
    assert ledger["cp_check_ok"] is True
    assert ledger["cp_makespan_s"] > 0
    # sans-io cluster simulator (distributed_tpu/sim, docs/simulator.md):
    # two same-seed runs of the sim_10k miniature — real engines, steal
    # + AMM cycles live, virtual clock — produced BIT-IDENTICAL digests
    # and virtual makespans, a chaos worker-death run converged with
    # zero lost keys, and a sim-recorded stimulus journal replayed
    # through the batched engine to the identical transition stream
    # (the bench half raises on any violation; these asserts pin the
    # contract in the gate's own output)
    # state census + retention sentinel (diagnostics/census.py,
    # docs/observability.md "State census & retention"): census-on
    # engine floods stay under the 5% budget (min-per-pair-ratio),
    # sentinel ticks are allocation-free, and a live run-then-quiesce
    # LocalCluster ends census-clean on every role with all
    # walk-vs-counter audits green (the bench half raises on any
    # violation; these asserts pin the contract in the gate's output)
    census = out["configs"]["census"]
    assert census["overhead_pct"] < 5.0
    assert census["alloc_delta_blocks"] < 50
    assert census["live_clean"] is True
    assert census["live_censuses"] == 3  # scheduler + 2 workers
    assert census["live_families"] > 100
    # determinism lint gate (analysis/rules/determinism.py,
    # docs/determinism.md): the tree has no hash-seed-ordered decision
    # path, so the bench numbers above are comparable across processes
    lint = out["configs"]["lint"]
    assert lint["rule"] == "determinism"
    assert lint["findings"] == 0
    sim = out["configs"]["sim"]
    assert sim["deterministic"] is True
    assert sim["virtual_makespan_s"] > 0
    assert sim["n_tasks"] > 0
    assert sim["decisions_per_s"] > 0
    assert sim["steals"] > 0
    assert sim["chaos_death_lost"] == 0
    assert sim["replay_match"] is True
    assert sim["replay_rows"] > 0


def test_bench_smoke_restart():
    """Scheduler-durability gate (scheduler/durability.py;
    docs/durability.md), run standalone via the ``--smoke restart``
    selector: a live TCP cluster computes keys, the scheduler snapshots
    and is HARD-bounced (comms aborted, no graceful close), a fresh
    scheduler restarts on the same port from snapshot + journal tail,
    the workers reconnect on their own carrying held_keys — zero
    completed keys lost, recovery under the RTO budget, fresh work
    computes after.  Plus the synthetic halves: steady-state capture
    overhead <5% (min-per-pair-ratio) and the digest-verified
    measured-RTO curve over snapshot cadence x journal-tail length."""
    proc = subprocess.run(
        [sys.executable, BENCH, "--smoke", "restart"],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    line = [
        ln for ln in proc.stdout.splitlines() if ln.strip().startswith("{")
    ][-1]
    out = json.loads(line)
    restart = out["configs"]["restart"]
    # the live hard-bounce half
    assert restart["lost_completed_keys"] == 0
    assert restart["pre_keys"] >= 50
    assert restart["rto_live_s"] < 30.0
    assert restart["restore_s"] > 0
    assert restart["replay_records"] > 0  # snapshot + TAIL, not snapshot alone
    assert restart["workers_reregistered"] == 2
    assert restart["liveness_ok"] is True
    # steady-state capture overhead (dirty tracker + journal segments)
    assert restart["overhead_pct"] < 5.0
    assert restart["amortized_snapshot_pct"] < 5.0
    # the measured-RTO curve: every point digest-verified, spanning
    # many-deltas/short-tail through base-only/whole-flood-tail
    curve = restart["rto_curve"]
    assert len(curve) == 3
    assert all(p["digest_ok"] for p in curve)
    assert all(p["restore_s"] > 0 for p in curve)
    assert curve[0]["epochs"] > curve[-1]["epochs"]
    assert curve[-1]["tail_records"] > curve[0]["tail_records"]
