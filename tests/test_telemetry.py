"""Measured-truth telemetry plane tests (telemetry.py;
docs/observability.md): per-link transfer stats, heartbeat deltas and
RTT, task-prefix priors, the shadow cost-model divergence monitor
(read-only proven by property test), /telemetry routes, dumps, and
Perfetto counter tracks."""

from __future__ import annotations

import asyncio
import json

import pytest

from distributed_tpu import config
from distributed_tpu.client.client import Client
from distributed_tpu.deploy.local import LocalCluster
from distributed_tpu.scheduler.server import Scheduler
from distributed_tpu.worker.server import Worker

from conftest import gen_test


async def http_get(port: int, path: str) -> tuple[int, bytes]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.0\r\n\r\n".encode())
    await writer.drain()
    data = await reader.read()
    writer.close()
    head, _, body = data.partition(b"\r\n\r\n")
    return int(head.split()[1]), body


# ------------------------------------------------------------------ units


def test_ewma_weighted_update():
    from distributed_tpu.telemetry import EWMA

    e = EWMA(alpha=0.5)
    e.update(10.0)
    assert e.value == 10.0 and e.count == 1
    e.update(20.0)
    assert e.value == 15.0
    # a weight-N row applies the N-fold decay in one step:
    # alpha_eff = 1 - (1-alpha)**N
    a, b = EWMA(0.5), EWMA(0.5)
    a.update(10.0)
    b.update(10.0)
    for _ in range(3):
        a.update(30.0)
    b.update(30.0, weight=3)
    assert a.value == pytest.approx(b.value)
    assert a.count == b.count == 4


def test_link_delta_take_restore_and_fold():
    from distributed_tpu.telemetry import LinkTelemetry

    lt = LinkTelemetry(alpha=0.5, enabled=True)
    lt.record("a", "b", 1_000_000, 0.01)   # 100 MB/s
    lt.record("a", "b", 1_000_000, 0.01)
    lt.record("b", "a", 500, 0.001)
    link = lt.links[("a", "b")]
    assert link.bandwidth.value == pytest.approx(1e8)
    assert link.bandwidth.count == 2
    assert link.bytes_total == 2_000_000
    # t-digest saw both samples
    assert link.digest.count() == 2
    delta = lt.take()
    assert not lt.since_heartbeat
    rows = lt.rows(delta)
    assert sorted(rows) == sorted(
        [["a", "b", 2_000_000, 0.02, 2], ["b", "a", 500, 0.001, 1]]
    )
    # failed heartbeat: restore merges back (and stacks with new samples)
    lt.restore(delta)
    lt.record("a", "b", 1_000_000, 0.01)
    rows2 = dict(
        ((r[0], r[1]), r[2:]) for r in lt.rows(lt.take())
    )
    assert rows2[("a", "b")] == [3_000_000, 0.03, 3]

    # scheduler-side fold: the DESTINATION's report is the bandwidth
    # sample; the SOURCE's report is the cross-check only
    from distributed_tpu.telemetry import ClusterTelemetry

    agg = ClusterTelemetry(alpha=0.5, enabled=True)
    agg.fold_rows([["a", "b", 4_000_000, 0.02, 2]], reporter="b")
    agg.fold_rows([["a", "b", 4_400_000, 0.02, 2]], reporter="a")
    link = agg.links[("a", "b")]
    assert link.bandwidth.value == pytest.approx(2e8)
    assert link.bandwidth.count == 2
    assert link.bytes_total == 4_000_000
    assert link.peer_bytes == 4_400_000 and link.peer_count == 2
    # removing a worker prunes its RTT and every link touching it
    # (restarted workers bind fresh ports; dead LinkStats would leak)
    agg.record_rtt("a", 0.001)
    agg.forget_worker("a")
    assert "a" not in agg.rtt and not agg.links
    # the LOCAL serving-end record (record_peer) also only touches the
    # cross-check totals — its clock stops at the OS write, so it must
    # never fold into the dst-observed bandwidth EWMA — but its delta
    # row still ships (the scheduler classifies by reporter)
    srv = LinkTelemetry(alpha=0.5, enabled=True)
    srv.record_peer("me", "peer", 2048, 0.001)
    link = srv.links[("me", "peer")]
    assert link.peer_bytes == 2048 and link.peer_count == 1
    assert link.bandwidth.count == 0 and link.bytes_total == 0
    assert srv.rows(srv.take()) == [["me", "peer", 2048, 0.001, 1]]
    # disabled collector records nothing
    off = LinkTelemetry(alpha=0.5, enabled=False)
    off.record("a", "b", 1, 1.0)
    off.record_peer("a", "b", 1, 1.0)
    assert not off.links and not off.since_heartbeat


def test_priors_fold_from_fine_rows():
    from distributed_tpu.telemetry import ClusterTelemetry

    tel = ClusterTelemetry(alpha=0.5, enabled=True)
    # one heartbeat's execute rows: 4 tasks of prefix "inc", mean
    # duration 0.25 s, mean output 1000 bytes; non-execute rows ignored
    tel.fold_fine_rows([
        ["execute", "span-1", "inc", "compute", "seconds", 1.0],
        ["execute", "span-1", "inc", "output", "bytes", 4000.0],
        ["execute", "span-1", "inc", "count", "tasks", 4],
        ["gather-dep", "", "", "network", "seconds", 9.0],
        ["execute", "", "", "compute", "seconds", 9.0],  # no prefix
    ])
    prior = tel.priors["inc"]
    assert prior.duration.value == pytest.approx(0.25)
    assert prior.nbytes.value == pytest.approx(1000.0)
    assert prior.n_tasks == 4
    assert len(tel.priors) == 1
    # second heartbeat folds as a count-weighted EWMA step
    tel.fold_fine_rows([
        ["execute", "span-1", "inc", "compute", "seconds", 0.75],
        ["execute", "span-1", "inc", "output", "bytes", 3000.0],
        ["execute", "span-1", "inc", "count", "tasks", 1],
    ])
    assert prior.duration.value == pytest.approx(0.5 * 0.25 + 0.5 * 0.75)
    assert prior.n_tasks == 5
    rec = prior.record()
    assert rec["type"] == "prior" and rec["prefix"] == "inc"


def test_get_comm_cost_measured_fallbacks():
    from distributed_tpu.graph.spec import TaskSpec
    from distributed_tpu.scheduler.state import SchedulerState

    state = SchedulerState(validate=True)
    w0 = state.add_worker_state("tcp://m:0", nthreads=1)
    w1 = state.add_worker_state("tcp://m:1", nthreads=1)
    w2 = state.add_worker_state("tcp://m:2", nthreads=1)
    dep = state.new_task("dep-k", TaskSpec(lambda: 1))
    dep.nbytes = 10_000_000
    state.add_replica(dep, w0)
    state.add_replica(dep, w1)
    ts = state.new_task("use-k", TaskSpec(lambda x: x))
    ts.dependencies.add(dep)

    # no telemetry at all: measured == the constant model, flag False
    constant = state.get_comm_cost(ts, w2)
    measured, used = state.get_comm_cost_measured(ts, w2)
    assert not used and measured == pytest.approx(constant)

    # rtt known but link unseen: constant bandwidth + measured fixed cost
    state.telemetry.record_rtt("tcp://m:2", 0.005)
    measured, used = state.get_comm_cost_measured(ts, w2)
    assert used
    assert measured == pytest.approx(
        dep.nbytes / state.bandwidth + 0.005
    )

    # measured links: the BEST holder link prices the dep
    state.telemetry.fold_rows(
        [["tcp://m:0", "tcp://m:2", 10_000_000, 0.1, 1],   # 100 MB/s
         ["tcp://m:1", "tcp://m:2", 10_000_000, 0.01, 1]],  # 1 GB/s
        reporter="tcp://m:2",
    )
    measured, used = state.get_comm_cost_measured(ts, w2)
    assert used
    best = state.telemetry.links[("tcp://m:1", "tcp://m:2")]
    assert measured == pytest.approx(
        dep.nbytes / best.bandwidth.value + best.latency.value
    )
    # a resident dep costs nothing in either model
    state.add_replica(dep, w2)
    assert state.get_comm_cost_measured(ts, w2) == (0.0, False)


def test_divergence_ratio_clamps_and_extremes():
    from distributed_tpu.telemetry import RATIO_CLAMP, ClusterTelemetry

    tel = ClusterTelemetry(alpha=0.5, enabled=True)
    # extremes are None until a MEASURED eval happens (a 1.0 default
    # would report a never-observed perfect agreement)
    assert tel.ratio_min is None and tel.ratio_max is None
    assert tel.observe_divergence(1.0, 0.1, True) == pytest.approx(0.1)
    assert tel.ratio_min == tel.ratio_max == pytest.approx(0.1)
    assert tel.observe_divergence(0.0, 0.0, False) == 1.0
    assert tel.observe_divergence(0.0, 5.0, True) == RATIO_CLAMP
    assert tel.hist_divergence.count == 3
    assert tel.shadow_evals == 3 and tel.shadow_measured == 2
    assert tel.ratio_min == pytest.approx(0.1)
    assert tel.ratio_max == RATIO_CLAMP
    rec = [r for r in tel.snapshot() if r["type"] == "divergence"][0]
    assert rec["evals"] == 3 and rec["measured"] == 2


# --------------------------------------------- shadow mode is READ-ONLY


def _build_decision_state(enabled: bool):
    """Identical graph + fleet, telemetry enabled/disabled; the enabled
    arm gets measured links wildly different from the constant."""
    from distributed_tpu.graph.spec import TaskSpec
    from distributed_tpu.scheduler.state import SchedulerState

    with config.set({"scheduler.telemetry.enabled": enabled}):
        state = SchedulerState(validate=True)
    addrs = [f"tcp://pd:{i}" for i in range(6)]
    for a in addrs:
        state.add_worker_state(a, nthreads=2, memory_limit=2**30)
    # measured links at 10x the constant bandwidth on every pair (the
    # disabled arm gets them too — proving they are never consulted)
    state.telemetry.fold_rows(
        [[a, b, 1_000_000_000, 1.0, 4] for a in addrs for b in addrs
         if a != b],
        reporter="",
    )
    for a in addrs:
        state.telemetry.record_rtt(a, 0.003)
    tasks = {f"src-{i}": TaskSpec(lambda: 1) for i in range(40)}
    deps: dict = {f"src-{i}": set() for i in range(40)}
    for i in range(20):
        tasks[f"mid-{i}"] = TaskSpec(lambda x: x)
        deps[f"mid-{i}"] = {f"src-{i}", f"src-{i + 1}"}
    for i in range(5):
        tasks[f"top-{i}"] = TaskSpec(lambda x: x)
        deps[f"top-{i}"] = {f"mid-{4 * i}", f"mid-{4 * i + 1}"}
    state.update_graph_core(
        tasks, deps, list(tasks), client="pd", stimulus_id="pd-graph"
    )
    return state


def _flood(state, nbytes=5_000_000):
    while True:
        batch = [
            (ts.key, ws.address, f"fin-{ts.key}", {"nbytes": nbytes})
            for ws in state.workers.values()
            for ts in list(ws.processing)
        ]
        if not batch:
            return
        state.stimulus_tasks_finished_batch(batch)


def test_shadow_mode_identical_decisions_on_off():
    """ACCEPTANCE: telemetry enabled vs disabled produces bit-identical
    placement AND steal decisions — the shadow monitor is read-only —
    while the enabled arm's divergence histogram records real nonzero
    divergence (measured 10x bandwidth vs the constant)."""
    from distributed_tpu.diagnostics.flight_recorder import (
        transition_stream,
    )
    from distributed_tpu.scheduler.stealing import WorkStealing
    from distributed_tpu.utils.test import StubScheduler

    streams, placements, steals, sents = [], [], [], []
    for enabled in (True, False):
        state = _build_decision_state(enabled)
        mark = len(state.transition_log)
        _flood(state)
        streams.append(transition_stream(state, mark))
        placements.append({
            k: ts.processing_on.address if ts.processing_on else None
            for k, ts in sorted(state.tasks.items())
        })
        # steal cycle: pile a restricted burst on one worker, balance
        from distributed_tpu.graph.spec import TaskSpec

        w0 = next(iter(state.workers.values()))
        state.new_task_prefix("stl").add_duration(0.05)
        stasks = {f"stl-{i}": TaskSpec(lambda: 1) for i in range(60)}
        sched = StubScheduler(state)
        stealing = WorkStealing(sched)
        state.update_graph_core(
            stasks, {k: set() for k in stasks}, list(stasks),
            client="pd",
            annotations_by_key={
                k: {"workers": [w0.address], "allow_other_workers": True}
                for k in stasks
            },
            stimulus_id="pd-steal",
        )
        stealing.balance()
        steals.append({
            k: (info.victim.address, info.thief.address)
            for k, info in sorted(stealing.in_flight.items())
        })
        sents.append(
            [sorted(wm) for _cm, wm in sched.sent]
        )
        if enabled:
            tel = state.telemetry
            assert tel.shadow_evals > 0
            assert tel.hist_divergence.count == tel.shadow_evals
            assert tel.shadow_measured > 0
            # measured 10x bandwidth: the ratio extremes moved off 1.0
            assert tel.ratio_min < 0.9, (tel.ratio_min, tel.ratio_max)
            # the sampled flight-recorder shadow hops carry stimuli
            shadow = [
                ev for ev in state.trace.tail() if ev["cat"] == "shadow"
            ]
            assert shadow and all(ev["stim"] for ev in shadow)
            assert {ev["name"] for ev in shadow} >= {"placement"}
        else:
            assert state.telemetry.shadow_evals == 0
            assert state.telemetry.hist_divergence.count == 0

    on, off = 0, 1
    assert streams[on] == streams[off]
    assert placements[on] == placements[off]
    assert steals[on] and steals[on] == steals[off]
    assert sents[on] == sents[off]


def test_steal_shadow_event_carries_stimulus():
    """Steal pricing records its own shadow hop under the move's
    stimulus id (stealing.move_task_request)."""
    from distributed_tpu.scheduler.stealing import WorkStealing
    from distributed_tpu.utils.test import StubScheduler

    state = _build_decision_state(True)
    _flood(state)
    from distributed_tpu.graph.spec import TaskSpec

    w0 = next(iter(state.workers.values()))
    state.new_task_prefix("stl").add_duration(0.05)
    stasks = {f"stl-{i}": TaskSpec(lambda: 1) for i in range(60)}
    sched = StubScheduler(state)
    stealing = WorkStealing(sched)
    state.update_graph_core(
        stasks, {k: set() for k in stasks}, list(stasks), client="pd",
        annotations_by_key={
            k: {"workers": [w0.address], "allow_other_workers": True}
            for k in stasks
        },
        stimulus_id="pd-steal",
    )
    stealing.balance()
    assert stealing.in_flight
    steal_shadow = [
        ev for ev in state.trace.tail()
        if ev["cat"] == "shadow" and ev["name"] == "steal"
    ]
    assert steal_shadow
    stims = {info.stimulus_id for info in stealing.in_flight.values()}
    assert {ev["stim"] for ev in steal_shadow} <= stims | {""}
    assert any(ev["stim"] in stims for ev in steal_shadow)


# ------------------------------------------------------------- live wire


@gen_test(timeout=120)
async def test_link_samples_both_ends_agree_over_tcp():
    """SATELLITE: get_data true-wire-bytes attribute to per-link samples
    on BOTH ends, and the two ends agree within framing overhead —
    asserted on the scheduler's fleet aggregate (the serving end's
    wire bytes land as the peer cross-check next to the requesting
    end's payload bytes)."""
    import numpy as np

    async with Scheduler(validate=True) as s:  # tcp by default
        async with Worker(s.address, nthreads=1,
                          heartbeat_interval=0.1) as a:
            async with Worker(s.address, nthreads=1,
                              heartbeat_interval=0.1) as b:
                async with Client(s.address) as c:
                    def chunk(i):
                        return np.full((512, 256), float(i))  # ~1 MB

                    chunks = [
                        c.submit(chunk, i, pure=False,
                                 workers=[[a.address, b.address][i % 2]])
                        for i in range(6)
                    ]
                    outs = [
                        c.submit(lambda x, y: float(x.sum() + y.sum()),
                                 u, v, pure=False)
                        for u, v in zip(chunks[:-1], chunks[1:])
                    ]
                    await asyncio.wait_for(c.gather(outs), 60)

                    # each worker recorded BOTH ends locally
                    for w, peer in ((a, b), (b, a)):
                        links = w.telemetry.links
                        assert (peer.address, w.address) in links, (
                            w.address, list(links)
                        )
                        assert (w.address, peer.address) in links

                    # heartbeats ship both views to the scheduler;
                    # wait until the aggregate caught up with BOTH
                    # workers' local totals (the two ends' deltas land
                    # on different heartbeats)
                    tel = s.state.telemetry
                    pairs = [(a, b), (b, a)]
                    deadline = asyncio.get_running_loop().time() + 30

                    def caught_up():
                        for src, dst in pairs:
                            key = (src.address, dst.address)
                            agg = tel.links.get(key)
                            req = dst.telemetry.links.get(key)
                            srv = src.telemetry.links.get(key)
                            if agg is None or req is None or srv is None:
                                return False
                            if agg.bytes_total != req.bytes_total:
                                return False
                            if agg.peer_bytes != srv.peer_bytes:
                                return False
                        return True

                    while not caught_up():
                        assert (
                            asyncio.get_running_loop().time() < deadline
                        ), {k: (v.bytes_total, v.peer_bytes)
                            for k, v in tel.links.items()}
                        await asyncio.sleep(0.05)
                    for src, dst in pairs:
                        link = tel.links[(src.address, dst.address)]
                        # the two ends recorded the same serves
                        assert link.bandwidth.count == link.peer_count, (
                            link.src, link.dst, link.bandwidth.count,
                            link.peer_count,
                        )
                        # true wire bytes vs sizeof payload: equal up
                        # to framing/serialization overhead (numpy
                        # payloads serialize ~1:1; headers are KBs)
                        assert link.peer_bytes == pytest.approx(
                            link.bytes_total, rel=0.1, abs=64 * 1024
                        ), (link.src, link.dst, link.bytes_total,
                            link.peer_bytes)
                        # framing ADDS bytes (sizeof vs serialized can
                        # differ by object-header noise, nothing more)
                        assert link.peer_bytes >= link.bytes_total - 4096
                        assert link.bandwidth.value > 0


@gen_test(timeout=120)
async def test_telemetry_routes_rtt_metrics_and_dump():
    """ACCEPTANCE: the snapshot (link EWMAs + t-digest quantiles +
    priors) is fetchable via /telemetry on BOTH roles, the heartbeat
    RTT EWMA shows up as dtpu_link_heartbeat_rtt_seconds, the
    divergence histogram is nonzero on a loopback cluster whose
    measured bandwidth differs from the constant, and the snapshot
    ships in cluster dumps."""
    import numpy as np

    from distributed_tpu.diagnostics.cluster_dump import DumpArtefact
    from distributed_tpu.tracing import from_jsonl

    async with LocalCluster(
        n_workers=2, threads_per_worker=1,
        worker_kwargs={"heartbeat_interval": 0.1},
    ) as cluster:
        async with Client(cluster.scheduler_address) as c:
            addrs = [w.address for w in cluster.workers]

            def chunk(i):
                return np.full((512, 256), float(i))  # ~1 MB

            async def cross_wave(offset):
                chunks = [
                    c.submit(chunk, offset + i, pure=False,
                             workers=[addrs[i % 2]])
                    for i in range(6)
                ]
                outs = [
                    c.submit(lambda x, y: float(x.sum() + y.sum()),
                             u, v, pure=False)
                    for u, v in zip(chunks[:-1], chunks[1:])
                ]
                await asyncio.wait_for(c.gather(outs), 60)

            await cross_wave(0)
            tel = cluster.scheduler.state.telemetry
            deadline = asyncio.get_running_loop().time() + 30
            while not (tel.links and tel.rtt and tel.priors):
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)
            # second wave AFTER links are measured: placement shadow
            # evals now price deps over measured links
            await cross_wave(100)
            while not tel.shadow_measured:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)

            # divergence histogram is NONZERO and the measured ratio
            # moved off 1.0 (loopback bandwidth != the 100 MB/s
            # constant)
            assert tel.hist_divergence.count > 0
            assert (tel.ratio_min, tel.ratio_max) != (1.0, 1.0)

            # /telemetry on the scheduler role
            sport = cluster.scheduler.http_server.port
            status, body = await http_get(sport, "/telemetry")
            assert status == 200
            recs = from_jsonl(body)
            by_type: dict = {}
            for r in recs:
                by_type.setdefault(r["type"], []).append(r)
            assert by_type.get("link") and by_type.get("rtt")
            assert by_type.get("prior") and by_type.get("divergence")
            link = by_type["link"][0]
            assert link["bandwidth"] > 0 and "bw_q50" in link
            assert {"bw_q90", "bw_q99"} <= set(link)
            prior = [
                p for p in by_type["prior"] if p["prefix"] == "chunk"
            ][0]
            assert prior["duration"] > 0 and prior["nbytes"] > 500_000
            assert by_type["divergence"][0]["count"] > 0

            # /telemetry on the worker role
            wport = cluster.workers[0].http_server.port
            status, body = await http_get(wport, "/telemetry")
            assert status == 200
            wrecs = from_jsonl(body)
            assert wrecs and all(r["type"] == "link" for r in wrecs)

            # RTT + divergence + priors on /metrics
            status, body = await http_get(sport, "/metrics")
            text = body.decode()
            for needle in (
                "dtpu_link_heartbeat_rtt_seconds",
                "dtpu_link_bandwidth_bytes_per_second",
                'dtpu_costmodel_divergence_ratio_bucket{le="+Inf"}',
                "dtpu_costmodel_shadow_measured_total",
                "dtpu_prior_duration_seconds",
                "dtpu_prior_tasks_total",
            ):
                assert needle in text, needle
            rtt_line = [
                ln for ln in text.splitlines()
                if ln.startswith("dtpu_link_heartbeat_rtt_seconds{")
            ][0]
            assert float(rtt_line.rsplit(" ", 1)[1]) > 0
            status, body = await http_get(wport, "/metrics")
            assert b"dtpu_link_bandwidth_bytes_per_second" in body

            # the snapshot ships in cluster dumps
            state = await c.scheduler.get_cluster_state()
            d = DumpArtefact(state)
            assert d.telemetry_records("link")
            assert d.telemetry_records("divergence")[0]["evals"] > 0
            # excluding it works like the other artefacts
            lean = await c.scheduler.get_cluster_state(
                exclude=["telemetry"]
            )
            assert "telemetry" not in lean["scheduler"]


@gen_test(timeout=120)
async def test_span_metrics_survive_worker_restart():
    """SATELLITE: cumulative_worker_metrics heartbeat-delta aggregation
    across a worker restart — re-registration must neither double-count
    nor lose the pre-restart cumulative samples."""
    async with Scheduler(validate=True) as s:
        spans = s.spans

        def exec_count():
            return sum(
                v for k, v in spans.cumulative_worker_metrics.items()
                if k[0] == "execute" and k[3] == "count"
            )

        async with Worker(s.address, nthreads=1,
                          heartbeat_interval=0.05) as a:
            async with Client(s.address) as c:
                await c.gather(c.map(lambda x: x + 1, range(7),
                                     pure=False))
                deadline = asyncio.get_running_loop().time() + 30
                while exec_count() < 7:
                    assert asyncio.get_running_loop().time() < deadline
                    await asyncio.sleep(0.05)
                # deltas were taken: a few idle heartbeats must not
                # re-add them
                await a.heartbeat()
                await a.heartbeat()
                assert exec_count() == 7
        # worker gone; pre-restart samples survive removal
        assert exec_count() == 7
        async with Worker(s.address, nthreads=1,
                          heartbeat_interval=0.05):
            async with Client(s.address) as c:
                await c.gather(c.map(lambda x: x + 1, range(5),
                                     pure=False))
                deadline = asyncio.get_running_loop().time() + 30
                while exec_count() < 12:
                    assert asyncio.get_running_loop().time() < deadline
                    await asyncio.sleep(0.05)
                await asyncio.sleep(0.2)  # extra heartbeats: no double
                assert exec_count() == 12


# ------------------------------------------------------------- exporters


def test_perfetto_counter_tracks_and_cli(tmp_path):
    """SATELLITE: the Perfetto exporter renders telemetry snapshots and
    shadow events as counter tracks on the stimulus timeline."""
    import subprocess
    import sys as _sys

    from distributed_tpu.diagnostics.flight_recorder import to_perfetto
    from distributed_tpu.tracing import to_jsonl

    state = _build_decision_state(True)
    _flood(state)
    events = state.trace.tail()
    telemetry = state.telemetry.snapshot()
    assert any(ev["cat"] == "shadow" for ev in events)
    doc = to_perfetto(events, telemetry=telemetry)
    counters = [
        ev for ev in doc["traceEvents"] if ev["ph"] == "C"
    ]
    names = {ev["name"] for ev in counters}
    assert "costmodel divergence ratio" in names
    assert any(n.startswith("link ") and n.endswith(" MB/s")
               for n in names)
    assert any(n.startswith("rtt ") for n in names)
    for ev in counters:
        assert ev["ts"] >= 0 and isinstance(ev["args"], dict)
    json.dumps(doc)
    # the shadow swimlane metadata track exists
    assert any(
        ev.get("ph") == "M"
        and "shadow" in (ev.get("args") or {}).get("name", "")
        for ev in doc["traceEvents"]
    )

    # CLI: --telemetry renders the counter tracks
    src = tmp_path / "trace.jsonl"
    src.write_text(to_jsonl(events))
    tsrc = tmp_path / "telemetry.jsonl"
    tsrc.write_text(to_jsonl(telemetry))
    out = tmp_path / "out.json"
    proc = subprocess.run(
        [_sys.executable, "-m",
         "distributed_tpu.diagnostics.flight_recorder",
         "--input", str(src), "--telemetry", str(tsrc),
         "--perfetto", str(out)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc2 = json.loads(out.read_text())
    assert any(
        ev["ph"] == "C" and ev["name"].startswith("link ")
        for ev in doc2["traceEvents"]
    )
