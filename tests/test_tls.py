"""TLS functional tests (reference tests/test_tls_functional.py): a real
``tls://`` cluster round-trip with mutual auth, and handshake rejection
for credentials signed by a different CA."""

from __future__ import annotations

import asyncio

import pytest

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.local import LocalCluster
from distributed_tpu.security import Security

from conftest import gen_test


def inc(x):
    return x + 1


def add(x, y):
    return x + y


@gen_test(timeout=90)
async def test_tls_cluster_roundtrip():
    """Scheduler, workers and client all talk tls:// with certificates
    from one self-signed CA; submit/gather and worker->worker dependency
    fetches all run over TLS."""
    sec = Security.temporary()
    async with LocalCluster(
        n_workers=2, threads_per_worker=1, protocol="tls", security=sec,
        scheduler_kwargs={"validate": True},
        worker_kwargs={"validate": True},
    ) as cluster:
        assert cluster.scheduler_address.startswith("tls://")
        assert all(w.address.startswith("tls://") for w in cluster.workers)
        async with Client(cluster.scheduler_address, security=sec) as c:
            fut = c.submit(inc, 1)
            assert await fut.result() == 2
            # cross-worker dependency: the data plane also rides TLS
            w0, w1 = [w.address for w in cluster.workers]
            a = c.submit(inc, 10, workers=[w0], key="tls-a")
            b = c.submit(add, a, 5, workers=[w1], key="tls-b")
            assert await b.result() == 16
            # scatter/gather through the client connection
            [x] = await c.scatter([41])
            assert await c.submit(inc, x).result() == 42


@gen_test(timeout=90)
async def test_tls_rejects_wrong_ca():
    """A client presenting certificates from a DIFFERENT CA must fail the
    handshake; the cluster keeps serving properly-authenticated peers."""
    sec = Security.temporary()
    intruder = Security.temporary()  # same structure, different CA
    async with LocalCluster(
        n_workers=1, protocol="tls", security=sec,
    ) as cluster:
        bad = Client(cluster.scheduler_address, security=intruder, timeout=5)
        with pytest.raises(Exception):
            await asyncio.wait_for(bad._start(), 15)
        try:
            await bad.close()
        except Exception:
            pass
        # the cluster is still healthy for trusted clients
        async with Client(cluster.scheduler_address, security=sec) as c:
            assert await c.submit(inc, 1).result() == 2


@gen_test(timeout=90)
async def test_tls_plaintext_connect_fails():
    """A plain-TCP client cannot talk to a TLS listener."""
    sec = Security.temporary()
    async with LocalCluster(
        n_workers=1, protocol="tls", security=sec,
    ) as cluster:
        plain_addr = cluster.scheduler_address.replace("tls://", "tcp://")
        bad = Client(plain_addr, timeout=5)
        with pytest.raises(Exception):
            await asyncio.wait_for(bad._start(), 15)
        try:
            await bad.close()
        except Exception:
            pass


# --------------------------------------------------------- CLI harness

import contextlib


@contextlib.contextmanager
def _spawn_cli(argv, marker, env, cwd, timeout=15):
    """Spawn a dtpu CLI process, yield the address after its marker line;
    SIGTERM + escalate on exit (shared by the TLS CLI tests)."""
    import signal
    import subprocess
    import sys

    proc = subprocess.Popen(
        [sys.executable, "-m", *argv],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=cwd,
    )
    try:
        line = proc.stdout.readline()
        assert line.startswith(marker), line
        yield line.split()[-1]
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()


def _cli_env():
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return {**os.environ, "PYTHONPATH": repo, "JAX_PLATFORMS": "cpu"}, repo


@pytest.mark.slow
def test_tls_cli_cluster_roundtrip():
    """dtpu-scheduler/dtpu-worker --tls-* flags: a real TLS cluster from
    the CLIs, driven by a TLS client (reference dask-scheduler
    --tls-cert/--tls-key/--tls-ca-file)."""
    sec = Security.temporary()
    env, repo = _cli_env()
    tls = ["--tls-ca-file", sec.tls_ca_file,
           "--tls-cert", sec.tls_scheduler_cert,
           "--tls-key", sec.tls_scheduler_key]

    with _spawn_cli(
        ["distributed_tpu.cli.scheduler", "--port", "0",
         "--protocol", "tls", *tls],
        "Scheduler at: tls://", env, repo,
    ) as address:
        with _spawn_cli(
            ["distributed_tpu.cli.worker", address, "--nthreads", "1", *tls],
            "Worker at: tls://", env, repo,
        ):
            async def drive():
                async with Client(address, security=sec) as c:
                    return await asyncio.wait_for(
                        c.submit(lambda x: x * 6, 7).result(), 30
                    )

            assert asyncio.run(drive()) == 42


@pytest.mark.slow
def test_tls_cli_nanny_cluster():
    """--nanny under TLS: the nanny's scheduler rpc, its control channel,
    and the spawned worker all ride tls://; certs without --protocol must
    INFER tls, never silently listen in plaintext."""
    sec = Security.temporary()
    env, repo = _cli_env()
    tls = ["--tls-ca-file", sec.tls_ca_file,
           "--tls-cert", sec.tls_scheduler_cert,
           "--tls-key", sec.tls_scheduler_key]

    with _spawn_cli(
        ["distributed_tpu.cli.scheduler", "--port", "0", *tls],
        "Scheduler at: tls://", env, repo,
    ) as address:
        with _spawn_cli(
            ["distributed_tpu.cli.worker", address,
             "--nthreads", "1", "--nanny", *tls],
            "Worker at: tls://", env, repo,
        ):
            async def drive():
                async with Client(address, security=sec) as c:
                    return await asyncio.wait_for(
                        c.submit(lambda x: x + 30, 12).result(), 60
                    )

            assert asyncio.run(drive()) == 42
