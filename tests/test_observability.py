"""Observability tests: HTTP routes, Prometheus, SystemMonitor, task
stream, profiler, events (reference http/*/tests, test_events patterns)."""

from __future__ import annotations

import asyncio
import json
import time as _time

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.local import LocalCluster
from distributed_tpu.scheduler.server import Scheduler
from distributed_tpu.worker.server import Worker

from conftest import gen_test


async def new_cluster(**kwargs):
    cluster = LocalCluster(
        n_workers=kwargs.pop("n_workers", 2),
        scheduler_kwargs={"validate": True, **kwargs.pop("scheduler_kwargs", {})},
        worker_kwargs={"validate": True, **kwargs.pop("worker_kwargs", {})},
        **kwargs,
    )
    await cluster._start()
    return cluster


async def http_get(port: int, path: str) -> tuple[int, bytes]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.0\r\n\r\n".encode())
    await writer.drain()
    data = await reader.read()
    writer.close()
    head, _, body = data.partition(b"\r\n\r\n")
    status = int(head.split()[1])
    return status, body


@gen_test()
async def test_http_health_info_metrics():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(lambda x: x + 1, range(5))
            await c.gather(futs)
            port = cluster.scheduler.http_server.port
            status, body = await http_get(port, "/health")
            assert status == 200 and body == b"ok"
            status, body = await http_get(port, "/info")
            info = json.loads(body)
            assert info["type"] == "Scheduler"
            assert len(info["workers"]) == 2
            status, body = await http_get(port, "/metrics")
            text = body.decode()
            assert "dtpu_scheduler_workers 2" in text
            assert "dtpu_scheduler_tasks" in text
            status, body = await http_get(port, "/json/counts.json")
            counts = json.loads(body)
            assert counts["workers"] == 2
            status, _ = await http_get(port, "/nope")
            assert status == 404
            # worker metrics too
            wport = cluster.workers[0].http_server.port
            status, body = await http_get(wport, "/metrics")
            assert b"dtpu_worker_tasks_stored" in body


@gen_test()
async def test_system_monitor_samples():
    async with await new_cluster(n_workers=1) as cluster:
        mon = cluster.scheduler.monitor
        mon.update()
        mon.update()
        recent = mon.recent()
        assert recent["memory"] > 0
        rq = mon.range_query()
        assert len(rq["time"]) >= 2


@gen_test()
async def test_task_stream_records():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(lambda x: x * 2, range(6), pure=False)
            await c.gather(futs)
            stream = await c.get_task_stream()
            assert len(stream) == 6
            rec = stream[0]
            assert rec["worker"] is not None
            assert rec["startstops"] and rec["startstops"][0]["action"] == "compute"
            # every rectangle carries the stimulus id of the transition
            # that produced it — the join key against /trace (PR 6)
            assert all(r["stimulus_id"] for r in stream)
            trace_stims = {
                ev["stim"] for ev in cluster.scheduler.trace.tail()
            }
            assert {r["stimulus_id"] for r in stream} <= trace_stims


@gen_test(timeout=60)
async def test_profile_collects_samples():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            def busy(x):
                t0 = _time.time()
                while _time.time() - t0 < 0.5:
                    sum(range(1000))
                return x

            fut = c.submit(busy, 1)
            await fut.result()
            prof = await c.profile()
            assert prof["count"] > 0
            # the busy function appears somewhere in the tree
            def find(node):
                if "busy" in node.get("description", ""):
                    return True
                return any(find(ch) for ch in node.get("children", {}).values())

            assert find(prof)


@gen_test()
async def test_events_and_subscription():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            seen: list = []
            c.subscribe_topic("my-topic", seen.append)
            await asyncio.sleep(0.05)
            c.log_event("my-topic", {"x": 1})
            for _ in range(100):
                if seen:
                    break
                await asyncio.sleep(0.01)
            assert seen == [{"x": 1}]
            events = await c.get_events("my-topic")
            assert len(events) == 1
            assert events[0][1] == {"x": 1}


@gen_test(timeout=60)
async def test_json_api_and_dashboard():
    """Dashboard-lite JSON routes + the self-contained HTML page
    (reference http/scheduler/api.py, dashboard/)."""
    import json as _json
    import urllib.request

    async with await new_cluster(
        n_workers=2, scheduler_kwargs={"http_port": 0}
    ) as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(lambda x: x * 2, range(20), pure=False)
            await c.gather(futs)
            for w in cluster.workers:
                await w.heartbeat()
            port = cluster.scheduler.http_server.port

            def get(path):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5
                ) as r:
                    return r.headers.get_content_type(), r.read()

            loop = asyncio.get_running_loop()
            ct, body = await loop.run_in_executor(None, get, "/api/v1/workers")
            ws = _json.loads(body)
            assert ct == "application/json" and len(ws) == 2
            assert all("managed_bytes" in w and "occupancy" in w for w in ws)

            _, body = await loop.run_in_executor(None, get, "/api/v1/tasks")
            tasks = _json.loads(body)
            assert tasks["by_state"].get("memory", 0) >= 20

            _, body = await loop.run_in_executor(
                None, get, "/api/v1/task_stream"
            )
            stream = _json.loads(body)
            assert len(stream) >= 20
            assert all("startstops" in r for r in stream)

            _, body = await loop.run_in_executor(None, get, "/api/v1/memory")
            mem = _json.loads(body)
            assert len(mem["workers"]) == 2

            ct, body = await loop.run_in_executor(None, get, "/dashboard")
            assert ct == "text/html"
            assert b"task_stream" in body and b"<svg" in body


@gen_test(timeout=60)
async def test_memory_sampler():
    """MemorySampler context manager records a cluster memory timeseries
    (reference diagnostics/memory_sampler.py:180)."""
    import numpy as np

    from distributed_tpu.diagnostics.memory_sampler import MemorySampler

    def chunk(i):
        return np.ones(1_000_000)  # 8 MB

    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            ms = MemorySampler()
            async with ms.sample("run", client=c, interval=0.05):
                futs = c.map(chunk, range(4), pure=False)
                await c.gather(futs)
                await asyncio.sleep(0.3)
            series = ms.to_list("run")
            assert len(series) >= 3
            assert ms.max("run") >= 4 * 8_000_000
            # offsets monotonically increase
            assert all(b[0] > a[0] for a, b in zip(series, series[1:]))


@gen_test()
async def test_progress_bar_tracks_futures():
    """progress() renders until every future settles and reports erred
    counts (reference diagnostics/tests/test_progressbar.py)."""
    import io

    from distributed_tpu.diagnostics.progressbar import progress

    async with Scheduler(listen_addr="inproc://", validate=True) as s:
        async with Worker(s.address, nthreads=2):
            async with Client(s.address) as c:
                futs = c.map(lambda x: x * 2, range(10))
                buf = io.StringIO()
                await asyncio.wait_for(progress(futs, file=buf), 30)
                text = buf.getvalue()
                assert "10/10" in text
                assert text.endswith("\n")
                assert await c.gather(futs) == [x * 2 for x in range(10)]

                bad = c.map(
                    lambda x: 1 // (x % 3), range(6), pure=False
                )
                buf = io.StringIO()
                await asyncio.wait_for(progress(bad, file=buf), 30)
                assert "2 erred" in buf.getvalue()


@gen_test(timeout=120)
async def test_dashboard_profile_and_graph_routes():
    """Dashboard-lite round 4: /api/v1/profile serves the merged worker
    flame-graph call tree and /api/v1/graph a layered dependency graph;
    the HTML page embeds renderers for both (reference
    dashboard/components/scheduler.py profile + graph components,
    diagnostics/graph_layout.py:9)."""
    import json
    import time as _time
    import urllib.request

    from distributed_tpu import config
    from distributed_tpu.client.client import Client
    from distributed_tpu.deploy.local import LocalCluster

    def work(i):
        _time.sleep(0.03)
        return sum(range(50_000)) + i

    with config.set({"worker.profile.enabled": True}):
        async with LocalCluster(
            n_workers=2, scheduler_kwargs={"http_port": 0}
        ) as cluster:
            async with Client(cluster.scheduler_address) as c:
                a = [c.submit(work, i, key=f"ga-{i}") for i in range(8)]
                b = [
                    c.submit(lambda x, y: x + y, a[i], a[i + 1],
                             key=f"gb-{i}")
                    for i in range(0, 6, 2)
                ]
                await c.gather(b)
                port = cluster.scheduler.http_server.port
                loop = asyncio.get_running_loop()

                def get(p):
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{p}"
                    ) as r:
                        return json.loads(r.read())

                g = await loop.run_in_executor(None, get, "/api/v1/graph")
                assert g["nodes"] and g["edges"]
                for src, dst in g["edges"]:
                    assert g["nodes"][src]["layer"] < g["nodes"][dst]["layer"]
                prof = await loop.run_in_executor(
                    None, get, "/api/v1/profile"
                )
                assert "count" in prof and "children" in prof

                def fetch_html():
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/dashboard"
                    ) as r:
                        return r.read().decode()

                html = await loop.run_in_executor(None, fetch_html)
                for needle in ("drawGraph", "drawFlame",
                               "/api/v1/graph", "/api/v1/profile"):
                    assert needle in html, needle


@gen_test(timeout=120)
async def test_worker_proxy_pages_with_deaths():
    """Per-worker pages THROUGH the scheduler (reference http/proxy.py
    role): health / metrics / profile / info render for live workers
    and stay serviceable while workers die mid-run."""
    import functools
    import json as _json
    import urllib.request

    async def fetch(url, expect_status=200):
        loop = asyncio.get_running_loop()

        def get(u):
            import urllib.error

            try:
                r = urllib.request.urlopen(u, timeout=10)
                return r.status, r.read()
            except urllib.error.HTTPError as e:
                return e.code, e.read()

        status, body = await loop.run_in_executor(
            None, functools.partial(get, url)
        )
        assert status == expect_status, (url, status, body[:200])
        return body

    def slow(x):
        import time as _t

        _t.sleep(0.05)
        return x + 1

    async with LocalCluster(n_workers=4, threads_per_worker=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            port = cluster.scheduler.http_server.port
            base = f"http://127.0.0.1:{port}"
            futs = c.map(slow, range(40), pure=False)

            idx = _json.loads(await fetch(f"{base}/workers/"))
            assert len(idx) == 4
            name = idx[0]["name"]
            health = _json.loads(await fetch(f"{base}/workers/{name}/health"))
            assert health["ok"] is True
            metrics = _json.loads(
                await fetch(f"{base}/workers/{name}/metrics")
            )
            assert metrics["worker"] == idx[0]["address"]
            prof = _json.loads(await fetch(f"{base}/workers/{name}/profile"))
            assert isinstance(prof, dict)
            info = _json.loads(await fetch(f"{base}/workers/{name}/info"))
            assert info["nthreads"] == 1

            # two workers die mid-run: the proxy keeps answering — the
            # index shrinks, a dead name 404s gracefully, survivors serve
            victims = [w for w in cluster.workers[:2]]
            dead_names = [str(w.name) for w in victims]
            for w in victims:
                await w.close(report=False)
            cluster.workers = cluster.workers[2:]
            deadline = asyncio.get_running_loop().time() + 30
            while len(cluster.scheduler.state.workers) > 2:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)
            idx2 = _json.loads(await fetch(f"{base}/workers"))
            assert len(idx2) == 2
            gone = _json.loads(
                await fetch(f"{base}/workers/{dead_names[0]}/health",
                            expect_status=404)
            )
            assert "error" in gone
            survivor = idx2[0]["name"]
            health2 = _json.loads(
                await fetch(f"{base}/workers/{survivor}/health")
            )
            assert health2["ok"] is True
            # the run itself survives the deaths
            assert await asyncio.wait_for(c.gather(futs), 60) == list(
                range(1, 41)
            )


@gen_test(timeout=120)
async def test_performance_report_activity_seconds_spill_workload():
    """The done-criterion for fine metrics (reference metrics.py:159,336):
    a spill-heavy workload's performance report carries per-activity
    seconds — spill serialize/disk-write/disk-read plus the gather-dep
    network/deserialize/other split from the DelayedMetricsLedger."""
    from distributed_tpu import config as dtpu_config

    # pause OFF: a 4 MB memory_limit makes the process-RSS fraction
    # permanently exceed the pause threshold, so on a slow box the
    # 100 ms monitor tick can fire mid-workload and pause both workers
    # FOREVER (nothing ever brings rss under 4 MB) — observed as a 60 s
    # gather timeout.  This test is about spill metering, which keys on
    # managed (fast_bytes) memory and still engages.
    with dtpu_config.set({"worker.memory.pause": 0}):
        await _spill_workload_body()


async def _spill_workload_body():
    import numpy as np

    def chunk(i):
        return np.full((512, 256), float(i))  # ~1 MB

    def combine(a, b):
        return float(a.sum() + b.sum())

    async with LocalCluster(
        n_workers=2,
        threads_per_worker=1,
        worker_kwargs={"memory_limit": 4_000_000,  # ~4 chunks -> spills
                       "heartbeat_interval": 0.1},
    ) as cluster:
        async with Client(cluster.scheduler_address) as c:
            # pin chunks alternately so every combine is cross-worker by
            # construction (scheduler load-balance drift under a loaded
            # box once co-located everything and no gather-dep traffic
            # ever happened)
            addrs = [w.address for w in cluster.workers]
            chunks = [
                c.submit(chunk, i, pure=False, workers=[addrs[i % 2]])
                for i in range(10)
            ]
            outs = [
                c.submit(combine, a, b, pure=False)
                for a, b in zip(chunks[:-1], chunks[1:])
            ]
            await asyncio.wait_for(c.gather(outs), 60)
            # let a couple of heartbeats ship the fine-metric deltas
            deadline = asyncio.get_running_loop().time() + 30
            spans = cluster.scheduler.spans
            def have(context, label):
                return any(
                    k[0] == context and k[3] == label and v > 0
                    for k, v in spans.cumulative_worker_metrics.items()
                )
            while not (have("spill", "disk-write")
                       and have("gather-dep", "network")):
                assert asyncio.get_running_loop().time() < deadline, (
                    dict(spans.cumulative_worker_metrics)
                )
                await asyncio.sleep(0.1)
            html = await cluster.scheduler.performance_report_html()
            assert "Activities (fine metrics)" in html
            for needle in ("disk-write", "network", "deserialize"):
                assert needle in html, needle


@gen_test(timeout=120)
async def test_cluster_dump_artefact_roundtrip():
    """dump_cluster_state -> DumpArtefact: offline post-mortem queries
    (reference cluster_dump.py:111 DumpArtefact)."""
    import os as _os
    import tempfile

    from distributed_tpu.diagnostics.cluster_dump import DumpArtefact

    tdir = tempfile.TemporaryDirectory()
    path = _os.path.join(tdir.name, "dump.json")
    async with LocalCluster(n_workers=2, threads_per_worker=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(lambda x: x + 1, range(6), pure=False)
            assert await asyncio.wait_for(c.gather(futs), 60) == list(
                range(1, 7)
            )
            await c.dump_cluster_state(path)

    d = DumpArtefact.from_file(path)
    assert len(d.workers) == 2
    assert d.state_counts().get("memory", 0) >= 6
    key = futs[0].key
    info = d.worker_of(key)
    assert info["state"] == "memory" and info["who_has"]
    story = d.story(key)
    assert story, "transition log rows for the key must travel in the dump"
    assert any(row[0] == key for row in story)
    summary = d.workers_summary()
    assert all(v["nthreads"] == 1 for v in summary.values())
    # the flight-recorder causal tails ship in the dump by default
    # (PR 6): scheduler last-N plus each node's, and the trace joins
    # the dumped story rows on stimulus id
    assert d.flight_recorder, "scheduler flight-recorder tail missing"
    assert d.trace_tail(cat="engine"), d.flight_recorder[:5]
    assert len(d.worker_traces) == 2, list(d.worker_traces)
    assert all(evs for evs in d.worker_traces.values())
    sid = story[0][4]
    assert d.trace_tail(stim=sid), f"no trace events for stimulus {sid}"
    tdir.cleanup()


@gen_test(timeout=120)
async def test_memory_trace_roundtrip():
    """tracemalloc-backed memory introspection (reference memray role):
    start -> allocate-heavy workload -> report shows allocation sites
    and the data-store view -> stop."""
    import numpy as np

    def allocate(i):
        return np.ones((256, 256)) * i  # ~0.5 MB per task

    async with LocalCluster(n_workers=2, threads_per_worker=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            await c.memory_trace_start()
            futs = c.map(allocate, range(6), pure=False)
            await asyncio.wait_for(c.gather(futs), 60)
            reports = await c.memory_trace_report(top_n=5)
            assert len(reports) == 2
            for addr, rep in reports.items():
                assert rep["status"] == "OK", (addr, rep)
                assert rep["traced_bytes"] > 0
                assert rep["top"] and all(
                    "site" in t and t["bytes"] >= 0 for t in rep["top"]
                )
                assert rep["data_store"]["keys"] >= 0
            stopped = await c.memory_trace_stop()
            # stop is refcounted per server (diagnostics/memtrace.py):
            # each response reports whether the process-global trace is
            # STILL live — only the last owner's stop reads False, and
            # after the broadcast nothing must be tracing
            import tracemalloc

            assert any(r["tracing"] is False for r in stopped.values())
            assert not tracemalloc.is_tracing()


@gen_test(timeout=120)
async def test_device_profile_roundtrip():
    """XLA device-timeline tracing (the reference's low-level profiler
    role, profile.py:550): start -> run jax work (tasks annotated with
    their keys on the device timeline) -> stop reports the trace
    artifact files.  One worker: the XLA profiler is process-global, so
    in-process clusters trace from a single worker (documented in
    diagnostics/device_profile.py)."""
    from distributed_tpu.diagnostics import device_profile

    if not device_profile.available():  # pragma: no cover
        import pytest

        pytest.skip("jax profiler unavailable")

    def devwork(i):
        import jax.numpy as jnp

        return float(jnp.sum(jnp.arange(64.0) * i))

    async with LocalCluster(n_workers=1, threads_per_worker=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            started = await c.device_profile_start()
            assert all(r["status"] == "OK" for r in started.values()), started
            # a second start must fail cleanly, not wedge the profiler
            again = await c.device_profile_start()
            assert all(r["status"] == "error" for r in again.values())
            futs = c.map(devwork, range(4), pure=False)
            assert await asyncio.wait_for(c.gather(futs), 60) == [
                float(sum(range(64)) * i) for i in range(4)
            ]
            stopped = await c.device_profile_stop()
            for rep in stopped.values():
                assert rep["status"] == "OK", rep
                # the XLA profiler wrote its TensorBoard/XProf artifact
                assert rep["files"], rep
                assert any("plugins/profile" in f for f in rep["files"])
            # stop without a trace running errors cleanly
            idle = await c.device_profile_stop()
            assert all(r["status"] == "error" for r in idle.values())


@gen_test()
async def test_group_timing_buckets():
    """GroupTiming (reference progress.py:344 role): compute seconds
    aggregate into wall-clock buckets per prefix."""
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            import time as _t

            def work(x):
                _t.sleep(0.05)
                return x

            futs = [c.submit(work, i, key=f"gt-{i}") for i in range(6)]
            await c.gather(futs)
            data = await c.scheduler.get_group_timing()
            assert data["bucket_s"] > 0
            assert "gt" in data["series"], data["series"].keys()
            total = sum(data["series"]["gt"])
            assert 0.2 < total < 3.0, total  # ~6 x 50ms of compute


@gen_test()
async def test_eventstream_topic():
    """Opt-in eventstream publishes per-task events on a topic
    (reference diagnostics/eventstream.py role)."""
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            topic = await c.scheduler.eventstream_start()
            assert topic == "task-events"
            await c.submit(lambda: 41, key="ev-1").result()
            events = await c.get_events(topic)
            acts = [m.get("action") for _, m in events]
            assert "task-finished" in acts, events
            keys = [m.get("key") for _, m in events]
            assert "ev-1" in keys
            await c.scheduler.eventstream_stop()
            n = len(await c.get_events(topic))
            await c.submit(lambda: 42, key="ev-2").result()
            assert len(await c.get_events(topic)) == n  # stopped


# --------------------------------------------------------- flight recorder


def _build_trace_state(n_workers=4, n_tasks=60):
    """Deterministic SchedulerState + pending graph for record/replay
    tests (same construction = same starting state, the replay
    contract's precondition; docs/observability.md)."""
    from distributed_tpu.graph.spec import TaskSpec
    from distributed_tpu.scheduler.state import SchedulerState

    state = SchedulerState(validate=True)
    for i in range(n_workers):
        state.add_worker_state(
            f"tcp://fr:{i}", nthreads=2, memory_limit=2**30, name=f"fr{i}"
        )
    tasks = {f"fr-{i}": TaskSpec(lambda: i) for i in range(n_tasks)}
    deps = {f"fr-{i}": set() for i in range(n_tasks)}
    # a dependent layer so the flood cascades through waiting->processing
    for i in range(0, n_tasks, 3):
        tasks[f"frd-{i}"] = TaskSpec(lambda x: x)
        deps[f"frd-{i}"] = {f"fr-{i}", f"fr-{(i + 1) % n_tasks}"}
    state.update_graph_core(
        tasks, deps, list(tasks), client="frc",
        stimulus_id="fr-graph",
    )
    return state


def _flood_to_memory(state):
    """Report every processing task finished, in payload-sized batches,
    until the whole graph is in memory — the multi-flood run."""
    rounds = 0
    while True:
        batch = [
            (ts.key, ws.address, f"fr-fin-{ts.key}", {"nbytes": 16})
            for ws in state.workers.values()
            for ts in list(ws.processing)
        ]
        if not batch:
            break
        state.stimulus_tasks_finished_batch(batch)
        rounds += 1
        assert rounds < 10_000
    return rounds


def test_record_replay_round_trip():
    """ACCEPTANCE (PR 6): a recorded stimulus trace of a multi-flood run
    re-fed through the batched engine offline reproduces the identical
    transition stream (key, start, finish, stimulus, order)."""
    from distributed_tpu.diagnostics.flight_recorder import (
        replay_stimulus_trace,
        transition_stream,
        verify_journal,
    )

    rec = _build_trace_state()
    mark = len(rec.transition_log)
    rec.trace.journal_start()
    rounds = _flood_to_memory(rec)
    assert rounds >= 2, "not a multi-flood run"
    records = list(rec.trace.journal)
    assert records and all(r["v"] == 1 for r in records)
    # floods journal as ONE record per engine batch (the durable-
    # capture hot-path format; scalar "task-finished" remains for the
    # single-RPC path)
    assert all(
        r["op"] in ("tasks-finished-batch", "transitions") for r in records
    )
    verify_journal(records)

    rep = _build_trace_state()
    mark_b = len(rep.transition_log)
    cm, wm = replay_stimulus_trace(rep, records)
    recorded = transition_stream(rec, mark)
    replayed = transition_stream(rep, mark_b)
    assert recorded, "flood produced no transitions"
    assert recorded == replayed
    # terminal states agree too, not just the log
    assert {k: ts.state for k, ts in rec.tasks.items()} == {
        k: ts.state for k, ts in rep.tasks.items()
    }
    # an edited journal must refuse to replay...
    import pytest

    tampered = [dict(r) for r in records]
    tampered[3] = dict(tampered[3], payload={"key": "tampered"})
    with pytest.raises(ValueError, match="digest"):
        replay_stimulus_trace(_build_trace_state(), tampered)
    # ...and so must a head-truncated one (deque overflow evicts the
    # OLDEST records; replaying from the wrong start would silently
    # present a divergent stream as faithful)
    with pytest.raises(ValueError, match="complete capture"):
        replay_stimulus_trace(_build_trace_state(), records[2:])


def test_record_replay_erred_and_transitions_ops():
    """The journal covers the erred arm and bare recommendation rounds,
    and replay folds mixed consecutive runs correctly."""
    from distributed_tpu.diagnostics.flight_recorder import (
        replay_stimulus_trace,
        transition_stream,
    )

    def drive(state):
        state.trace.journal_start()
        procs = [
            (ts.key, ws.address)
            for ws in state.workers.values()
            for ts in list(ws.processing)
        ]
        fin = [(k, a, f"mx-fin-{k}", {"nbytes": 8}) for k, a in procs[:3]]
        err = [
            (k, a, f"mx-err-{k}", {"exception_text": "boom"})
            for k, a in procs[3:5]
        ]
        state.stimulus_tasks_finished_batch(fin)
        state.stimulus_tasks_erred_batch(err)
        # the replica-release plane (AMM drops): the removal mutates
        # state OUTSIDE the engine and is journaled as its own op,
        # followed by the engine round it recommended
        rel_key, rel_addr = fin[0][0], fin[0][1]
        recs = state.stimulus_release_worker_data(
            rel_key, rel_addr, "mx-rwd"
        )
        if recs:
            state.transitions(recs, "mx-rwd")
        # a bare recommendation round (the release plane)
        state.transitions({procs[5][0]: "released"}, "mx-rel")
        return state

    rec = _build_trace_state()
    mark = len(rec.transition_log)
    drive(rec)
    ops = [r["op"] for r in rec.trace.journal]
    assert "tasks-finished-batch" in ops and "task-erred" in ops
    assert "release-worker-data" in ops and "transitions" in ops

    rep = _build_trace_state()
    mark_b = len(rep.transition_log)
    replay_stimulus_trace(rep, list(rec.trace.journal))
    assert transition_stream(rec, mark) == transition_stream(rep, mark_b)
    # the replayed removal really happened: replica sets agree
    assert {
        k: sorted(ws.address for ws in ts.who_has)
        for k, ts in rec.tasks.items()
    } == {
        k: sorted(ws.address for ws in ts.who_has)
        for k, ts in rep.tasks.items()
    }
    # a record whose digest field was DROPPED (not just stale) is an
    # edit too — verification must refuse, not silently skip
    import pytest

    clipped = [dict(r) for r in rec.trace.journal]
    clipped[1].pop("digest")
    with pytest.raises(ValueError, match="missing"):
        replay_stimulus_trace(_build_trace_state(), clipped)


def test_flight_recorder_ring_and_sampling():
    from distributed_tpu.tracing import FlightRecorder

    tr = FlightRecorder(ring_size=8, enabled=True, sample=1,
                        journal=False, journal_size=4)
    for i in range(20):
        tr.emit("engine", "e", f"s-{i}", n=i)
    assert tr.total == 20
    assert len(tr) == 8
    tail = tr.tail()
    assert [ev["n"] for ev in tail] == list(range(12, 20))
    assert [ev["seq"] for ev in tail] == list(range(12, 20))
    assert tr.tail(3)[0]["n"] == 17
    # disabled recorder emits nothing; sampling keeps 1-in-N
    off = FlightRecorder(ring_size=8, enabled=False)
    off.emit("engine", "e", "s")
    assert off.total == 0
    sam = FlightRecorder(ring_size=64, enabled=True, sample=4)
    for _ in range(40):
        sam.emit_task("transition", "memory", "s")
    assert sam.total == 10


def test_perfetto_export_schema_and_cli(tmp_path):
    """ACCEPTANCE (PR 6): the Perfetto export of a traced run is valid
    Chrome trace_event JSON (schema-validated, no browser needed), via
    both the API and the CLI."""
    import subprocess
    import sys as _sys

    from distributed_tpu.diagnostics.flight_recorder import to_perfetto
    from distributed_tpu.tracing import to_jsonl

    state = _build_trace_state()
    _flood_to_memory(state)
    events = state.trace.tail()
    assert events
    doc = to_perfetto(events)
    # trace_event JSON-object format contract
    assert isinstance(doc["traceEvents"], list)
    assert doc["displayTimeUnit"] in ("ms", "ns")
    cats = set()
    for ev in doc["traceEvents"]:
        assert set(ev) >= {"name", "ph", "ts", "pid", "tid"}, ev
        # "C" = counter samples (shadow divergence / telemetry tracks)
        assert ev["ph"] in ("i", "M", "X", "C")
        if ev["ph"] == "i":
            assert isinstance(ev["ts"], float) and ev["ts"] >= 0
            assert ev["s"] in ("t", "p", "g")
            cats.add(ev["cat"])
    # a bare SchedulerState run has no server, so only the engine-side
    # categories appear here; ingress/egress tracks are asserted on the
    # live cluster in test_trace_endpoint_and_histograms_live
    assert {"engine", "transition"} <= cats
    json.dumps(doc)  # round-trippable

    # CLI: JSONL file in, perfetto JSON out
    src = tmp_path / "trace.jsonl"
    src.write_text(to_jsonl(events))
    out = tmp_path / "out.json"
    proc = subprocess.run(
        [_sys.executable, "-m",
         "distributed_tpu.diagnostics.flight_recorder",
         "--input", str(src), "--perfetto", str(out)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc2 = json.loads(out.read_text())
    assert len(doc2["traceEvents"]) == len(doc["traceEvents"])
    # a newer schema major is refused, not mis-rendered
    import pytest

    with pytest.raises(ValueError, match="schema"):
        to_perfetto([{"v": 99, "cat": "engine", "ts": 0.0}])


@gen_test()
async def test_trace_endpoint_and_histograms_live():
    """/trace on both roles serves the schema-versioned JSONL tail, one
    stimulus id joins ingress -> engine -> egress across it, and the
    engine/egress histograms appear on /metrics with observations."""
    from distributed_tpu.tracing import from_jsonl

    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(lambda x: x + 3, range(12), pure=False)
            await c.gather(futs)
            sport = cluster.scheduler.http_server.port
            status, body = await http_get(sport, "/trace")
            assert status == 200
            events = from_jsonl(body)
            assert events and all(ev["v"] == 1 for ev in events)
            by_cat = {}
            for ev in events:
                by_cat.setdefault(ev["cat"], []).append(ev)
            assert by_cat.get("ingress") and by_cat.get("engine")
            assert by_cat.get("egress") and by_cat.get("transition")
            # causal join: some task-finished stimulus appears at
            # ingress AND in the engine pass it folded into
            fin_stims = {
                ev["stim"] for ev in by_cat["ingress"]
                if ev["name"] == "task-finished"
            }
            assert fin_stims & {
                ev["stim"]
                for ev in by_cat["engine"] + by_cat["transition"]
            }
            # the update-graph ingress joins the compute-task egress
            ug = [ev for ev in by_cat["ingress"]
                  if ev["name"] == "update-graph"]
            assert ug and any(
                ev["stim"] == ug[-1]["stim"] for ev in by_cat["egress"]
            )
            # worker role serves its own stimulus timeline
            wport = cluster.workers[0].http_server.port
            status, body = await http_get(wport, "/trace")
            assert status == 200
            wevents = from_jsonl(body)
            assert wevents and all(
                ev["cat"] == "wstim" for ev in wevents
            )
            assert any(ev["name"] == "ComputeTaskEvent" for ev in wevents)
            # histograms made it to /metrics with real observations
            status, body = await http_get(sport, "/metrics")
            text = body.decode()
            for needle in (
                'dtpu_engine_pass_seconds_bucket{le="+Inf"}',
                "dtpu_engine_transition_batch_size_count",
                "dtpu_egress_envelope_msgs_sum",
                "dtpu_trace_events_total",
            ):
                assert needle in text, needle
            count = [
                ln for ln in text.splitlines()
                if ln.startswith("dtpu_engine_pass_seconds_count")
            ][0]
            assert float(count.split()[-1]) > 0


@gen_test()
async def test_route_index_ledger_and_build_info_live():
    """The "/" route index lists every observability route on BOTH
    roles, /ledger serves the decision–outcome snapshot on the
    scheduler, and /metrics carries the dtpu_build_info identity gauge
    (docs/observability.md "Decision ledger & critical-path")."""
    import json as _json

    from distributed_tpu.tracing import from_jsonl

    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            await c.gather(c.map(lambda x: x + 1, range(8), pure=False))
            sport = cluster.scheduler.http_server.port
            status, body = await http_get(sport, "/")
            assert status == 200
            idx = _json.loads(body)
            assert idx["role"] == "scheduler"
            assert {
                "/metrics", "/trace", "/telemetry", "/profile", "/ledger",
            } <= set(idx["routes"])
            wport = cluster.workers[0].http_server.port
            status, body = await http_get(wport, "/")
            assert status == 200
            widx = _json.loads(body)
            assert widx["role"] == "worker"
            assert {
                "/metrics", "/trace", "/telemetry", "/profile",
            } <= set(widx["routes"])
            # /ledger: summary head + row tail, every flood placement
            # joined to its memory outcome
            status, body = await http_get(sport, "/ledger")
            assert status == 200
            recs = from_jsonl(body)
            assert recs[0]["type"] == "ledger-summary"
            assert recs[0]["outcomes"].get("memory", 0) >= 8
            rows = [r for r in recs if r["type"] == "ledger-row"]
            assert rows and all(r["v"] == 1 for r in rows)
            # the RPC twin serves the same snapshot shape
            rpc = await c.scheduler.get_ledger(n=4)
            assert rpc[0]["type"] == "ledger-summary"
            assert len(rpc) == 5
            # build info on both roles
            for port, role in ((sport, "scheduler"), (wport, "worker")):
                status, body = await http_get(port, "/metrics")
                line = [
                    ln for ln in body.decode().splitlines()
                    if ln.startswith("dtpu_build_info{")
                ][0]
                assert f'role="{role}"' in line
                assert line.endswith(" 1")
            # ledger regret families made it to the exposition
            status, body = await http_get(sport, "/metrics")
            text = body.decode()
            assert "dtpu_ledger_rows_total" in text
            assert "dtpu_ledger_joined_total" in text


def test_rate_limiter_filter():
    import logging

    from distributed_tpu.utils.misc import RateLimiterFilter

    f = RateLimiterFilter("spammy", rate=60.0)
    rec = logging.LogRecord("test-rlf", logging.INFO, "f", 1,
                            "spammy message", (), None)
    other = logging.LogRecord("test-rlf", logging.INFO, "f", 1,
                              "normal message", (), None)
    assert f.filter(rec) is True      # first passes
    assert f.filter(rec) is False     # repeat suppressed
    assert f.filter(other) is True    # non-matching always passes


@gen_test()
async def test_computations_track_submissions():
    """Computation objects group each update_graph batch
    (reference scheduler.py:864)."""
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            await c.gather([c.submit(lambda x: x, i, key=f"ca-{i}")
                            for i in range(3)])
            await c.gather([c.submit(lambda x: -x, i, key=f"cb-{i}")
                            for i in range(2)])
            comps = await c.scheduler.get_computations()
            assert len(comps) >= 2
            names = [set(co["groups"]) for co in comps]
            assert any("ca" in ns for ns in names)
            assert any("cb" in ns for ns in names)
            last = comps[-1]
            assert last["states"].get("memory", 0) + last["states"].get(
                "forgotten", 0
            ) > 0
            assert last["stop"] >= last["start"] or last["stop"] == 0.0


@gen_test()
async def test_computations_resubmission_does_not_duplicate():
    """Resubmitting known keys neither re-attributes old groups to a
    fresh Computation nor floods the bounded history deque."""
    from distributed_tpu.graph.spec import Graph, TaskSpec

    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            def build():
                g = Graph()
                for i in range(3):
                    g.tasks[f"rs-{i}"] = TaskSpec(lambda: 7)
                return g

            outs = [f"rs-{i}" for i in range(3)]
            futs = c.compute_graph(build(), outs)
            await c.gather([futs[k] for k in outs])
            comps = cluster.scheduler.state.computations
            assert sum(1 for co in comps if co.groups) == 1
            n0 = len(comps)
            # resubmit the SAME graph repeatedly (keys known, futures
            # held): no group may be re-attributed, and the bounded
            # history must not grow beyond one trailing empty entry
            for _ in range(5):
                futs2 = c.compute_graph(build(), outs)
                await c.gather([futs2[k] for k in outs])
            attributed = sum(1 for co in comps if co.groups)
            assert attributed == 1, [
                (co.id, sorted(tg.name for tg in co.groups)) for co in comps
            ]
            assert len(comps) <= n0 + 1  # at most one trailing empty


def test_metrics_names_unique_and_documented():
    """Every `dtpu_*` line each exposition emits must be unique (no
    duplicate samples, Prometheus rejects them) and documented in the
    consolidated docs/observability.md metric table — so the metric
    surface cannot drift away from its documentation."""
    from pathlib import Path

    from distributed_tpu.http.server import scheduler_metrics, worker_metrics
    from distributed_tpu.scheduler.state import SchedulerState
    from distributed_tpu.worker.state_machine import WorkerState

    from distributed_tpu.telemetry import LinkTelemetry

    from distributed_tpu.diagnostics.selfprofile import (
        ControlPlaneProfiler,
        LoopWatchdog,
    )

    class _Stealing:
        count = 3

    class _Sched:
        state = SchedulerState()
        extensions = {"stealing": _Stealing()}
        # self-profiling plane (diagnostics/selfprofile.py): the parity
        # gate must cover dtpu_wall_/dtpu_profile_/dtpu_loop_ families
        cp_profiler = ControlPlaneProfiler(idents=lambda: [])
        watchdog = LoopWatchdog()

    _Sched.watchdog.tick()
    with _Sched.state.wall.phase("engine.drain", "pm-stim"):
        pass

    # one task so the labeled per-state samples are exercised
    _Sched.state.new_task("metrics-k", None)
    # seed the telemetry plane so every dtpu_link_/dtpu_prior_/
    # dtpu_costmodel_ family is exercised (the parity gate must cover
    # the full measured-truth surface)
    tel = _Sched.state.telemetry
    tel.fold_rows(
        [["tcp://pm:1", "tcp://pm:2", 1_000_000, 0.01, 2]],
        reporter="tcp://pm:2",
    )
    tel.fold_rows(
        [["tcp://pm:1", "tcp://pm:2", 1_100_000, 0.01, 2]],
        reporter="tcp://pm:1",
    )
    tel.record_rtt("tcp://pm:2", 0.002)
    tel.fold_fine_rows([
        ["execute", "", "inc", "compute", "seconds", 0.5],
        ["execute", "", "inc", "output", "bytes", 1000.0],
        ["execute", "", "inc", "count", "tasks", 2],
    ])
    tel.observe_divergence(1.0, 0.1, True)
    # seed the decision ledger so every dtpu_ledger_* family is
    # exercised (ledger.py; docs/observability.md "Decision ledger"):
    # one joined dep-bearing row populates the regret histograms and
    # the per-prefix/per-link aggregates, one open row the gauge
    led = _Sched.state.ledger
    h = led.file(
        "placement", "pm-led-k", "inc", "tcp://pm:2", "pm-stim",
        0.01, 0.02, True, 4096, 1, 0.5, "tcp://pm:1", "",
    )
    led.join_row(h, "memory", "tcp://pm:2", None, 0.4, tel)
    led.file("steal", "pm-led-open", "inc", "tcp://pm:2", "pm-stim2")
    # seed the sharded-engine + sharded-mirror families (the mesh plan
    # path, PR 8): a real sharded_device_view over the conftest CPU
    # mesh populates the per-shard mirror counters, and one folded
    # engine-shard stat row populates dtpu_engine_shard_*
    _Sched.state.add_worker_state(
        "tcp://pm:9", nthreads=1, memory_limit=2**30, name="pm9"
    )
    from distributed_tpu.ops.partition import make_engine_mesh

    _Sched.state.mirror.sharded_device_view(make_engine_mesh(layout="4x2"))
    _Sched.state.observe_engine_shards(
        [{"shard": 0, "kernel_ms": 0.5, "h2d_bytes": 1024},
         {"shard": 1, "kernel_ms": 0.6, "h2d_bytes": 1024}]
    )
    # seed the native transition engine (scheduler/native_engine.py) so
    # the dtpu_engine_native_* families are exercised where the
    # toolchain exists; a no-g++ box skips them (graceful fallback is
    # the contract, and the names stay documented either way)
    _Sched.state.attach_native(build=True)
    # seed scheduler durability (scheduler/durability.py) so the
    # dtpu_durability_* family is exercised: an attached manager with
    # one epoch's stats
    from distributed_tpu.scheduler.durability import (
        DurabilityManager,
        MemorySink,
    )

    _Sched.durability = DurabilityManager(_Sched.state, MemorySink())
    _Sched.durability.snapshot(full=True)
    # seed the state census + retention sentinel on both roles so every
    # dtpu_census_* family is exercised (diagnostics/census.py;
    # docs/observability.md "State census & retention")
    from distributed_tpu.diagnostics.census import RetentionSentinel

    _Sched.state.census.sentinel = RetentionSentinel(
        _Sched.state.census, trace=_Sched.state.trace
    )
    _Sched.state.census.sentinel.tick()

    class _SpillDict(dict):  # enables the spill metric lines
        spilled_count = 0
        slow_bytes = 0

    class _Worker:
        state = WorkerState(nthreads=1)
        data = _SpillDict()
        get_data_wire_bytes = 0
        telemetry = LinkTelemetry()
        cp_profiler = ControlPlaneProfiler(idents=lambda: [])
        watchdog = LoopWatchdog()

    _Worker.telemetry.record("tcp://pm:2", "tcp://pm:3", 1000, 0.001)
    with _Worker.state.wall.phase("wengine.stimulus", "pm-stim"):
        pass
    _Worker.state.census.sentinel = RetentionSentinel(
        _Worker.state.census, trace=_Worker.state.trace
    )
    _Worker.state.census.sentinel.tick()

    repo = Path(__file__).resolve().parent.parent
    docs = (repo / "docs/observability.md").read_text()

    all_names: set[str] = set()
    for blob in (scheduler_metrics(_Sched()), worker_metrics(_Worker())):
        seen_samples: set[str] = set()
        declared: set[str] = set()
        for line in blob.decode().splitlines():
            if not line:
                continue
            if line.startswith("# TYPE "):
                name = line.split()[2]
                assert name not in declared, f"duplicate TYPE for {name}"
                declared.add(name)
                continue
            if line.startswith("#"):
                continue
            sample = line.rsplit(" ", 1)[0]  # "name{labels}" or "name"
            name = sample.split("{", 1)[0]
            assert name.startswith("dtpu_"), line
            assert sample not in seen_samples, f"duplicate sample {sample}"
            seen_samples.add(sample)
            all_names.add(name)

    # the full surface must be present in this test's expositions —
    # including the engine/egress histogram families, the flight-
    # recorder gauges (PR 6), and the telemetry plane (PR 7)
    assert {"dtpu_scheduler_tasks", "dtpu_worker_tasks_executing",
            "dtpu_wire_pool_bytes", "dtpu_stealing_moves_total",
            "dtpu_worker_spill_count_total",
            "dtpu_engine_transition_batch_size_bucket",
            "dtpu_engine_transition_batch_size_sum",
            "dtpu_engine_transition_batch_size_count",
            "dtpu_engine_pass_seconds_bucket",
            "dtpu_egress_envelope_msgs_bucket",
            "dtpu_trace_events_total",
            "dtpu_trace_ring_events",
            "dtpu_link_bandwidth_bytes_per_second",
            "dtpu_link_latency_seconds",
            "dtpu_link_transfer_bytes_total",
            "dtpu_link_samples_total",
            "dtpu_link_served_wire_bytes_total",
            "dtpu_link_heartbeat_rtt_seconds",
            "dtpu_prior_duration_seconds",
            "dtpu_prior_nbytes",
            "dtpu_prior_tasks_total",
            "dtpu_costmodel_divergence_ratio_bucket",
            "dtpu_costmodel_divergence_ratio_sum",
            "dtpu_costmodel_divergence_ratio_count",
            "dtpu_costmodel_shadow_evals_total",
            "dtpu_costmodel_shadow_measured_total",
            "dtpu_build_info",
            "dtpu_ledger_rows_total",
            "dtpu_ledger_joined_total",
            "dtpu_ledger_unjoined_total",
            "dtpu_ledger_superseded_total",
            "dtpu_ledger_open_rows",
            "dtpu_ledger_regret_seconds_bucket",
            "dtpu_ledger_regret_seconds_sum",
            "dtpu_ledger_regret_seconds_count",
            "dtpu_ledger_prefix_regret_seconds_total",
            "dtpu_ledger_prefix_decisions_total",
            "dtpu_ledger_link_regret_seconds_total",
            "dtpu_ledger_link_transfer_seconds_total",
            "dtpu_ledger_link_decisions_total",
            "dtpu_durability_snapshot_seconds_total",
            "dtpu_durability_snapshot_bytes_total",
            "dtpu_durability_snapshot_rows_total",
            "dtpu_durability_epochs_total",
            "dtpu_durability_base_epochs_total",
            "dtpu_durability_journal_records_total",
            "dtpu_durability_journal_bytes_total",
            "dtpu_durability_replay_records",
            "dtpu_durability_restore_seconds",
            "dtpu_durability_torn_records_total",
            "dtpu_durability_reconcile_corrections_total",
            "dtpu_durability_recovery_awaiting_workers",
            "dtpu_mirror_shard_rows_uploaded_total",
            "dtpu_mirror_shard_bytes_uploaded_total",
            "dtpu_mirror_shard_full_packs_total",
            "dtpu_engine_shard_kernel_ms",
            "dtpu_engine_shard_h2d_bytes_total",
            "dtpu_wall_seconds_total",
            "dtpu_wall_phase_entries_total",
            "dtpu_profile_samples_total",
            "dtpu_profile_idle_samples_total",
            "dtpu_loop_lag_seconds_bucket",
            "dtpu_loop_lag_seconds_sum",
            "dtpu_loop_lag_seconds_count",
            "dtpu_loop_ticks_total",
            "dtpu_loop_stalls_total",
            "dtpu_census_families",
            "dtpu_census_quiesced",
            "dtpu_census_count",
            "dtpu_census_growth_per_s",
            "dtpu_census_audits_total",
            "dtpu_census_audit_failures_total",
            "dtpu_census_findings_total",
            "dtpu_census_leaks_flagged_total"} <= all_names
    if _Sched.state.native is not None:
        assert {"dtpu_engine_native_transitions_total",
                "dtpu_engine_native_escapes_total",
                "dtpu_engine_native_oracle_transitions_total",
                "dtpu_engine_hydrations_total",
                "dtpu_engine_hydration_cache_hits_total",
                "dtpu_engine_hydration_cache_rows"} <= all_names
    undocumented = sorted(n for n in all_names if n not in docs)
    assert not undocumented, (
        f"metrics missing from the docs/observability.md table: "
        f"{undocumented}"
    )
