"""Observability tests: HTTP routes, Prometheus, SystemMonitor, task
stream, profiler, events (reference http/*/tests, test_events patterns)."""

from __future__ import annotations

import asyncio
import json
import time as _time

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.local import LocalCluster
from distributed_tpu.scheduler.server import Scheduler
from distributed_tpu.worker.server import Worker

from conftest import gen_test


async def new_cluster(**kwargs):
    cluster = LocalCluster(
        n_workers=kwargs.pop("n_workers", 2),
        scheduler_kwargs={"validate": True, **kwargs.pop("scheduler_kwargs", {})},
        worker_kwargs={"validate": True, **kwargs.pop("worker_kwargs", {})},
        **kwargs,
    )
    await cluster._start()
    return cluster


async def http_get(port: int, path: str) -> tuple[int, bytes]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.0\r\n\r\n".encode())
    await writer.drain()
    data = await reader.read()
    writer.close()
    head, _, body = data.partition(b"\r\n\r\n")
    status = int(head.split()[1])
    return status, body


@gen_test()
async def test_http_health_info_metrics():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(lambda x: x + 1, range(5))
            await c.gather(futs)
            port = cluster.scheduler.http_server.port
            status, body = await http_get(port, "/health")
            assert status == 200 and body == b"ok"
            status, body = await http_get(port, "/info")
            info = json.loads(body)
            assert info["type"] == "Scheduler"
            assert len(info["workers"]) == 2
            status, body = await http_get(port, "/metrics")
            text = body.decode()
            assert "dtpu_scheduler_workers 2" in text
            assert "dtpu_scheduler_tasks" in text
            status, body = await http_get(port, "/json/counts.json")
            counts = json.loads(body)
            assert counts["workers"] == 2
            status, _ = await http_get(port, "/nope")
            assert status == 404
            # worker metrics too
            wport = cluster.workers[0].http_server.port
            status, body = await http_get(wport, "/metrics")
            assert b"dtpu_worker_tasks_stored" in body


@gen_test()
async def test_system_monitor_samples():
    async with await new_cluster(n_workers=1) as cluster:
        mon = cluster.scheduler.monitor
        mon.update()
        mon.update()
        recent = mon.recent()
        assert recent["memory"] > 0
        rq = mon.range_query()
        assert len(rq["time"]) >= 2


@gen_test()
async def test_task_stream_records():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(lambda x: x * 2, range(6), pure=False)
            await c.gather(futs)
            stream = await c.get_task_stream()
            assert len(stream) == 6
            rec = stream[0]
            assert rec["worker"] is not None
            assert rec["startstops"] and rec["startstops"][0]["action"] == "compute"


@gen_test(timeout=60)
async def test_profile_collects_samples():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            def busy(x):
                t0 = _time.time()
                while _time.time() - t0 < 0.5:
                    sum(range(1000))
                return x

            fut = c.submit(busy, 1)
            await fut.result()
            prof = await c.profile()
            assert prof["count"] > 0
            # the busy function appears somewhere in the tree
            def find(node):
                if "busy" in node.get("description", ""):
                    return True
                return any(find(ch) for ch in node.get("children", {}).values())

            assert find(prof)


@gen_test()
async def test_events_and_subscription():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            seen: list = []
            c.subscribe_topic("my-topic", seen.append)
            await asyncio.sleep(0.05)
            c.log_event("my-topic", {"x": 1})
            for _ in range(100):
                if seen:
                    break
                await asyncio.sleep(0.01)
            assert seen == [{"x": 1}]
            events = await c.get_events("my-topic")
            assert len(events) == 1
            assert events[0][1] == {"x": 1}


@gen_test(timeout=60)
async def test_json_api_and_dashboard():
    """Dashboard-lite JSON routes + the self-contained HTML page
    (reference http/scheduler/api.py, dashboard/)."""
    import json as _json
    import urllib.request

    async with await new_cluster(
        n_workers=2, scheduler_kwargs={"http_port": 0}
    ) as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(lambda x: x * 2, range(20), pure=False)
            await c.gather(futs)
            for w in cluster.workers:
                await w.heartbeat()
            port = cluster.scheduler.http_server.port

            def get(path):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5
                ) as r:
                    return r.headers.get_content_type(), r.read()

            loop = asyncio.get_running_loop()
            ct, body = await loop.run_in_executor(None, get, "/api/v1/workers")
            ws = _json.loads(body)
            assert ct == "application/json" and len(ws) == 2
            assert all("managed_bytes" in w and "occupancy" in w for w in ws)

            _, body = await loop.run_in_executor(None, get, "/api/v1/tasks")
            tasks = _json.loads(body)
            assert tasks["by_state"].get("memory", 0) >= 20

            _, body = await loop.run_in_executor(
                None, get, "/api/v1/task_stream"
            )
            stream = _json.loads(body)
            assert len(stream) >= 20
            assert all("startstops" in r for r in stream)

            _, body = await loop.run_in_executor(None, get, "/api/v1/memory")
            mem = _json.loads(body)
            assert len(mem["workers"]) == 2

            ct, body = await loop.run_in_executor(None, get, "/dashboard")
            assert ct == "text/html"
            assert b"task_stream" in body and b"<svg" in body


@gen_test(timeout=60)
async def test_memory_sampler():
    """MemorySampler context manager records a cluster memory timeseries
    (reference diagnostics/memory_sampler.py:180)."""
    import numpy as np

    from distributed_tpu.diagnostics.memory_sampler import MemorySampler

    def chunk(i):
        return np.ones(1_000_000)  # 8 MB

    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            ms = MemorySampler()
            async with ms.sample("run", client=c, interval=0.05):
                futs = c.map(chunk, range(4), pure=False)
                await c.gather(futs)
                await asyncio.sleep(0.3)
            series = ms.to_list("run")
            assert len(series) >= 3
            assert ms.max("run") >= 4 * 8_000_000
            # offsets monotonically increase
            assert all(b[0] > a[0] for a, b in zip(series, series[1:]))


@gen_test()
async def test_progress_bar_tracks_futures():
    """progress() renders until every future settles and reports erred
    counts (reference diagnostics/tests/test_progressbar.py)."""
    import io

    from distributed_tpu.diagnostics.progressbar import progress

    async with Scheduler(listen_addr="inproc://", validate=True) as s:
        async with Worker(s.address, nthreads=2):
            async with Client(s.address) as c:
                futs = c.map(lambda x: x * 2, range(10))
                buf = io.StringIO()
                await asyncio.wait_for(progress(futs, file=buf), 30)
                text = buf.getvalue()
                assert "10/10" in text
                assert text.endswith("\n")
                assert await c.gather(futs) == [x * 2 for x in range(10)]

                bad = c.map(
                    lambda x: 1 // (x % 3), range(6), pure=False
                )
                buf = io.StringIO()
                await asyncio.wait_for(progress(bad, file=buf), 30)
                assert "2 erred" in buf.getvalue()


@gen_test(timeout=120)
async def test_dashboard_profile_and_graph_routes():
    """Dashboard-lite round 4: /api/v1/profile serves the merged worker
    flame-graph call tree and /api/v1/graph a layered dependency graph;
    the HTML page embeds renderers for both (reference
    dashboard/components/scheduler.py profile + graph components,
    diagnostics/graph_layout.py:9)."""
    import json
    import time as _time
    import urllib.request

    from distributed_tpu import config
    from distributed_tpu.client.client import Client
    from distributed_tpu.deploy.local import LocalCluster

    def work(i):
        _time.sleep(0.03)
        return sum(range(50_000)) + i

    with config.set({"worker.profile.enabled": True}):
        async with LocalCluster(
            n_workers=2, scheduler_kwargs={"http_port": 0}
        ) as cluster:
            async with Client(cluster.scheduler_address) as c:
                a = [c.submit(work, i, key=f"ga-{i}") for i in range(8)]
                b = [
                    c.submit(lambda x, y: x + y, a[i], a[i + 1],
                             key=f"gb-{i}")
                    for i in range(0, 6, 2)
                ]
                await c.gather(b)
                port = cluster.scheduler.http_server.port
                loop = asyncio.get_running_loop()

                def get(p):
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{p}"
                    ) as r:
                        return json.loads(r.read())

                g = await loop.run_in_executor(None, get, "/api/v1/graph")
                assert g["nodes"] and g["edges"]
                for src, dst in g["edges"]:
                    assert g["nodes"][src]["layer"] < g["nodes"][dst]["layer"]
                prof = await loop.run_in_executor(
                    None, get, "/api/v1/profile"
                )
                assert "count" in prof and "children" in prof

                def fetch_html():
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/dashboard"
                    ) as r:
                        return r.read().decode()

                html = await loop.run_in_executor(None, fetch_html)
                for needle in ("drawGraph", "drawFlame",
                               "/api/v1/graph", "/api/v1/profile"):
                    assert needle in html, needle


@gen_test(timeout=120)
async def test_worker_proxy_pages_with_deaths():
    """Per-worker pages THROUGH the scheduler (reference http/proxy.py
    role): health / metrics / profile / info render for live workers
    and stay serviceable while workers die mid-run."""
    import functools
    import json as _json
    import urllib.request

    async def fetch(url, expect_status=200):
        loop = asyncio.get_running_loop()

        def get(u):
            import urllib.error

            try:
                r = urllib.request.urlopen(u, timeout=10)
                return r.status, r.read()
            except urllib.error.HTTPError as e:
                return e.code, e.read()

        status, body = await loop.run_in_executor(
            None, functools.partial(get, url)
        )
        assert status == expect_status, (url, status, body[:200])
        return body

    def slow(x):
        import time as _t

        _t.sleep(0.05)
        return x + 1

    async with LocalCluster(n_workers=4, threads_per_worker=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            port = cluster.scheduler.http_server.port
            base = f"http://127.0.0.1:{port}"
            futs = c.map(slow, range(40), pure=False)

            idx = _json.loads(await fetch(f"{base}/workers/"))
            assert len(idx) == 4
            name = idx[0]["name"]
            health = _json.loads(await fetch(f"{base}/workers/{name}/health"))
            assert health["ok"] is True
            metrics = _json.loads(
                await fetch(f"{base}/workers/{name}/metrics")
            )
            assert metrics["worker"] == idx[0]["address"]
            prof = _json.loads(await fetch(f"{base}/workers/{name}/profile"))
            assert isinstance(prof, dict)
            info = _json.loads(await fetch(f"{base}/workers/{name}/info"))
            assert info["nthreads"] == 1

            # two workers die mid-run: the proxy keeps answering — the
            # index shrinks, a dead name 404s gracefully, survivors serve
            victims = [w for w in cluster.workers[:2]]
            dead_names = [str(w.name) for w in victims]
            for w in victims:
                await w.close(report=False)
            cluster.workers = cluster.workers[2:]
            deadline = asyncio.get_running_loop().time() + 30
            while len(cluster.scheduler.state.workers) > 2:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)
            idx2 = _json.loads(await fetch(f"{base}/workers"))
            assert len(idx2) == 2
            gone = _json.loads(
                await fetch(f"{base}/workers/{dead_names[0]}/health",
                            expect_status=404)
            )
            assert "error" in gone
            survivor = idx2[0]["name"]
            health2 = _json.loads(
                await fetch(f"{base}/workers/{survivor}/health")
            )
            assert health2["ok"] is True
            # the run itself survives the deaths
            assert await asyncio.wait_for(c.gather(futs), 60) == list(
                range(1, 41)
            )


@gen_test(timeout=120)
async def test_performance_report_activity_seconds_spill_workload():
    """The done-criterion for fine metrics (reference metrics.py:159,336):
    a spill-heavy workload's performance report carries per-activity
    seconds — spill serialize/disk-write/disk-read plus the gather-dep
    network/deserialize/other split from the DelayedMetricsLedger."""
    from distributed_tpu import config as dtpu_config

    # pause OFF: a 4 MB memory_limit makes the process-RSS fraction
    # permanently exceed the pause threshold, so on a slow box the
    # 100 ms monitor tick can fire mid-workload and pause both workers
    # FOREVER (nothing ever brings rss under 4 MB) — observed as a 60 s
    # gather timeout.  This test is about spill metering, which keys on
    # managed (fast_bytes) memory and still engages.
    with dtpu_config.set({"worker.memory.pause": 0}):
        await _spill_workload_body()


async def _spill_workload_body():
    import numpy as np

    def chunk(i):
        return np.full((512, 256), float(i))  # ~1 MB

    def combine(a, b):
        return float(a.sum() + b.sum())

    async with LocalCluster(
        n_workers=2,
        threads_per_worker=1,
        worker_kwargs={"memory_limit": 4_000_000,  # ~4 chunks -> spills
                       "heartbeat_interval": 0.1},
    ) as cluster:
        async with Client(cluster.scheduler_address) as c:
            # pin chunks alternately so every combine is cross-worker by
            # construction (scheduler load-balance drift under a loaded
            # box once co-located everything and no gather-dep traffic
            # ever happened)
            addrs = [w.address for w in cluster.workers]
            chunks = [
                c.submit(chunk, i, pure=False, workers=[addrs[i % 2]])
                for i in range(10)
            ]
            outs = [
                c.submit(combine, a, b, pure=False)
                for a, b in zip(chunks[:-1], chunks[1:])
            ]
            await asyncio.wait_for(c.gather(outs), 60)
            # let a couple of heartbeats ship the fine-metric deltas
            deadline = asyncio.get_running_loop().time() + 30
            spans = cluster.scheduler.spans
            def have(context, label):
                return any(
                    k[0] == context and k[3] == label and v > 0
                    for k, v in spans.cumulative_worker_metrics.items()
                )
            while not (have("spill", "disk-write")
                       and have("gather-dep", "network")):
                assert asyncio.get_running_loop().time() < deadline, (
                    dict(spans.cumulative_worker_metrics)
                )
                await asyncio.sleep(0.1)
            html = await cluster.scheduler.performance_report_html()
            assert "Activities (fine metrics)" in html
            for needle in ("disk-write", "network", "deserialize"):
                assert needle in html, needle


@gen_test(timeout=120)
async def test_cluster_dump_artefact_roundtrip():
    """dump_cluster_state -> DumpArtefact: offline post-mortem queries
    (reference cluster_dump.py:111 DumpArtefact)."""
    import os as _os
    import tempfile

    from distributed_tpu.diagnostics.cluster_dump import DumpArtefact

    tdir = tempfile.TemporaryDirectory()
    path = _os.path.join(tdir.name, "dump.json")
    async with LocalCluster(n_workers=2, threads_per_worker=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(lambda x: x + 1, range(6), pure=False)
            assert await asyncio.wait_for(c.gather(futs), 60) == list(
                range(1, 7)
            )
            await c.dump_cluster_state(path)

    d = DumpArtefact.from_file(path)
    assert len(d.workers) == 2
    assert d.state_counts().get("memory", 0) >= 6
    key = futs[0].key
    info = d.worker_of(key)
    assert info["state"] == "memory" and info["who_has"]
    story = d.story(key)
    assert story, "transition log rows for the key must travel in the dump"
    assert any(row[0] == key for row in story)
    summary = d.workers_summary()
    assert all(v["nthreads"] == 1 for v in summary.values())
    tdir.cleanup()


@gen_test(timeout=120)
async def test_memory_trace_roundtrip():
    """tracemalloc-backed memory introspection (reference memray role):
    start -> allocate-heavy workload -> report shows allocation sites
    and the data-store view -> stop."""
    import numpy as np

    def allocate(i):
        return np.ones((256, 256)) * i  # ~0.5 MB per task

    async with LocalCluster(n_workers=2, threads_per_worker=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            await c.memory_trace_start()
            futs = c.map(allocate, range(6), pure=False)
            await asyncio.wait_for(c.gather(futs), 60)
            reports = await c.memory_trace_report(top_n=5)
            assert len(reports) == 2
            for addr, rep in reports.items():
                assert rep["status"] == "OK", (addr, rep)
                assert rep["traced_bytes"] > 0
                assert rep["top"] and all(
                    "site" in t and t["bytes"] >= 0 for t in rep["top"]
                )
                assert rep["data_store"]["keys"] >= 0
            stopped = await c.memory_trace_stop()
            assert all(
                r["tracing"] is False for r in stopped.values()
            )


@gen_test(timeout=120)
async def test_device_profile_roundtrip():
    """XLA device-timeline tracing (the reference's low-level profiler
    role, profile.py:550): start -> run jax work (tasks annotated with
    their keys on the device timeline) -> stop reports the trace
    artifact files.  One worker: the XLA profiler is process-global, so
    in-process clusters trace from a single worker (documented in
    diagnostics/device_profile.py)."""
    from distributed_tpu.diagnostics import device_profile

    if not device_profile.available():  # pragma: no cover
        import pytest

        pytest.skip("jax profiler unavailable")

    def devwork(i):
        import jax.numpy as jnp

        return float(jnp.sum(jnp.arange(64.0) * i))

    async with LocalCluster(n_workers=1, threads_per_worker=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            started = await c.device_profile_start()
            assert all(r["status"] == "OK" for r in started.values()), started
            # a second start must fail cleanly, not wedge the profiler
            again = await c.device_profile_start()
            assert all(r["status"] == "error" for r in again.values())
            futs = c.map(devwork, range(4), pure=False)
            assert await asyncio.wait_for(c.gather(futs), 60) == [
                float(sum(range(64)) * i) for i in range(4)
            ]
            stopped = await c.device_profile_stop()
            for rep in stopped.values():
                assert rep["status"] == "OK", rep
                # the XLA profiler wrote its TensorBoard/XProf artifact
                assert rep["files"], rep
                assert any("plugins/profile" in f for f in rep["files"])
            # stop without a trace running errors cleanly
            idle = await c.device_profile_stop()
            assert all(r["status"] == "error" for r in idle.values())


@gen_test()
async def test_group_timing_buckets():
    """GroupTiming (reference progress.py:344 role): compute seconds
    aggregate into wall-clock buckets per prefix."""
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            import time as _t

            def work(x):
                _t.sleep(0.05)
                return x

            futs = [c.submit(work, i, key=f"gt-{i}") for i in range(6)]
            await c.gather(futs)
            data = await c.scheduler.get_group_timing()
            assert data["bucket_s"] > 0
            assert "gt" in data["series"], data["series"].keys()
            total = sum(data["series"]["gt"])
            assert 0.2 < total < 3.0, total  # ~6 x 50ms of compute


@gen_test()
async def test_eventstream_topic():
    """Opt-in eventstream publishes per-task events on a topic
    (reference diagnostics/eventstream.py role)."""
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            topic = await c.scheduler.eventstream_start()
            assert topic == "task-events"
            await c.submit(lambda: 41, key="ev-1").result()
            events = await c.get_events(topic)
            acts = [m.get("action") for _, m in events]
            assert "task-finished" in acts, events
            keys = [m.get("key") for _, m in events]
            assert "ev-1" in keys
            await c.scheduler.eventstream_stop()
            n = len(await c.get_events(topic))
            await c.submit(lambda: 42, key="ev-2").result()
            assert len(await c.get_events(topic)) == n  # stopped


def test_rate_limiter_filter():
    import logging

    from distributed_tpu.utils.misc import RateLimiterFilter

    f = RateLimiterFilter("spammy", rate=60.0)
    rec = logging.LogRecord("test-rlf", logging.INFO, "f", 1,
                            "spammy message", (), None)
    other = logging.LogRecord("test-rlf", logging.INFO, "f", 1,
                              "normal message", (), None)
    assert f.filter(rec) is True      # first passes
    assert f.filter(rec) is False     # repeat suppressed
    assert f.filter(other) is True    # non-matching always passes


@gen_test()
async def test_computations_track_submissions():
    """Computation objects group each update_graph batch
    (reference scheduler.py:864)."""
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            await c.gather([c.submit(lambda x: x, i, key=f"ca-{i}")
                            for i in range(3)])
            await c.gather([c.submit(lambda x: -x, i, key=f"cb-{i}")
                            for i in range(2)])
            comps = await c.scheduler.get_computations()
            assert len(comps) >= 2
            names = [set(co["groups"]) for co in comps]
            assert any("ca" in ns for ns in names)
            assert any("cb" in ns for ns in names)
            last = comps[-1]
            assert last["states"].get("memory", 0) + last["states"].get(
                "forgotten", 0
            ) > 0
            assert last["stop"] >= last["start"] or last["stop"] == 0.0


@gen_test()
async def test_computations_resubmission_does_not_duplicate():
    """Resubmitting known keys neither re-attributes old groups to a
    fresh Computation nor floods the bounded history deque."""
    from distributed_tpu.graph.spec import Graph, TaskSpec

    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            def build():
                g = Graph()
                for i in range(3):
                    g.tasks[f"rs-{i}"] = TaskSpec(lambda: 7)
                return g

            outs = [f"rs-{i}" for i in range(3)]
            futs = c.compute_graph(build(), outs)
            await c.gather([futs[k] for k in outs])
            comps = cluster.scheduler.state.computations
            assert sum(1 for co in comps if co.groups) == 1
            n0 = len(comps)
            # resubmit the SAME graph repeatedly (keys known, futures
            # held): no group may be re-attributed, and the bounded
            # history must not grow beyond one trailing empty entry
            for _ in range(5):
                futs2 = c.compute_graph(build(), outs)
                await c.gather([futs2[k] for k in outs])
            attributed = sum(1 for co in comps if co.groups)
            assert attributed == 1, [
                (co.id, sorted(tg.name for tg in co.groups)) for co in comps
            ]
            assert len(comps) <= n0 + 1  # at most one trailing empty


def test_metrics_names_unique_and_documented():
    """Every `dtpu_*` line each exposition emits must be unique (no
    duplicate samples, Prometheus rejects them) and documented in
    docs/wire.md / docs/scheduler_coprocessor.md — so the metric surface
    cannot drift away from its documentation."""
    from pathlib import Path

    from distributed_tpu.http.server import scheduler_metrics, worker_metrics
    from distributed_tpu.scheduler.state import SchedulerState
    from distributed_tpu.worker.state_machine import WorkerState

    class _Stealing:
        count = 3

    class _Sched:
        state = SchedulerState()
        extensions = {"stealing": _Stealing()}

    # one task so the labeled per-state samples are exercised
    _Sched.state.new_task("metrics-k", None)

    class _SpillDict(dict):  # enables the spill metric lines
        spilled_count = 0
        slow_bytes = 0

    class _Worker:
        state = WorkerState(nthreads=1)
        data = _SpillDict()
        get_data_wire_bytes = 0

    repo = Path(__file__).resolve().parent.parent
    docs = "".join(
        (repo / doc).read_text()
        for doc in ("docs/wire.md", "docs/scheduler_coprocessor.md")
    )

    all_names: set[str] = set()
    for blob in (scheduler_metrics(_Sched()), worker_metrics(_Worker())):
        seen_samples: set[str] = set()
        declared: set[str] = set()
        for line in blob.decode().splitlines():
            if not line:
                continue
            if line.startswith("# TYPE "):
                name = line.split()[2]
                assert name not in declared, f"duplicate TYPE for {name}"
                declared.add(name)
                continue
            if line.startswith("#"):
                continue
            sample = line.rsplit(" ", 1)[0]  # "name{labels}" or "name"
            name = sample.split("{", 1)[0]
            assert name.startswith("dtpu_"), line
            assert sample not in seen_samples, f"duplicate sample {sample}"
            seen_samples.add(sample)
            all_names.add(name)

    # the full surface must be present in this test's expositions
    assert {"dtpu_scheduler_tasks", "dtpu_worker_tasks_executing",
            "dtpu_wire_pool_bytes", "dtpu_stealing_moves_total",
            "dtpu_worker_spill_count_total"} <= all_names
    undocumented = sorted(n for n in all_names if n not in docs)
    assert not undocumented, (
        f"metrics missing from docs/wire.md / docs/scheduler_coprocessor.md: "
        f"{undocumented}"
    )
