"""State census + retention sentinel tests (diagnostics/census.py;
docs/observability.md "State census & retention"): registration
completeness, walk-vs-counter audits, quiesce-clean gates, the
deliberately re-introduced unknown_durations leak, and the leak fixes
this instrument drove (worker forget cascade, stealing overlays,
misrouted-completion free-keys, memtrace refcounting)."""

from __future__ import annotations

import asyncio
from collections import defaultdict, deque

import pytest

from distributed_tpu import config
from distributed_tpu.diagnostics.census import (
    CensusParityError,
    CensusResidueError,
    RetentionSentinel,
    StateCensus,
    build_scheduler_census,
    build_worker_census,
)
from distributed_tpu.scheduler.state import SchedulerState
from distributed_tpu.utils import HeapSet, OrderedSet
from distributed_tpu.worker.state_machine import (
    ComputeTaskEvent,
    FreeKeysEvent,
    GatherDepNetworkFailureEvent,
    WorkerState,
)

from conftest import gen_test

CONTAINER_TYPES = (dict, set, frozenset, list, deque, defaultdict,
                   HeapSet, OrderedSet)


def _container_attrs(obj) -> list[str]:
    return [
        name for name, value in vars(obj).items()
        if isinstance(value, CONTAINER_TYPES)
    ]


# ------------------------------------------------- registration completeness


def test_registration_completeness_scheduler():
    """Every dict/set/deque/list attribute SchedulerState.__init__
    assigns must be census-registered (a family's ``attrs``) or
    allowlisted with a mandatory reason — new state cannot silently
    dodge the census."""
    state = SchedulerState()
    covered = state.census.covered_attrs()
    missing = [a for a in _container_attrs(state) if a not in covered]
    assert not missing, (
        f"SchedulerState container attrs not covered by the census: "
        f"{missing} — register them in "
        f"diagnostics.census.build_scheduler_census (attrs=...) or "
        f"allowlist them there with a reason (allow_attr)"
    )


def test_registration_completeness_worker():
    state = WorkerState(nthreads=1)
    covered = state.census.covered_attrs()
    missing = [a for a in _container_attrs(state) if a not in covered]
    assert not missing, (
        f"WorkerState container attrs not covered by the census: "
        f"{missing} — register them in "
        f"diagnostics.census.build_worker_census (attrs=...) or "
        f"allowlist them there with a reason (allow_attr)"
    )


def test_attr_allowlist_requires_reason():
    c = StateCensus("x")
    with pytest.raises(AssertionError):
        c.allow_attr("foo", "")
    with pytest.raises(AssertionError):
        c.register("bar", lambda: 0, allow=True, reason="")


# --------------------------------------------------------- audits must fire


def test_audit_catches_maintained_counter_drift():
    """Mirror-parity style: corrupting a maintained counter makes the
    walk audit raise (the check that the engines' bookkeeping cannot
    silently drift from container truth)."""
    state = SchedulerState()
    state.new_task("drift-k", None)
    state.census.audit()  # clean
    next(iter(state.task_groups.values())).states["memory"] += 1
    with pytest.raises(CensusParityError, match="tasks.counted"):
        state.census.audit()


def test_audit_catches_ledger_open_row_drift():
    state = SchedulerState()
    h = state.ledger.file(
        "placement", "k", "p", "tcp://w:1", "stim", 0.1, 0.1, False,
    )
    state.census.audit()
    # tamper the derived-counter inputs without closing the ring row
    state.ledger._memory_joins += 1
    with pytest.raises(CensusParityError, match="ledger.open"):
        state.census.audit()
    state.ledger._memory_joins -= 1
    state.ledger.join_row(h, "memory")
    state.census.audit()


def test_census_check_env_parsing(monkeypatch):
    from distributed_tpu.diagnostics.census import census_check_enabled

    for off in ("", "0", "false", "off", "no", "False", "OFF"):
        monkeypatch.setenv("DTPU_CENSUS_CHECK", off)
        assert not census_check_enabled()
    monkeypatch.setenv("DTPU_CENSUS_CHECK", "1")
    assert census_check_enabled()


# ------------------------------------------------------ quiesce + findings


def test_residue_finding_names_holding_container():
    """A retained TaskState in unknown_durations produces a finding
    whose gc.get_referrers holder chain names the registered family."""
    state = SchedulerState()
    ts = state.new_task("leak-k", None)
    state.unknown_durations.setdefault("leak", set()).add(ts)
    del state.tasks["leak-k"]  # simulate the forget that missed the set
    findings = state.census.residue()
    fams = {f["family"] for f in findings}
    assert "tasks.unknown-durations" in fams
    assert "tasks.unknown-durations.members" in fams
    state.census.enrich_findings(findings)
    member = next(
        f for f in findings
        if f["family"] == "tasks.unknown-durations.members"
    )
    assert member["sample"], member
    assert "leak-k" in member["sample"][0]
    assert any(
        h.startswith("tasks.unknown-durations") for h in member["holders"]
    ), member


def test_quiesced_and_snapshot_shape():
    state = SchedulerState()
    assert state.census.quiesced()
    recs = state.census.snapshot(deep=True)
    head = recs[0]
    assert head["type"] == "census-head"
    assert head["quiesced"] is True
    fams = [r for r in recs if r["type"] == "census"]
    assert len(fams) == len(state.census.families)
    allow = {r["family"]: r.get("allow") for r in fams}
    # allowlisted families carry their reason in the snapshot
    assert allow["trace.ring"]
    assert allow["tasks"] is None
    state.new_task("q-k", None)
    assert not state.census.quiesced()


# --------------------------------------- the acceptance demonstration test


class _PoplessDict(dict):
    """Re-introduces the PR 10 ``unknown_durations`` leak: the pop on
    first completed duration becomes a no-op, so every pre-first-
    duration TaskState is pinned forever (append-only dict again)."""

    def pop(self, *a, **k):  # noqa: ARG002 - deliberately inert
        return None


def test_deliberate_unknown_durations_leak_is_caught():
    """The quiesce gate catches the deliberately re-introduced
    unknown_durations leak, with a referrer sample naming the holding
    container — the acceptance demonstration (ISSUE 15)."""
    from distributed_tpu.sim.chaos import _base_sim, _base_trace
    from distributed_tpu.sim.validate import check_census_clean

    sim = _base_sim(8, 11)
    sim.state.unknown_durations = _PoplessDict()
    _base_trace(11).start(sim)
    sim.run()
    with pytest.raises(CensusResidueError) as ei:
        check_census_clean(sim)
    msg = str(ei.value)
    assert "tasks.unknown-durations.members" in msg
    member = next(
        f for f in sim.state.census.findings
        if f["family"] == "tasks.unknown-durations.members"
    )
    assert member["count"] > 0
    assert member["holders"], member
    assert any(
        h.startswith("tasks.unknown-durations") for h in member["holders"]
    ), member


def test_sim_quiesce_gate_clean_on_healthy_run():
    from distributed_tpu.sim.chaos import _base_sim, _base_trace
    from distributed_tpu.sim.validate import check_census_clean

    sim = _base_sim(8, 12)
    _base_trace(12).start(sim)
    sim.run()
    out = check_census_clean(sim)
    assert out["census_clean"] is True
    assert out["censuses"] == 9  # scheduler + 8 workers
    # post-gate: literally zero TaskStates resident anywhere
    assert not sim.state.tasks
    assert all(not w.state.tasks for w in sim.workers.values())
    assert all(not w.state.data for w in sim.workers.values())


# ------------------------------------------------------- sentinel behavior


def test_sentinel_flags_growing_family_once_and_rearms():
    clock = [0.0]
    c = StateCensus("t", clock=lambda: clock[0])
    n = [0]
    c.register("grow", lambda: n[0], sample=lambda: iter(()))
    c.motion = ()
    from distributed_tpu.tracing import FlightRecorder

    tr = FlightRecorder(enabled=True, ring_size=64)
    s = RetentionSentinel(
        c, trace=tr, slope_threshold=10.0, min_count=100,
    )
    # grows 1000 members/second, above the floor: flags exactly once
    for _ in range(6):
        clock[0] += 1.0
        n[0] += 1000
        s.tick()
    assert s.leaks_flagged == 1
    leaks = [e for e in tr.tail() if e["cat"] == "leak"]
    assert len(leaks) == 1
    assert leaks[0]["name"] == "grow"
    assert leaks[0]["n"] >= 100
    # growth stops -> slope EWMA decays below half threshold -> re-arms
    for _ in range(20):
        clock[0] += 1.0
        s.tick()
    fam = c.families["grow"]
    assert not fam.flagged
    # a second episode flags again
    for _ in range(6):
        clock[0] += 1.0
        n[0] += 1000
        s.tick()
    assert s.leaks_flagged == 2


def test_sentinel_quiesce_edge_runs_residue_once():
    clock = [0.0]
    c = StateCensus("t", clock=lambda: clock[0])
    busy = [1]
    resid = [0]
    c.register("work", lambda: busy[0])
    c.register("junk", lambda: resid[0], sample=lambda: iter(()))
    c.motion = ("work",)
    s = RetentionSentinel(c, slope_threshold=1e9, min_count=10**9)
    clock[0] += 1.0
    assert s.tick() == []          # busy: no quiesce check
    resid[0] = 3
    busy[0] = 0
    clock[0] += 1.0
    fresh = s.tick()               # quiesce edge: diff runs
    assert [f["family"] for f in fresh] == ["junk"]
    clock[0] += 1.0
    assert s.tick() == []          # still quiesced: no re-fire
    busy[0] = 1
    clock[0] += 1.0
    s.tick()
    busy[0] = 0
    resid[0] = 0
    clock[0] += 1.0
    assert s.tick() == []          # clean quiesce: no findings


def test_census_check_mode_audits_throughout_sim(monkeypatch):
    """DTPU_CENSUS_CHECK=1 arms periodic walk-vs-counter audits on the
    sim's virtual clock — every census, throughout the run, not only at
    the quiesce gate."""
    monkeypatch.setenv("DTPU_CENSUS_CHECK", "1")
    from distributed_tpu.sim.chaos import scenario_worker_death

    # the scenario's curated default seed: chaos seeds are chosen to
    # converge (an unconvergeable workload loops its periodic ticks on
    # the virtual clock forever, by design)
    sim, report = scenario_worker_death()
    assert sim.counters["census_audits"] > 0
    assert sim.state.census.audits > sim.counters["census_audits"]  # + gate
    assert sim.state.census.audit_failures == 0
    assert report["census"]["census_clean"] is True


# ------------------------------------------------- leak fixes (regressions)


def test_worker_forget_cascades_to_orphaned_released_deps():
    """The released->forgotten arm recommends forgetting orphaned
    released dependencies — the census-found retention that pinned
    ~14% of WTaskStates (the old code had a no-op `pass` there)."""
    ws = WorkerState(nthreads=1)
    ws.handle_stimulus(
        ComputeTaskEvent(
            stimulus_id="s1", key="b", run_spec=None, priority=(1,),
            who_has={"a": ["tcp://peer:1"]}, nbytes={"a": 8},
            duration=0.1, resource_restrictions={}, actor=False,
            annotations={}, span_id=None,
        )
    )
    assert set(ws.tasks) == {"a", "b"}
    # free the dependent, then fail the in-flight fetch of the dep:
    # BOTH must forget (a becomes a released orphan the moment its
    # parked fetch resolves; has_what/who_has rows must go with them)
    ws.handle_stimulus(FreeKeysEvent(stimulus_id="s2", keys=("b",)))
    ws.handle_stimulus(
        GatherDepNetworkFailureEvent(
            stimulus_id="s3", worker="tcp://peer:1", keys=("a",),
        )
    )
    assert not ws.tasks, dict(ws.tasks)
    assert not ws.has_what, dict(ws.has_what)
    deep = ws.census.counts(deep=True)
    assert not any(
        v for k, v in deep.items() if not ws.census.families[k].allow
    ), deep


def test_worker_compute_task_severs_stale_dependency_edges():
    """A re-targeted compute-task whose who_has no longer names a
    previously-wired dependency severs the stale edge (the scheduler's
    dep list is authoritative) instead of wedging waiting->ready."""
    ws = WorkerState(nthreads=1)
    ws.handle_stimulus(
        ComputeTaskEvent(
            stimulus_id="s1", key="t", run_spec=None, priority=(1,),
            who_has={"old": ["tcp://peer:1"]}, nbytes={"old": 8},
            duration=0.1, resource_restrictions={}, actor=False,
            annotations={}, span_id=None,
        )
    )
    ws.handle_stimulus(FreeKeysEvent(stimulus_id="s2", keys=("t",)))
    ws.handle_stimulus(
        GatherDepNetworkFailureEvent(
            stimulus_id="s2b", worker="tcp://peer:1", keys=("old",),
        )
    )
    # re-submission with a different dep set; 'old' must not survive
    ws.handle_stimulus(
        ComputeTaskEvent(
            stimulus_id="s3", key="t", run_spec=None, priority=(1,),
            who_has={"new": ["tcp://peer:2"]}, nbytes={"new": 8},
            duration=0.1, resource_restrictions={}, actor=False,
            annotations={}, span_id=None,
        )
    )
    ts = ws.tasks["t"]
    assert {d.key for d in ts.dependencies} == {"new"}
    assert "old" not in ws.tasks


def test_stealing_overlays_deleted_and_pruned():
    """in_flight_tasks rows delete at zero, occupancy rows for a
    removed worker are purged, and a stimulus-mismatched confirm still
    reverts its window's overlays (census-found residue family
    steal.in-flight-*)."""
    from distributed_tpu.scheduler.stealing import WorkStealing
    from distributed_tpu.utils.test import StubScheduler

    state = SchedulerState()
    sched = StubScheduler(state)
    steal = WorkStealing(sched)
    v = state.add_worker_state("tcp://v:1", nthreads=1)
    t = state.add_worker_state("tcp://t:1", nthreads=1)
    ts = state.new_task("sk", object())
    ts.state = "processing"
    ts.processing_on = v
    v.processing[ts] = 1.0

    steal.seed_in_flight(ts, v, t, 1.0, 0.5, "stim-1")
    assert steal.in_flight_tasks[v] == 1
    # mismatched (forged/stale) confirm consumes the window AND reverts
    asyncio.run(
        steal.move_task_confirm(key="sk", state="ready",
                                stimulus_id="forged")
    )
    assert "sk" not in steal.in_flight
    assert not steal.in_flight_tasks     # zero rows deleted
    assert not steal.in_flight_occupancy  # bulk clear ran

    # overlay rows for a removed worker are purged even while other
    # windows stay open
    ts2 = state.new_task("sk2", object())
    ts2.state = "processing"
    ts2.processing_on = v
    v.processing[ts2] = 1.0
    steal.seed_in_flight(ts2, v, t, 1.0, 0.5, "stim-2")
    steal.remove_worker(sched, "tcp://t:1")
    assert t not in steal.in_flight_occupancy
    assert t not in steal.in_flight_tasks


def test_combined_occupancy_read_does_not_materialize_rows():
    from distributed_tpu.scheduler.stealing import WorkStealing
    from distributed_tpu.utils.test import StubScheduler

    state = SchedulerState()
    steal = WorkStealing(StubScheduler(state))
    ws = state.add_worker_state("tcp://w:1", nthreads=1)
    assert steal._combined_occupancy(ws) == 0.0
    assert not steal.in_flight_occupancy


def test_misrouted_completion_answers_free_keys():
    """A completion from a worker that is not processing_on gets a
    free-keys answer: the reporter's unaccounted copy must drop instead
    of outliving the task (census-found via the partition scenario)."""
    state = SchedulerState()
    w0 = state.add_worker_state("tcp://w:0", nthreads=1)
    state.add_worker_state("tcp://w:1", nthreads=1)
    state.client_desires_keys(["mk"], "c")
    cm, wm = state.update_graph_core(
        {"mk": object()}, {"mk": set()}, ["mk"], client="c",
        priorities={"mk": (0,)}, stimulus_id="g",
    )
    ts = state.tasks["mk"]
    assert ts.state == "processing"
    other = "tcp://w:1" if ts.processing_on is w0 else "tcp://w:0"
    cm, wm = state.stimulus_task_finished(
        "mk", other, "misroute-stim", nbytes=8,
    )
    assert wm == {other: [{
        "op": "free-keys", "keys": ["mk"], "stimulus_id": "misroute-stim",
    }]}
    assert ts.state == "processing"  # still awaiting the real worker


def test_unreachable_submission_is_culled_at_ingest():
    """A submitted task no requested key needs, nothing depends on and
    no client wants is forgotten at ingest instead of sitting released
    forever (buggy/hostile clients at production scale)."""
    state = SchedulerState()
    state.add_worker_state("tcp://w:0", nthreads=1)
    state.client_desires_keys(["want"], "c")
    state.update_graph_core(
        {"want": object(), "junk": object()},
        {"want": set(), "junk": set()},
        ["want"], client="c",
        priorities={"want": (0,), "junk": (1,)}, stimulus_id="g",
    )
    assert "junk" not in state.tasks
    assert "want" in state.tasks
    # the cull is a real released->forgotten story row, not a silent drop
    assert [(r[1], r[2]) for r in state.story("junk")] == [
        ("released", "forgotten"),
    ]


def test_groups_stale_last_worker_cleared_on_removal():
    state = SchedulerState()
    ws = state.add_worker_state("tcp://w:0", nthreads=1)
    state.new_task("gk", object())
    tg = next(iter(state.task_groups.values()))
    tg.last_worker = ws
    tg.last_worker_tasks_left = 3
    assert state.census.families["groups.stale-last-worker"].probe() == 0
    state.remove_worker_state("tcp://w:0", stimulus_id="rm")
    assert tg.last_worker is None
    assert state.census.families["groups.stale-last-worker"].probe() == 0


def test_telemetry_stale_links_walk():
    state = SchedulerState()
    state.add_worker_state("tcp://w:0", nthreads=1)
    tel = state.telemetry
    tel.fold_rows([["tcp://gone:1", "tcp://gone:2", 1000, 0.01, 1]],
                  reporter="tcp://gone:2")
    assert state.census.families["telemetry.links.stale"].probe() == 1
    # EITHER endpoint dead counts — the dominant leak shape is a LIVE
    # reporter re-creating a link to a removed peer
    tel.fold_rows([["tcp://w:0", "tcp://gone:3", 1000, 0.01, 1]],
                  reporter="tcp://w:0")
    assert state.census.families["telemetry.links.stale"].probe() == 2
    tel.forget_worker("tcp://gone:1")
    tel.forget_worker("tcp://gone:2")
    tel.forget_worker("tcp://gone:3")
    assert state.census.families["telemetry.links.stale"].probe() == 0


# ------------------------------------------------------------ memtrace fix


def test_memtrace_refcounted_per_owner():
    """With in-process workers one worker's stop must not kill the
    process-global trace for every other server (ISSUE 15 satellite)."""
    import tracemalloc

    from distributed_tpu.diagnostics import memtrace

    was_tracing = tracemalloc.is_tracing()
    try:
        memtrace.start_trace(owner="w-a")
        memtrace.start_trace(owner="w-b")
        assert tracemalloc.is_tracing()
        out = memtrace.stop_trace(owner="w-a")
        assert out["tracing"] is True, "other owner still tracing"
        assert tracemalloc.is_tracing()
        out = memtrace.stop_trace(owner="w-b")
        assert out["tracing"] is False
        assert not tracemalloc.is_tracing()
        # stale double-stop stays a no-op
        memtrace.stop_trace(owner="w-b")
        # an EXTERNALLY-armed trace is never memtrace's to stop: a
        # worker closing (its close path releases its hold defensively)
        # must not kill the user's own tracemalloc session
        tracemalloc.start()
        memtrace.stop_trace(owner="closing-worker")
        assert tracemalloc.is_tracing()
        tracemalloc.stop()
    finally:
        memtrace._owners.clear()
        memtrace._started_here = False
        if was_tracing and not tracemalloc.is_tracing():
            tracemalloc.start()
        elif not was_tracing and tracemalloc.is_tracing():
            tracemalloc.stop()


# --------------------------------------------------------------- live wiring


@gen_test(timeout=30)
async def test_heartbeat_fold_ignores_unregistered_link_endpoints():
    """Link rows naming a peer that already left (or never completed
    registration) do not re-create pruned LinkStats entries — the
    census's telemetry.links.stale family stays zero."""
    from distributed_tpu.scheduler.server import Scheduler

    async with Scheduler(listen_addr="inproc://") as s:
        s.state.add_worker_state("tcp://w:1", nthreads=1)
        s.state.add_worker_state("tcp://w:2", nthreads=1)
        await s.heartbeat_worker(
            address="tcp://w:1",
            link_telemetry=[
                ["tcp://w:2", "tcp://w:1", 1000, 0.01, 1],   # live pair
                ["tcp://ghost:9", "tcp://w:1", 1000, 0.01, 1],  # stale
            ],
        )
        tel = s.state.telemetry
        assert ("tcp://w:2", "tcp://w:1") in tel.links
        assert ("tcp://ghost:9", "tcp://w:1") not in tel.links
        assert s.state.census.families["telemetry.links.stale"].probe() == 0


@gen_test(timeout=60)
async def test_census_route_rpc_and_dump():
    """/census JSONL on both roles, the get_census RPC, the
    dtpu_census_* metric families, and the cluster-dump census artifact
    (DumpArtefact.census_counts/census_findings)."""
    import json as _json

    from distributed_tpu.diagnostics.cluster_dump import DumpArtefact
    from test_observability import http_get, new_cluster

    from distributed_tpu.client.client import Client

    async with await new_cluster(
        n_workers=1,
        scheduler_kwargs={"http_port": 0},
        worker_kwargs={"http_port": 0},
    ) as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(lambda x: x + 1, range(4))
            await c.gather(futs)

            # scheduler route
            port = cluster.scheduler.http_server.port
            status, body = await http_get(port, "/census")
            assert status == 200
            recs = [_json.loads(ln) for ln in body.splitlines() if ln]
            assert recs[0]["type"] == "census-head"
            assert recs[0]["role"] == "scheduler"
            fams = {r["family"] for r in recs if r["type"] == "census"}
            assert "tasks" in fams and "ledger.open" in fams

            # worker route
            w = cluster.workers[0]
            wport = w.http_server.port
            status, body = await http_get(wport, "/census")
            assert status == 200
            wrecs = [_json.loads(ln) for ln in body.splitlines() if ln]
            assert wrecs[0]["role"] == "worker"

            # metrics families
            status, body = await http_get(port, "/metrics")
            assert b"dtpu_census_count{" in body
            assert b"dtpu_census_quiesced" in body

            # RPC twin, deep (edge walks included)
            deep = await c.scheduler.get_census(deep=True)
            fams = {r["family"] for r in deep if r.get("type") == "census"}
            assert "edges.dependencies" in fams

            # cluster dump artifact
            dump = DumpArtefact(await c.dump_cluster_state())
            counts = dump.census_counts()
            assert counts.get("tasks", -1) >= 0
            assert "edges.dependencies" in counts  # dump census is deep
            assert dump.worker_census  # every worker shipped its census
            addr = next(iter(dump.worker_census))
            assert "wtasks" in dump.census_counts(addr)
            assert dump.census_findings() == []


@gen_test(timeout=60)
async def test_local_cluster_teardown_census_clean():
    """A LocalCluster that computed and released everything quiesces
    census-clean on both roles — the live half of the quiesce contract
    (durability dirty sets exempt by snapshot cadence; none here)."""
    from test_observability import new_cluster

    from distributed_tpu.client.client import Client

    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(lambda x: x * 2, range(10))
            await c.gather(futs)
            for f in futs:
                f.release()
            del futs
            s = cluster.scheduler.state
            for _ in range(100):
                if not s.tasks and s.census.quiesced():
                    break
                await asyncio.sleep(0.05)
            assert s.census.quiesced(), {
                m: s.census.families[m].probe() for m in s.census.motion
            }
            s.census.audit()
            assert s.census.residue() == []
            for w in cluster.workers:
                for _ in range(100):
                    if not w.state.tasks:
                        break
                    await asyncio.sleep(0.05)
                w.state.census.audit()
                assert w.state.census.residue() == [], w.address
