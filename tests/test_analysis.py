"""graft-lint tests: every rule proven on seeded-violation fixtures, the
pragma/baseline suppression paths, and the CLI over the real repo (the
tier-1 CI wiring — a clean tree is an acceptance criterion, so this file
IS the lint gate)."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

from distributed_tpu.analysis.core import all_rules, run_lint

REPO_ROOT = Path(__file__).resolve().parent.parent


def make_repo(tmp_path: Path, files: dict[str, str]) -> Path:
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def findings_for(tmp_path, files, rule):
    root = make_repo(tmp_path, files)
    result = run_lint(root, rule_names=[rule])
    assert not result.errors, result.errors
    return result.findings


# --------------------------------------------------------------- registry


def test_registry_has_all_contract_rules():
    rules = all_rules()
    assert set(rules) >= {
        "sans-io", "monotonic-time", "blocking-in-async", "handler-parity",
        "jit-purity", "swallowed-exceptions",
    }
    assert len(rules) >= 6
    for rule in rules.values():
        assert rule.description and rule.scope


# ---------------------------------------------------------------- sans-io


def test_sans_io_fires_on_seeded_violations(tmp_path):
    src = """
        import asyncio
        from distributed_tpu.comm.core import connect

        async def pull(self):
            await asyncio.sleep(0)

        def load(path):
            return open(path).read()
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src}, "sans-io"
    )
    msgs = "\n".join(f.message for f in found)
    assert "imports 'asyncio'" in msgs
    assert "imports from 'distributed_tpu.comm'" in msgs
    assert "async/await" in msgs
    assert "open" in msgs
    assert len(found) >= 4


def test_sans_io_clean_engine_passes(tmp_path):
    src = """
        from collections import deque

        def transition(state, key):
            return {"released": "waiting"}.get(state)
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src}, "sans-io"
    )


def test_sans_io_ignores_out_of_scope_files(tmp_path):
    # the same IO is legal outside the transition engines
    src = "import asyncio\n"
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/server.py": src}, "sans-io"
    )


# --------------------------------------------------------- monotonic-time


def test_monotonic_time_fires_including_aliases(tmp_path):
    src = """
        import time
        import time as _t
        from time import sleep

        def wait_for_worker(deadline):
            t0 = time.time()
            _t.sleep(0.1)
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/ttl.py": src}, "monotonic-time"
    )
    msgs = "\n".join(f.message for f in found)
    assert "time.time()" in msgs
    assert "time.sleep()" in msgs
    assert "imports wall-clock" in msgs
    assert len(found) == 3


def test_monotonic_time_allows_sanctioned_clocks(tmp_path):
    src = """
        from time import monotonic, perf_counter

        from distributed_tpu.utils.misc import time, wall_clock

        def stamp():
            return time(), wall_clock(), monotonic(), perf_counter()
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/ttl.py": src}, "monotonic-time"
    )


# ------------------------------------------------------ blocking-in-async


def test_blocking_in_async_fires(tmp_path):
    src = """
        import subprocess
        import time

        async def handler(self, path):
            time.sleep(1)
            subprocess.run(["ls"])
            with open(path) as f:
                f.read()
            self._lock.acquire()
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/worker/srv.py": src}, "blocking-in-async"
    )
    msgs = "\n".join(f.message for f in found)
    assert "time.sleep" in msgs
    assert "subprocess.run" in msgs
    assert "sync file IO" in msgs
    assert "lock.acquire" in msgs
    assert len(found) == 4


def test_blocking_in_async_exempts_executor_targets_and_sync_defs(tmp_path):
    src = """
        import asyncio
        import time

        def plain(path):
            time.sleep(1)  # sync helper: not loop code
            return open(path).read()

        async def handler(loop, path):
            def _work():
                time.sleep(1)  # executor target
                with open(path) as f:
                    return f.read()

            await asyncio.sleep(0.1)
            return await loop.run_in_executor(None, _work)
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/worker/srv.py": src}, "blocking-in-async"
    )


# --------------------------------------------------------- handler-parity


def test_handler_parity_unknown_rpc_op(tmp_path):
    src = """
        class Worker:
            def __init__(self):
                handlers = {"get_data": self.get_data}

            def get_data(self, keys=()):
                return keys

            async def fetch(self, addr):
                return await self.rpc(addr).get_dta(keys=[])
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/worker/srv.py": src}, "handler-parity"
    )
    assert len(found) == 1
    assert "get_dta" in found[0].message and "no server registers" in found[0].message


def test_handler_parity_keyword_mismatch(tmp_path):
    src = """
        class Worker:
            def __init__(self):
                handlers = {"get_data": self.get_data}

            def get_data(self, comm, keys=()):
                return keys

            async def fetch(self, addr):
                return await self.rpc(addr).get_data(keys=[], who="me")
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/worker/srv.py": src}, "handler-parity"
    )
    assert len(found) == 1
    assert "who" in found[0].message


def test_handler_parity_accepts_update_registration_and_stream_msgs(tmp_path):
    src = """
        class Ext:
            def __init__(self, scheduler):
                scheduler.stream_handlers.update(
                    {"shuffle-ping": self.ping}
                )

            def ping(self, id=None, stimulus_id=None):
                return id

        class Worker:
            def tell(self):
                self.batched_stream.send(
                    {"op": "shuffle-ping", "id": 1, "stimulus_id": "s"}
                )
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/shuffle/ext.py": src}, "handler-parity"
    )


def test_handler_parity_stream_msg_keyword_not_accepted(tmp_path):
    src = """
        class Server:
            def __init__(self):
                stream_handlers = {"task-done": self.handle_done}

            def handle_done(self, key=None):
                return key

            def report(self):
                self.batched_stream.send(
                    {"op": "task-done", "key": "k", "nbytes": 3}
                )
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/worker/srv.py": src}, "handler-parity"
    )
    assert len(found) == 1
    assert "nbytes" in found[0].message


def test_handler_parity_learns_manual_dispatch_arms(tmp_path):
    src = """
        def consume(q):
            msg = q.get()
            if msg.get("op") != "started":
                raise RuntimeError(msg)

        def produce(q, addr):
            q.put({"op": "started", "address": addr})
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/worker/boot.py": src}, "handler-parity"
    )


# ------------------------------------------------------------- jit-purity


def test_jit_purity_fires_on_host_syncs_and_captures(tmp_path):
    src = """
        import functools

        import jax
        import jax.numpy as jnp
        import numpy as np

        _CACHE = {}

        @functools.partial(jax.jit, static_argnames=("K",))
        def kern(x, K):
            n = float(x)
            k = float(K)  # static arg: concrete python value, fine
            v = x.item()
            h = np.asarray(x)
            return jnp.sum(x) + len(_CACHE)

        def call(x):
            return kern(x, K=[1, 2])
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/ops/kern.py": src}, "jit-purity"
    )
    msgs = "\n".join(f.message for f in found)
    assert "float() on a traced value" in msgs
    assert ".item() forces" in msgs
    assert "numpy.asarray on a traced value" in msgs
    assert "mutable module global '_CACHE'" in msgs
    assert "unhashable literal for static arg 'K'" in msgs
    assert len(found) == 5  # float(K) must NOT be flagged


def test_jit_purity_flags_mutable_static_default_and_jit_wrap(tmp_path):
    src = """
        import jax
        import jax.numpy as jnp

        def make(n):
            def shard(x, meta=[]):
                return jnp.sum(x) + meta.count(0) + x.tolist()[0]

            return jax.jit(shard, static_argnames=("meta",))
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/ops/wrap.py": src}, "jit-purity"
    )
    msgs = "\n".join(f.message for f in found)
    assert "mutable (unhashable) default" in msgs
    assert ".tolist()" in msgs
    assert len(found) == 2


def test_jit_purity_clean_kernel_passes(tmp_path):
    src = """
        import functools

        import jax
        import jax.numpy as jnp

        _EPS = 1e-6  # immutable scalar global: fine to close over

        @functools.partial(jax.jit, static_argnames=("K",))
        def kern(costs, K):
            top = jax.lax.top_k(costs, K)[0]
            return jnp.where(top > _EPS, top, 0.0)

        def host_wrapper(costs_host, K):
            import numpy as np

            return np.asarray(kern(jnp.asarray(costs_host), K=int(K)))
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/ops/kern.py": src}, "jit-purity"
    )


# ------------------------------------------------- swallowed-exceptions


def test_swallowed_exceptions_fires(tmp_path):
    src = """
        def dispatch(handler):
            try:
                handler()
            except Exception:
                pass
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/rpc/disp.py": src}, "swallowed-exceptions"
    )
    assert len(found) == 1


def test_swallowed_exceptions_allows_logged_or_narrow(tmp_path):
    src = """
        import logging

        logger = logging.getLogger(__name__)

        def dispatch(handler):
            try:
                handler()
            except KeyError:
                pass  # narrow: deliberate
            except Exception:
                logger.exception("handler failed")
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/rpc/disp.py": src}, "swallowed-exceptions"
    )


# ----------------------------------------------------------- mirror-parity


def test_mirror_parity_fires_on_rogue_mutations(tmp_path):
    src = """
        def sneak_occupancy(ws, delta):
            ws.occupancy += delta

        def sneak_status(ws):
            ws.status = "paused"

        def sneak_replica(ws, ts):
            ws.has_what[ts] = None
            ws.nbytes += 10

        def sneak_container(ws, ts):
            ws.processing.pop(ts, None)
            del ws.has_what[ts]
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/rogue.py": src}, "mirror-parity"
    )
    fields = sorted(
        f.message.split("mirrored field `")[1].split("`")[0] for f in found
    )
    assert fields == [
        "has_what", "has_what", "nbytes", "occupancy", "processing", "status",
    ], found


def test_mirror_parity_allows_helpers_scope_and_reads(tmp_path):
    src = """
        class WorkerState:
            def __init__(self):
                self.occupancy = 0.0
                self.status = "running"

            def clean(self):
                ws = WorkerState()
                ws.status = self.status
                return ws

        class SchedulerState:
            def _adjust_occupancy(self, ws, delta):
                ws.occupancy = max(0.0, ws.occupancy + delta)

            def add_replica(self, ts, ws):
                ws.nbytes += ts.nbytes
                ws.has_what[ts] = None

            def set_worker_status(self, ws, status):
                ws.status = status

        def reads_are_fine(ws):
            return ws.occupancy / max(ws.nthreads, 1), ws.processing.get(None)

        def other_objects_are_fine(ts, client):
            ts.nbytes = 5          # TaskState, not a worker
            client.status = "x"    # not a worker-state binding name
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src}, "mirror-parity"
    )
    # worker-side modules share field names but keep their own state:
    # out of scope by construction
    rogue = """
        def worker_side(ws):
            ws.occupancy = 1.0
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/worker/state_machine.py": rogue},
        "mirror-parity",
    )


# ------------------------------------------------------- wire-no-copy


def test_wire_no_copy_fires_on_materialization(tmp_path):
    src = """
        def write_frames(writer, frames):
            for f in frames:
                writer.write(bytes(f))

        def reassemble(parts):
            return b"".join(bytes(p) for p in parts)
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/comm/rogue.py": src}, "wire-no-copy"
    )
    # bytes(f), b"".join(...), bytes(p) inside the genexp
    assert len(found) == 3, found
    assert any("join" in f.message for f in found)


def test_wire_no_copy_allows_sanctioned_idioms(tmp_path):
    src = """
        import struct

        def scatter(writer, frames):
            for f in frames:
                writer.write(f)            # pass-through, no copy

        def gather(parts):
            out = bytearray(sum(len(p) for p in parts))
            pos = 0
            for p in parts:
                out[pos:pos + len(p)] = p  # one preallocated gather
                pos += len(p)
            return memoryview(out).toreadonly()

        def construction_not_conversion(n):
            return bytes(16), struct.pack("<Q", n), bytes()

        def outside_scope_is_fine():
            pass
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/protocol/clean.py": src}, "wire-no-copy"
    )
    # scheduler code may materialize freely: out of scope by construction
    rogue = """
        def report(frames):
            return b"".join(bytes(f) for f in frames)
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/report.py": rogue},
        "wire-no-copy",
    )


def test_wire_no_copy_pragma_suppresses(tmp_path):
    src = """
        def error_repr(frames):
            # graft-lint: allow[wire-no-copy] error-path repr only
            return repr(bytes(frames[0]))
    """
    root = make_repo(tmp_path, {"distributed_tpu/comm/err.py": src})
    result = run_lint(root, rule_names=["wire-no-copy"])
    assert not result.findings
    assert result.suppressed == 1


# ------------------------------------------------------ pragma / baseline


def test_inline_pragma_suppresses_with_reason(tmp_path):
    src = """
        def dispatch(handler):
            try:
                handler()
            # graft-lint: allow[swallowed-exceptions] probe path, outcome irrelevant
            except Exception:
                pass
    """
    root = make_repo(tmp_path, {"distributed_tpu/rpc/disp.py": src})
    result = run_lint(root, rule_names=["swallowed-exceptions"])
    assert not result.findings
    assert result.suppressed == 1


def test_inline_pragma_without_reason_does_not_suppress(tmp_path):
    src = """
        def dispatch(handler):
            try:
                handler()
            # graft-lint: allow[swallowed-exceptions]
            except Exception:
                pass
    """
    root = make_repo(tmp_path, {"distributed_tpu/rpc/disp.py": src})
    result = run_lint(root, rule_names=["swallowed-exceptions"])
    assert len(result.findings) == 1


def test_baseline_entry_suppresses_and_requires_reason(tmp_path):
    src = """
        def dispatch(handler):
            try:
                handler()
            except Exception:
                pass
    """
    root = make_repo(tmp_path, {"distributed_tpu/rpc/disp.py": src})
    (root / "graft-lint-baseline.toml").write_text(textwrap.dedent("""
        [[allow]]
        rule = "swallowed-exceptions"
        path = "distributed_tpu/rpc/disp.py"
        symbol = "dispatch"
        reason = "probe path, outcome irrelevant"
    """))
    result = run_lint(root, rule_names=["swallowed-exceptions"])
    assert not result.findings and result.suppressed == 1

    # an entry with no reason is itself an error, and never suppresses
    (root / "graft-lint-baseline.toml").write_text(textwrap.dedent("""
        [[allow]]
        rule = "swallowed-exceptions"
        path = "distributed_tpu/rpc/disp.py"
    """))
    result = run_lint(root, rule_names=["swallowed-exceptions"])
    assert len(result.findings) == 1
    assert any("no reason" in e for e in result.errors)
    assert result.exit_code == 1


def test_baseline_stale_entries_are_reported(tmp_path):
    root = make_repo(tmp_path, {"distributed_tpu/rpc/disp.py": "x = 1\n"})
    (root / "graft-lint-baseline.toml").write_text(textwrap.dedent("""
        [[allow]]
        rule = "swallowed-exceptions"
        path = "distributed_tpu/rpc/gone.py"
        reason = "was real once"
    """))
    result = run_lint(root)
    assert result.stale_baseline


def test_config_scoping_and_disable(tmp_path):
    src = "import asyncio\n"
    root = make_repo(tmp_path, {"distributed_tpu/graph/order.py": src})
    assert run_lint(root, rule_names=["sans-io"]).findings
    (root / "graft-lint.toml").write_text(textwrap.dedent("""
        [rules.sans-io]
        exclude = ["distributed_tpu/graph/order.py"]
    """))
    assert not run_lint(root, rule_names=["sans-io"]).findings
    (root / "graft-lint.toml").write_text(textwrap.dedent("""
        [rules.sans-io]
        enabled = false
    """))
    assert not run_lint(root, rule_names=["sans-io"]).findings


# ------------------------------------------------------- CLI / repo gate


def test_cli_json_clean_on_this_repo():
    """The tier-1 lint gate: the real tree must be graft-lint clean.

    Runs the module CLI exactly as CI does; any new violation (or a
    broken/stale-reasonless baseline entry) fails this test."""
    proc = subprocess.run(
        [sys.executable, "-m", "distributed_tpu.analysis", "--format", "json",
         "--root", str(REPO_ROOT)],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["findings"] == []
    assert report["errors"] == []


def test_cli_list_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "distributed_tpu.analysis", "--list-rules"],
        capture_output=True, text=True, timeout=60, cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0
    for name in ("sans-io", "monotonic-time", "blocking-in-async",
                 "handler-parity", "jit-purity", "swallowed-exceptions"):
        assert name in proc.stdout
