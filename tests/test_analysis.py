"""graft-lint tests: every rule proven on seeded-violation fixtures, the
pragma/baseline suppression paths, and the CLI over the real repo (the
tier-1 CI wiring — a clean tree is an acceptance criterion, so this file
IS the lint gate)."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

from distributed_tpu.analysis.core import all_rules, run_lint

REPO_ROOT = Path(__file__).resolve().parent.parent


def make_repo(tmp_path: Path, files: dict[str, str]) -> Path:
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def findings_for(tmp_path, files, rule):
    root = make_repo(tmp_path, files)
    result = run_lint(root, rule_names=[rule])
    assert not result.errors, result.errors
    return result.findings


# --------------------------------------------------------------- registry


def test_registry_has_all_contract_rules():
    rules = all_rules()
    assert set(rules) >= {
        "sans-io", "monotonic-time", "blocking-in-async", "handler-parity",
        "jit-purity", "swallowed-exceptions", "mirror-parity",
        "wire-no-copy", "state-machine", "await-atomicity", "config-keys",
        "determinism",
    }
    assert len(rules) >= 12
    for rule in rules.values():
        assert rule.description and rule.scope


# ---------------------------------------------------------------- sans-io


def test_sans_io_fires_on_seeded_violations(tmp_path):
    src = """
        import asyncio
        from distributed_tpu.comm.core import connect

        async def pull(self):
            await asyncio.sleep(0)

        def load(path):
            return open(path).read()
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src}, "sans-io"
    )
    msgs = "\n".join(f.message for f in found)
    assert "imports 'asyncio'" in msgs
    assert "imports from 'distributed_tpu.comm'" in msgs
    assert "async/await" in msgs
    assert "open" in msgs
    assert len(found) >= 4


def test_sans_io_clean_engine_passes(tmp_path):
    src = """
        from collections import deque

        def transition(state, key):
            return {"released": "waiting"}.get(state)
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src}, "sans-io"
    )


def test_sans_io_ignores_out_of_scope_files(tmp_path):
    # the same IO is legal outside the transition engines
    src = "import asyncio\n"
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/server.py": src}, "sans-io"
    )


# --------------------------------------------------------- monotonic-time


def test_monotonic_time_fires_including_aliases(tmp_path):
    src = """
        import time
        import time as _t
        from time import sleep

        def wait_for_worker(deadline):
            t0 = time.time()
            _t.sleep(0.1)
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/ttl.py": src}, "monotonic-time"
    )
    msgs = "\n".join(f.message for f in found)
    assert "time.time()" in msgs
    assert "time.sleep()" in msgs
    assert "imports wall-clock" in msgs
    assert len(found) == 3


def test_monotonic_time_allows_sanctioned_clocks(tmp_path):
    src = """
        from time import monotonic, perf_counter

        from distributed_tpu.utils.misc import time, wall_clock

        def stamp():
            return time(), wall_clock(), monotonic(), perf_counter()
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/ttl.py": src}, "monotonic-time"
    )


# ------------------------------------------------------ blocking-in-async


def test_blocking_in_async_fires(tmp_path):
    src = """
        import subprocess
        import time

        async def handler(self, path):
            time.sleep(1)
            subprocess.run(["ls"])
            with open(path) as f:
                f.read()
            self._lock.acquire()
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/worker/srv.py": src}, "blocking-in-async"
    )
    msgs = "\n".join(f.message for f in found)
    assert "time.sleep" in msgs
    assert "subprocess.run" in msgs
    assert "sync file IO" in msgs
    assert "lock.acquire" in msgs
    assert len(found) == 4


def test_blocking_in_async_exempts_executor_targets_and_sync_defs(tmp_path):
    src = """
        import asyncio
        import time

        def plain(path):
            time.sleep(1)  # sync helper: not loop code
            return open(path).read()

        async def handler(loop, path):
            def _work():
                time.sleep(1)  # executor target
                with open(path) as f:
                    return f.read()

            await asyncio.sleep(0.1)
            return await loop.run_in_executor(None, _work)
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/worker/srv.py": src}, "blocking-in-async"
    )


# --------------------------------------------------------- handler-parity


def test_handler_parity_unknown_rpc_op(tmp_path):
    src = """
        class Worker:
            def __init__(self):
                handlers = {"get_data": self.get_data}

            def get_data(self, keys=()):
                return keys

            async def fetch(self, addr):
                return await self.rpc(addr).get_dta(keys=[])
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/worker/srv.py": src}, "handler-parity"
    )
    assert len(found) == 1
    assert "get_dta" in found[0].message and "no server registers" in found[0].message


def test_handler_parity_keyword_mismatch(tmp_path):
    src = """
        class Worker:
            def __init__(self):
                handlers = {"get_data": self.get_data}

            def get_data(self, comm, keys=()):
                return keys

            async def fetch(self, addr):
                return await self.rpc(addr).get_data(keys=[], who="me")
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/worker/srv.py": src}, "handler-parity"
    )
    assert len(found) == 1
    assert "who" in found[0].message


def test_handler_parity_accepts_update_registration_and_stream_msgs(tmp_path):
    src = """
        class Ext:
            def __init__(self, scheduler):
                scheduler.stream_handlers.update(
                    {"shuffle-ping": self.ping}
                )

            def ping(self, id=None, stimulus_id=None):
                return id

        class Worker:
            def tell(self):
                self.batched_stream.send(
                    {"op": "shuffle-ping", "id": 1, "stimulus_id": "s"}
                )
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/shuffle/ext.py": src}, "handler-parity"
    )


def test_handler_parity_stream_msg_keyword_not_accepted(tmp_path):
    src = """
        class Server:
            def __init__(self):
                stream_handlers = {"task-done": self.handle_done}

            def handle_done(self, key=None):
                return key

            def report(self):
                self.batched_stream.send(
                    {"op": "task-done", "key": "k", "nbytes": 3}
                )
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/worker/srv.py": src}, "handler-parity"
    )
    assert len(found) == 1
    assert "nbytes" in found[0].message


def test_handler_parity_learns_manual_dispatch_arms(tmp_path):
    src = """
        def consume(q):
            msg = q.get()
            if msg.get("op") != "started":
                raise RuntimeError(msg)

        def produce(q, addr):
            q.put({"op": "started", "address": addr})
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/worker/boot.py": src}, "handler-parity"
    )


# ------------------------------------------------------------- jit-purity


def test_jit_purity_fires_on_host_syncs_and_captures(tmp_path):
    src = """
        import functools

        import jax
        import jax.numpy as jnp
        import numpy as np

        _CACHE = {}

        @functools.partial(jax.jit, static_argnames=("K",))
        def kern(x, K):
            n = float(x)
            k = float(K)  # static arg: concrete python value, fine
            v = x.item()
            h = np.asarray(x)
            return jnp.sum(x) + len(_CACHE)

        def call(x):
            return kern(x, K=[1, 2])
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/ops/kern.py": src}, "jit-purity"
    )
    msgs = "\n".join(f.message for f in found)
    assert "float() on a traced value" in msgs
    assert ".item() forces" in msgs
    assert "numpy.asarray on a traced value" in msgs
    assert "mutable module global '_CACHE'" in msgs
    assert "unhashable literal for static arg 'K'" in msgs
    assert len(found) == 5  # float(K) must NOT be flagged


def test_jit_purity_flags_mutable_static_default_and_jit_wrap(tmp_path):
    src = """
        import jax
        import jax.numpy as jnp

        def make(n):
            def shard(x, meta=[]):
                return jnp.sum(x) + meta.count(0) + x.tolist()[0]

            return jax.jit(shard, static_argnames=("meta",))
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/ops/wrap.py": src}, "jit-purity"
    )
    msgs = "\n".join(f.message for f in found)
    assert "mutable (unhashable) default" in msgs
    assert ".tolist()" in msgs
    assert len(found) == 2


def test_jit_purity_clean_kernel_passes(tmp_path):
    src = """
        import functools

        import jax
        import jax.numpy as jnp

        _EPS = 1e-6  # immutable scalar global: fine to close over

        @functools.partial(jax.jit, static_argnames=("K",))
        def kern(costs, K):
            top = jax.lax.top_k(costs, K)[0]
            return jnp.where(top > _EPS, top, 0.0)

        def host_wrapper(costs_host, K):
            import numpy as np

            return np.asarray(kern(jnp.asarray(costs_host), K=int(K)))
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/ops/kern.py": src}, "jit-purity"
    )


# ------------------------------------------------- swallowed-exceptions


def test_swallowed_exceptions_fires(tmp_path):
    src = """
        def dispatch(handler):
            try:
                handler()
            except Exception:
                pass
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/rpc/disp.py": src}, "swallowed-exceptions"
    )
    assert len(found) == 1


def test_swallowed_exceptions_allows_logged_or_narrow(tmp_path):
    src = """
        import logging

        logger = logging.getLogger(__name__)

        def dispatch(handler):
            try:
                handler()
            except KeyError:
                pass  # narrow: deliberate
            except Exception:
                logger.exception("handler failed")
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/rpc/disp.py": src}, "swallowed-exceptions"
    )


# ----------------------------------------------------------- mirror-parity


def test_mirror_parity_fires_on_rogue_mutations(tmp_path):
    src = """
        def sneak_occupancy(ws, delta):
            ws.occupancy += delta

        def sneak_status(ws):
            ws.status = "paused"

        def sneak_replica(ws, ts):
            ws.has_what[ts] = None
            ws.nbytes += 10

        def sneak_container(ws, ts):
            ws.processing.pop(ts, None)
            del ws.has_what[ts]
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/rogue.py": src}, "mirror-parity"
    )
    fields = sorted(
        f.message.split("mirrored field `")[1].split("`")[0] for f in found
    )
    assert fields == [
        "has_what", "has_what", "nbytes", "occupancy", "processing", "status",
    ], found


def test_mirror_parity_allows_helpers_scope_and_reads(tmp_path):
    src = """
        class WorkerState:
            def __init__(self):
                self.occupancy = 0.0
                self.status = "running"

            def clean(self):
                ws = WorkerState()
                ws.status = self.status
                return ws

        class SchedulerState:
            def _adjust_occupancy(self, ws, delta):
                ws.occupancy = max(0.0, ws.occupancy + delta)

            def add_replica(self, ts, ws):
                ws.nbytes += ts.nbytes
                ws.has_what[ts] = None

            def set_worker_status(self, ws, status):
                ws.status = status

        def reads_are_fine(ws):
            return ws.occupancy / max(ws.nthreads, 1), ws.processing.get(None)

        def other_objects_are_fine(ts, client):
            ts.nbytes = 5          # TaskState, not a worker
            client.status = "x"    # not a worker-state binding name
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src}, "mirror-parity"
    )
    # worker-side modules share field names but keep their own state:
    # out of scope by construction
    rogue = """
        def worker_side(ws):
            ws.occupancy = 1.0
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/worker/state_machine.py": rogue},
        "mirror-parity",
    )


# ------------------------------------------------------ soa-hydration


def test_soa_hydration_fires_on_raw_slot_writes(tmp_path):
    src = """
        def sneak_state(ts):
            ts._state = "memory"

        def sneak_relation(ts, ws):
            ts._waiting_on.add(ts)
            ws._processing[ts] = 1.0
            ws._occupancy += 2.0

        def sneak_alias(ts):
            push = ts._waiters.add
            return push

        def sneak_log(s, row):
            s._transition_log.append(row)
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/rogue.py": src}, "soa-hydration"
    )
    fields = sorted(
        f.message.split("SoA-backed slot `")[1].split("`")[0] for f in found
    )
    assert fields == [
        "_occupancy", "_processing", "_state", "_transition_log",
        "_waiters", "_waiting_on",
    ], found


def test_soa_hydration_allows_registered_helpers_and_reads(tmp_path):
    src = """
        class TaskState:
            def __init__(self):
                self._state = "released"
                self._waiting_on = set()

            @property
            def state(self):
                return self._state

            @state.setter
            def state(self, value):
                self._state = value

        class NativeEngine:
            def _apply_tape_inner(self, ts, s, row):
                ts._state = "memory"
                log = s._transition_log.append
                log(row)

            def sync(self, ts):
                ts._nbytes = 5

        def reads_are_fine(ts):
            return ts._state, len(ts._waiting_on)

        def other_underscores_are_fine(ts, obj):
            ts._nrow_cache = 1       # not an SoA-backed slot
            obj._state = "x"         # not a task/worker/state binding
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src}, "soa-hydration"
    )
    # worker-side state machines keep their own underscore fields
    rogue = """
        def worker_side(ws):
            ws._occupancy = 1.0
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/worker/state_machine.py": rogue},
        "soa-hydration",
    )


# ------------------------------------------------------- wire-no-copy


def test_wire_no_copy_fires_on_materialization(tmp_path):
    src = """
        def write_frames(writer, frames):
            for f in frames:
                writer.write(bytes(f))

        def reassemble(parts):
            return b"".join(bytes(p) for p in parts)
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/comm/rogue.py": src}, "wire-no-copy"
    )
    # bytes(f), b"".join(...), bytes(p) inside the genexp
    assert len(found) == 3, found
    assert any("join" in f.message for f in found)


def test_wire_no_copy_allows_sanctioned_idioms(tmp_path):
    src = """
        import struct

        def scatter(writer, frames):
            for f in frames:
                writer.write(f)            # pass-through, no copy

        def gather(parts):
            out = bytearray(sum(len(p) for p in parts))
            pos = 0
            for p in parts:
                out[pos:pos + len(p)] = p  # one preallocated gather
                pos += len(p)
            return memoryview(out).toreadonly()

        def construction_not_conversion(n):
            return bytes(16), struct.pack("<Q", n), bytes()

        def outside_scope_is_fine():
            pass
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/protocol/clean.py": src}, "wire-no-copy"
    )
    # scheduler code may materialize freely: out of scope by construction
    rogue = """
        def report(frames):
            return b"".join(bytes(f) for f in frames)
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/report.py": rogue},
        "wire-no-copy",
    )


def test_wire_no_copy_pragma_suppresses(tmp_path):
    src = """
        def error_repr(frames):
            # graft-lint: allow[wire-no-copy] error-path repr only
            return repr(bytes(frames[0]))
    """
    root = make_repo(tmp_path, {"distributed_tpu/comm/err.py": src})
    result = run_lint(root, rule_names=["wire-no-copy"])
    assert not result.findings
    assert result.suppressed == 1


# ------------------------------------------------------ pragma / baseline


def test_inline_pragma_suppresses_with_reason(tmp_path):
    src = """
        def dispatch(handler):
            try:
                handler()
            # graft-lint: allow[swallowed-exceptions] probe path, outcome irrelevant
            except Exception:
                pass
    """
    root = make_repo(tmp_path, {"distributed_tpu/rpc/disp.py": src})
    result = run_lint(root, rule_names=["swallowed-exceptions"])
    assert not result.findings
    assert result.suppressed == 1


def test_inline_pragma_without_reason_does_not_suppress(tmp_path):
    src = """
        def dispatch(handler):
            try:
                handler()
            # graft-lint: allow[swallowed-exceptions]
            except Exception:
                pass
    """
    root = make_repo(tmp_path, {"distributed_tpu/rpc/disp.py": src})
    result = run_lint(root, rule_names=["swallowed-exceptions"])
    assert len(result.findings) == 1


def test_baseline_entry_suppresses_and_requires_reason(tmp_path):
    src = """
        def dispatch(handler):
            try:
                handler()
            except Exception:
                pass
    """
    root = make_repo(tmp_path, {"distributed_tpu/rpc/disp.py": src})
    (root / "graft-lint-baseline.toml").write_text(textwrap.dedent("""
        [[allow]]
        rule = "swallowed-exceptions"
        path = "distributed_tpu/rpc/disp.py"
        symbol = "dispatch"
        reason = "probe path, outcome irrelevant"
    """))
    result = run_lint(root, rule_names=["swallowed-exceptions"])
    assert not result.findings and result.suppressed == 1

    # an entry with no reason is itself an error, and never suppresses
    (root / "graft-lint-baseline.toml").write_text(textwrap.dedent("""
        [[allow]]
        rule = "swallowed-exceptions"
        path = "distributed_tpu/rpc/disp.py"
    """))
    result = run_lint(root, rule_names=["swallowed-exceptions"])
    assert len(result.findings) == 1
    assert any("no reason" in e for e in result.errors)
    assert result.exit_code == 1


def test_baseline_stale_entries_are_reported(tmp_path):
    root = make_repo(tmp_path, {"distributed_tpu/rpc/disp.py": "x = 1\n"})
    (root / "graft-lint-baseline.toml").write_text(textwrap.dedent("""
        [[allow]]
        rule = "swallowed-exceptions"
        path = "distributed_tpu/rpc/gone.py"
        reason = "was real once"
    """))
    result = run_lint(root)
    assert result.stale_baseline


def test_config_scoping_and_disable(tmp_path):
    src = "import asyncio\n"
    root = make_repo(tmp_path, {"distributed_tpu/graph/order.py": src})
    assert run_lint(root, rule_names=["sans-io"]).findings
    (root / "graft-lint.toml").write_text(textwrap.dedent("""
        [rules.sans-io]
        exclude = ["distributed_tpu/graph/order.py"]
    """))
    assert not run_lint(root, rule_names=["sans-io"]).findings
    (root / "graft-lint.toml").write_text(textwrap.dedent("""
        [rules.sans-io]
        enabled = false
    """))
    assert not run_lint(root, rule_names=["sans-io"]).findings


# ------------------------------------------------------- CLI / repo gate


def test_cli_json_clean_on_this_repo():
    """The tier-1 lint gate: the real tree must be graft-lint clean.

    Runs the module CLI exactly as CI does; any new violation (or a
    broken/stale-reasonless baseline entry) fails this test."""
    proc = subprocess.run(
        [sys.executable, "-m", "distributed_tpu.analysis", "--format", "json",
         "--root", str(REPO_ROOT)],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["findings"] == []
    assert report["errors"] == []


def test_cli_determinism_clean_on_this_repo():
    """The determinism gate on its own: every decision/digest/journal
    surface in the real tree is free of hash-seed-ordered iteration
    (docs/determinism.md).  Split from the full-lint gate so a
    determinism regression names its rule in the failure, and because
    bench --smoke runs exactly this invocation."""
    proc = subprocess.run(
        [sys.executable, "-m", "distributed_tpu.analysis",
         "--rule", "determinism", "--format", "json",
         "--root", str(REPO_ROOT)],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["findings"] == []
    assert report["errors"] == []


def test_cli_list_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "distributed_tpu.analysis", "--list-rules"],
        capture_output=True, text=True, timeout=60, cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0
    for name in ("sans-io", "monotonic-time", "blocking-in-async",
                 "handler-parity", "jit-purity", "swallowed-exceptions",
                 "state-machine", "await-atomicity", "config-keys"):
        assert name in proc.stdout


# ------------------------------------------------- state-machine (rule 9)


#: a minimal but complete machine: every edge reachable, every handler
#: registered, batch arm matching its oracle
CLEAN_MACHINE = """
    ALL_TASK_STATES = ("released", "waiting", "memory")

    class S:
        def __init__(self):
            self._transitions_table = {
                ("released", "waiting"): self._transition_released_waiting,
                ("waiting", "memory"): self._transition_waiting_memory,
                ("waiting", "released"): self._transition_waiting_released,
                ("memory", "released"): self._transition_memory_released,
            }

        def _transition_released_waiting(self, key, stimulus_id):
            return {}, {}, {}

        def _transition_waiting_memory(self, key, stimulus_id):
            return {}, {}, {}

        def _transition_waiting_released(self, key, stimulus_id):
            return {}, {}, {}

        def _transition_memory_released(self, key, stimulus_id):
            return {}, {}, {}

        def stimulus_done(self, ts, recommendations):
            if ts.state == "released":
                recommendations[ts.key] = "waiting"
            recommendations[ts.key] = "memory"
            recommendations[ts.key] = "released"
            return recommendations
"""


def test_state_machine_clean_fixture_passes(tmp_path):
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": CLEAN_MACHINE},
        "state-machine",
    )


def test_state_machine_flags_unresolvable_pair(tmp_path):
    # (released, memory) is in no table, and the through-released
    # fallback cannot apply when the start already IS released
    src = CLEAN_MACHINE + """
        def bad(self, dts, recommendations):
            if dts.state == "released":
                recommendations[dts.key] = "memory"
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src},
        "state-machine",
    )
    pair = [f for f in found if "no registered transition" in f.message]
    assert len(pair) == 1 and "(released, memory)" in pair[0].message
    assert pair[0].symbol == "bad"


def test_state_machine_accepts_released_fallback(tmp_path):
    # (memory, waiting) missing, but memory->released and
    # released->waiting both exist: the engine routes through released
    src = CLEAN_MACHINE + """
        def ok(self, dts, recommendations):
            if dts.state == "waiting":
                recommendations[dts.key] = "memory"   # direct
            if dts.state == "memory":
                recommendations[dts.key] = "waiting"  # via released
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src},
        "state-machine",
    )


def test_state_machine_flags_unknown_state(tmp_path):
    src = CLEAN_MACHINE + """
        def typo(self, ts, recommendations):
            recommendations[ts.key] = "wating"
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src},
        "state-machine",
    )
    assert len(found) == 1 and "'wating'" in found[0].message


def test_state_machine_flags_unreachable_edge_and_dead_handler(tmp_path):
    src = """
        class S:
            def __init__(self):
                self._transitions_table = {
                    ("released", "waiting"): self._transition_released_waiting,
                    ("waiting", "queued"): self._transition_waiting_queued,
                }

            def _transition_released_waiting(self, key):
                return {}

            def _transition_waiting_queued(self, key):
                return {}

            def _transition_memory_forgotten(self, key):
                return {}

            def stimulus(self, ts, recommendations):
                recommendations[ts.key] = "waiting"
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src},
        "state-machine",
    )
    msgs = "\n".join(f.message for f in found)
    # nothing ever emits "queued": the edge is dead weight
    assert "(waiting, queued)" in msgs and "unreachable" in msgs
    # a handler in no table, called from nowhere
    assert "_transition_memory_forgotten" in msgs
    assert len(found) == 2


def test_state_machine_flags_batch_oracle_drift(tmp_path):
    src = CLEAN_MACHINE + """
        def stimulus_task_done(self, key):
            return self._transition(key, "memory", "sid")

        def stimulus_tasks_done_batch(self, items):
            for key in items:
                self._transition(key, "released", "sid")

        def stimulus_orphan_batch(self, items):
            return items
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src},
        "state-machine",
    )
    msgs = "\n".join(f.message for f in found)
    assert "different transition surface" in msgs
    assert "stimulus_orphan_batch" in msgs and "no scalar oracle" in msgs
    assert len(found) == 2


def test_state_machine_emissions_cross_module(tmp_path):
    # an emission in a sibling scheduler module resolves against the
    # machine owning the subpackage
    other = """
        def release_all(self, state, keys):
            recs = {k: "wating" for k in keys}
            return state.transitions(recs, "sid")
    """
    found = findings_for(
        tmp_path,
        {
            "distributed_tpu/scheduler/state.py": CLEAN_MACHINE,
            "distributed_tpu/scheduler/ext.py": other,
        },
        "state-machine",
    )
    assert len(found) == 1
    assert found[0].path == "distributed_tpu/scheduler/ext.py"
    assert "'wating'" in found[0].message


def test_state_machine_extractor_model_and_serialization(tmp_path):
    from distributed_tpu.analysis.config import LintConfig
    from distributed_tpu.analysis.core import LintContext
    from distributed_tpu.analysis.model import (
        extract_machines,
        machine_to_dot,
        machine_to_json,
    )

    root = make_repo(
        tmp_path, {"distributed_tpu/scheduler/state.py": CLEAN_MACHINE}
    )
    ctx = LintContext(root, LintConfig())
    machines = extract_machines(ctx.all_modules)
    assert len(machines) == 1
    m = machines[0]
    assert m.name == "scheduler"
    assert m.states == ("memory", "released", "waiting")
    assert {(t.start, t.finish) for t in m.transitions} == {
        ("released", "waiting"), ("waiting", "memory"),
        ("waiting", "released"), ("memory", "released"),
    }
    # every emission resolved, none flagged
    assert m.emissions
    assert all(
        e.resolution in ("direct", "fallback", "any-start")
        for e in m.emissions
    )
    guarded = [e for e in m.emissions if e.starts is not None]
    assert any(
        e.starts == ("released",) and e.finish == "waiting" for e in guarded
    )
    import json as _json

    doc = _json.loads(machine_to_json(m))
    assert doc["module"] == "distributed_tpu/scheduler/state.py"
    assert len(doc["transitions"]) == 4 and len(doc["emissions"]) == len(
        m.emissions
    )
    dot = machine_to_dot(m)
    assert '"released" -> "waiting"' in dot
    assert "_transition_released_waiting" in dot


def test_state_machine_artifacts_no_drift(tmp_path):
    """The checked-in docs/state_machine/ model must match a fresh
    extraction — regenerate with
    ``python -m distributed_tpu.analysis --dump-model docs/state_machine``
    whenever either state machine changes."""
    proc = subprocess.run(
        [sys.executable, "-m", "distributed_tpu.analysis",
         "--dump-model", str(tmp_path), "--root", str(REPO_ROOT)],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for name in ("scheduler", "worker"):
        for ext in (".json", ".dot"):
            fresh = (tmp_path / (name + ext)).read_text()
            checked = (
                REPO_ROOT / "docs" / "state_machine" / (name + ext)
            ).read_text()
            assert fresh == checked, (
                f"docs/state_machine/{name}{ext} is stale — regenerate it"
            )


# ----------------------------------------------- await-atomicity (rule 10)


def test_await_atomicity_fires_on_slot_reuse_steal_shape(tmp_path):
    """Must-fire: the PR 3 slot-reuse race — a mirror-slot worker binding
    priced into a device plan, then used to address a steal after the
    plan await; churn during the await can reuse the slot for a
    different worker."""
    src = """
        class WorkStealing:
            async def balance_device(self):
                state = self.scheduler.state
                victim = state.mirror.ws_of[self.vslot]
                plan = await self.run_device_kernel()
                self.batched_send(victim, {"op": "steal-request",
                                           "key": plan})
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/stealing.py": src},
        "await-atomicity",
    )
    assert len(found) == 1
    f = found[0]
    assert f.symbol == "balance_device" and "'victim'" in f.message
    assert "sink" in f.message


def test_await_atomicity_fires_on_readinto_buffer_shape(tmp_path):
    """Must-fire: the PR 4 readinto race — a StreamReader._buffer
    binding drained after a _wait_for_data await with no exception/EOF
    re-check (the sanctioned fix in comm/tcp.py binds via getattr and
    raises the reader's stored exception before every drain)."""
    src = """
        async def readinto_exactly(reader, view):
            n = view.nbytes
            pos = 0
            buffer = reader._buffer
            while pos < n:
                if not buffer:
                    await reader._wait_for_data("readinto")
                take = min(len(buffer), n - pos)
                view[pos:pos + take] = buffer[:take]
                del buffer[:take]
                pos += take
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/comm/rogue.py": src}, "await-atomicity"
    )
    assert found, "the readinto race shape must fire"
    assert any("'buffer'" in f.message for f in found)


def test_await_atomicity_revalidation_and_rebind_pass(tmp_path):
    src = """
        class Scheduler:
            async def guarded(self, key, addr):
                state = self.state
                ws = state.workers.get(addr)
                await self.flush()
                if state.workers.get(addr) is ws:
                    ws.processing.pop(key, None)

            async def reread(self, key):
                state = self.state
                ts = state.tasks.get(key)
                nbytes = await self.fetch(ts.key)
                ts = state.tasks.get(key)
                ts.nbytes = nbytes

            async def before_await_is_fine(self, key):
                ts = self.state.tasks.get(key)
                ts.nbytes = 1
                await self.flush()
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/server.py": src},
        "await-atomicity",
    )


def test_await_atomicity_pragma_suppresses(tmp_path):
    src = """
        async def push(self, key):
            ts = self.state.tasks.get(key)
            await self.flush()
            # graft-lint: allow[await-atomicity] key is unforgettable here: pinned by the caller
            ts.nbytes = 1
    """
    root = make_repo(tmp_path, {"distributed_tpu/scheduler/ext.py": src})
    result = run_lint(root, rule_names=["await-atomicity"])
    assert not result.findings
    assert result.suppressed == 1


# ------------------------------------------------------------ config-keys


CONFIG_FIXTURE = """
    defaults = {
        "scheduler": {"bandwidth": 1, "dead-knob": 2},
        "worker": {"preload": [], "nested": {"a": 1, "b": 2}},
    }
"""


def test_config_keys_missing_and_dead(tmp_path):
    reader = """
        from distributed_tpu import config

        def f(prefix):
            config.get("scheduler.bandwidth")
            config.get("scheduler.typo-key")
            config.get("worker.nested")
            config.get(f"{prefix}.preload")
    """
    found = findings_for(
        tmp_path,
        {
            "distributed_tpu/config.py": CONFIG_FIXTURE,
            "distributed_tpu/reader.py": reader,
        },
        "config-keys",
    )
    msgs = sorted(f.message for f in found)
    assert len(found) == 2, found
    assert "scheduler.typo-key" in msgs[0] and "not present" in msgs[0]
    assert "scheduler.dead-knob" in msgs[1] and "dead configuration" in msgs[1]


def test_config_keys_indirect_full_path_constant_counts_as_read(tmp_path):
    reader = """
        from distributed_tpu import config

        KEY = "scheduler.dead-knob"

        def f():
            config.get("scheduler.bandwidth")
            config.get("worker.nested")
            config.get("worker.preload")
            return config.get(KEY)
    """
    assert not findings_for(
        tmp_path,
        {
            "distributed_tpu/config.py": CONFIG_FIXTURE,
            "distributed_tpu/reader.py": reader,
        },
        "config-keys",
    )


# ------------------------------------------- handler-parity batch plane


def test_handler_parity_batch_without_scalar_and_orphan_keys(tmp_path):
    src = """
        class Server:
            def __init__(self):
                stream_handlers = {"task-done": self.handle_done}
                self.stream_batch_handlers["task-done"] = self.handle_done_batch
                self.stream_batch_handlers["task-gone"] = self.handle_gone_batch

            def handle_done(self, key=None, stimulus_id=None):
                self._trace_ingress("task-done", 1, stimulus_id)
                return key

            def handle_done_batch(self, msgs, worker=""):
                self._trace_ingress("task-done", len(msgs), "")
                out = []
                for m in msgs:
                    k = m.pop("key", None)
                    sid = m.pop("stimulus_id", "")
                    nb = m.pop("nbytes", 0)
                    out.append((k, sid, nb))
                return out

            def handle_gone_batch(self, msgs):
                return msgs
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/worker/srv.py": src}, "handler-parity"
    )
    msgs = "\n".join(f.message for f in found)
    assert "'task-gone'" in msgs and "no scalar stream handler" in msgs
    assert "nbytes" in msgs and "no scalar stream handler for the op accepts" in msgs
    assert len(found) == 2


def test_handler_parity_batch_dropping_scalar_param_flagged(tmp_path):
    src = """
        class Server:
            def __init__(self):
                stream_handlers = {"task-done": self.handle_done}
                self.stream_batch_handlers["task-done"] = self.handle_done_batch

            def handle_done(self, key=None, nbytes=0, stimulus_id=None):
                self._trace_ingress("task-done", 1, stimulus_id)
                return key

            def handle_done_batch(self, msgs, worker=""):
                self._trace_ingress("task-done", len(msgs), "")
                return [m.pop("key", None) for m in msgs]
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/worker/srv.py": src}, "handler-parity"
    )
    assert len(found) == 1
    assert "neither consumes nor carries through" in found[0].message
    assert "nbytes" in found[0].message


def test_handler_parity_batch_residual_carry_through_passes(tmp_path):
    src = """
        class Server:
            def __init__(self):
                stream_handlers = {"task-done": self.handle_done}
                self.stream_batch_handlers["task-done"] = self.handle_done_batch

            def handle_done(self, key=None, nbytes=0, stimulus_id=None,
                            **kw):
                self._trace_ingress("task-done", 1, stimulus_id)
                return key

            def handle_done_batch(self, msgs, worker=""):
                self._trace_ingress("task-done", len(msgs), "")
                out = []
                for m in msgs:
                    key = m.pop("key", None)
                    sid = m.pop("stimulus_id", "")
                    out.append((key, sid, m))
                return out
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/worker/srv.py": src}, "handler-parity"
    )


def test_handler_parity_batch_wholesale_forward_passes(tmp_path):
    """An arm with no keyed reads forwards its messages wholesale
    (``**m`` delegation, ``m.items()``) — nothing provably drops, so the
    dropped-keys claim must stay silent."""
    src = """
        class Server:
            def __init__(self):
                stream_handlers = {"task-done": self.handle_done}
                self.stream_batch_handlers["task-done"] = self.handle_done_batch
                stream_handlers["task-gone"] = self.handle_gone
                self.stream_batch_handlers["task-gone"] = self.handle_gone_batch

            def handle_done(self, key=None, nbytes=0, stimulus_id=None):
                self._trace_ingress("task-done", 1, stimulus_id)
                return key

            def handle_done_batch(self, msgs, worker=""):
                return [self.handle_done(**m) for m in msgs]

            def handle_gone(self, key=None, reason=None):
                self.trace.emit("ingress", "task-gone", "")
                return key

            def handle_gone_batch(self, msgs, worker=""):
                self.trace.emit("ingress", "task-gone", "", n=len(msgs))
                return [sorted(m.items()) for m in msgs]
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/worker/srv.py": src}, "handler-parity"
    )
    # note: handle_done_batch carries NO emission of its own — the
    # wholesale delegation to the emitting scalar covers the batch
    # plane transitively (trace-parity pass 5)


def test_handler_parity_trace_parity_must_fire(tmp_path):
    """Trace-parity (pass 5): a batched op whose arms never stamp the
    flight recorder's ingress hop is flagged on BOTH planes — the blind
    spot causal stimulus tracing exists to remove."""
    src = """
        class Server:
            def __init__(self):
                stream_handlers = {"task-done": self.handle_done}
                self.stream_batch_handlers["task-done"] = self.handle_done_batch

            def handle_done(self, key=None, stimulus_id=None):
                return key

            def handle_done_batch(self, msgs, worker=""):
                out = []
                for m in msgs:
                    out.append((m.pop("key", None), m.pop("stimulus_id", ""), m))
                return out
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/worker/srv.py": src}, "handler-parity"
    )
    msgs = "\n".join(f.message for f in found)
    assert "emits no ingress trace" in msgs
    assert "batch arm for op 'task-done'" in msgs
    assert "scalar twin of batched op 'task-done'" in msgs
    assert len(found) == 2


def test_handler_parity_trace_parity_accepts_direct_emit_and_helper(tmp_path):
    """Both sanctioned emission shapes pass: the ``*trace_ingress``
    helper and a direct ``<...>.trace.emit("ingress", ...)``; an emit
    with a NON-ingress category does not count."""
    src = """
        class Server:
            def __init__(self):
                stream_handlers = {"task-done": self.handle_done}
                self.stream_batch_handlers["task-done"] = self.handle_done_batch
                stream_handlers["task-gone"] = self.handle_gone
                self.stream_batch_handlers["task-gone"] = self.handle_gone_batch

            def handle_done(self, key=None, stimulus_id=None):
                self.trace.emit("ingress", "task-done", stimulus_id)
                return key

            def handle_done_batch(self, msgs, worker=""):
                self._trace_ingress("task-done", len(msgs), "")
                return [(m.pop("key", None), m.pop("stimulus_id", ""), m)
                        for m in msgs]

            def handle_gone(self, key=None, stimulus_id=None):
                self.trace.emit("engine", "not-ingress", stimulus_id)
                return key

            def handle_gone_batch(self, msgs, worker=""):
                self._trace_ingress("task-gone", len(msgs), "")
                return [(m.pop("key", None), m.pop("stimulus_id", ""), m)
                        for m in msgs]
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/worker/srv.py": src}, "handler-parity"
    )
    assert len(found) == 1
    assert "scalar twin of batched op 'task-gone'" in found[0].message


def test_await_atomicity_bare_annotation_is_not_a_bind(tmp_path):
    """A value-less ``ts: TaskState`` annotation after the await binds
    nothing — it must not move the last bind past the await and mask the
    stale pre-await read."""
    src = """
        class Scheduler:
            async def annotated(self, key):
                ts = self.state.tasks.get(key)
                await self.flush()
                ts: object
                ts.nbytes = 1
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/server.py": src},
        "await-atomicity",
    )
    assert len(found) == 1
    assert "'ts'" in found[0].message


def test_cli_dump_model_rejects_rule_combination():
    """--dump-model runs no rules; silently skipping a requested --rule
    would let a CI gate pass without linting, so the combination is a
    hard usage error."""
    proc = subprocess.run(
        [sys.executable, "-m", "distributed_tpu.analysis",
         "--dump-model", "/tmp/_should_not_exist_dump",
         "--rule", "state-machine", "--root", str(REPO_ROOT)],
        capture_output=True, text=True, timeout=60, cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 2
    assert "pure extraction mode" in proc.stderr
    assert not os.path.exists("/tmp/_should_not_exist_dump")


# ---------------------------------------------------- determinism (rule 12)


#: the PR 13 bug, verbatim shape: TaskState relation fields as plain
#: sets, iterated inside a transition to build recommendations — the
#: recommendation order (and with it the journal/digest) then depends
#: on PYTHONHASHSEED
PR13_RELATION_SET_BUG = """
    class TaskState:
        def __init__(self, key):
            self.key = key
            self.dependents: set[TaskState] = set()
            self.waiters: set[TaskState] = set()

    class SchedulerState:
        def _transition_processing_memory(self, ts: TaskState, stimulus_id):
            recommendations = {}
            for dts in ts.dependents:
                if not dts.waiters:
                    recommendations[dts.key] = "released"
            return recommendations
"""


def test_determinism_fires_on_pr13_relation_set_bug(tmp_path):
    found = findings_for(
        tmp_path,
        {"distributed_tpu/scheduler/state.py": PR13_RELATION_SET_BUG},
        "determinism",
    )
    assert any(
        f.symbol.endswith("_transition_processing_memory") for f in found
    ), [f.message for f in found]
    assert any("recommendations" in f.message for f in found)


def test_determinism_clean_with_ordered_relations(tmp_path):
    # the actual PR 13 fix: OrderedSet relations make iteration order
    # insertion order, which is stimulus-derived and seed-independent
    src = """
        from distributed_tpu.utils.collections import OrderedSet

        class TaskState:
            def __init__(self, key):
                self.key = key
                self.dependents: OrderedSet[TaskState] = OrderedSet()
                self.waiters: OrderedSet[TaskState] = OrderedSet()

        class SchedulerState:
            def _transition_processing_memory(self, ts: TaskState, stimulus_id):
                recommendations = {}
                for dts in ts.dependents:
                    if not dts.waiters:
                        recommendations[dts.key] = "released"
                return recommendations
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src}, "determinism"
    )


#: the PR 14 bug: steal victims picked by first-match scan over the
#: plain ``saturated`` set — which worker loses a task depends on the
#: hash seed, so two same-seed runs steal differently
PR14_SATURATED_SET_BUG = """
    class SchedulerState:
        def __init__(self):
            self.saturated: set = set()

        def pick_steal_victim(self):
            for ws in self.saturated:
                if ws.nprocessing > 1:
                    return ws
            return None
"""


def test_determinism_fires_on_pr14_saturated_set_bug(tmp_path):
    found = findings_for(
        tmp_path,
        {"distributed_tpu/ops/stealing.py": PR14_SATURATED_SET_BUG},
        "determinism",
    )
    assert any(f.symbol.endswith("pick_steal_victim") for f in found), [
        f.message for f in found
    ]


def test_determinism_clean_with_keyed_sorted(tmp_path):
    # sorted() with a deterministic key is a sanitizer: the scan order
    # no longer depends on the set's internal layout
    src = """
        class SchedulerState:
            def __init__(self):
                self.saturated: set = set()

            def pick_steal_victim(self):
                for ws in sorted(self.saturated, key=lambda w: w.address):
                    if ws.nprocessing > 1:
                        return ws
                return None
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/ops/stealing.py": src}, "determinism"
    )


def test_determinism_pragma_suppresses_with_reason(tmp_path):
    src = """
        class SchedulerState:
            def __init__(self):
                self.saturated: set = set()

            def pick_steal_victim(self):
                # graft-lint: allow[determinism] victim choice audited order-free
                for ws in self.saturated:
                    if ws.nprocessing > 1:
                        return ws
                return None
    """
    root = make_repo(tmp_path, {"distributed_tpu/ops/stealing.py": src})
    result = run_lint(root, rule_names=["determinism"])
    assert not result.findings
    assert result.suppressed == 1


def test_determinism_fires_on_unstable_min_key(tmp_path):
    # min() over a set with a key that can tie picks whichever tied
    # element the iteration meets first — needs an address tiebreak
    src = """
        class SchedulerState:
            def __init__(self):
                self.idle: set = set()

            def decide_worker(self):
                return min(self.idle, key=lambda ws: ws.occupancy)
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src}, "determinism"
    )
    assert len(found) == 1
    assert "min" in found[0].message


def test_determinism_clean_with_address_tiebreak(tmp_path):
    src = """
        class SchedulerState:
            def __init__(self):
                self.idle: set = set()

            def decide_worker(self):
                return min(self.idle, key=lambda ws: (ws.occupancy, ws.address))
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src}, "determinism"
    )


def test_determinism_fires_on_id_keyed_sort_and_set_pop(tmp_path):
    src = """
        class Plan:
            def __init__(self):
                self.pending: set = set()

            def order_policies(self, policies):
                return sorted(policies, key=id)

            def take(self):
                return self.pending.pop()
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/amm.py": src}, "determinism"
    )
    msgs = " | ".join(f.message for f in found)
    assert "id()" in msgs, msgs
    assert ".pop()" in msgs or "pop" in msgs, msgs


def test_determinism_next_iter_requires_singleton_guard(tmp_path):
    src = """
        class S:
            def __init__(self):
                self.workers: set = set()

            def only_unsafe(self):
                return next(iter(self.workers))

            def only_safe(self):
                if len(self.workers) == 1:
                    return next(iter(self.workers))
                return None
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src}, "determinism"
    )
    assert len(found) == 1
    assert found[0].symbol.endswith("only_unsafe")


# ------------------------------------------------- tape_safe contract pass


def test_tape_safe_plugin_reading_occupancy_fires(tmp_path):
    # tape_safe plugins replay against lazily-hydrated rows: derived
    # aggregates like ws.occupancy are NOT restored row-locally, so a
    # tape_safe=True plugin touching them diverges under replay
    src = """
        class StealTap:
            tape_safe = True

            def transition(self, key, start, finish, stimulus_id=None, ws=None):
                if ws is not None and ws.occupancy > 1.0:
                    self.hot.append(key)
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src}, "determinism"
    )
    assert any("occupancy" in f.message for f in found), [
        f.message for f in found
    ]


def test_tape_safe_plugin_cross_row_scan_fires(tmp_path):
    # reached through a same-class helper: the contract pass follows
    # self.method() calls from transition()
    src = """
        class CensusTap:
            tape_safe = True

            def transition(self, key, start, finish, stimulus_id=None):
                self._rescan()

            def _rescan(self):
                self.n = len([ts for ts in self.state.tasks.values()])
    """
    found = findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src}, "determinism"
    )
    assert any("tasks" in f.message for f in found), [f.message for f in found]


def test_tape_safe_plugin_args_only_is_clean(tmp_path):
    src = """
        class CountTap:
            tape_safe = True

            def transition(self, key, start, finish, stimulus_id=None):
                self.counts[finish] = self.counts.get(finish, 0) + 1
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src}, "determinism"
    )


def test_non_tape_safe_plugin_may_read_occupancy(tmp_path):
    # the contract pass only binds classes that DECLARE tape_safe = True
    src = """
        class LooseTap:
            tape_safe = False

            def transition(self, key, start, finish, stimulus_id=None, ws=None):
                if ws is not None and ws.occupancy > 1.0:
                    self.hot.append(key)
    """
    assert not findings_for(
        tmp_path, {"distributed_tpu/scheduler/state.py": src}, "determinism"
    )


# ------------------------------------------- baseline prune / moved symbol


def test_baseline_moved_symbol_matches_before_path(tmp_path):
    # a baselined finding whose enclosing function moved file intact is
    # still suppressed via (rule, symbol) — not double-reported as one
    # stale entry plus one new finding
    src = """
        def dispatch(handler):
            try:
                handler()
            except Exception:
                pass
    """
    root = make_repo(tmp_path, {"distributed_tpu/rpc/new_home.py": src})
    (root / "graft-lint-baseline.toml").write_text(textwrap.dedent("""
        [[allow]]
        rule = "swallowed-exceptions"
        path = "distributed_tpu/rpc/old_home.py"
        symbol = "dispatch"
        reason = "probe path, outcome irrelevant"
    """))
    result = run_lint(root, rule_names=["swallowed-exceptions"])
    assert not result.findings
    assert result.suppressed == 1
    assert not result.stale_baseline

    # without a symbol the entry stays pinned to its path: path mismatch
    # means stale + unsuppressed, as before
    (root / "graft-lint-baseline.toml").write_text(textwrap.dedent("""
        [[allow]]
        rule = "swallowed-exceptions"
        path = "distributed_tpu/rpc/old_home.py"
        reason = "probe path, outcome irrelevant"
    """))
    result = run_lint(root, rule_names=["swallowed-exceptions"])
    assert len(result.findings) == 1
    assert result.stale_baseline


def test_prune_baseline_round_trip_preserves_live_blocks(tmp_path):
    from distributed_tpu.analysis.baseline import Baseline

    src = """
        def dispatch(handler):
            try:
                handler()
            except Exception:
                pass
    """
    root = make_repo(tmp_path, {"distributed_tpu/rpc/disp.py": src})
    baseline_path = root / "graft-lint-baseline.toml"
    baseline_path.write_text(textwrap.dedent("""\
        # graft-lint baseline — every entry argues its case.

        # probe dispatch: outcome is irrelevant by design, see rpc docs
        [[allow]]
        rule = "swallowed-exceptions"
        path = "distributed_tpu/rpc/disp.py"
        symbol = "dispatch"
        reason = "probe path, outcome irrelevant"

        # this one rotted: the file is long gone
        [[allow]]
        rule = "swallowed-exceptions"
        path = "distributed_tpu/rpc/gone.py"
        reason = "was real once"
    """))
    baseline = Baseline.load(baseline_path)
    result = run_lint(root, baseline=baseline)
    assert not result.findings

    dropped = baseline.prune(baseline_path)
    assert dropped == ["swallowed-exceptions @ distributed_tpu/rpc/gone.py"]
    text = baseline_path.read_text()
    # the live entry survives verbatim, rationale comment included
    assert "# probe dispatch: outcome is irrelevant by design" in text
    assert 'reason = "probe path, outcome irrelevant"' in text
    # the stale block is gone, comment and all
    assert "gone.py" not in text
    assert "# this one rotted" not in text
    # file header preamble is kept
    assert text.startswith("# graft-lint baseline")

    # re-load + re-lint: nothing further to prune, file untouched
    baseline2 = Baseline.load(baseline_path)
    run_lint(root, baseline=baseline2)
    assert baseline2.prune(baseline_path) == []
    assert baseline_path.read_text() == text


def test_prune_baseline_refuses_partial_run():
    import pytest

    from distributed_tpu.analysis.cli import main

    with pytest.raises(SystemExit) as exc:
        main(["--prune-baseline", "--rule", "determinism",
              "--root", str(REPO_ROOT)])
    assert exc.value.code == 2
