"""WebSocket comm backend tests (reference comm/tests/test_ws.py patterns)."""

from __future__ import annotations

import asyncio

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.local import LocalCluster
from distributed_tpu.comm.core import connect, listen

from conftest import gen_test


@gen_test()
async def test_ws_comm_roundtrip():
    received = []

    async def handle(comm):
        msg = await comm.read()
        received.append(msg)
        await comm.write({"echo": msg})

    listener = listen("ws://127.0.0.1:0", handle)
    await listener.start()
    comm = await connect(listener.contact_address)
    await comm.write({"hello": "ws", "n": 42})
    resp = await comm.read()
    assert resp == {"echo": {"hello": "ws", "n": 42}}
    assert received == [{"hello": "ws", "n": 42}]
    await comm.close()
    listener.stop()


@gen_test()
async def test_ws_large_payload_fragmented():
    """Payloads beyond one fragment survive (8 MiB fragmentation)."""

    async def handle(comm):
        msg = await comm.read()
        await comm.write({"len": len(msg["blob"])})

    listener = listen("ws://127.0.0.1:0", handle)
    await listener.start()
    comm = await connect(listener.contact_address)
    from distributed_tpu.protocol.serialize import Serialize

    blob = bytes(9 * 2**20)  # forces a continuation frame
    await comm.write({"blob": Serialize(blob)})
    resp = await comm.read()
    assert resp == {"len": 9 * 2**20}
    await comm.close()
    listener.stop()


@gen_test(timeout=90)
async def test_cluster_over_ws():
    """A whole cluster runs over the ws:// protocol."""
    async with LocalCluster(n_workers=2, protocol="ws") as cluster:
        assert cluster.scheduler_address.startswith("ws://")
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(lambda x: x * 3, range(10))
            assert await asyncio.wait_for(c.gather(futs), 60) == [
                3 * i for i in range(10)
            ]


@gen_test(timeout=90)
async def test_ws_cluster_roundtrip():
    """A full cluster over ws:// — scheduler, workers, client, and the
    worker->worker data plane all ride websocket framing."""
    from distributed_tpu.client.client import Client
    from distributed_tpu.deploy.local import LocalCluster

    async with LocalCluster(
        n_workers=2, protocol="ws",
        scheduler_kwargs={"validate": True},
        worker_kwargs={"validate": True},
    ) as cluster:
        assert cluster.scheduler_address.startswith("ws://")
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(lambda x: x + 1, range(8))
            assert await c.gather(futs) == list(range(1, 9))
            # cross-worker dependency over ws
            w0, w1 = [w.address for w in cluster.workers]
            a = c.submit(lambda: 10, key="ws-a", workers=[w0])
            b = c.submit(lambda x: x + 5, a, key="ws-b", workers=[w1])
            assert await b.result() == 15
