"""Probabilistic chaos soak (the role of reference tests/test_chaos.py,
test_stress.py): kill workers on a random clock under sustained load and
require full, correct completion with a quiescent scheduler at the end.
The deterministic race harness pins known interleavings; this layer
hunts the unknown ones."""

from __future__ import annotations

import asyncio
import random

from distributed_tpu import config
from distributed_tpu.client.client import Client
from distributed_tpu.deploy.local import LocalCluster

from conftest import gen_test


def _inc(x):
    return x + 1


def _tree_sum(xs):
    return sum(xs)


async def _chaos_soak(n_tasks: int, protocol: str, seed: int = 42,
                      mean_kill_s: float = 0.8):
    rng = random.Random(seed)
    with config.set({
        "scheduler.allowed-failures": 100,  # deaths are the POINT here
        "scheduler.jax.enabled": False,
    }):
        async with LocalCluster(
            n_workers=8, threads_per_worker=1,
            protocol=protocol,
            scheduler_kwargs={"validate": True},
            worker_kwargs={"validate": True},
        ) as cluster:
            async with Client(cluster.scheduler_address) as c:
                stop = asyncio.Event()
                kills = 0

                async def chaos():
                    nonlocal kills
                    while not stop.is_set():
                        try:
                            await asyncio.wait_for(
                                stop.wait(), rng.expovariate(1 / mean_kill_s)
                            )
                            return
                        except asyncio.TimeoutError:
                            pass
                        if len(cluster.workers) <= 2:
                            continue
                        victim = rng.choice(cluster.workers)
                        cluster.workers.remove(victim)
                        await victim.close(report=False)
                        kills += 1
                        await cluster.add_worker(
                            name=f"chaos-replacement-{kills}"
                        )

                chaos_task = asyncio.create_task(chaos())
                try:
                    futs = c.map(_inc, range(n_tasks))
                    # a reduction layer so the chaos also hits tasks
                    # with dependencies (lost-replica recompute paths)
                    sums = [
                        c.submit(_tree_sum, futs[i : i + 50],
                                 key=f"chaos-sum-{i}")
                        for i in range(0, n_tasks, 50)
                    ]
                    # generous budget: this soak takes ~75s alone but ~3x
                    # that on this single-core box when the whole suite's
                    # collected modules (jax backends, compiled ops) are
                    # resident — the timeout guards against a HANG, not
                    # against slowness
                    total = await asyncio.wait_for(
                        c.gather(c.submit(_tree_sum, sums)), 420
                    )
                finally:
                    stop.set()
                    await chaos_task
                assert total == sum(range(1, n_tasks + 1)), total
                assert kills >= 3, f"chaos too tame: {kills} kills"
                # quiescence: nothing processing or queued once done.
                # The client's answer can land while a lost-replica
                # recompute of some _inc straggler is still in flight
                # (a kill raced the finish) — give the scheduler a
                # settle window before asserting
                s = cluster.scheduler
                def _busy():
                    return [
                        ts for ts in s.state.tasks.values()
                        if ts.state not in ("memory", "released", "forgotten")
                    ]
                deadline = asyncio.get_running_loop().time() + 15
                while _busy() and asyncio.get_running_loop().time() < deadline:
                    await asyncio.sleep(0.1)
                assert not _busy(), _busy()[:5]


@gen_test(timeout=480)
async def test_chaos_kill_workers_under_load():
    """5k-task workload while a chaos clock (exponential, mean ~0.8 s)
    closes a random worker and replaces it.  Done means: every result
    correct, no stuck tasks, scheduler quiescent."""
    await _chaos_soak(5000, "inproc")


@gen_test(timeout=480)
async def test_chaos_kill_workers_under_load_tcp():
    """The same soak with every comm over REAL sockets: worker death now
    severs TCP streams mid-frame, so the recovery paths digest framing
    truncation, half-open connections, and reconnect races that inproc
    can never produce."""
    await _chaos_soak(1500, "tcp", seed=7, mean_kill_s=1.0)
