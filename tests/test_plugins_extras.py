"""Plugin API, rebalance, cluster dump, worker_client, executor tests
(reference test_worker_plugins, test_client_executor, test_rebalance
patterns)."""

from __future__ import annotations

import asyncio
import logging
import os

import pytest

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.local import LocalCluster
from distributed_tpu.diagnostics.plugin import SchedulerPlugin, WorkerPlugin

from conftest import gen_test


async def new_cluster(n_workers=2, **kwargs):
    cluster = LocalCluster(
        n_workers=n_workers,
        scheduler_kwargs={"validate": True, **kwargs.pop("scheduler_kwargs", {})},
        worker_kwargs={"validate": True, **kwargs.pop("worker_kwargs", {})},
        **kwargs,
    )
    await cluster._start()
    return cluster


class CountingWorkerPlugin(WorkerPlugin):
    name = "counter-plugin"

    def __init__(self):
        self.setup_calls = 0

    def setup(self, worker):
        self.setup_calls += 1
        worker._counting_plugin_active = True

    def teardown(self, worker):
        worker._counting_plugin_active = False


class TransitionRecorder(SchedulerPlugin):
    name = "transition-recorder"

    def __init__(self):
        self.transitions = []

    def transition(self, key, start, finish, *args, **kwargs):
        self.transitions.append((key, start, finish))


@gen_test()
async def test_worker_plugin_on_existing_and_new_workers():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            await c.register_plugin(CountingWorkerPlugin())
            assert getattr(cluster.workers[0], "_counting_plugin_active", False)
            # a later-joining worker gets it too
            w2 = await cluster.add_worker(name="late")
            for _ in range(100):
                if getattr(w2, "_counting_plugin_active", False):
                    break
                await asyncio.sleep(0.01)
            assert w2._counting_plugin_active
            await c.unregister_worker_plugin("counter-plugin")
            assert not cluster.workers[0]._counting_plugin_active


@gen_test()
async def test_scheduler_plugin_sees_transitions():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            rec = TransitionRecorder()
            # register in-process (inproc comm passes the object through)
            await c.register_plugin(rec)
            fut = c.submit(lambda: 1, key="plugged")
            await fut.result()
            plugin = cluster.scheduler.state.plugins["transition-recorder"]
            states = [(s, f) for k, s, f in plugin.transitions if k == "plugged"]
            assert ("waiting", "processing") in states
            assert ("processing", "memory") in states


@gen_test()
async def test_rebalance_evens_memory():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            w0 = cluster.workers[0].address
            # pile data onto worker 0 only
            futs = c.map(
                lambda i: bytes(50_000), range(8), workers=[w0], pure=False
            )
            await c.gather(futs)
            assert len(cluster.workers[1].data) == 0
            out = await c.rebalance()
            assert out["moves"] > 0
            total = sum(len(w.data) for w in cluster.workers)
            for _ in range(100):
                if len(cluster.workers[1].data) > 0:
                    break
                await asyncio.sleep(0.01)
            assert len(cluster.workers[1].data) > 0
            # nothing lost
            results = await c.gather(futs)
            assert all(len(r) == 50_000 for r in results)


@gen_test()
async def test_cluster_dump():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(lambda x: x + 1, range(4))
            await c.gather(futs)
            dump = await c.dump_cluster_state()
            assert len(dump["scheduler"]["workers"]) == 2
            assert len(dump["scheduler"]["tasks"]) == 4
            assert all(
                t["state"] == "memory"
                for t in dump["scheduler"]["tasks"].values()
            )


@gen_test()
async def test_recreate_error_locally():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            def boom(x):
                raise ValueError("recreate-me")

            fut = c.submit(boom, 5, key="boom-task")
            with pytest.raises(ValueError):
                await fut.result()
            with pytest.raises(ValueError, match="recreate-me"):
                await c.recreate_error_locally(fut)


@gen_test(timeout=90)
async def test_worker_client_subtasks():
    """A task spawns sub-tasks via worker_client (reference
    test_worker_client patterns)."""
    async with await new_cluster(n_workers=2, threads_per_worker=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            def parent(n):
                from distributed_tpu.client.worker_client import worker_client

                with worker_client() as wc:
                    futs = [wc.submit(lambda x: x * 2, i, pure=False)
                            for i in range(n)]
                    return sorted(wc.gather_sync(futs))

            fut = c.submit(parent, 4)
            assert await asyncio.wait_for(fut.result(), 60) == [0, 2, 4, 6]


def test_client_executor_facade():
    """ClientExecutor: stdlib executor API over the cluster."""
    import asyncio as aio

    async def main():
        async with await new_cluster(n_workers=2) as cluster:
            c = Client(cluster.scheduler_address)
            async with c:
                ex = c.get_executor()
                cfut = ex.submit(lambda x: x + 100, 1)
                result = await aio.get_running_loop().run_in_executor(
                    None, cfut.result, 30
                )
                assert result == 101
                ex.shutdown(wait=False)

    aio.run(main())


@gen_test(timeout=120)
async def test_rebalance_device_path_evens_memory():
    """Enough keys + the jax gates open -> move selection runs through
    the device kernel (ops/rebalance.py) and still evens memory."""
    from distributed_tpu import config

    with config.set({"scheduler.jax.enabled": True,
                     "scheduler.jax.min-workers": 0}):
        async with await new_cluster(n_workers=2) as cluster:
            async with Client(cluster.scheduler_address) as c:
                w0 = cluster.workers[0].address
                futs = c.map(
                    lambda i: bytes(2_000), range(520), workers=[w0],
                    pure=False,
                )
                await c.gather(futs)
                assert len(cluster.workers[1].data) == 0
                out = await c.rebalance()
                assert out["moves"] > 0
                for _ in range(100):
                    if len(cluster.workers[1].data) > 0:
                        break
                    await asyncio.sleep(0.01)
                assert len(cluster.workers[1].data) > 0
                results = await c.gather(futs)
                assert all(len(r) == 2_000 for r in results)


@gen_test()
async def test_client_restart_clears_state_and_cluster_still_works():
    """client.restart(): all tasks forgotten, pending futures cancelled,
    the cluster keeps working (reference test_client.py::test_restart)."""
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(lambda x: x * 2, range(6))
            assert await c.gather(futs) == [x * 2 for x in range(6)]
            await c.restart()
            for _ in range(100):
                if not cluster.scheduler.state.tasks:
                    break
                await asyncio.sleep(0.02)
            assert not cluster.scheduler.state.tasks
            assert all(f.status in ("cancelled", "lost") for f in futs)
            # fresh work proceeds
            assert await c.submit(lambda: 9).result() == 9


@gen_test()
async def test_upload_file_imports_on_workers():
    """client.upload_file ships a module to every worker, current and
    future (reference test_client.py::test_upload_file)."""
    import os
    import sys
    import tempfile
    import textwrap

    from distributed_tpu.worker.server import Worker

    with tempfile.TemporaryDirectory() as td:
        mod = os.path.join(td, "dtpu_uploaded_mod.py")
        with open(mod, "w") as f:
            f.write(textwrap.dedent("""
                def quadruple(x):
                    return x * 4
                """))
        try:
            async with await new_cluster(n_workers=1) as cluster:
                async with Client(cluster.scheduler_address) as c:
                    await c.upload_file(mod)

                    def use_it(x):
                        import dtpu_uploaded_mod

                        return dtpu_uploaded_mod.quadruple(x)

                    assert await c.submit(use_it, 5).result() == 20
                    # a LATE worker gets the file too (plugin re-runs on join)
                    w2 = Worker(cluster.scheduler_address, nthreads=1)
                    await w2.start()
                    try:
                        assert await c.submit(
                            use_it, 7, workers=[w2.address]
                        ).result() == 28
                    finally:
                        await w2.close()
        finally:
            # UploadFile writes into the WORKER's cwd (this process for
            # in-proc workers): a leftover copy would make later runs
            # pass vacuously off the stale file
            sys.modules.pop("dtpu_uploaded_mod", None)
            stray = os.path.join(os.getcwd(), "dtpu_uploaded_mod.py")
            if os.path.exists(stray):
                os.remove(stray)


@gen_test()
async def test_upload_directory_ships_package():
    """UploadDirectory zips a package tree client-side and unpacks it on
    the node, importable by tasks (reference plugin.py:863)."""
    import os
    import sys
    import tempfile
    import textwrap

    from distributed_tpu.diagnostics.plugin import UploadDirectory

    with tempfile.TemporaryDirectory() as td:
        pkg = os.path.join(td, "dtpu_uploaded_pkg")
        os.makedirs(os.path.join(pkg, "__pycache__"))
        with open(os.path.join(pkg, "__init__.py"), "w") as f:
            f.write("from .mod import five\n")
        with open(os.path.join(pkg, "mod.py"), "w") as f:
            f.write(textwrap.dedent("""
                def five():
                    return 5
                """))
        # junk that must be pruned from the zip
        with open(os.path.join(pkg, "__pycache__", "x.pyc"), "wb") as f:
            f.write(b"junk")
        plugin = UploadDirectory(pkg)
        assert b"x.pyc" not in plugin.data

        # nanny-less cluster: nanny=False routes the NannyPlugin to the
        # workers (the default isinstance routing would broadcast to the
        # zero nannies and silently ship nothing)
        added = []
        try:
            async with await new_cluster(n_workers=1) as cluster:
                async with Client(cluster.scheduler_address) as c:
                    await c.register_plugin(plugin, nanny=False)
                    w = cluster.workers[0]
                    added.append(getattr(w, "local_directory", os.getcwd()))

                    def use_it(x):
                        import dtpu_uploaded_pkg

                        return dtpu_uploaded_pkg.five() + x

                    assert await c.submit(use_it, 1).result() == 6
        finally:
            import sys

            sys.modules.pop("dtpu_uploaded_pkg", None)
            sys.modules.pop("dtpu_uploaded_pkg.mod", None)
            for base in added:
                if base in sys.path:
                    sys.path.remove(base)


@gen_test()
async def test_forward_output_streams_prints_to_client():
    """ForwardOutput tees worker stdout/stderr into the scheduler event
    log under the "print" topic; a subscribed client sees task print()
    lines (reference plugin.py:992)."""
    from distributed_tpu.diagnostics.plugin import ForwardOutput

    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            seen: list = []
            got = asyncio.Event()

            def on_print(msg):
                # worker log-events arrive wrapped {"worker":, "msg":}
                inner = msg.get("msg") if isinstance(msg, dict) else None
                if isinstance(inner, dict):
                    seen.append(inner)
                    if inner.get("text") == "hello-from-task":
                        got.set()

            c.subscribe_topic("print", on_print)
            await c.register_plugin(ForwardOutput())
            try:
                def shout(x):
                    print("hello-from-task")
                    return x

                assert await c.submit(shout, 1).result() == 1
                await asyncio.wait_for(got.wait(), 30)
                assert any(
                    m["text"] == "hello-from-task"
                    and m["stream"] == "stdout" for m in seen
                )
            finally:
                # restore process-global streams before other tests run
                await c.unregister_worker_plugin("forward-output")
                import sys as _sys

                assert not hasattr(_sys.stdout, "_inner")


@gen_test(timeout=60)
async def test_config_driven_preloads():
    """scheduler.preload / worker.preload from CONFIG run at node start
    (reference distributed.yaml:27-28,90-91) — not only CLI flags."""
    import os
    import tempfile

    from distributed_tpu import config as dtpu_config

    with tempfile.TemporaryDirectory() as td:
        marker = os.path.join(td, "preload-ran")
        src = (
            "def dtpu_setup(worker):\n"
            f"    open({marker!r}, 'a').write(type(worker).__name__ + '\\n')\n"
        )
        with dtpu_config.set({
            "scheduler.preload": [src],
            "worker.preload": [src],
        }):
            async with LocalCluster(n_workers=1, threads_per_worker=1) as cluster:
                async with Client(cluster.scheduler_address) as c:
                    assert await c.submit(lambda: 1, key="pl-1").result() == 1
        kinds = sorted(open(marker).read().split())
        assert "Scheduler" in kinds and "Worker" in kinds, kinds


@gen_test(timeout=60)
async def test_no_workers_timeout_fails_unsatisfiable_tasks():
    """A task whose restrictions no worker can satisfy errs after
    scheduler.no-workers-timeout instead of parking forever."""
    from distributed_tpu import config as dtpu_config
    from distributed_tpu.exceptions import NoValidWorkerError

    with dtpu_config.set({"scheduler.no-workers-timeout": "500ms"}):
        async with LocalCluster(n_workers=1, threads_per_worker=1) as cluster:
            async with Client(cluster.scheduler_address) as c:
                fut = c.submit(lambda: 1, key="impossible",
                               resources={"GPU": 1})  # nobody has GPUs
                with pytest.raises(NoValidWorkerError):
                    await asyncio.wait_for(fut.result(), 30)
                # healthy tasks unaffected
                assert await c.submit(lambda: 2, key="fine").result() == 2


@gen_test(timeout=60)
async def test_config_preload_teardown_sees_live_cluster():
    """dtpu_teardown from CONFIG preloads runs before the node tears
    its comms down (same ordering as the CLI flag path)."""
    import os
    import tempfile

    from distributed_tpu import config as dtpu_config

    with tempfile.TemporaryDirectory() as td:
        marker = os.path.join(td, "teardown")
        src = (
            "def dtpu_setup(worker):\n"
            "    pass\n"
            "def dtpu_teardown(worker):\n"
            "    alive = not worker.batched_stream.closed()\n"
            f"    open({marker!r}, 'a').write(str(alive) + '\\n')\n"
        )
        with dtpu_config.set({"worker.preload": [src]}):
            async with LocalCluster(n_workers=1, threads_per_worker=1) as cluster:
                async with Client(cluster.scheduler_address) as c:
                    assert await c.submit(lambda: 1, key="td-1").result() == 1
        lines = open(marker).read().split()
        assert lines == ["True"], lines  # stream was live at teardown


@gen_test(timeout=60)
async def test_no_workers_timeout_does_not_pin_dependencies():
    """An erred-by-timeout task deregisters from its dependencies so a
    finished dep is not pinned in memory forever."""
    from distributed_tpu import config as dtpu_config
    from distributed_tpu.exceptions import NoValidWorkerError

    with dtpu_config.set({"scheduler.no-workers-timeout": "500ms"}):
        async with LocalCluster(n_workers=1, threads_per_worker=1) as cluster:
            async with Client(cluster.scheduler_address) as c:
                dep = c.submit(lambda: 11, key="dep-ok")
                assert await dep.result() == 11
                bad = c.submit(lambda x: x, dep, key="bad-gpu",
                               resources={"GPU": 1})
                with pytest.raises(NoValidWorkerError):
                    await asyncio.wait_for(bad.result(), 30)
                sts = cluster.scheduler.state.tasks["dep-ok"]
                assert not [w.key for w in sts.waiters], sts.waiters
                # releasing both futures must actually free the dep
                bad.release()
                dep.release()
                for _ in range(100):
                    if "dep-ok" not in cluster.scheduler.state.tasks:
                        break
                    await asyncio.sleep(0.05)
                assert "dep-ok" not in cluster.scheduler.state.tasks
