from distributed_tpu import config


def test_defaults_loaded():
    assert config.get("scheduler.worker-saturation") == 1.1
    assert config.get("scheduler.allowed-failures") == 3
    assert config.get("scheduler.bandwidth") == 100_000_000
    assert config.get("worker.memory.target") == 0.60


def test_get_default():
    assert config.get("no.such.path", 42) == 42


def test_set_restore():
    with config.set({"scheduler.worker-saturation": 2.0}):
        assert config.get("scheduler.worker-saturation") == 2.0
    assert config.get("scheduler.worker-saturation") == 1.1


def test_set_kwargs():
    with config.set(scheduler__work_stealing=False):
        assert config.get("scheduler.work-stealing") is False
    assert config.get("scheduler.work-stealing") is True


def test_parse_timedelta():
    assert config.parse_timedelta("100ms") == 0.1
    assert config.parse_timedelta("5 minutes") == 300.0
    assert config.parse_timedelta("1us") == 1e-6
    assert config.parse_timedelta(3) == 3.0
    assert config.parse_timedelta(None) is None
    assert config.parse_timedelta("5") == 5.0


def test_parse_bytes():
    assert config.parse_bytes("64MiB") == 64 * 2**20
    assert config.parse_bytes("50MB") == 50_000_000
    assert config.parse_bytes(123) == 123
    assert config.parse_bytes("1.5kb") == 1500


def test_env_override(monkeypatch):
    monkeypatch.setenv("DTPU_SCHEDULER__WORKER_SATURATION", "3.5")
    config.refresh()
    try:
        assert config.get("scheduler.worker-saturation") == 3.5
    finally:
        monkeypatch.delenv("DTPU_SCHEDULER__WORKER_SATURATION")
        config.refresh()
    assert config.get("scheduler.worker-saturation") == 1.1
