"""Live-cluster tests of the JAX placement co-processor: plans are
computed at update_graph time and consumed by decide_worker, with exact
fallback to the python oracle."""

from __future__ import annotations

import asyncio

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.local import LocalCluster
from distributed_tpu.scheduler.jax_placement import JaxPlacement

from conftest import gen_test


def inc(x):
    return x + 1


@gen_test(timeout=120)
async def test_plan_consumed_and_results_correct():
    placement = JaxPlacement(min_batch=4, min_workers=0, sync=True, min_transfer_ratio=0)
    async with LocalCluster(
        n_workers=2,
        scheduler_kwargs={"validate": True, "placement": placement},
        worker_kwargs={"validate": True},
    ) as cluster:
        async with Client(cluster.scheduler_address) as c:
            # one update-graph carrying a whole batch of chains: large
            # enough to trigger device planning; distinct key prefixes
            # keep the groups non-rootish so decide_worker_non_rootish
            # consults the plan
            from distributed_tpu.graph.spec import Graph, TaskRef, TaskSpec

            g = Graph()
            keys = []
            for i in range(6):
                g.tasks[f"src{i}-x"] = TaskSpec(inc, (i,))
                g.tasks[f"out{i}-x"] = TaskSpec(inc, (TaskRef(f"src{i}-x"),))
                keys.append(f"out{i}-x")
            futs = c.compute_graph(g, keys)
            results = await asyncio.wait_for(
                c.gather([futs[k] for k in keys]), 60
            )
            assert results == [i + 2 for i in range(6)]
            assert placement.plans_computed >= 1
            assert placement.plan_hits > 0


@gen_test(timeout=120)
async def test_async_plan_lands_mid_execution():
    """Default (async) planning: the device plan is computed off-loop and
    serves the waves that become ready after it lands; early tasks fall
    back to the python oracle with no loop stall."""
    import time as _time

    placement = JaxPlacement(min_batch=4, min_workers=0, min_transfer_ratio=0)
    assert not placement.sync

    # warm the partitioner jit off-line: the async plan's sleep slack
    # below must cover planning only, not the first XLA-CPU compile
    # (~seconds on a loaded box — the plan would land after the second
    # layer was already oracle-placed and plan_hits would read 0)
    import numpy as np

    placement._plan_from_arrays(
        [f"warm{i}" for i in range(8)],
        np.ones(8, np.float32), np.full(8, 1e6, np.float32),
        np.arange(4, dtype=np.int32), np.arange(4, 8, dtype=np.int32),
        np.ones(2, np.int32), np.zeros(2, np.float32),
        np.ones(2, bool), ["w0", "w1"], 1e8, 0.001,
    )

    def slow_inc(x):
        _time.sleep(0.5)
        return x + 1

    async with LocalCluster(
        n_workers=2,
        scheduler_kwargs={"validate": True, "placement": placement},
        worker_kwargs={"validate": True},
    ) as cluster:
        async with Client(cluster.scheduler_address) as c:
            from distributed_tpu.graph.spec import Graph, TaskRef, TaskSpec

            g = Graph()
            keys = []
            for i in range(6):
                g.tasks[f"asrc{i}-x"] = TaskSpec(slow_inc, (i,))
                g.tasks[f"aout{i}-x"] = TaskSpec(inc, (TaskRef(f"asrc{i}-x"),))
                keys.append(f"aout{i}-x")
            futs = c.compute_graph(g, keys)
            results = await asyncio.wait_for(
                c.gather([futs[k] for k in keys]), 60
            )
            assert results == [i + 2 for i in range(6)]
            # plan landed off-loop (0.3 s of slack) and the second layer
            # consumed it
            assert placement.plans_computed >= 1
            assert placement.plan_hits > 0


@gen_test(timeout=120)
async def test_plan_fallback_when_worker_dies():
    placement = JaxPlacement(min_batch=4, min_workers=0, sync=True, min_transfer_ratio=0)
    async with LocalCluster(
        n_workers=2,
        scheduler_kwargs={"validate": True, "placement": placement},
        worker_kwargs={"validate": True},
    ) as cluster:
        async with Client(cluster.scheduler_address) as c:
            from distributed_tpu.graph.spec import Graph, TaskRef, TaskSpec

            g = Graph()
            keys = []
            for i in range(4):
                g.tasks[f"fbsrc{i}-x"] = TaskSpec(inc, (i,))
                g.tasks[f"fbout{i}-x"] = TaskSpec(inc, (TaskRef(f"fbsrc{i}-x"),))
                keys.append(f"fbout{i}-x")
            futs = c.compute_graph(g, keys)
            assert await asyncio.wait_for(
                c.gather([futs[k] for k in keys]), 60
            ) == [i + 2 for i in range(4)]
            # drop a worker: its plan entries must be purged, new work runs
            victim = cluster.workers[0]
            await victim.close(report=False)
            cluster.workers = cluster.workers[1:]
            assert all(
                follow is not None or addr != victim.address
                for follow, addr in placement.plan.values()
            )
            futs2 = c.map(inc, range(8), pure=False)
            assert await asyncio.wait_for(c.gather(futs2), 60) == list(
                range(1, 9)
            )


@gen_test()
async def test_placement_disabled_by_flag():
    async with LocalCluster(
        n_workers=1,
        scheduler_kwargs={"validate": True, "placement": False},
    ) as cluster:
        assert cluster.scheduler.state.placement is None
        async with Client(cluster.scheduler_address) as c:
            assert await c.submit(inc, 1).result() == 2


def test_hint_resolution_hit_park_yield():
    """Three-verdict hint consumption (finite home-depth): open slot ->
    hit; home stacked to depth but backlog in line with the cluster
    average -> park (the home pulls it at its next slot-open); home an
    extreme backlog outlier with a tiny dep -> yield to an idle worker;
    a huge dep keeps the task bound to its home (park) even then."""
    from distributed_tpu import config
    from distributed_tpu.scheduler.state import SchedulerState

    with config.set({"scheduler.jax.home-depth": 0,
                     "scheduler.jax.drift-yield": True}):
        state = SchedulerState(validate=True)
        placement = JaxPlacement(min_batch=1, min_workers=0, sync=True)
    busy = state.add_worker_state("tcp://h:1", nthreads=1, memory_limit=2**30)
    idle = state.add_worker_state("tcp://h:2", nthreads=1, memory_limit=2**30)
    state.check_idle_saturated(busy)
    state.check_idle_saturated(idle)

    dep = state.new_task("dep-1", None, "released")
    dep.state = "memory"
    dep.who_has.add(busy)
    busy.has_what[dep] = None
    ts = state.new_task("child-1", None, "released")
    ts.add_dependency(dep)

    # open slot on the home -> immediate hit, no second-guessing
    placement.plan = {ts.key: (dep.key, busy.address)}
    verdict, ws = placement.resolve(state, ts, None)
    assert (verdict, ws) == ("hit", busy)
    assert placement.plan_hits == 1

    # fill the home's stack to the accepted depth (home-depth=0 ->
    # ceil(nthreads*saturation) = 2)
    import math

    depth = math.ceil(busy.nthreads * state.WORKER_SATURATION)
    for i in range(depth):
        filler = state.new_task(f"filler-{i}", None, "released")
        busy.processing[filler] = 0.001
    state.idle.pop(busy.address, None)
    state.idle_task_count.discard(busy)
    assert idle.address in state.idle

    # home backlog in line with the cluster average -> park for the home
    busy.occupancy = 0.002
    state._total_occupancy = 0.002
    dep.nbytes = 1
    placement.plan = {ts.key: (dep.key, busy.address)}
    verdict, ws = placement.resolve(state, ts, None)
    assert (verdict, ws) == ("park", busy)
    assert placement.plan_parks == 1
    assert ts.key in placement.plan  # hint kept for the later pull

    # home an EXTREME outlier vs the average + tiny dep: waiting behind
    # 10s of queue to save a 1-byte transfer is absurd -> yield (miss)
    busy.occupancy = 10.0
    state._total_occupancy = 10.0
    placement.plan = {ts.key: (dep.key, busy.address)}
    verdict, ws = placement.resolve(state, ts, None)
    assert (verdict, ws) == ("miss", None)
    assert placement.plan_misses == 1

    # huge dep (100s at the configured bandwidth): locality beats the
    # 10s queue -> the task stays bound to its home and parks for it
    dep.nbytes = int(state.bandwidth * 100)
    placement.plan = {ts.key: (dep.key, busy.address)}
    verdict, ws = placement.resolve(state, ts, None)
    assert (verdict, ws) == ("park", busy)
