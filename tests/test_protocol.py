"""Frame protocol + serialization families (reference
distributed/protocol/tests/test_serialize.py, test_numpy.py,
test_torch.py, test_arrow.py patterns)."""

from __future__ import annotations

import numpy as np
import pytest

from distributed_tpu.protocol.core import dumps, loads
from distributed_tpu.protocol.serialize import (
    Serialize,
    Serialized,
    ToPickle,
    deserialize,
    payload_nbytes,
    serialize,
    wrap_opaque,
)


def roundtrip(msg):
    return loads(dumps(msg))


def test_msgpack_body_roundtrip():
    msg = {"op": "test", "n": 3, "keys": ["a", "b"], "nested": {"x": 1.5},
           "flag": True, "none": None, "blob": b"bytes"}
    assert roundtrip(msg) == msg


def test_numpy_family_zero_copy_shape_dtype():
    for arr in (
        np.arange(12, dtype=np.int64).reshape(3, 4),
        np.random.default_rng(0).random((5, 7)).astype(np.float32),
        np.array([], dtype=np.uint8),
    ):
        out = roundtrip({"data": Serialize(arr)})["data"]
        assert out.dtype == arr.dtype and out.shape == arr.shape
        np.testing.assert_array_equal(out, arr)


def test_jax_family_roundtrip():
    import jax.numpy as jnp

    x = jnp.arange(8.0).reshape(2, 4)
    out = roundtrip({"data": Serialize(x)})["data"]
    assert isinstance(out, type(x))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_torch_family_roundtrip():
    torch = pytest.importorskip("torch")

    t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    out = roundtrip({"data": Serialize(t)})["data"]
    assert isinstance(out, torch.Tensor)
    assert torch.equal(out, t)
    # non-contiguous and grad-carrying tensors survive
    nc = torch.arange(12.0).reshape(3, 4).t()
    assert not nc.is_contiguous()
    out = roundtrip({"data": Serialize(nc)})["data"]
    assert torch.equal(out, nc)
    g = torch.ones(3, requires_grad=True)
    out = roundtrip({"data": Serialize(g)})["data"]
    assert out.requires_grad


def test_arrow_family_roundtrip():
    pa = pytest.importorskip("pyarrow")

    table = pa.table({"k": [1, 2, 3], "v": ["a", "b", "c"]})
    out = roundtrip({"data": Serialize(table)})["data"]
    assert isinstance(out, pa.Table)
    assert out.equals(table)
    batch = table.to_batches()[0]
    out = roundtrip({"data": Serialize(batch)})["data"]
    assert isinstance(out, pa.RecordBatch)
    assert out.equals(batch)


def test_pickle_fallback_for_plain_objects():
    class Thing:
        def __init__(self, v):
            self.v = v

        def __eq__(self, other):
            return self.v == other.v

    out = roundtrip({"data": Serialize(Thing(41))})["data"]
    assert out == Thing(41)


def test_topickle_roundtrip():
    msg = {"tasks": ToPickle({"a": (sum, [1, 2])})}
    out = roundtrip(msg)["tasks"]
    assert out["a"][0] is sum


def test_large_frame_compression_and_shard_split():
    from distributed_tpu import config

    # compression is off by default (like the reference's comm default);
    # opt in and a highly compressible 8 MB payload shrinks >10x
    arr = np.zeros(1_000_000, dtype=np.float64)
    with config.set({"comm.compression": "auto"}):
        frames = dumps({"data": Serialize(arr)})
        assert sum(len(f) for f in frames) < arr.nbytes / 10
        out = loads(frames)["data"]
    np.testing.assert_array_equal(out, arr)
    # shard splitting: frames above comm.shard are split and re-merged
    with config.set({"comm.shard": "64KiB"}):
        rnd = np.random.default_rng(0).random(100_000)  # incompressible
        frames = dumps({"data": Serialize(rnd)})
        assert len(frames) > 5  # split into ~12 shards + header/body
        np.testing.assert_array_equal(loads(frames)["data"], rnd)


def test_opaque_mode_keeps_frames_and_forwards():
    """deserialize=False semantics: loads leaves Serialized leaves; a
    second dumps emits the same frames without re-serializing; the final
    consumer sees the original object."""
    arr = np.arange(100, dtype=np.int32)
    opaque = loads(dumps({"x": Serialize(arr)}), deserializers=False)["x"]
    assert isinstance(opaque, Serialized)
    # forwarding hop (scheduler -> worker)
    final = loads(dumps({"x": opaque}))["x"]
    np.testing.assert_array_equal(final, arr)
    # a careless double-wrap must not pickle the wrapper
    final2 = loads(dumps({"x": Serialize(opaque)}))["x"]
    np.testing.assert_array_equal(final2, arr)


def test_wrap_opaque_and_payload_nbytes():
    arr = np.arange(10, dtype=np.int64)
    header, frames = serialize(arr)
    opq = Serialized(header, frames)
    assert wrap_opaque(opq) is opq
    assert wrap_opaque(None) is None
    assert isinstance(wrap_opaque({"fn": len}), ToPickle)
    assert payload_nbytes(opq) == sum(
        len(f) if isinstance(f, (bytes, bytearray)) else f.nbytes
        for f in frames
    )
    assert payload_nbytes(Serialize(arr)) >= arr.nbytes
    assert deserialize(header, frames).tolist() == arr.tolist()


def test_error_family_raises_on_load():
    class Unpicklable:
        def __reduce__(self):
            raise RuntimeError("nope")

    frames = dumps({"x": Serialize(Unpicklable())})
    with pytest.raises(TypeError, match="Could not deserialize"):
        loads(frames)


def test_arrow_empty_batch_and_frame_contract():
    pa = pytest.importorskip("pyarrow")

    # zero-row RecordBatch survives the roundtrip
    empty = pa.RecordBatch.from_arrays(
        [pa.array([], type=pa.int64()), pa.array([], type=pa.string())],
        names=["k", "v"],
    )
    out = roundtrip({"data": Serialize(empty)})["data"]
    assert isinstance(out, pa.RecordBatch)
    assert out.num_rows == 0 and out.schema.equals(empty.schema)
    # frames honor the bytes/memoryview contract (payload_nbytes sizes them)
    header, frames = serialize(pa.table({"k": [1, 2, 3]}))
    assert all(isinstance(f, (bytes, bytearray, memoryview)) for f in frames)
    assert payload_nbytes(Serialized(header, frames)) > 0


def test_shared_serialized_leaf_many_paths():
    """One Serialized object at MANY message paths (a single erred
    exception blamed on every dependent in one report batch): each
    placeholder must get its own sub-header/frames.  dumps used to
    annotate the leaf's own header dict in place, so all sub-headers
    aliased the last path and 15 of 16 placeholders lost their frames
    (found by the 2-process pod test: the client report stream died on
    KeyError and every future errored with 'lost connection')."""
    from distributed_tpu.protocol.core import dumps, loads
    from distributed_tpu.protocol.serialize import Serialize, serialize, Serialized

    exc = ValueError("boom")
    header, frames = serialize(Serialize(exc))
    shared = Serialized(header, frames)
    msgs = [
        {"op": "task-erred", "key": f"k{i}", "exception": shared}
        for i in range(16)
    ]
    out = loads(dumps(msgs))
    assert len(out) == 16
    for m in out:
        assert isinstance(m["exception"], ValueError)
        assert str(m["exception"]) == "boom"
    # the shared header must NOT have been polluted with path metadata
    assert "path" not in header and "frame-start" not in header


def test_nested_deserialize_cow_and_subclasses():
    """Copy-on-write: wrapper-free messages return the SAME object;
    wrappers anywhere (including namedtuples / dict subclasses) unwrap."""
    from collections import OrderedDict, namedtuple

    from distributed_tpu.protocol.serialize import Serialize, nested_deserialize

    plain = {"op": "compute-task", "who_has": {"a": ["w1"]}, "pri": (1, 2)}
    assert nested_deserialize(plain) is plain

    msg = {"op": "g", "payload": [Serialize(11), {"x": Serialize(22)}]}
    out = nested_deserialize(msg)
    assert out["payload"][0] == 11 and out["payload"][1]["x"] == 22
    assert isinstance(msg["payload"][0], Serialize)  # original untouched

    Point = namedtuple("Point", ["x", "y"])
    p = Point(Serialize(1), 2)
    q = nested_deserialize(p)
    assert isinstance(q, Point) and q == Point(1, 2)
    p2 = Point(1, 2)
    assert nested_deserialize(p2) is p2  # unchanged namedtuple passes through

    od = OrderedDict([("k", Serialize(9))])
    assert nested_deserialize(od)["k"] == 9
