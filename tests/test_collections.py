import pytest

from distributed_tpu.utils import LRU, HeapSet


class El:
    def __init__(self, name, pri):
        self.name = name
        self.pri = pri

    def __repr__(self):
        return f"El({self.name})"


def test_heapset_basic():
    hs = HeapSet(key=lambda el: el.pri)
    a, b, c = El("a", 3), El("b", 1), El("c", 2)
    for el in (a, b, c):
        hs.add(el)
    assert len(hs) == 3
    assert b in hs
    assert hs.peek() is b
    assert hs.pop() is b
    assert hs.pop() is c
    assert hs.pop() is a
    assert len(hs) == 0
    with pytest.raises(KeyError):
        hs.pop()


def test_heapset_discard_and_stale_entries():
    hs = HeapSet(key=lambda el: el.pri)
    a, b = El("a", 1), El("b", 2)
    hs.add(a)
    hs.add(b)
    hs.discard(a)
    assert hs.peek() is b
    hs.add(a)  # re-add with same priority
    assert hs.pop() is a


def test_heapset_peekn():
    hs = HeapSet(key=lambda el: el.pri)
    els = [El(str(i), i) for i in [5, 3, 8, 1]]
    for el in els:
        hs.add(el)
    names = [el.name for el in hs.peekn(3)]
    assert names == ["1", "3", "5"]
    assert len(hs) == 4  # peekn restores


def test_heapset_add_idempotent():
    hs = HeapSet(key=lambda el: el.pri)
    a = El("a", 1)
    hs.add(a)
    hs.add(a)
    assert len(hs) == 1
    hs.pop()
    assert len(hs) == 0


def test_lru():
    lru = LRU(maxsize=2)
    lru["a"] = 1
    lru["b"] = 2
    lru["c"] = 3
    assert "a" not in lru
    assert lru["b"] == 2
    lru["d"] = 4
    assert "c" not in lru  # b was touched, c evicted


def test_heapset_readd_reorders_both_directions():
    """remove+add with a changed priority must be fully visible to
    peek/pop/peekn — stale entries (old priority, either direction)
    lose to the element's latest add."""
    from distributed_tpu.utils import HeapSet

    class El:
        def __init__(self, name, pri):
            self.name = name
            self.pri = pri

    h = HeapSet(key=lambda e: e.pri)
    a, b = El("a", 5), El("b", 3)
    h.add(a)
    h.add(b)
    assert h.peek() is b
    # deprioritize b below a: the old (3) entry must not shadow it
    h.remove(b)
    b.pri = 9
    h.add(b)
    assert h.peek() is a
    assert [e.name for e in h.peekn(2)] == ["a", "b"]
    # and prioritization works too
    h.remove(b)
    b.pri = 1
    h.add(b)
    assert [e.name for e in h.peekn(2)] == ["b", "a"]
    assert h.pop() is b
    assert h.pop() is a

