"""Ring attention over the virtual 8-device mesh vs the O(N^2) oracle."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tpu.ops.ici import make_mesh_1d
from distributed_tpu.ops.ring_attention import (
    reference_attention,
    ring_attention,
)

needs_mesh = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device CPU mesh"
)


def _qkv(n=256, h=2, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((n, h, d)), jnp.float32)
    return mk(), mk(), mk()


@needs_mesh
@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(causal):
    mesh = make_mesh_1d(8, axis="sp")
    q, k, v = _qkv()
    out = ring_attention(mesh, q, k, v, axis="sp", causal=causal)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@needs_mesh
def test_ring_output_stays_sharded():
    """Input sharded over the mesh -> output sharded over the mesh: the
    whole sequence never materializes on one device."""
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = make_mesh_1d(8, axis="sp")
    q, k, v = _qkv(n=512)
    sh = NamedSharding(mesh, PartitionSpec("sp"))
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
    out = ring_attention(mesh, q, k, v, axis="sp")
    assert out.sharding.spec == PartitionSpec("sp")
    # per-shard size is 1/8th of the sequence
    shard = out.addressable_shards[0]
    assert shard.data.shape[0] == 512 // 8


@needs_mesh
def test_ring_handles_uneven_magnitudes():
    """Online-softmax stability: huge score spread across blocks."""
    mesh = make_mesh_1d(8, axis="sp")
    q, k, v = _qkv(n=128, h=1, d=8, seed=3)
    q = q * 30.0  # sharp, near-one-hot softmax rows
    out = ring_attention(mesh, q, k, v, axis="sp")
    want = reference_attention(q, k, v)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), rtol=1e-4, atol=1e-4
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kernel_matches_reference(causal):
    from distributed_tpu.ops.flash import flash_attention

    q, k, v = _qkv(n=256, h=2, d=16, seed=1)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_flash_kernel_rejects_ragged_blocks():
    from distributed_tpu.ops.flash import flash_attention

    q, k, v = _qkv(n=100, h=1, d=8)
    with pytest.raises(ValueError, match="divide"):
        flash_attention(q, k, v, block_q=64, block_k=64)


@needs_mesh
@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(causal):
    from distributed_tpu.ops.ulysses import ulysses_attention

    mesh = make_mesh_1d(8, axis="sp")
    q, k, v = _qkv(n=256, h=8, d=16, seed=2)
    out = ulysses_attention(mesh, q, k, v, axis="sp", causal=causal)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@needs_mesh
def test_ulysses_rejects_indivisible_heads():
    from distributed_tpu.ops.ulysses import ulysses_attention

    mesh = make_mesh_1d(8, axis="sp")
    q, k, v = _qkv(n=64, h=4, d=8)
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(mesh, q, k, v)


@needs_mesh
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_gradients_match_reference(causal):
    """Long-context is TRAINING-grade: jax.grad flows through the ring
    (scan + ppermute have transpose rules) and matches the dense
    attention gradient on every shard."""
    import jax
    import jax.numpy as jnp

    from distributed_tpu.ops.ring_attention import (
        reference_attention,
        ring_attention,
    )

    mesh = make_mesh_1d(8, axis="sp")
    q, k, v = _qkv(n=64, h=2, d=8, seed=5)
    q = jnp.asarray(q, jnp.float32)
    k = jnp.asarray(k, jnp.float32)
    v = jnp.asarray(v, jnp.float32)

    def loss_ring(q, k, v):
        out = ring_attention(mesh, q, k, v, axis="sp", causal=causal)
        return (out * out).sum()

    def loss_ref(q, k, v):
        out = reference_attention(q, k, v, causal=causal)
        return (out * out).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3,
            err_msg=f"d/d{name} diverged",
        )


@needs_mesh
def test_ulysses_gradients_match_reference():
    """all_to_all also has a transpose rule: Ulysses attention trains."""
    import jax
    import jax.numpy as jnp

    from distributed_tpu.ops.ring_attention import reference_attention
    from distributed_tpu.ops.ulysses import ulysses_attention

    mesh = make_mesh_1d(8, axis="sp")
    q, k, v = _qkv(n=64, h=8, d=8, seed=6)
    q = jnp.asarray(q, jnp.float32)
    k = jnp.asarray(k, jnp.float32)
    v = jnp.asarray(v, jnp.float32)

    def loss_uly(q, k, v):
        out = ulysses_attention(mesh, q, k, v, axis="sp", causal=True)
        return (out * out).sum()

    def loss_ref(q, k, v):
        out = reference_attention(q, k, v, causal=True)
        return (out * out).sum()

    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_uly, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3,
            err_msg=f"d/d{name} diverged",
        )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_reference(causal):
    """The pallas kernel's custom_vjp (recompute backward) matches the
    dense attention gradient."""
    import jax
    import jax.numpy as jnp

    from distributed_tpu.ops.flash import flash_attention
    from distributed_tpu.ops.ring_attention import reference_attention

    q, k, v = _qkv(n=128, h=2, d=16, seed=7)
    q = jnp.asarray(q, jnp.float32)
    k = jnp.asarray(k, jnp.float32)
    v = jnp.asarray(v, jnp.float32)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        return (out * jnp.cos(out)).sum()

    def loss_ref(q, k, v):
        out = reference_attention(q, k, v, causal=causal)
        return (out * jnp.cos(out)).sum()

    g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_f, g_r, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3,
            err_msg=f"d/d{name} diverged",
        )


def test_flash_gradients_cross_length():
    """Cross-attention shape (KV longer than Q): backward works and
    matches the dense oracle."""
    import jax
    import jax.numpy as jnp

    from distributed_tpu.ops.flash import flash_attention
    from distributed_tpu.ops.ring_attention import reference_attention

    rngq = np.random.default_rng(8)
    q = jnp.asarray(rngq.standard_normal((32, 2, 16)), jnp.float32)
    k = jnp.asarray(rngq.standard_normal((64, 2, 16)), jnp.float32)
    v = jnp.asarray(rngq.standard_normal((64, 2, 16)), jnp.float32)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, block_q=32, block_k=32)
        return (out * out).sum()

    def loss_ref(q, k, v):
        out = reference_attention(q, k, v)
        return (out * out).sum()

    g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_f, g_r, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3,
            err_msg=f"d/d{name} diverged",
        )
