"""Ring attention over the virtual 8-device mesh vs the O(N^2) oracle."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tpu.ops.ici import make_mesh_1d
from distributed_tpu.ops.ring_attention import (
    reference_attention,
    ring_attention,
)

needs_mesh = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device CPU mesh"
)


def _qkv(n=256, h=2, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((n, h, d)), jnp.float32)
    return mk(), mk(), mk()


@needs_mesh
@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(causal):
    mesh = make_mesh_1d(8, axis="sp")
    q, k, v = _qkv()
    out = ring_attention(mesh, q, k, v, axis="sp", causal=causal)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@needs_mesh
def test_ring_output_stays_sharded():
    """Input sharded over the mesh -> output sharded over the mesh: the
    whole sequence never materializes on one device."""
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = make_mesh_1d(8, axis="sp")
    q, k, v = _qkv(n=512)
    sh = NamedSharding(mesh, PartitionSpec("sp"))
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
    out = ring_attention(mesh, q, k, v, axis="sp")
    assert out.sharding.spec == PartitionSpec("sp")
    # per-shard size is 1/8th of the sequence
    shard = out.addressable_shards[0]
    assert shard.data.shape[0] == 512 // 8


@needs_mesh
def test_ring_handles_uneven_magnitudes():
    """Online-softmax stability: huge score spread across blocks."""
    mesh = make_mesh_1d(8, axis="sp")
    q, k, v = _qkv(n=128, h=1, d=8, seed=3)
    q = q * 30.0  # sharp, near-one-hot softmax rows
    out = ring_attention(mesh, q, k, v, axis="sp")
    want = reference_attention(q, k, v)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), rtol=1e-4, atol=1e-4
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kernel_matches_reference(causal):
    from distributed_tpu.ops.flash import flash_attention

    q, k, v = _qkv(n=256, h=2, d=16, seed=1)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_flash_kernel_rejects_ragged_blocks():
    from distributed_tpu.ops.flash import flash_attention

    q, k, v = _qkv(n=100, h=1, d=8)
    with pytest.raises(ValueError, match="divide"):
        flash_attention(q, k, v, block_q=64, block_k=64)


@needs_mesh
@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(causal):
    from distributed_tpu.ops.ulysses import ulysses_attention

    mesh = make_mesh_1d(8, axis="sp")
    q, k, v = _qkv(n=256, h=8, d=16, seed=2)
    out = ulysses_attention(mesh, q, k, v, axis="sp", causal=causal)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@needs_mesh
def test_ulysses_rejects_indivisible_heads():
    from distributed_tpu.ops.ulysses import ulysses_attention

    mesh = make_mesh_1d(8, axis="sp")
    q, k, v = _qkv(n=64, h=4, d=8)
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(mesh, q, k, v)
