"""Process-backed deploy layer: SubprocessCluster, SSHCluster, memory-limit
detection (reference deploy/tests/test_subprocess.py, test_ssh.py,
tests/test_system.py patterns)."""

from __future__ import annotations

import asyncio
import os
import stat
import sys

import pytest

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.ssh import SSHCluster
from distributed_tpu.deploy.subprocess import SubprocessCluster, child_env
from distributed_tpu.utils.system import (
    MEMORY_LIMIT,
    memory_limit,
    parse_memory_limit,
)

from conftest import gen_test

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# pickled BY VALUE (cloudpickle), so subprocess workers need not import
# this test module; see also test_scheduler_opaque_specs for the
# by-reference case (scheduler must never unpickle user code)
_inc = lambda x: x + 1  # noqa: E731


@pytest.mark.slow
@gen_test(timeout=120)
async def test_subprocess_cluster_roundtrip():
    async with SubprocessCluster(n_workers=2, nthreads=1) as cluster:
        assert cluster.scheduler_address.startswith("tcp://127.0.0.1:")
        assert len(cluster.workers) == 2
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(_inc, range(8))
            assert await c.gather(futs) == list(range(1, 9))


@pytest.mark.slow
@gen_test(timeout=180)
async def test_subprocess_cluster_scales():
    async with SubprocessCluster(n_workers=1, nthreads=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            await cluster.scale(3)
            assert len(cluster.workers) == 3
            # all three processes execute work
            futs = c.map(_inc, range(12))
            assert await c.gather(futs) == list(range(1, 13))
            await cluster.scale(1)
            assert len(cluster.workers) == 1
            # the survivor still works after its peers were retired
            assert await c.submit(_inc, 100).result() == 101


def _write_fake_ssh(tmp_path) -> str:
    """An 'ssh client' that ignores the host and runs the command locally —
    exercises SSHCluster's full command construction + address discovery."""
    script = tmp_path / "fake-ssh"
    script.write_text('#!/bin/bash\nshift\nexec bash -c "$*"\n')
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    return str(script)


@pytest.mark.slow
@gen_test(timeout=120)
async def test_ssh_cluster_roundtrip(tmp_path=None):
    import tempfile
    from pathlib import Path

    with tempfile.TemporaryDirectory() as td:
        fake_ssh = _write_fake_ssh(Path(td))
        env = child_env()
        async with SSHCluster(
            ["127.0.0.1", "127.0.0.1", "127.0.0.1"],
            connect_command=[fake_ssh],
            remote_python=sys.executable,
            env_vars={
                "PYTHONPATH": env["PYTHONPATH"],
                "JAX_PLATFORMS": "cpu",
            },
            scheduler_options={"port": 0},
        ) as cluster:
            # bind address rewritten to the dialable host
            assert cluster.scheduler_address.startswith("tcp://127.0.0.1:")
            assert len(cluster.workers) == 2
            async with Client(cluster.scheduler_address) as c:
                futs = c.map(_inc, range(6))
                assert await c.gather(futs) == list(range(1, 7))


def test_ssh_cluster_needs_two_hosts():
    with pytest.raises(ValueError, match="hosts"):
        SSHCluster(["onlyhost"])


def test_ssh_command_construction():
    from distributed_tpu.deploy.ssh import SSHScheduler, SSHWorker

    s = SSHScheduler(
        "gw", port=8786, connect_command=["ssh", "-A"],
        remote_python="/opt/py/bin/python", env_vars={"X": "a b"},
    )
    argv = s._argv()
    assert argv[:3] == ["ssh", "-A", "gw"]
    assert "X='a b'" in argv[3]
    assert "/opt/py/bin/python -m distributed_tpu.cli.scheduler" in argv[3]

    w = SSHWorker("tcp://gw:8786", host="node1", nthreads=2, nanny=True)
    argv = w._argv()
    assert argv[:2] == ["ssh", "node1"]
    assert "tcp://gw:8786" in argv[2]
    assert "--nthreads 2" in argv[2]
    assert "--nanny" in argv[2]
    # binds the scheduler-routing interface, not the ssh alias
    assert "--host auto" in argv[2]
    w2 = SSHWorker("tcp://gw:8786", host="node1", bind_host="10.0.0.7")
    assert "--host 10.0.0.7" in w2._argv()[2]


def test_memory_limit_detection():
    limit = memory_limit()
    assert limit > 0
    assert MEMORY_LIMIT == limit or MEMORY_LIMIT > 0
    # never more than physical memory
    import psutil

    assert limit <= psutil.virtual_memory().total


def test_parse_memory_limit():
    assert parse_memory_limit(None) == 0
    assert parse_memory_limit("0") == 0
    assert parse_memory_limit(0) == 0
    assert parse_memory_limit(12345) == 12345
    assert parse_memory_limit("4GiB") == 4 * 2**30
    assert parse_memory_limit("auto", nworkers=4) == MEMORY_LIMIT // 4
    assert parse_memory_limit(0.5) == int(0.5 * MEMORY_LIMIT)
    assert parse_memory_limit("0.5") == int(0.5 * MEMORY_LIMIT)
