"""Device kernels for work stealing and AMM replica drops
(ops/stealing.py, ops/amm.py): oracle-parity by sequential re-validation
against the python criterion, plus live-cluster tests where the device
path makes real decisions."""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from distributed_tpu.ops.amm import DropBatch, plan_drop_rounds, plan_drops
from distributed_tpu.ops.stealing import (
    LATENCY,
    StealBatch,
    make_key,
    plan_steals,
)

from conftest import gen_test


# ----------------------------------------------------------- ops.stealing


def random_steal_batch(rng, T=200, W=16, idle_frac=0.5):
    victim_workers = rng.integers(0, W, T)
    level = rng.integers(0, 15, T)
    rank = np.arange(T)
    occ = np.zeros(W, np.float32)
    compute = rng.uniform(0.05, 0.5, T).astype(np.float32)
    cost = rng.uniform(0.0, 0.05, T).astype(np.float32) + LATENCY
    for t in range(T):
        occ[victim_workers[t]] += compute[t]
    idle = occ < np.quantile(occ, idle_frac)
    return StealBatch(
        task_victim=victim_workers.astype(np.int32),
        task_key=make_key(level, rank),
        task_cost=cost,
        task_compute=compute,
        occ=occ,
        nthreads=np.full(W, 2, np.int32),
        idle=idle,
        running=np.ones(W, bool),
    )


def test_steals_satisfy_python_criterion_sequentially():
    """Every emitted move must satisfy the reference steal criterion when
    the moves are replayed sequentially (the python oracle's contract,
    reference stealing.py:462-465)."""
    rng = np.random.default_rng(0)
    batch = random_steal_batch(rng)
    thief_of = plan_steals(batch)
    assert (thief_of >= 0).sum() > 0, "kernel made no steals on an imbalance"

    occ = batch.occ.astype(np.float64).copy()
    threads = np.maximum(batch.nthreads, 1)
    for t in np.nonzero(thief_of >= 0)[0]:
        v = batch.task_victim[t]
        th = thief_of[t]
        assert v != th
        cp = batch.task_compute[t]
        tc = batch.task_cost[t]
        # tolerance: the kernel evaluates the criterion at round-local
        # occupancy; replay order within a round is arbitrary but rounds
        # touch distinct victim/thief pairs, so the inequality holds up
        # to float32 rounding
        assert occ[th] / threads[th] + tc + cp <= occ[v] / threads[v] - cp / 2 + 1e-4, (
            t, v, th,
        )
        occ[v] -= cp
        occ[th] += cp + tc
    # no task stolen twice, no thief == victim
    stolen = thief_of[thief_of >= 0]
    assert len(stolen) == (thief_of >= 0).sum()


def test_steal_prefers_low_levels():
    """Within one victim, the lowest (level, rank) task moves first —
    the python scan order (reference stealing.py:420)."""
    W = 4
    T = 8
    victim = np.zeros(T, np.int32)  # all on worker 0
    level = np.asarray([9, 1, 5, 1, 14, 0, 7, 3])
    batch = StealBatch(
        task_victim=victim,
        task_key=make_key(level, np.arange(T)),
        task_cost=np.full(T, LATENCY, np.float32),
        task_compute=np.full(T, 1.0, np.float32),
        occ=np.asarray([8.0, 0, 0, 0], np.float32),
        nthreads=np.ones(W, np.int32),
        idle=np.asarray([False, True, True, True]),
        running=np.ones(W, bool),
    )
    thief_of = plan_steals(batch, rounds=1)
    # one round, one task per idle THIEF (a single overloaded victim can
    # feed the whole fleet at once); steal order follows (level, rank)
    stolen = set(np.flatnonzero(thief_of >= 0).tolist())
    assert 1 <= len(stolen) <= 3  # 3 idle thieves
    # the stolen tasks must be exactly the lowest-(level, rank) ones:
    # levels [9,1,5,1,14,0,7,3] -> 0 (idx 5), then 1 (idx 1), 1 (idx 3)
    expected_order = [5, 1, 3]
    assert stolen == set(expected_order[: len(stolen)]), (stolen, thief_of)


def test_no_steals_when_balanced():
    rng = np.random.default_rng(1)
    W, T = 8, 64
    batch = StealBatch(
        task_victim=rng.integers(0, W, T).astype(np.int32),
        task_key=make_key(np.zeros(T, np.int64), np.arange(T)),
        task_cost=np.full(T, LATENCY, np.float32),
        task_compute=np.full(T, 0.1, np.float32),
        occ=np.full(W, 0.8, np.float32),  # perfectly balanced
        nthreads=np.ones(W, np.int32),
        idle=np.zeros(W, bool),  # nobody idle
        running=np.ones(W, bool),
    )
    assert (plan_steals(batch) >= 0).sum() == 0


def test_empty_steal_batch():
    batch = StealBatch(
        task_victim=np.zeros(0, np.int32),
        task_key=np.zeros(0, np.int32),
        task_cost=np.zeros(0, np.float32),
        task_compute=np.zeros(0, np.float32),
        occ=np.zeros(4, np.float32),
        nthreads=np.ones(4, np.int32),
        idle=np.ones(4, bool),
        running=np.ones(4, bool),
    )
    assert len(plan_steals(batch)) == 0


# ---------------------------------------------------------------- ops.amm


def test_drops_match_python_policy_invariants():
    """Replaying device drops sequentially must satisfy the python
    oracle: never the last replica, never an excluded holder, always the
    max-projected-memory eligible holder at application time
    (reference active_memory_manager.py:290,527)."""
    rng = np.random.default_rng(2)
    R, W = 60, 12
    holders = rng.random((R, W)) < 0.4
    holders[:, 0] |= ~holders.any(axis=1)  # at least one replica each
    excluded = (rng.random((R, W)) < 0.1) & holders
    nbytes = rng.uniform(1e3, 1e6, R).astype(np.float32)
    desired = np.maximum(1, rng.integers(1, 3, R))
    ndrop = np.maximum(holders.sum(1) - desired, 0).astype(np.int32)
    mem = (holders * nbytes[:, None]).sum(0).astype(np.float32)

    rounds = plan_drop_rounds(DropBatch(holders, excluded, nbytes, ndrop, mem))
    assert rounds, "no drops planned on an over-replicated state"

    h = holders.copy()
    m = mem.astype(np.float64).copy()
    left = ndrop.copy()
    for rnd in rounds:
        m0 = m.copy()  # drops in one round see the round-start projection
        seen_rows = set()
        for r, w in rnd:
            assert r not in seen_rows, "two drops for one task in a round"
            seen_rows.add(r)
            assert h[r, w], "dropped a replica that does not exist"
            assert not excluded[r, w], "dropped from an excluded holder"
            assert h[r].sum() >= 2, "dropped the last replica"
            assert left[r] > 0, "dropped more than requested"
            # max-projected-memory among this task's eligible holders at
            # round start (f32 kernel: allow rounding slack)
            elig = h[r] & ~excluded[r]
            assert m0[w] >= m0[elig].max() - max(1e-5 * m0[elig].max(), 1e-3), (r, w)
            h[r, w] = False
            left[r] -= 1
            m[w] = max(m[w] - nbytes[r], 0.0)
    # every satisfiable requested drop got planned
    planned_by_row = np.zeros(R, int)
    for rnd in rounds:
        for r, _ in rnd:
            planned_by_row[r] += 1
    for r in range(R):
        # bounded by the request, by eligible (non-excluded) holders, and
        # by the never-drop-the-last-replica floor over ALL holders
        satisfiable = max(0, min(
            int(ndrop[r]),
            int((holders[r] & ~excluded[r]).sum()),
            int(holders[r].sum()) - 1,
        ))
        assert planned_by_row[r] == satisfiable, (r, planned_by_row[r], satisfiable)


def test_drop_never_last_replica():
    holders = np.asarray([[True, True, False]])
    excluded = np.zeros((1, 3), bool)
    drops = plan_drops(DropBatch(
        holders, excluded,
        np.asarray([100.0], np.float32),
        np.asarray([5], np.int32),  # asks for more than possible
        np.asarray([100.0, 100.0, 0.0], np.float32),
    ))
    assert len(drops) == 1  # only one can go


def test_empty_drop_batch():
    assert plan_drops(DropBatch(
        np.zeros((0, 4), bool), np.zeros((0, 4), bool),
        np.zeros(0, np.float32), np.zeros(0, np.int32),
        np.zeros(4, np.float32),
    )) == []


# ------------------------------------------------------------- live paths


def _slow(i, delay=0.1):
    import time

    time.sleep(delay)
    return i


@gen_test(timeout=120)
async def test_device_stealing_live():
    """With the fleet gates lowered, a pinned-imbalance workload must be
    rebalanced by the DEVICE balance path (>= 1 device-planned steal)."""
    from distributed_tpu import config
    from distributed_tpu.client.client import Client
    from distributed_tpu.deploy.local import LocalCluster

    with config.set(
        {
            "scheduler.jax.enabled": True,
            "scheduler.jax.min-workers": 0,
            "scheduler.work-stealing-interval": "50ms",
        }
    ):
        async with LocalCluster(n_workers=4, threads_per_worker=1) as cluster:
            steal = cluster.scheduler.extensions["stealing"]
            steal.DEVICE_MIN_TASKS = 1  # tiny cluster: always use device
            async with Client(cluster.scheduler_address) as c:
                await c.submit(_slow, -1, delay=0.1).result()
                w0 = cluster.workers[0].address
                futs = c.map(
                    _slow, range(24), delay=0.1,
                    workers=[w0], allow_other_workers=True,
                )
                assert await asyncio.wait_for(c.gather(futs), 60) == list(
                    range(24)
                )
                assert steal.count >= 1, steal.log
                counts = {
                    w.address: len(w.data) for w in cluster.workers
                }
                assert sum(1 for v in counts.values() if v) >= 2, counts


@gen_test(timeout=120)
async def test_device_amm_drop_live():
    """Broadcast-replicated data beyond demand must be trimmed by the
    DEVICE ReduceReplicas path (>= 1 device-planned drop)."""
    from distributed_tpu import config
    from distributed_tpu.client.client import Client
    from distributed_tpu.deploy.local import LocalCluster
    from distributed_tpu.scheduler.amm import ReduceReplicas

    with config.set(
        {
            "scheduler.jax.enabled": True,
            "scheduler.jax.min-workers": 0,
        }
    ):
        async with LocalCluster(n_workers=4, threads_per_worker=1) as cluster:
            amm = cluster.scheduler.extensions["amm"]
            policy = next(
                p for p in amm.policies if isinstance(p, ReduceReplicas)
            )
            policy.DEVICE_MIN_TASKS = 1
            async with Client(cluster.scheduler_address) as c:
                futs = await c.scatter(list(range(6)), broadcast=True)
                state = cluster.scheduler.state
                # broadcast replication is async (acquire-replicas round
                # trips): wait for the replicas to land
                for _ in range(100):
                    if len(state.replicated_tasks) >= 6:
                        break
                    await asyncio.sleep(0.05)
                assert state.replicated_tasks
                n_before = sum(
                    len(state.tasks[f.key].who_has) for f in futs
                )
                amm.run_once()
                # drops are async worker round-trips; poll for the trim
                for _ in range(100):
                    await asyncio.sleep(0.05)
                    n_now = sum(
                        len(state.tasks[f.key].who_has) for f in futs
                    )
                    if n_now < n_before:
                        break
                else:
                    pytest.fail("device AMM round dropped nothing")
                # data still gatherable after the trim
                assert await c.gather(futs) == list(range(6))


# ---------------------------------------------------------------- rebalance


def _rebalance_setup(seed=0, N=400, W=16):
    rng = np.random.default_rng(seed)
    owner = rng.integers(0, W, N).astype(np.int32)
    nbytes = rng.uniform(1e3, 1e7, N).astype(np.float32)
    eligible = rng.random(N) < 0.9
    # worker memory includes ineligible keys too (ws.nbytes does)
    mem = np.zeros(W, np.float32)
    np.add.at(mem, owner, nbytes)
    # skew: first worker hoards extra
    mem[0] += mem.sum()
    return owner, nbytes, eligible, mem


def test_rebalance_kernel_invariants_and_band():
    from distributed_tpu.ops.rebalance import RebalanceBatch, plan_rebalance

    owner, nbytes, eligible, mem = _rebalance_setup()
    W = len(mem)
    mean = mem.sum() / W
    moves = plan_rebalance(
        RebalanceBatch(owner, nbytes, eligible, mem.copy()), rounds=32
    )
    assert moves, "skewed memory must produce moves"
    proj = mem.copy()
    seen = set()
    imbalance0 = proj.max() - proj.min()
    for key, src, dst in moves:
        assert key not in seen, "key moved twice"
        seen.add(key)
        assert eligible[key]
        assert owner[key] == src
        # python-policy invariants at application point
        assert proj[src] > mean, "sender was not above the mean"
        assert proj[dst] + nbytes[key] <= mean * 1.05 + 1, (
            "recipient pushed past the 1.05 band"
        )
        proj[src] -= nbytes[key]
        proj[dst] += nbytes[key]
    assert proj.max() - proj.min() <= imbalance0, "imbalance grew"
    # the hoarder actually drained toward the band
    assert proj[0] < mem[0]


def test_rebalance_kernel_noop_when_balanced():
    from distributed_tpu.ops.rebalance import RebalanceBatch, plan_rebalance

    rng = np.random.default_rng(1)
    W, N = 8, 160
    owner = np.repeat(np.arange(W), N // W).astype(np.int32)
    nbytes = np.full(N, 1e5, np.float32)
    mem = np.full(W, N // W * 1e5, np.float32)
    moves = plan_rebalance(
        RebalanceBatch(owner, nbytes, np.ones(N, bool), mem), rounds=8
    )
    assert moves == []
