"""Streamed pack+place driver (ops/leveled.place_graph_streamed): the
pipelined fill/upload/dispatch path must produce the same placements as
the one-shot driver, and the compact 11 B/task wire format must keep
placement validity and load quality.

Role model: the reference keeps its scheduler decisions identical under
transport changes (distributed/tests/test_scheduler.py spirit); here the
wire format and upload pipelining are the "transport" of the placement
co-processor.
"""

from __future__ import annotations

import numpy as np
import pytest

from distributed_tpu.ops.leveled import (
    _COST_XMIN,
    _dec_cost,
    _enc_cost,
    _enc_heavy_pair,
    pack_graph,
    place_graph_leveled,
    place_graph_streamed,
    validate_leveled,
)
from distributed_tpu import native

from test_leveled import BW, random_dag, workers


needs_native = pytest.mark.skipif(
    native.load() is None, reason="native toolchain unavailable"
)


# ------------------------------------------------------------ wire format


def test_cost_codec_roundtrip():
    x = np.array(
        [0.0, 1e-7, 1e-6, 1e-4, 3.1e-3, 0.9, 80.0, 9e3, 5e4], np.float32
    )
    dec = np.asarray(_dec_cost(_enc_cost(x)))
    # exact zero survives exactly
    assert dec[0] == 0.0
    # sub-XMIN positives clamp to the smallest nonzero code
    assert dec[1] == pytest.approx(_COST_XMIN, rel=1e-3)
    # in-range values round-trip within the quantization step, including
    # the ~80 s transfers of multi-GB deps (an earlier XMAX=60 saturated
    # exactly those and erased their co-location advantage)
    np.testing.assert_allclose(dec[2:8], x[2:8], rtol=0.06)
    # saturation at the top of the scale
    assert dec[8] == pytest.approx(1e4, rel=0.06)


def test_heavy_pair_codec_roundtrip():
    import jax.numpy as jnp
    from jax import lax

    rng = np.random.default_rng(3)
    h = rng.integers(-1, 2**21 - 2, 10_000).astype(np.int32)
    h2 = rng.integers(-1, 2**21 - 2, 10_000).astype(np.int32)
    lo, hi = _enc_heavy_pair(h, h2)
    assert lo.dtype == np.int32 and hi.dtype == np.uint16
    v = jnp.asarray(lo)
    hhi = jnp.asarray(hi).astype(jnp.int32)
    dh = np.asarray((v & 0x1FFFFF) - 1)
    dh2 = np.asarray(
        ((lax.shift_right_logical(v, 21) & 0x7FF) | (hhi << 11)) - 1
    )
    np.testing.assert_array_equal(dh, h)
    np.testing.assert_array_equal(dh2, h2)


# ------------------------------------------------------- streamed driver


@needs_native
def test_streamed_exact_parity_with_oneshot():
    """compact=False streams the same arrays the one-shot driver uploads:
    same kernel, same wave order, bit-identical placements."""
    rng = np.random.default_rng(11)
    durations, out_bytes, src, dst = random_dag(rng, 40_000)
    nthreads, occ0, running = workers(16)
    packed0 = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    res0 = place_graph_leveled(packed0, nthreads, occ0, running)
    packed1, res1 = place_graph_streamed(
        durations, out_bytes, src, dst, nthreads, occ0, running,
        bandwidth=BW, compact=False, chunk_rows=7_000, min_stream=1,
    )
    assert packed1.n_levels == packed0.n_levels
    np.testing.assert_array_equal(packed1.perm, packed0.perm)
    np.testing.assert_array_equal(packed1.heavy_s, packed0.heavy_s)
    np.testing.assert_allclose(
        packed1.xfer_all_s, packed0.xfer_all_s, rtol=1e-6
    )
    np.testing.assert_array_equal(res1.assignment, res0.assignment)
    np.testing.assert_array_equal(res1.choice, res0.choice)
    np.testing.assert_allclose(res1.occupancy, res0.occupancy, rtol=1e-5)


@needs_native
def test_streamed_compact_valid_and_balanced():
    """The 11 B/task wire format may flip near-tie argmins but must keep
    validity and load quality."""
    rng = np.random.default_rng(12)
    durations, out_bytes, src, dst = random_dag(rng, 60_000)
    nthreads, occ0, running = workers(32)
    packed0 = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    res0 = place_graph_leveled(packed0, nthreads, occ0, running)
    packed2, res2 = place_graph_streamed(
        durations, out_bytes, src, dst, nthreads, occ0, running,
        bandwidth=BW, compact=True, chunk_rows=9_000, min_stream=1,
    )
    validate_leveled(packed2, res2, src, dst, running)
    W = len(nthreads)
    c0 = np.bincount(res0.assignment, minlength=W)
    c2 = np.bincount(res2.assignment, minlength=W)
    assert c2.max() / c2.mean() < c0.max() / c0.mean() * 1.15 + 0.05
    # quantization flips only near-ties: the vast majority agrees
    assert (res2.assignment == res0.assignment).mean() > 0.5


@needs_native
def test_streamed_auto_compact_is_exact_on_cpu():
    """compact="auto" (the default) disables the lossy wire format on the
    cpu backend, so the chunked pack/upload overlap is byte-identical to
    the unchunked path there."""
    import jax

    if jax.default_backend() != "cpu":
        pytest.skip("auto resolves to packed on accelerator backends")
    rng = np.random.default_rng(21)
    durations, out_bytes, src, dst = random_dag(rng, 20_000)
    nthreads, occ0, running = workers(8)
    packed0 = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    res0 = place_graph_leveled(packed0, nthreads, occ0, running)
    tm: dict = {}
    _, res1 = place_graph_streamed(
        durations, out_bytes, src, dst, nthreads, occ0, running,
        bandwidth=BW, chunk_rows=6_000, min_stream=1, timings=tm,
    )
    assert tm["fmt"] == "f16"
    np.testing.assert_array_equal(res1.assignment, res0.assignment)
    np.testing.assert_array_equal(res1.choice, res0.choice)


@needs_native
def test_fused_topo_parity_with_numpy_pack_threaded():
    """The fused (and, above 2^18 edges, two-threaded) native topo pass
    must agree with the pure-numpy oracle on every output the placement
    consumes — including the threaded branch."""
    rng = np.random.default_rng(22)
    T = 140_000
    durations, out_bytes, src, dst = random_dag(rng, T, max_deps=4)
    assert len(src) >= (1 << 18), "graph too small to exercise the threads"
    native_pack = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)

    import distributed_tpu.native as native_mod

    real_load = native_mod.load
    try:
        native_mod.load = lambda: None
        numpy_pack = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    finally:
        native_mod.load = real_load
    assert native_pack.n_levels == numpy_pack.n_levels
    np.testing.assert_array_equal(native_pack.level, numpy_pack.level)
    np.testing.assert_array_equal(native_pack.perm, numpy_pack.perm)
    np.testing.assert_array_equal(native_pack.offsets, numpy_pack.offsets)
    np.testing.assert_array_equal(native_pack.heavy_s, numpy_pack.heavy_s)
    np.testing.assert_array_equal(native_pack.heavy2_s, numpy_pack.heavy2_s)
    np.testing.assert_allclose(
        native_pack.xfer_pref_s, numpy_pack.xfer_pref_s, rtol=1e-5
    )
    np.testing.assert_allclose(
        native_pack.xfer_all_s, numpy_pack.xfer_all_s, rtol=1e-5
    )


@needs_native
def test_streamed_respects_stopped_workers():
    rng = np.random.default_rng(13)
    durations, out_bytes, src, dst = random_dag(rng, 30_000)
    nthreads, occ0, running = workers(8, stopped=(2, 5))
    _, res = place_graph_streamed(
        durations, out_bytes, src, dst, nthreads, occ0, running,
        bandwidth=BW, chunk_rows=8_000, min_stream=1,
    )
    assert (res.assignment >= 0).all()
    assert running[res.assignment].all()


@needs_native
def test_streamed_chunk_geometry_edge_cases():
    """Chunk > T, chunk == T, T slightly over a power of two, and a
    last-chunk clamp that re-sends overlap rows."""
    nthreads, occ0, running = workers(4)
    for n, chunk in [(1025, 4096), (2048, 2048), (4099, 1000), (513, 512)]:
        rng = np.random.default_rng(n)
        durations, out_bytes, src, dst = random_dag(rng, n)
        packed0 = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
        res0 = place_graph_leveled(packed0, nthreads, occ0, running)
        _, res1 = place_graph_streamed(
            durations, out_bytes, src, dst, nthreads, occ0, running,
            bandwidth=BW, compact=False, chunk_rows=chunk, min_stream=1,
        )
        np.testing.assert_array_equal(res1.assignment, res0.assignment)


def test_streamed_fallback_below_threshold():
    """Below min_stream (or without the native lib) the driver delegates
    to pack+place — same results, no thread."""
    rng = np.random.default_rng(14)
    durations, out_bytes, src, dst = random_dag(rng, 2_000)
    nthreads, occ0, running = workers(4)
    packed0 = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    res0 = place_graph_leveled(packed0, nthreads, occ0, running)
    _, res1 = place_graph_streamed(
        durations, out_bytes, src, dst, nthreads, occ0, running,
        bandwidth=BW, min_stream=1_000_000,
    )
    np.testing.assert_array_equal(res1.assignment, res0.assignment)


@needs_native
def test_streamed_sharded_1x1_is_identity_refactor():
    """The streamed driver's SHARDED branch on a 1x1 mesh must be
    bit-identical to the single-device streamed driver (compact=False):
    same topo, same waves, same kernel math — the mesh is pure
    transport there (ops/leveled sharded engine; tests/
    test_sharded_engine.py covers multi-device meshes)."""
    from distributed_tpu.ops.partition import make_engine_mesh

    rng = np.random.default_rng(31)
    durations, out_bytes, src, dst = random_dag(rng, 30_000)
    nthreads, occ0, running = workers(16)
    _, res0 = place_graph_streamed(
        durations, out_bytes, src, dst, nthreads, occ0, running,
        bandwidth=BW, compact=False, chunk_rows=7_000, min_stream=1,
    )
    mesh = make_engine_mesh(layout="1x1")
    tm: dict = {}
    _, res1 = place_graph_streamed(
        durations, out_bytes, src, dst, nthreads, occ0, running,
        bandwidth=BW, chunk_rows=7_000, min_stream=1, mesh=mesh,
        timings=tm,
    )
    assert tm["fmt"] == "f16"
    np.testing.assert_array_equal(res1.assignment, res0.assignment)
    np.testing.assert_array_equal(res1.choice, res0.choice)
    np.testing.assert_array_equal(res1.occupancy, res0.occupancy)


@needs_native
def test_streamed_cycle_raises():
    src = np.array([0, 1, 2], np.int32)
    dst = np.array([1, 2, 0], np.int32)
    ones = np.ones(3, np.float32)
    nthreads, occ0, running = workers(2)
    with pytest.raises(ValueError, match="cycle"):
        place_graph_streamed(
            ones, ones, src, dst, nthreads, occ0, running, min_stream=1
        )
