"""Actor tests (reference test_actor.py patterns)."""

from __future__ import annotations

import asyncio

import pytest

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.local import LocalCluster

from conftest import gen_test


class Counter:
    def __init__(self, start=0):
        self.n = start

    def increment(self, by=1):
        self.n += by
        return self.n

    def value(self):
        return self.n


async def new_cluster(n_workers=2, **kwargs):
    cluster = LocalCluster(
        n_workers=n_workers,
        scheduler_kwargs={"validate": True},
        worker_kwargs={"validate": True},
        **kwargs,
    )
    await cluster._start()
    return cluster


@gen_test()
async def test_actor_basic():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            fut = c.submit(Counter, actor=True)
            counter = await fut.result()
            assert await counter.increment() == 1
            assert await counter.increment(by=10) == 11
            assert await counter.value() == 11
            # plain attribute access
            assert await counter.n == 11


@gen_test()
async def test_actor_state_is_pinned():
    """All calls hit the same instance on the same worker."""
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            fut = c.submit(Counter, 100, actor=True)
            counter = await fut.result()
            for _ in range(5):
                await counter.increment()
            assert await counter.value() == 105
            # exactly one worker hosts the instance
            hosts = [w for w in cluster.workers if w.state.actors]
            assert len(hosts) == 1


@gen_test()
async def test_actor_method_error():
    class Bad:
        def boom(self):
            raise RuntimeError("actor-boom")

    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            fut = c.submit(Bad, actor=True)  # hold: actor lives with future
            actor = await fut.result()
            with pytest.raises(RuntimeError, match="actor-boom"):
                await actor.boom()


@gen_test()
async def test_two_actors_independent():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            fa = c.submit(Counter, 0, actor=True, key="actor-a")
            fb = c.submit(Counter, 50, actor=True, key="actor-b")
            a = await fa.result()
            b = await fb.result()
            await a.increment()
            await b.increment()
            assert await a.value() == 1
            assert await b.value() == 51


@gen_test(timeout=120)
async def test_actor_futures_and_as_completed():
    """ActorFuture surface (reference actor.py BaseActorFuture): method
    calls return futures with done()/add_done_callback, awaitable, and
    usable in as_completed next to task futures."""
    from distributed_tpu.client.client import as_completed

    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            fut = c.submit(Counter, actor=True)
            counter = await fut.result()
            af = counter.increment()
            from distributed_tpu.client.actor import ActorFuture

            assert isinstance(af, ActorFuture)
            fired = []
            af.add_done_callback(lambda t: fired.append(True))
            assert await af == 1
            assert af.done()
            await asyncio.sleep(0)  # let the callback run
            assert fired == [True]

            # mixed as_completed: one task future + two actor futures
            tfut = c.submit(lambda: 41, pure=False)
            acs = as_completed([counter.increment(), tfut,
                                counter.increment()], with_results=True)
            got = []
            async for f, result in acs:
                got.append(result)
            assert len(got) == 3
            assert 41 in got          # the task future's result
            assert {2, 3} <= set(got)  # the two increments
            assert await counter.value() == 3
