"""Test harness configuration.

Tests run JAX on a virtual 8-device CPU mesh (multi-chip shardings are
validated without TPU hardware, like the reference validates multi-node
behavior with in-process clusters, utils_test.py:865).  Must run before any
jax import.
"""

import os

# Hard override: this box pins JAX_PLATFORMS=axon (the real TPU) and a
# sitecustomize.py imports jax in every process, so env vars are too late —
# use jax.config.update, which works as long as no backend is initialized
# yet.  Tests run on a virtual 8-device CPU mesh (jax_num_cpu_devices is the
# supported mechanism on jax 0.9; the XLA_FLAGS host-device-count is ignored).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")
# jax < 0.5 has no jax_num_cpu_devices option; there the XLA flag is the
# only mechanism and IS honored (it became a no-op later).  Set both.
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # jax < 0.5
    pass

import asyncio  # noqa: E402
import functools  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def gen_test(timeout: float = 120):
    """Run an async test on a fresh event loop (reference utils_test.py:708)."""

    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            async def run():
                return await asyncio.wait_for(fn(*args, **kwargs), timeout)

            return asyncio.run(run())

        return wrapper

    return decorator



# ------------------------------------------------ hashseed sweep harness
#
# Cross-process determinism (docs/determinism.md) is proven empirically
# by re-running the same work in subprocesses under several
# PYTHONHASHSEEDs and demanding bit-identical results.  Every hashseed
# test in the suite goes through these two helpers so the seed list and
# the failure report stay uniform.

import subprocess  # noqa: E402
import sys  # noqa: E402

#: the default sweep: three seeds, none of them the hash-randomization
#: default, chosen to have caught real bugs historically (1 and 6/7)
HASHSEEDS = ("1", "7", "13")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def sweep_hashseed_pytest(node: str, seeds=HASHSEEDS, timeout: float = 240):
    """Run one pytest node in a subprocess per hash seed; each must pass.

    For scenario tests that assert their own determinism internally
    (digest equality between twin runs) — the sweep proves the property
    holds whatever allocation/hash layout the interpreter starts with.
    """
    for seed in seeds:
        env = dict(os.environ, PYTHONHASHSEED=str(seed),
                   JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "pytest", node, "-q",
             "-p", "no:randomly", "-p", "no:cacheprovider"],
            capture_output=True, timeout=timeout, env=env, cwd=_REPO_ROOT,
        )
        assert r.returncode == 0, (
            f"PYTHONHASHSEED={seed}: " + r.stdout.decode()[-1500:]
        )


def sweep_hashseed_stdout(code: str, seeds=HASHSEEDS,
                          timeout: float = 240) -> str:
    """Run ``python -c code`` once per hash seed; stdout must be
    bit-identical across the sweep.  Returns the common output so the
    caller can pin further expectations on it."""
    outs: dict[str, str] = {}
    for seed in seeds:
        env = dict(os.environ, PYTHONHASHSEED=str(seed),
                   JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            timeout=timeout, env=env, cwd=_REPO_ROOT,
        )
        assert r.returncode == 0, (
            f"PYTHONHASHSEED={seed}: " + r.stderr.decode()[-1500:]
        )
        outs[seed] = r.stdout.decode()
    distinct = set(outs.values())
    assert len(distinct) == 1, (
        "output diverged across hash seeds:\n"
        + "\n".join(f"--- seed {s} ---\n{o}" for s, o in outs.items())
    )
    return outs[next(iter(outs))]
