"""Test harness configuration.

Tests run JAX on a virtual 8-device CPU mesh (multi-chip shardings are
validated without TPU hardware, like the reference validates multi-node
behavior with in-process clusters, utils_test.py:865).  Must run before any
jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import asyncio  # noqa: E402
import functools  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def gen_test(timeout: float = 60):
    """Run an async test on a fresh event loop (reference utils_test.py:708)."""

    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            async def run():
                return await asyncio.wait_for(fn(*args, **kwargs), timeout)

            return asyncio.run(run())

        return wrapper

    return decorator


