"""Test harness configuration.

Tests run JAX on a virtual 8-device CPU mesh (multi-chip shardings are
validated without TPU hardware, like the reference validates multi-node
behavior with in-process clusters, utils_test.py:865).  Must run before any
jax import.
"""

import os

# Hard override: this box pins JAX_PLATFORMS=axon (the real TPU) and a
# sitecustomize.py imports jax in every process, so env vars are too late —
# use jax.config.update, which works as long as no backend is initialized
# yet.  Tests run on a virtual 8-device CPU mesh (jax_num_cpu_devices is the
# supported mechanism on jax 0.9; the XLA_FLAGS host-device-count is ignored).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")
# jax < 0.5 has no jax_num_cpu_devices option; there the XLA flag is the
# only mechanism and IS honored (it became a no-op later).  Set both.
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # jax < 0.5
    pass

import asyncio  # noqa: E402
import functools  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


def gen_test(timeout: float = 120):
    """Run an async test on a fresh event loop (reference utils_test.py:708)."""

    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            async def run():
                return await asyncio.wait_for(fn(*args, **kwargs), timeout)

            return asyncio.run(run())

        return wrapper

    return decorator


