"""Cross-process determinism sweep (docs/determinism.md).

The control plane promises that every decision, digest, and journal
surface is a pure function of the stimulus stream — independent of
PYTHONHASHSEED and allocation order.  The static half of that proof is
the graft-lint ``determinism`` rule (tests/test_analysis.py); this file
is the empirical half: the same seeded simulation run in fresh
subprocesses under several hash seeds must produce bit-identical
transition digests, stimulus journals, and ledger digests, on the
oracle AND the native engine.
"""

from __future__ import annotations

import pytest
from conftest import sweep_hashseed_pytest, sweep_hashseed_stdout


def _fingerprint_code(native: bool) -> str:
    """A self-contained script printing every determinism surface of
    one seeded sim run: transition digest, journal hash, ledger digest,
    and makespan.  Any hash-seed dependence anywhere in the decision
    path shows up as a diff in at least one line."""
    if native:
        ctor = ("ClusterSim(8, seed=0, validate=False, native=True,\n"
                "           config_overrides="
                "{'scheduler.native-engine.min-flood': 0})")
        guard = "assert sim.state.native is not None, 'native never attached'"
    else:
        ctor = "ClusterSim(8, seed=0, validate=True)"
        guard = ""
    return f"""\
import hashlib, json
from distributed_tpu.sim import ClusterSim, SyntheticDag

sim = {ctor}
{guard}
sim.install_digest()
sim.journal_start()
SyntheticDag(seed=0, n_layers=6, layer_width=16, fanin=2).start(sim)
sim.run()
journal = json.dumps(sim.journal(), sort_keys=True).encode()
print("transition-digest", sim.digest())
print("journal-blake2b",
      hashlib.blake2b(journal, digest_size=8).hexdigest())
print("ledger-digest", sim.state.ledger.digest())
print("makespan", sim.clock.now)
"""


def test_oracle_fingerprint_identical_across_hashseeds():
    out = sweep_hashseed_stdout(_fingerprint_code(native=False))
    # sanity: all four surfaces actually printed
    for label in ("transition-digest", "journal-blake2b",
                  "ledger-digest", "makespan"):
        assert label in out, out


def test_native_fingerprint_identical_across_hashseeds():
    from distributed_tpu import native

    if native.load() is None:
        pytest.skip("native toolchain unavailable")
    out_native = sweep_hashseed_stdout(_fingerprint_code(native=True))
    # engine parity is part of the contract: the native tape replays
    # the oracle's exact decision sequence, so the whole fingerprint —
    # not just the digest line — must match the oracle's
    out_oracle = sweep_hashseed_stdout(
        _fingerprint_code(native=False), seeds=("1",)
    )
    assert out_native == out_oracle


def test_bounce_scenario_across_hashseeds():
    """The PR 13-era repro, now on the shared harness: the scheduler
    bounce proof (snapshot + journal-tail restart digesting identically
    to the unbounced twin) under the standard seed sweep.  Seeds 6/8
    caught the original plain-set ``stealable``/``saturated`` bug, so
    they ride along with the defaults."""
    sweep_hashseed_pytest(
        "tests/test_durability.py::test_scenario_scheduler_bounce_oracle",
        seeds=("1", "6", "8"),
    )


def test_partition_chaos_across_hashseeds():
    """The PR 14-era repro on the shared harness: partition chaos with
    in-flight executes completing for released tasks — seeds 1/6 used
    to crash ``(released, memory)`` before the worker relations went
    insertion-ordered."""
    sweep_hashseed_pytest(
        "tests/test_sim.py::test_chaos_partition", seeds=("1", "6", "13")
    )
