"""Native C++ t-digest tests (the crick-equivalent, reference counter.py)."""

from __future__ import annotations

import numpy as np
import pytest

from distributed_tpu import native
from distributed_tpu.utils.counter import Counter, Digest


def test_native_library_builds():
    lib = native.load()
    assert lib is not None, "g++ is available here; the native build must work"


def test_digest_quantiles_accurate():
    d = Digest()
    assert d.native
    rng = np.random.default_rng(0)
    samples = rng.normal(100.0, 15.0, 50_000)
    d.add_batch(samples)
    assert d.count() == 50_000
    for q in (0.01, 0.25, 0.5, 0.75, 0.99):
        exact = float(np.quantile(samples, q))
        est = d.quantile(q)
        # t-digest is tight at the tails and center
        assert abs(est - exact) < 1.0, (q, est, exact)
    assert d.min() == samples.min()
    assert d.max() == samples.max()


def test_digest_serialize_merge():
    rng = np.random.default_rng(1)
    a, b = Digest(), Digest()
    xs = rng.uniform(0, 100, 10_000)
    ys = rng.uniform(100, 200, 10_000)
    a.add_batch(xs)
    b.add_batch(ys)
    merged = Digest()
    merged.merge_serialized(a.serialize())
    merged.merge_serialized(b.serialize())
    all_samples = np.concatenate([xs, ys])
    est = merged.quantile(0.5)
    exact = float(np.quantile(all_samples, 0.5))
    assert abs(est - exact) < 3.0, (est, exact)


def test_digest_weighted_add():
    d = Digest()
    d.add(10.0, weight=3)
    d.add(20.0, weight=1)
    assert d.count() == 4
    assert d.quantile(0.25) <= 15


def test_counter():
    c = Counter()
    c.update(["a", "b", "a", "a"])
    assert c.most_common(1) == [("a", 3)]
    assert c.n == 4


def test_server_digest_metric_uses_tdigest():
    from distributed_tpu.rpc.core import Server

    s = Server()
    for v in (0.1, 0.2, 0.3, 0.4):
        s.digest_metric("latency", v)
    assert abs(s.digests["latency"] - 1.0) < 1e-9  # cumulative total
    sketch = s.digests_tdigest["latency"]
    assert sketch.count() == 4
    assert 0.1 <= sketch.quantile(0.5) <= 0.4


def test_digest_buffered_add_flush_on_read():
    """add() buffers samples (no per-sample FFI); any read flushes."""
    from distributed_tpu.utils.counter import Digest

    d = Digest(block_on_build=True)
    for i in range(100):
        d.add(float(i))
    assert d.count() == 100
    assert d.min() == 0.0 and d.max() == 99.0
    d.add(5.0, weight=3.0)  # weighted path flushes + direct FFI
    assert d.count() == 103


def test_digest_concurrent_add_and_read():
    """Executor threads add while a reader flushes: no sample lost or
    double-counted (the flush swap + FFI run under a lock)."""
    import threading

    from distributed_tpu.utils.counter import Digest

    d = Digest(block_on_build=True)
    N, T = 20_000, 4
    def adder():
        for i in range(N):
            d.add(float(i % 100))
    threads = [threading.Thread(target=adder) for _ in range(T)]
    for t in threads:
        t.start()
    # concurrent reads force racing flushes
    for _ in range(50):
        d.count()
    for t in threads:
        t.join()
    assert d.count() == N * T
