"""Device-resident shuffle: shard movement over mesh collectives with
ZERO host-serialized shard bytes (the TPU-native analogue of reference
comm/ucx.py:211's device frames)."""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from distributed_tpu.client.client import Client, wait as wait_futures
from distributed_tpu.deploy.local import LocalCluster
from distributed_tpu.shuffle import p2p_shuffle_device

from conftest import gen_test

N_DEV = 8


def _mix32_np(x: np.ndarray) -> np.ndarray:
    z = x.astype(np.uint32)
    z ^= z >> np.uint32(16)
    z = (z * np.uint32(0x85EBCA6B)) & np.uint32(0xFFFFFFFF)
    z ^= z >> np.uint32(13)
    z = (z * np.uint32(0xC2B2AE35)) & np.uint32(0xFFFFFFFF)
    z ^= z >> np.uint32(16)
    return z


def make_device_part(i, n):
    """(keys, values) jax arrays resident on mesh device i."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(i)
    keys = rng.integers(0, 1 << 30, n).astype(np.int32)
    values = np.stack([keys.astype(np.float32), np.full(n, i, np.float32)], 1)
    dev = jax.devices()[i]
    return (
        jax.device_put(jnp.asarray(keys), dev),
        jax.device_put(jnp.asarray(values), dev),
    )


async def new_cluster(n_workers=N_DEV):
    cluster = LocalCluster(
        n_workers=n_workers,
        scheduler_kwargs={"validate": True},
        worker_kwargs={"validate": True},
    )
    await cluster._start()
    return cluster


@gen_test(timeout=180)
async def test_device_shuffle_zero_host_shard_bytes():
    """E2E on the virtual 8-device mesh: rows land on the device their
    key hashes to, while the host shard plane (shuffle_receive pushes,
    jax serialization) moves ZERO bytes."""
    import jax

    assert len(jax.devices()) >= N_DEV
    import importlib

    ser = importlib.import_module("distributed_tpu.protocol.serialize")
    from distributed_tpu.shuffle.core import ShuffleRun

    sends = []
    orig_send = ShuffleRun._send_to_peer

    async def counting_send(self, addr, shards):
        sends.append((addr, shards))
        return await orig_send(self, addr, shards)

    jax_dumps = []
    orig_jax = ser.families["jax"]

    def counting_jax_dumps(x):
        jax_dumps.append(type(x))
        return orig_jax[0](x)

    ShuffleRun._send_to_peer = counting_send
    ser.families["jax"] = (counting_jax_dumps, orig_jax[1])
    try:
        async with await new_cluster() as cluster:
            async with Client(cluster.scheduler_address) as c:
                n_rows = 400
                inputs = [
                    c.submit(make_device_part, i, n_rows, key=f"dpart-{i}")
                    for i in range(N_DEV)
                ]
                await c.gather(inputs)  # materialize on workers
                # reset: the input gather above serializes legitimately
                jax_dumps.clear()
                outs = await p2p_shuffle_device(c, inputs)
                # wait for the pipeline to finish WITHOUT gathering
                # (gather would serialize results to the client)
                await asyncio.wait_for(wait_futures(outs), 120)
                assert not sends, "host shard pushes must not happen"
                assert not jax_dumps, (
                    "no jax array may be serialized during a device "
                    f"shuffle; saw {jax_dumps[:5]}"
                )
                # NOW check correctness (client hop serializes, fine)
                results = await c.gather(outs)
        all_keys = np.concatenate(
            [np.asarray(make_device_part(i, n_rows)[0]) for i in range(N_DEV)]
        )
        want_per_dev = {
            d: sorted(all_keys[_mix32_np(all_keys) % N_DEV == d].tolist())
            for d in range(N_DEV)
        }
        got_total = 0
        for d, (ko, vo) in enumerate(results):
            ko = np.asarray(ko)
            vo = np.asarray(vo)
            assert sorted(ko.tolist()) == want_per_dev[d], f"device {d}"
            # values rode along with their keys
            np.testing.assert_array_equal(vo[:, 0], ko.astype(np.float32))
            got_total += len(ko)
        assert got_total == N_DEV * n_rows
    finally:
        ShuffleRun._send_to_peer = orig_send
        ser.families["jax"] = orig_jax


@gen_test(timeout=120)
async def test_device_shuffle_outputs_live_on_their_mesh_device():
    """Output partition d must be RESIDENT on mesh device d — the point
    of the device plane is that unpacked shards never left the mesh."""
    import jax

    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            inputs = [
                c.submit(make_device_part, i, 64, key=f"dres-{i}")
                for i in range(N_DEV)
            ]
            await c.gather(inputs)
            outs = await p2p_shuffle_device(c, inputs)
            await asyncio.wait_for(wait_futures(outs), 90)

            # residency is asserted ON the workers (gathering to the
            # client would serialize): a follow-up task reads its input
            # partition's device in place
            def check_dev(part, d):
                import jax as _jax

                ko, _vo = part
                (dev,) = ko.devices()
                return dev == _jax.devices()[d]

            checks = [
                c.submit(check_dev, outs[d], d, key=f"chk-{d}")
                for d in range(N_DEV)
            ]
            assert all(await c.gather(checks))
            # and the store released the run once every output was served
            from distributed_tpu.shuffle.device import device_store

            sid = outs[0].key.rsplit("-unpack-", 1)[0]
            assert not any(k[0] == sid for k in device_store().runs)


def test_ici_valid_mask_drops_padding():
    """Ragged partitions pad to a common length; padded rows must not
    appear in any output block."""
    import jax

    from distributed_tpu.ops.ici import (
        compact_shuffle_output,
        make_mesh_1d,
        shuffle_on_mesh,
    )

    n_dev = min(8, len(jax.devices()))
    mesh = make_mesh_1d(n_dev)
    rng = np.random.default_rng(3)
    n_local = 32
    keys = rng.integers(0, 1 << 30, n_dev * n_local).astype(np.int32)
    vals = rng.random((n_dev * n_local, 3)).astype(np.float32)
    valid = np.ones(n_dev * n_local, bool)
    # mask out a ragged tail on each device's shard
    for d in range(n_dev):
        valid[d * n_local + n_local - d - 1 : (d + 1) * n_local] = False
    ko, vo, counts, _ = shuffle_on_mesh(
        mesh, keys, vals, capacity=n_local * n_dev, valid=valid
    )
    parts = compact_shuffle_output(ko, vo, counts, n_dev)
    got = np.concatenate([k for k, _ in parts])
    want = keys[valid]
    assert sorted(got.tolist()) == sorted(want.tolist())
    assert len(got) == valid.sum()
