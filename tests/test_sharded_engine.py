"""Sharded leveled engine (ops/leveled.place_graph_leveled_sharded):
lockstep parity with the single-device engine across device meshes.

The 1x1 mesh case is the load-bearing one: there the collectives are
identities and the kernel must compute the same floating-point
expressions in the same order as ``_place_run`` — bit-identical
assignments, choices, occupancy and start times prove the sharded path
is the identity refactor.  Multi-device meshes re-associate the wave
load ``psum``, which can flip exact float ties, so those assert
validity, near-total agreement and matching load totals instead.

Role model: the reference keeps scheduler decisions identical under
transport changes; here the mesh partitioning is the "transport" of the
placement co-processor (same contract style as
tests/test_leveled_streamed.py).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from distributed_tpu import config
from distributed_tpu.ops.leveled import (
    pack_graph,
    place_graph_leveled,
    place_graph_leveled_sharded,
    place_graph_streamed,
    validate_leveled,
)
from distributed_tpu.ops.partition import make_engine_mesh, shard_bucket
from distributed_tpu import native

from test_leveled import BW, random_dag, workers

MESH_LAYOUTS = ["1x1", "2x1", "4x2", "8x1"]

needs_native = pytest.mark.skipif(
    native.load() is None, reason="native toolchain unavailable"
)


def _needed(layout: str) -> int:
    dt, dw = (int(p) for p in layout.split("x"))
    return dt * dw


def _mesh_or_skip(layout: str):
    if len(jax.devices()) < _needed(layout):
        pytest.skip(f"mesh {layout} needs {_needed(layout)} devices")
    return make_engine_mesh(layout=layout)


# ------------------------------------------------------------- parity


@pytest.mark.parametrize("layout", MESH_LAYOUTS)
@pytest.mark.parametrize("seed,T,W", [(0, 3000, 16), (1, 12_000, 64)])
def test_lockstep_parity_randomized(layout, seed, T, W):
    """Randomized graphs + non-uniform fleets (mixed occupancy, stopped
    workers) against every mesh shape; 1x1 must be bit-identical."""
    mesh = _mesh_or_skip(layout)
    rng = np.random.default_rng(seed)
    durations, out_bytes, src, dst = random_dag(rng, T)
    nthreads, occ0, running = workers(W, stopped=(2,) if W > 8 else ())
    occ0 = rng.uniform(0, 2.0, W).astype(np.float32)
    packed = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    res = place_graph_leveled(packed, nthreads, occ0, running)
    res_sh = place_graph_leveled_sharded(
        mesh, packed, nthreads, occ0, running
    )
    assert (res_sh.assignment >= 0).all()
    assert running[res_sh.assignment].all()
    if layout == "1x1":
        np.testing.assert_array_equal(res_sh.assignment, res.assignment)
        np.testing.assert_array_equal(res_sh.choice, res.choice)
        np.testing.assert_array_equal(res_sh.occupancy, res.occupancy)
        np.testing.assert_array_equal(res_sh.start_time, res.start_time)
    else:
        agree = (res_sh.assignment == res.assignment).mean()
        assert agree > 0.97, agree
        np.testing.assert_allclose(
            res_sh.occupancy, res.occupancy, rtol=1e-4, atol=1e-4
        )
        np.testing.assert_allclose(
            res_sh.start_time, res.start_time, rtol=1e-3, atol=1e-3
        )


@pytest.mark.parametrize("layout", ["1x1", "4x2"])
def test_uniform_fleet_takes_uniform_kernel_path(layout):
    """A homogeneous idle fleet routes both engines through their
    ``uniform`` fast path; parity must hold there too (the scalar
    queue-cost specialization changes the fp expression tree)."""
    mesh = _mesh_or_skip(layout)
    rng = np.random.default_rng(3)
    durations, out_bytes, src, dst = random_dag(rng, 5_000)
    nthreads, occ0, running = workers(32)
    packed = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    res = place_graph_leveled(packed, nthreads, occ0, running)
    res_sh = place_graph_leveled_sharded(
        mesh, packed, nthreads, occ0, running
    )
    if layout == "1x1":
        np.testing.assert_array_equal(res_sh.assignment, res.assignment)
        np.testing.assert_array_equal(res_sh.choice, res.choice)
    else:
        assert (res_sh.assignment == res.assignment).mean() > 0.97


@needs_native
def test_streamed_sharded_matches_oneshot_sharded():
    """The streamed driver's sharded branch (per-run tiles assembled
    while the pack fill is still running) must equal the one-shot
    sharded engine — the overlap is transport, not semantics."""
    mesh = _mesh_or_skip("4x2")
    rng = np.random.default_rng(11)
    durations, out_bytes, src, dst = random_dag(rng, 40_000)
    nthreads, occ0, running = workers(16)
    packed = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    res_one = place_graph_leveled_sharded(
        mesh, packed, nthreads, occ0, running
    )
    tm: dict = {}
    stats: dict = {}
    packed2, res_str = place_graph_streamed(
        durations, out_bytes, src, dst, nthreads, occ0, running,
        bandwidth=BW, chunk_rows=7_000, min_stream=1, mesh=mesh,
        timings=tm, stats=stats,
    )
    assert tm["fmt"] == "f16"  # sharded wire is always exact
    np.testing.assert_array_equal(res_str.assignment, res_one.assignment)
    np.testing.assert_array_equal(res_str.choice, res_one.choice)
    validate_leveled(packed2, res_str, src, dst, running)
    # per-shard H2D accounting: every shard shipped the same tile bytes
    assert stats["n_shards"] == 8
    bytes_per_shard = {row["h2d_bytes"] for row in stats["shards"]}
    assert len(bytes_per_shard) == 1 and bytes_per_shard.pop() > 0


def test_streamed_sharded_fallback_below_threshold():
    """Below min_stream the mesh path delegates to pack + one-shot
    sharded place — same results, no fill thread."""
    mesh = _mesh_or_skip("2x1")
    rng = np.random.default_rng(14)
    durations, out_bytes, src, dst = random_dag(rng, 2_000)
    nthreads, occ0, running = workers(8)
    packed = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    res0 = place_graph_leveled_sharded(mesh, packed, nthreads, occ0,
                                       running)
    _, res1 = place_graph_streamed(
        durations, out_bytes, src, dst, nthreads, occ0, running,
        bandwidth=BW, min_stream=1_000_000, mesh=mesh,
    )
    np.testing.assert_array_equal(res1.assignment, res0.assignment)


def test_stopped_workers_never_assigned_on_mesh():
    mesh = _mesh_or_skip("4x2")
    rng = np.random.default_rng(13)
    durations, out_bytes, src, dst = random_dag(rng, 6_000)
    nthreads, occ0, running = workers(16, stopped=(2, 5, 11))
    packed = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)
    res = place_graph_leveled_sharded(mesh, packed, nthreads, occ0,
                                      running)
    assert (res.assignment >= 0).all()
    assert running[res.assignment].all()


def test_shard_bucket_geometry():
    assert shard_bucket(0, 8, floor=512) == 512
    assert shard_bucket(4096, 8, floor=512) == 512
    assert shard_bucket(4097, 8, floor=512) == 1024
    assert shard_bucket(4096, 1, floor=512) == 4096
    # never below one lane per shard even for degenerate floors
    assert shard_bucket(5, 8, floor=1) == 1


# ----------------------------------------------- mirror-resident fleet


def test_mirror_fleet_dev_path_matches_host_upload():
    """The engine fed the mirror's workers-axis device shards must place
    identically to the same engine fed replicated host arrays — and a
    fresh second cycle must ship zero fleet rows on every shard."""
    from distributed_tpu.scheduler.state import SchedulerState

    mesh = _mesh_or_skip("4x2")
    state = SchedulerState()
    assert state.mirror is not None
    W = 32
    for i in range(W):
        state.add_worker_state(f"tcp://se:{i}", nthreads=2,
                               memory_limit=2**30, name=f"w{i}")
    fv = state.mirror.fleet_view()
    nthreads = fv.nthreads.copy()
    occ0 = fv.occupancy.copy()
    running = fv.running.copy()
    rng = np.random.default_rng(21)
    durations, out_bytes, src, dst = random_dag(rng, 4_000)
    packed = pack_graph(durations, out_bytes, src, dst, bandwidth=BW)

    res_host = place_graph_leveled_sharded(
        mesh, packed, nthreads, occ0, running
    )
    fleet_dev = state.mirror.sharded_device_view(mesh)
    assert fleet_dev is not None
    res_dev = place_graph_leveled_sharded(
        mesh, packed, nthreads, occ0, running, fleet_dev=fleet_dev
    )
    np.testing.assert_array_equal(res_dev.assignment, res_host.assignment)

    before = state.mirror.sharded_stats()
    res_dev2 = place_graph_leveled_sharded(
        mesh, packed, nthreads, occ0, running,
        fleet_dev=state.mirror.sharded_device_view(mesh),
    )
    after = state.mirror.sharded_stats()
    assert after["rows_uploaded"] == before["rows_uploaded"]
    assert after["full_packs"] == before["full_packs"]
    np.testing.assert_array_equal(res_dev2.assignment, res_dev.assignment)


# -------------------------------------------------- mesh plan path


def _inc(x):
    return x + 1


def test_jax_placement_mesh_plan_path_and_stats():
    """JaxPlacement with the mesh subtree enabled plans through the
    sharded engine: hints land, the state records per-shard engine
    stats, and the mirror's shards stay cold on a fresh plan."""
    from distributed_tpu.graph.spec import TaskSpec
    from distributed_tpu.scheduler.jax_placement import JaxPlacement
    from distributed_tpu.scheduler.state import SchedulerState

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    with config.set({
        "scheduler.jax.mesh.enabled": True,
        "scheduler.jax.mesh.layout": "4x2",
        "scheduler.jax.partitioner": "off",
    }):
        placement = JaxPlacement(min_batch=4, min_workers=0, sync=True,
                                 min_transfer_ratio=0)
        state = SchedulerState(placement=placement)
        for i in range(16):
            state.add_worker_state(f"tcp://mp:{i}", nthreads=2,
                                   memory_limit=2**30, name=f"w{i}")
        tasks = {}
        deps: dict = {}
        for i in range(120):
            tasks[f"a-{i}"] = TaskSpec(_inc, (i,))
            deps[f"a-{i}"] = set()
            tasks[f"b-{i}"] = TaskSpec(_inc, (i,))
            deps[f"b-{i}"] = {f"a-{i}"}
        state.update_graph_core(tasks, deps, list(tasks), client="t",
                                stimulus_id="mesh-plan")
        assert placement.plans_computed == 1
        assert len(state.engine_shards) == 8
        assert all(r["h2d_bytes"] > 0 for r in state.engine_shards)
        assert all(r["plans"] == 1 for r in state.engine_shards)
        ss = state.mirror.sharded_stats()
        assert ss["n_shards"] == 2
        assert ss["rows_uploaded"] == [0, 0]  # fresh fleet: full pack only
        assert ss["full_packs"] == [1, 1]


def test_jax_placement_mesh_auto_default():
    """``scheduler.jax.mesh.enabled`` defaults to "auto" (ROADMAP item
    2 leftover): on when more than one device is visible at mesh-build
    time, single-device path otherwise, explicit booleans force."""
    from distributed_tpu.scheduler.jax_placement import JaxPlacement

    # default parses to auto (None)
    placement = JaxPlacement(min_batch=4, min_workers=0, sync=True)
    assert placement.mesh_enabled is None

    # explicit off stays off, never builds
    with config.set({"scheduler.jax.mesh.enabled": False}):
        off = JaxPlacement(min_batch=4, min_workers=0, sync=True)
        assert off.mesh_enabled is False
        assert off._get_mesh(build=True) is None

    # auto on a 1-device host: the single-device path (a 1x1 mesh is
    # bit-identical but pays dispatch overhead for nothing)
    single = JaxPlacement(min_batch=4, min_workers=0, sync=True)
    single._n_visible = lambda: 1  # instance shadow of the probe
    assert single._get_mesh(build=True) is None

    # auto on this multi-device host: the mesh builds
    if len(jax.devices()) >= 2:
        multi = JaxPlacement(min_batch=4, min_workers=0, sync=True)
        mesh = multi._get_mesh(build=True)
        assert mesh is not None
        assert mesh.devices.size == len(jax.devices())


def test_jax_placement_bad_layout_falls_back():
    """An impossible layout must not kill planning: the mesh builder
    logs and the planner degrades to the single-device engine."""
    from distributed_tpu.graph.spec import TaskSpec
    from distributed_tpu.scheduler.jax_placement import JaxPlacement
    from distributed_tpu.scheduler.state import SchedulerState

    with config.set({
        "scheduler.jax.mesh.enabled": True,
        "scheduler.jax.mesh.layout": "64x64",  # more than any host has
        "scheduler.jax.partitioner": "off",
    }):
        placement = JaxPlacement(min_batch=4, min_workers=0, sync=True,
                                 min_transfer_ratio=0)
        assert placement._get_mesh(build=True) is None
        state = SchedulerState(placement=placement)
        for i in range(8):
            state.add_worker_state(f"tcp://fb:{i}", nthreads=2,
                                   memory_limit=2**30, name=f"w{i}")
        tasks = {f"t-{i}": TaskSpec(_inc, (i,)) for i in range(64)}
        state.update_graph_core(tasks, {k: set() for k in tasks},
                                list(tasks), client="t",
                                stimulus_id="mesh-fallback")
        # the plan still landed — through the single-device engine
        assert placement.plans_computed == 1
        assert state.engine_shards == []
