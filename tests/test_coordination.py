"""Coordination primitive tests (reference test_semaphore.py, test_locks.py,
test_events.py, test_queues.py, test_variable.py, test_pubsub.py,
test_publish.py patterns)."""

from __future__ import annotations

import asyncio

import pytest

from distributed_tpu.client.client import Client
from distributed_tpu.coordination import (
    Event,
    Lock,
    MultiLock,
    Pub,
    Queue,
    Semaphore,
    Sub,
    Variable,
)
from distributed_tpu.deploy.local import LocalCluster

from conftest import gen_test


async def new_cluster(n_workers=2, **kwargs):
    cluster = LocalCluster(
        n_workers=n_workers,
        scheduler_kwargs={"validate": True},
        worker_kwargs={"validate": True},
        **kwargs,
    )
    await cluster._start()
    return cluster


@gen_test()
async def test_event():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            ev = Event("my-event", client=c)
            assert not await ev.is_set()
            assert not await ev.wait(timeout=0.05)

            async def setter():
                await asyncio.sleep(0.05)
                await Event("my-event", client=c).set()

            task = asyncio.ensure_future(setter())
            assert await ev.wait(timeout=5)
            assert await ev.is_set()
            await ev.clear()
            assert not await ev.is_set()
            await task


@gen_test()
async def test_lock():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            lock1 = Lock("x", client=c)
            lock2 = Lock("x", client=c)
            assert await lock1.acquire()
            assert await lock1.locked()
            # a second holder times out while held
            assert not await lock2.acquire(timeout=0.05)
            await lock1.release()
            assert await lock2.acquire(timeout=5)
            await lock2.release()
            # context manager form
            async with Lock("y", client=c):
                assert await Lock("y", client=c).locked()


@gen_test()
async def test_lock_reentrant_same_id():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            lock = Lock("re", client=c)
            assert await lock.acquire()
            assert await lock.acquire(timeout=1)  # same id: reentrant
            await lock.release()


@gen_test()
async def test_multilock():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            m1 = MultiLock(["a", "b"], client=c)
            assert await m1.acquire()
            m2 = MultiLock(["b", "c"], client=c)
            assert not await m2.acquire(timeout=0.05)  # blocked on b
            await m1.release()
            assert await m2.acquire(timeout=5)
            await m2.release()


@gen_test()
async def test_semaphore():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            sem = Semaphore(max_leases=2, name="sem", client=c)
            assert await sem.acquire()
            assert await sem.acquire()
            assert await sem.get_value() == 2
            assert not await sem.acquire(timeout=0.05)  # exhausted
            await sem.release()
            assert await sem.acquire(timeout=5)
            await sem.release()
            await sem.release()
            await sem.close()


@gen_test()
async def test_queue_data_roundtrip():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            q = Queue("q1", client=c)
            await q.put({"a": 1})
            await q.put(42)
            assert await q.qsize() == 2
            assert await q.get() == {"a": 1}
            assert await q.get() == 42
            with pytest.raises(asyncio.TimeoutError):
                await q.get(timeout=0.05)
            await q.close()


@gen_test()
async def test_queue_futures():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            q = Queue("qf", client=c)
            fut = c.submit(lambda x: x * 3, 5, key="qf-task")
            await fut.result()
            await q.put(fut)
            got = await q.get()
            assert got.key == "qf-task"
            assert await got.result() == 15


@gen_test()
async def test_variable():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            v = Variable("var1", client=c)
            with pytest.raises(asyncio.TimeoutError):
                await v.get(timeout=0.05)
            await v.set(123)
            assert await v.get() == 123
            await v.set(456)  # overwrite
            assert await v.get() == 456
            fut = c.submit(lambda: "hello", key="var-task")
            await fut.result()
            await v.set(fut)
            got = await v.get()
            assert await got.result() == "hello"
            await v.delete()


@gen_test()
async def test_variable_keeps_future_alive():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            v = Variable("keeper", client=c)
            fut = c.submit(lambda: 7, key="kept-task")
            await fut.result()
            await v.set(fut)
            fut.release()
            del fut
            await asyncio.sleep(0.1)
            # still alive because the variable holds it
            assert "kept-task" in cluster.scheduler.state.tasks
            got = await v.get()
            assert await got.result() == 7


@gen_test()
async def test_pubsub_client_to_client():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c1:
            async with Client(cluster.scheduler_address) as c2:
                sub = Sub("topic-1", client=c2)
                await asyncio.sleep(0.05)  # let subscription register
                pub = Pub("topic-1", client=c1)
                pub.put({"hello": "world"})
                msg = await sub.get(timeout=5)
                assert msg == {"hello": "world"}


@gen_test()
async def test_publish_datasets():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            fut = c.submit(lambda: [1, 2, 3], key="pub-task")
            await fut.result()
            await c.publish_dataset("my-data", fut)
            assert await c.list_datasets() == ["my-data"]
            fut.release()
            await asyncio.sleep(0.05)
            assert "pub-task" in cluster.scheduler.state.tasks
        # a brand-new client can retrieve it
        async with Client(cluster.scheduler_address) as c2:
            got = await c2.get_dataset("my-data")
            assert await got.result() == [1, 2, 3]
            await c2.unpublish_dataset("my-data")
            assert await c2.list_datasets() == []


@gen_test()
async def test_queue_future_pending_across_clients():
    """A Future put in a queue before it finishes must be awaitable by
    another client (regression: unknown keys were marked finished)."""
    import time as _t

    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c1:
            async with Client(cluster.scheduler_address) as c2:
                q1 = Queue("xq", client=c1)
                q2 = Queue("xq", client=c2)

                def slow():
                    _t.sleep(0.3)
                    return "slow-result"

                fut = c1.submit(slow, key="slow-task")
                await q1.put(fut)  # still pending when handed over
                got = await q2.get(timeout=5)
                assert got.key == "slow-task"
                assert await asyncio.wait_for(got.result(), 10) == "slow-result"
