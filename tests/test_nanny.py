"""Nanny / subprocess worker tests (reference test_nanny.py patterns).

Tier-3 style: real child processes over tcp.  Kept few and small — each
spawn pays the interpreter + jax import cost.
"""

from __future__ import annotations

import asyncio
import os
import signal

import pytest

from distributed_tpu.client.client import Client
from distributed_tpu.scheduler.server import Scheduler
from distributed_tpu.worker.nanny import Nanny

from conftest import gen_test

pytestmark = pytest.mark.slow

CHILD_ENV = {"JAX_PLATFORMS": "cpu", "JAX_NUM_CPU_DEVICES": "1"}


@gen_test(timeout=120)
async def test_nanny_runs_worker_and_restarts_on_death():
    async with Scheduler(validate=True) as s:
        nanny = Nanny(s.address, nthreads=1, name="nanny-w0", env=CHILD_ENV)
        async with nanny:
            assert nanny.worker_address is not None
            for _ in range(100):
                if s.state.workers:
                    break
                await asyncio.sleep(0.1)
            assert nanny.worker_address in s.state.workers

            async with Client(s.address) as c:
                fut = c.submit(lambda x: x + 1, 1)
                assert await asyncio.wait_for(fut.result(), 30) == 2

                # hard-kill the worker process: nanny must respawn it
                old_pid = nanny.process.pid
                os.kill(old_pid, signal.SIGKILL)
                for _ in range(300):
                    if (
                        nanny.process is not None
                        and nanny.process.pid not in (None, old_pid)
                        and nanny.worker_address in s.state.workers
                    ):
                        break
                    await asyncio.sleep(0.1)
                assert nanny.process.pid != old_pid

                fut2 = c.submit(lambda x: x * 10, 5, pure=False)
                assert await asyncio.wait_for(fut2.result(), 30) == 50


@gen_test(timeout=120)
async def test_nanny_graceful_kill_no_restart():
    async with Scheduler(validate=True) as s:
        nanny = Nanny(s.address, nthreads=1, name="nanny-w1", env=CHILD_ENV)
        async with nanny:
            pid = nanny.process.pid
            await nanny.kill()
            assert not nanny.process.is_alive()
            await asyncio.sleep(0.5)
            # no auto-restart after an explicit kill
            assert nanny.process.pid == pid
