"""Nanny / subprocess worker tests (reference test_nanny.py patterns).

Tier-3 style: real child processes over tcp.  Kept few and small — each
spawn pays the interpreter + jax import cost.
"""

from __future__ import annotations

import asyncio
import os
import signal

import pytest

from distributed_tpu.client.client import Client
from distributed_tpu.scheduler.server import Scheduler
from distributed_tpu.worker.nanny import Nanny

from conftest import gen_test

pytestmark = pytest.mark.slow

CHILD_ENV = {"JAX_PLATFORMS": "cpu", "JAX_NUM_CPU_DEVICES": "1"}


@gen_test(timeout=120)
async def test_nanny_runs_worker_and_restarts_on_death():
    async with Scheduler(validate=True) as s:
        nanny = Nanny(s.address, nthreads=1, name="nanny-w0", env=CHILD_ENV)
        async with nanny:
            assert nanny.worker_address is not None
            for _ in range(100):
                if s.state.workers:
                    break
                await asyncio.sleep(0.1)
            assert nanny.worker_address in s.state.workers

            async with Client(s.address) as c:
                fut = c.submit(lambda x: x + 1, 1)
                assert await asyncio.wait_for(fut.result(), 30) == 2

                # hard-kill the worker process: nanny must respawn it
                old_pid = nanny.process.pid
                os.kill(old_pid, signal.SIGKILL)
                for _ in range(300):
                    if (
                        nanny.process is not None
                        and nanny.process.pid not in (None, old_pid)
                        and nanny.worker_address in s.state.workers
                    ):
                        break
                    await asyncio.sleep(0.1)
                assert nanny.process.pid != old_pid

                fut2 = c.submit(lambda x: x * 10, 5, pure=False)
                assert await asyncio.wait_for(fut2.result(), 30) == 50


@gen_test(timeout=120)
async def test_nanny_graceful_kill_no_restart():
    async with Scheduler(validate=True) as s:
        nanny = Nanny(s.address, nthreads=1, name="nanny-w1", env=CHILD_ENV)
        async with nanny:
            pid = nanny.process.pid
            await nanny.kill()
            assert not nanny.process.is_alive()
            await asyncio.sleep(0.5)
            # no auto-restart after an explicit kill
            assert nanny.process.pid == pid


@gen_test(timeout=60)
async def test_worker_lifetime_retires_gracefully():
    """--lifetime on a bare worker: after the deadline it retires through
    the scheduler (data replicated away) and closes; the cluster keeps
    working (reference dask-worker --lifetime)."""
    from distributed_tpu.client.client import Client
    from distributed_tpu.scheduler.server import Scheduler
    from distributed_tpu.worker.server import Worker

    async with Scheduler(listen_addr="inproc://", validate=True) as s:
        async with Worker(s.address, nthreads=1) as keeper:
            mortal = Worker(s.address, nthreads=1, lifetime=0.8,
                            lifetime_stagger=0)
            await mortal.start()
            try:
                async with Client(s.address) as c:
                    fut = c.submit(lambda: 123, workers=[mortal.address])
                    assert await fut.result() == 123
                    # wait out the lifetime: the mortal worker must leave
                    for _ in range(200):
                        if mortal.address not in s.state.workers:
                            break
                        await asyncio.sleep(0.05)
                    assert mortal.address not in s.state.workers
                    assert keeper.address in s.state.workers
                    # its data survived retirement and the cluster works
                    assert await fut.result() == 123
                    assert await c.submit(lambda: 7).result() == 7
            finally:
                await mortal.close()


@pytest.mark.slow
@gen_test(timeout=180)
async def test_nanny_lifetime_restart_cycles_worker():
    """--lifetime-restart under a nanny: each lifetime boundary retires
    the worker process and spawns a fresh one (reference dask-worker
    --lifetime-restart)."""
    async with Scheduler(listen_addr="tcp://127.0.0.1:0", validate=True) as s:
        nanny = Nanny(s.address, nthreads=1, lifetime=1.0,
                      lifetime_stagger=0, lifetime_restart=True)
        await nanny.start()
        try:
            first = nanny.worker_address
            assert first is not None
            for _ in range(600):
                if (nanny.worker_address is not None
                        and nanny.worker_address != first):
                    break
                await asyncio.sleep(0.2)
            assert nanny.worker_address != first, "worker never cycled"
            # the fresh worker registers with the scheduler
            for _ in range(200):
                if nanny.worker_address in s.state.workers:
                    break
                await asyncio.sleep(0.1)
            assert nanny.worker_address in s.state.workers
        finally:
            await nanny.close()


@pytest.mark.slow
@gen_test(timeout=180)
async def test_run_on_nanny_and_nanny_plugin():
    """client.run(nanny=True) executes on the nanny process, and a
    NannyPlugin registered through the client reaches current AND
    late-joining nannies (reference test_nanny.py patterns)."""
    from distributed_tpu.diagnostics.plugin import NannyPlugin

    class Tag(NannyPlugin):
        name = "tagger"

        def setup(self, nanny):
            nanny.tagged = True

    async with Scheduler(listen_addr="tcp://127.0.0.1:0", validate=True) as s:
        nanny = Nanny(s.address, nthreads=1)
        await nanny.start()
        try:
            async with Client(s.address) as c:
                # the worker reported its nanny address
                ws = s.state.workers[nanny.worker_address]
                assert ws.extra.get("nanny") == nanny.address
                # run on the NANNY, not the worker
                out = await c.run(lambda dtpu_nanny=None: type(dtpu_nanny).__name__,
                                  nanny=True)
                assert out == {nanny.address: "Nanny"}
                # plugin reaches the live nanny
                await c.register_plugin(Tag())
                assert getattr(nanny, "tagged", False)
                # ...and a late-joining nanny
                n2 = Nanny(s.address, nthreads=1)
                await n2.start()
                try:
                    for _ in range(100):
                        if getattr(n2, "tagged", False):
                            break
                        await asyncio.sleep(0.1)
                    assert getattr(n2, "tagged", False)
                finally:
                    await n2.close()
        finally:
            await nanny.close()


@gen_test(timeout=180)
async def test_scheduler_restart_cycles_nannied_worker():
    """Scheduler.restart must also cycle worker processes under a nanny
    (ADVICE r3: the reference's restart clears worker-side module and
    memory state too, scheduler.py:6193 -> nanny restart)."""
    async with Scheduler(validate=True) as s:
        nanny = Nanny(s.address, nthreads=1, name="nanny-rc", env=CHILD_ENV)
        async with nanny:
            for _ in range(100):
                if s.state.workers:
                    break
                await asyncio.sleep(0.1)
            old_pid = nanny.process.pid
            async with Client(s.address) as c:
                assert await c.submit(lambda: 3, key="pre").result() == 3
                await c.restart()
                # the worker process must be REPLACED, and come back
                for _ in range(300):
                    if (
                        nanny.process is not None
                        and nanny.process.pid not in (None, old_pid)
                        and s.state.workers
                    ):
                        break
                    await asyncio.sleep(0.1)
                assert nanny.process.pid != old_pid, "worker not cycled"
                fut = c.submit(lambda: 11, key="post", pure=False)
                assert await asyncio.wait_for(fut.result(), 60) == 11


@gen_test(timeout=90)
async def test_nanny_blocked_handlers_key():
    """nanny.blocked-handlers governs the nanny independently of the
    worker/scheduler keys (each node type owns its blocklist)."""
    from distributed_tpu import config as dtpu_config
    from distributed_tpu.rpc.core import rpc
    from distributed_tpu.scheduler.server import Scheduler
    from distributed_tpu.worker.nanny import Nanny

    with dtpu_config.set({"nanny.blocked-handlers": ["run"]}):
        async with Scheduler(listen_addr="tcp://127.0.0.1:0",
                             http_port=None) as s:
            async with Nanny(s.address, nthreads=1) as n:
                # the nanny's own "run" RPC is blocked
                async with rpc(n.address) as r:
                    with pytest.raises(ValueError,
                                       match="unknown operation"):
                        await r.send_recv(op="run", reply=True,
                                          function=None)
                # but the worker under it still computes
                from distributed_tpu.client.client import Client

                async with Client(s.address) as c:
                    assert await c.submit(lambda: 6, key="nb-1").result() == 6
