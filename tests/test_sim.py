"""Deterministic sans-io cluster simulator (distributed_tpu/sim;
docs/simulator.md): determinism contract, chaos scenarios against the
drift-gated state-machine model, sim<->live journal replay parity, the
policy A/B driver, and the virtual-clock seams.
"""

from __future__ import annotations

import json
import os

import pytest

from distributed_tpu.diagnostics.flight_recorder import (
    replay_stimulus_trace,
    transition_stream,
    verify_journal,
)
from distributed_tpu.sim import (
    ClusterSim,
    JournalTrace,
    LinkProfile,
    SyntheticDag,
    VirtualClock,
    run_ab,
)
from distributed_tpu.sim.chaos import (
    scenario_partition,
    scenario_poison_flood,
    scenario_straggler,
    scenario_worker_death,
)
from distributed_tpu.sim.validate import check_no_lost_keys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_model() -> dict:
    out = {}
    for role in ("scheduler", "worker"):
        path = os.path.join(REPO_ROOT, "docs", "state_machine", f"{role}.json")
        with open(path) as f:
            out[role] = json.load(f)
    return out


MODEL = load_model()


def small_sim(seed=0, n_workers=8, **kwargs) -> ClusterSim:
    sim = ClusterSim(n_workers, seed=seed, validate=True, **kwargs)
    sim.install_digest()
    return sim


def small_trace(seed=0, **kwargs) -> SyntheticDag:
    kwargs.setdefault("n_layers", 6)
    kwargs.setdefault("layer_width", 16)
    kwargs.setdefault("fanin", 2)
    return SyntheticDag(seed=seed, **kwargs)


# ------------------------------------------------------------- primitives


def test_virtual_clock_monotone():
    clock = VirtualClock()
    assert clock() == 0.0
    clock.advance_to(1.5)
    assert clock() == 1.5
    with pytest.raises(ValueError):
        clock.advance_to(1.0)


def test_link_profile_deterministic_and_seeded():
    a = LinkProfile(jitter=0.3, seed=1)
    b = LinkProfile(jitter=0.3, seed=1)
    c = LinkProfile(jitter=0.3, seed=2)
    e = ("sim://w1", "sim://w2")
    assert a.transfer_seconds(*e, 10**6) == b.transfer_seconds(*e, 10**6)
    assert a.transfer_seconds(*e, 10**6) != c.transfer_seconds(*e, 10**6)
    # jitter is per-edge, independent of use order
    assert a.transfer_seconds("sim://w3", "sim://w4", 1) == b.transfer_seconds(
        "sim://w3", "sim://w4", 1
    )


def test_link_profile_from_measured_records():
    """Telemetry's link-profile export seeds the sim's network model
    (the measured-truth loop: live cluster -> LinkStats -> sim)."""
    from distributed_tpu.telemetry import LinkTelemetry

    tel = LinkTelemetry(alpha=0.5, enabled=True)
    for _ in range(4):
        tel.record("sim://w0", "sim://w1", 10**6, 0.01)  # 100 MB/s
    records = tel.link_profile()
    assert records and records[0]["src"] == "sim://w0"
    prof = LinkProfile.from_records(records, bandwidth=1e9, latency=1e-4)
    measured = prof.transfer_seconds("sim://w0", "sim://w1", 10**6)
    # ~ 1 MB over ~100 MB/s => ~10ms, nothing like the 1 GB/s default
    assert 0.005 < measured < 0.05
    # unmeasured edges keep the synthetic default
    assert prof.transfer_seconds("sim://w1", "sim://w0", 10**6) < 0.005


def test_partition_windows():
    prof = LinkProfile()
    prof.add_partition(["a"], ["b"], 1.0, 2.0)
    assert prof.reachable("a", "b", 0.5)
    assert not prof.reachable("a", "b", 1.5)
    assert not prof.reachable("b", "a", 1.5)
    assert prof.reachable("a", "b", 2.0)


# ------------------------------------------------------------ determinism


def test_same_seed_bit_identical():
    """The acceptance gate: same seed => bit-identical digest and
    virtual makespan; different seed => different digest."""
    reports, digests = [], []
    for seed in (0, 0, 3):
        sim = small_sim(seed=seed)
        small_trace(seed=seed).start(sim)
        reports.append(sim.run())
        check_no_lost_keys(sim)
        digests.append(sim.digest())
    assert digests[0] == digests[1]
    assert reports[0]["virtual_makespan_s"] == reports[1]["virtual_makespan_s"]
    assert reports[0]["scheduler_transitions"] == reports[1]["scheduler_transitions"]
    assert digests[0] != digests[2]


def test_makespan_is_virtual_not_wall():
    """The makespan must be virtual seconds derived from the task
    profile, not anything wall-adjacent: 10x the task durations ~10x
    the makespan, irrespective of how fast the host ran the sim."""
    outs = []
    for scale in (1.0, 10.0):
        sim = small_sim()
        small_trace(
            duration_range=(0.002 * scale, 0.004 * scale)
        ).start(sim)
        outs.append(sim.run()["virtual_makespan_s"])
    assert 5.0 < outs[1] / outs[0] < 15.0


# ------------------------------------------------------------------ chaos


def test_chaos_worker_death():
    sim, rep = scenario_worker_death(model=MODEL)
    assert rep["counters"]["workers_killed"] == 2
    assert rep["n_alive"] == rep["n_workers"] - 2
    # deterministic: the same scenario digests identically
    _sim2, rep2 = scenario_worker_death(model=MODEL)
    assert rep["digest"] == rep2["digest"]


def test_chaos_partition():
    sim, rep = scenario_partition(model=MODEL)
    assert rep["counters"].get("gather_failures", 0) > 0, (
        "partition never failed a fetch — the scenario tested nothing"
    )
    _sim2, rep2 = scenario_partition(model=MODEL)
    assert rep["digest"] == rep2["digest"]


def test_chaos_straggler_steal_pays():
    sim, rep = scenario_straggler(model=MODEL)
    assert rep["steals"] > 0
    assert rep["virtual_makespan_s"] < rep["nosteal_makespan_s"]


def test_chaos_poison_flood():
    sim, rep = scenario_poison_flood(model=MODEL)
    assert rep["faults"]["scheduler-unknown-op"] >= 1
    _sim2, rep2 = scenario_poison_flood(model=MODEL)
    assert rep["digest"] == rep2["digest"]


# -------------------------------------------------------- journal replay


def replay_build(seed=5):
    """Single-chunk workload with periodics off: the journal records
    ENGINE stimuli, so record/replay states must be structurally
    identical up front and free of outside-the-journal mutations
    (steal confirms bypass the stimulus plane by design)."""
    sim = ClusterSim(
        6, seed=seed, validate=True,
        steal_interval=0, amm_interval=0, find_missing_interval=0,
    )
    SyntheticDag(
        n_layers=4, layer_width=10, fanin=2, seed=seed, layers_per_chunk=4
    ).start(sim)
    return sim


def test_sim_journal_replays_through_live_engine():
    """A sim-recorded journal re-fed through the batched engine on an
    identically-prepared state reproduces the identical transition
    stream — the sim half of the replay-format contract."""
    rec = replay_build()
    mark = len(rec.state.transition_log)
    rec.journal_start()
    rec.run()
    records = rec.journal()
    verify_journal(records)
    # dependency graphs exercise the add-keys journal op (replica truth
    # outside the engine); without it placements drift on replay
    assert any(r["op"] == "add-keys" for r in records)

    rep = replay_build()
    mark_b = len(rep.state.transition_log)
    replay_stimulus_trace(rep.state, records)
    assert transition_stream(rec.state, mark) == transition_stream(
        rep.state, mark_b
    )


def test_live_journal_replays_through_sim():
    """The other direction: a journal recorded off one engine replays
    through a fresh simulator's engine (JournalTrace), digests
    verified, bit-identical stream."""
    live = replay_build()
    mark_l = len(live.state.transition_log)
    live.journal_start()
    live.run()
    records = live.journal()

    sim = replay_build()
    mark_s = len(sim.state.transition_log)
    JournalTrace(records).replay(sim)
    assert transition_stream(live.state, mark_l) == transition_stream(
        sim.state, mark_s
    )


def test_journal_file_roundtrip(tmp_path):
    """dump_journal/load_journal + JournalTrace.from_file: the on-disk
    JSONL format survives a round trip with digests intact."""
    from distributed_tpu.tracing import dump_journal, load_journal

    rec = replay_build()
    rec.journal_start()
    rec.run()
    records = rec.journal()
    path = str(tmp_path / "journal.jsonl")
    n = dump_journal(records, path)
    assert n == len(records)
    loaded = load_journal(path)
    verify_journal(loaded)
    sim = replay_build()
    mark = len(sim.state.transition_log)
    JournalTrace.from_file(path).replay(sim)
    assert len(transition_stream(sim.state, mark)) > 0


def test_self_journaled_stimuli_do_not_double_journal():
    """stimulus_reschedule / stimulus_missing_data journal their own op
    AND drive an engine round internally — that round must NOT also
    journal as a "transitions" record, or replay runs it twice (the
    release-worker-data rule).  Captured here: fire both during a
    journal capture and require bit-identical replay."""
    rec = replay_build()
    mark = len(rec.state.transition_log)
    rec.journal_start()
    rec.run(max_events=120)  # mid-flight: processing tasks exist
    state = rec.state
    proc = sorted(
        (ts for ts in state.tasks.values() if ts.state == "processing"),
        key=lambda ts: ts.key,
    )
    assert proc, "no processing task mid-flight"
    state.stimulus_reschedule(
        proc[0].key, proc[0].processing_on.address, "resched-poke"
    )
    mem = sorted(
        (ts for ts in state.tasks.values()
         if ts.state == "memory" and len(ts.who_has) == 1),
        key=lambda ts: ts.key,
    )
    if mem:
        state.stimulus_missing_data(
            mem[0].key, next(iter(mem[0].who_has)).address, "md-poke"
        )
    records = rec.journal()
    ops = [r["op"] for r in records]
    assert "reschedule" in ops
    # exactly one journal record per self-journaled stimulus: no
    # trailing "transitions" twin carrying the same round
    for op in ("reschedule", "missing-data"):
        for i, r in enumerate(records):
            if r["op"] == op and i + 1 < len(records):
                nxt = records[i + 1]
                assert not (
                    nxt["op"] == "transitions"
                    and nxt["stim"] == r["stim"]
                ), f"{op} double-journaled its engine round"

    rep = replay_build()
    mark2 = len(rep.state.transition_log)
    replay_stimulus_trace(rep.state, records)
    assert transition_stream(rec.state, mark) == transition_stream(
        rep.state, mark2
    )


def test_tampered_journal_refused(tmp_path):
    rec = replay_build()
    rec.journal_start()
    rec.run()
    records = rec.journal()
    records[1]["payload"] = {"forged": True}
    sim = replay_build()
    with pytest.raises(ValueError, match="digest"):
        JournalTrace(records).replay(sim)


# ------------------------------------------------------------- A/B driver


def test_ab_driver_steal_cadence():
    """The same trace under two steal cadences: identical overrides
    give identical digests; a policy change moves the virtual-time
    outcome and the diff reports it."""
    def trace_factory():
        # fanin=1 chains cluster hard onto their input holders: real
        # imbalance, so stealing measurably matters
        return SyntheticDag(
            n_layers=8, layer_width=40, fanin=1, n_roots=4, seed=9,
        )

    out = run_ab(
        10, trace_factory,
        {"scheduler.work-stealing-interval": "50ms"},
        {"scheduler.work-stealing-interval": "50ms"},
        seed=9,
    )
    assert out["a"]["digest"] == out["b"]["digest"]
    assert out["diff"]["virtual_makespan_s"] == 0.0
    assert out["a"]["steals"] > 0

    out2 = run_ab(
        10, trace_factory,
        {"scheduler.work-stealing-interval": "50ms"},
        {"scheduler.work-stealing": False},
        seed=9,
    )
    assert out2["a"]["digest"] != out2["b"]["digest"]
    assert out2["b"]["steals"] == 0 < out2["a"]["steals"]
    assert out2["diff"]["makespan_ratio"] is not None


# ----------------------------------------------------- virtual-clock seams


def test_telemetry_ewmas_fed_from_simulated_transfers():
    """PR 7's telemetry plane under the virtual clock: simulated
    gathers file per-link samples whose EWMA bandwidth reproduces the
    link profile, and the snapshot timestamp is VIRTUAL time (the
    injected-clock satellite: no residual real-clock stamp)."""
    profile_bw = 200e6
    sim = small_sim(links=LinkProfile(bandwidth=profile_bw, latency=1e-4))
    small_trace(nbytes_range=(200_000, 400_000)).start(sim)
    sim.run()
    tel = sim.state.telemetry
    assert tel.links, "no simulated transfers filed telemetry"
    bws = [
        link.bandwidth.value for link in tel.links.values()
        if link.bandwidth.count
    ]
    assert bws
    mean_bw = sum(bws) / len(bws)
    # per-sample bandwidth = nbytes / (latency + nbytes/bw) < profile bw;
    # with >=200 KB payloads the latency term is small
    assert profile_bw / 3 < mean_bw <= profile_bw * 1.01, mean_bw
    snap = tel.snapshot()
    assert snap
    vnow = sim.clock()
    assert all(rec["ts"] <= vnow + 1e-9 for rec in snap), (
        "telemetry snapshot stamped off the virtual clock"
    )
    # the trace ring's journal clock is virtual too
    assert sim.state.trace.clock is sim.clock


def test_transition_log_stamps_are_virtual():
    sim = small_sim()
    small_trace().start(sim)
    sim.run()
    stamps = [row[5] for row in sim.state.transition_log]
    assert stamps and max(stamps) <= sim.clock() + 1e-9


# -------------------------------------------------- engine fixes (found
# by the simulator; regression-pinned here)


def test_scatter_release_pure_data_with_live_dependents():
    """Scatter -> consume -> client-release under validate: forgetting
    pure data while (released) dependents remain is legal (reference
    parity); the old assert crashed the engine."""
    from distributed_tpu.scheduler.state import SchedulerState

    state = SchedulerState(validate=True, mirror=False)
    state.add_worker_state("tcp://sc:1", nthreads=1, memory_limit=2**30)
    state.client_desires_keys(["datum"], "c")  # creates the TaskState
    recs, cm, wm = state._transition(
        "datum", "memory", "scatter", nbytes=8, worker="tcp://sc:1"
    )
    state._transitions(recs, cm, wm, "scatter")
    from distributed_tpu.sim.core import SIM_SPEC

    state.update_graph_core(
        {"use": SIM_SPEC}, {"use": {"datum"}}, ["use"], client="c",
        priorities={"use": (0,)}, stimulus_id="graph",
    )
    cm, wm = state.stimulus_task_finished(
        "use", "tcp://sc:1", "fin", nbytes=8
    )
    # consumer done; client drops both — must not trip the forgotten
    # validate assert even though "use" is released-not-forgotten while
    # "datum" forgets
    state.client_releases_keys(["use", "datum"], "c", "rel")
    assert "datum" not in state.tasks


def test_worker_compute_task_on_missing_task_waits_for_data():
    """A compute-task landing on a task in 'missing' (or fetch) state
    must keep the freshly-wired waiting_for_data — the released
    fallback wiped it and raced the task to ready without inputs
    (found by the partition chaos scenario)."""
    from distributed_tpu.worker.state_machine import (
        ComputeTaskEvent,
        Execute,
        GatherDep,
        GatherDepSuccessEvent,
        WorkerState,
    )

    ws = WorkerState(nthreads=1, address="sim://me", validate=True)
    spec = object()
    # dep lands 'missing': wanted as a dependency with NO known holders
    # (no gather can even start)
    ws.handle_stimulus(ComputeTaskEvent(
        stimulus_id="s1", key="consumer", run_spec=spec,
        priority=(1,), who_has={"dep": []}, nbytes={"dep": 8},
    ))
    assert ws.tasks["dep"].state == "missing"
    # the scheduler re-assigns the MISSING task as a compute with its
    # own absent dependency
    instrs = ws.handle_stimulus(ComputeTaskEvent(
        stimulus_id="s3", key="dep", run_spec=spec, priority=(0,),
        who_has={"base": ["sim://peer"]}, nbytes={"base": 8},
    ))
    dep = ws.tasks["dep"]
    assert dep.state == "waiting"
    assert {d.key for d in dep.waiting_for_data} == {"base"}
    assert not [i for i in instrs if isinstance(i, Execute) and i.key == "dep"]
    gathers = [i for i in instrs if isinstance(i, GatherDep)]
    assert gathers and "base" in gathers[0].to_gather
    # data arrives -> NOW it executes
    instrs = ws.handle_stimulus(GatherDepSuccessEvent(
        stimulus_id="s4", worker="sim://peer", data={"base": 1},
        total_nbytes=8,
    ))
    assert [i for i in instrs if isinstance(i, Execute) and i.key == "dep"]
    ws.validate_state()


# ---------------------------------------------------------- housekeeping


def test_sim_package_is_sans_io_scoped():
    """The lint scoping satellite: graft-lint's sans-io and
    monotonic-time rules must cover distributed_tpu/sim/."""
    from distributed_tpu.analysis.rules.monotonic_time import (
        MonotonicTimeRule,
    )
    from distributed_tpu.analysis.rules.sans_io import SansIORule

    assert any("sim" in pat for pat in SansIORule.scope)
    assert any("sim" in pat for pat in MonotonicTimeRule.scope)
    with open(os.path.join(REPO_ROOT, "graft-lint.toml")) as f:
        toml = f.read()
    assert "distributed_tpu/sim/*.py" in toml
