"""Zero-copy scatter-gather data plane (docs/wire.md): big-frame round
trips over every comm backend, the send-path zero-copy counter contract,
receive-pool reuse/ownership, dumps/loads parity across compression
codecs and the opaque forwarding path, and the corrupt-header guards."""

from __future__ import annotations

import asyncio
import struct

import numpy as np
import pytest

from distributed_tpu import config
from distributed_tpu.comm.core import connect, listen
from distributed_tpu.exceptions import CommClosedError
from distributed_tpu.protocol.buffers import WIRE, BufferPool, recv_pool
from distributed_tpu.protocol.core import dumps, loads
from distributed_tpu.protocol.serialize import Serialize, Serialized, ToPickle

from conftest import gen_test


def _rewrap(msg):
    """Re-mark array payloads for the return hop (a deserializing read
    hands the handler plain ndarrays)."""
    if isinstance(msg, dict):
        return {
            k: Serialize(v) if isinstance(v, np.ndarray) else v
            for k, v in msg.items()
        }
    return msg


async def _echo_listener(scheme: str):
    async def echo(comm):
        try:
            while True:
                msg = await comm.read()
                await comm.write(_rewrap(msg))
        except Exception:
            pass

    listener = listen(f"{scheme}://127.0.0.1:0", echo)
    await listener.start()
    return listener


def _tls_security_or_skip():
    from distributed_tpu.security import Security

    try:
        return Security.temporary()
    except ImportError:
        pytest.skip("cryptography not available for tls://")


# ------------------------------------------------- backend round trips


@pytest.mark.parametrize("scheme", ["tcp", "ws", "inproc"])
def test_big_frame_roundtrip_over_backend(scheme):
    """Frames larger than comm.shard survive every backend: the shard
    split, the scatter write, the pooled read and the adjacency merge
    are all exercised by a payload that must fragment."""

    @gen_test()
    async def run():
        arr = np.random.default_rng(0).integers(
            0, 255, 1_500_000, dtype=np.uint8
        )
        with config.set({"comm.shard": "256KiB"}):
            listener = await _echo_listener(scheme)
            comm = await connect(listener.contact_address)
            try:
                await comm.write({"op": "blob", "data": Serialize(arr)})
                out = await comm.read()
                np.testing.assert_array_equal(out["data"], arr)
            finally:
                await comm.close()
                listener.stop()

    run()


@gen_test()
async def test_big_frame_roundtrip_over_tls():
    sec = _tls_security_or_skip()
    arr = np.arange(200_000, dtype=np.int64)
    listener = listen(
        "tls://127.0.0.1:0",
        lambda comm: _echo_forever(comm),
        **sec.get_listen_args("scheduler"),
    )
    await listener.start()
    comm = await connect(
        listener.contact_address, **sec.get_connection_args("client")
    )
    try:
        await comm.write({"data": Serialize(arr)})
        out = await comm.read()
        np.testing.assert_array_equal(out["data"], arr)
    finally:
        await comm.close()
        listener.stop()


async def _echo_forever(comm):
    try:
        while True:
            await comm.write(_rewrap(await comm.read()))
    except Exception:
        pass


# ------------------------------------------- zero-copy send contract


@gen_test()
async def test_tcp_send_path_records_zero_payload_copies():
    """The acceptance contract: a >=1 MB payload crosses the TCP send
    path with dtpu_wire_payload_copies == 0 — no bytes(frame), no
    joins, straight memoryview hand-off to the transport."""
    arr = np.random.default_rng(1).integers(0, 255, 2_000_000, dtype=np.uint8)
    listener = await _echo_listener("tcp")
    comm = await connect(listener.contact_address)
    try:
        before = WIRE.snapshot()
        await comm.write({"op": "blob", "data": Serialize(arr)})
        out = await comm.read()
        after = WIRE.snapshot()
        np.testing.assert_array_equal(out["data"], arr)
        # the echo round trip covers BOTH sides' send paths (client and
        # server live in this process): zero copies total
        assert after["payload_copies"] - before["payload_copies"] == 0
        assert after["bytes_sent"] - before["bytes_sent"] >= 2 * arr.nbytes
        assert after["bytes_recv"] - before["bytes_recv"] >= 2 * arr.nbytes
    finally:
        await comm.close()
        listener.stop()


@gen_test()
async def test_sharded_opaque_forwarding_merges_zero_copy():
    """deserialize=False: sharded frames reassemble as ONE zero-copy
    slice of the contiguous receive buffer (the store-and-forward path
    the scheduler depends on), and a forwarding hop preserves bytes."""
    arr = np.random.default_rng(2).integers(0, 255, 1_000_000, dtype=np.uint8)
    with config.set({"comm.shard": "128KiB"}):
        async def handle(comm):
            try:
                while True:
                    await comm.write(await comm.read())
            except Exception:
                pass

        listener = listen("tcp://127.0.0.1:0", handle, deserialize=False)
        await listener.start()
        comm = await connect(listener.contact_address, deserialize=False)
        try:
            before = WIRE.snapshot()
            await comm.write({"op": "blob", "data": Serialize(arr)})
            out = await comm.read()
            after = WIRE.snapshot()
            opaque = out["data"]
            assert isinstance(opaque, Serialized)
            # the sharded leaf merged into a single zero-copy view
            assert len(opaque.frames) == 1
            assert isinstance(opaque.frames[0], memoryview)
            assert after["payload_copies"] - before["payload_copies"] == 0
            # final consumer sees the original bytes
            final = loads(dumps({"x": opaque}))["x"]
            np.testing.assert_array_equal(final, arr)
        finally:
            await comm.close()
            listener.stop()


# ----------------------------------------------------- receive pool


@gen_test()
async def test_pool_reuse_on_control_plane_and_drop_on_pinned_views():
    listener = await _echo_listener("tcp")
    comm = await connect(listener.contact_address)
    try:
        # warm the pool classes
        await comm.write({"op": "warm"})
        await comm.read()
        before = WIRE.snapshot()
        for i in range(8):
            await comm.write({"op": "ctl", "i": i})
            await comm.read()
        after = WIRE.snapshot()
        # control messages fully deserialize (msgpack copies), so their
        # buffers return to the pool and get reused: hits, no drops
        assert after["pool_hits"] - before["pool_hits"] >= 8
        assert after["pool_drops"] - before["pool_drops"] == 0
        # a numpy payload pins its zero-copy view of the receive buffer:
        # the pool must DROP that buffer, never recycle it under the view
        before = WIRE.snapshot()
        arr = np.arange(50_000, dtype=np.int64)
        await comm.write({"data": Serialize(arr)})
        out = await comm.read()
        after = WIRE.snapshot()
        assert after["pool_drops"] - before["pool_drops"] >= 1
        np.testing.assert_array_equal(out["data"], arr)
        # ... and the received array still reads correctly afterwards
        # even as the pool keeps serving other messages
        for i in range(4):
            await comm.write({"op": "ctl", "i": i})
            await comm.read()
        np.testing.assert_array_equal(out["data"], arr)
    finally:
        await comm.close()
        listener.stop()


def test_buffer_pool_classes_and_export_probe():
    pool = BufferPool(max_bytes=1 << 20)
    b1 = pool.acquire(10_000)
    assert len(b1) == 1 << 14  # next pow2 class
    pool.release(b1)
    assert pool.pooled_bytes == len(b1)
    b2 = pool.acquire(12_000)
    assert b2 is b1  # class hit
    # a live export keeps the buffer out of the pool
    view = memoryview(b2)
    pool.release(b2)
    assert pool.pooled_bytes == 0
    view.release()
    pool.release(b2)
    assert pool.pooled_bytes == len(b2)
    # giants bypass pooling entirely (exact alloc)
    g = pool.acquire((1 << pool.MAX_CLASS) + 1)
    assert len(g) == (1 << pool.MAX_CLASS) + 1
    pool.release(g)
    assert pool.pooled_bytes == len(b2)
    # budget cap: releases beyond max_bytes are dropped
    small_pool = BufferPool(max_bytes=1 << 14)
    c1 = small_pool.acquire(1 << 14)
    c2 = small_pool.acquire(1 << 14)
    small_pool.release(c1)
    small_pool.release(c2)
    assert small_pool.pooled_bytes == 1 << 14


# ------------------------------------------------- dumps/loads parity


def _random_message(rng: np.random.Generator, depth: int = 0):
    kind = rng.integers(0, 8 if depth < 2 else 6)
    if kind == 0:
        return {"k": int(rng.integers(0, 100)), "s": "x" * int(rng.integers(0, 50))}
    if kind == 1:
        return rng.integers(0, 255, int(rng.integers(0, 200_000)),
                            dtype=np.uint8).tobytes()
    if kind == 2:
        return Serialize(rng.random(int(rng.integers(1, 100_000))))
    if kind == 3:
        return Serialize(
            rng.integers(0, 255, int(rng.integers(1, 300_000)), dtype=np.uint8)
        )
    if kind == 4:
        return ToPickle({"fn": len, "args": [1, 2, 3]})
    if kind == 5:
        return [int(x) for x in rng.integers(0, 10, 5)]
    if kind == 6:
        return {f"key-{i}": _random_message(rng, depth + 1) for i in range(3)}
    return [_random_message(rng, depth + 1) for i in range(3)]


def _assert_parity(a, b):
    if isinstance(a, np.ndarray):
        np.testing.assert_array_equal(a, b)
    elif isinstance(a, dict):
        assert set(a) == set(b)
        for k in a:
            _assert_parity(a[k], b[k])
    elif isinstance(a, (list, tuple)):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            _assert_parity(x, y)
    else:
        assert a == b


@pytest.mark.parametrize("compression", [None, "zlib", "zstd"])
def test_loads_dumps_parity_property(compression):
    """Property test: random nested messages survive dumps/loads
    bit-identically across codecs, shard sizes, and the opaque
    (deserialize=False) forwarding hop."""
    if compression == "zstd":
        pytest.importorskip("zstandard")
    rng = np.random.default_rng(42)
    for trial in range(10):
        msg = {"op": "prop", "body": _random_message(rng)}
        expect = loads(dumps(msg, compression=None))  # reference decode
        for shard in ("64KiB", "64MiB"):
            with config.set({"comm.shard": shard}):
                frames = dumps(msg, compression=compression)
                # frames always satisfy the wire contract
                assert all(
                    isinstance(f, (bytes, bytearray, memoryview))
                    for f in frames
                )
                _assert_parity(loads(frames), expect)
                # opaque hop: loads without deserializers, re-dump, load
                opaque = loads(
                    dumps(msg, compression=compression), deserializers=False
                )
                _assert_parity(loads(dumps(opaque)), expect)


# ------------------------------------------------- corrupt-header guards


async def _malicious_server(payload: bytes):
    """A raw TCP server that writes ``payload`` and half-closes."""

    async def handle(reader, writer):
        writer.write(payload)
        try:
            await writer.drain()
            writer.write_eof()
        except Exception:
            pass

    server = await asyncio.start_server(handle, "127.0.0.1", 0)
    return server, server.sockets[0].getsockname()[1]


@gen_test()
async def test_oversized_lengths_header_rejected():
    """One corrupt/hostile header must not trigger an arbitrary-size
    allocation: the lengths sum is capped by comm.max-message-bytes."""
    from distributed_tpu.comm.tcp import TCP

    bogus = struct.pack("<Q", 2) + struct.pack("<QQ", 2**40, 2**40)
    server, port = await _malicious_server(bogus)
    try:
        with config.set({"comm.max-message-bytes": "64MiB"}):
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            comm = TCP(reader, writer, "tcp://local", "tcp://peer")
            with pytest.raises(CommClosedError, match="max-message-bytes"):
                await comm.read()
            assert comm.closed
    finally:
        server.close()


@gen_test()
async def test_bad_frame_count_rejected():
    from distributed_tpu.comm.tcp import TCP, MAX_FRAME_COUNT

    bogus = struct.pack("<Q", MAX_FRAME_COUNT + 1)
    server, port = await _malicious_server(bogus)
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        comm = TCP(reader, writer, "tcp://local", "tcp://peer")
        with pytest.raises(CommClosedError, match="bad frame count"):
            await comm.read()
    finally:
        server.close()


@pytest.mark.parametrize("scheme", ["tcp", "ws"])
def test_cancelled_idle_read_leaves_comm_usable(scheme):
    """Teardown paths cancel pending reads on comms they then close in
    an orderly way: a cancel while idle-waiting at a message boundary
    (readexactly is all-or-nothing) must NOT abort the comm — only a
    cancel once header bytes are consumed desyncs the stream."""

    @gen_test()
    async def run():
        listener = await _echo_listener(scheme)
        comm = await connect(listener.contact_address)
        try:
            reader = asyncio.ensure_future(comm.read())
            await asyncio.sleep(0.05)  # parked on the idle header wait
            reader.cancel()
            with pytest.raises(asyncio.CancelledError):
                await reader
            await comm.write({"op": "ping", "n": 42})
            out = await comm.read()
            assert out["n"] == 42
        finally:
            await comm.close()
            listener.stop()

    run()


@gen_test()
async def test_unexpected_acquire_error_aborts_comm():
    """MemoryError from the pool acquire (a legitimate under-cap message
    on a memory-tight process) escapes the CommClosedError/OSError arms;
    the header is already consumed, so the comm must abort — a later
    read would parse payload bytes as a frame count."""
    from distributed_tpu.comm.tcp import TCP
    from distributed_tpu.protocol.buffers import recv_pool

    bogus = struct.pack("<Q", 1) + struct.pack("<Q", 4096) + b"x" * 4096
    server, port = await _malicious_server(bogus)
    pool = recv_pool()

    def boom(n):
        raise MemoryError(f"cannot allocate {n}")

    orig, pool.acquire = pool.acquire, boom
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        comm = TCP(reader, writer, "tcp://local", "tcp://peer")
        with pytest.raises(MemoryError):
            await comm.read()
        assert comm._closed  # aborted, not merely at_eof
    finally:
        pool.acquire = orig
        server.close()


@gen_test()
async def test_truncated_payload_raises_comm_closed():
    """Header promises more bytes than the peer ever sends: the pooled
    readinto path must surface CommClosedError, not hang or mis-frame."""
    from distributed_tpu.comm.tcp import TCP

    bogus = struct.pack("<Q", 1) + struct.pack("<Q", 4096) + b"x" * 100
    server, port = await _malicious_server(bogus)
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        comm = TCP(reader, writer, "tcp://local", "tcp://peer")
        with pytest.raises(CommClosedError, match="read failed"):
            await comm.read()
    finally:
        server.close()


def test_scatter_frames_never_mutates_caller_bytearray():
    """Coalescing small frames must only extend scratch buffers
    scatter_frames itself created: a large caller-owned bytearray frame
    sits in the scatter list as-is, and a following small frame must
    not be appended INTO it."""
    from distributed_tpu.comm.tcp import COALESCE_MAX, scatter_frames

    big = bytearray(b"x" * (COALESCE_MAX + 1))
    small = b"tail"
    n_before = len(big)
    bufs, total = scatter_frames([big, small])
    assert len(big) == n_before, "caller-owned frame was mutated"
    assert total == 8 + 2 * 8 + len(big) + len(small)
    assert sum(len(b) for b in bufs) == total
    assert b"".join(bytes(b) for b in bufs).endswith(b"x" * 5 + b"tail")


@gen_test()
async def test_ws_control_frame_length_capped():
    """RFC 6455 caps control payloads at 125 bytes: a hostile ping
    header advertising an extended length must raise, not allocate."""
    from distributed_tpu.comm.ws import _read_ws_message

    reader = asyncio.StreamReader()
    reader.feed_data(bytes([0x89, 127]) + struct.pack(">Q", 1 << 40))
    with pytest.raises(CommClosedError, match="control frame"):
        await asyncio.wait_for(_read_ws_message(reader), timeout=5)


@gen_test()
async def test_ws_corrupt_preamble_rejected():
    """A well-formed ws frame whose payload preamble is garbage must
    surface as CommClosedError (orderly disconnect, same as the tcp
    guards), not a raw struct.error, and a bogus frame count is capped
    before the lengths unpack."""
    from distributed_tpu.comm.ws import WS

    async def read_with(payload):
        reader = asyncio.StreamReader()
        reader.feed_data(bytes([0x82, len(payload)]) + payload)
        comm = WS(reader, None, "ws://local", "ws://peer", is_client=False)
        return await asyncio.wait_for(comm.read(), timeout=5)

    with pytest.raises(CommClosedError, match="bad frame count"):
        await read_with(struct.pack("<Q", 1 << 40))
    with pytest.raises(CommClosedError, match="corrupt preamble"):
        await read_with(b"\x01\x02\x03")  # too short for the u64 count


def test_cloudpickle_fallback_drops_stale_oob_buffers():
    """Plain pickle can emit out-of-band buffers for early objects and
    THEN raise on an unpicklable one (lambda): the stale buffers must
    not reach the caller's frame list, or every out-of-band payload
    after them shifts at load time — silent corruption."""
    pytest.importorskip("cloudpickle")
    # a buffer-bearing object BEFORE the lambda (its buffer goes stale
    # when plain pickle raises) and one AFTER, same size/dtype so the
    # stale-shift manifests as wrong DATA, not a length error
    arr_a = np.arange(1000, dtype=np.float64)
    arr_b = np.arange(1000, dtype=np.float64) * -1.0
    fn = lambda: 1  # noqa: E731 - the unpicklable-by-plain-pickle leaf
    frames = dumps({"op": "x", "data": Serialize(("x", arr_a, fn, arr_b))})
    out = loads(frames)
    tag, a2, fn2, b2 = out["data"]
    assert tag == "x"
    np.testing.assert_array_equal(a2, arr_a)
    np.testing.assert_array_equal(b2, arr_b)
    assert fn2() == 1


def test_compact_frames_releases_receive_buffer():
    """A long-lived Serialized (e.g. a scheduler run_spec) must stop
    pinning the pooled receive buffer it was carved from: compaction
    copies view frames to owned bytes and drops the export."""
    from distributed_tpu.protocol.serialize import compact_frames

    buf = bytearray(8192)
    s = Serialized({"serializer": "pickle"}, [memoryview(buf)[100:200]])
    with pytest.raises(BufferError):
        buf.append(0)  # the view pins the buffer
    compact_frames(s)
    assert all(isinstance(f, bytes) for f in s.frames)
    assert len(s.frames[0]) == 100
    buf.append(0)  # no exports left: the pool could take this back
    # non-wrappers pass through untouched
    assert compact_frames(123) == 123


@gen_test()
async def test_readinto_exactly_raises_stored_exception():
    """A connection error recorded while no waiter is pending
    (``set_exception`` with an empty buffer and ``_eof`` unset) must
    raise out of ``readinto_exactly`` immediately — ``_wait_for_data``
    has no exception check, so waiting would hang forever."""
    from distributed_tpu.comm.tcp import readinto_exactly

    reader = asyncio.StreamReader()
    reader.set_exception(ConnectionResetError("peer RST mid-message"))
    with pytest.raises(ConnectionResetError):
        await asyncio.wait_for(
            readinto_exactly(reader, memoryview(bytearray(16))), timeout=5
        )


@gen_test()
async def test_ws_message_size_cap():
    """The ws backend honours comm.max-message-bytes on its fragment
    accounting too."""
    listener = await _echo_listener("ws")
    comm = await connect(listener.contact_address)
    try:
        with config.set({"comm.max-message-bytes": "1KiB"}):
            blob = np.zeros(1_000_000, dtype=np.uint8)
            with pytest.raises(CommClosedError):
                # the server aborts on its oversized read; depending on
                # timing the client sees it on its write or its read
                await comm.write({"data": Serialize(blob)})
                await comm.read()
    finally:
        await comm.close()
        listener.stop()


# ----------------------------------------------------------- metrics


def test_wire_metrics_exposition():
    from distributed_tpu.http.server import wire_metric_lines

    text = "\n".join(wire_metric_lines())
    for name in (
        "dtpu_wire_bytes_sent_total",
        "dtpu_wire_bytes_recv_total",
        "dtpu_wire_payload_copies_total",
        "dtpu_wire_pool_hits_total",
        "dtpu_wire_pool_misses_total",
        "dtpu_wire_pool_bytes",
    ):
        assert name in text
