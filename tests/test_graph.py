import operator

import pytest

from distributed_tpu.graph import Graph, TaskRef, TaskSpec, order, validate_order


def test_taskspec_dependencies():
    spec = TaskSpec(operator.add, (TaskRef("x"), 1))
    assert spec.dependencies() == {"x"}
    spec = TaskSpec(sum, ([TaskRef("a"), TaskRef("b")],), {"start": TaskRef("c")})
    assert spec.dependencies() == {"a", "b", "c"}


def test_taskspec_substitute():
    spec = TaskSpec(operator.add, (TaskRef("x"), 10))
    fn, args, kwargs = spec.substitute({"x": 32})
    assert fn(*args, **kwargs) == 42


def test_graph_build_and_validate():
    g = Graph()
    g["a"] = 1
    g["b"] = TaskSpec(operator.add, (TaskRef("a"), 1))
    k = g.add(operator.mul, TaskRef("b"), 3)
    g.validate()
    deps = g.dependencies()
    assert deps["b"] == {"a"}
    assert deps[k] == {"b"}


def test_graph_missing_dep():
    g = Graph({"b": TaskSpec(operator.add, (TaskRef("zzz"), 1))})
    with pytest.raises(ValueError, match="missing"):
        g.validate()


def test_graph_cycle():
    g = Graph(
        {
            "a": TaskSpec(operator.neg, (TaskRef("b"),)),
            "b": TaskSpec(operator.neg, (TaskRef("a"),)),
        }
    )
    with pytest.raises(ValueError, match="cycle"):
        g.validate()


def test_order_linear_chain():
    deps = {"a": set(), "b": {"a"}, "c": {"b"}, "d": {"c"}}
    ranks = order(deps)
    validate_order(deps, ranks)
    assert ranks["a"] < ranks["b"] < ranks["c"] < ranks["d"]


def test_order_diamond():
    deps = {"a": set(), "b": {"a"}, "c": {"a"}, "d": {"b", "c"}}
    ranks = order(deps)
    validate_order(deps, ranks)


def test_order_depth_first_reduction():
    # map-reduce tree: order should complete one branch before starting another
    deps = {
        "x0": set(), "x1": set(), "x2": set(), "x3": set(),
        "s0": {"x0", "x1"}, "s1": {"x2", "x3"},
        "total": {"s0", "s1"},
    }
    ranks = order(deps)
    validate_order(deps, ranks)
    # one full branch (both leaves + its sum) finishes before the other starts
    b0 = max(ranks["x0"], ranks["x1"], ranks["s0"])
    b1 = max(ranks["x2"], ranks["x3"], ranks["s1"])
    lo0 = min(ranks["x0"], ranks["x1"], ranks["s0"])
    lo1 = min(ranks["x2"], ranks["x3"], ranks["s1"])
    assert b0 < lo1 or b1 < lo0


def test_order_independent_components_dont_interleave():
    deps = {}
    for comp in ("l", "r"):
        deps[f"{comp}0"] = set()
        deps[f"{comp}1"] = {f"{comp}0"}
        deps[f"{comp}2"] = {f"{comp}1"}
    ranks = order(deps)
    validate_order(deps, ranks)
    left = [ranks[f"l{i}"] for i in range(3)]
    right = [ranks[f"r{i}"] for i in range(3)]
    assert max(left) < min(right) or max(right) < min(left)


def test_order_cycle_detection():
    deps = {"a": {"b"}, "b": {"a"}}
    with pytest.raises(ValueError, match="cycle"):
        order(deps)


def test_order_large_random():
    import random

    rng = random.Random(0)
    deps = {"k0": set()}
    keys = ["k0"]
    for i in range(1, 2000):
        k = f"k{i}"
        nd = rng.randint(0, min(3, len(keys)))
        deps[k] = set(rng.sample(keys, nd))
        keys.append(k)
    ranks = order(deps)
    validate_order(deps, ranks)
