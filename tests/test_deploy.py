"""Deploy layer tests: SpecCluster, Adaptive, CLI (reference deploy/tests,
cli/tests patterns)."""

from __future__ import annotations

import asyncio
import os
import signal
import subprocess
import sys

import pytest

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.spec import Adaptive, SpecCluster
from distributed_tpu.scheduler.server import Scheduler
from distributed_tpu.worker.server import Worker

from conftest import gen_test

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI_ENV = {
    **os.environ,
    "PYTHONPATH": REPO,
    "JAX_PLATFORMS": "cpu",
    "JAX_NUM_CPU_DEVICES": "1",
}


@gen_test()
async def test_spec_cluster_reconciles():
    async with SpecCluster(
        workers={
            "a": {"cls": Worker, "options": {"nthreads": 1, "listen_addr": "inproc://"}},
            "b": {"cls": Worker, "options": {"nthreads": 1, "listen_addr": "inproc://"}},
        },
        scheduler={"cls": Scheduler, "options": {"listen_addr": "inproc://",
                                                 "validate": True}},
        worker={"cls": Worker, "options": {"nthreads": 1, "listen_addr": "inproc://"}},
    ) as cluster:
        assert sorted(cluster.workers) == ["a", "b"]
        async with Client(cluster.scheduler_address) as c:
            futs = c.map(lambda x: x + 1, range(8))
            assert await c.gather(futs) == list(range(1, 9))
        # scale up then down through the spec
        await cluster.scale(4)
        assert len(cluster.workers) == 4
        assert len(cluster.scheduler.state.workers) == 4
        await cluster.scale(1)
        assert len(cluster.workers) == 1
        for _ in range(100):
            if len(cluster.scheduler.state.workers) == 1:
                break
            await asyncio.sleep(0.02)
        assert len(cluster.scheduler.state.workers) == 1


@gen_test()
async def test_adaptive_scales_up_and_down():
    import time as _t

    adaptive = Adaptive(minimum=1, maximum=4, interval=0.05, wait_count=2,
                        target_duration=0.5)
    async with SpecCluster(
        workers={},
        scheduler={"cls": Scheduler, "options": {"listen_addr": "inproc://"}},
        worker={"cls": Worker, "options": {"nthreads": 1, "listen_addr": "inproc://"}},
        adaptive=adaptive,
    ) as cluster:
        async with Client(cluster.scheduler_address) as c:
            # queue slow work: adaptive must scale up from 0
            futs = c.map(lambda x: (_t.sleep(0.2), x)[1], range(8), pure=False)
            for _ in range(200):
                if len(cluster.workers) >= 2:
                    break
                await asyncio.sleep(0.05)
            assert len(cluster.workers) >= 2
            assert await asyncio.wait_for(c.gather(futs), 30) == list(range(8))
        # idle: must shrink to minimum
        for _ in range(200):
            if len(cluster.workers) <= 1:
                break
            await asyncio.sleep(0.05)
        assert len(cluster.workers) <= 1
        assert any(entry[0] == "up" for entry in adaptive.log)


@pytest.mark.slow
def test_cli_scheduler_and_worker_roundtrip():
    """Spawn real dtpu-scheduler / dtpu-worker processes (reference
    cli/tests)."""
    sched = subprocess.Popen(
        [sys.executable, "-m", "distributed_tpu.cli.scheduler", "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=CLI_ENV, cwd=REPO,
    )
    worker = None
    try:
        line = sched.stdout.readline()
        assert line.startswith("Scheduler at:"), line
        address = line.split()[-1]
        worker = subprocess.Popen(
            [sys.executable, "-m", "distributed_tpu.cli.worker", address,
             "--nthreads", "1"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            env=CLI_ENV, cwd=REPO,
        )
        wline = worker.stdout.readline()
        assert wline.startswith("Worker at:"), wline

        async def drive():
            async with Client(address) as c:
                fut = c.submit(lambda x: x * 7, 6)
                return await asyncio.wait_for(fut.result(), 30)

        assert asyncio.run(drive()) == 42
    finally:
        for proc in (worker, sched):
            if proc is not None:
                proc.send_signal(signal.SIGTERM)
        for proc in (worker, sched):
            if proc is not None:
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()


@pytest.mark.slow
def test_cli_scheduler_jupyter():
    """--jupyter runs a lifecycle-tied Jupyter server next to the
    scheduler (reference scheduler.py:3663 --jupyter flag)."""
    import time
    import urllib.error
    import urllib.request

    pytest.importorskip("jupyter_server")
    import socket

    with socket.socket() as s:  # a free port, not a hardcoded one
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    def up():
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/status", timeout=2
            )
            return True
        except urllib.error.HTTPError:
            return True  # 403 = alive, auth required
        except Exception:
            return False

    sched = subprocess.Popen(
        [sys.executable, "-m", "distributed_tpu.cli.scheduler", "--port", "0",
         "--jupyter", "--jupyter-port", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=CLI_ENV, cwd=REPO,
    )
    try:
        line = sched.stdout.readline()
        assert line.startswith("Scheduler at:"), line
        assert sched.stdout.readline().startswith("Jupyter at:")
        deadline = time.time() + 60
        while time.time() < deadline and not up():
            time.sleep(1)
        assert up(), "jupyter server never came up"
    finally:
        sched.send_signal(signal.SIGTERM)
        try:
            sched.wait(timeout=15)
        except subprocess.TimeoutExpired:
            sched.kill()
    time.sleep(1)
    assert not up(), "jupyter survived scheduler shutdown"


@pytest.mark.slow
def test_cli_version():
    out = subprocess.run(
        [sys.executable, "-m", "distributed_tpu.cli.scheduler", "--version"],
        capture_output=True, text=True, env=CLI_ENV, cwd=REPO,
    )
    assert out.returncode == 0
    assert out.stdout.strip()


@pytest.mark.slow
def test_cli_spec_spawns_worker_from_json():
    """dtpu-spec: run a Worker from a JSON spec against a live scheduler
    (reference cli/dask_spec.py)."""
    import json

    sched = subprocess.Popen(
        [sys.executable, "-m", "distributed_tpu.cli.scheduler", "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=CLI_ENV, cwd=REPO,
    )
    worker = None
    try:
        line = sched.stdout.readline()
        assert line.startswith("Scheduler at:"), line
        address = line.split()[-1]
        spec = json.dumps({
            "cls": "distributed_tpu.worker.server.Worker",
            "opts": {"nthreads": 2, "name": "spec-w"},
        })
        worker = subprocess.Popen(
            [sys.executable, "-m", "distributed_tpu.cli.spec",
             "--spec", spec, address],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            env=CLI_ENV, cwd=REPO,
        )
        wline = worker.stdout.readline()
        assert wline.startswith("Server at:"), wline

        async def drive():
            async with Client(address) as c:
                info = await c.scheduler_info()
                assert any(
                    w.get("name") == "spec-w" for w in info["workers"].values()
                )
                return await asyncio.wait_for(
                    c.submit(lambda x: x - 4, 46).result(), 30
                )

        assert asyncio.run(drive()) == 42
    finally:
        for proc in (worker, sched):
            if proc is not None:
                proc.send_signal(signal.SIGTERM)
        for proc in (worker, sched):
            if proc is not None:
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()


@pytest.mark.slow
def test_sync_client_facade():
    """Client(asynchronous=False): the blocking facade drives submit/
    map/scatter/gather from a plain script with no event loop
    (reference SyncMethodMixin semantics)."""
    sched = subprocess.Popen(
        [sys.executable, "-m", "distributed_tpu.cli.scheduler", "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=CLI_ENV, cwd=REPO,
    )
    worker = None
    try:
        line = sched.stdout.readline()
        assert line.startswith("Scheduler at:"), line
        address = line.split()[-1]
        worker = subprocess.Popen(
            [sys.executable, "-m", "distributed_tpu.cli.worker", address,
             "--nthreads", "2"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            env=CLI_ENV, cwd=REPO,
        )
        assert worker.stdout.readline().startswith("Worker at:")

        with Client(address, asynchronous=False) as c:
            fut = c.submit(lambda x: x * 2, 21)
            assert c.result_sync(fut) == 42
            futs = c.map(lambda x: x + 1, range(10))
            assert c.gather_sync(futs) == list(range(1, 11))
            [x] = c.scatter_sync([5])
            assert c.result_sync(c.submit(lambda v: v + 1, x)) == 6
    finally:
        for proc in (worker, sched):
            if proc is not None:
                proc.send_signal(signal.SIGTERM)
        for proc in (worker, sched):
            if proc is not None:
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
