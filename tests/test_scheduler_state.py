"""Tier-1 deterministic tests of the scheduler state machine (no IO).

Mirrors the reference's pure state-machine test strategy (SURVEY.md §4): drive
SchedulerState with synthetic stimuli, assert on returned messages, run
validate_state() as the oracle after every step.
"""

from __future__ import annotations

import pytest

from distributed_tpu.exceptions import KilledWorker
from distributed_tpu.graph import Graph, TaskRef, TaskSpec
from distributed_tpu.scheduler.state import SchedulerState


class Sim:
    """Simulate a cluster around a SchedulerState: collect compute-task
    messages per worker and let the test 'finish' them."""

    def __init__(self, nworkers: int = 2, nthreads: int = 1, **kwargs):
        kwargs.setdefault("validate", True)
        kwargs.setdefault("transition_counter_max", 50_000)
        self.state = SchedulerState(**kwargs)
        self.inbox: dict[str, list[dict]] = {}
        self.client_inbox: dict[str, list[dict]] = {}
        self.addrs = []
        for i in range(nworkers):
            addr = f"tcp://127.0.0.1:{10000 + i}"
            self.addrs.append(addr)
            ws = self.state.add_worker_state(
                addr, nthreads=nthreads, memory_limit=2**30, name=f"w{i}"
            )
            self.state.check_idle_saturated(ws)

    def submit_graph(self, g: Graph, keys, client="client-1", **kwargs):
        g.validate()
        deps = g.dependencies()
        cmsgs, wmsgs = self.state.update_graph_core(
            dict(g.tasks), deps, list(keys), client=client, **kwargs
        )
        self._route(cmsgs, wmsgs)
        self.state.validate_state()

    def _route(self, cmsgs, wmsgs):
        for addr, msgs in wmsgs.items():
            self.inbox.setdefault(addr, []).extend(msgs)
        for client, msgs in cmsgs.items():
            self.client_inbox.setdefault(client, []).extend(msgs)

    def pending_computes(self, addr=None):
        out = []
        for a, msgs in self.inbox.items():
            if addr is not None and a != addr:
                continue
            for m in msgs:
                if m["op"] == "compute-task":
                    out.append((a, m))
        return out

    def finish(self, addr, key, nbytes=8, duration=0.01):
        """Simulate worker `addr` completing `key`."""
        # drop the compute msg from the inbox
        self.inbox[addr] = [
            m for m in self.inbox.get(addr, []) if not (m["op"] == "compute-task" and m["key"] == key)
        ]
        cmsgs, wmsgs = self.state.stimulus_task_finished(
            key,
            addr,
            "sim-finish",
            nbytes=nbytes,
            typename="int",
            startstops=[{"action": "compute", "start": 0.0, "stop": duration}],
        )
        self._route(cmsgs, wmsgs)
        self.state.validate_state()

    def fail(self, addr, key, exc=None):
        cmsgs, wmsgs = self.state.stimulus_task_erred(
            key,
            addr,
            "sim-err",
            exception=exc or ValueError("boom"),
            exception_text="boom",
        )
        self._route(cmsgs, wmsgs)
        self.state.validate_state()

    def run_to_completion(self, max_steps=100_000):
        """Greedily execute every pending compute message."""
        steps = 0
        while True:
            pending = self.pending_computes()
            if not pending:
                break
            addr, msg = pending[0]
            self.finish(addr, msg["key"])
            steps += 1
            assert steps < max_steps, "simulation did not converge"

    def client_reports(self, client="client-1", op=None):
        msgs = self.client_inbox.get(client, [])
        if op:
            msgs = [m for m in msgs if m["op"] == op]
        return msgs


def linear_graph(n=4):
    g = Graph()
    g["t0"] = TaskSpec(lambda: 1)
    for i in range(1, n):
        g[f"t{i}"] = TaskSpec(lambda x: x + 1, (TaskRef(f"t{i-1}"),))
    return g


def test_single_task_lifecycle():
    sim = Sim(nworkers=1)
    g = Graph({"x": TaskSpec(lambda: 42)})
    sim.submit_graph(g, ["x"])
    ts = sim.state.tasks["x"]
    assert ts.state == "processing"
    pending = sim.pending_computes()
    assert len(pending) == 1
    addr, msg = pending[0]
    assert msg["key"] == "x"
    assert msg["priority"] is not None
    sim.finish(addr, "x", nbytes=100)
    assert ts.state == "memory"
    assert ts.nbytes == 100
    assert [m["op"] for m in sim.client_reports()] == ["key-in-memory"]


def test_linear_chain_executes_in_order():
    sim = Sim(nworkers=2)
    sim.submit_graph(linear_graph(4), ["t3"])
    # only the root is runnable
    assert sim.state.tasks["t0"].state == "processing"
    assert sim.state.tasks["t1"].state == "waiting"
    sim.run_to_completion()
    assert sim.state.tasks["t3"].state == "memory"
    # intermediates released once consumed (only t3 is wanted)
    for k in ("t0", "t1", "t2"):
        assert sim.state.tasks[k].state in ("released", "forgotten"), k


def test_diamond_dependencies():
    g = Graph()
    g["a"] = TaskSpec(lambda: 1)
    g["b"] = TaskSpec(lambda x: x + 1, (TaskRef("a"),))
    g["c"] = TaskSpec(lambda x: x * 2, (TaskRef("a"),))
    g["d"] = TaskSpec(lambda x, y: x + y, (TaskRef("b"), TaskRef("c")))
    sim = Sim(nworkers=2)
    sim.submit_graph(g, ["d"])
    sim.run_to_completion()
    assert sim.state.tasks["d"].state == "memory"
    reports = sim.client_reports(op="key-in-memory")
    assert [m["key"] for m in reports] == ["d"]


def test_data_locality_placement():
    """Non-rootish tasks go where their (large) dependencies live."""
    sim = Sim(nworkers=2)
    g = Graph()
    g["big"] = TaskSpec(lambda: b"x")
    g["consume"] = TaskSpec(lambda x: len(x), (TaskRef("big"),))
    sim.submit_graph(g, ["consume"])
    (addr, _), = sim.pending_computes()
    sim.finish(addr, "big", nbytes=10_000_000)
    ts = sim.state.tasks["consume"]
    assert ts.state == "processing"
    assert ts.processing_on.address == addr  # placed on the data


def test_fanout_spreads_across_workers():
    """A wide embarrassingly-parallel map should use all workers."""
    sim = Sim(nworkers=4, nthreads=2)
    g = Graph()
    for i in range(64):
        g[f"task-{i}"] = TaskSpec(lambda i=i: i)
    sim.submit_graph(g, list(g.tasks))
    # with queuing: exactly ceil(2*1.1)=3 slots per worker processing
    processing_per_worker = {
        addr: len(sim.state.workers[addr].processing) for addr in sim.addrs
    }
    assert all(v > 0 for v in processing_per_worker.values()), processing_per_worker
    assert len(sim.state.queued) == 64 - sum(processing_per_worker.values())
    sim.run_to_completion()
    assert all(sim.state.tasks[k].state == "memory" for k in g.tasks)
    assert len(sim.state.queued) == 0


def test_queued_tasks_flow_as_slots_open():
    sim = Sim(nworkers=1, nthreads=1)
    g = Graph()
    for i in range(10):
        g[f"t-{i}"] = TaskSpec(lambda i=i: i)
    sim.submit_graph(g, list(g.tasks))
    # saturation 1.1 * 1 thread -> ceil = 2 in processing
    assert sum(1 for t in sim.state.tasks.values() if t.state == "processing") == 2
    assert len(sim.state.queued) == 8
    sim.run_to_completion()
    assert all(t.state == "memory" for t in sim.state.tasks.values())


def test_error_propagates_to_dependents():
    sim = Sim(nworkers=1)
    g = linear_graph(3)
    sim.submit_graph(g, ["t2"])
    sim.fail(sim.addrs[0], "t0")
    assert sim.state.tasks["t0"].state == "erred"
    assert sim.state.tasks["t1"].state == "erred"
    assert sim.state.tasks["t2"].state == "erred"
    errs = sim.client_reports(op="task-erred")
    assert any(m["key"] == "t2" for m in errs)


def test_retries_rerun_task():
    sim = Sim(nworkers=1)
    g = Graph({"flaky": TaskSpec(lambda: 1)})
    sim.submit_graph(g, ["flaky"], retries=1)
    sim.fail(sim.addrs[0], "flaky")
    ts = sim.state.tasks["flaky"]
    assert ts.state == "processing"  # rescheduled
    assert ts.retries == 0
    sim.finish(sim.addrs[0], "flaky")
    assert ts.state == "memory"


def test_stimulus_retry_after_err():
    sim = Sim(nworkers=1)
    g = linear_graph(2)
    sim.submit_graph(g, ["t1"])
    sim.fail(sim.addrs[0], "t0")
    assert sim.state.tasks["t1"].state == "erred"
    cmsgs, wmsgs = sim.state.stimulus_retry(["t1"], "retry-1")
    sim._route(cmsgs, wmsgs)
    sim.state.validate_state()
    assert sim.state.tasks["t0"].state == "processing"
    sim.run_to_completion()
    assert sim.state.tasks["t1"].state == "memory"


def test_worker_loss_recomputes_lineage():
    """Lineage-based recomputation: losing the only replica reruns tasks."""
    sim = Sim(nworkers=2)
    g = linear_graph(3)
    sim.submit_graph(g, ["t2"])
    # run t0 and t1, then kill the worker holding their outputs
    sim.run_to_completion()
    assert sim.state.tasks["t2"].state == "memory"
    holder = next(iter(sim.state.tasks["t2"].who_has))
    cmsgs, wmsgs = sim.state.remove_worker_state(
        holder.address, stimulus_id="sim-remove"
    )
    sim._route(cmsgs, wmsgs)
    sim.state.validate_state()
    ts = sim.state.tasks["t2"]
    # t2 must be recomputed from lineage on the remaining worker
    assert ts.state in ("processing", "waiting")
    assert any(m["op"] == "lost-data" for m in sim.client_reports())
    sim.run_to_completion()
    assert ts.state == "memory"


def test_killed_worker_after_allowed_failures():
    sim = Sim(nworkers=4)
    g = Graph({"poison": TaskSpec(lambda: 1)})
    sim.submit_graph(g, ["poison"])
    for round_ in range(sim.state.ALLOWED_FAILURES + 1):
        ts = sim.state.tasks["poison"]
        assert ts.state == "processing", round_
        addr = ts.processing_on.address
        cmsgs, wmsgs = sim.state.remove_worker_state(addr, stimulus_id=f"kill-{round_}")
        sim._route(cmsgs, wmsgs)
        sim.state.validate_state()
    ts = sim.state.tasks["poison"]
    assert ts.state == "erred"
    assert isinstance(ts.exception, KilledWorker)


def test_client_release_forgets_chain():
    sim = Sim(nworkers=1)
    g = linear_graph(3)
    sim.submit_graph(g, ["t2"])
    sim.run_to_completion()
    cmsgs, wmsgs = sim.state.client_releases_keys(["t2"], "client-1", "rel-1")
    sim._route(cmsgs, wmsgs)
    assert sim.state.tasks == {}  # whole chain forgotten
    # worker told to free the data
    frees = [m for m in sim.inbox[sim.addrs[0]] if m["op"] == "free-keys"]
    assert any("t2" in m["keys"] for m in frees)


def test_no_worker_tasks_schedule_on_join():
    sim = Sim(nworkers=0)
    g = Graph({"x": TaskSpec(lambda: 1)})
    sim.submit_graph(g, ["x"])
    assert sim.state.tasks["x"].state == "no-worker"
    ws = sim.state.add_worker_state("tcp://127.0.0.1:20000", nthreads=1)
    recs = sim.state.bulk_schedule_unrunnable_after_adding_worker(ws)
    cmsgs, wmsgs = sim.state.transitions(recs, "join-1")
    sim._route(cmsgs, wmsgs)
    sim.state.validate_state()
    assert sim.state.tasks["x"].state == "processing"
    sim.addrs.append(ws.address)
    sim.finish(ws.address, "x")
    assert sim.state.tasks["x"].state == "memory"


def test_worker_restrictions():
    sim = Sim(nworkers=3)
    g = Graph({"pinned": TaskSpec(lambda: 1)})
    target = sim.addrs[2]
    sim.submit_graph(
        g, ["pinned"], annotations_by_key={"pinned": {"workers": [target]}}
    )
    ts = sim.state.tasks["pinned"]
    assert ts.state == "processing"
    assert ts.processing_on.address == target


def test_resource_restrictions():
    sim = Sim(nworkers=2)
    # only worker 1 has the GPU resource
    ws1 = sim.state.workers[sim.addrs[1]]
    ws1.resources["GPU"] = 1
    ws1.used_resources["GPU"] = 0
    sim.state.resources["GPU"][sim.addrs[1]] = 1
    g = Graph({"gpu-task": TaskSpec(lambda: 1)})
    sim.submit_graph(
        g, ["gpu-task"], annotations_by_key={"gpu-task": {"resources": {"GPU": 1}}}
    )
    ts = sim.state.tasks["gpu-task"]
    assert ts.processing_on.address == sim.addrs[1]
    assert ws1.used_resources["GPU"] == 1
    sim.finish(sim.addrs[1], "gpu-task")
    assert ws1.used_resources["GPU"] == 0


def test_rootish_coassignment_without_queuing():
    """With queuing disabled, sibling root tasks batch onto the same worker."""
    from distributed_tpu import config

    with config.set({"scheduler.worker-saturation": "inf"}):
        sim = Sim(nworkers=4)
        g = Graph()
        for i in range(40):
            g[f"root-{i}"] = TaskSpec(lambda i=i: i)
        sim.submit_graph(g, list(g.tasks))
        per_worker = [len(ws.processing) for ws in sim.state.workers.values()]
        # all processing immediately (no queue), roughly balanced blocks
        assert sum(per_worker) == 40
        assert len(sim.state.queued) == 0
        assert max(per_worker) <= 40  # sanity
        sim.run_to_completion()


def test_transition_log_and_story():
    sim = Sim(nworkers=1)
    g = Graph({"x": TaskSpec(lambda: 1)})
    sim.submit_graph(g, ["x"])
    sim.finish(sim.addrs[0], "x")
    story = sim.state.story("x")
    transitions = [(t[1], t[2]) for t in story]
    assert ("released", "waiting") in transitions
    assert ("waiting", "processing") in transitions
    assert ("processing", "memory") in transitions


def test_duration_learning():
    sim = Sim(nworkers=1)
    g = Graph({"inc-1": TaskSpec(lambda: 1), "inc-2": TaskSpec(lambda: 2)})
    sim.submit_graph(g, list(g.tasks))
    sim.finish(sim.addrs[0], "inc-1", duration=2.0)
    prefix = sim.state.task_prefixes["inc"]
    assert prefix.duration_average == pytest.approx(2.0)
    sim.finish(sim.addrs[0], "inc-2", duration=1.0)
    assert prefix.duration_average == pytest.approx(1.5)


def test_occupancy_tracking():
    sim = Sim(nworkers=2)
    g = Graph()
    for i in range(4):
        g[f"t-{i}"] = TaskSpec(lambda: 1)
    sim.submit_graph(g, list(g.tasks))
    assert sim.state.total_occupancy > 0
    sim.run_to_completion()
    assert sim.state.total_occupancy == pytest.approx(0.0, abs=1e-9)


def test_placement_reroutes_on_vanished_replica():
    """A dependency's last replica vanishes between the transition that
    recommended a task to processing and the placement itself (worker death
    race).  Production mode must reroute the dep through released→recompute
    instead of crashing (reference scheduler.py:2247-2250 guards the invariant
    behind validate)."""
    sim = Sim(nworkers=2, validate=False)
    g = Graph()
    g["a"] = TaskSpec(lambda: 1)
    g["b"] = TaskSpec(lambda x: x + 1, (TaskRef("a"),))
    sim.submit_graph(g, ["b"])
    st = sim.state
    addr_a = st.tasks["a"].processing_on.address
    sim.finish(addr_a, "a")
    ta, tb = st.tasks["a"], st.tasks["b"]
    assert ta.state == "memory" and tb.state == "processing"

    # reproduce the race: a's replicas vanish while its state is still memory
    for ws in list(ta.who_has):
        st.remove_replica(ta, ws)
    assert not ta.who_has and ta.state == "memory"

    # winding b back through released triggers waiting -> processing placement
    # against the inconsistent state; must not raise
    cmsgs, wmsgs = st.transitions({"b": "released"}, "test-race")
    sim._route(cmsgs, wmsgs)

    # b parked in waiting on a; a recommended for recompute
    assert tb.state == "waiting"
    assert ta in tb.waiting_on
    assert ta.state == "processing"

    # the recompute converges and b completes
    sim.run_to_completion()
    assert tb.state == "memory"
    assert "key-in-memory" in [m["op"] for m in sim.client_reports()]
