"""Spans, versions handshake, performance report, workspace, hardware
bench tests (reference test_spans, test_versions patterns)."""

from __future__ import annotations

import asyncio
import os

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.local import LocalCluster
from distributed_tpu.diagnostics.spans import span
from distributed_tpu.utils.diskutils import WorkSpace

from conftest import gen_test


async def new_cluster(n_workers=2, **kwargs):
    cluster = LocalCluster(
        n_workers=n_workers,
        scheduler_kwargs={"validate": True},
        worker_kwargs={"validate": True},
        **kwargs,
    )
    await cluster._start()
    return cluster


@gen_test()
async def test_spans_aggregate_tasks():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            with span("etl"):
                futs = c.map(lambda x: x + 1, range(6), pure=False)
                await c.gather(futs)
                with span("load"):
                    f2 = c.submit(sum, futs)
                    await f2.result()
            spans = await c.get_spans()
            assert len(spans) == 1
            etl = spans[0]
            assert etl["name"] == ["etl"]
            assert etl["n_tasks"] == 6
            assert etl["states"]["memory"] >= 6
            assert etl["compute_seconds"] >= 0
            assert len(etl["children"]) == 1
            assert etl["children"][0]["name"] == ["etl", "load"]
            assert etl["children"][0]["n_tasks"] == 1


@gen_test()
async def test_untagged_tasks_have_no_span():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            await c.submit(lambda: 1).result()
            assert await c.get_spans() == []


@gen_test()
async def test_versions_handshake():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            info = await c.get_versions()
            assert info["client"]["distributed_tpu"]
            assert info["scheduler"]["python"]
            assert len(info["workers"]) == 2
            for v in info["workers"].values():
                assert v["numpy"]
            # same process everywhere: no mismatches
            assert info["mismatches"] == {}


@gen_test(timeout=90)
async def test_benchmark_hardware():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            out = await c.benchmark_hardware()
            assert len(out) == 1
            bench = next(iter(out.values()))
            assert bench["memory_copy_bps"] > 1e6
            assert bench["disk_write_bps"] > 1e5


@gen_test()
async def test_performance_report(tmp_path=None):
    import tempfile

    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            with span("report-span"):
                futs = c.map(lambda x: x * 2, range(5), pure=False)
                await c.gather(futs)
            path = os.path.join(tempfile.mkdtemp(), "report.html")
            out = await c.performance_report(path)
            html = open(out).read()
            assert "distributed_tpu performance report" in html
            assert "report-span" in html
            assert "workers" in html.lower()


def test_workspace_purges_stale_dirs(tmp_path):
    ws = WorkSpace(str(tmp_path))
    d = ws.new_work_dir(prefix="w")
    assert os.path.isdir(d.path)
    # fake a dead owner
    with open(d.path + ".lock", "w") as f:
        f.write("999999999")
    WorkSpace(str(tmp_path))  # re-scan purges it
    assert not os.path.exists(d.path)
