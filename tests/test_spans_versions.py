"""Spans, versions handshake, performance report, workspace, hardware
bench tests (reference test_spans, test_versions patterns)."""

from __future__ import annotations

import asyncio
import os

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.local import LocalCluster
from distributed_tpu.diagnostics.spans import span
from distributed_tpu.utils.diskutils import WorkSpace

from conftest import gen_test


async def new_cluster(n_workers=2, **kwargs):
    cluster = LocalCluster(
        n_workers=n_workers,
        scheduler_kwargs={"validate": True},
        worker_kwargs={"validate": True},
        **kwargs,
    )
    await cluster._start()
    return cluster


@gen_test()
async def test_spans_aggregate_tasks():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            with span("etl"):
                futs = c.map(lambda x: x + 1, range(6), pure=False)
                await c.gather(futs)
                with span("load"):
                    f2 = c.submit(sum, futs)
                    await f2.result()
            spans = await c.get_spans()
            assert len(spans) == 1
            etl = spans[0]
            assert etl["name"] == ["etl"]
            assert etl["n_tasks"] == 6
            assert etl["states"]["memory"] >= 6
            assert etl["compute_seconds"] >= 0
            assert len(etl["children"]) == 1
            assert etl["children"][0]["name"] == ["etl", "load"]
            assert etl["children"][0]["n_tasks"] == 1


@gen_test()
async def test_untagged_tasks_have_no_span():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            await c.submit(lambda: 1).result()
            assert await c.get_spans() == []


@gen_test()
async def test_versions_handshake():
    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            info = await c.get_versions()
            assert info["client"]["distributed_tpu"]
            assert info["scheduler"]["python"]
            assert len(info["workers"]) == 2
            for v in info["workers"].values():
                assert v["numpy"]
            # same process everywhere: no mismatches
            assert info["mismatches"] == {}


@gen_test(timeout=90)
async def test_benchmark_hardware():
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            out = await c.benchmark_hardware()
            assert len(out) == 1
            bench = next(iter(out.values()))
            assert bench["memory_copy_bps"] > 1e6
            assert bench["disk_write_bps"] > 1e5


@gen_test()
async def test_performance_report(tmp_path=None):
    import tempfile

    async with await new_cluster() as cluster:
        async with Client(cluster.scheduler_address) as c:
            with span("report-span"):
                futs = c.map(lambda x: x * 2, range(5), pure=False)
                await c.gather(futs)
            path = os.path.join(tempfile.mkdtemp(), "report.html")
            out = await c.performance_report(path)
            html = open(out).read()
            assert "distributed_tpu performance report" in html
            assert "report-span" in html
            assert "workers" in html.lower()


def test_workspace_purges_stale_dirs(tmp_path):
    ws = WorkSpace(str(tmp_path))
    d = ws.new_work_dir(prefix="w")
    assert os.path.isdir(d.path)
    # fake a dead owner
    with open(d.path + ".lock", "w") as f:
        f.write("999999999")
    WorkSpace(str(tmp_path))  # re-scan purges it
    assert not os.path.exists(d.path)


@gen_test(timeout=60)
async def test_fine_metrics_per_span_activity():
    """ContextMeter-style activity metering: execute seconds are
    aggregated per (span, prefix, activity) on the scheduler, and
    transfer/serve activities are metered fleet-wide
    (reference metrics.py:159,336)."""
    import time as _time

    def work(x):
        _time.sleep(0.05)
        return x + 1

    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            with span("metered"):
                futs = c.map(work, range(4), pure=False)
                await c.gather(futs)
            # force a cross-worker transfer (gather-dep + get-data)
            w0, w1 = [w.address for w in cluster.workers]
            a = c.submit(work, 10, workers=[w0], key="fm-a")
            b = c.submit(lambda v: v, a, workers=[w1], key="fm-b")
            await b.result()
            # heartbeats ship the deltas
            for w in cluster.workers:
                await w.heartbeat()
            fine = await c.scheduler.get_fine_metrics()
            assert any(
                k.startswith("execute|") and k.endswith("|compute|seconds")
                and v > 0
                for k, v in fine.items()
            ), fine
            assert any(
                k.startswith("gather-dep|") and "network|seconds" in k
                for k in fine
            ), fine
            assert any(
                k.startswith("get-data|") and "serve|bytes" in k
                for k in fine
            ), fine
            # span-attributed compute seconds
            spans = await c.get_spans()
            metered = next(s for s in spans if s["name"] == ["metered"])
            acts = metered["activity"]
            key = next(k for k in acts if k.endswith("compute|seconds"))
            assert acts[key] >= 4 * 0.05 * 0.9, acts


@gen_test(timeout=60)
async def test_context_meter_user_samples():
    """User task code can emit custom activity samples through
    context_meter; they land in the scheduler's fine metrics
    (reference metrics.py:159)."""
    def task_with_meter(x):
        import time as _time

        from distributed_tpu.worker.metrics import context_meter

        with context_meter.meter("custom-phase"):
            _time.sleep(0.02)
        context_meter.digest_metric("custom-bytes", 1234, "bytes")
        return x

    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            await c.gather(c.map(task_with_meter, range(3), pure=False))
            for w in cluster.workers:
                await w.heartbeat()
            fine = await c.scheduler.get_fine_metrics()
            assert any("custom-phase|seconds" in k and v >= 0.02
                       for k, v in fine.items()), fine
            assert any("custom-bytes|bytes" in k and v == 3 * 1234
                       for k, v in fine.items()), fine


@gen_test(timeout=120)
async def test_span_tree_cumulative_aggregation():
    """Nested spans roll up to arbitrary depth (reference spans.py
    cumulative properties): a parent span's cumulative() covers every
    task submitted under ANY descendant."""
    from distributed_tpu.diagnostics.spans import span

    async with LocalCluster(n_workers=2, threads_per_worker=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            with span("flow"):
                with span("stage-a"):
                    fa = c.map(lambda x: x + 1, range(4), pure=False)
                with span("stage-a", "inner"):
                    fi = c.map(lambda x: x * 2, range(3), pure=False)
                with span("stage-b"):
                    fb = c.map(lambda x: x - 1, range(5), pure=False)
            await asyncio.wait_for(c.gather(fa + fi + fb), 60)

            ext = cluster.scheduler.spans
            flow = ext.spans[("flow",)]
            stage_a = ext.spans[("flow", "stage-a")]
            inner = ext.spans[("flow", "stage-a", "inner")]

            assert inner.n_tasks == 3
            # direct counts stay per-node ...
            assert stage_a.n_tasks == 4
            # ... cumulative rolls descendants up, to any depth
            assert stage_a.cumulative()["n_tasks"] == 7
            cum = flow.cumulative()
            assert cum["n_tasks"] == 12
            assert cum["states"].get("memory", 0) == 12
            assert [c.name for c in flow.children] == [
                ("flow", "stage-a"), ("flow", "stage-b"),
            ]
            # the tree serializes with cumulative sections
            d = flow.to_dict()
            assert d["cumulative"]["n_tasks"] == 12
            assert d["children"][0]["cumulative"]["n_tasks"] == 7
            # spans carry the stimulus ids of the transitions that fed
            # them — the causal join key against /trace (PR 6)
            assert inner.recent_stimuli
            trace_stims = {
                ev["stim"] for ev in cluster.scheduler.trace.tail()
            }
            assert set(inner.recent_stimuli) <= trace_stims
            assert d["children"][0]["recent_stimuli"]
