"""Shuffle storage layer + fault tolerance (reference shuffle/_disk.py,
_limiter.py, _comms.py, _scheduler_plugin.py:336-344 behaviors)."""

from __future__ import annotations

import asyncio
import os
import time as _time

import numpy as np
import pytest

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.local import LocalCluster
from distributed_tpu.shuffle import p2p_merge, p2p_shuffle
from distributed_tpu.shuffle.buffers import (
    DiskShardsBuffer,
    MemoryShardsBuffer,
    ResourceLimiter,
)

from conftest import gen_test


async def new_cluster(n_workers=3, **kwargs):
    cluster = LocalCluster(
        n_workers=n_workers,
        scheduler_kwargs={"validate": True},
        worker_kwargs={"validate": True},
        **kwargs,
    )
    await cluster._start()
    return cluster


# ------------------------------------------------------------- buffers


@gen_test()
async def test_resource_limiter_blocks_until_released():
    lim = ResourceLimiter(100)
    await lim.acquire(80)
    await lim.acquire(30)  # oversized final acquire allowed through
    assert not lim.free()
    blocked = asyncio.create_task(lim.acquire(10))
    await asyncio.sleep(0.05)
    assert not blocked.done()
    lim.release(80)
    lim.release(30)
    await asyncio.wait_for(blocked, 1)
    lim.release(10)
    assert lim.acquired == 0


@gen_test()
async def test_memory_buffer_roundtrip():
    buf = MemoryShardsBuffer()
    await buf.write({1: ["a", "b"], 2: ["c"]})
    await buf.write({1: ["d"]})
    assert await buf.read(1) == ["a", "b", "d"]
    assert await buf.read(2) == ["c"]
    assert await buf.read(3) == []
    await buf.close()


@gen_test()
async def test_disk_buffer_spills_and_reads_back(tmp_path):
    buf = DiskShardsBuffer(str(tmp_path / "spill"))
    payload = np.arange(1000)
    await buf.write({0: [(0, payload)], 7: [(1, "x")]})
    await buf.write({7: [(2, "y")]})
    await buf.flush()
    # shards actually hit disk
    assert os.path.exists(str(tmp_path / "spill" / "0.shards"))
    got0 = await buf.read(0)
    assert len(got0) == 1
    np.testing.assert_array_equal(got0[0][1], payload)
    assert await buf.read(7) == [(1, "x"), (2, "y")]
    await buf.close()
    assert not os.path.exists(str(tmp_path / "spill"))


@gen_test()
async def test_disk_shards_read_back_writable(tmp_path):
    """Spilled shards must reconstruct as writable arrays: the in-band
    pickle path returned writable copies, and a consumer mutating a
    shard in place must not fail only when its partition spilled."""
    buf = DiskShardsBuffer(str(tmp_path / "spill"))
    payload = np.arange(16)
    await buf.write({0: [(0, payload)]})
    await buf.flush()
    (got,) = await buf.read(0)
    arr = got[1]
    assert arr.flags.writeable
    arr += 1
    np.testing.assert_array_equal(arr, payload + 1)
    await buf.close()


@gen_test()
async def test_disk_buffer_backpressure_still_completes(tmp_path):
    # limiter far smaller than the data: writers must block-and-drain,
    # never fail — this is the "shuffle more than memory" contract
    lim = ResourceLimiter(2_000)
    buf = DiskShardsBuffer(str(tmp_path / "spill"), limiter=lim)
    total = 0
    for i in range(50):
        shard = np.full(500, i)  # ~4KB each, 200KB total >> 2KB limit
        await buf.write({i % 5: [(i, shard)]})
        total += 1
    await buf.flush()
    assert lim.acquired == 0
    back = 0
    for j in range(5):
        back += len(await buf.read(j))
    assert back == total
    await buf.close()


# ------------------------------------------- shuffle > memory-limit e2e


def big_partition(seed, n=200):
    rng = np.random.default_rng(seed)
    return [int(x) for x in rng.integers(0, 10_000, n)]


@gen_test(timeout=120)
async def test_shuffle_larger_than_memory_limit():
    """With a tiny shard-memory budget every shard spills through disk,
    and the shuffle still completes correctly."""
    from distributed_tpu import config

    with config.set({"shuffle.memory-limit": "4kB", "shuffle.disk": True}):
        async with await new_cluster(n_workers=3) as cluster:
            async with Client(cluster.scheduler_address) as c:
                inputs = [
                    c.submit(big_partition, i, key=f"in-{i}") for i in range(6)
                ]
                await c.gather(inputs)
                outs = await p2p_shuffle(c, inputs, npartitions_out=4)
                results = await asyncio.wait_for(c.gather(outs), 60)
                all_in = sorted(
                    x for i in range(6) for x in big_partition(i)
                )
                all_out = sorted(x for part in results for x in part)
                assert all_out == all_in
                # the runs actually used the disk store
                for w in cluster.workers:
                    for run in w.shuffle.runs.values():
                        assert isinstance(run.store, DiskShardsBuffer)


# ------------------------------------------------------------- merge


def left_part(i):
    return [(k, f"L{i}-{k}") for k in range(i * 3, i * 3 + 5)]


def right_part(i):
    return [(k, f"R{i}-{k}") for k in range(i * 4, i * 4 + 5)]


@gen_test(timeout=120)
async def test_p2p_merge_inner_join():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            left = [c.submit(left_part, i, key=f"L-{i}") for i in range(3)]
            right = [c.submit(right_part, i, key=f"R-{i}") for i in range(2)]
            await c.gather(left + right)
            outs = await p2p_merge(c, left, right, npartitions_out=3)
            results = await asyncio.wait_for(c.gather(outs), 60)
            joined = [t for part in results for t in part]

            lrecs = [r for i in range(3) for r in left_part(i)]
            rrecs = [r for i in range(2) for r in right_part(i)]
            expect = {
                (lk, lr, rr)
                for lk, lr in [(r[0], r) for r in lrecs]
                for rk, rr in [(r[0], r) for r in rrecs]
                if lk == rk
            }
            assert set(joined) == expect
            # keys co-partition: every joined key lands in exactly one part
            seen_keys = [t[0] for t in joined]
            assert len(seen_keys) == len(joined)


@gen_test(timeout=120)
async def test_p2p_merge_outer_join_includes_misses():
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            left = [c.submit(lambda: [(1, "a"), (2, "b")], key="L-0")]
            right = [c.submit(lambda: [(2, "x"), (3, "y")], key="R-0")]
            await c.gather(left + right)
            outs = await p2p_merge(c, left, right, npartitions_out=2, how="outer")
            results = await asyncio.wait_for(c.gather(outs), 60)
            joined = sorted(t for part in results for t in part)
            assert joined == [
                (1, (1, "a"), None),
                (2, (2, "b"), (2, "x")),
                (3, None, (3, "y")),
            ]


# ------------------------------------------------- restart / fault tolerance


@gen_test(timeout=180)
async def test_mid_shuffle_worker_loss_restarts_with_bumped_run_id():
    """Killing a participating worker mid-shuffle bumps the run_id and
    the shuffle completes on the survivors.

    THIN integration smoke: the full worker-death recovery semantics
    (lineage recompute, replica truth, no lost keys, model-legal
    transitions) are covered deterministically by the simulator's
    chaos suite (tests/test_sim.py::test_chaos_worker_death) — this
    live test only proves the networked shuffle extension's restart
    protocol end to end, with small data and generous timeouts (the
    old 6x200-int / 90 s-in-120 s variant flaked under full-suite
    load, PR 6 tier-1 run)."""
    async with await new_cluster(n_workers=3) as cluster:
        async with Client(cluster.scheduler_address) as c:
            ext = cluster.scheduler.extensions["shuffle"]
            inputs = [
                c.submit(big_partition, i, key=f"in-{i}") for i in range(4)
            ]
            await c.gather(inputs)

            outs = await p2p_shuffle(c, inputs, npartitions_out=4)
            # wait until the shuffle is registered and has begun
            # (bounded: a wedge here must fail loudly, not eat the
            # whole gen_test budget spinning)
            for _ in range(2000):
                if ext.active:
                    break
                await asyncio.sleep(0.01)
            assert ext.active, "shuffle never registered"
            sid = next(iter(ext.active))
            victim_addr = ext.active[sid].worker_for[0]
            victim = next(
                w for w in cluster.workers if w.address == victim_addr
            )
            await victim.close()
            cluster.workers.remove(victim)

            results = await asyncio.wait_for(c.gather(outs), 150)
            assert ext.active[sid].run_id >= 2
            assert victim_addr not in set(ext.active[sid].worker_for.values())
            all_in = sorted(x for i in range(4) for x in big_partition(i))
            all_out = sorted(x for part in results for x in part)
            assert all_out == all_in


@gen_test(timeout=120)
async def test_duplicate_output_fetch_restarts_instead_of_empty():
    """A recomputed unpack whose partition was already served must
    trigger an epoch restart and yield REAL data (never a silently-empty
    partition)."""
    async with await new_cluster(n_workers=2) as cluster:
        async with Client(cluster.scheduler_address) as c:
            ext = cluster.scheduler.extensions["shuffle"]
            inputs = [
                c.submit(big_partition, i, key=f"in-{i}") for i in range(4)
            ]
            await c.gather(inputs)
            outs = await p2p_shuffle(c, inputs, npartitions_out=4)
            await asyncio.wait_for(c.gather(outs), 60)
            sid = next(iter(ext.active))
            st = ext.active[sid]
            run_before = st.run_id

            # drop partition 0's future so the scheduler forgets the
            # unpack task, then resubmit the same key — the worker-side
            # run has already served partition 0
            key0 = outs[0].key
            outs[0].release()
            for _ in range(100):
                if key0 not in cluster.scheduler.state.tasks:
                    break
                await asyncio.sleep(0.05)

            from distributed_tpu.graph.spec import TaskSpec
            from distributed_tpu.shuffle.api import shuffle_unpack

            futs = c._graph_to_futures(
                {key0: TaskSpec(shuffle_unpack, (sid, 0, run_before))},
                [key0],
            )
            part = await asyncio.wait_for(futs[key0].result(), 90)
            expect = sorted(
                x
                for i in range(4)
                for x in big_partition(i)
                if hash(x) % 4 == 0
            )
            assert sorted(part) == expect
            assert st.run_id > run_before


@gen_test(timeout=120)
async def test_dep_free_unpack_cannot_wedge_single_thread_worker():
    """Regression: a recomputed unpack with NO graph dependencies lands
    on a 1-thread worker and waits for the barrier — the transfers the
    barrier needs are queued BEHIND it on the same worker.  The unpack
    must secede (long-running) before blocking, or the worker wedges
    until the 30s collect timeout (measured deadlock)."""
    async with await new_cluster(n_workers=1) as cluster:
        async with Client(cluster.scheduler_address) as c:
            ext = cluster.scheduler.extensions["shuffle"]
            inputs = [
                c.submit(big_partition, i, key=f"sin-{i}") for i in range(4)
            ]
            await c.gather(inputs)
            outs = await p2p_shuffle(c, inputs, npartitions_out=2)
            await asyncio.wait_for(c.gather(outs), 60)
            sid = next(iter(ext.active))
            run_before = ext.active[sid].run_id
            key0 = outs[0].key
            outs[0].release()
            for _ in range(100):
                if key0 not in cluster.scheduler.state.tasks:
                    break
                await asyncio.sleep(0.05)

            from distributed_tpu.graph.spec import TaskSpec
            from distributed_tpu.shuffle.api import shuffle_unpack

            t0 = _time.monotonic()
            futs = c._graph_to_futures(
                {key0: TaskSpec(shuffle_unpack, (sid, 0, run_before))},
                [key0],
            )
            part = await asyncio.wait_for(futs[key0].result(), 90)
            elapsed = _time.monotonic() - t0
            expect = sorted(
                x
                for i in range(4)
                for x in big_partition(i)
                if hash(x) % 2 == 0
            )
            assert sorted(part) == expect
            # the deadlock variant only completes via the 30s timeout path
            assert elapsed < 25, f"unpack took {elapsed:.1f}s: worker wedged"
