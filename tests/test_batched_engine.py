"""Batched stimulus→transition engine (docs/batching.md).

The contract under test: the ``*_batch`` entries on ``SchedulerState``
are drop-in producers of the same ``(recs, client_msgs, worker_msgs)``
triples as N sequential per-key calls — bit-identical final task states,
worker assignments, per-destination message multisets, and per-key
``story`` rows — and the server-side wire coalescer
(``_coalesce_worker_stream_msgs``) is a pure re-batching whose expansion
round-trips to the original message list.
"""

from __future__ import annotations

import random

import pytest

from distributed_tpu.graph import Graph, TaskRef, TaskSpec
from distributed_tpu.scheduler.server import _coalesce_worker_stream_msgs
from distributed_tpu.scheduler.state import SchedulerState


def _noop(*args):
    return 0


def _build_state(n_workers: int, nthreads: int = 1) -> SchedulerState:
    state = SchedulerState(validate=True, transition_counter_max=200_000)
    for i in range(n_workers):
        ws = state.add_worker_state(
            f"tcp://127.0.0.1:{10000 + i}",
            nthreads=nthreads,
            memory_limit=2**30,
            name=f"w{i}",
        )
        state.check_idle_saturated(ws)
    return state


def _random_graph(rng: random.Random, n_tasks: int) -> Graph:
    """Random DAG over a few prefix families (so some groups go rootish)."""
    g = Graph()
    keys: list[str] = []
    for i in range(n_tasks):
        fam = f"fam{i % 3}"
        key = f"{fam}-{i}"
        n_deps = rng.randint(0, min(2, len(keys)))
        deps = rng.sample(keys, n_deps) if n_deps else []
        g.tasks[key] = TaskSpec(_noop, tuple(TaskRef(d) for d in deps))
        keys.append(key)
    return g


def _freeze(obj):
    """Hashable canonical form of a message value; opaque leaves (wrapped
    run_specs, exception objects) compare by identity-independent repr of
    their type — both engines wrap the SAME underlying objects."""
    if isinstance(obj, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in obj.items()))
    if isinstance(obj, (list, tuple)):
        return tuple(_freeze(v) for v in obj)
    if isinstance(obj, (str, bytes, int, float, bool)) or obj is None:
        return obj
    return repr(obj)


def _canon(msgs_by_dest: dict) -> dict:
    """dest -> sorted multiset of frozen messages (run_spec dropped: the
    wrapper objects differ per call; the key identifies the spec)."""
    out = {}
    for dest, msgs in msgs_by_dest.items():
        frozen = []
        for m in msgs:
            m = {k: v for k, v in m.items() if k not in ("run_spec",)}
            frozen.append(_freeze(m))
        out[dest] = sorted(frozen, key=repr)
    return {d: v for d, v in out.items() if v}


def _stories(state: SchedulerState) -> list[tuple]:
    # transition_log rows minus the wall-clock stamp
    return [row[:5] for row in state.transition_log]


def _snapshot(state: SchedulerState) -> dict:
    return {
        key: (
            ts.state,
            ts.processing_on.address if ts.processing_on else None,
            tuple(sorted(ws.address for ws in ts.who_has)),
        )
        for key, ts in state.tasks.items()
    }


def _processing(state: SchedulerState, addr: str) -> list[str]:
    return sorted(ts.key for ts in state.workers[addr].processing)


FINISH_KW = dict(
    nbytes=64,
    typename="int",
    startstops=[{"action": "compute", "start": 0.0, "stop": 0.01}],
)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_tasks_finished_batch_oracle_parity(seed):
    """Replay an identical stimulus trace through the per-key engine and
    the batched engine: identical final task states, assignments, message
    multisets, and per-key stories."""
    rng = random.Random(seed)
    n_workers = rng.choice([2, 3, 5])
    g = _random_graph(rng, 60)
    g.validate()
    deps = g.dependencies()
    roots = [k for k in g.tasks if not any(k in d for d in deps.values())]
    wanted = list(g.tasks)[-10:]

    oracle = _build_state(n_workers)
    batched = _build_state(n_workers)
    for st in (oracle, batched):
        cm, wm = st.update_graph_core(
            dict(g.tasks), {k: set(v) for k, v in deps.items()}, wanted,
            client="client-1", stimulus_id="graph-in",
        )
        st.validate_state()
    del roots

    step = 0
    for _ in range(400):
        # a flood: every task currently processing on one random worker
        # (the engines are asserted identical each round, so both see
        # the same processing sets)
        addrs = [a for a in oracle.workers if _processing(oracle, a)]
        if not addrs:
            break
        addr = rng.choice(addrs)
        keys = _processing(oracle, addr)
        assert keys == _processing(batched, addr)
        erred = rng.random() < 0.2
        step += 1
        if erred:
            exc = ValueError(f"boom-{step}")
            items = [
                (key, addr, f"err-{step}-{i}",
                 dict(exception=exc, exception_text="boom"))
                for i, key in enumerate(keys)
            ]
            o_c, o_w = {}, {}
            for key, w, sid, kw in items:
                c, wmsg = oracle.stimulus_task_erred(key, w, sid, **kw)
                for dst, v in c.items():
                    o_c.setdefault(dst, []).extend(v)
                for dst, v in wmsg.items():
                    o_w.setdefault(dst, []).extend(v)
            b_c, b_w = batched.stimulus_tasks_erred_batch(
                [(k, w, s, dict(kw)) for k, w, s, kw in items]
            )
        else:
            items = [
                (key, addr, f"fin-{step}-{i}", dict(FINISH_KW))
                for i, key in enumerate(keys)
            ]
            o_c, o_w = {}, {}
            for key, w, sid, kw in items:
                c, wmsg = oracle.stimulus_task_finished(key, w, sid, **kw)
                for dst, v in c.items():
                    o_c.setdefault(dst, []).extend(v)
                for dst, v in wmsg.items():
                    o_w.setdefault(dst, []).extend(v)
            b_c, b_w = batched.stimulus_tasks_finished_batch(
                [(k, w, s, dict(kw)) for k, w, s, kw in items]
            )
        assert _canon(o_c) == _canon(b_c)
        assert _canon(o_w) == _canon(b_w)
        assert _snapshot(oracle) == _snapshot(batched)
        oracle.validate_state()
        batched.validate_state()

    assert _snapshot(oracle) == _snapshot(batched)
    assert _stories(oracle) == _stories(batched)


def test_stale_completion_flood_returns_free_keys():
    """Completions for unknown/cancelled keys produce one free-keys per
    stale key, identical to the per-key engine."""
    state = _build_state(2)
    addr = next(iter(state.workers))
    cm, wm = state.stimulus_tasks_finished_batch(
        [
            ("ghost-1", addr, "s1", dict(FINISH_KW)),
            ("ghost-2", addr, "s2", dict(FINISH_KW)),
        ]
    )
    assert cm == {}
    assert [m["keys"] for m in wm[addr]] == [["ghost-1"], ["ghost-2"]]
    assert [m["op"] for m in wm[addr]] == ["free-keys", "free-keys"]


def test_poison_event_does_not_lose_flood_output():
    """A malformed event mid-flood is logged and skipped; events before
    and after it still apply and their messages survive — the
    sequential per-message path loses only the poison message too."""
    g = Graph()
    for i in range(3):
        g.tasks[f"a-{i}"] = TaskSpec(_noop, ())
    state = _build_state(3)
    state.update_graph_core(
        dict(g.tasks), {k: set() for k in g.tasks}, list(g.tasks),
        client="c", stimulus_id="in",
    )
    items = []
    for ws in state.workers.values():
        for ts in list(ws.processing):
            items.append((ts.key, ws.address))
    assert len(items) == 3
    poison = dict(FINISH_KW)
    poison["startstops"] = ["not-a-dict"]  # AttributeError inside _transition
    flood = [
        (items[0][0], items[0][1], "s0", dict(FINISH_KW)),
        (items[1][0], items[1][1], "s1", poison),
        (items[2][0], items[2][1], "s2", dict(FINISH_KW)),
    ]
    cm, wm = state.stimulus_tasks_finished_batch(flood)
    assert state.tasks[items[0][0]].state == "memory"
    assert state.tasks[items[2][0]].state == "memory"
    # the healthy events' client reports survived the poison event
    reported = {
        m["key"] for msgs in cm.values() for m in msgs
        if m["op"] == "key-in-memory"
    }
    assert {items[0][0], items[2][0]} <= reported


def test_transitions_batch_generator_interleaves():
    """transitions_batch consumes its rounds lazily, so a generator can
    interleave side effects (replica removal) with each round exactly
    like sequential per-message handling."""
    g = Graph()
    g.tasks["a-0"] = TaskSpec(_noop, ())
    state = _build_state(2)
    state.update_graph_core(
        dict(g.tasks), {"a-0": set()}, ["a-0"], client="c",
        stimulus_id="in",
    )
    [(addr, ts)] = [
        (ws.address, ts)
        for ws in state.workers.values()
        for ts in ws.processing
    ]
    state.stimulus_task_finished(ts.key, addr, "fin", **FINISH_KW)
    assert state.tasks["a-0"].state == "memory"

    seen = []

    def rounds():
        ws = state.tasks["a-0"].who_has and next(iter(state.tasks["a-0"].who_has))
        state.remove_replica(state.tasks["a-0"], ws)
        seen.append(state.tasks["a-0"].state)  # still memory: lazy proof
        yield {"a-0": "released"}, "rel-1"

    cm, wm = state.transitions_batch(rounds())
    assert seen == ["memory"]
    assert state.tasks.get("a-0") is None or state.tasks["a-0"].state != "memory"
    state.validate_state()


# ------------------------------------------------------- wire coalescer


def _expand(msgs):
    out = []
    for m in msgs:
        if m.get("op") == "compute-tasks":
            out.extend(m["tasks"])
        elif m.get("op") == "free-keys":
            for k in m["keys"]:
                out.append({**m, "keys": [k]})
        else:
            out.append(m)
    return out


def test_coalescer_expansion_roundtrip():
    msgs = [
        {"op": "compute-task", "key": "a", "stimulus_id": "s1"},
        {"op": "compute-task", "key": "b", "stimulus_id": "s2"},
        {"op": "compute-task", "key": "c", "stimulus_id": "s3"},
        {"op": "free-keys", "keys": ["x"], "stimulus_id": "s4"},
        {"op": "free-keys", "keys": ["y"], "stimulus_id": "s4"},
        {"op": "free-keys", "keys": ["z"], "stimulus_id": "s5"},
        {"op": "compute-task", "key": "d", "stimulus_id": "s6"},
        {"op": "remove-replicas", "keys": ["q"], "stimulus_id": "s7"},
        {"op": "compute-task", "key": "e", "stimulus_id": "s8"},
    ]
    orig = [dict(m) for m in msgs]
    coalesced = _coalesce_worker_stream_msgs(msgs)
    # compute-task runs fold; ordering relative to other ops preserved
    ops = [m["op"] for m in coalesced]
    assert ops == [
        "compute-tasks", "free-keys", "free-keys", "compute-task",
        "remove-replicas", "compute-task",
    ]
    assert _expand(coalesced) == orig


def test_coalescer_never_merges_across_stimuli_or_mutates():
    shared = {"op": "free-keys", "keys": ["k"], "stimulus_id": "s1"}
    msgs = [shared, {"op": "free-keys", "keys": ["m"], "stimulus_id": "s1"}]
    out = _coalesce_worker_stream_msgs(msgs)
    assert len(out) == 1 and out[0]["keys"] == ["k", "m"]
    # the SHARED input dict (the state machine reuses one dict across
    # destinations) must not be mutated by the merge
    assert shared["keys"] == ["k"]
    # different stimulus ids never merge (worker-side causal stories)
    msgs2 = [
        {"op": "free-keys", "keys": ["a"], "stimulus_id": "s1"},
        {"op": "free-keys", "keys": ["b"], "stimulus_id": "s2"},
    ]
    assert len(_coalesce_worker_stream_msgs(msgs2)) == 2


def test_coalescer_short_lists_passthrough():
    one = [{"op": "compute-task", "key": "a"}]
    assert _coalesce_worker_stream_msgs(one) is one
    assert _coalesce_worker_stream_msgs([]) == []


# ------------------------------------------------------------ end to end


def test_compute_tasks_batch_reaches_worker(monkeypatch):
    """A fan-out submission crosses the wire as compute-tasks batch
    envelopes and still computes correctly end to end."""
    import asyncio

    from distributed_tpu.client.client import Client
    from distributed_tpu.deploy.local import LocalCluster
    from distributed_tpu.worker.server import Worker

    batch_sizes: list[int] = []
    orig = Worker._stream_compute_tasks

    def spy(self, tasks=(), **kw):
        batch_sizes.append(len(tasks))
        return orig(self, tasks=tasks, **kw)

    monkeypatch.setattr(Worker, "_stream_compute_tasks", spy)

    def inc(x):
        return x + 1

    async def run():
        async with LocalCluster(n_workers=2, threads_per_worker=4) as cluster:
            async with Client(cluster.scheduler_address) as c:
                futs = c.map(inc, range(30))
                return await c.gather(futs)

    results = asyncio.run(asyncio.wait_for(run(), 120))
    assert results == list(range(1, 31))
    assert batch_sizes and max(batch_sizes) >= 2
