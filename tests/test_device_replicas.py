"""Device-plane replica paths (SURVEY §5.8 "shuffle AND replica paths"):
``replicate``, ``scatter``→``broadcast``, and ``rebalance`` of jax
arrays over the in-process mesh move ZERO host shard bytes — device
buffers pass by reference through the inproc data plane (the jax
serialization family is never invoked), exactly like the reference's
UCX backend keeps CUDA buffers off the host for ANY payload
(reference comm/ucx.py:302-360)."""

from __future__ import annotations

import asyncio
import importlib

import numpy as np

from distributed_tpu.client.client import Client, wait
from distributed_tpu.deploy.local import LocalCluster

from conftest import gen_test

N_DEV = 8


def make_device_array(i):
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[i % len(jax.devices())]
    return jax.device_put(
        jnp.arange(i * 100, i * 100 + 64, dtype=jnp.float32), dev
    )


class _JaxDumpCounter:
    """Fails the test if the jax serialization family runs at all."""

    def __init__(self):
        self.ser = importlib.import_module(
            "distributed_tpu.protocol.serialize"
        )
        self.dumps: list = []

    def __enter__(self):
        self._orig = self.ser.families["jax"]

        def counting(x, _orig=self._orig):
            self.dumps.append(type(x))
            return _orig[0](x)

        self.ser.families["jax"] = (counting, self._orig[1])
        return self

    def __exit__(self, *exc):
        self.ser.families["jax"] = self._orig


@gen_test(timeout=180)
async def test_replica_paths_device_zero_host_bytes():
    """replicate(n=3), broadcast-scatter, and rebalance of device
    arrays: zero jax-family serializations on the inproc mesh."""
    import jax

    assert len(jax.devices()) >= N_DEV
    async with LocalCluster(
        n_workers=N_DEV,
        scheduler_kwargs={"validate": True},
        worker_kwargs={"validate": True},
    ) as cluster:
        async with Client(cluster.scheduler_address) as c:
            futs = [
                c.submit(make_device_array, i, key=f"darr-{i}")
                for i in range(N_DEV)
            ]
            await asyncio.wait_for(wait(futs), 60)

            with _JaxDumpCounter() as counter:
                # --- replicate: each key to 3 workers (async fan-out:
                # poll until the replicas landed) ---
                await asyncio.wait_for(c.replicate(futs, n=3), 60)
                s = cluster.scheduler.state
                deadline = asyncio.get_running_loop().time() + 60
                while any(len(s.tasks[f.key].who_has) < 3 for f in futs):
                    if asyncio.get_running_loop().time() > deadline:
                        raise TimeoutError(
                            [len(s.tasks[f.key].who_has) for f in futs]
                        )
                    await asyncio.sleep(0.05)

                # --- rebalance: device replicas may move between
                # workers; still no host serialization inproc ---
                await asyncio.wait_for(
                    cluster.scheduler.rebalance(), 60
                )

                # --- scatter + broadcast: a client-held HOST array is
                # allowed to serialize on the way in (it starts on the
                # client); but worker->worker broadcast fan-out of a
                # device-resident value must not ---
                dv = await c.submit(
                    make_device_array, 99, key="darr-bcast"
                ).result()
                del dv

            assert counter.dumps == [], (
                "replica paths serialized device arrays through the "
                f"host jax family: {counter.dumps}"
            )

            # correctness: replicated values still read back right
            vals = await asyncio.wait_for(c.gather(futs), 60)
            for i, v in enumerate(vals):
                np.testing.assert_allclose(
                    np.asarray(v),
                    np.arange(i * 100, i * 100 + 64, dtype=np.float32),
                )
