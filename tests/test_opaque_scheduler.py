"""The scheduler never touches user code or user payloads.

Reference parity: the reference scheduler runs ``Server(deserialize=False)``
so run_specs, results, and exceptions cross it as opaque frames and the
scheduler process needs neither user modules nor pickle CPU on the hot
path.  These tests pin that property structurally (wrapper types in
scheduler state) and end-to-end (a scheduler that CANNOT import the user's
module still schedules the work and routes the user-defined exception)."""

from __future__ import annotations

import asyncio
import os
import sys
import textwrap

import pytest

from distributed_tpu.client.client import Client
from distributed_tpu.deploy.subprocess import SubprocessCluster
from distributed_tpu.protocol.serialize import Serialize, Serialized
from distributed_tpu.scheduler.server import Scheduler
from distributed_tpu.worker.server import Worker

from conftest import gen_test


@gen_test()
async def test_run_spec_stays_serialized_over_tcp():
    """Over tcp the scheduler stores run_specs as Serialized frames —
    never the live TaskSpec — and workers still execute them."""
    async with Scheduler(listen_addr="tcp://127.0.0.1:0", validate=True) as s:
        async with Worker(s.address, nthreads=1) as w:  # noqa: F841
            async with Client(s.address) as c:
                fut = c.submit(lambda x: x * 2, 21)
                assert await fut.result() == 42
                ts = s.state.tasks[fut.key]
                assert isinstance(ts.run_spec, Serialized), type(ts.run_spec)


@gen_test()
async def test_run_spec_stays_wrapped_over_inproc():
    """Over inproc nothing is serialized at all: the scheduler holds the
    client's Serialize wrapper (zero-copy), opaque by convention."""
    async with Scheduler(listen_addr="inproc://", validate=True) as s:
        async with Worker(s.address, nthreads=1) as w:  # noqa: F841
            async with Client(s.address) as c:
                fut = c.submit(lambda x: x + 1, 1)
                assert await fut.result() == 2
                ts = s.state.tasks[fut.key]
                assert isinstance(ts.run_spec, Serialize), type(ts.run_spec)


@gen_test()
async def test_user_exception_stays_opaque_on_scheduler():
    """A failing task's exception is held by the scheduler as opaque
    frames (tcp) yet reaches the client as the real exception object."""
    async with Scheduler(listen_addr="tcp://127.0.0.1:0", validate=True) as s:
        async with Worker(s.address, nthreads=1):
            async with Client(s.address) as c:
                fut = c.submit(lambda: 1 / 0)
                with pytest.raises(ZeroDivisionError):
                    await fut.result()
                ts = s.state.tasks[fut.key]
                assert isinstance(ts.exception, Serialized), type(ts.exception)


@pytest.mark.slow
@gen_test(timeout=120)
async def test_scheduler_schedules_code_it_cannot_import(tmp_path=None):
    """End-to-end proof: client and workers share a user module; the
    scheduler process does NOT have it on its path.  By-reference
    pickles (function AND custom exception class) must flow client ->
    scheduler -> worker -> scheduler -> client untouched."""
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        mod = os.path.join(td, "dtpu_userlib.py")
        with open(mod, "w") as f:
            f.write(textwrap.dedent("""
                class UserError(Exception):
                    pass

                def triple(x):
                    return x * 3

                def boom():
                    raise UserError("user-defined failure")
                """))
        sys.path.insert(0, td)
        try:
            import dtpu_userlib  # noqa: F401

            # workers get the module via PYTHONPATH; the scheduler's env
            # is untouched (child_env gives it only the repo)
            async with SubprocessCluster(
                n_workers=1,
                nthreads=1,
                worker_options={
                    "extra_env": {
                        "PYTHONPATH": td + os.pathsep
                        + os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
                    }
                },
            ) as cluster:
                async with Client(cluster.scheduler_address) as c:
                    fut = c.submit(dtpu_userlib.triple, 14)
                    assert await asyncio.wait_for(fut.result(), 60) == 42
                    bad = c.submit(dtpu_userlib.boom, pure=False)
                    with pytest.raises(dtpu_userlib.UserError, match="user-defined"):
                        await asyncio.wait_for(bad.result(), 60)
        finally:
            sys.path.remove(td)
            sys.modules.pop("dtpu_userlib", None)
