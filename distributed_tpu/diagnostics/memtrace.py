"""Worker memory introspection (the reference's memray integration role,
diagnostics/memray.py:26 — memray itself is not in this image, so the
stdlib ``tracemalloc`` fills the role with zero dependencies).

Flow mirrors the reference's start → workload → report cycle:

    async with Client(...) as c:
        await c.memory_trace_start()            # all workers
        ... run the suspect workload ...
        reports = await c.memory_trace_report(top_n=10)
        await c.memory_trace_stop()

Each worker's report carries its top allocation sites (file:line,
cumulative bytes, block counts), total traced memory, peak, and the
data-store view (managed bytes, spill counts) so leaked interpreter
memory can be told apart from legitimately stored results.
"""

from __future__ import annotations

import tracemalloc
from typing import Any

#: owners (server ids) that asked for tracing and have not stopped it.
#: tracemalloc itself is PROCESS-global — with in-process workers
#: (LocalCluster) a bare stop on one worker used to kill the trace for
#: every server in the process.  start/stop are refcounted per OWNER:
#: the underlying trace only stops when the LAST owner stops.  A bare
#: (ownerless) start/stop pair uses the "" owner, preserving the old
#: single-caller semantics.
_owners: set[str] = set()
#: True only when THIS module called tracemalloc.start(): a trace the
#: user armed themselves (PYTHONTRACEMALLOC, their own start()) is
#: never ours to stop, no matter what the owner set does
_started_here = False


def start_trace(nframes: int = 5, owner: str = "") -> dict:
    """Begin tracing allocations in this process (idempotent per
    owner)."""
    global _started_here
    _owners.add(owner)
    if not tracemalloc.is_tracing():
        tracemalloc.start(nframes)
        _started_here = True
    return {"status": "OK", "tracing": True, "owners": len(_owners)}


def stop_trace(owner: str = "") -> dict:
    """Release this owner's hold on the trace; the process-global
    tracemalloc stops only when no owner remains AND this module
    started it (an externally-armed trace is left alone)."""
    global _started_here
    _owners.discard(owner)
    if not _owners and _started_here and tracemalloc.is_tracing():
        tracemalloc.stop()
        _started_here = False
    return {
        "status": "OK",
        "tracing": tracemalloc.is_tracing(),
        "owners": len(_owners),
    }


def report(top_n: int = 10, group_by: str = "lineno") -> dict:
    """Snapshot of the top allocation sites since ``start_trace``."""
    if not tracemalloc.is_tracing():
        return {"status": "not-tracing"}
    snap = tracemalloc.take_snapshot()
    current, peak = tracemalloc.get_traced_memory()
    stats = snap.statistics(group_by)[: int(top_n)]
    return {
        "status": "OK",
        "traced_bytes": current,
        "peak_bytes": peak,
        "top": [
            {
                "site": str(st.traceback[0]) if st.traceback else "?",
                "bytes": st.size,
                "blocks": st.count,
            }
            for st in stats
        ],
    }


def worker_report(worker: Any, top_n: int = 10) -> dict:
    """report() plus the worker's data-store view: interpreter-level
    allocations vs legitimately managed task results.

    NOTE: tracemalloc is PROCESS-global.  In-process clusters
    (LocalCluster) share one trace across every worker, the scheduler
    and the client — the allocation sites are process-wide, only the
    data_store section is truly per-worker.  Per-worker attribution of
    allocation sites requires process-backed workers (Nanny /
    SubprocessCluster)."""
    out = report(top_n=top_n)
    out["process_wide"] = True
    out["data_store"] = worker.data_store_summary()
    return out
