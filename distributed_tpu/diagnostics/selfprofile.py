"""Control-plane self-profiling: the scheduler watches itself.

``diagnostics/profile.py`` samples worker *executor* threads — the
threads running user tasks.  The paper's innovation, though, lives in
the control plane: the event-loop thread running ``transitions_batch``,
``send_all`` flushes and mirror uploads, and the jax-placement planner
thread.  This module turns those blind spots into a continuously
answered question ("where did the scheduler's second go?") with three
cooperating pieces (docs/observability.md "Self-profiling"):

- :class:`WallBudget` — exact monotonic-clock accumulators per
  control-plane *phase* (``engine.drain``, ``egress.flush``,
  ``kernel.dispatch``, ``mirror.upload``, ``telemetry.fold``, and —
  opt-in, ``scheduler.profile.arm-attribution`` — the per-transition
  ``engine.scalar-arm:<start>,<finish>`` arms).  Phases are entered at
  the existing hot-path seams in ``scheduler/state.py``,
  ``scheduler/server.py``, ``scheduler/jax_placement.py`` and
  ``scheduler/mirror.py``; totals export as
  ``dtpu_wall_seconds_total{phase=}`` at ``/metrics`` and the
  per-arm table is the payoff artifact ``sim.profile_run`` emits
  (ROADMAP item 4's prioritization input).
- :class:`ControlPlaneProfiler` — a :class:`~distributed_tpu.
  diagnostics.profile.Profiler` aimed at the loop/planner thread idents,
  with a ``stop=`` frame boundary so the shared asyncio ``run_forever``
  prefix doesn't swamp the tree, idle selector frames counted apart from
  the signal, and the active phase + stimulus id stamped onto every
  sample (the join to the flight recorder's causal timeline).
- :class:`LoopWatchdog` — a loop-side tick measuring event-loop lag
  into ``dtpu_loop_lag_seconds`` plus an off-loop monitor thread that,
  when the loop stops ticking past ``scheduler.profile.stall-threshold``,
  captures the blocked loop thread's stack via ``sys._current_frames``
  into a flight-recorder ``stall`` event (formatted traceback +
  in-progress phase): the postmortem for "the scheduler froze".

Always-on budget: batch-level phase enters only (a handful of monotonic
reads per engine pass), sampling at a low configurable rate
(``scheduler.profile.interval``), arm attribution off by default.  The
``selfprofile`` bench smoke gates sampling-on overhead <5% on the
engine flood (tests/test_bench_smoke.py).

Covered by graft-lint's monotonic-time rule (diagnostics/**): every
clock read here is the monotonic ``utils.misc.time``, and the watchdog
thread waits on an ``Event``, never ``time.sleep``.
"""

from __future__ import annotations

import logging
import sys
import threading
import traceback as _traceback
from collections import deque
from typing import Any, Callable, Iterable

from distributed_tpu import config
from distributed_tpu.diagnostics.profile import Profiler, create, merge, process
from distributed_tpu.tracing import SECONDS_BUCKETS, Histogram, to_jsonl
from distributed_tpu.utils.misc import time

logger = logging.getLogger("distributed_tpu.selfprofile")

#: phase vocabulary (docs/observability.md "Self-profiling") — the
#: batch-level phases entered unconditionally at the hot-path seams.
#: ``engine.scalar-arm:<start>,<finish>`` (scheduler) and
#: ``wengine.scalar-arm:<start>,<finish>`` (worker) join them when
#: ``scheduler.profile.arm-attribution`` is on.
PHASES = (
    "engine.drain",      # a transition-engine round drained to fixed point
    "wengine.stimulus",  # a worker state-machine stimulus batch
    "egress.flush",      # Scheduler.stream_payload_flush coalescing/writes
    "kernel.dispatch",   # a device placement plan (loop or planner thread)
    "mirror.upload",     # fleet-mirror device upload (scatter or full)
    "telemetry.fold",    # heartbeat telemetry folding into the aggregate
)

#: innermost frames in these files mean "the loop is idle in select()" —
#: counted apart so an idle scheduler's tree stays signal-dense
IDLE_FILES = ("selectors.py",)

#: pseudo-frame prefix for the phase layer stamped under a profile root
PHASE_PREFIX = "phase:"


class WallBudget:
    """Exact wall attribution of control-plane threads by phase.

    A per-thread phase *stack* (entering a child phase pauses the
    parent's accumulation, so every total is **self time**) plus shared
    totals.  ``push``/``pop`` are the hot-path API (two monotonic reads
    and a couple of dict operations each); :meth:`phase` is the
    context-manager convenience for batch-level seams.  The top of each
    thread's stack is published in ``_active`` so the sampler and stall
    watchdog (other threads) can stamp the in-progress phase +
    stimulus id onto samples and stall events.
    """

    def __init__(self, clock: Callable[[], float] = time):
        # REAL monotonic clock even under the simulator: the budget
        # measures python cost, not virtual time (sim.profile_run)
        self.clock = clock
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self._lock = threading.Lock()
        self._tls = threading.local()
        # thread ident -> (phase, stimulus) of that thread's stack top
        self._active: dict[int, tuple[str, str]] = {}

    # ------------------------------------------------------------ hot path

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def push(self, phase: str, stim: str = "") -> None:
        now = self.clock()
        st = self._stack()
        if st:
            top = st[-1]
            self._fold(top[0], now - top[2], entered=False)
            top[2] = now
        st.append([phase, stim, now])
        self._active[threading.get_ident()] = (phase, stim)

    def pop(self) -> None:
        now = self.clock()
        st = self._stack()
        if not st:  # unbalanced pop: never corrupt the accumulators
            return
        phase, _stim, seg = st.pop()
        self._fold(phase, now - seg, entered=True)
        ident = threading.get_ident()
        if st:
            top = st[-1]
            top[2] = now
            self._active[ident] = (top[0], top[1])
        else:
            self._active.pop(ident, None)

    def _fold(self, phase: str, dt: float, entered: bool) -> None:
        # the lock covers cross-thread accumulation (loop + planner
        # thread share one budget); push/pop frequency is batch-level
        # unless arm attribution is on, where the cost is opted into
        with self._lock:
            self.totals[phase] = self.totals.get(phase, 0.0) + dt
            if entered:
                self.counts[phase] = self.counts.get(phase, 0) + 1

    # ----------------------------------------------------------- slow path

    def phase(self, name: str, stim: str = ""):
        """``with budget.phase("egress.flush", stim): ...``"""
        return _PhaseCtx(self, name, stim)

    def current(self, ident: int) -> tuple[str, str]:
        """(phase, stimulus) at the top of thread ``ident``'s stack
        ("", "") when it is outside every phase.  Safe from any thread."""
        return self._active.get(ident, ("", ""))

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return dict(self.totals)

    def snapshot_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self.counts)

    def clear(self) -> None:
        with self._lock:
            self.totals.clear()
            self.counts.clear()

    def __repr__(self) -> str:
        return f"<WallBudget phases={len(self.totals)}>"


class _PhaseCtx:
    __slots__ = ("_budget", "_name", "_stim")

    def __init__(self, budget: WallBudget, name: str, stim: str):
        self._budget = budget
        self._name = name
        self._stim = stim

    def __enter__(self):
        self._budget.push(self._name, self._stim)
        return self

    def __exit__(self, *exc):
        self._budget.pop()


class ControlPlaneProfiler(Profiler):
    """Statistical profiler for control-plane threads (loop + planner).

    Differences from the executor profiler it extends:

    - defaults come from the ``scheduler.profile.*`` subtree (shared by
      both roles, like ``scheduler.trace.*``), not ``worker.profile``;
    - ``stop`` frame boundary cuts the shared asyncio machinery prefix;
    - samples whose innermost frame sits in ``IDLE_FILES`` count into
      ``idle_samples`` instead of the tree (an idle selector wait is not
      control-plane work);
    - every tree insertion lands under a ``phase:<name>`` pseudo-frame
      read from the :class:`WallBudget` of the sampled thread, and the
      (ts, phase, stimulus) triple of recent samples is kept in
      ``samples`` — the join between profiles and the flight recorder.
    """

    def __init__(self, idents: Callable[[], Iterable[int]],
                 wall: WallBudget | None = None,
                 interval: float | None = None, cycle: float | None = None,
                 maxlen: int | None = None, stop: str | None = None):
        cfg = config.get("scheduler.profile")
        super().__init__(
            thread_filter="dtpu-control-plane",  # unused: idents given
            interval=(
                interval if interval is not None
                else config.parse_timedelta(cfg["interval"])
            ),
            cycle=(
                cycle if cycle is not None
                else config.parse_timedelta(cfg["cycle"])
            ),
            maxlen=maxlen if maxlen is not None else int(cfg["history"]),
            idents=idents,
            stop=stop if stop is not None else (cfg["stop"] or None),
        )
        self.wall = wall
        self.total_samples = 0
        self.idle_samples = 0
        #: recent (ts, phase, stim) sample stamps, newest last
        self.samples: deque[tuple[float, str, str]] = deque(maxlen=512)

    def _add_sample(self, frame, now: float, ident: int | None = None) -> None:
        self.total_samples += 1
        if frame.f_code.co_filename.endswith(IDLE_FILES):
            self.idle_samples += 1
            return
        phase, stim = ("", "")
        if self.wall is not None and ident is not None:
            phase, stim = self.wall.current(ident)
        with self._lock:
            root = self.current
            root["count"] += 1
            process(frame, _phase_node(root, phase), stop=self.stop_file)
            self.samples.append((now, phase, stim))
            if now - self._last_cycle > self.cycle:
                self.history.append((now, self.current))
                self.current = create()
                self._last_cycle = now


def _phase_node(root: dict, phase: str) -> dict:
    ident = PHASE_PREFIX + (phase or "unattributed")
    node = root["children"].get(ident)
    if node is None:
        node = root["children"][ident] = {
            "count": 0,
            "children": {},
            "identifier": ident,
            "description": ident,
        }
    return node


class LoopWatchdog:
    """Tick/stall watchdog for one event loop.

    Loop side: :meth:`tick` runs as a periodic callback and observes the
    loop's scheduling lag (actual gap minus the nominal interval) into
    ``hist_lag`` — a loaded loop shows up as a fattening
    ``dtpu_loop_lag_seconds`` histogram long before anything freezes.

    Thread side: a daemon monitor (``Event.wait`` paced, never a
    blocking sleep) notices when the last tick is older than
    ``stall-threshold`` while the loop is supposed to be alive, and —
    exactly once per stall episode — captures the loop thread's stack
    via ``sys._current_frames()`` into a ``stall`` record and
    flight-recorder event carrying the formatted traceback and the
    in-progress :class:`WallBudget` phase.  The episode re-arms only
    after a fresh tick proves the loop recovered.

    The flight-recorder ring is SINGLE-WRITER by design (``emit`` is an
    unsynchronized in-place slot write on the loop thread), so the
    capture only buffers the event; the first :meth:`tick` after
    recovery writes it into the ring from the loop thread.  The
    ``stalls`` deque and the log warning carry the postmortem
    immediately either way — including when the loop never recovers.
    """

    def __init__(self, trace: Any = None, wall: WallBudget | None = None,
                 interval: float | None = None,
                 stall_threshold: float | None = None,
                 max_stalls: int = 32):
        cfg = config.get("scheduler.profile")
        self.interval = (
            interval if interval is not None
            else config.parse_timedelta(cfg["watchdog-interval"])
        )
        self.stall_threshold = (
            stall_threshold if stall_threshold is not None
            else config.parse_timedelta(cfg["stall-threshold"])
        )
        self.trace = trace
        self.wall = wall
        self.hist_lag = Histogram(SECONDS_BUCKETS)
        self.stalls: deque[dict] = deque(maxlen=max_stalls)
        self.stalls_total = 0
        self.ticks_total = 0
        # stall events captured off-loop, ring-written by tick() on the
        # loop thread (deque append/popleft are GIL-atomic)
        self._pending_events: deque[tuple] = deque()
        self._last_tick = 0.0
        self._loop_ident: int | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ loop side

    def tick(self) -> None:
        now = time()
        if self._last_tick:
            self.hist_lag.observe(max(0.0, now - self._last_tick - self.interval))
        self._last_tick = now
        self.ticks_total += 1
        while self._pending_events:
            # ring writes happen HERE, on the loop thread: the watchdog
            # thread must never race the loop's own emits
            phase, stim, tb, lag_ms = self._pending_events.popleft()
            if self.trace is not None:
                self.trace.emit(
                    "stall", phase or "loop-blocked", stim, key=tb, n=lag_ms
                )

    # ---------------------------------------------------------- thread side

    def start(self, loop_ident: int) -> None:
        self._loop_ident = loop_ident
        self._last_tick = time()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="dtpu-stall-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread = None

    def _run(self) -> None:
        # check twice per threshold: a stall is noticed within ~1.5x the
        # threshold without the monitor itself becoming a busy loop
        period = max(min(self.interval, self.stall_threshold / 2), 0.005)
        reported = False
        while not self._stop.wait(period):
            lag = time() - self._last_tick
            if lag <= self.stall_threshold:
                reported = False  # fresh tick seen: episode over, re-arm
                continue
            if reported:
                continue  # one stall event per episode
            reported = True
            try:
                self._capture(lag)
            except Exception:  # pragma: no cover - diagnostics must not kill
                logger.exception("stall capture failed")

    def _capture(self, lag: float) -> None:
        frame = sys._current_frames().get(self._loop_ident)
        tb = "".join(_traceback.format_stack(frame)) if frame is not None else ""
        phase, stim = ("", "")
        if self.wall is not None and self._loop_ident is not None:
            phase, stim = self.wall.current(self._loop_ident)
        rec = {
            "ts": time(),
            "lag_s": round(lag, 4),
            "phase": phase,
            "stim": stim,
            "traceback": tb,
        }
        self.stalls.append(rec)
        self.stalls_total += 1
        # the ring slot's key field carries the formatted traceback (a
        # stall is rare, the postmortem IS the payload); buffered here,
        # ring-written by the next on-loop tick — see the class docstring
        self._pending_events.append(
            (phase, stim, tb, int(lag * 1000))
        )
        logger.warning(
            "event loop stalled %.2fs (phase=%s stim=%s); stack:\n%s",
            lag, phase or "?", stim or "?", tb,
        )


# ------------------------------------------------------------- exposure


def profile_records(role: str, profiler: ControlPlaneProfiler | None,
                    wall: WallBudget | None,
                    watchdog: LoopWatchdog | None,
                    extra_trees: dict[str, dict] | None = None) -> list[dict]:
    """The ``/profile`` route body, shared by both roles: a ``head``
    record (counters, wall totals, recent stalls), one ``profile``
    record per tree (``which`` = ``loop`` / extra keys such as ``exec``),
    and a ``samples`` record with the recent (ts, phase, stim) stamps.
    Serialized with :func:`distributed_tpu.tracing.to_jsonl`."""
    head: dict[str, Any] = {"v": 1, "kind": "head", "role": role}
    if wall is not None:
        head["wall_seconds"] = {
            k: round(v, 6) for k, v in wall.snapshot().items()
        }
        head["wall_entries"] = wall.snapshot_counts()
    if profiler is not None:
        head["samples_total"] = profiler.total_samples
        head["idle_samples"] = profiler.idle_samples
    if watchdog is not None:
        head["ticks_total"] = watchdog.ticks_total
        head["stalls_total"] = watchdog.stalls_total
        head["stalls"] = list(watchdog.stalls)
    records = [head]
    if profiler is not None:
        records.append({
            "v": 1, "kind": "profile", "which": "loop",
            "tree": profiler.get_profile(),
        })
        records.append({
            "v": 1, "kind": "samples",
            "recent": [
                {"ts": ts, "phase": ph, "stim": st}
                for ts, ph, st in list(profiler.samples)
            ],
        })
    for which, tree in (extra_trees or {}).items():
        records.append(
            {"v": 1, "kind": "profile", "which": which, "tree": tree}
        )
    return records


def profile_jsonl(role: str, profiler: ControlPlaneProfiler | None,
                  wall: WallBudget | None, watchdog: LoopWatchdog | None,
                  extra_trees: dict[str, dict] | None = None) -> str:
    return to_jsonl(profile_records(role, profiler, wall, watchdog,
                                    extra_trees))


def profile_to_speedscope(tree: dict, name: str = "dtpu-profile") -> dict:
    """Convert a profile call tree (``diagnostics.profile`` format, as
    served by ``/profile`` ``profile`` records) into a speedscope
    sampled profile (https://www.speedscope.app file format): each
    node's *self* count becomes one weighted sample of its root-first
    stack, so the flamegraph shows exactly the sampled distribution."""
    frames: list[dict] = []
    findex: dict[str, int] = {}
    samples: list[list[int]] = []
    weights: list[int] = []

    def frame_id(node: dict) -> int:
        ident = node["identifier"]
        i = findex.get(ident)
        if i is None:
            i = findex[ident] = len(frames)
            parts = ident.split(";")
            frames.append({
                "name": node.get("description") or parts[0] or ident,
                "file": parts[1] if len(parts) > 1 else "",
                "line": int(parts[2]) if len(parts) > 2
                and parts[2].isdigit() else 0,
            })
        return i

    def walk(node: dict, stack: list[int]) -> None:
        children = node.get("children", {})
        self_count = node.get("count", 0) - sum(
            c.get("count", 0) for c in children.values()
        )
        if self_count > 0 and stack:
            samples.append(stack)
            weights.append(self_count)
        for child in children.values():
            walk(child, stack + [frame_id(child)])

    walk(tree, [])
    total = sum(weights)
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "exporter": "distributed_tpu",
        "name": name,
        "shared": {"frames": frames},
        "profiles": [{
            "type": "sampled",
            "name": name,
            "unit": "none",
            "startValue": 0,
            "endValue": total,
            "samples": samples,
            "weights": weights,
        }],
    }


__all__ = [
    "ControlPlaneProfiler",
    "IDLE_FILES",
    "LoopWatchdog",
    "PHASES",
    "PHASE_PREFIX",
    "WallBudget",
    "merge",
    "profile_jsonl",
    "profile_records",
    "profile_to_speedscope",
]
