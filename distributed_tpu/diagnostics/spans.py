"""Spans: tag workloads and aggregate their task statistics
(reference spans.py).

``span("workflow")`` on the client annotates every task submitted inside
the context (reference spans.py:31 does it via dask annotations); the
scheduler-side ``SpansSchedulerExtension`` builds a tree of Span records
aggregating task states, compute time, and bytes as transitions flow
through the plugin hook (reference SpansSchedulerExtension :450,483).
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import uuid
from collections import defaultdict, deque
from typing import TYPE_CHECKING, Any, Iterator

from distributed_tpu.utils.misc import time

if TYPE_CHECKING:
    from distributed_tpu.scheduler.server import Scheduler

_current_span: contextvars.ContextVar[tuple[str, ...] | None] = (
    contextvars.ContextVar("dtpu_span", default=None)
)


@contextlib.contextmanager
def span(*names: str) -> Iterator[str]:
    """Tag tasks submitted in this context (reference spans.py:31)."""
    parent = _current_span.get() or ()
    full = parent + names
    token = _current_span.set(full)
    try:
        yield "/".join(full)
    finally:
        _current_span.reset(token)


def current_span() -> tuple[str, ...] | None:
    return _current_span.get()


class Span:
    """Aggregated stats for one span node (reference spans.py:74)."""

    __slots__ = ("id", "name", "parent", "children", "states", "n_tasks",
                 "compute_seconds", "nbytes", "start", "stop", "activity",
                 "recent_stimuli")

    def __init__(self, name: tuple[str, ...], parent: "Span | None" = None):
        self.id = f"span-{uuid.uuid4().hex[:12]}"
        self.name = name
        self.parent = parent
        self.children: list[Span] = []
        self.states: defaultdict[str, int] = defaultdict(int)
        self.n_tasks = 0
        self.compute_seconds = 0.0
        self.nbytes = 0
        self.start = 0.0
        self.stop = 0.0
        # fine performance metrics: (prefix, label, unit) -> total
        # (reference spans.py cumulative_worker_metrics)
        self.activity: defaultdict[tuple[str, str, str], float] = defaultdict(float)
        # newest stimulus ids whose transitions fed this span (bounded):
        # the causal join key against /trace and the flight recorder —
        # a span's fine-metric rows can be correlated with the engine
        # passes that scheduled its tasks
        self.recent_stimuli: deque[str] = deque(maxlen=32)

    def traverse(self) -> "Iterator[Span]":
        """This span and every descendant, depth-first (reference
        spans.py:74 Span.traverse_spans)."""
        yield self
        for child in self.children:
            yield from child.traverse()

    def cumulative(self) -> dict:
        """Aggregates over the WHOLE subtree — nested spans roll up to
        any depth (reference spans.py cumulative properties: a parent
        span answers for everything submitted under it, not just tasks
        annotated with its exact name)."""
        states: defaultdict[str, int] = defaultdict(int)
        activity: defaultdict[tuple[str, str, str], float] = defaultdict(
            float
        )
        n_tasks = 0
        compute = 0.0
        nbytes = 0
        start, stop = self.start, self.stop
        for sp in self.traverse():
            n_tasks += sp.n_tasks
            compute += sp.compute_seconds
            nbytes += sp.nbytes
            for k, v in sp.states.items():
                states[k] += v
            for k, v in sp.activity.items():
                activity[k] += v
            if sp.start and (not start or sp.start < start):
                start = sp.start
            if sp.stop > stop:
                stop = sp.stop
        return {
            "n_tasks": n_tasks,
            "states": dict(states),
            "compute_seconds": compute,
            "nbytes": nbytes,
            "start": start,
            "stop": stop,
            "activity": {"|".join(k): v for k, v in activity.items()},
        }

    def to_dict(self) -> dict:
        # bottom-up: build children first and fold their ALREADY-rolled
        # cumulative dicts into this node's, so serializing a tree is
        # O(N) instead of re-traversing every subtree per ancestor
        children = [c.to_dict() for c in self.children]
        cum = {
            "n_tasks": self.n_tasks,
            "states": dict(self.states),
            "compute_seconds": self.compute_seconds,
            "nbytes": self.nbytes,
            "start": self.start,
            "stop": self.stop,
            "activity": {
                "|".join(k): v for k, v in self.activity.items()
            },
        }
        for cd in children:
            cc = cd["cumulative"]
            cum["n_tasks"] += cc["n_tasks"]
            cum["compute_seconds"] += cc["compute_seconds"]
            cum["nbytes"] += cc["nbytes"]
            for k, v in cc["states"].items():
                cum["states"][k] = cum["states"].get(k, 0) + v
            for k, v in cc["activity"].items():
                cum["activity"][k] = cum["activity"].get(k, 0.0) + v
            if cc["start"] and (not cum["start"] or cc["start"] < cum["start"]):
                cum["start"] = cc["start"]
            if cc["stop"] > cum["stop"]:
                cum["stop"] = cc["stop"]
        return {
            "id": self.id,
            "name": list(self.name),
            "n_tasks": self.n_tasks,
            "states": dict(self.states),
            "compute_seconds": self.compute_seconds,
            "nbytes": self.nbytes,
            "start": self.start,
            "stop": self.stop,
            "activity": {
                "|".join(k): v for k, v in self.activity.items()
            },
            "recent_stimuli": list(self.recent_stimuli),
            "cumulative": cum,
            "children": children,
        }


class SpansSchedulerExtension:
    """Builds the span tree from task annotations + transitions
    (reference spans.py:450)."""

    def __init__(self, scheduler: "Scheduler"):
        self.scheduler = scheduler
        self.spans: dict[tuple[str, ...], Span] = {}
        self.by_id: dict[str, Span] = {}
        self.key_span: dict[str, Span] = {}
        # fleet-wide fine metrics, spans or not:
        # (context, span_id, prefix, label, unit) -> total
        # (reference spans.py cumulative_worker_metrics)
        self.cumulative_worker_metrics: defaultdict[tuple, float] = (
            defaultdict(float)
        )
        scheduler.state.plugins["spans"] = self
        scheduler.handlers["get_spans"] = self.get_spans
        scheduler.handlers["get_fine_metrics"] = self.get_fine_metrics

    def _get_or_create(self, name: tuple[str, ...]) -> Span:
        sp = self.spans.get(name)
        if sp is None:
            parent = self._get_or_create(name[:-1]) if len(name) > 1 else None
            sp = self.spans[name] = Span(name, parent)
            self.by_id[sp.id] = sp
            if parent is not None:
                parent.children.append(sp)
        return sp

    def collect_fine_metrics(self, rows: list) -> None:
        """Fold one worker heartbeat's activity samples in
        (reference spans.py SpansSchedulerExtension.heartbeat)."""
        for row in rows:
            try:
                context, span_id, prefix, label, unit, value = row
            except (TypeError, ValueError):
                continue
            self.cumulative_worker_metrics[
                (context, span_id, prefix, label, unit)
            ] += value
            sp = self.by_id.get(span_id)
            if sp is not None:
                sp.activity[(prefix, label, unit)] += value

    async def get_fine_metrics(self) -> dict:
        return {
            "|".join(str(p) for p in k): v
            for k, v in self.cumulative_worker_metrics.items()
        }

    # tape-safe (scheduler/native_engine.py): this hook reads only its
    # arguments, row-current task state and plugin-private structures,
    # never WorkerState.occupancy — so the native engine's applier may
    # replay it per tape row in stream order (docs/native_engine.md)
    tape_safe = True

    def transition(self, key: str, start: str, finish: str, *args: Any,
                   **kwargs: Any) -> None:
        sp = self.key_span.get(key)
        if sp is None:
            ts = self.scheduler.state.tasks.get(key)
            if ts is None or not ts.annotations:
                return
            name = ts.annotations.get("span")
            if not name:
                return
            sp = self._get_or_create(tuple(name))
            self.key_span[key] = sp
            sp.n_tasks += 1
            if not sp.start:
                sp.start = time()
            # stamp the group so compute-task messages carry the span id
            # to workers (fine-metric attribution).  Last association
            # wins: consecutive spans sharing a key prefix (and thus a
            # TaskGroup) each retarget the group at association time —
            # concurrent overlap of two spans on one prefix can still
            # misattribute, which the reference avoids only by splitting
            # TaskGroups per span
            if ts.group is not None:
                ts.group.span_id = sp.id
        sp.states[finish] += 1
        sid = kwargs.get("stimulus_id")
        if sid and (not sp.recent_stimuli or sp.recent_stimuli[-1] != sid):
            sp.recent_stimuli.append(sid)
        if finish == "memory" and start == "processing":
            for ss in kwargs.get("startstops") or ():
                if ss.get("action") == "compute":
                    sp.compute_seconds += ss["stop"] - ss["start"]
            nbytes = kwargs.get("nbytes")
            if nbytes:
                sp.nbytes += nbytes
            sp.stop = time()
        if finish == "forgotten":
            self.key_span.pop(key, None)

    async def get_spans(self) -> list[dict]:
        return [
            sp.to_dict() for name, sp in self.spans.items() if len(name) == 1
        ]
