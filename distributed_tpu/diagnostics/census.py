"""State census + retention sentinel (docs/observability.md "State
census & retention").

The observability stack answers "what happened" (flight recorder),
"what is true" (telemetry), "where the time went" (self-profiler) and
"was the decision right" (ledger) — this module answers **"what are we
still holding"**.  A ``StateCensus`` is a typed inventory of every
long-lived container a control plane owns: scheduler tasks by state,
relation-set edges, client interest, HeapSet queues, stealing level
sets and in-flight maps, mirror slots, native-engine SoA rows,
durability dirty sets, telemetry links, ledger ring occupancy, the
flight-recorder rings, and the worker-side twins.  Each registered
*family* carries a kind from a fixed vocabulary, a cheap probe, an
optional from-scratch verification walk, and — for families that are
legitimately non-empty at rest — an allowlist reason.

Three consumers:

- **quiesce-clean proofs**: when a control plane is quiescent (no
  tasks, nothing in flight), the census diffed against the empty
  baseline must be zero outside the allowlist; any residue is a named
  finding with a bounded ``gc.get_referrers`` sample identifying the
  holding container.  Every sim chaos scenario and ``sim.run_ab`` end
  on this gate (sim/validate.check_census_clean) — the bounded-memory
  oracle ROADMAP item 5(b)'s stimulus fuzzer asserts.
- **walk-vs-counter audits** (``DTPU_CENSUS_CHECK``, mirror-parity
  style): families whose probe reads a *maintained* counter (task
  state counts maintained by both transition engines, the ledger's
  derived open-row count, native SoA row registries, mirror slots)
  are recounted from scratch and any drift raises
  :class:`CensusParityError`.
- **live leak detection**: a :class:`RetentionSentinel` ticks on the
  server loop, folds per-family growth slopes into EWMAs, and emits
  flight-recorder ``leak`` events + ``dtpu_census_*`` gauges when a
  family grows without bound.

This file is pure (no IO, no event loop, no threads): the sans-io
state machines build their census in ``__init__`` and the monotonic
lint covers it.  ``gc`` introspection only runs on the slow finding
path, never per probe.
"""

from __future__ import annotations

import gc
import os
from collections import deque
from typing import Any, Callable, Iterable

from distributed_tpu import config
from distributed_tpu.ledger import _OUTCOME as _LEDGER_OUTCOME
from distributed_tpu.ledger import _W as _LEDGER_W
from distributed_tpu.utils import time

#: bump when the snapshot record shape changes (docs/observability.md)
CENSUS_SCHEMA_VERSION = 1

#: family vocabulary — the ``kind`` field (docs/observability.md):
#:   state     resident first-class records (tasks, workers, clients)
#:   edges     relation-set members summed across records
#:   queue     poppable work queues (HeapSet / deque members)
#:   in-flight open windows awaiting a completion that must come
#:   interest  who-wants / wants-what client interest edges
#:   index     derived lookup structures that must shadow a primary
#:   ring      bounded-by-construction rings/deques (always allowlisted)
#:   pool      reusable capacity (free lists, buffer pools, tombstones)
#:   scratch   between-pass marks drained by the next flush/snapshot
FAMILY_KINDS = (
    "state", "edges", "queue", "in-flight", "interest", "index", "ring",
    "pool", "scratch",
)

#: findings kept per census (oldest evicted)
MAX_FINDINGS = 64
#: bounded referrer identification (per finding)
REFERRER_BREADTH = 8
REFERRER_DEPTH = 3
SAMPLE_MEMBERS = 3


def census_check_enabled() -> bool:
    """``DTPU_CENSUS_CHECK`` — same spelling as the mirror/native parity
    switches: unset/0/false/off/no = off, anything else = on."""
    v = os.environ.get("DTPU_CENSUS_CHECK", "")
    return v.lower() not in ("", "0", "false", "off", "no")


class CensusParityError(AssertionError):
    """A maintained counter diverged from its verification walk."""


class CensusResidueError(AssertionError):
    """A quiescent control plane retained non-allowlisted state."""


class Family:
    """One registered container family."""

    __slots__ = (
        "name", "kind", "probe", "walk", "cost", "allow", "reason",
        "sample", "containers", "attrs", "last", "last_ts", "slope",
        "flagged",
    )

    def __init__(self, name: str, probe: Callable[[], int], *,
                 kind: str = "state", cost: str = "o1",
                 walk: Callable[[], int] | None = None,
                 allow: bool = False, reason: str = "",
                 sample: Callable[[], Iterable[Any]] | None = None,
                 containers: Callable[[], Iterable[Any]] | None = None,
                 attrs: tuple[str, ...] = ()):
        assert kind in FAMILY_KINDS, kind
        assert cost in ("o1", "walk"), cost
        assert not allow or reason, f"allowlisted family {name} needs a reason"
        self.name = name
        self.kind = kind
        self.probe = probe
        self.walk = walk
        self.cost = cost
        self.allow = allow
        self.reason = reason
        self.sample = sample
        self.containers = containers
        self.attrs = attrs
        # sentinel state (mutated in place by tick — no allocation)
        self.last = 0
        self.last_ts = 0.0
        self.slope = 0.0
        self.flagged = False


class StateCensus:
    """Typed inventory of one control plane's long-lived containers.

    One per state machine (``SchedulerState.census``, worker
    ``WorkerState.census``), built by the role builders below.  Probes
    are closures over the owning state; everything here is read-only
    with respect to the state itself.
    """

    def __init__(self, role: str, clock: Callable[[], float] | None = None):
        self.role = role
        self.clock = clock if clock is not None else time
        self.families: dict[str, Family] = {}
        #: families whose non-zero count means "work in motion" — all
        #: zero = the control plane is quiescent
        self.motion: tuple[str, ...] = ()
        #: state-attribute allowlist for the registration-completeness
        #: gate: container attrs deliberately NOT census-registered,
        #: each with a mandatory reason (tests/test_census.py)
        self.attr_allowlist: dict[str, str] = {}
        self.check = census_check_enabled()
        self.audits = 0
        self.audit_failures = 0
        self.findings_total = 0
        self.findings: deque[dict] = deque(maxlen=MAX_FINDINGS)
        self.sentinel: RetentionSentinel | None = None

    # -------------------------------------------------------- registration

    def register(self, name: str, probe: Callable[[], int], **kwargs: Any) -> None:
        assert name not in self.families, f"duplicate census family {name}"
        self.families[name] = Family(name, probe, **kwargs)

    def allow_attr(self, attr: str, reason: str) -> None:
        assert reason, f"attr allowlist entry {attr} needs a reason"
        self.attr_allowlist[attr] = reason

    def covered_attrs(self) -> set[str]:
        """State attributes accounted for: census-registered or
        allowlisted-with-reason (the registration-completeness gate)."""
        out = set(self.attr_allowlist)
        for fam in self.families.values():
            out.update(fam.attrs)
        return out

    # ------------------------------------------------------------- reading

    def counts(self, deep: bool = False) -> dict[str, int]:
        """Per-family member counts.  ``deep=False`` reads only the
        O(1)/maintained probes; ``deep=True`` adds the O(n) walk-cost
        families (quiesce gates, ``/census?deep=1``, audits)."""
        return {
            name: fam.probe()
            for name, fam in self.families.items()
            if deep or fam.cost == "o1"
        }

    def quiesced(self) -> bool:
        """No tasks, nothing in flight — every motion family reads 0."""
        fams = self.families
        return all(fams[name].probe() == 0 for name in self.motion)

    # -------------------------------------------------- walk-vs-counter

    def audit(self, raise_: bool = True) -> list[dict]:
        """Recount every family that declared a verification walk and
        compare against its maintained probe (mirror-parity style).
        Returns the mismatches; raises :class:`CensusParityError` when
        ``raise_`` (the ``DTPU_CENSUS_CHECK`` mode and the sim gates)."""
        self.audits += 1
        mismatches = []
        for fam in self.families.values():
            if fam.walk is None:
                continue
            counted = fam.probe()
            walked = fam.walk()
            if counted != walked:
                mismatches.append({
                    "family": fam.name, "counted": counted, "walked": walked,
                })
        if mismatches:
            self.audit_failures += 1
            if raise_:
                raise CensusParityError(
                    f"{self.role} census counter/walk drift: {mismatches}"
                )
        return mismatches

    # ------------------------------------------------------- quiesce diff

    def residue(self, extra_allow: Iterable[str] = ()) -> list[dict]:
        """Census-vs-empty-baseline diff at quiesce: every family whose
        deep count is non-zero and that is neither allowlisted at
        registration nor named in ``extra_allow`` becomes a finding.
        Findings are recorded (bounded) and counted; enrich with
        :meth:`enrich_findings` (off-loop in live servers) to attach the
        member sample and the ``gc.get_referrers`` holder chain."""
        extra = set(extra_allow)
        now = self.clock()
        out = []
        for fam in self.families.values():
            if fam.allow or fam.name in extra:
                continue
            n = fam.probe()
            if n:
                out.append({
                    "v": CENSUS_SCHEMA_VERSION,
                    "type": "census-finding",
                    "ts": now,
                    "role": self.role,
                    "family": fam.name,
                    "kind": fam.kind,
                    "count": n,
                })
        for f in out:
            self.findings.append(f)
        self.findings_total += len(out)
        return out

    def enrich_findings(self, findings: list[dict]) -> list[dict]:
        """Attach a bounded member sample and referrer-derived holder
        identification to findings IN PLACE.  Runs ``gc.get_referrers``
        — keep it off the event loop (the scheduler server submits this
        to its executor; sim gates call it synchronously)."""
        for f in findings:
            fam = self.families.get(f.get("family", ""))
            if fam is None or "holders" in f:
                continue
            # defensive per-finding: when enrichment runs off-loop the
            # event loop may mutate the sampled container concurrently
            # (dict-changed-size mid-iteration) — a lost sample must
            # degrade the finding, never lose it or kill the thread
            try:
                members = []
                if fam.sample is not None:
                    for obj in fam.sample():
                        members.append(_safe_repr(obj))
                        if len(members) >= SAMPLE_MEMBERS:
                            break
                f["sample"] = members
                holders: list[str] = []
                if fam.sample is not None:
                    for obj in fam.sample():
                        holders = self.identify_holders(obj)
                        break
                f["holders"] = holders
            except Exception as exc:  # graft-lint: allow[swallowed-exceptions] diagnostics must degrade, not raise — the partial finding records why
                f.setdefault("sample", [])
                f["holders"] = [f"<enrich-failed: {type(exc).__name__}>"]
        return findings

    def identify_holders(self, obj: Any) -> list[str]:
        """Bounded BFS over ``gc.get_referrers`` naming which registered
        containers (or, failing that, which container types) hold
        ``obj`` — the "who is pinning this" answer a leak finding needs.
        Depth/breadth capped; never raises."""
        registry: list[tuple[str, Any]] = []
        for fam in self.families.values():
            if fam.containers is None:
                continue
            try:
                for c in fam.containers():
                    registry.append((fam.name, c))
            except Exception:  # graft-lint: allow[swallowed-exceptions] a torn-down component's container fn must not break diagnostics
                continue
        out: list[str] = []
        seen: set[int] = set()
        frontier = [obj]
        for _depth in range(REFERRER_DEPTH):
            nxt: list[Any] = []
            for o in frontier:
                try:
                    refs = gc.get_referrers(o)
                except Exception:  # graft-lint: allow[swallowed-exceptions] diagnostics must degrade, not raise
                    refs = []
                for r in refs[:REFERRER_BREADTH * 4]:
                    if id(r) in seen or r is frontier or r is nxt:
                        continue
                    seen.add(id(r))
                    named = False
                    for fname, c in registry:
                        if r is c:
                            if fname not in out:
                                out.append(fname)
                            named = True
                            break
                    if named:
                        continue
                    if isinstance(r, (dict, list, set, frozenset, tuple, deque)):
                        if len(nxt) < REFERRER_BREADTH:
                            nxt.append(r)
                    elif hasattr(type(r), "__mro__") and not _is_frame(r):
                        tag = f"<{type(r).__module__}.{type(r).__name__}>"
                        if tag not in out and len(out) < REFERRER_BREADTH:
                            out.append(tag)
            if out or not nxt:
                break
            frontier = nxt
        return out

    # ----------------------------------------------------------- snapshot

    def snapshot(self, deep: bool = False, now: float | None = None) -> list[dict]:
        """JSON-safe records for ``/census`` and cluster dumps: one head
        record, one record per family (counts, slope, allowlist status),
        then the recent findings.  One monotonic ``ts`` per snapshot so
        records line up with flight-recorder events on the same clock."""
        if now is None:
            now = self.clock()
        head = {
            "v": CENSUS_SCHEMA_VERSION,
            "type": "census-head",
            "ts": now,
            "role": self.role,
            "families": len(self.families),
            "quiesced": self.quiesced(),
            "deep": bool(deep),
            "audits": self.audits,
            "audit_failures": self.audit_failures,
            "findings_total": self.findings_total,
        }
        out = [head]
        for fam in self.families.values():
            if not deep and fam.cost != "o1":
                continue
            rec = {
                "v": CENSUS_SCHEMA_VERSION,
                "type": "census",
                "ts": now,
                "role": self.role,
                "family": fam.name,
                "kind": fam.kind,
                "count": fam.probe(),
                "slope": round(fam.slope, 3),
            }
            if fam.allow:
                rec["allow"] = fam.reason
            out.append(rec)
        out.extend(self.findings)
        return out


def _safe_repr(obj: Any, limit: int = 120) -> str:
    try:
        r = repr(obj)
    except Exception:  # graft-lint: allow[swallowed-exceptions] diagnostics must degrade, not raise
        r = f"<unreprable {type(obj).__name__}>"
    return r if len(r) <= limit else r[: limit - 3] + "..."


def _is_frame(obj: Any) -> bool:
    return type(obj).__name__ == "frame"


# ---------------------------------------------------------------- sentinel


class RetentionSentinel:
    """Live leak detection over a census: per-family growth-slope EWMAs
    plus quiesce-edge residue checks.

    ``tick`` is the periodic entry (server ``PeriodicCallback`` at
    ``scheduler.census.interval``; allocation-free per the bench-smoke
    gate): it reads every cheap probe, folds the members-per-second
    slope into an EWMA, and flags families whose slope stays above
    ``scheduler.census.slope-threshold`` while holding at least
    ``scheduler.census.min-count`` members — each flag emits ONE
    flight-recorder ``leak`` event (re-armed when the slope halves).
    When the plane goes quiescent, the census-vs-empty-baseline diff
    runs once per quiesce edge; fresh findings are returned so the
    caller can enrich them off-loop."""

    def __init__(self, census: StateCensus, trace: Any = None, *,
                 alpha: float = 0.3,
                 slope_threshold: float | None = None,
                 min_count: int | None = None,
                 quiesce_allow: Iterable[str] = ()):
        self.census = census
        self.trace = trace
        self.alpha = alpha
        if slope_threshold is None:
            slope_threshold = float(config.get("scheduler.census.slope-threshold"))
        if min_count is None:
            min_count = int(config.get("scheduler.census.min-count"))
        self.slope_threshold = slope_threshold
        self.min_count = min_count
        #: families exempted from LIVE quiesce diffs only (e.g. the
        #: durability dirty sets, drained by snapshot cadence rather
        #: than at the instant of quiesce) — the sim/bench teardown
        #: gates snapshot first and pass nothing here
        self.quiesce_allow = tuple(quiesce_allow)
        self.leaks_flagged = 0
        self.ticks = 0
        self._was_quiesced = True
        # cheap-probe tuple snapshot: tick iterates families directly
        # (no dict build on the periodic path)
        self._cheap = tuple(
            f for f in census.families.values() if f.cost == "o1"
        )

    def tick(self, now: float | None = None) -> list[dict]:
        """One sentinel pass; returns NEW findings (usually empty)."""
        c = self.census
        if now is None:
            now = c.clock()
        self.ticks += 1
        alpha = self.alpha
        thr = self.slope_threshold
        floor = self.min_count
        trace = self.trace
        for fam in self._cheap:
            n = fam.probe()
            dt = now - fam.last_ts
            if fam.last_ts > 0.0 and dt > 0.0:
                fam.slope += alpha * ((n - fam.last) / dt - fam.slope)
            fam.last = n
            fam.last_ts = now
            if fam.slope > thr and n >= floor:
                if not fam.flagged:
                    fam.flagged = True
                    self.leaks_flagged += 1
                    if trace is not None:
                        trace.emit("leak", fam.name, "", n=n)
            elif fam.flagged and fam.slope < thr / 2.0:
                fam.flagged = False
        if c.check:
            c.audit()
        quiesced = c.quiesced()
        fresh: list[dict] = []
        if quiesced and not self._was_quiesced:
            fresh = c.residue(extra_allow=self.quiesce_allow)
            if trace is not None:
                for f in fresh:
                    trace.emit("leak", f["family"], "", n=f["count"])
        self._was_quiesced = quiesced
        return fresh


# ------------------------------------------------------------- role builders
#
# Every dict/set/deque/list attribute either of the two ``__init__``
# bodies assigns must be covered here — census-registered via ``attrs``
# or allowlisted with a reason — or tests/test_census.py's
# registration-completeness gate fails the build.


def _walk_edges(tasks: dict, field: str) -> Callable[[], int]:
    def walk() -> int:
        return sum(len(getattr(ts, field)) for ts in tasks.values())
    return walk


def build_scheduler_census(state: Any) -> StateCensus:
    """Register every long-lived container of one ``SchedulerState``
    (plus the extension/engine/diagnostic structures hanging off it).
    Probes read through ``state`` lazily, so components attached after
    ``__init__`` (stealing, durability, spans) are covered the moment
    they exist."""
    c = StateCensus("scheduler", clock=state.clock)
    tasks = state.tasks

    # ---- first-class records
    c.register(
        "tasks", lambda: len(tasks), kind="state",
        sample=lambda: tasks.values(),
        containers=lambda: (tasks,),
        attrs=("tasks",),
    )
    # maintained-counter twin of ``tasks``: both transition engines
    # maintain TaskGroup.states per arm (`_count_transition` and the
    # native tape appliers); summing the non-forgotten buckets must
    # always equal a from-scratch walk of ``state.tasks`` — THE
    # walk-vs-counter audit that catches a missed engine count
    def _counted_tasks() -> int:
        return sum(
            n
            for tg in state.task_groups.values()
            for s, n in tg.states.items()
            if s != "forgotten" and n
        )

    # O(#groups) per probe — vocabulary-bounded (one group per key
    # prefix), cheap enough for the tick/scrape surface
    c.register(
        "tasks.counted", _counted_tasks, kind="state",
        walk=lambda: len(tasks),
    )
    c.register(
        "groups", lambda: len(state.task_groups), kind="state",
        allow=True, reason="per-group duration/type history persists by "
        "design (bounded by the key-group vocabulary)",
        attrs=("task_groups",),
    )
    # a group may legitimately outlive its tasks, but it must not pin a
    # REMOVED WorkerState via last_worker (cleared on worker removal;
    # regression-tested)
    c.register(
        "groups.stale-last-worker",
        lambda: sum(
            1
            for tg in state.task_groups.values()
            if tg.last_worker is not None
            and state.workers.get(tg.last_worker.address) is not tg.last_worker
        ),
        kind="index", cost="walk",
        sample=lambda: (
            tg.last_worker
            for tg in state.task_groups.values()
            if tg.last_worker is not None
            and state.workers.get(tg.last_worker.address) is not tg.last_worker
        ),
    )
    c.register(
        "prefixes", lambda: len(state.task_prefixes), kind="state",
        allow=True, reason="per-prefix duration priors persist by design "
        "(bounded by the key-prefix vocabulary)",
        attrs=("task_prefixes",),
    )
    c.register(
        "computations", lambda: len(state.computations), kind="ring",
        allow=True,
        reason="bounded deque (diagnostics.computations.max-history)",
        attrs=("computations",),
    )
    c.register(
        "tasks.unknown-durations",
        lambda: len(state.unknown_durations), kind="index",
        containers=lambda: (state.unknown_durations,),
        attrs=("unknown_durations",),
    )
    c.register(
        "tasks.unknown-durations.members",
        lambda: sum(len(s) for s in state.unknown_durations.values()),
        kind="index",
        sample=lambda: (
            ts for s in state.unknown_durations.values() for ts in s
        ),
        containers=lambda: (
            state.unknown_durations,
            *state.unknown_durations.values(),
        ),
    )
    c.register(
        "tasks.replicated", lambda: len(state.replicated_tasks),
        kind="index",
        sample=lambda: state.replicated_tasks,
        containers=lambda: (state.replicated_tasks,),
        attrs=("replicated_tasks",),
    )
    c.register(
        "tasks.metadata", lambda: len(state.task_metadata), kind="state",
        allow=True, reason="client-set task metadata persists until "
        "explicitly deleted (reference semantics)",
        attrs=("task_metadata",),
    )

    # ---- relation-set edges (O(n) walks; zero whenever tasks is zero)
    for field in ("dependencies", "dependents", "waiters", "waiting_on",
                  "who_has"):
        c.register(
            f"edges.{field.replace('_', '-')}",
            _walk_edges(tasks, field), kind="edges", cost="walk",
        )
    c.register(
        "edges.who-wants", _walk_edges(tasks, "who_wants"),
        kind="edges", cost="walk",
    )

    # ---- client interest
    c.register(
        "clients", lambda: len(state.clients), kind="state",
        allow=True, reason="connected clients persist until they "
        "disconnect (their interest edges must still drain to zero)",
        attrs=("clients",),
    )
    c.register(
        "interest.wants",
        lambda: sum(len(cs.wants_what) for cs in state.clients.values()),
        kind="interest", cost="walk",
        sample=lambda: (
            ts for cs in state.clients.values() for ts in cs.wants_what
        ),
        containers=lambda: tuple(
            cs.wants_what for cs in state.clients.values()
        ),
    )

    # ---- queues
    c.register(
        "queue.queued", lambda: len(state.queued), kind="queue",
        sample=lambda: iter(state.queued),
        containers=lambda: (state.queued, state.queued._data),
        attrs=("queued",),
    )
    c.register(
        "queue.unparked", lambda: len(state.queued_unparked), kind="queue",
        containers=lambda: (state.queued_unparked._data,),
        attrs=("queued_unparked",),
    )
    c.register(
        "queue.parked",
        lambda: sum(len(h) for h in state.parked.values()), kind="queue",
        sample=lambda: (
            ts for h in state.parked.values() for ts in h
        ),
        containers=lambda: (state.parked,),
    )
    c.register(
        "queue.parked-heaps", lambda: len(state.parked), kind="queue",
        attrs=("parked",),
    )
    c.register(
        "queue.parked-keys", lambda: len(state._parked_keys), kind="index",
        walk=lambda: sum(len(h) for h in state.parked.values()),
        containers=lambda: (state._parked_keys,),
        attrs=("_parked_keys",),
    )
    c.register(
        "queue.unrunnable", lambda: len(state.unrunnable), kind="queue",
        sample=lambda: state.unrunnable.keys(),
        containers=lambda: (state.unrunnable,),
        attrs=("unrunnable",),
    )

    # ---- fleet
    workers = state.workers
    c.register(
        "workers", lambda: len(workers), kind="state",
        allow=True, reason="registered workers persist until removal",
        sample=lambda: workers.values(),
        containers=lambda: (workers,),
        attrs=("workers",),
    )
    c.register(
        "fleet.aliases", lambda: len(state.aliases), kind="index",
        allow=True, reason="one name alias per registered worker "
        "(pruned on removal)",
        attrs=("aliases",),
    )
    c.register(
        "fleet.hosts", lambda: len(state.host_info), kind="state",
        attrs=("host_info",),
    )
    c.register(
        "fleet.resources",
        lambda: sum(len(d) for d in state.resources.values()),
        kind="index",
        allow=True, reason="per-resource supply rows mirror registered "
        "workers (pruned on removal)",
        attrs=("resources",),
    )
    # idle/saturated/running mirror the registered fleet — allowlisted
    # as counts, but a member that is NOT a registered worker is
    # retained garbage: fleet.stale walks all four
    c.register(
        "fleet.idle", lambda: len(state.idle), kind="index",
        allow=True, reason="subset view of registered workers",
        attrs=("idle",),
    )
    c.register(
        "fleet.idle-task-count", lambda: len(state.idle_task_count),
        kind="index",
        allow=True, reason="subset view of registered workers",
        attrs=("idle_task_count",),
    )
    c.register(
        "fleet.saturated", lambda: len(state.saturated), kind="index",
        allow=True, reason="subset view of registered workers",
        attrs=("saturated",),
    )
    c.register(
        "fleet.running", lambda: len(state.running), kind="index",
        allow=True, reason="subset view of registered workers",
        attrs=("running",),
    )

    def _fleet_stale() -> int:
        live = set(map(id, workers.values()))
        return sum(
            1
            for coll in (state.idle.values(), state.idle_task_count,
                         state.saturated, state.running)
            for ws in coll
            if id(ws) not in live
        )

    c.register(
        "fleet.stale", _fleet_stale, kind="index", cost="walk",
        sample=lambda: (
            ws
            for coll in (state.idle.values(), state.idle_task_count,
                         state.saturated, state.running)
            for ws in coll
            if state.workers.get(ws.address) is not ws
        ),
    )
    c.register(
        "fleet.nthreads-history",
        lambda: len(state.total_nthreads_history), kind="ring",
        allow=True, reason="bounded deque of fleet-capacity flips",
        attrs=("total_nthreads_history",),
    )
    # per-worker mirrors of task state: all drain to zero with the tasks
    for field, kind in (
        ("has_what", "edges"), ("processing", "in-flight"),
        ("executing", "in-flight"), ("long_running", "index"),
        ("actors", "index"),
    ):
        c.register(
            f"fleet.{field.replace('_', '-')}",
            (lambda f=field: sum(
                len(getattr(ws, f)) for ws in workers.values()
            )),
            kind=kind, cost="walk",
            sample=(lambda f=field: (
                ts for ws in workers.values() for ts in getattr(ws, f)
            )),
        )

    # ---- transition engine scratch + logs
    c.register(
        "transition-log", lambda: len(state.transition_log), kind="ring",
        allow=True, reason="bounded deque "
        "(scheduler.transition-log-length)",
        attrs=("_transition_log",),
    )
    c.register(
        "events",
        lambda: sum(len(dq) for dq in state.events.values()), kind="ring",
        allow=True, reason="bounded per-topic deques "
        "(scheduler.events-log-length)",
        attrs=("events", "event_counts"),
    )
    c.register(
        "engine-shards", lambda: len(state.engine_shards), kind="state",
        allow=True, reason="one stat row per mesh shard",
        attrs=("engine_shards",),
    )
    c.register(
        "plugins", lambda: len(state.plugins), kind="state",
        allow=True, reason="installed scheduler plugins persist",
        attrs=("plugins",),
    )
    c.register(
        "extensions", lambda: len(state.extensions), kind="state",
        allow=True, reason="installed scheduler extensions persist",
        attrs=("extensions",),
    )

    # ---- stealing (extension; probes no-op until it attaches)
    def _steal(attr: str, default: Any = None) -> Any:
        # getattr with default so a stub extension (tests) reads empty
        ext = state.extensions.get("stealing")
        return getattr(ext, attr, default) if ext is not None else default

    c.register(
        "steal.stealable",
        lambda: sum(
            len(level)
            for levels in _steal("stealable", {}).values()
            for level in levels
        ),
        kind="index", cost="walk",
        sample=lambda: (
            ts
            for levels in _steal("stealable", {}).values()
            for level in levels
            for ts in level
        ),
    )
    c.register(
        "steal.stealable-workers",
        lambda: len(_steal("stealable", {})), kind="index",
        allow=True, reason="one level-set vector per registered worker "
        "(pruned on removal)",
    )
    c.register(
        "steal.key-stealable",
        lambda: len(_steal("key_stealable", {})), kind="index",
        containers=lambda: tuple(
            x for x in (_steal("key_stealable", None),) if x is not None
        ),
    )
    c.register(
        "steal.in-flight", lambda: len(_steal("in_flight", {})),
        kind="in-flight",
        sample=lambda: _steal("in_flight", {}).values(),
        containers=lambda: tuple(
            x for x in (_steal("in_flight", None),) if x is not None
        ),
    )
    c.register(
        "steal.in-flight-occupancy",
        lambda: len(_steal("in_flight_occupancy", {})), kind="scratch",
        sample=lambda: _steal("in_flight_occupancy", {}).keys(),
    )
    c.register(
        "steal.in-flight-tasks",
        lambda: len(_steal("in_flight_tasks", {})), kind="scratch",
        sample=lambda: _steal("in_flight_tasks", {}).keys(),
    )
    c.register(
        "steal.log", lambda: len(_steal("log", ())), kind="ring",
        allow=True, reason="bounded deque",
    )

    # ---- decision ledger
    led = state.ledger
    c.register(
        "ledger.open", lambda: led.open_rows, kind="in-flight",
        walk=lambda: sum(
            1
            for off in range(0, len(led._ring), _LEDGER_W)
            if led._ring[off] >= 0 and led._ring[off + _LEDGER_OUTCOME] == ""
        ),
    )
    c.register(
        "ledger.amm-open", lambda: len(led._open_amm), kind="in-flight",
        sample=lambda: led._open_amm.keys(),
        containers=lambda: (led._open_amm,),
    )
    c.register(
        "ledger.ring", lambda: len(led), kind="ring",
        allow=True, reason="bounded decision ring (scheduler.ledger.size)",
    )
    c.register(
        "ledger.aggregates",
        lambda: len(led.prefix_agg) + len(led.link_agg)
        + len(led._kind_stats),
        kind="state",
        allow=True, reason="per-prefix/per-link/per-kind regret "
        "aggregates persist by design (bounded by vocabulary x fleet)",
    )

    # ---- telemetry
    tel = state.telemetry
    c.register(
        "telemetry.links", lambda: len(tel.links), kind="state",
        allow=True, reason="per-link EWMAs for the live fleet persist "
        "by design (stale endpoints walk-audited to zero)",
    )

    def _stale_links() -> int:
        # EITHER endpoint unregistered = stale: forget_worker prunes on
        # either side, and the dominant leak shape is a live reporter
        # re-creating a link to a dead peer
        return sum(
            1
            for (src, dst) in tel.links
            if src not in workers or dst not in workers
        )

    c.register(
        "telemetry.links.stale", _stale_links, kind="index", cost="walk",
        sample=lambda: (
            link for (src, dst), link in tel.links.items()
            if src not in workers or dst not in workers
        ),
        containers=lambda: (tel.links,),
    )
    c.register(
        "telemetry.rtt", lambda: len(tel.rtt), kind="index",
        allow=True, reason="per-worker heartbeat RTT EWMAs (pruned on "
        "worker removal; stale endpoints walk-audited to zero)",
    )
    c.register(
        "telemetry.rtt.stale",
        lambda: sum(1 for w in tel.rtt if w not in workers),
        kind="index", cost="walk",
    )
    c.register(
        "telemetry.priors", lambda: len(tel.priors), kind="state",
        allow=True, reason="per-prefix priors persist by design "
        "(bounded by the key-prefix vocabulary)",
    )
    c.register(
        "telemetry.pending-delta", lambda: len(tel.since_heartbeat),
        kind="scratch",
        allow=True, reason="scheduler-side collector never fills its "
        "own delta buffer (worker heartbeats fold through fold_rows)",
    )

    # ---- fleet mirror
    def _mirror(attr: str, default: Any = ()) -> Any:
        m = state.mirror
        return getattr(m, attr) if m is not None else default

    c.register(
        "mirror.slots-live",
        lambda: sum(1 for ws in _mirror("ws_of") if ws is not None),
        kind="state", cost="walk",
        walk=lambda: len(workers) if state.mirror is not None else 0,
        allow=True, reason="one live slot per registered worker "
        "(walk-audited against the worker count)",
    )
    c.register(
        "mirror.tombstones", lambda: len(_mirror("_free", ())),
        kind="pool",
        allow=True, reason="LIFO slot free-list, reused by the next "
        "registration (bounded by capacity)",
    )
    c.register(
        "mirror.dirty",
        lambda: len(_mirror("_dirty", ())) + len(_mirror("_device_dirty", ()))
        + len(_mirror("_sdev_dirty", ())),
        kind="scratch",
        allow=True, reason="dirty row marks pending the next device "
        "refresh (bounded by mirror capacity; slot ints, not object refs)",
    )

    # ---- native engine
    def _native(attr: str, default: Any = ()) -> Any:
        n = state.native
        return getattr(n, attr) if n is not None else default

    c.register(
        "native.rows-live",
        lambda: len(_native("_rows", ())) - len(_native("_row_free", ())),
        kind="state",
        walk=lambda: sum(1 for ts in tasks.values() if ts.nrow >= 0)
        if state.native is not None else 0,
        sample=lambda: (ts for ts in _native("_rows") if ts is not None),
    )
    c.register(
        "native.row-free", lambda: len(_native("_row_free", ())),
        kind="pool",
        allow=True, reason="SoA row free-list, reused by the next task",
    )
    c.register(
        "native.wslot-tombstones",
        lambda: sum(1 for ws in _native("_wslots") if ws is None),
        kind="pool", cost="walk",
        allow=True, reason="worker slots are never reused by design "
        "(one null entry per departed worker)",
    )
    c.register(
        "native.dirty", lambda: len(_native("_dirty", ())), kind="scratch",
        sample=lambda: iter(_native("_dirty", ())),
        containers=lambda: tuple(
            x for x in (_native("_dirty", None),) if x is not None
        ),
    )
    c.register(
        "native.dirty-workers", lambda: len(_native("_dirty_workers", ())),
        kind="scratch",
        allow=True, reason="worker resync marks pending the next flood "
        "flush (bounded by the registered fleet)",
    )
    c.register(
        "native.interned",
        lambda: len(_native("_prefix_ids", ())) + len(_native("_group_ids", ())),
        kind="state",
        allow=True, reason="interned prefix/group id maps (bounded by "
        "the key vocabulary)",
    )
    # authoritative-SoA families (deferred materialization): parked
    # segments must drain to zero at quiesce (every release goes
    # through a sync-first mutation hook), and the hydrated python
    # rows — the "hydration cache" — must empty with the tasks
    c.register(
        "native.pending-segments",
        lambda: len(_native("_pending", ())), kind="scratch",
    )
    c.register(
        "native.tape-pool", lambda: len(_native("_tape_pool", ())),
        kind="pool",
        allow=True, reason="recycled tape buffers (bounded: one per "
        "concurrently-deferred segment, reused across floods)",
    )

    def _eng_counts(i: int) -> int:
        # live-row counts read from the C++ side: the authoritative
        # store's own accounting, audited against a python-mirror walk
        n = state.native
        if n is None or n.h is None:
            return 0
        import ctypes as _ct
        out = (_ct.c_int64 * 6)()
        n.lib.eng_counts(n.h, out)
        return int(out[i])

    c.register(
        "native.soa-rows", lambda: _eng_counts(0), kind="state",
        cost="walk",
        # rows allocated but never yet flushed (_fresh) are python-only:
        # subtract them so the walk matches the C++ live count exactly
        walk=lambda: sum(1 for ts in _native("_rows") if ts is not None)
        - len(_native("_fresh", ())),
        sample=lambda: (ts for ts in _native("_rows") if ts is not None),
    )
    c.register(
        "native.soa-workers", lambda: _eng_counts(2), kind="state",
        cost="walk",
        walk=lambda: sum(1 for ws in _native("_wslots") if ws is not None),
        allow=True, reason="one live SoA slot per registered worker "
        "(drains on worker close, not task release)",
    )
    c.register(
        "native.hydration-cache",
        lambda: (
            max(0, sum(1 for ts in _native("_rows") if ts is not None)
                - sum(p[1] for p in _native("_pending", ())))
        ),
        kind="state", cost="walk",
        sample=lambda: (ts for ts in _native("_rows") if ts is not None),
    )

    # ---- durability (attached by the server / sim when enabled)
    def _durability(attr: str) -> int:
        d = state.durability
        return len(getattr(d, attr)) if d is not None else 0

    for attr in ("dirty_tasks", "removed_tasks", "dirty_workers",
                 "removed_workers"):
        c.register(
            f"durability.{attr.replace('_', '-')}",
            (lambda a=attr: _durability(a)), kind="scratch",
            sample=(lambda a=attr: iter(
                getattr(state.durability, a) if state.durability is not None
                else ()
            )),
        )

    # ---- flight recorder
    c.register(
        "trace.ring", lambda: len(state.trace), kind="ring",
        allow=True, reason="bounded event ring (scheduler.trace.ring-size)",
    )
    c.register(
        "trace.journal", lambda: len(state.trace.journal), kind="ring",
        allow=True, reason="bounded stimulus journal deque "
        "(scheduler.trace.journal-size)",
    )

    # attrs deliberately NOT census-registered (mandatory reasons):
    c.allow_attr("_transitions_table", "static dispatch table, fixed size")
    c.allow_attr("DEFAULT_TASK_DURATIONS", "static config snapshot")
    c.allow_attr("_arm_phases", "interned per-arm phase names, bounded "
                 "by the transition-arm vocabulary")

    # all O(1) probes: quiesced() runs per sentinel tick AND per
    # /metrics scrape (dtpu_census_quiesced).  fleet.processing is
    # implied zero by tasks == 0 (processing sets hold live
    # TaskStates); a bug breaking that implication is still caught by
    # the quiesce residue scan, which probes every family
    c.motion = (
        "tasks", "queue.queued", "queue.unrunnable", "steal.in-flight",
        "native.pending-segments",
    )
    return c


def build_worker_census(state: Any) -> StateCensus:
    """Register every long-lived container of one worker
    ``WorkerState`` (the scheduler census's twin)."""
    # deref the recorder's clock per read: the sim may re-point it at
    # its VirtualClock after construction
    c = StateCensus("worker", clock=lambda: state.trace.clock())
    tasks = state.tasks

    c.register(
        "wtasks", lambda: len(tasks), kind="state",
        sample=lambda: tasks.values(),
        containers=lambda: (tasks,),
        attrs=("tasks",),
    )
    c.register(
        "wtasks.data", lambda: len(state.data), kind="state",
        sample=lambda: state.data.keys(),
        containers=lambda: (state.data,),
        attrs=("data",),
    )
    c.register(
        "wtasks.actors", lambda: len(state.actors), kind="state",
        containers=lambda: (state.actors,),
        attrs=("actors",),
    )

    # relation edges on the worker machine (walks; zero with zero tasks)
    for field in ("dependencies", "dependents", "waiters",
                  "waiting_for_data", "who_has"):
        c.register(
            f"edges.{field.replace('_', '-')}",
            _walk_edges(tasks, field), kind="edges", cost="walk",
        )

    c.register(
        "queue.ready", lambda: len(state.ready), kind="queue",
        containers=lambda: (state.ready._data,),
        attrs=("ready",),
    )
    c.register(
        "queue.constrained", lambda: len(state.constrained), kind="queue",
        attrs=("constrained",),
    )
    for attr in ("executing", "long_running", "in_flight_tasks",
                 "missing_dep_flight"):
        c.register(
            f"exec.{attr.replace('_', '-')}",
            (lambda a=attr: len(getattr(state, a))), kind="in-flight",
            sample=(lambda a=attr: iter(getattr(state, a))),
            containers=(lambda a=attr: (getattr(state, a),)),
            attrs=(attr,),
        )

    # fetch bookkeeping
    c.register(
        "fetch.data-needed",
        lambda: sum(len(h) for h in state.data_needed.values()),
        kind="queue",
        sample=lambda: (
            ts for h in state.data_needed.values() for ts in h
        ),
        containers=lambda: (state.data_needed,),
    )
    c.register(
        "fetch.data-needed-peers", lambda: len(state.data_needed),
        kind="queue", attrs=("data_needed",),
    )
    c.register(
        "fetch.in-flight-workers", lambda: len(state.in_flight_workers),
        kind="in-flight",
        containers=lambda: (state.in_flight_workers,),
        attrs=("in_flight_workers",),
    )
    c.register(
        "fetch.in-flight-keys",
        lambda: sum(len(s) for s in state.in_flight_workers.values()),
        kind="in-flight", cost="walk",
    )
    c.register(
        "fetch.busy-workers", lambda: len(state.busy_workers),
        kind="scratch",
        sample=lambda: iter(state.busy_workers),
        containers=lambda: (state.busy_workers,),
        attrs=("busy_workers",),
    )
    c.register(
        "fetch.has-what",
        lambda: sum(len(s) for s in state.has_what.values()),
        kind="edges", cost="walk",
    )
    c.register(
        "fetch.has-what-peers", lambda: len(state.has_what), kind="index",
        walk=lambda: sum(
            1 for s in state.has_what.values() if s
        ),
        containers=lambda: (state.has_what,),
        attrs=("has_what",),
    )

    c.register(
        "resources",
        lambda: len(state.total_resources) + len(state.available_resources),
        kind="state",
        allow=True, reason="static resource declarations",
        attrs=("total_resources", "available_resources"),
    )
    c.register(
        "log", lambda: len(state.log), kind="ring",
        allow=True, reason="bounded transition log deque",
        attrs=("log",),
    )
    c.register(
        "stimulus-log", lambda: len(state.stimulus_log), kind="ring",
        allow=True, reason="bounded stimulus log deque",
        attrs=("stimulus_log",),
    )
    c.register(
        "task-counter", lambda: len(state.task_counter), kind="state",
        allow=True, reason="per-prefix lifetime counters (bounded by "
        "the key-prefix vocabulary)",
        attrs=("task_counter",),
    )
    c.register(
        "trace.ring", lambda: len(state.trace), kind="ring",
        allow=True, reason="bounded event ring (scheduler.trace.ring-size)",
    )
    c.register(
        "trace.journal", lambda: len(state.trace.journal), kind="ring",
        allow=True, reason="bounded stimulus journal deque",
    )

    c.allow_attr("_transitions_table", "static dispatch table, fixed size")
    c.allow_attr("_arm_phases", "interned per-arm phase names, bounded "
                 "by the transition-arm vocabulary")

    c.motion = (
        "wtasks", "queue.ready", "queue.constrained", "exec.executing",
        "exec.in-flight-tasks", "fetch.data-needed",
    )
    return c
